"""Memchecker — buffer-validity checking at messaging boundaries.

≈ opal/mca/memchecker/valgrind: the reference annotates buffers
defined/undefined at PML/convertor boundaries so valgrind can flag reads
of uninitialized message data.  CPython has no valgrind client hooks, so
the same discipline is realized directly, gated off by default
(``--mca memchecker enable 1``):

- **send side**: the outgoing buffer must be a readable array; with
  ``memchecker_nan_check`` on, float payloads are scanned for NaN — the
  closest observable analog of "sending undefined memory" (a poisoned
  recv buffer forwarded without ever being written).
- **recv side**: the destination must be writable (catching recvs into
  read-only views, which numpy would otherwise fail deep inside unpack);
  with ``memchecker_poison`` on, it is pre-filled with a NaN/0xCC pattern
  before delivery — exactly valgrind's "mark undefined": any rank that
  reads more than the matched message actually wrote sees poison, not
  stale plausible data.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ompi_tpu.core import output
from ompi_tpu.core.config import VarType, register_var, var_registry

__all__ = ["enabled", "check_send", "prepare_recv", "MemcheckError"]

_log = output.get_stream("memchecker")

register_var("memchecker", "enable", VarType.BOOL, False,
             "validate buffers at PML boundaries (≈ memchecker/valgrind)")
register_var("memchecker", "nan_check", VarType.BOOL, True,
             "with memchecker on: reject float send payloads containing "
             "NaN (the 'sending undefined memory' signal)")
register_var("memchecker", "poison", VarType.BOOL, True,
             "with memchecker on: pre-fill recv buffers with a poison "
             "pattern so reads beyond the received data are detectable")


class MemcheckError(ValueError):
    """A buffer failed a memchecker validation."""


def enabled() -> bool:
    return bool(var_registry.get("memchecker_enable"))


def check_send(buf, where: str = "send") -> None:
    """Validate an outgoing payload (call only when :func:`enabled`)."""
    arr = np.asarray(buf)
    if arr.dtype == object:
        raise MemcheckError(f"{where}: object-dtype buffer is not a "
                            f"wire-safe payload")
    if (var_registry.get("memchecker_nan_check")
            and np.issubdtype(arr.dtype, np.floating) and arr.size):
        # NaN in an outgoing buffer usually means a poisoned/uninitialized
        # region is being forwarded — the memchecker's raison d'être
        if bool(np.isnan(arr).any()):
            raise MemcheckError(
                f"{where}: payload contains NaN "
                f"(uninitialized/poisoned data on the wire; disable with "
                f"--mca memchecker_nan_check 0 if NaN is legitimate)")


def prepare_recv(buf: Optional[np.ndarray],
                 where: str = "recv") -> None:
    """Validate (and optionally poison) a recv destination in place."""
    if buf is None:
        return
    if not isinstance(buf, np.ndarray):
        raise MemcheckError(f"{where}: destination must be a numpy array")
    if not buf.flags.writeable:
        raise MemcheckError(f"{where}: destination buffer is read-only")
    if var_registry.get("memchecker_poison") and buf.size:
        # mark undefined: NaN for floats, 0xCC bytes otherwise
        if np.issubdtype(buf.dtype, np.floating):
            buf.fill(np.nan)
        elif buf.dtype != object:
            buf.view(np.uint8).fill(0xCC)
