"""Shared-memory segments — generic named create/attach framework.

≈ opal/mca/shmem (mmap/posix/sysv components): the one place that knows
how to create, publish, attach, and clean up shared segments; consumers
(the shm BTL's rings, any future shared cache) layer their protocols on
top instead of each reinventing tmpfile+mmap+rendezvous.

Design (mirrors the mmap component, the one the reference prefers):
- a segment is a file in /dev/shm (tmpfs) — or TMPDIR when absent —
  created atomically (tempfile + rename) so attachers never observe a
  half-initialized segment;
- the creator maps it read-write and owns unlink; attachers map an
  existing path (the mapping survives unlink — crash cleanup is free);
- a small magic+size header guards against attaching garbage.
"""

from __future__ import annotations

import mmap
import os
import struct
import tempfile
from typing import Optional

__all__ = ["SharedSegment", "create", "attach", "attach_retry",
           "backing_dir"]

_MAGIC = 0x53454731            # "SEG1"
_HDR = 16                      # magic u32 | pad u32 | size u64


def backing_dir() -> str:
    """tmpfs when the platform offers it (zero-copy page cache), TMPDIR
    otherwise — the mmap-component fallback order."""
    return "/dev/shm" if os.path.isdir("/dev/shm") else tempfile.gettempdir()


class SharedSegment:
    """One mapped segment; ``buf`` is the usable memoryview (header
    excluded)."""

    def __init__(self, path: str, mm: mmap.mmap, size: int,
                 creator: bool) -> None:
        self.path = path
        self.size = size
        self.creator = creator
        self._mm = mm
        self._tmp: Optional[str] = None   # set for unpublished segments
        self.buf = memoryview(mm)[_HDR:_HDR + size]

    def publish(self) -> None:
        """Rename an unpublished segment into place (after the consumer
        initialized its own header in ``buf``)."""
        if self._tmp is not None:
            os.rename(self._tmp, self.path)
            self._tmp = None

    def detach(self) -> None:
        try:
            self.buf.release()
            self._mm.close()
        except (BufferError, ValueError):
            pass

    def unlink(self) -> None:
        """Remove the name (creator's job); live mappings stay valid.
        An unpublished segment removes its temp file instead."""
        try:
            os.unlink(self._tmp or self.path)
        except OSError:
            pass
        self._tmp = None

    def close(self) -> None:
        if self.creator:
            self.unlink()
        self.detach()

    def __enter__(self) -> "SharedSegment":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def create(name: str, size: int, dir: Optional[str] = None,
           publish: bool = True) -> SharedSegment:
    """Create a named segment; with ``publish=True`` (default) it is
    renamed into place immediately (atomic: an attacher either sees the
    full initialized segment or nothing).

    A consumer that writes ITS OWN protocol header into ``buf`` before
    attachers may look (the shm BTL ring does) passes ``publish=False``,
    initializes, then calls :meth:`SharedSegment.publish` — keeping the
    never-see-half-initialized invariant for the layered protocol too.
    """
    base = dir or backing_dir()
    fd, tmp = tempfile.mkstemp(prefix=".seg-", dir=base)
    try:
        os.ftruncate(fd, _HDR + size)
        mm = mmap.mmap(fd, _HDR + size)
    finally:
        os.close(fd)
    struct.pack_into("<IIQ", mm, 0, _MAGIC, 0, size)
    path = os.path.join(base, name)
    seg = SharedSegment(path, mm, size, creator=True)
    if publish:
        os.rename(tmp, path)
    else:
        seg._tmp = tmp
    return seg


def attach_retry(path: str, timeout: float = 5.0,
                 interval: float = 0.001) -> SharedSegment:
    """Attach, waiting out the creator's publish window: a consumer that
    learned ``path`` out-of-band (a business card, a bootstrap bcast)
    may look before the atomic rename lands.  Bounded poll, then the
    last OSError propagates."""
    import time

    deadline = time.monotonic() + timeout
    while True:
        try:
            return attach(path)
        except OSError:
            if time.monotonic() >= deadline:
                raise
            time.sleep(interval)


def attach(path: str) -> SharedSegment:
    """Attach an existing segment; raises OSError on garbage/missing."""
    fd = os.open(path, os.O_RDWR)
    try:
        total = os.fstat(fd).st_size
        mm = mmap.mmap(fd, total)
    finally:
        os.close(fd)
    magic, _, size = struct.unpack_from("<IIQ", mm, 0)
    if magic != _MAGIC or _HDR + size > total:
        mm.close()
        raise OSError(f"{path}: not a valid shared segment")
    return SharedSegment(path, mm, size, creator=False)
