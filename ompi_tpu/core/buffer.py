"""Buffer-location abstraction: host vs device vs traced.

The reference threads CUDA special cases through its convertor, PML, BTL and
coll layers via the ``CONVERTOR_CUDA`` flag (opal/datatype/opal_convertor.h:43-59,
opal_convertor.c:574-614 ``mca_cuda_convertor_init``) — device-ness is
discovered per-buffer and changes which memcpy/protocol runs.  SURVEY.md §7
flags this as the abstraction to design *first*, so here it is, as data:

- ``HOST``    — numpy arrays / python buffers; move via the host path
                (sockets, shared memory, the native convertor).
- ``DEVICE``  — committed ``jax.Array``s in HBM (or on CPU devices); move via
                XLA collectives / device-to-device transfer; never serialized.
- ``TRACED``  — JAX tracers inside ``jit``/``shard_map``; operations MUST
                lower to XLA ops (ppermute/psum/...), anything host-side is a
                programming error surfaced here, early, with a good message.

Every layer above (p2p, coll, RMA, SHMEM) dispatches on ``classify()`` instead
of sprinkling isinstance checks — the single choke point the reference never
had (its CUDA checks appear in 4 layers).
"""

from __future__ import annotations

import enum
from typing import Any

import numpy as np

__all__ = ["BufferKind", "classify", "is_device", "nbytes_of", "BufferLocationError"]


class BufferKind(enum.Enum):
    HOST = "host"
    DEVICE = "device"
    TRACED = "traced"


class BufferLocationError(TypeError):
    pass


def classify(buf: Any) -> BufferKind:
    """Classify a user buffer. Cheap for host buffers (no jax import)."""
    if buf is None:  # "no data on this rank" placeholder (non-root scatter)
        return BufferKind.HOST
    if isinstance(buf, np.ndarray) or np.isscalar(buf):
        return BufferKind.HOST
    if isinstance(buf, (bytes, bytearray, memoryview)):
        return BufferKind.HOST
    if isinstance(buf, (list, tuple)):
        # v-collective part lists: the parts share a location; classify the
        # first (an empty list is a host no-op)
        return classify(buf[0]) if buf else BufferKind.HOST
    # Only now touch jax (keeps host-only processes light).
    mod = type(buf).__module__ or ""
    if mod.startswith("jax") or hasattr(buf, "aval"):
        import jax.core

        if isinstance(buf, jax.core.Tracer):
            return BufferKind.TRACED
        import jax

        if isinstance(buf, jax.Array):
            return BufferKind.DEVICE
    # any other array-like the host path already accepts (array.array,
    # pandas Series, objects with __array__ / the buffer protocol)
    if hasattr(buf, "__array__") or hasattr(buf, "__array_interface__"):
        return BufferKind.HOST
    try:
        memoryview(buf)
        return BufferKind.HOST
    except TypeError:
        pass
    raise BufferLocationError(
        f"cannot classify buffer of type {type(buf).__name__}; expected "
        f"numpy array, jax array, or bytes-like")


def is_device(buf: Any) -> bool:
    k = classify(buf)
    return k in (BufferKind.DEVICE, BufferKind.TRACED)


def nbytes_of(buf: Any) -> int:
    if isinstance(buf, (bytes, bytearray, memoryview)):
        return len(buf)
    nb = getattr(buf, "nbytes", None)
    if nb is not None:
        return int(nb)
    return int(np.asarray(buf).nbytes)
