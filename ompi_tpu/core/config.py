"""Typed configuration-variable registry.

TPU-native equivalent of the reference's MCA variable system
(opal/mca/base/mca_base_var.h:78-96,404-475; mca_base_var.c): every tunable in
the framework is a *registered, typed, self-describing variable* with a
uniform namespace and a fixed source-precedence order:

    default  <  file ($OMPI_TPU_PARAM_FILE / ompi-tpu-params.conf)
             <  environment (OMPI_TPU_MCA_<framework>_<name>)
             <  command line (--mca <framework>_<name> <value>)
             <  programmatic set_var()

Variables support synonyms/deprecation and info levels, and the whole registry
is introspectable (the ``ompi_tpu.tools.info`` tool dumps it, like
``ompi_info``).  Unlike the reference there is no dlopen: registration happens
at import time of the owning module, which plays the role of component open.
"""

from __future__ import annotations

import dataclasses
import enum
import os
import threading
from typing import Any, Callable, Iterable, Optional

__all__ = [
    "VarType",
    "VarSource",
    "InfoLevel",
    "Var",
    "VarRegistry",
    "var_registry",
    "register_var",
    "get_var",
    "set_var",
]


#: external knob: highest-precedence params file (≈ the reference's
#: OMPI_MCA_mca_param_files)
ENV_PARAM_FILE = "OMPI_TPU_PARAM_FILE"


class VarType(enum.Enum):
    INT = "int"
    UNSIGNED = "unsigned"
    SIZE = "size"
    STRING = "string"
    BOOL = "bool"
    DOUBLE = "double"
    # list of strings (comma separated in env/CLI), used for component selection
    STRING_LIST = "string_list"


class VarSource(enum.Enum):
    """Where the current value came from (precedence low→high)."""

    DEFAULT = 0
    FILE = 1
    ENV = 2
    COMMAND_LINE = 3
    SET = 4  # programmatic override — wins over everything


class InfoLevel(enum.IntEnum):
    """Audience levels, mirroring MCA_BASE_VAR_INFO_LVL_* (mca_base_var.h)."""

    USER_BASIC = 1
    USER_DETAIL = 2
    USER_ALL = 3
    TUNER_BASIC = 4
    TUNER_DETAIL = 5
    TUNER_ALL = 6
    DEV_BASIC = 7
    DEV_DETAIL = 8
    DEV_ALL = 9


_PARSERS: dict[VarType, Callable[[str], Any]] = {
    VarType.INT: int,
    VarType.UNSIGNED: lambda s: _nonneg(int(s)),
    VarType.SIZE: lambda s: _parse_size(s),
    VarType.STRING: str,
    VarType.BOOL: lambda s: _parse_bool(s),
    VarType.DOUBLE: float,
    VarType.STRING_LIST: lambda s: [p for p in (t.strip() for t in s.split(",")) if p],
}


def _nonneg(v: int) -> int:
    if v < 0:
        raise ValueError(f"negative value {v} for unsigned variable")
    return v


def _parse_size(s: str) -> int:
    """Parse sizes with optional K/M/G suffix (binary units), e.g. '64K'."""
    s = s.strip()
    mult = 1
    if s and s[-1].upper() in "KMG":
        mult = {"K": 1 << 10, "M": 1 << 20, "G": 1 << 30}[s[-1].upper()]
        s = s[:-1]
    return _nonneg(int(float(s) * mult))


def _parse_bool(s: str) -> bool:
    s = s.strip().lower()
    if s in ("1", "true", "yes", "on", "enabled"):
        return True
    if s in ("0", "false", "no", "off", "disabled"):
        return False
    raise ValueError(f"cannot parse {s!r} as bool")


@dataclasses.dataclass
class Var:
    """One registered configuration variable."""

    framework: str
    name: str
    vtype: VarType
    default: Any
    description: str = ""
    info_level: InfoLevel = InfoLevel.USER_ALL
    read_only: bool = False
    deprecated: bool = False
    enumerator: Optional[tuple[Any, ...]] = None  # allowed values
    synonyms: tuple[str, ...] = ()  # alternate full names
    # current state
    value: Any = None
    source: VarSource = VarSource.DEFAULT

    @property
    def full_name(self) -> str:
        return f"{self.framework}_{self.name}" if self.framework else self.name

    def parse(self, raw: str) -> Any:
        v = _PARSERS[self.vtype](raw)
        self._check(v)
        return v

    def _check(self, v: Any) -> None:
        if self.enumerator is not None and v not in self.enumerator:
            raise ValueError(
                f"value {v!r} for {self.full_name} not in {self.enumerator}"
            )


class VarRegistry:
    """The process-wide variable registry with four-source precedence.

    Sources are applied at registration time (so late registration still sees
    CLI/env/file settings, mirroring how mca_base_var re-scans its file/env
    caches in mca_base_var_register).
    """

    ENV_PREFIX = "OMPI_TPU_MCA_"

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._vars: dict[str, Var] = {}
        self._synonyms: dict[str, str] = {}
        # pending settings keyed by full name: raw string + source
        self._pending: dict[str, tuple[str, VarSource]] = {}
        self._load_files()

    # -- source loading -------------------------------------------------

    def _load_files(self) -> None:
        """Load params files, lowest precedence first:
        ``~/.ompi_tpu/params.conf`` < ``./ompi-tpu-params.conf`` <
        ``$OMPI_TPU_PARAM_FILE``.

        File format is the reference's (mca_base_parse_paramfile.c): one
        ``name = value`` per line, '#' comments.
        """
        # First file to define a name wins (setdefault below), so list paths
        # highest precedence first.
        paths: list[str] = []
        envp = os.environ.get(ENV_PARAM_FILE)
        if envp:
            paths.append(envp)
        paths.append(os.path.join(os.getcwd(), "ompi-tpu-params.conf"))
        paths.append(os.path.join(os.path.expanduser("~"), ".ompi_tpu", "params.conf"))
        for path in paths:
            try:
                with open(path) as fh:
                    for line in fh:
                        line = line.split("#", 1)[0].strip()
                        if not line or "=" not in line:
                            continue
                        k, v = (p.strip() for p in line.split("=", 1))
                        self._pending.setdefault(k, (v, VarSource.FILE))
            except OSError:
                continue

    def load_cli(self, pairs: Iterable[tuple[str, str]]) -> None:
        """Record ``--mca name value`` pairs (called by CLI front-ends)."""
        with self._lock:
            for name, raw in pairs:
                self._pending[name] = (raw, VarSource.COMMAND_LINE)
                canon = self._synonyms.get(name, name)
                var = self._vars.get(canon)
                if var is not None:
                    self._apply(var, raw, VarSource.COMMAND_LINE)

    # -- registration ---------------------------------------------------

    def register(self, var: Var) -> Var:
        with self._lock:
            existing = self._vars.get(var.full_name)
            if existing is not None:
                return existing
            var.value = var.default
            self._vars[var.full_name] = var
            for syn in var.synonyms:
                self._synonyms[syn] = var.full_name
            # precedence: file < env < cli; _pending holds file+cli, env is
            # live.  Among canonical name + synonyms, the highest-precedence
            # source wins (a CLI setting under a synonym must beat a file
            # setting under the canonical name).
            pend: Optional[tuple[str, VarSource]] = None
            for cand in (var.full_name, *var.synonyms):
                p = self._pending.get(cand)
                if p is not None and (pend is None or p[1].value > pend[1].value):
                    pend = p
            if pend is not None and pend[1] == VarSource.FILE:
                self._apply(var, pend[0], VarSource.FILE)
            env_raw = os.environ.get(self.ENV_PREFIX + var.full_name)
            for syn in var.synonyms:
                if env_raw is None:
                    env_raw = os.environ.get(self.ENV_PREFIX + syn)
            if env_raw is not None:
                self._apply(var, env_raw, VarSource.ENV)
            if pend is not None and pend[1] == VarSource.COMMAND_LINE:
                self._apply(var, pend[0], VarSource.COMMAND_LINE)
            return var

    def _apply(self, var: Var, raw: str, source: VarSource) -> None:
        if var.read_only and source != VarSource.DEFAULT:
            # Mirror the reference: an external setting on a read-only var is
            # ignored with a warning, never an import-time crash.
            import sys

            print(f"ompi_tpu: ignoring {source.name.lower()} override of "
                  f"read-only variable {var.full_name}", file=sys.stderr)
            return
        try:
            var.value = var.parse(raw)
        except ValueError as e:
            hint = (self.ENV_PREFIX + var.full_name
                    if source == VarSource.ENV else source.name.lower())
            raise ValueError(
                f"bad value {raw!r} for {var.vtype.value} variable "
                f"{var.full_name} (from {hint}): {e}") from None
        var.source = source

    # -- access ---------------------------------------------------------

    def get(self, full_name: str) -> Any:
        with self._lock:
            canon = self._synonyms.get(full_name, full_name)
            return self._vars[canon].value

    def lookup(self, full_name: str) -> Optional[Var]:
        with self._lock:
            canon = self._synonyms.get(full_name, full_name)
            return self._vars.get(canon)

    def set(self, full_name: str, value: Any) -> None:
        """Programmatic override (highest precedence)."""
        with self._lock:
            canon = self._synonyms.get(full_name, full_name)
            var = self._vars[canon]
            if var.read_only:
                raise ValueError(f"variable {full_name} is read-only")
            if isinstance(value, str) and var.vtype != VarType.STRING:
                value = var.parse(value)
            else:
                var._check(value)
            var.value = value
            var.source = VarSource.SET

    def all_vars(self) -> list[Var]:
        with self._lock:
            return sorted(self._vars.values(), key=lambda v: v.full_name)

    def dump(self, max_level: InfoLevel = InfoLevel.DEV_ALL) -> str:
        lines: list[str] = []
        for var in self.all_vars():
            if var.info_level > max_level:
                continue
            lines.append(
                f"{var.full_name} = {var.value!r}  "
                f"[{var.vtype.value}, {var.source.name.lower()}]"
                + (f"  # {var.description}" if var.description else "")
            )
        return "\n".join(lines)


var_registry = VarRegistry()


def register_var(
    framework: str,
    name: str,
    vtype: VarType | str,
    default: Any,
    description: str = "",
    **kw: Any,
) -> Var:
    if isinstance(vtype, str):
        vtype = VarType(vtype)
    return var_registry.register(
        Var(framework=framework, name=name, vtype=vtype, default=default,
            description=description, **kw)
    )


def get_var(full_name: str) -> Any:
    return var_registry.get(full_name)


def set_var(full_name: str, value: Any) -> None:
    var_registry.set(full_name, value)
