"""Component/framework registry — the Modular Component Architecture, TPU-native.

The reference's single most load-bearing design idea (opal/mca/mca.h:281-343,
opal/mca/base/mca_base_framework.h:127-157, mca_base_components_select.c) is a
uniform plugin system: every subsystem is a *framework* (a fixed interface)
holding N *components* (implementations), selected at runtime by priority and
user directives (``--mca coll xla``).

Here a framework is a named registry of ``Component`` subclasses.  Instead of
dlopen, components register via a decorator at import time; the selection
algorithm (priority query, include/exclude lists from the ``<framework>``
config variable, negation with ``^``) is preserved because it is what makes
behavior-gated substitution (``--mca coll xla`` vs byte-identical fallback)
possible — the north-star requirement of BASELINE.json.
"""

from __future__ import annotations

import threading
from typing import Any, Optional, Type

from ompi_tpu.core.config import VarType, register_var, var_registry
from ompi_tpu.core import output

__all__ = ["Component", "Framework", "framework_registry", "ComponentError"]


class ComponentError(RuntimeError):
    pass


class Component:
    """Base class for all components (≈ mca_base_component_2_1_0_t).

    Subclasses set ``NAME`` and ``PRIORITY`` and may override the lifecycle
    hooks.  ``query()`` returns (priority, module): a component may decline
    selection in the current context by returning None (≈ mca_query_component
    returning OMPI_ERR_NOT_AVAILABLE).
    """

    NAME: str = ""
    PRIORITY: int = 0
    FRAMEWORK: str = ""  # filled in by Framework.component()

    def register_params(self) -> None:
        """Register this component's config vars (≈ mca_register_component_params)."""

    def open(self) -> None:
        """Called once when the framework opens (≈ mca_open_component)."""

    def close(self) -> None:
        """Called at framework close (≈ mca_close_component)."""

    def query(self, **context: Any) -> Optional[int]:
        """Return selection priority for this context, or None to decline."""
        return self.PRIORITY

    @property
    def full_name(self) -> str:
        return f"{self.FRAMEWORK}/{self.NAME}"


class Framework:
    """A plugin slot: fixed interface, N components, priority selection.

    Selection directives come from the config variable named after the
    framework (settable via ``--mca <fw> a,b`` / env / file):

    - ``""``        → all components eligible, highest query() wins
    - ``"xla"``     → only the listed component(s) eligible (error if none)
    - ``"^xla"``    → all but the listed components eligible

    This mirrors mca_base_components_select.c's include/exclude semantics.
    """

    def __init__(self, name: str, description: str = "") -> None:
        self.name = name
        self.description = description
        self._components: dict[str, Component] = {}
        self._lock = threading.RLock()
        self._opened = False
        self._opened_components: set[str] = set()
        register_var(
            name, "", VarType.STRING, "",
            description=f"Component selection for the {name} framework "
                        f"(comma list; prefix with ^ to exclude)",
            synonyms=(name,),
        )
        framework_registry.add(self)

    # -- registration ---------------------------------------------------

    def component(self, cls: Type[Component]) -> Type[Component]:
        """Class decorator registering a component with this framework."""
        if not cls.NAME:
            raise ComponentError(f"component {cls!r} has no NAME")
        cls.FRAMEWORK = self.name
        with self._lock:
            if cls.NAME in self._components:
                raise ComponentError(
                    f"duplicate component {self.name}/{cls.NAME}")
            inst = cls()
            inst.register_params()
            self._components[cls.NAME] = inst
        return cls

    def add_instance(self, inst: Component) -> None:
        inst.FRAMEWORK = self.name
        with self._lock:
            if inst.NAME in self._components:
                raise ComponentError(
                    f"duplicate component {self.name}/{inst.NAME}")
            self._components[inst.NAME] = inst
            inst.register_params()
            if self._opened:
                inst.open()
                self._opened_components.add(inst.NAME)

    # -- lifecycle ------------------------------------------------------

    def open(self) -> None:
        """Open all currently-eligible components. Idempotent per component:
        a component newly made eligible by a later directive change is opened
        on the next open()/select() call; close() only closes what opened."""
        with self._lock:
            for comp in self._eligible():
                if comp.NAME not in self._opened_components:
                    comp.open()
                    self._opened_components.add(comp.NAME)
            self._opened = True

    def close(self) -> None:
        with self._lock:
            if not self._opened:
                return
            for name in self._opened_components:
                self._components[name].close()
            self._opened_components.clear()
            self._opened = False

    # -- selection ------------------------------------------------------

    def _directive(self) -> tuple[set[str], bool]:
        """Parse the selection variable → (names, is_exclude)."""
        raw = (var_registry.get(f"{self.name}_") or "").strip()
        if not raw:
            return set(), True  # exclude-nothing == everything eligible
        if raw.startswith("^"):
            return {s.strip() for s in raw[1:].split(",") if s.strip()}, True
        return {s.strip() for s in raw.split(",") if s.strip()}, False

    def _eligible(self) -> list[Component]:
        names, is_exclude = self._directive()
        comps: list[Component] = []
        with self._lock:
            components = dict(self._components)
        for name, comp in components.items():
            if is_exclude:
                if name in names:
                    continue
            else:
                if name not in names:
                    continue
            comps.append(comp)
        if not is_exclude:
            missing = names - set(components)
            if missing:
                output.show_help(
                    "mca", "component-not-found",
                    framework=self.name, components=", ".join(sorted(missing)),
                    available=", ".join(sorted(components)),
                )
                raise ComponentError(
                    f"requested {self.name} component(s) not found: "
                    f"{sorted(missing)}")
        return comps

    def select(self, **context: Any) -> Component:
        """Pick the single highest-priority component that accepts `context`."""
        best = self.select_all(**context)
        if not best:
            raise ComponentError(
                f"no {self.name} component available for context {context!r}")
        return best[0]

    def select_all(self, **context: Any) -> list[Component]:
        """All accepting components, highest priority first (for stacked
        frameworks like coll where modules layer per-function)."""
        self.open()
        scored: list[tuple[int, Component]] = []
        for comp in self._eligible():
            pri = comp.query(**context)
            if pri is None:
                continue
            scored.append((pri, comp))
        scored.sort(key=lambda pc: (-pc[0], pc[1].NAME))
        return [c for _, c in scored]

    def components(self) -> dict[str, Component]:
        with self._lock:
            return dict(self._components)

    def lookup(self, name: str) -> Component:
        try:
            return self._components[name]
        except KeyError:
            raise ComponentError(f"no component {self.name}/{name}") from None


class _FrameworkRegistry:
    """Global directory of frameworks (for the info tool and tests)."""

    def __init__(self) -> None:
        self._frameworks: dict[str, Framework] = {}
        self._lock = threading.Lock()

    def add(self, fw: Framework) -> None:
        with self._lock:
            if fw.name in self._frameworks:
                raise ComponentError(f"duplicate framework {fw.name}")
            self._frameworks[fw.name] = fw

    def get(self, name: str) -> Framework:
        return self._frameworks[name]

    def all(self) -> dict[str, Framework]:
        with self._lock:
            return dict(self._frameworks)

    def close_all(self) -> None:
        for fw in self._frameworks.values():
            fw.close()


framework_registry = _FrameworkRegistry()
