"""Logging streams and aggregated user diagnostics.

TPU-native equivalent of ``opal_output`` (opal/util/output.{c,h}) and
``opal_show_help`` (opal/util/show_help.h:32-78):

- ``Stream``: named verbosity-controlled debug streams.  Each stream's
  verbosity is a registered config var (``output_<stream>_verbose``) so it is
  settable via ``--mca``/env/file exactly like the reference's per-framework
  ``*_base_verbose`` params.
- ``show_help``: templated, *deduplicated* user-facing diagnostics.  The
  reference aggregates identical help messages across ranks at the HNP; here
  duplicates within a process are counted and suppressed, and in host process
  mode the launcher aggregates across ranks over the control plane.

Help templates live in ``ompi_tpu/core/help/help-<topic>.txt`` as
``[tag]``-sectioned text files, the reference's format.
"""

from __future__ import annotations

import os
import sys
import threading

__all__ = ["Stream", "get_stream", "show_help", "ShowHelpError", "help_text"]

_lock = threading.RLock()
_streams: dict[str, "Stream"] = {}
_seen_help: dict[tuple, int] = {}
_HELP_DIR = os.path.join(os.path.dirname(__file__), "help")


class Stream:
    """A named output stream with a verbosity level.

    Levels follow the reference convention: 0 = errors/off, higher = chattier.
    ``stream.verbose(level, msg)`` prints only if the stream's configured
    verbosity >= level.
    """

    def __init__(self, name: str, default_verbosity: int = 0) -> None:
        from ompi_tpu.core.config import VarType, register_var

        self.name = name
        self._var = register_var(
            "output", f"{name}_verbose", VarType.INT, default_verbosity,
            description=f"Verbosity for the {name} output stream",
        )

    @property
    def verbosity(self) -> int:
        return self._var.value

    def verbose(self, level: int, msg: str, *args: object) -> None:
        if self.verbosity >= level:
            self._emit(msg % args if args else msg)

    def error(self, msg: str, *args: object) -> None:
        self._emit("ERROR: " + (msg % args if args else msg))

    def emit(self, msg: str, *args: object) -> None:
        """Unconditional output (no verbosity gate, no ERROR prefix) — for
        messages that already passed their own filter (e.g. the notifier's
        severity threshold)."""
        self._emit(msg % args if args else msg)

    def _emit(self, text: str) -> None:
        rank = os.environ.get("OMPI_TPU_RANK")
        prefix = f"[{self.name}" + (f":{rank}" if rank is not None else "") + "] "
        print(prefix + text, file=sys.stderr, flush=True)


def get_stream(name: str, default_verbosity: int = 0) -> Stream:
    with _lock:
        st = _streams.get(name)
        if st is None:
            st = _streams[name] = Stream(name, default_verbosity)
        return st


class ShowHelpError(KeyError):
    pass


def help_text(topic: str, tag: str, **subst: object) -> str:
    """Load ``help-<topic>.txt``, extract the ``[tag]`` section, substitute."""
    path = os.path.join(_HELP_DIR, f"help-{topic}.txt")
    try:
        with open(path) as fh:
            content = fh.read()
    except OSError:
        raise ShowHelpError(f"no help file for topic {topic!r} ({path})")
    lines = content.splitlines()
    out: list[str] = []
    in_section = False
    for line in lines:
        if line.startswith("[") and line.rstrip().endswith("]"):
            if in_section:
                break
            in_section = line.strip() == f"[{tag}]"
            continue
        if in_section:
            out.append(line)
    if not out and not in_section:
        raise ShowHelpError(f"no [{tag}] section in help-{topic}.txt")
    body = "\n".join(out).strip("\n")
    if not subst:
        return body
    try:
        return body % subst
    except (KeyError, ValueError) as e:
        # Template/call-site drift must stay visible, not print raw %(x)s.
        return (body + f"\n[show_help: substitution failed for "
                       f"help-{topic}.txt [{tag}]: {e!r}; args={subst}]")


def show_help(topic: str, tag: str, want_error_header: bool = True,
              **subst: object) -> None:
    """Emit an aggregated user-facing diagnostic (≈ opal_show_help).

    Repeated *identical* diagnostics (same topic, tag, and substitutions) are
    suppressed after the first occurrence and a count is kept;
    ``flush_help_counts`` reports them, mirroring the reference's 'N more
    processes sent this message' aggregation.  Distinct substitutions are
    distinct messages and all print.
    """
    key = (topic, tag, tuple(sorted((k, repr(v)) for k, v in subst.items())))
    with _lock:
        count = _seen_help.get(key, 0)
        _seen_help[key] = count + 1
        if count:
            return
    try:
        body = help_text(topic, tag, **subst)
    except ShowHelpError:
        body = f"(missing help text: topic={topic} tag={tag} args={subst})"
    if want_error_header:
        bar = "-" * 76
        body = f"{bar}\n{body}\n{bar}"
    print(body, file=sys.stderr, flush=True)


def flush_help_counts() -> list[tuple[str, str, int]]:
    """Return and reset suppressed-duplicate counts (launcher calls at exit)."""
    with _lock:
        out = [(k[0], k[1], n - 1) for k, n in _seen_help.items() if n > 1]
        _seen_help.clear()
    return out
