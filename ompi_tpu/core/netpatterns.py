"""Communication-pattern topology helpers.

≈ ompi/patterns/net (netpatterns k-ary/binomial trees) + the peer
schedules hard-wired into the reference's collective algorithms: pure
functions from (rank, size, …) to parents/children/peer lists, shared by
anything that fans out over ranks — the RML routed overlay uses the k-ary
tree, the collective library's round structures correspond to the
recursive-doubling/Bruck schedules.

Everything is rooted-at-0 in a *virtual* rank space; callers with a
different root rotate ranks ((rank - root) % size) before and after, the
same shift the reference's coll_base_topo does.
"""

from __future__ import annotations

from typing import Optional

__all__ = ["kary_parent", "kary_children", "binomial_parent",
           "binomial_children", "recursive_doubling_peers", "bruck_peers",
           "tree_depth"]


def kary_parent(rank: int, k: int = 2) -> Optional[int]:
    """Parent in the k-ary tree over 0..n-1 (None for the root)."""
    if k < 1:
        raise ValueError(f"k must be >= 1, got {k}")
    return None if rank == 0 else (rank - 1) // k


def kary_children(rank: int, n: int, k: int = 2) -> list[int]:
    """Children of ``rank`` in the k-ary tree over 0..n-1."""
    if k < 1:
        raise ValueError(f"k must be >= 1, got {k}")
    first = k * rank + 1
    return [c for c in range(first, first + k) if c < n]


def binomial_parent(rank: int) -> Optional[int]:
    """Parent in the binomial tree (clear the lowest set bit) — the shape
    of the reference's bcast/reduce binomial (coll_base_bcast.c:313)."""
    return None if rank == 0 else rank & (rank - 1)


def binomial_children(rank: int, n: int) -> list[int]:
    """Children of ``rank`` in the binomial tree over 0..n-1: rank + 2^j
    for every bit below rank's lowest set bit (all bits for the root),
    ascending."""
    children = []
    lsb = rank & -rank if rank else None
    bit = 1
    while (lsb is None or bit < lsb) and rank + bit < n:
        children.append(rank + bit)
        bit <<= 1
    return children


def recursive_doubling_peers(rank: int, size: int) -> list[int]:
    """Peer per round of recursive doubling (round r: rank XOR 2^r) for
    the power-of-two prefix; callers handle the non-power-of-two fold the
    way coll_base_allreduce.c:128 does."""
    peers = []
    bit = 1
    while bit < size:
        peer = rank ^ bit
        if peer < size:
            peers.append(peer)
        bit <<= 1
    return peers


def bruck_peers(rank: int, size: int) -> list[tuple[int, int]]:
    """(send_to, recv_from) per Bruck round (round r: distance 2^r) —
    the allgather/alltoall Bruck schedule (coll_base_allgather.c:85)."""
    out = []
    dist = 1
    while dist < size:
        out.append(((rank - dist) % size, (rank + dist) % size))
        dist <<= 1
    return out


def tree_depth(n: int, k: int = 2) -> int:
    """Depth of the k-ary tree over n ranks (0 for a single rank)."""
    depth, reach, level = 0, 1, 1
    while reach < n:
        level *= k
        reach += level
        depth += 1
    return depth
