"""Host topology discovery — the hwloc-lite.

≈ the role opal's vendored hwloc plays for ras/rmaps (opal/mca/hwloc):
how many packages/cores/threads does this host have, what accelerators
are attached, and which CPUs may this process use.  Reads Linux /sys
and falls back to ``os.cpu_count`` elsewhere; no external dependency —
the consumers (ras slot counts, rmaps binding, diagnostics) need counts
and ids, not hwloc's full tree.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

__all__ = ["Topology", "discover"]


@dataclasses.dataclass(frozen=True)
class Topology:
    """One host's compute layout."""

    logical_cpus: int          # schedulable hardware threads
    physical_cores: int        # distinct (package, core) pairs
    packages: int              # sockets
    allowed_cpus: int          # this process's cpuset width (affinity)
    accelerators: int          # non-CPU jax devices visible (0 = none/unknown)

    @property
    def smt(self) -> int:
        """Hardware threads per core (≥1)."""
        return max(1, self.logical_cpus // max(1, self.physical_cores))


def _sysfs_topology() -> Optional[tuple[int, int, int]]:
    """(logical, cores, packages) from /sys, or None off-Linux."""
    base = "/sys/devices/system/cpu"
    try:
        cpus = [d for d in os.listdir(base)
                if d.startswith("cpu") and d[3:].isdigit()]
    except OSError:
        return None
    if not cpus:
        return None
    pairs = set()
    packages = set()
    logical = 0
    for c in cpus:
        tdir = os.path.join(base, c, "topology")
        try:
            with open(os.path.join(tdir, "core_id")) as f:
                core = int(f.read())
            with open(os.path.join(tdir, "physical_package_id")) as f:
                pkg = int(f.read())
        except (OSError, ValueError):
            continue
        logical += 1
        pairs.add((pkg, core))
        packages.add(pkg)
    if not logical:
        return None
    return logical, len(pairs), len(packages)


def discover(probe_accelerators: bool = False) -> Topology:
    """Inspect this host.  ``probe_accelerators`` touches jax (may
    initialize a backend — callers on the launch path keep it False and
    let the app side probe)."""
    sysfs = _sysfs_topology()
    if sysfs is not None:
        logical, cores, pkgs = sysfs
    else:
        logical = os.cpu_count() or 1
        cores, pkgs = logical, 1
    try:
        allowed = len(os.sched_getaffinity(0))
    except (AttributeError, OSError):
        allowed = logical
    accel = 0
    if probe_accelerators:
        try:
            import jax

            accel = sum(1 for d in jax.devices() if d.platform != "cpu")
        except Exception:  # noqa: BLE001 — no backend ⇒ no accelerators
            accel = 0
    return Topology(logical_cpus=logical, physical_cores=cores,
                    packages=pkgs, allowed_cpus=allowed,
                    accelerators=accel)
