"""Core substrate (≈ the reference's OPAL layer, opal/).

Single-process portability and plumbing: the component/plugin registry
(``mca``), the typed configuration-variable registry (``config``), structured
logging and aggregated user diagnostics (``output``), control-message
serialization (``dss``), and the buffer-location abstraction (``buffer``)
that threads device/host duality through the whole stack the way the
reference threads its CUDA convertor flag (opal/datatype/opal_convertor.h:43-59).
"""
