"""Core substrate (≈ the reference's OPAL layer, opal/).

Single-process portability and plumbing: the component/plugin registry
(``mca``), the typed configuration-variable registry (``config``), structured
logging and aggregated user diagnostics (``output``), control-message
serialization (``dss``), and the buffer-location abstraction (``buffer``)
that threads device/host duality through the whole stack the way the
reference threads its CUDA convertor flag (opal/datatype/opal_convertor.h:43-59).
"""

import os as _os

__all__ = ["pkg_root"]


def pkg_root() -> str:
    """Directory CONTAINING the ompi_tpu package — what a child process
    needs on PYTHONPATH to import this framework (≈ plm_rsh prefixing its
    install dirs, plm_rsh_module.c).  One definition so local and remote
    launch paths cannot drift."""
    return _os.path.dirname(_os.path.dirname(_os.path.dirname(
        _os.path.abspath(__file__))))
