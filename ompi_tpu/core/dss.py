"""DSS — self-describing typed serialization for control messages.

Equivalent of the reference's data storage service (opal/dss/dss.h:107,212):
control-plane messages (launch commands, modex business cards, IOF chunks)
are packed as a sequence of (type-tag, payload) records into a buffer and
unpacked with type checking on the far side.  Used by the runtime's RML
messaging and the host-path p2p bootstrap; *never* on the device data path
(device buffers move via XLA collectives, not serialization).

Wire format: little-endian; each record is [1B type][payload]; variable-length
payloads carry a u32 length.  Numpy arrays pack dtype + shape + raw bytes.
"""

from __future__ import annotations

import io
import struct
from typing import Any, Optional

import numpy as np

__all__ = ["Buffer", "pack", "unpack", "DSSError"]


class DSSError(ValueError):
    pass


# type tags
_T_INT64 = 1
_T_FLOAT64 = 2
_T_STRING = 3
_T_BYTES = 4
_T_BOOL = 5
_T_NONE = 6
_T_LIST = 7
_T_DICT = 8
_T_NDARRAY = 9
_T_TUPLE = 10

_NAMES = {
    _T_INT64: "int", _T_FLOAT64: "float", _T_STRING: "str", _T_BYTES: "bytes",
    _T_BOOL: "bool", _T_NONE: "none", _T_LIST: "list", _T_DICT: "dict",
    _T_NDARRAY: "ndarray", _T_TUPLE: "tuple",
}


class Buffer:
    """An append/consume byte buffer (≈ opal_buffer_t)."""

    def __init__(self, data: bytes = b"") -> None:
        self._w = io.BytesIO()
        self._w.write(data)
        self._r = 0

    # -- pack -----------------------------------------------------------

    def pack(self, value: Any) -> "Buffer":
        w = self._w
        if value is None:
            w.write(bytes([_T_NONE]))
        elif isinstance(value, bool):  # before int: bool is an int subclass
            w.write(bytes([_T_BOOL, 1 if value else 0]))
        elif isinstance(value, int):
            w.write(bytes([_T_INT64]))
            w.write(struct.pack("<q", value))
        elif isinstance(value, float):
            w.write(bytes([_T_FLOAT64]))
            w.write(struct.pack("<d", value))
        elif isinstance(value, str):
            raw = value.encode()
            w.write(bytes([_T_STRING]))
            w.write(struct.pack("<I", len(raw)))
            w.write(raw)
        elif isinstance(value, (bytes, bytearray, memoryview)):
            raw = bytes(value)
            w.write(bytes([_T_BYTES]))
            w.write(struct.pack("<I", len(raw)))
            w.write(raw)
        elif isinstance(value, np.ndarray):
            dt = value.dtype.str.encode()
            # ascontiguousarray promotes 0-d to 1-d; shape metadata must come
            # from the original value.
            arr = np.ascontiguousarray(value)
            w.write(bytes([_T_NDARRAY]))
            w.write(struct.pack("<B", len(dt)))
            w.write(dt)
            w.write(struct.pack("<B", value.ndim))
            w.write(struct.pack(f"<{value.ndim}q", *value.shape))
            raw = arr.tobytes()
            w.write(struct.pack("<Q", len(raw)))
            w.write(raw)
        elif isinstance(value, (list, tuple)):
            w.write(bytes([_T_LIST if isinstance(value, list) else _T_TUPLE]))
            w.write(struct.pack("<I", len(value)))
            for item in value:
                self.pack(item)
        elif isinstance(value, dict):
            w.write(bytes([_T_DICT]))
            w.write(struct.pack("<I", len(value)))
            for k, v in value.items():
                self.pack(k)
                self.pack(v)
        else:
            raise DSSError(f"cannot pack value of type {type(value).__name__}")
        return self

    # -- unpack ---------------------------------------------------------

    def _read(self, n: int) -> bytes:
        # getbuffer() is a zero-copy view; only the n requested bytes are
        # copied out (getvalue() would copy the whole buffer per record).
        with self._w.getbuffer() as view:
            if self._r + n > len(view):
                raise DSSError("buffer underrun")
            out = bytes(view[self._r:self._r + n])
        self._r += n
        return out

    def unpack(self, expect: Optional[type] = None) -> Any:
        tag = self._read(1)[0]
        if tag == _T_NONE:
            value: Any = None
        elif tag == _T_BOOL:
            value = bool(self._read(1)[0])
        elif tag == _T_INT64:
            value = struct.unpack("<q", self._read(8))[0]
        elif tag == _T_FLOAT64:
            value = struct.unpack("<d", self._read(8))[0]
        elif tag == _T_STRING:
            (n,) = struct.unpack("<I", self._read(4))
            value = self._read(n).decode()
        elif tag == _T_BYTES:
            (n,) = struct.unpack("<I", self._read(4))
            value = self._read(n)
        elif tag == _T_NDARRAY:
            (dn,) = struct.unpack("<B", self._read(1))
            dt = np.dtype(self._read(dn).decode())
            (ndim,) = struct.unpack("<B", self._read(1))
            shape = struct.unpack(f"<{ndim}q", self._read(8 * ndim)) if ndim else ()
            (nb,) = struct.unpack("<Q", self._read(8))
            value = np.frombuffer(self._read(nb), dtype=dt).reshape(shape).copy()
        elif tag in (_T_LIST, _T_TUPLE):
            (n,) = struct.unpack("<I", self._read(4))
            items = [self.unpack() for _ in range(n)]
            value = items if tag == _T_LIST else tuple(items)
        elif tag == _T_DICT:
            (n,) = struct.unpack("<I", self._read(4))
            value = {}
            for _ in range(n):
                k = self.unpack()
                value[k] = self.unpack()
        else:
            raise DSSError(f"unknown type tag {tag}")
        if expect is not None and not isinstance(value, expect):
            raise DSSError(
                f"type mismatch: expected {expect.__name__}, "
                f"got {_NAMES.get(tag, tag)}")
        return value

    def remaining(self) -> int:
        with self._w.getbuffer() as view:  # zero-copy size probe
            return len(view) - self._r

    def bytes(self) -> bytes:
        return self._w.getvalue()


def pack(*values: Any) -> bytes:
    buf = Buffer()
    for v in values:
        buf.pack(v)
    return buf.bytes()


def unpack(data: bytes, n: Optional[int] = None) -> list[Any]:
    buf = Buffer(data)
    out = []
    while buf.remaining() and (n is None or len(out) < n):
        out.append(buf.unpack())
    return out
