"""DSS — self-describing typed serialization for control messages.

Equivalent of the reference's data storage service (opal/dss/dss.h:107,212):
control-plane messages (launch commands, modex business cards, IOF chunks)
are packed as a sequence of (type-tag, payload) records into a buffer and
unpacked with type checking on the far side.  Used by the runtime's RML
messaging and the host-path p2p bootstrap; *never* on the device data path
(device buffers move via XLA collectives, not serialization).

Wire format: little-endian; each record is [1B type][payload]; variable-length
payloads carry a u32 length.  Numpy arrays pack dtype + shape + raw bytes.
"""

from __future__ import annotations

import io
import struct
from typing import Any, Optional

import numpy as np

__all__ = ["Buffer", "pack", "unpack", "DSSError"]


class DSSError(ValueError):
    pass


# type tags
_T_INT64 = 1
_T_FLOAT64 = 2
_T_STRING = 3
_T_BYTES = 4
_T_BOOL = 5
_T_NONE = 6
_T_LIST = 7
_T_DICT = 8
_T_NDARRAY = 9
_T_TUPLE = 10

_NAMES = {
    _T_INT64: "int", _T_FLOAT64: "float", _T_STRING: "str", _T_BYTES: "bytes",
    _T_BOOL: "bool", _T_NONE: "none", _T_LIST: "list", _T_DICT: "dict",
    _T_NDARRAY: "ndarray", _T_TUPLE: "tuple",
}


class Buffer:
    """An append/consume byte buffer (≈ opal_buffer_t)."""

    def __init__(self, data: bytes = b"") -> None:
        self._w = io.BytesIO()
        self._w.write(data)
        self._r = 0

    # -- pack -----------------------------------------------------------

    def pack(self, value: Any) -> "Buffer":
        w = self._w
        if value is None:
            w.write(bytes([_T_NONE]))
        elif isinstance(value, bool):  # before int: bool is an int subclass
            w.write(bytes([_T_BOOL, 1 if value else 0]))
        elif isinstance(value, int):
            w.write(bytes([_T_INT64]))
            w.write(struct.pack("<q", value))
        elif isinstance(value, float):
            w.write(bytes([_T_FLOAT64]))
            w.write(struct.pack("<d", value))
        elif isinstance(value, str):
            raw = value.encode()
            w.write(bytes([_T_STRING]))
            w.write(struct.pack("<I", len(raw)))
            w.write(raw)
        elif isinstance(value, (bytes, bytearray, memoryview)):
            raw = bytes(value)
            w.write(bytes([_T_BYTES]))
            w.write(struct.pack("<I", len(raw)))
            w.write(raw)
        elif isinstance(value, np.ndarray):
            dt = value.dtype.str.encode()
            # ascontiguousarray promotes 0-d to 1-d; shape metadata must come
            # from the original value.
            arr = np.ascontiguousarray(value)
            w.write(bytes([_T_NDARRAY]))
            w.write(struct.pack("<B", len(dt)))
            w.write(dt)
            w.write(struct.pack("<B", value.ndim))
            w.write(struct.pack(f"<{value.ndim}q", *value.shape))
            raw = arr.tobytes()
            w.write(struct.pack("<Q", len(raw)))
            w.write(raw)
        elif isinstance(value, (list, tuple)):
            w.write(bytes([_T_LIST if isinstance(value, list) else _T_TUPLE]))
            w.write(struct.pack("<I", len(value)))
            for item in value:
                self.pack(item)
        elif isinstance(value, dict):
            w.write(bytes([_T_DICT]))
            w.write(struct.pack("<I", len(value)))
            for k, v in value.items():
                self.pack(k)
                self.pack(v)
        else:
            raise DSSError(f"cannot pack value of type {type(value).__name__}")
        return self

    # -- unpack ---------------------------------------------------------

    def _read(self, n: int) -> bytes:
        # getbuffer() is a zero-copy view; only the n requested bytes are
        # copied out (getvalue() would copy the whole buffer per record).
        with self._w.getbuffer() as view:
            if self._r + n > len(view):
                raise DSSError("buffer underrun")
            out = bytes(view[self._r:self._r + n])
        self._r += n
        return out

    def unpack(self, expect: Optional[type] = None) -> Any:
        tag = self._read(1)[0]
        if tag == _T_NONE:
            value: Any = None
        elif tag == _T_BOOL:
            value = bool(self._read(1)[0])
        elif tag == _T_INT64:
            value = struct.unpack("<q", self._read(8))[0]
        elif tag == _T_FLOAT64:
            value = struct.unpack("<d", self._read(8))[0]
        elif tag == _T_STRING:
            (n,) = struct.unpack("<I", self._read(4))
            value = self._read(n).decode()
        elif tag == _T_BYTES:
            (n,) = struct.unpack("<I", self._read(4))
            value = self._read(n)
        elif tag == _T_NDARRAY:
            (dn,) = struct.unpack("<B", self._read(1))
            dt = np.dtype(self._read(dn).decode())
            (ndim,) = struct.unpack("<B", self._read(1))
            shape = struct.unpack(f"<{ndim}q", self._read(8 * ndim)) if ndim else ()
            (nb,) = struct.unpack("<Q", self._read(8))
            value = np.frombuffer(self._read(nb), dtype=dt).reshape(shape).copy()
        elif tag in (_T_LIST, _T_TUPLE):
            (n,) = struct.unpack("<I", self._read(4))
            items = [self.unpack() for _ in range(n)]
            value = items if tag == _T_LIST else tuple(items)
        elif tag == _T_DICT:
            (n,) = struct.unpack("<I", self._read(4))
            value = {}
            for _ in range(n):
                k = self.unpack()
                value[k] = self.unpack()
        else:
            raise DSSError(f"unknown type tag {tag}")
        if expect is not None and not isinstance(value, expect):
            raise DSSError(
                f"type mismatch: expected {expect.__name__}, "
                f"got {_NAMES.get(tag, tag)}")
        return value

    def remaining(self) -> int:
        with self._w.getbuffer() as view:  # zero-copy size probe
            return len(view) - self._r

    def bytes(self) -> bytes:
        return self._w.getvalue()


# -- fast module-level codecs -------------------------------------------
#
# Every shm/tcp frame and RML message pays one pack + one unpack of a
# small header dict; the Buffer class's per-record BytesIO getbuffer()
# export made that ~9µs/33µs per header.  These standalone codecs emit
# the identical wire format with prebound structs and a single cursor
# (measured ~8× faster on a 7-key header); Buffer remains for
# incremental append/consume use.

_Sq = struct.Struct("<q")
_Sd = struct.Struct("<d")
_SI = struct.Struct("<I")
_SQ8 = struct.Struct("<Q")
_B_NONE = bytes([_T_NONE])
_B_TRUE = bytes([_T_BOOL, 1])
_B_FALSE = bytes([_T_BOOL, 0])
_B_INT = bytes([_T_INT64])
_B_FLOAT = bytes([_T_FLOAT64])
_B_STR = bytes([_T_STRING])
_B_BYTES = bytes([_T_BYTES])
_B_LIST = bytes([_T_LIST])
_B_TUPLE = bytes([_T_TUPLE])
_B_DICT = bytes([_T_DICT])


def _pack_into(parts: list, value: Any) -> None:
    t = type(value)
    if t is int:
        parts.append(_B_INT)
        parts.append(_Sq.pack(value))
    elif t is str:
        raw = value.encode()
        parts.append(_B_STR)
        parts.append(_SI.pack(len(raw)))
        parts.append(raw)
    elif value is None:
        parts.append(_B_NONE)
    elif t is bool:
        parts.append(_B_TRUE if value else _B_FALSE)
    elif t is float:
        parts.append(_B_FLOAT)
        parts.append(_Sd.pack(value))
    elif t is bytes or t is bytearray or t is memoryview:
        raw = bytes(value)
        parts.append(_B_BYTES)
        parts.append(_SI.pack(len(raw)))
        parts.append(raw)
    elif t is list or t is tuple:
        parts.append(_B_LIST if t is list else _B_TUPLE)
        parts.append(_SI.pack(len(value)))
        for item in value:
            _pack_into(parts, item)
    elif t is dict:
        parts.append(_B_DICT)
        parts.append(_SI.pack(len(value)))
        for k, v in value.items():
            _pack_into(parts, k)
            _pack_into(parts, v)
    else:
        # subclasses and ndarrays take the general Buffer path (identical
        # wire format; just not the single-isinstance fast lane)
        b = Buffer()
        b.pack(value)
        parts.append(b.bytes())


_fast = None
_fast_tried = False


def _fastmod():
    """The compiled codec (ompi_tpu._native.fastdss), or None."""
    global _fast, _fast_tried
    if not _fast_tried:
        _fast_tried = True
        try:
            from ompi_tpu import _native

            _fast = _native.fastdss()
        except Exception:  # noqa: BLE001 — loader failure → python codec
            _fast = None
    return _fast


def pack(*values: Any) -> bytes:
    fast = _fastmod()
    if fast is not None:
        try:
            return fast.pack(values)
        except fast.Unsupported:
            pass          # exotic type (ndarray, subclass): python codec
    parts: list = []
    for v in values:
        _pack_into(parts, v)
    return b"".join(parts)


def _unpack_one(data: bytes, pos: int) -> tuple[Any, int]:
    tag = data[pos]
    pos += 1
    if tag == _T_INT64:
        return _Sq.unpack_from(data, pos)[0], pos + 8
    if tag == _T_STRING:
        n = _SI.unpack_from(data, pos)[0]
        pos += 4
        if pos + n > len(data):   # slicing would silently truncate
            raise DSSError("buffer underrun in string")
        return data[pos:pos + n].decode(), pos + n
    if tag == _T_NONE:
        return None, pos
    if tag == _T_BOOL:
        return bool(data[pos]), pos + 1
    if tag == _T_FLOAT64:
        return _Sd.unpack_from(data, pos)[0], pos + 8
    if tag == _T_BYTES:
        n = _SI.unpack_from(data, pos)[0]
        pos += 4
        if pos + n > len(data):
            raise DSSError("buffer underrun in bytes")
        return data[pos:pos + n], pos + n
    if tag == _T_LIST or tag == _T_TUPLE:
        n = _SI.unpack_from(data, pos)[0]
        pos += 4
        items = []
        for _ in range(n):
            v, pos = _unpack_one(data, pos)
            items.append(v)
        return (items if tag == _T_LIST else tuple(items)), pos
    if tag == _T_DICT:
        n = _SI.unpack_from(data, pos)[0]
        pos += 4
        out = {}
        for _ in range(n):
            k, pos = _unpack_one(data, pos)
            out[k], pos = _unpack_one(data, pos)
        return out, pos
    if tag == _T_NDARRAY:
        dn = data[pos]
        pos += 1
        dt = np.dtype(data[pos:pos + dn].decode())
        pos += dn
        ndim = data[pos]
        pos += 1
        shape = struct.unpack_from(f"<{ndim}q", data, pos) if ndim else ()
        pos += 8 * ndim
        nb = _SQ8.unpack_from(data, pos)[0]
        pos += 8
        if pos + nb > len(data):
            raise DSSError("buffer underrun in ndarray")
        value = np.frombuffer(data[pos:pos + nb],
                              dtype=dt).reshape(shape).copy()
        return value, pos + nb
    raise DSSError(f"unknown type tag {tag}")


def unpack(data: bytes, n: Optional[int] = None) -> list[Any]:
    if not isinstance(data, (bytes, bytearray, memoryview)):
        data = bytes(data)     # uniform accept surface for both codecs
    fast = _fastmod()
    if fast is not None:
        try:
            return fast.unpack(data, -1 if n is None else n)
        except fast.Unsupported:
            pass          # ndarray record: python codec handles the call
        except ValueError as e:
            raise DSSError(str(e)) from None
    if not isinstance(data, bytes):
        data = bytes(data)
    out: list[Any] = []
    pos = 0
    end = len(data)
    try:
        while pos < end and (n is None or len(out) < n):
            v, pos = _unpack_one(data, pos)
            out.append(v)
    except (IndexError, struct.error, ValueError, TypeError) as e:
        # TypeError: np.dtype on a truncated descriptor string
        if isinstance(e, DSSError):
            raise
        raise DSSError(f"buffer underrun: {e}") from None
    return out
