"""System introspection: timers, process stats, crash backtraces.

≈ three small OPAL frameworks the reference always builds:

- **timer** (``opal/mca/timer``): monotonic + cycle-resolution timestamps.
  On modern CPython ``time.perf_counter_ns`` already reads the best
  monotonic clock the OS offers, so the framework collapses to a thin,
  testable facade with an interval helper.
- **pstat** (``opal/mca/pstat``): per-process resource usage read from
  ``/proc`` (Linux) or ``resource`` (portable) — RSS, user/system time,
  thread count.  The launcher/daemons report these in diagnostics.
- **backtrace** (``opal/mca/backtrace`` + ``opal/util/stacktrace.c``,
  registered at ``opal/runtime/opal_init.c:440-444``): install signal
  handlers that dump every thread's Python stack on fatal signals —
  CPython's ``faulthandler`` is exactly this mechanism.
"""

from __future__ import annotations

import os
import time
from typing import Optional

__all__ = ["Timer", "proc_stats", "install_backtrace_handlers"]


class Timer:
    """Monotonic interval timer (≈ opal_timer_base_get_cycles/usec)."""

    @staticmethod
    def cycles() -> int:
        """Highest-resolution monotonic tick (ns — the cycle analog)."""
        return time.perf_counter_ns()

    @staticmethod
    def usec() -> float:
        return time.perf_counter_ns() / 1e3

    @staticmethod
    def resolution_s() -> float:
        """Resolution of :meth:`cycles` in seconds (the underlying clock's
        resolution, floored at the 1ns integer truncation)."""
        return max(time.get_clock_info("perf_counter").resolution, 1e-9)

    def __init__(self) -> None:
        self._t0 = time.perf_counter_ns()

    def elapsed_s(self) -> float:
        return (time.perf_counter_ns() - self._t0) / 1e9

    def restart(self) -> float:
        """Return elapsed seconds and restart the interval."""
        now = time.perf_counter_ns()
        dt = (now - self._t0) / 1e9
        self._t0 = now
        return dt


def proc_stats(pid: Optional[int] = None) -> dict:
    """Resource usage for one process (≈ pstat query: rss, cpu, threads).

    Reads /proc when available (any pid), falls back to ``resource`` for
    the calling process on non-Linux.
    """
    pid = os.getpid() if pid is None else pid
    stats: dict = {"pid": pid}
    try:
        with open(f"/proc/{pid}/stat") as f:
            fields = f.read().rsplit(") ", 1)[1].split()
        tick = os.sysconf("SC_CLK_TCK")
        page = os.sysconf("SC_PAGE_SIZE")
        stats.update(
            state=fields[0],
            utime_s=int(fields[11]) / tick,
            stime_s=int(fields[12]) / tick,
            threads=int(fields[17]),
            vsize_bytes=int(fields[20]),
            rss_bytes=int(fields[21]) * page,
        )
        return stats
    except (OSError, IndexError, ValueError):
        pass
    try:  # portable fallback: self/children only
        import resource

        ru = resource.getrusage(resource.RUSAGE_SELF)
        stats.update(utime_s=ru.ru_utime, stime_s=ru.ru_stime,
                     rss_bytes=ru.ru_maxrss * 1024, threads=None,
                     state="?")
    except Exception:  # noqa: BLE001 — diagnostics must never raise
        pass
    return stats


_installed = False


def install_backtrace_handlers(all_threads: bool = True) -> bool:
    """Dump Python stacks of every thread on SIGSEGV/SIGFPE/SIGABRT/SIGBUS
    (≈ opal_util_register_stackhandlers).  Idempotent; returns whether the
    handlers are active."""
    global _installed
    if _installed:
        return True
    try:
        import faulthandler

        faulthandler.enable(all_threads=all_threads)
        _installed = True
    except Exception:  # noqa: BLE001 — e.g. no stderr in embedded use
        return False
    return True


def host_identity() -> str:
    """The canonical host identity — what reachability decisions, host
    keys, and MPI_Get_processor_name all report.  ``OMPI_TPU_FAKE_HOST``
    (set by the sim plm) overrides the nodename so co-located simulated
    hosts are genuinely distinct to every consumer at once."""
    return os.environ.get("OMPI_TPU_FAKE_HOST") or os.uname().nodename
