"""Autoregressive decoding with a KV cache — the inference counterpart
of the train step, built from the same layer math.

TPU-first shape: ONE compiled program per (prompt_len, max_new) pair —
prefill runs the training backbone once (``collect_kv`` returns every
layer's post-rope K/V in a single pass), then a ``lax.scan`` generates
tokens against a static-shape cache updated with
``lax.dynamic_update_slice`` (no growing arrays, no recompilation per
token).  Sharding: batch over dp, heads over tp (the cache is
head-sharded exactly like the weights); greedy argmax over the full
vocab.  Sequence parallelism is a training-time layout — decode
requires sp == 1.  MoE configs route each generated token through the
same ep-sharded switch as training; note the switch capacity is
computed per single-token step (B tokens), so under a binding capacity
the drop pattern can differ from a full-sequence forward — cached and
full paths agree exactly whenever capacity doesn't bind.
"""

from __future__ import annotations

from ompi_tpu.models.transformer import (TransformerConfig,
                                         _dense_ffn_tail, _rmsnorm,
                                         _rope, param_specs)

__all__ = ["make_decoder"]


def _step_layer(cfg: TransformerConfig, comm, lp, h, kc, vc, pos):
    """One layer for ONE new token position, updating this layer's cache.

    h: (B, 1, D); kc/vc: (B, Tmax, Hl, hd).  Returns (h, kc, vc) with
    the new token's k/v written at index ``pos``.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ompi_tpu.parallel.layers import column_parallel, row_parallel

    cdt = h.dtype
    B = h.shape[0]
    Tmax, hl, hd = kc.shape[1], kc.shape[2], kc.shape[3]

    x = _rmsnorm(h, lp["ln1"])
    q = column_parallel(x, lp["wq"].astype(cdt)).reshape(B, 1, hl, hd)
    k = column_parallel(x, lp["wk"].astype(cdt)).reshape(B, 1, hl, hd)
    v = column_parallel(x, lp["wv"].astype(cdt)).reshape(B, 1, hl, hd)
    q = _rope(q, pos[None])
    k = _rope(k, pos[None])
    kc = lax.dynamic_update_slice(kc, k.astype(kc.dtype), (0, pos, 0, 0))
    vc = lax.dynamic_update_slice(vc, v.astype(vc.dtype), (0, pos, 0, 0))
    # scores against every cached position, masked beyond `pos`
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   kc.astype(jnp.float32)) * (hd ** -0.5)
    live = jnp.arange(Tmax)[None, None, None, :] <= pos
    s = jnp.where(live, s, -1e30)
    w = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhqk,bkhd->bqhd", w, vc.astype(jnp.float32))
    o = o.astype(cdt).reshape(B, 1, hl * hd)
    h = h + row_parallel(o, lp["wo"].astype(cdt), comm, axis="tp")
    if cfg.moe_experts:
        from ompi_tpu.models.transformer import _moe_ffn_tail

        h, _aux = _moe_ffn_tail(cfg, h, lp, comm)  # aux: training-only
        return h, kc, vc
    return _dense_ffn_tail(h, lp, comm, cdt), kc, vc


def make_decoder(cfg: TransformerConfig, mesh, max_new: int,
                 temperature: float = 0.0, top_k: int = 0):
    """jitted (params, prompt (B, Tp) int32[, seed]) → (B, Tp+max_new).

    Greedy decode by default: prefill through the training backbone
    (one pass, K/V collected per layer), then ``max_new`` single-token
    steps over the static cache.  Requires sp == 1; dense and
    switch-MoE configs both supported (MoE routes each token through
    the same ep-sharded switch as training).

    ``temperature > 0`` switches to sampling (optionally truncated to
    the ``top_k`` highest logits); the returned callable then takes a
    third argument ``seed`` (int32 scalar).  Each step folds the
    position — and the dp coordinate, so data-parallel shards draw
    independent noise — into the key; tp ranks share the key and hence
    agree on every sampled token (their logits are identical).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from ompi_tpu.models import transformer as tfm
    from ompi_tpu.mpi.device_comm import DeviceCommunicator

    for ax in ("dp", "sp", "tp"):
        if ax not in mesh.shape:
            raise ValueError(f"decode needs a mesh with dp/sp/tp axes "
                             f"(missing {ax!r}; have "
                             f"{tuple(mesh.shape)})")
    if int(mesh.shape["sp"]) != 1:
        raise ValueError("decode requires sp == 1 (sequence parallelism "
                         "is a training-time layout)")
    axes = tuple(a for a in ("dp", "sp", "tp", "ep")
                 if a in mesh.axis_names)
    comm = DeviceCommunicator(mesh, axes)
    cdt = jnp.dtype(cfg.compute_dtype)
    keys = ["wq", "wk", "wv", "wo", "w1", "w2", "ln1", "ln2"]
    if cfg.moe_experts:
        keys.append("wg")
    if temperature < 0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    if top_k and not temperature:
        raise ValueError("top_k needs temperature > 0")
    if top_k < 0 or top_k > cfg.vocab:
        raise ValueError(f"top_k must be in [0, vocab={cfg.vocab}], "
                         f"got {top_k}")

    def pick(logits, pos, seed):
        """Next token from (B, V) f32 logits."""
        if not temperature:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        scaled = logits / jnp.float32(temperature)
        if top_k:
            kth = lax.top_k(scaled, top_k)[0][..., -1:]
            scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
        key = jax.random.fold_in(
            jax.random.fold_in(jax.random.PRNGKey(seed), pos),
            lax.axis_index("dp"))
        return jax.random.categorical(key, scaled,
                                      axis=-1).astype(jnp.int32)

    def local(params, prompt, seed):
        B, Tp = prompt.shape
        emb = params["emb"].astype(cdt)
        # ---- prefill: one training-backbone pass, K/V collected ----
        h, (_aux, ks, vs) = tfm._local_backbone(cfg, comm, params, prompt,
                                                collect_kv=True)
        pad = [(0, 0), (0, 0), (0, max_new), (0, 0), (0, 0)]
        kc = jnp.pad(ks, pad)       # (L, B, Tp+max_new, Hl, hd)
        vc = jnp.pad(vs, pad)
        logits = jnp.einsum("bd,vd->bv", h[:, -1, :], emb,
                            preferred_element_type=jnp.float32)
        tok0 = pick(logits, jnp.int32(Tp - 1), seed)          # (B,)

        layer_params = {k: params[k] for k in keys}

        def gen(carry, _):
            kc, vc, tok, pos = carry
            h = params["emb"][tok].astype(cdt)[:, None, :]    # (B, 1, D)

            def per_layer(h, inp):
                lp, kc_l, vc_l = inp
                h, kc_l, vc_l = _step_layer(cfg, comm, lp, h,
                                            kc_l, vc_l, pos)
                return h, (kc_l, vc_l)

            h, (kc, vc) = lax.scan(per_layer, h, (layer_params, kc, vc))
            h = _rmsnorm(h, params["lnf"])
            logits = jnp.einsum("bd,vd->bv", h[:, 0, :], emb,
                                preferred_element_type=jnp.float32)
            nxt = pick(logits, pos, seed)
            return (kc, vc, nxt, pos + 1), nxt

        # emit the PRODUCED token and scan max_new-1 steps: tok0 is
        # already known from prefill, so the last single-token pass is
        # not computed just to be thrown away
        (_, _, _, _), toks = lax.scan(
            gen, (kc, vc, tok0, jnp.int32(Tp)), None,
            length=max_new - 1)
        gen_toks = jnp.concatenate(
            [tok0[None], toks], axis=0)       # (max_new, B)
        return jnp.concatenate([prompt, gen_toks.swapaxes(0, 1)], axis=1)

    mapped = jax.shard_map(
        local, mesh=mesh,
        in_specs=(param_specs(P, cfg, mesh), P("dp", None), P()),
        out_specs=P("dp", None), check_vma=False)
    if temperature:
        return jax.jit(mapped)
    # greedy keeps its two-argument signature; seed is inert
    import numpy as _np

    jitted = jax.jit(mapped)
    return lambda params, prompt: jitted(params, prompt,
                                         _np.int32(0))
