"""Input pipeline: token batches onto the mesh, prefetched.

The counterpart of the train loop's device side — the host side keeps
the chip fed:

- :class:`TokenSource` readers: an in-memory array, or a memory-mapped
  token file (the flat uint16/int32 next-token-prediction corpus
  layout), sliced into (batch, seq+0) windows deterministically by
  step index, so every dp rank computes ITS slice of every global
  batch without coordination (rank r takes rows [r·b/dp, (r+1)·b/dp)).
- :func:`prefetch`: a double-buffered iterator that `device_put`s the
  NEXT global batch (with its dp sharding) while the current step
  computes — host→device transfer rides under the train step instead
  of serializing after it.

Everything is deterministic in (seed, step): resuming from a
checkpoint's step counter reproduces the exact batch stream, which is
what ties this to ckpt/ restart (no loader state to snapshot beyond
the step).
"""

from __future__ import annotations

import os
import queue
import threading
from typing import Iterator, Optional

import numpy as np

__all__ = ["TokenSource", "ArraySource", "MemmapSource", "prefetch",
           "batches"]


class TokenSource:
    """Deterministic (seed, step) → (batch, seq) int32 token windows."""

    def batch(self, step: int, batch: int, seq: int) -> np.ndarray:
        raise NotImplementedError


class ArraySource(TokenSource):
    """Windows over an in-memory 1-D token array (wraps around)."""

    def __init__(self, tokens: np.ndarray, seed: int = 0):
        self.tokens = np.ascontiguousarray(tokens.reshape(-1))
        if self.tokens.size < 2:
            raise ValueError("need at least 2 tokens")
        self.seed = seed

    def batch(self, step: int, batch: int, seq: int) -> np.ndarray:
        n = self.tokens.size
        rng = np.random.default_rng((self.seed, step))
        starts = rng.integers(0, n, size=batch)
        idx = (starts[:, None] + np.arange(seq)[None, :]) % n
        return self.tokens[idx].astype(np.int32)


class MemmapSource(ArraySource):
    """Windows over a flat binary token file via np.memmap — the corpus
    never loads into RAM; page cache serves the hot windows."""

    def __init__(self, path: str, dtype=np.uint16, seed: int = 0):
        size = os.path.getsize(path) // np.dtype(dtype).itemsize
        mm = np.memmap(path, dtype=dtype, mode="r", shape=(size,))
        # note: keep the memmap (no ascontiguousarray copy)
        self.tokens = mm
        self.seed = seed
        if size < 2:
            raise ValueError(f"{path}: too few tokens ({size})")


def batches(source: TokenSource, batch: int, seq: int,
            start_step: int = 0) -> Iterator[np.ndarray]:
    """Endless deterministic batch stream from ``start_step``."""
    step = start_step
    while True:
        yield source.batch(step, batch, seq)
        step += 1


def prefetch(it: Iterator[np.ndarray], mesh=None, spec=None,
             depth: int = 2) -> Iterator:
    """Double-buffered device prefetch.

    A daemon thread pulls host batches from ``it`` and ``device_put``s
    them (with ``NamedSharding(mesh, spec)`` when given — normally
    ``P("dp", None)``), keeping up to ``depth`` batches in flight so
    the H2D transfer of step k+1 overlaps step k's compute.  Yields
    device arrays in order.
    """
    import jax

    if mesh is not None:
        from jax.sharding import NamedSharding

        sharding = NamedSharding(mesh, spec)
    else:
        sharding = None

    q: queue.Queue = queue.Queue(maxsize=depth)
    _stop = object()
    closed = threading.Event()

    def _put(item) -> bool:
        # A consumer that abandons the stream early (break/exception)
        # stops draining; a bare q.put would then block forever and pin
        # up to ``depth`` device batches in HBM for the process lifetime.
        # Poll against the closed flag so the worker exits instead.
        while not closed.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def worker() -> None:
        try:
            for host_batch in it:
                dev = (jax.device_put(host_batch, sharding)
                       if sharding is not None
                       else jax.device_put(host_batch))
                if not _put(dev):
                    return
            _put(_stop)
        except BaseException as e:  # noqa: BLE001 — must reach consumer
            # a swallowed source/transfer error would read as a clean
            # end-of-stream; re-raise it on the consumer thread instead
            _put(e)

    t = threading.Thread(target=worker, daemon=True,
                         name="ompi-tpu-prefetch")
    t.start()

    class _PrefetchIter:
        """Iterator (not a generator): ``close`` must release the worker
        even when called before the first ``next`` or via GC — a
        generator's finally never runs if it was never started."""

        def __iter__(self):
            return self

        def __next__(self):
            if closed.is_set():
                raise StopIteration
            item = q.get()
            if item is _stop:
                self.close()
                raise StopIteration
            if isinstance(item, BaseException):
                self.close()
                raise item
            return item

        def close(self, _empty=queue.Empty) -> None:
            # release the worker and drop any buffered device batches.
            # queue.Empty is bound at definition time: __del__ may run at
            # interpreter shutdown after module globals are cleared.
            closed.set()

            def drain() -> None:
                try:
                    while True:
                        q.get_nowait()
                except _empty:
                    pass

            drain()
            # a worker mid-q.put slips one item past the first drain
            # (the drain frees the slot its blocked put then fills);
            # wait for it to observe `closed` and drain again
            t.join(timeout=2.0)
            drain()

        __del__ = close

    return _PrefetchIter()


def train_stream(source: TokenSource, mesh, batch: int, seq: int,
                 start_step: int = 0, depth: int = 2,
                 spec: Optional[object] = None) -> Iterator:
    """The one-call composition: deterministic batches → dp-sharded
    device prefetch (resume by passing the checkpointed step)."""
    from jax.sharding import PartitionSpec as P

    return prefetch(batches(source, batch, seq, start_step), mesh,
                    spec if spec is not None else P("dp", None),
                    depth=depth)
