"""Flagship models built on the framework's parallel primitives.

Lazy re-exports keep ``import ompi_tpu.models`` free of jax imports;
submodules load on first attribute access.
"""

from __future__ import annotations

import importlib
from typing import Any

_LAZY = {
    "TransformerConfig": ("ompi_tpu.models.transformer",
                          "TransformerConfig"),
    "init_params": ("ompi_tpu.models.transformer", "init_params"),
    "make_train_step": ("ompi_tpu.models.transformer", "make_train_step"),
    "make_train_loop": ("ompi_tpu.models.transformer", "make_train_loop"),
    "make_forward": ("ompi_tpu.models.transformer", "make_forward"),
    "make_loss_fn": ("ompi_tpu.models.transformer", "make_loss_fn"),
    "make_decoder": ("ompi_tpu.models.decode", "make_decoder"),
    "ArraySource": ("ompi_tpu.models.data", "ArraySource"),
    "MemmapSource": ("ompi_tpu.models.data", "MemmapSource"),
    "train_stream": ("ompi_tpu.models.data", "train_stream"),
}

__all__ = sorted(_LAZY)


def __getattr__(name: str) -> Any:
    try:
        mod, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(f"module {__name__!r} has no attribute "
                             f"{name!r}") from None
    return getattr(importlib.import_module(mod), attr)
