"""Flagship models built on the framework's parallel primitives."""
