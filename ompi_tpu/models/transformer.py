"""Flagship model: a 3D-parallel (dp × sp × tp) transformer LM whose every
communication goes through this framework's device collectives.

This is the "7B-param data-parallel gradient harness" config of BASELINE.json
generalized: data parallelism over ``dp``, sequence/context parallelism over
``sp`` (ring attention — K/V ppermute ring, exact online-softmax), Megatron
column/row tensor parallelism over ``tp`` (one psum per block), gradient
synchronization over dp×sp via the AD transpose of replicated params.

Everything is expressed with shard_map + explicit collectives (no GSPMD
auto-sharding): the model is the framework's integration test and benchmark.
Compute dtype is bfloat16 (MXU-native), accumulation float32.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Optional

import numpy as np

__all__ = ["TransformerConfig", "init_params", "param_specs", "make_loss_fn",
           "make_train_step", "make_train_loop", "make_forward"]


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab: int = 32_000
    d_model: int = 512
    n_heads: int = 8
    n_layers: int = 4
    d_ff: int = 2048
    seq: int = 512
    attention: str = "ring"  # ring | ulysses | flash | xla | gathered
    # ("flash" = ulysses resharding + the pallas flash kernel for the
    # local attention — offsets are static there, so the kernel applies;
    # "xla" = the same ulysses resharding but the jnp/XLA local attention,
    # the pallas-vs-XLA ablation pair for "flash")
    # MoE model family: >0 replaces every layer's dense FFN with a
    # switch-MoE of this many experts, sharded over the mesh's "ep" axis
    # (experts % ep == 0); the load-balancing aux loss joins the training
    # loss with weight moe_aux_weight.
    moe_experts: int = 0
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01
    # chunked cross-entropy: >0 computes the loss over sequence chunks of
    # this length without materializing the full (B, T, V) logits/log-
    # softmax pair — at vocab 32k that pair is the single largest HBM
    # tensor in the train step (f32, ~4 GiB at batch 16 / seq 1024).
    # Each chunk's logits are recomputed in the backward (jax.checkpoint),
    # so peak memory drops from O(T·V) to O(chunk·V).  0 = full path.
    ce_chunk: int = 0
    compute_dtype: Any = "bfloat16"
    # jax.checkpoint policy per layer — HBM ↔ FLOPs trade:
    #   True/"full" = save only layer inputs (max recompute, min HBM);
    #   "dots"      = save matmul outputs, recompute elementwise (cheap
    #                 recompute, still drops the big attention temporaries);
    #   False/None  = no remat (fastest when activations fit).
    remat: Any = "dots"
    # AdamW first-moment dtype: "bfloat16" halves the m buffer (~0.9 GiB
    # at 468M params) — the HBM lever that lets batch 32 fit without
    # paying full remat.  The second moment stays f32 (v's dynamic range
    # spans grad², where bf16's 8-bit mantissa visibly hurts; m is a
    # smoothed gradient and tolerates it — standard mixed-precision
    # Adam practice).  None = f32 moments.
    adam_mu_dtype: Any = None
    # Parameter STORAGE dtype (distinct from compute_dtype, which is the
    # matmul dtype).  "bfloat16": live params and their gradients are
    # bf16; the optimizer keeps a float32 master copy and applies
    # updates there, so small lr·update increments are not lost to
    # bf16's 8-bit mantissa — the standard mixed-precision
    # master-weights scheme.  Note this is HBM-NEUTRAL on one chip (the
    # resident f32 master cancels what bf16 params+grads save); its
    # value is halved param-read bandwidth per step and, under dp
    # sharding, a master/optimizer tree that can shard ZeRO-style while
    # live params stay replicated.  None/float32 = f32, no master.
    param_dtype: Any = None
    # Gradient accumulation: >1 splits the batch into this many
    # microbatches inside ONE compiled step — a lax.scan accumulates
    # the (mean) gradients, then the optimizer runs once.  Peak
    # activation memory scales with the MICRObatch, so effective batch
    # sizes that would OOM in one pass fit.  Constraints: batch %
    # grad_accum == 0 AND (batch / grad_accum) % dp == 0 (each
    # microbatch still shards over dp).
    grad_accum: int = 1
    # ZeRO-1: name a mesh axis (normally "dp") to shard the optimizer's
    # persistent tree (f32 master + Adam moments) over it — each rank
    # stores/updates 1/dp of every leaf and XLA's SPMD partitioner
    # inserts the one all-gather per leaf that re-replicates updated
    # params (see parallel/zero.py).  None = replicated optimizer state.
    zero1_axis: Any = None

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


def init_params(cfg: TransformerConfig, seed: int = 0) -> dict:
    """Global (unsharded) parameter pytree; layers stacked for lax.scan."""
    rng = np.random.default_rng(seed)
    L, D, F, V = cfg.n_layers, cfg.d_model, cfg.d_ff, cfg.vocab

    def w(*shape, scale=None):
        scale = scale if scale is not None else (shape[-2] ** -0.5)
        return rng.normal(0, scale, size=shape).astype(np.float32)

    params = {
        "emb": w(V, D, scale=0.02),
        "wq": w(L, D, D), "wk": w(L, D, D), "wv": w(L, D, D),
        "wo": w(L, D, D, scale=(D ** -0.5) / max(1, 2 * L) ** 0.5),
        "ln1": np.ones((L, D), np.float32),
        "ln2": np.ones((L, D), np.float32),
        "lnf": np.ones((D,), np.float32),
    }
    if cfg.moe_experts:
        E = cfg.moe_experts
        params["wg"] = w(L, D, E, scale=0.02)
        params["w1"] = w(L, E, D, F)
        params["w2"] = w(L, E, F, D,
                         scale=(F ** -0.5) / max(1, 2 * L) ** 0.5)
    else:
        params["w1"] = w(L, D, F)
        params["w2"] = w(L, F, D, scale=(F ** -0.5) / max(1, 2 * L) ** 0.5)
    if cfg.param_dtype not in (None, "float32"):
        # live params are stored in param_dtype; the optimizer's f32
        # master copy is created from them at init (one-time rounding)
        import jax.numpy as jnp

        sd = jnp.dtype(cfg.param_dtype)
        params = {k: np.asarray(v).astype(sd) for k, v in params.items()}
    return params


def param_specs(P, cfg: Optional[TransformerConfig] = None, mesh=None):
    """PartitionSpecs: attention weights tp-sharded Megatron-style, dense
    FFN tp-sharded, MoE experts ep-sharded (replicated when the mesh has
    no "ep" axis), everything else replicated (grad-synced over dp/sp by
    the AD transpose)."""
    specs = {
        "emb": P(), "lnf": P(), "ln1": P(), "ln2": P(),
        "wq": P(None, None, "tp"), "wk": P(None, None, "tp"),
        "wv": P(None, None, "tp"), "wo": P(None, "tp", None),
    }
    if cfg is not None and cfg.moe_experts:
        has_ep = mesh is not None and "ep" in mesh.axis_names
        specs["wg"] = P()
        specs["w1"] = P(None, "ep", None, None) if has_ep else P()
        specs["w2"] = P(None, "ep", None, None) if has_ep else P()
    else:
        specs["w1"] = P(None, None, "tp")
        specs["w2"] = P(None, "tp", None)
    return specs


def _rmsnorm(x, scale):
    import jax.numpy as jnp
    from jax import lax

    xf = x.astype(jnp.float32)
    norm = xf * lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + 1e-6)
    return (norm * scale).astype(x.dtype)


def _rope(x, positions):
    """Rotary embeddings with *global* positions (sp-offset aware)."""
    import jax.numpy as jnp

    B, T, H, D = x.shape
    half = D // 2
    freqs = 1.0 / (10_000 ** (jnp.arange(half, dtype=jnp.float32) / half))
    ang = positions[:, None].astype(jnp.float32) * freqs[None, :]  # (T, half)
    cos, sin = jnp.cos(ang), jnp.sin(ang)
    x1, x2 = x[..., :half], x[..., half:]
    rot = jnp.concatenate([x1 * cos[None, :, None] - x2 * sin[None, :, None],
                           x1 * sin[None, :, None] + x2 * cos[None, :, None]],
                          axis=-1)
    return rot.astype(x.dtype)


def _moe_ffn_tail(cfg, h, lp, comm):
    """Post-attention half of the MoE layer: ln2 → ep-sharded switch →
    residual (shared by the training layer and the cached decode step —
    one source of truth, like _dense_ffn_tail).  Returns (h, aux)."""
    from ompi_tpu.parallel.moe import switch_moe

    x = _rmsnorm(h, lp["ln2"])
    mo, aux = switch_moe(
        comm, x, {"wg": lp["wg"], "w1": lp["w1"], "w2": lp["w2"]},
        axis="ep", capacity_factor=cfg.moe_capacity_factor,
        with_aux=True)
    return h + mo, aux


def _dense_ffn_tail(h, lp, comm, cdt):
    """Post-attention half of the dense layer: ln2 → gelu MLP →
    residual (shared by the training layer and the cached decode step,
    models/decode.py — one source of truth for this math)."""
    import jax

    from ompi_tpu.parallel.layers import column_parallel, row_parallel

    x = _rmsnorm(h, lp["ln2"])
    y = jax.nn.gelu(column_parallel(x, lp["w1"].astype(cdt)))
    return h + row_parallel(y, lp["w2"].astype(cdt), comm, axis="tp")


def _local_backbone(cfg: TransformerConfig, comm, params, tokens,
                    collect_kv: bool = False):
    """Per-device forward through the final rmsnorm (everything except the
    unembed matmul).

    tokens: (B/dp, S/sp) int32.  Returns (h (B/dp, S/sp, D) compute-dtype,
    aux) — aux is the summed MoE load-balancing loss (0.0 for dense).
    With ``collect_kv`` returns (h, (aux, k, v)) where k/v are the
    post-rope per-layer attention inputs stacked (L, B, T, H/tp, hd) —
    the KV-cache prefill (models/decode.py).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ompi_tpu.parallel import attention as attn_mod
    from ompi_tpu.parallel.layers import column_parallel, row_parallel

    cdt = jnp.dtype(cfg.compute_dtype)
    tp = int(comm.mesh.shape["tp"])
    sp = int(comm.mesh.shape["sp"])
    h_local = cfg.n_heads // tp
    hd = cfg.head_dim
    T = tokens.shape[1]
    sp_idx = lax.axis_index("sp")
    positions = sp_idx * T + jnp.arange(T)

    h = params["emb"][tokens].astype(cdt)  # (b, t, D)

    def layer(h, lp):
        x = _rmsnorm(h, lp["ln1"])
        q = column_parallel(x, lp["wq"].astype(cdt))
        k = column_parallel(x, lp["wk"].astype(cdt))
        v = column_parallel(x, lp["wv"].astype(cdt))
        B, t = x.shape[0], x.shape[1]
        q = _rope(q.reshape(B, t, h_local, hd), positions)
        k = _rope(k.reshape(B, t, h_local, hd), positions)
        v = v.reshape(B, t, h_local, hd)
        if cfg.attention == "ring":
            o = attn_mod.ring_attention(comm, q, k, v, axis="sp")
        elif cfg.attention == "ulysses":
            o = attn_mod.ulysses_attention(comm, q, k, v, axis="sp")
        elif cfg.attention == "flash":
            o = attn_mod.ulysses_attention(comm, q, k, v, axis="sp",
                                           impl="flash")
        elif cfg.attention == "xla":
            o = attn_mod.ulysses_attention(comm, q, k, v, axis="sp",
                                           impl="jnp")
        else:
            o = attn_mod.gathered_attention(comm, q, k, v, axis="sp")
        o = o.reshape(B, t, h_local * hd)
        h = h + row_parallel(o, lp["wo"].astype(cdt), comm, axis="tp")
        if cfg.moe_experts:
            # MoE family: expert-parallel switch FFN over the "ep" axis
            # (tp ranks replicate the expert compute — activations are
            # identical across tp after the row_parallel psum)
            h, aux = _moe_ffn_tail(cfg, h, lp, comm)
        else:
            h = _dense_ffn_tail(h, lp, comm, cdt)
            aux = jnp.zeros((), jnp.float32)
        if collect_kv:
            return h, (aux, k, v)
        return h, aux

    keys = ["wq", "wk", "wv", "wo", "w1", "w2", "ln1", "ln2"]
    if cfg.moe_experts:
        keys.append("wg")
    layer_params = {k: params[k] for k in keys}
    if cfg.remat in (True, "full"):
        layer_fn = jax.checkpoint(layer)
    elif cfg.remat == "dots":
        layer_fn = jax.checkpoint(
            layer, policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable)
    else:
        layer_fn = layer
    h, ys = lax.scan(layer_fn, h, layer_params)
    h = _rmsnorm(h, params["lnf"])
    if collect_kv:
        aux, ks, vs = ys
        return h, (aux.sum(), ks, vs)
    return h, ys.sum()


def _local_forward(cfg: TransformerConfig, comm, params, tokens):
    """Per-device forward inside shard_map.

    tokens: (B/dp, S/sp) int32.  Returns (logits (B/dp, S/sp, V) float32,
    aux) — aux is the summed MoE load-balancing loss (0.0 for dense).
    """
    import jax.numpy as jnp

    h, aux = _local_backbone(cfg, comm, params, tokens)
    cdt = jnp.dtype(cfg.compute_dtype)
    # unembed on the MXU in compute dtype, f32 accumulation — a f32×f32
    # matmul here would run at a fraction of the bf16 rate
    logits = jnp.einsum("btd,vd->btv", h, params["emb"].astype(cdt),
                        preferred_element_type=jnp.float32)
    return logits, aux


def _chunked_nll_sum(cfg: TransformerConfig, h, emb, labels, weight):
    """Σ weight·nll over the local shard WITHOUT materializing the full
    (B, T, V) logits: lax.scan over sequence chunks, each chunk's logits
    recomputed in the backward (jax.checkpoint around the chunk body).

    h: (B, T, D) compute dtype; emb: (V, D) f32; labels: (B, T) int32;
    weight: (B, T) f32.  Returns a f32 scalar.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    B, T, D = h.shape
    c = cfg.ce_chunk
    n = T // c
    emb_c = emb.astype(h.dtype)

    def body(acc, inp):
        h_c, lab_c, w_c = inp  # (B, c, D), (B, c), (B, c)
        logits = jnp.einsum("btd,vd->btv", h_c, emb_c,
                            preferred_element_type=jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        lab_logit = jnp.take_along_axis(
            logits, lab_c[..., None], axis=-1)[..., 0]
        return acc + ((lse - lab_logit) * w_c).sum(), None

    hs = jnp.moveaxis(h.reshape(B, n, c, D), 1, 0)
    labs = jnp.moveaxis(labels.reshape(B, n, c), 1, 0)
    ws = jnp.moveaxis(weight.reshape(B, n, c), 1, 0)
    total, _ = lax.scan(jax.checkpoint(body), jnp.zeros((), jnp.float32),
                        (hs, labs, ws))
    return total


def _local_loss(cfg: TransformerConfig, comm, params, tokens):
    """Next-token cross entropy; labels cross sp-shard boundaries via a ring
    shift (the first token of my right neighbor labels my last position)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    sp = int(comm.mesh.shape["sp"])
    T = tokens.shape[1]
    sp_idx = lax.axis_index("sp")

    # labels: tokens shifted left by one *global* position
    first_col = tokens[:, :1]
    if sp == 1:
        from_right = first_col  # self-permute: skip the channel op
    else:
        # neighbor's first token: device r receives from r+1 (shift -1)
        perm = [((i + 1) % sp, i) for i in range(sp)]
        from_right = lax.ppermute(first_col, "sp", perm)
    labels = jnp.concatenate([tokens[:, 1:], from_right], axis=1)
    # the final global position has no next token
    positions = sp_idx * T + jnp.arange(T)
    weight = (positions < cfg.seq - 1).astype(jnp.float32)[None, :]

    if cfg.ce_chunk and T % cfg.ce_chunk == 0:
        h, aux = _local_backbone(cfg, comm, params, tokens)
        B = tokens.shape[0]
        local_sum = _chunked_nll_sum(
            cfg, h, params["emb"], labels,
            jnp.broadcast_to(weight, (B, T)))
    else:
        logits, aux = _local_forward(cfg, comm, params, tokens)
        logprobs = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(
            logprobs, labels[..., None], axis=-1)[..., 0]
        local_sum = (nll * weight).sum()
    local_cnt = weight.sum() * tokens.shape[0]
    dp = int(comm.mesh.shape["dp"])
    if dp * sp == 1:  # degenerate data/seq axes: psum is identity
        total, count = local_sum, local_cnt
    else:
        total = lax.psum(local_sum, ("dp", "sp"))
        count = lax.psum(local_cnt, ("dp", "sp"))
    loss = total / count
    if cfg.moe_experts:
        # average the per-device balance loss over the whole mesh (tp/ep
        # ranks see replicated tokens, so the mean is layout-invariant)
        aux_mean = (aux if comm.size == 1
                    else lax.psum(aux, comm.axes)) / comm.size
        loss = loss + cfg.moe_aux_weight * aux_mean
    return loss


def make_loss_fn(cfg: TransformerConfig, mesh):
    """shard_map'd global loss: (params, tokens) → scalar."""
    import jax
    from jax.sharding import PartitionSpec as P

    from ompi_tpu.mpi.device_comm import DeviceCommunicator

    axes = tuple(a for a in ("dp", "sp", "tp", "ep")
                 if a in mesh.axis_names)
    comm = DeviceCommunicator(mesh, axes)

    local = functools.partial(_local_loss, cfg, comm)
    return jax.shard_map(
        local, mesh=mesh,
        in_specs=(param_specs(P, cfg, mesh), P("dp", "sp")),
        out_specs=P(), check_vma=False)


def make_forward(cfg: TransformerConfig, mesh):
    """shard_map'd forward: (params, tokens) → logits, for entry()/serving."""
    import jax
    from jax.sharding import PartitionSpec as P

    from ompi_tpu.mpi.device_comm import DeviceCommunicator

    axes = tuple(a for a in ("dp", "sp", "tp", "ep")
                 if a in mesh.axis_names)
    comm = DeviceCommunicator(mesh, axes)

    def local(params, tokens):
        return _local_forward(cfg, comm, params, tokens)[0]  # drop aux

    return jax.shard_map(
        local, mesh=mesh,
        in_specs=(param_specs(P, cfg, mesh), P("dp", "sp")),
        out_specs=P("dp", "sp", None), check_vma=False)


def _make_step_body(cfg: TransformerConfig, mesh, lr: float):
    """Shared optimizer-step body: (params, opt_state, tokens) →
    (params, opt_state, loss) — the single definition both the one-step
    and the scanned-loop entry points compile."""
    import jax
    import optax

    import jax.numpy as jnp
    from jax import lax

    loss_fn = make_loss_fn(cfg, mesh)
    opt = optax.adamw(lr, b1=0.9, b2=0.95, weight_decay=0.01,
                      mu_dtype=cfg.adam_mu_dtype)

    accum = int(cfg.grad_accum)
    if accum < 1:
        raise ValueError(f"grad_accum must be >= 1, got {cfg.grad_accum}")

    def loss_and_grads(params, tokens):
        """(mean loss, mean grads) — one pass, or a lax.scan over
        ``grad_accum`` microbatches whose activations never coexist."""
        if accum == 1:
            return jax.value_and_grad(loss_fn)(params, tokens)
        B = tokens.shape[0]
        if B % accum:
            raise ValueError(f"batch {B} not divisible by "
                             f"grad_accum {accum}")
        micro = tokens.reshape(accum, B // accum, *tokens.shape[1:])

        def body(carry, toks):
            acc_loss, acc_g = carry
            loss, g = jax.value_and_grad(loss_fn)(params, toks)
            # accumulate in f32 even when grads arrive in a storage
            # dtype (param_dtype=bf16): summing K microbatches in bf16
            # rounds small components away before the optimizer's own
            # f32 cast ever sees them
            acc_g = jax.tree_util.tree_map(
                lambda a, b: a + b.astype(jnp.float32), acc_g, g)
            return (acc_loss + loss, acc_g), None

        zeros = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params)
        (total, g_sum), _ = lax.scan(
            body, (jnp.zeros((), jnp.float32), zeros), micro)
        inv = 1.0 / accum
        return total * inv, jax.tree_util.tree_map(
            lambda g: g * inv, g_sum)
    store = (None if cfg.param_dtype in (None, "float32", jnp.float32)
             else jnp.dtype(cfg.param_dtype))

    if cfg.zero1_axis:
        from jax.sharding import PartitionSpec as _P

        from ompi_tpu.parallel.zero import zero1_wrap

        z_init, z_update = zero1_wrap(
            opt, mesh, cfg.zero1_axis, param_dtype=store,
            # updated live params keep their Megatron/MoE shardings —
            # only the zero1-axis redundancy is re-gathered
            param_specs=param_specs(_P, cfg, mesh))

        def body(params, opt_state, tokens):
            loss, grads = loss_and_grads(params, tokens)
            params, opt_state = z_update(grads, opt_state, params)
            return params, opt_state, loss

        class _ZeroOpt:
            init = staticmethod(z_init)

        return body, _ZeroOpt

    if store is None:
        def body(params, opt_state, tokens):
            loss, grads = loss_and_grads(params, tokens)
            updates, opt_state = opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        return body, opt

    # master-weights scheme: live params (and grads) in `store` dtype,
    # f32 master copy updated by the optimizer, live params re-derived
    # by casting the master down each step
    def master_init(params):
        master = jax.tree_util.tree_map(
            lambda p: jnp.asarray(p, jnp.float32), params)
        return {"opt": opt.init(master), "master": master}

    def body(params, opt_state, tokens):
        loss, grads = loss_and_grads(params, tokens)
        g32 = jax.tree_util.tree_map(
            lambda g: g.astype(jnp.float32), grads)
        updates, inner = opt.update(g32, opt_state["opt"],
                                    opt_state["master"])
        master = optax.apply_updates(opt_state["master"], updates)
        params = jax.tree_util.tree_map(
            lambda m: m.astype(store), master)
        return params, {"opt": inner, "master": master}, loss

    class _MasterOpt:
        init = staticmethod(master_init)

    return body, _MasterOpt


def make_train_step(cfg: TransformerConfig, mesh, lr: float = 3e-4):
    """jitted (params, opt_state, tokens) → (params, opt_state, loss).

    AdamW via optax; gradients arrive already synchronized (psum over dp/sp
    is the AD transpose of the replicated in_specs; tp shards update their
    local slice only — exactly ZeRO-0 + Megatron semantics).
    """
    import jax

    body, opt = _make_step_body(cfg, mesh, lr)
    # params/opt_state are donated: the updated trees reuse their HBM
    # in place of a second full copy (≈1.6 GiB at 133M params with Adam)
    step = functools.partial(jax.jit, donate_argnums=(0, 1))(body)
    return step, opt.init


def make_train_loop(cfg: TransformerConfig, mesh, lr: float = 3e-4,
                    steps: int = 8):
    """jitted (params, opt_state, tokens) → (params, opt_state, losses):
    ``steps`` optimizer steps inside ONE compiled program (lax.scan over
    the step), donated carry.

    One dispatch per K steps is how real training loops run — and the only
    honest way to time the device when the host link has per-call latency
    (a remote/tunneled runtime stalls between dispatches; chaining keeps
    the chip busy back-to-back).
    """
    import jax
    from jax import lax

    body, opt = _make_step_body(cfg, mesh, lr)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def run(params, opt_state, tokens):
        def scan_body(carry, _):
            p, s, loss = body(*carry, tokens)
            return (p, s), loss

        (params, opt_state), losses = lax.scan(
            scan_body, (params, opt_state), None, length=steps)
        return params, opt_state, losses

    return run, opt.init
