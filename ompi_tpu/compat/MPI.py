"""mpi4py-compatible facade over ompi_tpu.

The reference's Python users overwhelmingly reach it through mpi4py
(``from mpi4py import MPI``); this module lets those scripts run on this
framework with one changed import::

    from ompi_tpu.compat import MPI

    comm = MPI.COMM_WORLD
    rank = comm.Get_rank()
    comm.Send(buf, dest=1, tag=7)          # uppercase = buffer API
    obj = comm.bcast(obj, root=0)          # lowercase = pickled objects

Covered surface (the part real scripts use): Comm point-to-point (both
case conventions, all send modes, persistent requests, matched probe),
blocking + nonblocking collectives, communicator management
(Dup/Split/Split_type/Create/Create_group/Free/group ops), Status,
Request families (Wait*/Test*), Op including Op.Create, Datatype-as-
numpy-dtype buffer specs ``[buf, count, MPI.DOUBLE]``, and the
environment calls (Wtime, Get_processor_name, Init/Finalize).

RMA windows (``MPI.Win``: Create/Allocate, Put/Get/Accumulate/
Get_accumulate/Fetch_and_op/Compare_and_swap, fence / lock / PSCW),
MPI-IO (``MPI.File``: explicit-offset, individual, collective, shared
and ordered reads/writes over file views), Cartesian topologies
(``Comm.Create_cart`` → ``Cartcomm``, ``Compute_dims``) and dynamic
processes (``Comm.Spawn`` / ``Comm.Get_parent`` / ``Intercomm``) are
covered too, as are graph topologies (``Comm.Create_graph`` →
``Graphcomm``).  MIGRATION.md maps every remaining native-only call.

Naming follows mpi4py exactly, hence the non-PEP8 method names.  The
module references the reference's C API (``/root/reference/ompi/mpi/c``)
only through the names mpi4py derives from it; everything executes on
this framework's PML/coll stack.
"""

from __future__ import annotations

import pickle
from typing import Any, Optional, Sequence

import numpy as np

from ompi_tpu.mpi import constants as _const
from ompi_tpu.mpi import op as _op_mod
from ompi_tpu.mpi.request import Status as _NativeStatus
from ompi_tpu.mpi import request as _req_mod

# ---------------------------------------------------------------------------
# constants
# ---------------------------------------------------------------------------

ANY_SOURCE = _const.ANY_SOURCE
ANY_TAG = _const.ANY_TAG
PROC_NULL = _const.PROC_NULL
ORDER_C = 0
ORDER_FORTRAN = 1
DISTRIBUTE_NONE = 100
DISTRIBUTE_BLOCK = 101
DISTRIBUTE_CYCLIC = 102
DISTRIBUTE_DFLT_DARG = -1
UNDEFINED = _const.UNDEFINED
IN_PLACE = _const.IN_PLACE
COMM_TYPE_SHARED = _const.COMM_TYPE_SHARED
SUCCESS = 0

THREAD_SINGLE, THREAD_FUNNELED, THREAD_SERIALIZED, THREAD_MULTIPLE = range(4)

ERRORS_ARE_FATAL = "errors_are_fatal"
ERRORS_RETURN = "errors_return"
ROOT = _const.ROOT              # intercomm collective root marker
BOTTOM = 0                      # address-0 buffer sentinel (unused here)
KEYVAL_INVALID = -1
MODE_NOCHECK = 1024             # win assertion hint (accepted, advisory)
# comparison results (≈ MPI_Comm_compare / MPI_Group_compare)
IDENT, CONGRUENT, SIMILAR, UNEQUAL = 0, 1, 2, 3
# topology kinds for Get_topology (no topology → the UNDEFINED constant,
# mpi4py/MPI_Topo_test semantics)
CART, GRAPH, DIST_GRAPH = 1, 2, 3

from ompi_tpu.mpi.errhandler import (  # noqa: E402
    Errhandler, create_errhandler,
)
from ompi_tpu.mpi.info import (  # noqa: E402
    Keyval as _Keyval, keyval_create as _keyval_create,
    keyval_free as _keyval_free,
)
from ompi_tpu.mpi.info import Info as _NativeInfo  # noqa: E402


class Info(_NativeInfo):
    """mpi4py-cased Info over the native hint dictionary (the native
    lowercase API stays available; File/Win/native layers consume it
    directly)."""

    @classmethod
    def Create(cls, items=None) -> "Info":
        return cls(dict(items) if items else None)

    def Set(self, key: str, value: str) -> None:
        self.set(key, value)

    def Get(self, key: str, default=None):
        return self.get(key, default)

    def Delete(self, key: str) -> None:
        self.delete(key)

    def Get_nkeys(self) -> int:
        return self.nkeys          # native exposes it as a property

    def Get_nthkey(self, n: int) -> str:
        return self.nthkey(n)

    def Dup(self) -> "Info":
        return Info(dict(self.items()))

    def Free(self) -> None:
        pass


INFO_NULL = None
# well-known attribute keyvals (≈ MPI_TAG_UB etc.); queried via
# comm.Get_attr — the facade answers them itself
TAG_UB = _keyval_create(extra="TAG_UB")
WIN_BASE = _keyval_create(extra="WIN_BASE")
WIN_SIZE = _keyval_create(extra="WIN_SIZE")
WIN_DISP_UNIT = _keyval_create(extra="WIN_DISP_UNIT")
_MAX_TAG = (1 << 30) - 1        # user tags below the reserved ranges


class Exception(RuntimeError):  # noqa: A001 — mpi4py exports MPI.Exception
    """mpi4py-shaped MPI exception (wraps the native MPIException)."""

    def __init__(self, native):
        super().__init__(str(native))
        self._native = native

    def Get_error_class(self) -> int:
        return getattr(self._native, "error_class", -1)

    def Get_error_string(self) -> str:
        return str(self._native)


# ---------------------------------------------------------------------------
# Datatype: numpy dtype in mpi4py clothing
# ---------------------------------------------------------------------------

class Datatype:
    """A named numpy dtype — enough for ``[buf, count, MPI.DOUBLE]``
    specs, ``Status.Get_count``, dtype checks, and (via the
    ``Create_*`` family) derived types for file views."""

    def __init__(self, np_dtype, name: str):
        self.np_dtype = np.dtype(np_dtype)
        self._name = name

    def Get_size(self) -> int:
        return self.np_dtype.itemsize

    @property
    def size(self) -> int:
        return self.np_dtype.itemsize

    def Get_name(self) -> str:
        return self._name

    def __repr__(self) -> str:
        return f"<MPI.Datatype {self._name}>"

    def __eq__(self, other) -> bool:
        # plain (predefined) types compare by element dtype, so a
        # Get_view round-trip satisfies `etype == MPI.DOUBLE`; derived
        # types keep identity semantics
        if self is other:
            return True
        return (type(self) is Datatype and type(other) is Datatype
                and self.np_dtype == other.np_dtype)

    def __hash__(self) -> int:
        if type(self) is Datatype:
            return hash(("mpi-dt", str(self.np_dtype)))
        return id(self)

    # -- derived-type constructors (mpi4py spelling over the native
    #    datatype engine; the results drive File.Set_view) --------------
    def _to_native(self):
        from ompi_tpu.mpi.datatype import from_numpy

        return from_numpy(self.np_dtype)

    def Create_contiguous(self, count: int) -> "Datatype":
        return _Derived(self._to_native().contiguous(count), self)

    def Create_vector(self, count: int, blocklength: int,
                      stride: int) -> "Datatype":
        return _Derived(
            self._to_native().vector(count, blocklength, stride), self)

    def Create_hvector(self, count: int, blocklength: int,
                       stride: int) -> "Datatype":
        return _Derived(
            self._to_native().hvector(count, blocklength, stride), self)

    def Create_indexed(self, blocklengths, displacements) -> "Datatype":
        return _Derived(
            self._to_native().indexed(list(blocklengths),
                                      list(displacements)), self)

    def Create_indexed_block(self, blocklength: int,
                             displacements) -> "Datatype":
        return _Derived(
            self._to_native().indexed_block(blocklength,
                                            list(displacements)), self)

    def Create_hindexed(self, blocklengths, displacements) -> "Datatype":
        return _Derived(
            self._to_native().hindexed(list(blocklengths),
                                       list(displacements)), self)

    def Create_subarray(self, sizes, subsizes, starts,
                        order=None) -> "Datatype":
        return _Derived(
            self._to_native().subarray(list(sizes), list(subsizes),
                                       list(starts),
                                       "F" if order == ORDER_FORTRAN
                                       else "C"), self)

    def Create_hindexed_block(self, blocklength: int,
                              displacements) -> "Datatype":
        return _Derived(
            self._to_native().hindexed_block(blocklength,
                                             list(displacements)), self)

    def Create_darray(self, size: int, rank: int, gsizes, distribs,
                      dargs, psizes, order=None) -> "Datatype":
        from ompi_tpu.mpi import datatype as _dt
        from ompi_tpu.mpi.datatype import create_darray

        name_of = {DISTRIBUTE_NONE: _dt.DISTRIBUTE_NONE,
                   DISTRIBUTE_BLOCK: _dt.DISTRIBUTE_BLOCK,
                   DISTRIBUTE_CYCLIC: _dt.DISTRIBUTE_CYCLIC}
        return _Derived(create_darray(
            size, rank, list(gsizes),
            [name_of.get(d, d) for d in distribs], list(dargs),
            list(psizes), self._to_native(),
            "F" if order == ORDER_FORTRAN else "C"), self)

    def Create_resized(self, lb: int, extent: int) -> "Datatype":
        if lb:
            raise Exception(
                "Create_resized: nonzero lower bounds are not "
                "supported (the native engine keeps lb == 0)")
        return _Derived(self._to_native().resized(extent), self)

    @staticmethod
    def Create_struct(blocklengths, displacements,
                      datatypes) -> "Datatype":
        from ompi_tpu.mpi.datatype import create_struct

        native = create_struct(
            list(blocklengths), list(displacements),
            [d._nat if isinstance(d, _Derived) else d._to_native()
             for d in datatypes])
        return _Derived(native, datatypes[0])

    def Commit(self) -> "Datatype":
        return self            # native types are always ready

    def Free(self) -> None:
        pass

    def Get_extent(self) -> tuple:
        return 0, self.size    # scalar: lb 0, extent == size


class _Derived(Datatype):
    """A committed derived type: wraps a native DerivedDatatype (passed
    through to ``File.Set_view``); the element dtype of the BASE type is
    kept so count conversions still work."""

    def __init__(self, native, base: "Datatype") -> None:
        self._nat = native
        self.np_dtype = base.np_dtype
        self._name = native.name

    def Get_size(self) -> int:
        return self._nat.size

    @property
    def size(self) -> int:
        return self._nat.size

    def Get_extent(self) -> tuple:
        return 0, self._nat.extent

    @property
    def extent(self) -> int:
        return self._nat.extent

    def _to_native(self):
        return self._nat


BYTE = Datatype(np.uint8, "MPI_BYTE")
CHAR = Datatype(np.int8, "MPI_CHAR")
SHORT = Datatype(np.int16, "MPI_SHORT")
INT = Datatype(np.int32, "MPI_INT")
LONG = Datatype(np.int64, "MPI_LONG")
LONG_LONG = Datatype(np.int64, "MPI_LONG_LONG")
UNSIGNED_CHAR = Datatype(np.uint8, "MPI_UNSIGNED_CHAR")
UNSIGNED_SHORT = Datatype(np.uint16, "MPI_UNSIGNED_SHORT")
UNSIGNED = Datatype(np.uint32, "MPI_UNSIGNED")
UNSIGNED_LONG = Datatype(np.uint64, "MPI_UNSIGNED_LONG")
FLOAT = Datatype(np.float32, "MPI_FLOAT")
DOUBLE = Datatype(np.float64, "MPI_DOUBLE")
C_BOOL = Datatype(np.bool_, "MPI_C_BOOL")
BOOL = C_BOOL
INT8_T = Datatype(np.int8, "MPI_INT8_T")
INT16_T = Datatype(np.int16, "MPI_INT16_T")
INT32_T = Datatype(np.int32, "MPI_INT32_T")
INT64_T = Datatype(np.int64, "MPI_INT64_T")
UINT8_T = Datatype(np.uint8, "MPI_UINT8_T")
UINT16_T = Datatype(np.uint16, "MPI_UINT16_T")
UINT32_T = Datatype(np.uint32, "MPI_UINT32_T")
UINT64_T = Datatype(np.uint64, "MPI_UINT64_T")
COMPLEX = Datatype(np.complex64, "MPI_COMPLEX")
DOUBLE_COMPLEX = Datatype(np.complex128, "MPI_DOUBLE_COMPLEX")
# (value, location) pair types for MAXLOC/MINLOC reductions — the same
# structured dtypes the native op layer folds
FLOAT_INT = Datatype(np.dtype([("val", np.float32), ("loc", np.int32)]),
                     "MPI_FLOAT_INT")
DOUBLE_INT = Datatype(np.dtype([("val", np.float64), ("loc", np.int32)]),
                      "MPI_DOUBLE_INT")
LONG_INT = Datatype(np.dtype([("val", np.int64), ("loc", np.int32)]),
                    "MPI_LONG_INT")
TWOINT = Datatype(np.dtype([("val", np.int32), ("loc", np.int32)]),
                  "MPI_2INT")


# ---------------------------------------------------------------------------
# Op
# ---------------------------------------------------------------------------

class Op:
    """Wraps a native reduction op; callable like mpi4py's, and carries
    the Python-object fold used by the lowercase collectives."""

    def __init__(self, native, pyfold=None, name: str = "user"):
        self._native = native
        self._py = pyfold
        self._name = name

    @classmethod
    def Create(cls, function, commute: bool = False) -> "Op":
        native = _op_mod.create_op(
            lambda a, b: function(a, b), commutative=commute)
        return cls(native, pyfold=function)

    def Free(self) -> None:
        pass

    def Reduce_local(self, inbuf, inoutbuf) -> None:
        """≈ MPI_Reduce_local: inoutbuf = op(inbuf, inoutbuf), purely
        local — delegates to the native helper, which enforces the
        equal-counts contract (a silent broadcast/truncate would give
        wrong reductions)."""
        _op_mod.reduce_local(_as_array(inbuf), _as_array(inoutbuf),
                             self._native)

    def Is_commutative(self) -> bool:
        return _op_mod.op_commutative(self._native)

    def __call__(self, a, b):
        if self._py is not None:
            return self._py(a, b)
        return self._native(a, b)

    def __repr__(self) -> str:
        return f"<MPI.Op {self._name}>"


SUM = Op(_op_mod.SUM, lambda a, b: a + b, "MPI_SUM")
PROD = Op(_op_mod.PROD, lambda a, b: a * b, "MPI_PROD")
MAX = Op(_op_mod.MAX, lambda a, b: max(a, b), "MPI_MAX")
MIN = Op(_op_mod.MIN, lambda a, b: min(a, b), "MPI_MIN")
LAND = Op(_op_mod.LAND, lambda a, b: bool(a) and bool(b), "MPI_LAND")
LOR = Op(_op_mod.LOR, lambda a, b: bool(a) or bool(b), "MPI_LOR")
LXOR = Op(_op_mod.LXOR, lambda a, b: bool(a) != bool(b), "MPI_LXOR")
BAND = Op(_op_mod.BAND, lambda a, b: a & b, "MPI_BAND")
BOR = Op(_op_mod.BOR, lambda a, b: a | b, "MPI_BOR")
BXOR = Op(_op_mod.BXOR, lambda a, b: a ^ b, "MPI_BXOR")
MAXLOC = Op(_op_mod.MAXLOC, None, "MPI_MAXLOC")
MINLOC = Op(_op_mod.MINLOC, None, "MPI_MINLOC")
REPLACE = Op(_op_mod.REPLACE, lambda a, b: b, "MPI_REPLACE")
NO_OP = Op(_op_mod.NO_OP, lambda a, b: a, "MPI_NO_OP")


def _native_op(op) -> Any:
    return op._native if isinstance(op, Op) else op


# ---------------------------------------------------------------------------
# Status
# ---------------------------------------------------------------------------

class Status(_NativeStatus):
    """Native Status + the mpi4py accessor spelling."""

    def Get_source(self) -> int:
        return self.source

    def Get_tag(self) -> int:
        return self.tag

    def Get_error(self) -> int:
        return getattr(self, "error", 0)

    def Get_count(self, datatype: Datatype = BYTE) -> int:
        """Count in items of ``datatype`` (mpi4py semantics: converted
        from the received byte count when the PML recorded it)."""
        nbytes = getattr(self, "count_bytes", None)
        if nbytes is None:
            return self.count
        item = datatype.Get_size()
        if item <= 0:
            return 0
        if nbytes % item:
            return UNDEFINED
        return nbytes // item

    def Get_elements(self, datatype: Datatype = BYTE) -> int:
        return self.Get_count(datatype)

    def Is_cancelled(self) -> bool:
        # the native Status records cancellation as ``_cancelled``
        # (absorbed via __dict__.update in _fill_status)
        return bool(getattr(self, "cancelled",
                            getattr(self, "_cancelled", False)))

    def _absorb(self, native: Optional[_NativeStatus]) -> None:
        if native is not None:
            self.__dict__.update(native.__dict__)


def _fill_status(status: Optional[Status], native) -> None:
    if status is not None and native is not None:
        status.__dict__.update(native.__dict__)


# ---------------------------------------------------------------------------
# pickle framing for the lowercase API (≈ mpi4py's MPI.pickle hook:
# swap dumps/loads — e.g. for dill or a protocol pin — and every
# lowercase send/recv/bcast uses it)
# ---------------------------------------------------------------------------

_STDPICKLE = pickle   # stable stdlib alias: the name `pickle` is
# re-bound to the serializer INSTANCE at module end (mpi4py spelling)


class Pickle:
    def __init__(self, dumps=None, loads=None, protocol=None):
        self.PROTOCOL = (_STDPICKLE.HIGHEST_PROTOCOL
                         if protocol is None else protocol)
        self._dumps = dumps or (lambda o, p: _STDPICKLE.dumps(o, p))
        self._loads = loads or _STDPICKLE.loads

    def dumps(self, obj) -> bytes:
        return self._dumps(obj, self.PROTOCOL)

    def loads(self, data) -> Any:
        return self._loads(bytes(data))


pickle_impl = Pickle()


def _serializer() -> "Pickle":
    """The LIVE serializer: read through the module global so
    ``MPI.pickle = MPI.Pickle(dumps=..., loads=...)`` (the mpi4py idiom)
    swaps serialization for the whole lowercase API."""
    p = globals().get("pickle")
    return p if isinstance(p, Pickle) else pickle_impl


def _dumps(obj) -> np.ndarray:
    return np.frombuffer(_serializer().dumps(obj), dtype=np.uint8).copy()


def _loads(arr) -> Any:
    return _serializer().loads(
        np.ascontiguousarray(arr).view(np.uint8).tobytes())


# ---------------------------------------------------------------------------
# buffer specs: ndarray | [buf] | [buf, type] | [buf, count] |
#               [buf, count, type] | [buf, (counts, displs), type]
# ---------------------------------------------------------------------------

def _as_array(spec) -> np.ndarray:
    if isinstance(spec, (list, tuple)):
        buf = spec[0]
        arr = np.asarray(buf)
        count = None
        dtype = None
        for extra in spec[1:]:
            if isinstance(extra, Datatype):
                dtype = extra
            elif isinstance(extra, (int, np.integer)):
                count = int(extra)
        if dtype is not None and arr.dtype != dtype.np_dtype:
            arr = arr.view(dtype.np_dtype)
        if count is not None:
            arr = arr.reshape(-1)[:count]
        return arr
    return np.asarray(spec)


def _to_native_dt(dt):
    """Facade (or native) datatype → native datatype — the ONE coercion."""
    return dt._to_native() if isinstance(dt, Datatype) else dt


def _wrap_info(native) -> "Info":
    """Native Info → facade Info (identity when already wrapped)."""
    return native if isinstance(native, Info) \
        else Info(dict(native.items()))


def _copy_into(dst_spec, src) -> None:
    """Write a collective/receive result into the caller's buffer."""
    dst = _as_array(dst_spec)
    src = np.asarray(src)
    flat = src.reshape(-1)
    if dst.dtype != flat.dtype:
        flat = flat.astype(dst.dtype)
    dst.reshape(-1)[: flat.size] = flat


# ---------------------------------------------------------------------------
# Request / Prequest
# ---------------------------------------------------------------------------

class Request:
    """Wraps a native request.  ``wait``/``test`` (lowercase) return the
    payload (unpickled for object receives); ``Wait``/``Test`` follow the
    buffer-API convention."""

    def __init__(self, native, transform=None):
        self._r = native
        self._transform = transform

    def _finish(self, out):
        """Apply the landing transform exactly once.  For the uppercase
        buffer API the transform is what copies collective results into
        the caller's receive buffer (Ibcast/Iallreduce), so EVERY
        completion path — Wait/Test and the families, not just the
        lowercase object API — must run it."""
        if self._transform is not None:
            t, self._transform = self._transform, None
            return t(out)
        return out

    # -- buffer convention -------------------------------------------------
    def Wait(self, status: Optional[Status] = None) -> bool:
        self._finish(self._r.wait())
        _fill_status(status, getattr(self._r, "status", None))
        return True

    def Test(self, status: Optional[Status] = None) -> bool:
        done = self._r.test()
        if done:
            self._finish(self._r.wait())  # complete: returns the payload
            _fill_status(status, getattr(self._r, "status", None))
        return bool(done)

    def Cancel(self) -> None:
        self._r.cancel()

    def Free(self) -> None:
        pass

    # -- object convention -------------------------------------------------
    def wait(self, status: Optional[Status] = None) -> Any:
        out = self._r.wait()
        _fill_status(status, getattr(self._r, "status", None))
        return self._finish(out)

    def test(self, status: Optional[Status] = None):
        done = self._r.test()
        if not done:
            return (False, None)
        _fill_status(status, getattr(self._r, "status", None))
        out = self._r.wait()  # already complete: returns the payload
        return (True, self._finish(out))

    # -- families ----------------------------------------------------------
    @staticmethod
    def Waitall(requests: Sequence["Request"], statuses=None) -> bool:
        _req_mod.wait_all([r._r for r in requests])
        for i, req in enumerate(requests):
            req._finish(req._r.wait())  # complete: landing transforms run
            if statuses is not None and i < len(statuses):
                _fill_status(statuses[i], getattr(req._r, "status", None))
        return True

    @staticmethod
    def waitall(requests: Sequence["Request"]) -> list:
        _req_mod.wait_all([r._r for r in requests])
        return [r._finish(r._r.wait()) for r in requests]

    @staticmethod
    def Waitany(requests: Sequence["Request"],
                status: Optional[Status] = None) -> int:
        idx, _ = _req_mod.wait_any([r._r for r in requests])
        if idx is not None and idx >= 0:
            req = requests[idx]
            req._finish(req._r.wait())
            _fill_status(status, getattr(req._r, "status", None))
        return UNDEFINED if idx is None else idx

    @staticmethod
    def Testall(requests: Sequence["Request"], statuses=None) -> bool:
        if not all(r._r.test() for r in requests):
            return False
        for i, req in enumerate(requests):
            req._finish(req._r.wait())
            if statuses is not None and i < len(statuses):
                _fill_status(statuses[i], getattr(req._r, "status", None))
        return True

    @staticmethod
    def Startall(requests: Sequence["Prequest"]) -> None:
        """Passthrough so loops written against ``MPI.Request.Startall``
        port unchanged (all-or-nothing, like the native start_all)."""
        _req_mod.start_all([r._r for r in requests])


class Prequest(Request):
    """Persistent request (MPI_Send_init/Recv_init → Start; also the
    handle type the persistent-collective and partitioned ``*_init``
    families return)."""

    def _finish(self, out):
        # persistent: the landing transform re-runs after EVERY
        # start/wait cycle (the base class clears it after one shot —
        # a persistent Allreduce_init must refill recvbuf each time)
        if self._transform is not None:
            return self._transform(out)
        return out

    def Start(self) -> None:
        self._r.start()

    # Startall is inherited from Request (the all-or-nothing native
    # start_all), reachable as both MPI.Request.Startall and the
    # mpi4py-canonical MPI.Prequest.Startall.

    # -- partitioned operations (MPI-4; valid on Psend/Precv handles) ------

    def Pready(self, partition: int) -> None:
        self._r.pready(partition)

    def Pready_range(self, partition_low: int,
                     partition_high: int) -> None:
        self._r.pready_range(partition_low, partition_high)

    def Pready_list(self, partitions) -> None:
        self._r.pready_list(partitions)

    def Parrived(self, partition: int) -> bool:
        return self._r.parrived(partition)


class Message:
    """Matched-probe handle (MPI_Mprobe → MPI_Mrecv)."""

    def __init__(self, comm, native_msg):
        self._comm = comm
        self._m = native_msg

    def Recv(self, buf=None, status: Optional[Status] = None):
        arr = None if buf is None else _as_array(buf)
        st = _NativeStatus()
        out = self._comm.mrecv(arr, self._m, status=st)
        _fill_status(status, st)
        if buf is not None and out is not None and not np.shares_memory(
                _as_array(buf), np.asarray(out)):
            _copy_into(buf, out)
        return out

    def Irecv(self, buf=None) -> Request:
        arr = None if buf is None else _as_array(buf)
        return Request(self._comm.imrecv(arr, self._m))

    def recv(self, status: Optional[Status] = None) -> Any:
        st = _NativeStatus()
        out = self._comm.mrecv(None, self._m, status=st)
        _fill_status(status, st)
        return _loads(out)


# ---------------------------------------------------------------------------
# Group
# ---------------------------------------------------------------------------

class Group:
    def __init__(self, native, my_world_rank: Optional[int] = None):
        self._g = native
        self._my_world = my_world_rank

    def Get_size(self) -> int:
        return self._g.size

    def Get_rank(self) -> int:
        if self._my_world is None:
            return UNDEFINED
        r = self._g.rank_of(self._my_world)
        return UNDEFINED if r is None or r < 0 else r

    def Compare(self, other: "Group") -> int:
        """≈ MPI_Group_compare."""
        mine, theirs = list(self._g.ranks), list(other._g.ranks)
        if mine == theirs:
            return IDENT
        if sorted(mine) == sorted(theirs):
            return SIMILAR
        return UNEQUAL

    def Incl(self, ranks) -> "Group":
        return Group(self._g.incl(ranks), self._my_world)

    def Excl(self, ranks) -> "Group":
        return Group(self._g.excl(ranks), self._my_world)

    def Range_incl(self, ranges) -> "Group":
        return Group(self._g.range_incl(ranges), self._my_world)

    def Range_excl(self, ranges) -> "Group":
        return Group(self._g.range_excl(ranges), self._my_world)

    def Union(self, other: "Group") -> "Group":
        return Group(self._g.union(other._g), self._my_world)

    def Intersection(self, other: "Group") -> "Group":
        return Group(self._g.intersection(other._g), self._my_world)

    def Difference(self, other: "Group") -> "Group":
        return Group(self._g.difference(other._g), self._my_world)

    def Translate_ranks(self, ranks, other: "Group"):
        return self._g.translate_ranks(ranks, other._g)

    def Free(self) -> None:
        pass

    @property
    def size(self) -> int:
        return self.Get_size()

    @property
    def rank(self) -> int:
        return self.Get_rank()


# ---------------------------------------------------------------------------
# Comm
# ---------------------------------------------------------------------------

class Comm:
    """mpi4py-shaped communicator over a native :class:`Communicator`.

    Uppercase methods take buffers (numpy arrays or ``[buf, count, type]``
    specs) and write results into caller-provided receive buffers;
    lowercase methods move arbitrary pickled Python objects.
    """

    def __init__(self, native):
        self._comm = native

    @property
    def _c(self):
        return self._comm

    # -- identity ----------------------------------------------------------

    def Get_rank(self) -> int:
        return self._c.rank

    def Get_size(self) -> int:
        return self._c.size

    def Get_name(self) -> str:
        return self._c.get_name()

    def Set_name(self, name: str) -> None:
        self._c.set_name(name)

    def Get_group(self) -> Group:
        g = self._c.get_group()
        return Group(g, g.world_rank(self._c.rank))

    def Is_inter(self) -> bool:
        return self._c.test_inter()

    def Is_intra(self) -> bool:
        return not self._c.test_inter()

    # -- nonblocking collectives (remaining family) ------------------------
    def Igather(self, sendbuf, recvbuf, root: int = 0) -> Request:
        me = self._c.rank

        def land(out):
            if me == root and recvbuf is not None:
                _copy_into(recvbuf, self._stacked(out))

        return Request(self._c.igather(_as_array(sendbuf), root),
                       transform=land)

    def Iscatter(self, sendbuf, recvbuf, root: int = 0) -> Request:
        send = None
        if self._c.rank == root:
            send = _as_array(sendbuf).reshape(self._c.size, -1)

        def land(out):
            if recvbuf is not None:
                _copy_into(recvbuf, out)

        return Request(self._c.iscatter(send, root), transform=land)

    def Iallgather(self, sendbuf, recvbuf) -> Request:
        return Request(
            self._c.iallgather(_as_array(sendbuf)),
            transform=lambda out: _copy_into(recvbuf,
                                             self._stacked(out)))

    def Ialltoall(self, sendbuf, recvbuf) -> Request:
        arr = _as_array(sendbuf).reshape(self._c.size, -1)
        return Request(
            self._c.ialltoall(arr),
            transform=lambda out: _copy_into(recvbuf,
                                             self._stacked(out)))

    def Iscan(self, sendbuf, recvbuf, op: "Op" = None) -> Request:
        return Request(
            self._c.iscan(_as_array(sendbuf),
                          _native_op(op or SUM)),
            transform=lambda out: _copy_into(recvbuf, out))

    def Iexscan(self, sendbuf, recvbuf, op: "Op" = None) -> Request:
        me = self._c.rank

        def land(out):
            if me != 0 and out is not None:
                _copy_into(recvbuf, out)

        return Request(self._c.iexscan(_as_array(sendbuf),
                                       _native_op(op or SUM)),
                       transform=land)

    # -- v-collectives (remaining uppercase forms) -------------------------
    def Alltoallv(self, sendbuf, recvbuf) -> None:
        arr, counts, displs, _dt = _vspec(sendbuf)
        flat = arr.reshape(-1)
        parts = [flat[d:d + c] for c, d in zip(counts, displs)]
        out = self._c.alltoallv(parts)
        _place_v(recvbuf, out)

    def Alltoallw(self, sendmsg, recvmsg) -> None:
        """mpi4py message format: ``[buf, counts, displs, datatypes]``
        (displacements in BYTES, one datatype per peer).  Converted to
        the native per-peer (buf-view, datatype, count) triples; recv
        views alias the caller's buffer so the fill is in place."""
        def conv(msg):
            buf, counts, displs, dts = msg
            raw = np.asarray(buf).view(np.uint8).reshape(-1)
            out = []
            for r in range(self._c.size):
                cnt = int(counts[r])
                if cnt == 0:
                    out.append(None)
                    continue
                nat = _to_native_dt(dts[r] if isinstance(dts, (list,
                                                              tuple))
                                    else dts)
                lo = int(displs[r])
                view = raw[lo:lo + cnt * nat.size].view(nat.base_np)
                out.append((view, nat, cnt))
            return out

        self._c.alltoallw(conv(sendmsg), conv(recvmsg))

    # -- attributes (≈ MPI_Comm_{set,get,delete}_attr) ---------------------
    @staticmethod
    def Create_keyval(copy_fn=None, delete_fn=None) -> "_Keyval":
        return _keyval_create(copy_fn, delete_fn)

    @staticmethod
    def Free_keyval(keyval) -> int:
        _keyval_free(keyval)
        return KEYVAL_INVALID

    def Set_attr(self, keyval, value) -> None:
        self._c.set_attr(keyval, value)

    def Get_attr(self, keyval):
        if keyval is TAG_UB:
            return _MAX_TAG
        return self._c.get_attr(keyval)

    def Delete_attr(self, keyval) -> None:
        self._c.delete_attr(keyval)

    # -- info / errhandler -------------------------------------------------
    def Set_info(self, info) -> None:
        self._c.set_info(info)

    def Get_info(self) -> "Info":
        return _wrap_info(self._c.get_info())

    def Set_errhandler(self, errhandler) -> None:
        from ompi_tpu.mpi import errhandler as _eh

        named = {ERRORS_RETURN: _eh.ERRORS_RETURN,
                 ERRORS_ARE_FATAL: _eh.ERRORS_ARE_FATAL}
        self._c.errhandler = named.get(errhandler, errhandler)

    def Get_errhandler(self):
        return self._c.errhandler

    # -- structure queries -------------------------------------------------
    def Compare(self, other: "Comm") -> int:
        """≈ MPI_Comm_compare (classic group-based definition)."""
        if self._c is other._c:
            return IDENT
        mine = list(self._c.group.ranks)
        theirs = list(other._c.group.ranks)
        if mine == theirs:
            return CONGRUENT
        if sorted(mine) == sorted(theirs):
            return SIMILAR
        return UNEQUAL

    def Get_topology(self) -> int:
        t = getattr(self._c, "topo", None)
        if t is None:
            return UNDEFINED
        return {"cart": CART, "graph": GRAPH,
                "dist_graph": DIST_GRAPH}[t.kind]

    def Idup(self) -> tuple["Comm", "Request"]:
        """mpi4py order: (newcomm, request) — use the comm only after
        the request completes."""
        req, new = self._c.idup()
        return Comm(new), Request(req)

    def Clone(self) -> "Comm":
        return self.Dup()

    def Create_dist_graph_adjacent(self, sources, destinations,
                                   sourceweights=None,
                                   destweights=None,
                                   info=None,
                                   reorder: bool = False
                                   ) -> "Distgraphcomm":
        new = self._c.dist_graph_create_adjacent(
            list(sources), list(destinations),
            list(sourceweights) if sourceweights is not None else None,
            list(destweights) if destweights is not None else None)
        return Distgraphcomm(new) if new is not None else None

    def Create_dist_graph(self, sources, degrees, destinations,
                          weights=None, info=None,
                          reorder: bool = False) -> "Distgraphcomm":
        new = self._c.dist_graph_create(
            list(sources), list(degrees), list(destinations),
            list(weights) if weights is not None else None)
        return Distgraphcomm(new) if new is not None else None

    # -- buffered sends (object forms; uppercase Bsend/Ibsend exist) ------
    def bsend(self, obj, dest: int, tag: int = 0) -> None:
        self._c.bsend(_dumps(obj), dest, tag)

    def ibsend(self, obj, dest: int, tag: int = 0) -> Request:
        return Request(self._c.ibsend(_dumps(obj), dest, tag))

    @property
    def rank(self) -> int:
        return self._c.rank

    @property
    def size(self) -> int:
        return self._c.size

    @property
    def name(self) -> str:
        return self._c.get_name()

    # -- management --------------------------------------------------------

    def Spawn(self, command: str, args=None, maxprocs: int = 1,
              info=None, root: int = 0) -> "Intercomm":
        """≈ MPI_Comm_spawn through the real launcher (root semantics:
        every rank calls; the native layer launches from rank 0)."""
        from ompi_tpu.mpi import dpm as _dpm

        argv = [command] + list(args or [])
        return Intercomm(_dpm.spawn(self._c, argv, maxprocs=maxprocs))

    @staticmethod
    def Get_parent() -> Optional["Intercomm"]:
        from ompi_tpu.mpi import dpm as _dpm

        native = _dpm.get_parent(COMM_WORLD._c)
        return Intercomm(native) if native is not None else None

    def Create_graph(self, index, edges,
                     reorder: bool = False) -> "Graphcomm":
        """≈ MPI_Graph_create (collective; None on excluded ranks)."""
        new = self._c.graph_create(index, edges, reorder=reorder)
        return Graphcomm(new) if new is not None else None

    def Create_cart(self, dims, periods=None,
                    reorder: bool = False) -> "Cartcomm":
        """≈ MPI_Cart_create (collective; None on excluded ranks).

        mpi4py defaults periods to all-False — the native layer's
        default is all-True (TPU torus), so the facade must pin it."""
        if periods is None:
            periods = [False] * len(list(dims))
        new = self._c.cart_create(dims, periods=periods, reorder=reorder)
        return Cartcomm(new) if new is not None else None

    def Dup(self) -> "Comm":
        return Comm(self._c.dup())

    def Split(self, color: int = 0, key: int = 0) -> Optional["Comm"]:
        sub = self._c.split(color, key)
        return None if sub is None else Comm(sub)

    def Split_type(self, split_type: int = COMM_TYPE_SHARED, key: int = 0,
                   info=None) -> Optional["Comm"]:
        sub = self._c.split_type(split_type, key)
        return None if sub is None else Comm(sub)

    def Create(self, group: Group) -> Optional["Comm"]:
        sub = self._c.create(group._g)
        return None if sub is None else Comm(sub)

    def Create_group(self, group: Group, tag: int = 0) -> Optional["Comm"]:
        sub = self._c.create_group(group._g, tag)
        return None if sub is None else Comm(sub)

    def Free(self) -> None:
        self._c.free()

    def Abort(self, errorcode: int = 1):
        import ompi_tpu

        ompi_tpu.abort(errorcode)

    # -- point-to-point: buffer convention ---------------------------------

    def Send(self, buf, dest: int, tag: int = 0) -> None:
        self._c.send(_as_array(buf), dest, tag)

    def Ssend(self, buf, dest: int, tag: int = 0) -> None:
        self._c.ssend(_as_array(buf), dest, tag)

    def Bsend(self, buf, dest: int, tag: int = 0) -> None:
        self._c.bsend(_as_array(buf), dest, tag)

    def Rsend(self, buf, dest: int, tag: int = 0) -> None:
        self._c.rsend(_as_array(buf), dest, tag)

    def Recv(self, buf, source: int = ANY_SOURCE, tag: int = ANY_TAG,
             status: Optional[Status] = None) -> None:
        arr = _as_array(buf)
        st = _NativeStatus()
        out = self._c.recv(arr, source, tag, status=st)
        _fill_status(status, st)
        if out is not None and not np.shares_memory(arr, np.asarray(out)):
            _copy_into(buf, out)

    def Isend(self, buf, dest: int, tag: int = 0) -> Request:
        return Request(self._c.isend(_as_array(buf), dest, tag))

    def Issend(self, buf, dest: int, tag: int = 0) -> Request:
        return Request(self._c.issend(_as_array(buf), dest, tag))

    def Ibsend(self, buf, dest: int, tag: int = 0) -> Request:
        return Request(self._c.ibsend(_as_array(buf), dest, tag))

    def Irsend(self, buf, dest: int, tag: int = 0) -> Request:
        return Request(self._c.irsend(_as_array(buf), dest, tag))

    def Irecv(self, buf, source: int = ANY_SOURCE,
              tag: int = ANY_TAG) -> Request:
        return Request(self._c.irecv(_as_array(buf), source, tag))

    def Sendrecv(self, sendbuf, dest: int, sendtag: int = 0, recvbuf=None,
                 source: int = ANY_SOURCE, recvtag: int = ANY_TAG,
                 status: Optional[Status] = None) -> None:
        st = _NativeStatus()
        out = self._c.sendrecv(
            _as_array(sendbuf), dest,
            None if recvbuf is None else _as_array(recvbuf),
            source, sendtag, recvtag, status=st)
        _fill_status(status, st)
        if recvbuf is not None and out is not None and not np.shares_memory(
                _as_array(recvbuf), np.asarray(out)):
            _copy_into(recvbuf, out)

    def Sendrecv_replace(self, buf, dest: int, sendtag: int = 0,
                         source: int = ANY_SOURCE, recvtag: int = ANY_TAG,
                         status: Optional[Status] = None) -> None:
        st = _NativeStatus()
        self._c.sendrecv_replace(_as_array(buf), dest, source, sendtag,
                                 recvtag, status=st)
        _fill_status(status, st)

    def Send_init(self, buf, dest: int, tag: int = 0) -> Prequest:
        return Prequest(self._c.send_init(_as_array(buf), dest, tag))

    def Recv_init(self, buf, source: int = ANY_SOURCE,
                  tag: int = ANY_TAG) -> Prequest:
        return Prequest(self._c.recv_init(_as_array(buf), source, tag))

    # -- persistent collectives + partitioned p2p (MPI-4 *_init) -----------

    def Barrier_init(self) -> Prequest:
        return Prequest(self._c.barrier_init())

    def Bcast_init(self, buf, root: int = 0) -> Prequest:
        # one buffer, both roles (the mpi4py shape): the root's payload
        # is re-read per start, a non-root's is the landing buffer the
        # native layer fills in place at each wait
        return Prequest(self._c.bcast_init(_as_array(buf), root=root))

    def Allreduce_init(self, sendbuf, recvbuf, op: "Op" = None
                       ) -> Prequest:
        return Prequest(
            self._c.allreduce_init(_as_array(sendbuf),
                                   op=_native_op(op or SUM)),
            transform=lambda out: _copy_into(recvbuf, out))

    def Psend_init(self, buf, partitions: int, dest: int,
                   tag: int = 0) -> Prequest:
        return Prequest(self._c.psend_init(
            _as_array(buf), dest, tag=tag, partitions=partitions))

    def Precv_init(self, buf, partitions: int, source: int,
                   tag: int = 0) -> Prequest:
        return Prequest(self._c.precv_init(
            _as_array(buf), source, tag=tag, partitions=partitions))

    # -- probes ------------------------------------------------------------

    def Probe(self, source: int = ANY_SOURCE, tag: int = ANY_TAG,
              status: Optional[Status] = None) -> bool:
        st = self._c.probe(source, tag)
        _fill_status(status, st)
        return True

    def Iprobe(self, source: int = ANY_SOURCE, tag: int = ANY_TAG,
               status: Optional[Status] = None) -> bool:
        st = self._c.iprobe(source, tag)
        if st is None:
            return False
        _fill_status(status, st)
        return True

    def Mprobe(self, source: int = ANY_SOURCE, tag: int = ANY_TAG,
               status: Optional[Status] = None) -> Message:
        msg, st = self._c.mprobe(source, tag)
        _fill_status(status, st)
        return Message(self._c, msg)

    def Improbe(self, source: int = ANY_SOURCE, tag: int = ANY_TAG,
                status: Optional[Status] = None) -> Optional[Message]:
        out = self._c.improbe(source, tag)
        if out is None:
            return None
        msg, st = out
        _fill_status(status, st)
        return Message(self._c, msg)

    # -- collectives: buffer convention ------------------------------------

    def Barrier(self) -> None:
        self._c.barrier()

    def Bcast(self, buf, root: int = 0) -> None:
        arr = _as_array(buf)
        out = self._c.bcast(arr if self._c.rank == root else None, root)
        if self._c.rank != root:
            _copy_into(buf, out)

    def Reduce(self, sendbuf, recvbuf, op: Op = SUM, root: int = 0) -> None:
        send = (_as_array(recvbuf) if sendbuf is IN_PLACE
                else _as_array(sendbuf))
        out = self._c.reduce(send, op=_native_op(op), root=root)
        if self._c.rank == root and recvbuf is not None:
            _copy_into(recvbuf, out)

    def Allreduce(self, sendbuf, recvbuf, op: Op = SUM) -> None:
        send = (_as_array(recvbuf) if sendbuf is IN_PLACE
                else _as_array(sendbuf))
        out = self._c.allreduce(send, op=_native_op(op))
        _copy_into(recvbuf, out)

    @staticmethod
    def _stacked(out):
        """Uniform-count collectives return a stacked ndarray from the
        native path — pass it straight through (uppercase = zero extra
        copies); only a non-array per-rank list pays the concatenate."""
        if isinstance(out, np.ndarray):
            return out
        return np.concatenate([np.asarray(p).reshape(-1) for p in out])

    def Gather(self, sendbuf, recvbuf, root: int = 0) -> None:
        out = self._c.gather(_as_array(sendbuf), root)
        if self._c.rank == root and recvbuf is not None:
            _copy_into(recvbuf, self._stacked(out))

    def Gatherv(self, sendbuf, recvbuf, root: int = 0) -> None:
        out = self._c.gatherv(_as_array(sendbuf), root)
        if self._c.rank == root and recvbuf is not None:
            _place_v(recvbuf, out)

    def Allgather(self, sendbuf, recvbuf) -> None:
        out = self._c.allgather(_as_array(sendbuf))
        _copy_into(recvbuf, self._stacked(out))

    def Allgatherv(self, sendbuf, recvbuf) -> None:
        out = self._c.allgatherv(_as_array(sendbuf))
        _place_v(recvbuf, out)

    def Scatter(self, sendbuf, recvbuf, root: int = 0) -> None:
        send = None
        if self._c.rank == root:
            arr = _as_array(sendbuf)
            send = arr.reshape(self._c.size, -1)
        out = self._c.scatter(send, root)
        if recvbuf is not None:
            _copy_into(recvbuf, out)

    def Scatterv(self, sendbuf, recvbuf, root: int = 0) -> None:
        parts = None
        if self._c.rank == root:
            arr, counts, displs, dtype = _vspec(sendbuf)
            parts = [arr.reshape(-1)[d:d + c]
                     for c, d in zip(counts, displs)]
        out = self._c.scatterv(parts, root)
        if recvbuf is not None:
            _copy_into(recvbuf, out)

    def Alltoall(self, sendbuf, recvbuf) -> None:
        arr = _as_array(sendbuf).reshape(self._c.size, -1)
        out = self._c.alltoall(arr)
        _copy_into(recvbuf, self._stacked(out))

    def Reduce_scatter_block(self, sendbuf, recvbuf, op: Op = SUM) -> None:
        out = self._c.reduce_scatter_block(_as_array(sendbuf),
                                           op=_native_op(op))
        _copy_into(recvbuf, out)

    def Reduce_scatter(self, sendbuf, recvbuf, recvcounts=None,
                       op: Op = SUM) -> None:
        arr = _as_array(sendbuf)
        if recvcounts is not None:
            # explicit counts: reduce everywhere, keep my segment (the
            # native reduce_scatter contract is the equal array_split)
            me = self._c.rank
            displs = np.concatenate([[0], np.cumsum(recvcounts)[:-1]])
            reduced = np.asarray(
                self._c.allreduce(arr, op=_native_op(op))).reshape(-1)
            out = reduced[displs[me]:displs[me] + recvcounts[me]]
        else:
            out = self._c.reduce_scatter(arr, op=_native_op(op))
        _copy_into(recvbuf, out)

    def Scan(self, sendbuf, recvbuf, op: Op = SUM) -> None:
        out = self._c.scan(_as_array(sendbuf), op=_native_op(op))
        _copy_into(recvbuf, out)

    def Exscan(self, sendbuf, recvbuf, op: Op = SUM) -> None:
        out = self._c.exscan(_as_array(sendbuf), op=_native_op(op))
        if self._c.rank != 0 and out is not None:
            _copy_into(recvbuf, out)

    # nonblocking collectives (the libnbc twins)
    def Ibarrier(self) -> Request:
        return Request(self._c.ibarrier())

    def Ibcast(self, buf, root: int = 0) -> Request:
        arr = _as_array(buf)
        me = self._c.rank
        req = self._c.ibcast(arr if me == root else None, root)
        if me == root:
            return Request(req)

        def land(out, _buf=buf):
            if out is not None:
                _copy_into(_buf, out)
            return out

        return Request(req, transform=land)

    def Iallreduce(self, sendbuf, recvbuf, op: Op = SUM) -> Request:
        send = (_as_array(recvbuf) if sendbuf is IN_PLACE
                else _as_array(sendbuf))
        req = self._c.iallreduce(send, op=_native_op(op))

        def land(out, _buf=recvbuf):
            _copy_into(_buf, out)
            return out

        return Request(req, transform=land)

    # -- point-to-point: object convention ---------------------------------

    def send(self, obj, dest: int, tag: int = 0) -> None:
        self._c.send(_dumps(obj), dest, tag)

    def ssend(self, obj, dest: int, tag: int = 0) -> None:
        self._c.ssend(_dumps(obj), dest, tag)

    def recv(self, buf=None, source: int = ANY_SOURCE, tag: int = ANY_TAG,
             status: Optional[Status] = None) -> Any:
        st = _NativeStatus()
        out = self._c.recv(None, source, tag, status=st)
        _fill_status(status, st)
        return _loads(out)

    def isend(self, obj, dest: int, tag: int = 0) -> Request:
        return Request(self._c.isend(_dumps(obj), dest, tag))

    def issend(self, obj, dest: int, tag: int = 0) -> Request:
        return Request(self._c.issend(_dumps(obj), dest, tag))

    def irecv(self, buf=None, source: int = ANY_SOURCE,
              tag: int = ANY_TAG) -> Request:
        return Request(self._c.irecv(None, source, tag), transform=_loads)

    def sendrecv(self, sendobj, dest: int, sendtag: int = 0, recvbuf=None,
                 source: int = ANY_SOURCE, recvtag: int = ANY_TAG,
                 status: Optional[Status] = None) -> Any:
        st = _NativeStatus()
        out = self._c.sendrecv(_dumps(sendobj), dest, None, source,
                               sendtag, recvtag, status=st)
        _fill_status(status, st)
        return _loads(out)

    def probe(self, source: int = ANY_SOURCE, tag: int = ANY_TAG,
              status: Optional[Status] = None) -> bool:
        return self.Probe(source, tag, status)

    def iprobe(self, source: int = ANY_SOURCE, tag: int = ANY_TAG,
               status: Optional[Status] = None) -> bool:
        return self.Iprobe(source, tag, status)

    def mprobe(self, source: int = ANY_SOURCE, tag: int = ANY_TAG,
               status: Optional[Status] = None) -> Message:
        return self.Mprobe(source, tag, status)

    def improbe(self, source: int = ANY_SOURCE, tag: int = ANY_TAG,
                status: Optional[Status] = None) -> Optional[Message]:
        return self.Improbe(source, tag, status)

    # -- collectives: object convention ------------------------------------

    def barrier(self) -> None:
        self._c.barrier()

    def bcast(self, obj, root: int = 0) -> Any:
        me = self._c.rank
        out = self._c.bcast(_dumps(obj) if me == root else None, root)
        return _loads(out)

    def gather(self, sendobj, root: int = 0) -> Optional[list]:
        out = self._c.gatherv(_dumps(sendobj), root)
        if self._c.rank != root:
            return None
        return [_loads(p) for p in out]

    def allgather(self, sendobj) -> list:
        out = self._c.allgatherv(_dumps(sendobj))
        return [_loads(p) for p in out]

    def scatter(self, sendobj, root: int = 0) -> Any:
        parts = None
        if self._c.rank == root:
            if len(sendobj) != self._c.size:
                raise ValueError(
                    f"scatter list has {len(sendobj)} entries for "
                    f"{self._c.size} ranks")
            parts = [_dumps(o) for o in sendobj]
        out = self._c.scatterv(parts, root)
        return _loads(out)

    def alltoall(self, sendobjs) -> list:
        parts = [_dumps(o) for o in sendobjs]
        out = self._c.alltoallv(parts)
        return [_loads(p) for p in out]

    def reduce(self, sendobj, op: Op = SUM, root: int = 0) -> Any:
        vals = self.allgather(sendobj)
        if self._c.rank != root:
            return None
        return _pyfold(op, vals)

    def allreduce(self, sendobj, op: Op = SUM) -> Any:
        return _pyfold(op, self.allgather(sendobj))

    def scan(self, sendobj, op: Op = SUM) -> Any:
        vals = self.allgather(sendobj)
        return _pyfold(op, vals[: self._c.rank + 1])

    def exscan(self, sendobj, op: Op = SUM) -> Any:
        vals = self.allgather(sendobj)
        if self._c.rank == 0:
            return None
        return _pyfold(op, vals[: self._c.rank])

    def __repr__(self) -> str:
        return f"<MPI.Comm {self._c!r}>"


Intracomm = Comm  # mpi4py exposes COMM_WORLD as an Intracomm


def _pyfold(op: Op, vals: list) -> Any:
    fold = op._py if isinstance(op, Op) and op._py is not None else op
    acc = vals[0]
    for v in vals[1:]:
        acc = fold(acc, v)
    return acc


def _place_v(recv_spec, parts) -> None:
    """Write gathered per-rank pieces into the receive buffer.  With a
    [buf, counts, displs?, type?] spec each rank's piece lands at its
    displacement (displs may reorder or leave gaps — MPI Gatherv
    semantics); a bare buffer packs the pieces contiguously."""
    parts = [np.asarray(p).reshape(-1) for p in parts]
    has_layout = (isinstance(recv_spec, (list, tuple))
                  and any(not isinstance(e, Datatype)
                          for e in recv_spec[1:]))
    if not has_layout:
        _copy_into(recv_spec, np.concatenate(parts))
        return
    buf, counts, displs, _ = _vspec(recv_spec)
    flat = buf.reshape(-1)
    for p, c, d in zip(parts, counts, displs):
        seg = p[:c]
        if flat.dtype != seg.dtype:
            seg = seg.astype(flat.dtype)
        flat[d:d + seg.size] = seg


def _vspec(spec):
    """[buf, counts, displs?, datatype?] → (arr, counts, displs, dtype)."""
    if not isinstance(spec, (list, tuple)):
        raise ValueError("Scatterv/Gatherv need [buf, counts, ...] specs")
    buf = np.asarray(spec[0])
    counts = None
    displs = None
    dtype = None
    seq = []
    for extra in spec[1:]:
        if isinstance(extra, Datatype):
            dtype = extra
        else:
            seq.append(extra)
    if len(seq) == 1:
        item = seq[0]
        if (isinstance(item, (list, tuple)) and len(item) == 2
                and isinstance(item[0], (list, tuple, np.ndarray))):
            counts, displs = item
        else:
            counts = item
    elif len(seq) >= 2:
        counts, displs = seq[0], seq[1]
    counts = [int(c) for c in np.asarray(counts).reshape(-1)]
    if displs is None:
        displs = list(np.concatenate([[0], np.cumsum(counts)[:-1]]))
    else:
        displs = [int(d) for d in np.asarray(displs).reshape(-1)]
    if dtype is not None and buf.dtype != dtype.np_dtype:
        buf = buf.view(dtype.np_dtype)
    return buf, counts, displs, dtype




# ---------------------------------------------------------------------------
# Intercomm / spawn facade (dynamic process management)
# ---------------------------------------------------------------------------

class Intercomm:
    """mpi4py-style intercommunicator over the native DPM intercomm:
    p2p ranks address the REMOTE group; Merge folds both groups into
    one intracommunicator."""

    def __init__(self, native) -> None:
        self._i = native

    def Get_rank(self) -> int:
        return self._i.rank

    def Get_size(self) -> int:
        return self._i.size

    def Get_remote_size(self) -> int:
        return self._i.remote_size

    @property
    def rank(self) -> int:
        return self._i.rank

    @property
    def size(self) -> int:
        return self._i.size

    @property
    def remote_size(self) -> int:
        return self._i.remote_size

    # -- buffer p2p against the remote group -------------------------------
    def Send(self, buf, dest: int, tag: int = 0) -> None:
        self._i.send(_as_array(buf), dest, tag)

    def Recv(self, buf, source: int = ANY_SOURCE, tag: int = ANY_TAG,
             status: Optional[Status] = None) -> None:
        st = _NativeStatus()
        out = self._i.recv(source=source, tag=tag, status=st)
        _fill_status(status, st)
        _copy_into(buf, out)

    # -- object p2p --------------------------------------------------------
    def send(self, obj, dest: int, tag: int = 0) -> None:
        self._i.send(_dumps(obj), dest, tag)

    def recv(self, source: int = ANY_SOURCE, tag: int = ANY_TAG,
             status: Optional[Status] = None):
        st = _NativeStatus()
        out = self._i.recv(source=source, tag=tag, status=st)
        _fill_status(status, st)
        return _loads(out)

    def Merge(self, high: bool = False) -> "Comm":
        return Comm(self._i.merge(high=high))

    def Disconnect(self) -> None:
        self._i.disconnect()

    def Free(self) -> None:
        self.Disconnect()


# ---------------------------------------------------------------------------
# Cartesian topology facade
# ---------------------------------------------------------------------------

class Cartcomm(Comm):
    """Communicator with a Cartesian topology (mpi4py surface over the
    native topo framework — everything reads the attached CartTopology
    at ``self._c.topo``; Sendrecv etc. inherit from Comm)."""

    def Get_topo(self):
        t = self._c.topo
        return (list(t.dims), [bool(p) for p in t.periods],
                t.coords(self._c.rank))

    def Get_dim(self) -> int:
        return self._c.topo.ndims

    @property
    def dims(self):
        return list(self._c.topo.dims)

    @property
    def periods(self):
        return [bool(p) for p in self._c.topo.periods]

    @property
    def coords(self):
        return self._c.topo.coords(self._c.rank)

    @property
    def dim(self) -> int:
        return self._c.topo.ndims

    @property
    def topo(self):
        return self.Get_topo()

    def Get_coords(self, rank: int):
        return self._c.topo.coords(rank)

    def Get_cart_rank(self, coords):
        return self._c.topo.rank(coords)

    def Shift(self, direction: int, disp: int = 1):
        """→ (source, dest) with PROC_NULL at non-periodic edges."""
        return self._c.topo.shift(self._c.rank, direction, disp)

    def Sub(self, remain_dims) -> "Cartcomm":
        sub = self._c.cart_sub(remain_dims)
        return Cartcomm(sub) if sub is not None else None


class Graphcomm(Comm):
    """Communicator with a general graph topology (mpi4py surface over
    the native topo framework)."""

    def Get_topo(self):
        from ompi_tpu.mpi import topo as _topo

        return _topo.graph_get(self._c)

    def Get_dims(self):
        from ompi_tpu.mpi import topo as _topo

        return _topo.graphdims_get(self._c)

    def Get_neighbors(self, rank: int):
        return self._c.topo.neighbors_of(rank)

    def Get_neighbors_count(self, rank: int) -> int:
        return len(self._c.topo.neighbors_of(rank))

    @property
    def nnodes(self) -> int:
        return self.Get_dims()[0]

    @property
    def nedges(self) -> int:
        return self.Get_dims()[1]


class Distgraphcomm(Comm):
    """Communicator with a distributed-graph topology (mpi4py surface
    over the native topo framework)."""

    def Get_dist_neighbors_count(self) -> tuple:
        from ompi_tpu.mpi.topo import dist_graph_neighbors_count

        return dist_graph_neighbors_count(self._c)

    def Get_dist_neighbors(self) -> tuple:
        from ompi_tpu.mpi.topo import dist_graph_neighbors

        return dist_graph_neighbors(self._c)


def Compute_dims(nnodes: int, dims) -> list:
    """≈ mpi4py MPI.Compute_dims / MPI_Dims_create."""
    from ompi_tpu.mpi.topo import dims_create

    if isinstance(dims, int):
        dims = [0] * dims
    return dims_create(nnodes, len(dims), dims)


def Get_address(buf) -> int:
    """≈ MPI_Get_address."""
    from ompi_tpu.mpi.datatype import get_address

    return get_address(np.asarray(buf))


def Alloc_mem(size: int, info=None):
    """≈ MPI_Alloc_mem → a uint8 buffer."""
    from ompi_tpu.mpi.datatype import alloc_mem

    return alloc_mem(int(size))


def Free_mem(buf) -> None:
    from ompi_tpu.mpi.datatype import free_mem

    free_mem(buf)


def Attach_buffer(buf) -> None:
    """≈ MPI_Buffer_attach: back buffered-mode sends.  mpi4py passes a
    bytearray/array; the pool only needs its SIZE."""
    from ompi_tpu.mpi.pml import buffer_attach

    # memoryview.nbytes counts BYTES for every buffer protocol object
    # (array.array's len() would count elements)
    buffer_attach(int(memoryview(buf).nbytes))


def Detach_buffer():
    """≈ MPI_Buffer_detach (drains pending buffered sends)."""
    from ompi_tpu.mpi.pml import buffer_detach

    return buffer_detach()


# ---------------------------------------------------------------------------
# Win (one-sided) / File (MPI-IO) facades
# ---------------------------------------------------------------------------

LOCK_EXCLUSIVE = 1
LOCK_SHARED = 2

# file amodes re-exported under mpi4py's names
from ompi_tpu.mpi import io as _io_mod  # noqa: E402

MODE_RDONLY = _io_mod.MODE_RDONLY
MODE_RDWR = _io_mod.MODE_RDWR
MODE_WRONLY = _io_mod.MODE_WRONLY
MODE_CREATE = _io_mod.MODE_CREATE
MODE_EXCL = _io_mod.MODE_EXCL
MODE_APPEND = _io_mod.MODE_APPEND
MODE_DELETE_ON_CLOSE = _io_mod.MODE_DELETE_ON_CLOSE
SEEK_SET = _io_mod.SEEK_SET
SEEK_CUR = _io_mod.SEEK_CUR
SEEK_END = _io_mod.SEEK_END


def _target_spec(target, origin_size: int, *, need: str):
    """mpi4py target spec: None | disp | [disp, count(, datatype)] →
    (disp, count); the explicit count must fit the origin buffer
    (``need`` = "origin holds at least count" direction)."""
    if target is None:
        return 0, origin_size
    if isinstance(target, (int, np.integer)):
        return int(target), origin_size
    seq = list(target)
    disp = int(seq[0]) if seq else 0
    count = origin_size
    for extra in seq[1:]:
        if isinstance(extra, (int, np.integer)):
            count = int(extra)
    if count > origin_size:
        raise Exception(
            f"target count {count} exceeds the {need} buffer size "
            f"{origin_size}")
    return disp, count


class Win:
    """mpi4py-style window over the native active-message osc window.

    Displacements count WINDOW ELEMENTS (create with
    ``disp_unit=memory.itemsize``, mpi4py's common idiom; byte
    displacements with ``disp_unit=1`` are converted and must align)."""

    def __init__(self, native, disp_unit: int) -> None:
        self._w = native
        self._du = disp_unit

    # -- constructors ------------------------------------------------------
    @classmethod
    def Create(cls, memory, disp_unit: int = 1, info=None,
               comm: "Comm" = None) -> "Win":
        arr = np.asarray(memory)
        from ompi_tpu.mpi.osc import Window as _NativeWin

        if comm is None:
            comm = COMM_SELF     # mpi4py's default
        native = _NativeWin(comm._c, buffer=arr, info=info)
        return cls(native, disp_unit)

    @classmethod
    def Allocate(cls, size: int, disp_unit: int = 1, info=None,
                 comm: "Comm" = None) -> "Win":
        arr = np.zeros(size, np.uint8)
        return cls.Create(arr, disp_unit, info, comm)

    @classmethod
    def Allocate_shared(cls, size: int, disp_unit: int = 1, info=None,
                        comm: "Comm" = None) -> "_SharedWin":
        """≈ MPI_Win_allocate_shared (osc/sm model): one shm segment,
        every rank owns a slice; ``Shared_query`` returns zero-copy
        views and data moves by direct load/store + ``Sync`` — the
        message-window RMA verbs raise with that explanation.  Requires
        a single-host communicator (Split_type(COMM_TYPE_SHARED)
        first).  ``info`` is accepted for parity (osc/sm has no lock
        service to hint)."""
        from ompi_tpu.mpi.osc import SharedWindow as _SW

        if comm is None:
            comm = COMM_SELF
        return _SharedWin(_SW(comm._c, local_size=int(size)), disp_unit)

    def Shared_query(self, rank: int) -> tuple:
        raise Exception(
            "Shared_query is only valid on a Win.Allocate_shared window")

    @classmethod
    def Create_dynamic(cls, info=None, comm: "Comm" = None) -> "Win":
        from ompi_tpu.mpi.osc import Window as _NativeWin

        if comm is None:
            comm = COMM_SELF
        native = _NativeWin.create_dynamic(comm._c, info=info)
        return cls(native, 1)

    def Attach(self, memory) -> int:
        """≈ MPI_Win_attach; returns the region's base WINDOW OFFSET —
        the value peers use as the target displacement (this facade
        addresses dynamic windows by offset, not virtual address)."""
        return self._w.attach(np.asarray(memory))

    def Detach(self, memory_or_base) -> None:
        """Accepts the buffer passed to Attach (mpi4py convention) or
        the base offset Attach returned."""
        if isinstance(memory_or_base, (int, np.integer)):
            self._w.detach(int(memory_or_base))
            return
        arr = np.asarray(memory_or_base).reshape(-1)
        want = arr.__array_interface__["data"][0]
        for base, region in list(self._w._regions.items()):
            if region.__array_interface__["data"][0] == want:
                self._w.detach(base)
                return
        raise Exception(
            "Detach: this buffer is not attached to the window")

    def Set_name(self, name: str) -> None:
        self._w.name = str(name)

    def Get_name(self) -> str:
        return getattr(self._w, "name", "win")

    def _disp(self, disp: int, itemsize: int) -> int:
        nbytes = disp * self._du
        if nbytes % itemsize:
            raise Exception(  # noqa: B904 — MPI.Exception
                f"target displacement {disp} (disp_unit {self._du}) is "
                f"not aligned to the window element size {itemsize}")
        return nbytes // itemsize

    # -- data movement -----------------------------------------------------
    def _reinterprets(self, operand_dtype) -> bool:
        """True when an operand of this dtype crosses a byte
        (``Win.Allocate``) window and must be handled bitwise — the ONE
        place the reinterpretation rule lives."""
        return (self._w.buf.dtype == np.uint8
                and np.dtype(operand_dtype) != np.uint8)

    def _wire(self, data: np.ndarray, what: str, op: Op = None):
        """Origin data as the window's element type.

        ``Win.Allocate`` windows are raw bytes (uint8); the mpi4py idiom
        Puts/Gets TYPED buffers through them, which must be a bitwise
        copy — a value-cast would wrap a float64 into 0..255.  Arithmetic
        accumulate ops on reinterpreted bytes are meaningless, so those
        raise instead of corrupting silently."""
        if not self._reinterprets(data.dtype):
            return data
        if op is not None and op not in (REPLACE, NO_OP, BAND, BOR, BXOR):
            raise Exception(
                f"{what} with {op._name} on a byte (Win.Allocate) window "
                f"requires a uint8 origin; arithmetic on reinterpreted "
                f"bytes would corrupt — use Win.Create with a typed "
                f"buffer instead")
        return np.ascontiguousarray(data).view(np.uint8)

    def Put(self, origin, target_rank: int, target=None) -> None:
        arr = _as_array(origin)
        disp, count = _target_spec(target, arr.size, need="origin")
        off = self._disp(disp, self._w.buf.itemsize)
        self._w.put(target_rank,
                    self._wire(arr.reshape(-1)[:count], "Put"), offset=off)

    def Get(self, origin, target_rank: int, target=None) -> None:
        # one definition of the byte-window read path: Rget's (the
        # native layer defines get() as rget().wait() the same way)
        self.Rget(origin, target_rank, target).Wait()

    def Accumulate(self, origin, target_rank: int, target=None,
                   op: Op = SUM) -> None:
        arr = _as_array(origin)
        disp, count = _target_spec(target, arr.size, need="origin")
        off = self._disp(disp, self._w.buf.itemsize)
        self._w.accumulate(target_rank,
                           self._wire(arr.reshape(-1)[:count],
                                      "Accumulate", op),
                           op=_native_op(op), offset=off)

    def Get_accumulate(self, origin, result, target_rank: int,
                       target=None, op: Op = SUM) -> None:
        arr = _as_array(origin)
        disp, count = _target_spec(target, arr.size, need="origin")
        off = self._disp(disp, self._w.buf.itemsize)
        data = self._wire(arr.reshape(-1)[:count], "Get_accumulate", op)
        old = self._w.get_accumulate(target_rank, data,
                                     op=_native_op(op), offset=off)
        if self._reinterprets(arr.dtype):
            old = np.ascontiguousarray(old).view(arr.dtype)
        _copy_into(result, old)

    def _scalar_guard(self, arr: np.ndarray, what: str,
                      operand: str = "origin") -> None:
        """Single-element atomics target ONE window element; on a byte
        (Win.Allocate) window a typed operand cannot be reinterpreted
        into one uint8 — refuse rather than value-cast into 0..255."""
        if self._reinterprets(arr.dtype):
            raise Exception(
                f"{what} on a byte (Win.Allocate) window requires a "
                f"uint8 {operand}: the target element is a single byte — "
                f"use Win.Create over a typed buffer for typed atomics")

    def Fetch_and_op(self, origin, result, target_rank: int,
                     target_disp: int = 0, op: Op = SUM) -> None:
        arr = _as_array(origin)
        self._scalar_guard(arr, "Fetch_and_op")
        val = arr.reshape(-1)[0]
        off = self._disp(int(target_disp), self._w.buf.itemsize)
        old = self._w.fetch_op(target_rank, val, op=_native_op(op),
                               offset=off)
        _copy_into(result, np.asarray(old).reshape(1))

    def Compare_and_swap(self, origin, compare, result,
                         target_rank: int, target_disp: int = 0) -> None:
        val = _as_array(origin)
        self._scalar_guard(val, "Compare_and_swap")
        cmp_arr = _as_array(compare)
        self._scalar_guard(cmp_arr, "Compare_and_swap", operand="compare")
        cmp_ = cmp_arr.reshape(-1)[0]
        off = self._disp(int(target_disp), self._w.buf.itemsize)
        old = self._w.compare_swap(target_rank, cmp_,
                                   val.reshape(-1)[0], offset=off)
        _copy_into(result, np.asarray(old).reshape(1))

    # -- request-based RMA (results/completion via Request) ----------------
    def Rput(self, origin, target_rank: int, target=None) -> "Request":
        arr = _as_array(origin)
        disp, count = _target_spec(target, arr.size, need="origin")
        off = self._disp(disp, self._w.buf.itemsize)
        return Request(self._w.rput(
            target_rank, self._wire(arr.reshape(-1)[:count], "Rput"),
            offset=off))

    def Rget(self, origin, target_rank: int, target=None) -> "Request":
        dst = _as_array(origin)
        disp, count = _target_spec(target, dst.size, need="receive")
        off = self._disp(disp, self._w.buf.itemsize)
        if self._reinterprets(dst.dtype):
            req = self._w.rget(target_rank, count * dst.itemsize,
                               offset=off)

            def land(out):
                _copy_into(origin,
                           np.ascontiguousarray(out).view(dst.dtype))
        else:
            req = self._w.rget(target_rank, count, offset=off)

            def land(out):
                _copy_into(origin, out)

        return Request(req, transform=land)

    def Raccumulate(self, origin, target_rank: int, target=None,
                    op: Op = SUM) -> "Request":
        arr = _as_array(origin)
        disp, count = _target_spec(target, arr.size, need="origin")
        off = self._disp(disp, self._w.buf.itemsize)
        return Request(self._w.raccumulate(
            target_rank,
            self._wire(arr.reshape(-1)[:count], "Raccumulate", op),
            op=_native_op(op), offset=off))

    def Flush_local(self, rank: int) -> None:
        self._w.flush_local(rank)

    def Flush_local_all(self) -> None:
        self._w.flush_local_all()

    def Test(self) -> bool:
        """≈ MPI_Win_test (PSCW exposure-epoch poll)."""
        return bool(self._w.test_epoch())

    def Get_group(self) -> "Group":
        g = self._w.get_group()
        return Group(g, g.world_rank(self._w.comm.rank))

    # -- attributes --------------------------------------------------------
    def Get_attr(self, keyval):
        if keyval is WIN_BASE:
            from ompi_tpu.mpi.datatype import get_address

            return get_address(np.asarray(self._w.buf))
        if keyval is WIN_SIZE:
            return self._w.buf.nbytes
        if keyval is WIN_DISP_UNIT:
            return self._du
        return None

    # -- synchronization ---------------------------------------------------
    def Fence(self, assertion: int = 0) -> None:
        self._w.fence()

    def Sync(self) -> None:
        """≈ MPI_Win_sync (message windows: no-op — delivery orders
        stores; the shared-window subclass overrides with the real
        memory barrier)."""

    def Lock(self, rank: int, lock_type: int = LOCK_EXCLUSIVE,
             assertion: int = 0) -> None:
        self._w.lock(rank, exclusive=lock_type == LOCK_EXCLUSIVE)

    def Unlock(self, rank: int) -> None:
        self._w.unlock(rank)

    def Lock_all(self, assertion: int = 0) -> None:
        self._w.lock_all()

    def Unlock_all(self) -> None:
        self._w.unlock_all()

    def Flush(self, rank: int) -> None:
        self._w.flush(rank)

    def Flush_all(self) -> None:
        self._w.flush_all()

    def _group_ranks(self, group: Group) -> list:
        g = self._w.comm.group
        out = []
        for w in group._g._ranks:
            r = g.rank_of(w)
            if r is None or r < 0:
                raise Exception(f"group rank {w} not in window comm")
            out.append(r)
        return out

    def Start(self, group: Group, assertion: int = 0) -> None:
        self._w.start(self._group_ranks(group))

    def Complete(self) -> None:
        self._w.complete()

    def Post(self, group: Group, assertion: int = 0) -> None:
        self._w.post(self._group_ranks(group))

    def Wait(self) -> None:
        self._w.wait()

    def Free(self) -> None:
        self._w.free()

    @property
    def memory(self):
        return self._w.buf


class _SharedWin(Win):
    """A Win over the osc/sm SharedWindow: the RMA verbs are served by
    direct memcpy/load-store on the shared mapping (the osc/sm model —
    the memory IS the window).  Lock epochs are consistency points only
    (the mapping is cache-coherent; there is no lock service), and
    accumulates are NOT hardware-atomic per element — concurrent
    conflicting accumulates from different origins may interleave (use
    ``fetch_add`` for lock-free counters).  PSCW epochs are not defined
    on this component and raise."""

    def Shared_query(self, rank: int) -> tuple:
        """(size_bytes, disp_unit, zero-copy buf-view) of rank's slice."""
        view = self._w.shared_query(rank)
        return view.nbytes, self._du, view

    # -- data movement: memcpy on the mapping -----------------------------
    def _bytes_of(self, rank: int) -> np.ndarray:
        return self._w.shared_query(rank).view(np.uint8)

    def Put(self, origin, target_rank: int, target=None) -> None:
        arr = _as_array(origin)
        disp, count = _target_spec(target, arr.size, need="origin")
        raw = np.ascontiguousarray(
            arr.reshape(-1)[:count]).view(np.uint8).reshape(-1)
        dst = self._bytes_of(target_rank)
        off = disp * self._du
        dst[off:off + raw.size] = raw

    def Get(self, origin, target_rank: int, target=None) -> None:
        dst = _as_array(origin)
        disp, count = _target_spec(target, dst.size, need="receive")
        src = self._bytes_of(target_rank)
        off = disp * self._du
        nbytes = count * dst.itemsize
        _copy_into(origin, np.ascontiguousarray(
            src[off:off + nbytes]).view(dst.dtype))

    def _seg(self, target_rank: int, disp: int, count: int, dtype):
        raw = self._bytes_of(target_rank)
        off = disp * self._du
        return raw[off:off + count * dtype.itemsize].view(dtype)

    def Accumulate(self, origin, target_rank: int, target=None,
                   op: Op = SUM) -> None:
        arr = _as_array(origin)
        disp, count = _target_spec(target, arr.size, need="origin")
        src = arr.reshape(-1)[:count]
        seg = self._seg(target_rank, disp, count, arr.dtype)
        nat = _native_op(op)
        seg[:] = nat.host(seg.copy(), src)

    def Get_accumulate(self, origin, result, target_rank: int,
                       target=None, op: Op = SUM) -> None:
        arr = _as_array(origin)
        disp, count = _target_spec(target, arr.size, need="origin")
        src = arr.reshape(-1)[:count]
        seg = self._seg(target_rank, disp, count, arr.dtype)
        old = seg.copy()
        seg[:] = _native_op(op).host(old.copy(), src)
        _copy_into(result, old)

    def Fetch_and_op(self, origin, result, target_rank: int,
                     target_disp: int = 0, op: Op = SUM) -> None:
        arr = _as_array(origin)
        seg = self._seg(target_rank, int(target_disp), 1, arr.dtype)
        old = seg.copy()
        seg[:] = _native_op(op).host(old.copy(), arr.reshape(-1)[:1])
        _copy_into(result, old)

    def Compare_and_swap(self, origin, compare, result,
                         target_rank: int, target_disp: int = 0) -> None:
        arr = _as_array(origin)
        cmp_ = _as_array(compare).reshape(-1)[0]
        seg = self._seg(target_rank, int(target_disp), 1, arr.dtype)
        old = seg.copy()
        if old[0] == cmp_:
            seg[0] = arr.reshape(-1)[0]
        _copy_into(result, old)

    def Rput(self, origin, target_rank: int, target=None) -> "Request":
        from ompi_tpu.mpi.request import CompletedRequest

        self.Put(origin, target_rank, target)
        return Request(CompletedRequest())

    def Rget(self, origin, target_rank: int, target=None) -> "Request":
        from ompi_tpu.mpi.request import CompletedRequest

        self.Get(origin, target_rank, target)
        return Request(CompletedRequest())

    def Raccumulate(self, origin, target_rank: int, target=None,
                    op: Op = SUM) -> "Request":
        from ompi_tpu.mpi.request import CompletedRequest

        self.Accumulate(origin, target_rank, target, op)
        return Request(CompletedRequest())

    # -- synchronization: coherence points, no lock service ---------------
    def Fence(self, assertion: int = 0) -> None:
        self._w.sync()              # memory barrier + comm barrier

    def Sync(self) -> None:
        self._w.sync()

    def Lock(self, rank: int, lock_type: int = LOCK_EXCLUSIVE,
             assertion: int = 0) -> None:
        pass                        # coherence only; see class docstring

    def Unlock(self, rank: int) -> None:
        pass

    def Lock_all(self, assertion: int = 0) -> None:
        pass

    def Unlock_all(self) -> None:
        pass

    def Flush(self, rank: int) -> None:
        pass

    def Flush_all(self) -> None:
        pass

    def Flush_local(self, rank: int) -> None:
        pass

    def Flush_local_all(self) -> None:
        pass

    def _no_pscw(self, what: str):
        raise Exception(
            f"{what} is not defined on a Win.Allocate_shared window "
            f"(osc/sm has no PSCW epochs) — use Fence()/Sync()")

    def Start(self, group, assertion: int = 0) -> None:
        self._no_pscw("Start")

    def Complete(self) -> None:
        self._no_pscw("Complete")

    def Post(self, group, assertion: int = 0) -> None:
        self._no_pscw("Post")

    def Wait(self) -> None:
        self._no_pscw("Wait")

    def Test(self) -> bool:
        self._no_pscw("Test")

    def Get_group(self) -> "Group":
        g = self._w.comm.group
        return Group(g, g.world_rank(self._w.comm.rank))

    def Get_attr(self, keyval):
        if keyval is WIN_SIZE:
            return self._w.shared_query(self._w.comm.rank).nbytes
        if keyval is WIN_DISP_UNIT:
            return self._du
        if keyval is WIN_BASE:
            from ompi_tpu.mpi.datatype import get_address

            return get_address(self._w.shared_query(self._w.comm.rank))
        return None

    @property
    def memory(self):
        return self._w.shared_query(self._w.comm.rank)

    def fetch_add(self, rank: int, offset8: int, delta: int) -> int:
        """The osc/sm lock-free counter (native u64 atomics)."""
        return self._w.fetch_add(rank, offset8, delta)


class File:
    """mpi4py-style handle over the native MPI-IO file (fcoll/sharedfp
    engines included)."""

    def __init__(self, native) -> None:
        self._f = native

    @classmethod
    def Open(cls, comm: "Comm", filename: str,
             amode: int = MODE_RDONLY, info=None) -> "File":
        return cls(_io_mod.File.open(comm._c, filename, amode,
                                     info=info))

    # -- views / pointers --------------------------------------------------
    def Set_view(self, disp: int = 0, etype: Datatype = BYTE,
                 filetype=None, datarep: str = "native",
                 info=None) -> None:
        from ompi_tpu.mpi.datatype import from_numpy as _from_np

        native_et = (_from_np(etype.np_dtype)
                     if isinstance(etype, Datatype) else etype)
        if isinstance(filetype, _Derived):
            # a Create_vector/indexed/… facade type: its wrapped native
            # derived datatype IS the view
            filetype = filetype._nat
        elif isinstance(filetype, Datatype):
            # a scalar compat Datatype as the filetype = contiguous
            # elements of that type (native derived types pass through
            # for strided/vector views)
            filetype = _from_np(filetype.np_dtype)
        self._f.set_view(disp=disp, etype=native_et,
                         filetype=filetype, datarep=datarep)

    def Seek(self, offset: int, whence: int = SEEK_SET) -> None:
        self._f.seek(offset, whence)

    def Get_position(self) -> int:
        return self._f.get_position()

    # mpi4py semantics: the BUFFER's numpy dtype is the memory datatype;
    # the view's etype only sets file offsets/units.  The native layer
    # instead value-casts data to the etype, so the facade reinterprets
    # bitwise both ways (a float64 buffer through the default BYTE view
    # moves its raw bytes, not uint8-casted values).

    def _etype_np(self):
        return self._f.view.etype.base_np

    def _to_file(self, buf) -> np.ndarray:
        a = np.ascontiguousarray(_as_array(buf)).reshape(-1)
        et = self._etype_np()
        if a.dtype == et:
            return a
        if a.nbytes % et.itemsize:
            raise Exception(
                f"buffer of {a.nbytes} bytes is not a whole number of "
                f"file etype elements ({et})")
        return a.view(et)

    def _count(self, buf) -> int:
        dst = _as_array(buf)
        et = self._etype_np()
        if dst.nbytes % et.itemsize:
            raise Exception(
                f"receive buffer of {dst.nbytes} bytes is not a whole "
                f"number of file etype elements ({et})")
        return dst.nbytes // et.itemsize

    def _land(self, buf, out) -> None:
        dst = _as_array(buf)
        raw = np.ascontiguousarray(np.asarray(out)).reshape(-1)
        if raw.dtype != dst.dtype:
            if raw.nbytes % dst.dtype.itemsize:
                raise Exception(
                    f"read of {raw.nbytes} bytes does not fill whole "
                    f"{dst.dtype} elements")
            raw = raw.view(dst.dtype)
        _copy_into(buf, raw)

    # -- explicit-offset / individual / shared / ordered -------------------
    def Read_at(self, offset: int, buf) -> None:
        self._land(buf, self._f.read_at(offset, self._count(buf)))

    def Write_at(self, offset: int, buf) -> None:
        self._f.write_at(offset, self._to_file(buf))

    def Read_at_all(self, offset: int, buf) -> None:
        self._land(buf, self._f.read_at_all(offset, self._count(buf)))

    def Write_at_all(self, offset: int, buf) -> None:
        self._f.write_at_all(offset, self._to_file(buf))

    def Read(self, buf) -> None:
        self._land(buf, self._f.read(self._count(buf)))

    def Write(self, buf) -> None:
        self._f.write(self._to_file(buf))

    def Read_all(self, buf) -> None:
        self._land(buf, self._f.read_all(self._count(buf)))

    def Write_all(self, buf) -> None:
        self._f.write_all(self._to_file(buf))

    def Read_shared(self, buf) -> None:
        self._land(buf, self._f.read_shared(self._count(buf)))

    def Write_shared(self, buf) -> None:
        self._f.write_shared(self._to_file(buf))

    def Read_ordered(self, buf) -> None:
        self._land(buf, self._f.read_ordered(self._count(buf)))

    def Write_ordered(self, buf) -> None:
        self._f.write_ordered(self._to_file(buf))

    # -- nonblocking IO (requests land into the caller's buffer on
    #    Wait/Test, the mpi4py convention) ---------------------------------
    def _iread(self, native_req, buf) -> Request:
        return Request(native_req,
                       transform=lambda out: self._land(buf, out))

    def Iread_at(self, offset: int, buf) -> Request:
        return self._iread(self._f.iread_at(offset, self._count(buf)), buf)

    def Iwrite_at(self, offset: int, buf) -> Request:
        return Request(self._f.iwrite_at(offset, self._to_file(buf)))

    def Iread(self, buf) -> Request:
        return self._iread(self._f.iread(self._count(buf)), buf)

    def Iwrite(self, buf) -> Request:
        return Request(self._f.iwrite(self._to_file(buf)))

    def Iread_all(self, buf) -> Request:
        return self._iread(self._f.iread_all(self._count(buf)), buf)

    def Iwrite_all(self, buf) -> Request:
        return Request(self._f.iwrite_all(self._to_file(buf)))

    def Iread_at_all(self, offset: int, buf) -> Request:
        return self._iread(
            self._f.iread_at_all(offset, self._count(buf)), buf)

    def Iwrite_at_all(self, offset: int, buf) -> Request:
        return Request(self._f.iwrite_at_all(offset, self._to_file(buf)))

    def Iread_shared(self, buf) -> Request:
        return self._iread(self._f.iread_shared(self._count(buf)), buf)

    def Iwrite_shared(self, buf) -> Request:
        return Request(self._f.iwrite_shared(self._to_file(buf)))

    # -- split collectives (one outstanding per handle, ends must match) --
    def Read_all_begin(self, buf) -> None:
        self._f.read_all_begin(self._count(buf))

    def Read_all_end(self, buf) -> None:
        self._land(buf, self._f.read_all_end())

    def Write_all_begin(self, buf) -> None:
        self._f.write_all_begin(self._to_file(buf))

    def Write_all_end(self, buf) -> None:
        self._f.write_all_end()

    def Read_at_all_begin(self, offset: int, buf) -> None:
        self._f.read_at_all_begin(offset, self._count(buf))

    def Read_at_all_end(self, buf) -> None:
        self._land(buf, self._f.read_at_all_end())

    def Write_at_all_begin(self, offset: int, buf) -> None:
        self._f.write_at_all_begin(offset, self._to_file(buf))

    def Write_at_all_end(self, buf) -> None:
        self._f.write_at_all_end()

    def Read_ordered_begin(self, buf) -> None:
        self._f.read_ordered_begin(self._count(buf))

    def Read_ordered_end(self, buf) -> None:
        self._land(buf, self._f.read_ordered_end())

    def Write_ordered_begin(self, buf) -> None:
        self._f.write_ordered_begin(self._to_file(buf))

    def Write_ordered_end(self, buf) -> None:
        self._f.write_ordered_end()

    # -- management --------------------------------------------------------
    def Get_view(self) -> tuple:
        disp, etype, ftype = self._f.get_view()

        def wrap(nat):
            if getattr(nat, "base_np", None) is not None \
                    and nat.is_contiguous and nat.size == nat.base_np.itemsize:
                return Datatype(nat.base_np, nat.get_name())
            base = Datatype(nat.base_np, str(nat.base_np))
            return _Derived(nat, base)

        return disp, wrap(etype), wrap(ftype)

    def Get_byte_offset(self, offset: int) -> int:
        return self._f.get_byte_offset(offset)

    def Get_type_extent(self, datatype) -> int:
        return self._f.get_type_extent(_to_native_dt(datatype))

    def Set_size(self, size: int) -> None:
        self._f.set_size(size)

    def Get_amode(self) -> int:
        return self._f.get_amode()

    def Set_info(self, info) -> None:
        self._f.set_info(info)

    def Get_info(self) -> "Info":
        return _wrap_info(self._f.get_info())

    def Seek_shared(self, offset: int, whence: int = SEEK_SET) -> None:
        self._f.seek_shared(offset, whence)

    def Get_position_shared(self) -> int:
        return self._f.get_position_shared()

    def Sync(self) -> None:
        self._f.sync()

    def Preallocate(self, size: int) -> None:
        self._f.preallocate(size)

    def Get_size(self) -> int:
        return self._f.get_size()

    def Set_atomicity(self, flag: bool) -> None:
        self._f.set_atomicity(bool(flag))

    def Get_atomicity(self) -> bool:
        return self._f.get_atomicity()

    def Close(self) -> None:
        self._f.close()

    @staticmethod
    def Delete(filename: str, info=None) -> None:
        _io_mod.File.delete(filename)


# ---------------------------------------------------------------------------
# world / environment
# ---------------------------------------------------------------------------

class _LazyComm(Comm):
    """COMM_WORLD/COMM_SELF resolved (and the runtime initialized) on
    first use — mpi4py initializes at import; deferring to first touch
    keeps ``import ompi_tpu.compat`` side-effect-free."""

    def __init__(self, which: str):
        self._which = which

    @property
    def _c(self):
        import ompi_tpu

        if not ompi_tpu.initialized():
            from ompi_tpu.mpi import runtime as _rt

            _rt.init()
        return getattr(ompi_tpu, self._which)


COMM_WORLD = _LazyComm("COMM_WORLD")
COMM_SELF = _LazyComm("COMM_SELF")
COMM_NULL = None


def Init() -> None:
    import ompi_tpu

    if not ompi_tpu.initialized():
        from ompi_tpu.mpi import runtime as _rt

        _rt.init()


def Init_thread(required: int = THREAD_MULTIPLE) -> int:
    Init()
    return THREAD_MULTIPLE


def Finalize() -> None:
    import ompi_tpu

    if ompi_tpu.initialized():
        from ompi_tpu.mpi import runtime as _rt

        _rt.finalize()


def Is_initialized() -> bool:
    import ompi_tpu

    return ompi_tpu.initialized()


def Is_finalized() -> bool:
    from ompi_tpu.mpi import runtime as _rt

    return _rt.finalized()


def Query_thread() -> int:
    return THREAD_MULTIPLE


def Get_processor_name() -> str:
    import ompi_tpu

    return ompi_tpu.get_processor_name()


def Wtime() -> float:
    import ompi_tpu

    return ompi_tpu.wtime()


def Wtick() -> float:
    import ompi_tpu

    return ompi_tpu.wtick()


def Get_version() -> tuple:
    import ompi_tpu

    return ompi_tpu.get_version()


def Get_library_version() -> str:
    import ompi_tpu

    return ompi_tpu.get_library_version()


def pickle_dumps(obj) -> bytes:  # legacy helpers; MPI.pickle is the hook
    return _serializer().dumps(obj)


def pickle_loads(data: bytes) -> Any:
    return _serializer().loads(data)


# mpi4py spells the serializer instance MPI.pickle (the stdlib module is
# aliased away above) — assigning .dumps/.loads or a new Pickle swaps
# serialization for the whole lowercase API
globals()["pickle"] = pickle_impl
