"""Compatibility facades for users switching from the reference stack.

``from ompi_tpu.compat import MPI`` is a drop-in for mpi4py's
``from mpi4py import MPI`` — the de-facto Python binding of the reference
(Open MPI) — covering the Comm/Request/Status/Op/Group/Message surface an
mpi4py script actually touches.  See :mod:`ompi_tpu.compat.MPI`.
"""

from ompi_tpu.compat import MPI

__all__ = ["MPI"]
