"""ompi-tpu-info — dump frameworks, components, and config variables.

≈ ompi/tools/ompi_info: the introspection tool that lists every registered
framework, its components (with priorities), and every config variable with
its current value and source.
"""

from __future__ import annotations

import argparse
import importlib
import sys

from ompi_tpu.core.config import InfoLevel, var_registry
from ompi_tpu.core.mca import framework_registry

# Modules whose import registers frameworks/components/vars. Import errors are
# tolerated (e.g. jax-dependent modules on a host without accelerators).
_REGISTERING_MODULES = [
    "ompi_tpu.runtime.ras",
    "ompi_tpu.runtime.rmaps",
    "ompi_tpu.runtime.errmgr",
    "ompi_tpu.runtime.launcher",
    "ompi_tpu.runtime.notifier",
    "ompi_tpu.runtime.rtc",
    "ompi_tpu.runtime.plm",
    "ompi_tpu.runtime.metrics",       # metrics_agg_* fan-in valve vars
    "ompi_tpu.runtime.doctor",        # doctor_* capture-budget vars
    "ompi_tpu.mpi.coll",
    "ompi_tpu.mpi.coll.host",
    "ompi_tpu.mpi.coll.selfcoll",
    "ompi_tpu.mpi.coll.shm",
    "ompi_tpu.mpi.coll.xla",
    "ompi_tpu.mpi.pml",
    "ompi_tpu.mpi.op",
    "ompi_tpu.mpi.io",
    "ompi_tpu.mpi.btl_shm",
    "ompi_tpu.core.memchecker",
    "ompi_tpu.parallel.multihost",
    "ompi_tpu.shmem.api",
    "ompi_tpu.ops.flash_attention",   # ops_flash_* kernel tuning vars
]


def load_all() -> list[str]:
    failures = []
    for mod in _REGISTERING_MODULES:
        try:
            importlib.import_module(mod)
        except Exception as e:
            failures.append(f"{mod}: {type(e).__name__}: {e}")
    return failures


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="ompi-tpu-info")
    p.add_argument("--level", type=int, default=9,
                   help="max info level to show (1=user basic .. 9=dev all)")
    p.add_argument("--param", default=None,
                   help="show only variables whose name contains this string")
    args = p.parse_args(argv)

    failures = load_all()
    import ompi_tpu

    print(f"ompi_tpu version: {ompi_tpu.__version__}")
    print()
    print("Frameworks and components:")
    for name, fw in sorted(framework_registry.all().items()):
        comps = ", ".join(
            f"{c.NAME}(pri={c.PRIORITY})"
            for c in sorted(fw.components().values(), key=lambda c: -c.PRIORITY))
        print(f"  {name:<12} {fw.description or ''}")
        print(f"  {'':<12}   components: {comps or '(none)'}")
    print()
    print("Configuration variables (name = value [type, source]):")
    for var in var_registry.all_vars():
        if var.info_level > args.level:
            continue
        if args.param and args.param not in var.full_name:
            continue
        print(f"  {var.full_name} = {var.value!r} "
              f"[{var.vtype.value}, {var.source.name.lower()}]"
              + (f"  # {var.description}" if var.description else ""))
    from ompi_tpu.mpi.mpit import pvar_registry

    names = pvar_registry.names()
    if names:
        print()
        print("Performance variables (MPI_T pvars):")
        for n in names:
            pv = pvar_registry.lookup(n)
            print(f"  {n} [{pv.klass.value}"
                  + (f", {pv.unit}" if pv.unit else "") + "]"
                  + (f"  # {pv.description}" if pv.description else ""))
    if failures:
        print("\nmodules not loaded:", file=sys.stderr)
        for f in failures:
            print(f"  {f}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
