"""tpurun — the mpirun equivalent.

≈ orte/tools/orterun (orterun.c:131-236): parse the command line, apply
--mca directives, build the job, drive the launch state machine, forward
output, propagate the first failure's exit code.

    tpurun -np 4 python ring.py
    tpurun -np 8 --mca coll host --tpu python app.py
    tpurun -np 4 --hostfile hf --map-by bynode ./a.out args...
    tpurun -np 4 --plm sim --hosts 2 python ring.py   # multi-host (simulated)
    tpurun -np 8 --plm ssh --hostfile hf python app.py
"""

from __future__ import annotations

import argparse
import sys

from ompi_tpu.core.config import var_registry


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpurun",
        description="Launch an ompi_tpu job (mpirun equivalent).")
    p.add_argument("-np", "-n", type=int, default=1, dest="np",
                   help="number of ranks to launch")
    p.add_argument("--mca", nargs=2, action="append", default=[],
                   metavar=("PARAM", "VALUE"),
                   help="set a config variable (repeatable)")
    p.add_argument("--tpu", action="store_true",
                   help="map ranks 1:1 onto local TPU chips")
    p.add_argument("--hostfile", default=None, help="hostfile path")
    p.add_argument("--map-by", default=None, choices=["byslot", "bynode"],
                   help="round-robin mapping policy")
    p.add_argument("--plm", default=None, choices=["sim", "ssh"],
                   help="multi-host launch via a daemon tree: 'sim' runs "
                        "one daemon per simulated host on this machine, "
                        "'ssh' spawns daemons over ssh (≈ plm/rsh)")
    p.add_argument("--hosts", type=int, default=2,
                   help="number of simulated hosts for --plm sim")
    p.add_argument("--trace", action="store_true",
                   help="arm the per-rank flight recorder "
                        "(OMPI_TPU_TRACE=1 in every rank); each rank "
                        "flushes a Chrome-trace JSON to "
                        "$TMPDIR/ompi_tpu_trace_<jobid>_rank<r>.json at "
                        "finalize/abort — merge with tools/trace_export.py")
    p.add_argument("--timeout", type=float, default=None, metavar="SECS",
                   help="kill the job and exit nonzero after SECS "
                        "seconds (mpirun --timeout; CI hang guard)")
    p.add_argument("--stdin", default=None, metavar="RANK|all|none",
                   help="forward launcher stdin to this rank (default 0)")
    # persistent DVM (≈ orte-dvm / orte-submit / orte-ps)
    p.add_argument("--dvm-start", action="store_true",
                   help="bring up a persistent daemon VM and serve job "
                        "submissions (≈ orte-dvm)")
    p.add_argument("--dvm-submit", action="store_true",
                   help="run the command on a standing DVM (fast: skips "
                        "VM bring-up; ≈ orte-submit)")
    p.add_argument("--dvm-ps", action="store_true",
                   help="print a standing DVM's daemon/queue/job/proc "
                        "table (≈ orte-ps)")
    p.add_argument("--dvm-shrink", default=None, metavar="JOBID:RANK",
                   help="planned elastic shrink: retire one rank of a "
                        "running DVM job (no revive; the survivors "
                        "continue smaller per the ULFM recipe)")
    p.add_argument("--dvm-stop", action="store_true",
                   help="shut a standing DVM down")
    p.add_argument("--dvm-uri", default=None, metavar="FILE|HOST:PORT",
                   help="DVM control URI or the file holding it "
                        "(default: the per-user uri file in TMPDIR)")
    p.add_argument("--slots", type=int, default=None,
                   help="total rank slots the DVM allocates at start "
                        "(--dvm-start; default: np or hosts*ceil)")
    p.add_argument("--metrics-port", type=int, default=None,
                   metavar="PORT",
                   help="with --dvm-start: serve a long-lived HTTP "
                        "observability endpoint on 127.0.0.1:PORT — "
                        "/metrics (Prometheus text, per-job labels) and "
                        "/status (proc table + FT event timeline).  "
                        "Arms the per-rank metrics uplink "
                        "(trace_metrics_push_period, default 1.0 s when "
                        "this flag is given).  PORT 0 binds an "
                        "ephemeral port, recorded in <uri>.metrics")
    p.add_argument("--clean", action="store_true",
                   help="remove stale job debris (shm inboxes/segments "
                        "of dead ranks, dead DVM uri) — ≈ orte-clean; "
                        "liveness-checked unless --clean-age is given")
    p.add_argument("--clean-age", type=float, default=0.0, metavar="SECS",
                   help="with --clean: also remove ANY artifact older "
                        "than SECS (use when none of your jobs run)")
    p.add_argument("--clean-dry-run", action="store_true",
                   help="with --clean: report, remove nothing")
    p.add_argument("--tag-output", dest="tag", action="store_true",
                   default=None, help="tag output lines with [jobid,rank]")
    p.add_argument("--no-tag-output", dest="tag", action="store_false")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="program and arguments to launch")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.clean:
        from ompi_tpu.runtime import clean as clean_mod

        try:
            removed = clean_mod.clean(
                age=args.clean_age, dry_run=args.clean_dry_run,
                report=lambda s: print(f"tpurun: {s}", file=sys.stderr))
        except OSError as e:
            print(f"tpurun: {e}", file=sys.stderr)
            return 1
        verb = "would remove" if args.clean_dry_run else "removed"
        print(f"tpurun: {verb} {len(removed)} stale artifact(s)",
              file=sys.stderr)
        return 0
    if args.dvm_ps:
        import json as _json

        from ompi_tpu.runtime import dvm

        try:
            print(_json.dumps(dvm.ps(args.dvm_uri), indent=1))
        except RuntimeError as e:
            print(f"tpurun: {e}", file=sys.stderr)
            return 1
        return 0
    if args.dvm_shrink:
        import json as _json

        from ompi_tpu.runtime import dvm

        try:
            jobid, _, rank = args.dvm_shrink.partition(":")
            reply = dvm.shrink(int(jobid), int(rank), uri=args.dvm_uri)
        except ValueError:
            print(f"tpurun: --dvm-shrink wants JOBID:RANK "
                  f"(got {args.dvm_shrink!r})", file=sys.stderr)
            return 2
        except RuntimeError as e:
            print(f"tpurun: {e}", file=sys.stderr)
            return 1
        print(_json.dumps(reply))
        return 0
    if args.dvm_stop:
        from ompi_tpu.runtime import dvm

        try:
            dvm.stop(args.dvm_uri)
        except RuntimeError as e:
            print(f"tpurun: {e}", file=sys.stderr)
            return 1
        print("dvm: stopped", file=sys.stderr)
        return 0
    if not args.command and not args.dvm_start:
        print("tpurun: no command given (try: tpurun -np 4 python app.py)",
              file=sys.stderr)
        return 2
    cmd = args.command
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]

    if args.timeout is not None:
        if args.timeout <= 0:
            print("tpurun: --timeout must be > 0 seconds "
                  f"(got {args.timeout:g})", file=sys.stderr)
            return 2
        import os as _os
        import signal as _signal
        import threading as _threading
        import time as _time

        # become our own process-group leader so the expiry kill hits
        # exactly the launcher + its ranks, not the invoking shell/CI
        # harness (trade-off: terminal ^C no longer fans out to the job
        # group — acceptable for the CI hang-guard this flag exists for)
        try:
            _os.setpgrp()
        except OSError:
            pass

        # The expiry killpg below hits our own process too; without a
        # handler the launcher dies of that SIGTERM (status 143) before
        # reaching _exit(124).  The handler shields exactly the expiry
        # window — an external SIGTERM before expiry still terminates.
        _expiring = _threading.Event()

        def _on_term(signum, frame) -> None:
            if _expiring.is_set():
                return              # our own group-kill; _exit(124) follows
            _signal.signal(_signal.SIGTERM, _signal.SIG_DFL)
            _os.kill(_os.getpid(), _signal.SIGTERM)

        _signal.signal(_signal.SIGTERM, _on_term)   # main thread only

        def _expire() -> None:
            _time.sleep(args.timeout)
            _expiring.set()
            print(f"tpurun: job timed out after {args.timeout:g}s — "
                  f"aborting (mpirun --timeout semantics)",
                  file=sys.stderr, flush=True)
            try:
                # our process group holds the launcher and local ranks;
                # daemon-tree members notice the HNP's death via their
                # lifelines and tear down
                _os.killpg(_os.getpgid(0), _signal.SIGTERM)
            except OSError:
                pass
            _time.sleep(2.0)
            _os._exit(124)

        _threading.Thread(target=_expire, daemon=True,
                          name="tpurun-timeout").start()

    # CLI --mca pairs get top precedence; framework-selection vars use the
    # bare framework name (e.g. --mca coll xla → synonym of coll_).  They are
    # also exported to the environment so app processes inherit them — most
    # frameworks (pml/coll/...) select inside the app, not the launcher.
    import os

    if args.trace:
        # local fork/exec and --dvm-submit inherit the launcher's
        # os.environ; the ssh daemon tree does NOT (env doesn't travel
        # over ssh), so the flag ALSO rides the job's app env below
        os.environ["OMPI_TPU_TRACE"] = "1"
    trace_env = {"OMPI_TPU_TRACE": "1"} if args.trace else {}
    var_registry.load_cli([(k, v) for k, v in args.mca])
    for k, v in args.mca:
        os.environ[var_registry.ENV_PREFIX + k] = v
    if args.map_by:
        var_registry.load_cli([("rmaps_rr_policy", args.map_by)])
    if args.tag is not None:
        var_registry.load_cli([("launcher_tag_output", "1" if args.tag else "0")])
    if args.hostfile:
        var_registry.load_cli([("ras_hostfile", args.hostfile)])

    def _configure_sim_ras(total_slots: int) -> None:
        """Shared sim-RAS setup for --plm sim and --dvm-start."""
        import math

        var_registry.load_cli([
            ("ras", "simulator"),
            ("ras_sim_num_nodes", str(args.hosts)),
            ("ras_sim_slots_per_node",
             str(math.ceil(total_slots / max(1, args.hosts)))),
        ])

    if args.dvm_submit:
        from ompi_tpu.runtime import dvm
        from ompi_tpu.runtime import pmix as _pmix

        # ship the CLIENT's environment as the job env (orte-submit /
        # mpirun semantics: app processes see the submitter's variables,
        # overlaid on the daemon's own env) — minus the per-rank/per-job
        # identity vars the launcher owns and the HOST-LOCAL vars whose
        # client values would break ranks on remote (ssh) daemons.  The
        # --mca pairs were exported into os.environ above, so they ride
        # along.
        _skip = {_pmix.ENV_URI, _pmix.ENV_RANK, _pmix.ENV_SIZE,
                 _pmix.ENV_JOBID, _pmix.ENV_LOCAL_RANK, _pmix.ENV_CHIP,
                 "OMPI_TPU_RESTART", "OMPI_TPU_FAKE_HOST",
                 "PATH", "HOME", "TMPDIR", "TMP", "TEMP", "PWD",
                 "OLDPWD", "SHLVL", "HOSTNAME", "LD_LIBRARY_PATH",
                 "LD_PRELOAD", "VIRTUAL_ENV", "PYTHONHOME"}
        job_env = {k: v for k, v in os.environ.items() if k not in _skip}
        if args.tag is not None:
            job_env[var_registry.ENV_PREFIX + "launcher_tag_output"] = \
                "1" if args.tag else "0"
        try:
            return dvm.submit(cmd, np_=args.np, uri=args.dvm_uri,
                              env=job_env)
        except dvm.DvmRejected as e:
            # machine-readable admission verdict on stdout + EX_TEMPFAIL
            # (75): schedulers and scripts can parse-and-retry instead of
            # hanging against a full pool
            import json as _json

            print(_json.dumps(e.verdict))
            print(f"tpurun: dvm rejected the job: {e}", file=sys.stderr)
            return 75
        except RuntimeError as e:
            print(f"tpurun: {e}", file=sys.stderr)
            return 1

    if args.dvm_start:
        from ompi_tpu.runtime import dvm

        slots = args.slots or max(args.np, args.hosts)
        plm_name = args.plm or "sim"
        if plm_name == "sim" and not args.hostfile:
            _configure_sim_ras(slots)
        if args.metrics_port is not None:
            # the scrape endpoint is only useful with the uplink armed:
            # default the push period on (daemons inherit it via their
            # spawn env, ranks via the launch env overlay) unless the
            # user pinned it with --mca / the environment
            os.environ.setdefault(
                var_registry.ENV_PREFIX + "trace_metrics_push_period",
                "1.0")
        hnp = dvm.DvmHnp(plm_name=plm_name, want_tpu=args.tpu,
                         uri_path=args.dvm_uri,
                         metrics_port=args.metrics_port,
                         remote_hosts=plm_name == "ssh")
        hnp.start(np_slots=slots)
        print(f"dvm: up ({args.hosts} hosts, {slots} slots); "
              f"uri file {hnp.uri_path}", file=sys.stderr)
        if hnp.metrics_uri:
            print(f"dvm: metrics at {hnp.metrics_uri}/metrics and "
                  f"{hnp.metrics_uri}/status", file=sys.stderr)
        try:
            return hnp.serve_forever()
        except KeyboardInterrupt:
            hnp.shutdown()
            return 0

    if args.plm:
        # multi-host path: one orted per host, routed tree, IOF up the tree
        if args.plm == "sim" and not args.hostfile:
            _configure_sim_ras(args.np)
        from ompi_tpu.runtime.job import AppContext, Job
        from ompi_tpu.runtime.plm import MultiHostLauncher

        job = Job([AppContext(argv=cmd, np=args.np, env=trace_env)])
        return MultiHostLauncher(
            plm_name=args.plm, want_tpu=args.tpu,
            stdin_target=args.stdin if args.stdin is not None else "0",
            remote_hosts=args.plm == "ssh",
        ).run(job)

    from ompi_tpu.runtime.launcher import launch

    return launch(cmd, np=args.np, want_tpu=args.tpu, env=trace_env,
                  stdin_target=args.stdin)


if __name__ == "__main__":
    sys.exit(main())
