"""tpurun — the mpirun equivalent.

≈ orte/tools/orterun (orterun.c:131-236): parse the command line, apply
--mca directives, build the job, drive the launch state machine, forward
output, propagate the first failure's exit code.

    tpurun -np 4 python ring.py
    tpurun -np 8 --mca coll host --tpu python app.py
    tpurun -np 4 --hostfile hf --map-by bynode ./a.out args...
    tpurun -np 4 --plm sim --hosts 2 python ring.py   # multi-host (simulated)
    tpurun -np 8 --plm ssh --hostfile hf python app.py
"""

from __future__ import annotations

import argparse
import sys

from ompi_tpu.core.config import var_registry


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpurun",
        description="Launch an ompi_tpu job (mpirun equivalent).")
    p.add_argument("-np", "-n", type=int, default=1, dest="np",
                   help="number of ranks to launch")
    p.add_argument("--mca", nargs=2, action="append", default=[],
                   metavar=("PARAM", "VALUE"),
                   help="set a config variable (repeatable)")
    p.add_argument("--tpu", action="store_true",
                   help="map ranks 1:1 onto local TPU chips")
    p.add_argument("--hostfile", default=None, help="hostfile path")
    p.add_argument("--map-by", default=None, choices=["byslot", "bynode"],
                   help="round-robin mapping policy")
    p.add_argument("--plm", default=None, choices=["sim", "ssh"],
                   help="multi-host launch via a daemon tree: 'sim' runs "
                        "one daemon per simulated host on this machine, "
                        "'ssh' spawns daemons over ssh (≈ plm/rsh)")
    p.add_argument("--hosts", type=int, default=2,
                   help="number of simulated hosts for --plm sim")
    p.add_argument("--stdin", default=None, metavar="RANK|all|none",
                   help="forward launcher stdin to this rank (default 0)")
    p.add_argument("--tag-output", dest="tag", action="store_true",
                   default=None, help="tag output lines with [jobid,rank]")
    p.add_argument("--no-tag-output", dest="tag", action="store_false")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="program and arguments to launch")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if not args.command:
        print("tpurun: no command given (try: tpurun -np 4 python app.py)",
              file=sys.stderr)
        return 2
    cmd = args.command
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]

    # CLI --mca pairs get top precedence; framework-selection vars use the
    # bare framework name (e.g. --mca coll xla → synonym of coll_).  They are
    # also exported to the environment so app processes inherit them — most
    # frameworks (pml/coll/...) select inside the app, not the launcher.
    import os

    var_registry.load_cli([(k, v) for k, v in args.mca])
    for k, v in args.mca:
        os.environ[var_registry.ENV_PREFIX + k] = v
    if args.map_by:
        var_registry.load_cli([("rmaps_rr_policy", args.map_by)])
    if args.tag is not None:
        var_registry.load_cli([("launcher_tag_output", "1" if args.tag else "0")])
    if args.hostfile:
        var_registry.load_cli([("ras_hostfile", args.hostfile)])

    if args.plm:
        # multi-host path: one orted per host, routed tree, IOF up the tree
        if args.plm == "sim" and not args.hostfile:
            import math

            var_registry.load_cli([
                ("ras", "simulator"),
                ("ras_sim_num_nodes", str(args.hosts)),
                ("ras_sim_slots_per_node",
                 str(math.ceil(args.np / max(1, args.hosts)))),
            ])
        from ompi_tpu.runtime.job import AppContext, Job
        from ompi_tpu.runtime.plm import MultiHostLauncher

        job = Job([AppContext(argv=cmd, np=args.np)])
        return MultiHostLauncher(
            plm_name=args.plm, want_tpu=args.tpu,
            stdin_target=args.stdin if args.stdin is not None else "0",
            remote_hosts=args.plm == "ssh",
        ).run(job)

    from ompi_tpu.runtime.launcher import launch

    return launch(cmd, np=args.np, want_tpu=args.tpu,
                  stdin_target=args.stdin)


if __name__ == "__main__":
    sys.exit(main())
