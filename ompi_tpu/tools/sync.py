"""ompi-tpu-sync — cross-rank clock-offset measurement.

≈ ompi/tools/mpisync: measures each rank's wall-clock offset against rank 0
so cross-host traces (monitoring matrices, xprof timelines) can be aligned
to one timebase.  Same algorithm as the reference (and NTP): a ping-pong
per sample; the peer's clock read is bracketed by the origin's send/recv
timestamps, offset = t_peer − (t_send + t_recv)/2, and the sample with the
smallest round-trip wins (least queueing noise).

Run under the launcher::

    tpurun -np 4 -- python -m ompi_tpu.tools.sync

or call :func:`clock_offsets` from a program that already has a
communicator (the monitoring subsystem feeds the result into trace
alignment).
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

__all__ = ["clock_offsets", "main"]

_TAG_PING = 0x53C0
_TAG_PONG = 0x53C1


def clock_offsets(comm, samples: int = 32
                  ) -> Optional[dict[int, tuple[float, float]]]:
    """Measure every rank's clock offset against rank 0.

    Collective over ``comm``.  Returns ``{rank: (offset_s, min_rtt_s)}``
    on rank 0 (offset > 0 ⇒ that rank's clock is ahead), ``None``
    elsewhere.  Accuracy ≈ min_rtt/2 (the reference's bound as well —
    mpisync carries the same ±rtt/2 uncertainty).
    """
    if comm.rank == 0:
        out: dict[int, tuple[float, float]] = {0: (0.0, 0.0)}
        for peer in range(1, comm.size):
            best_rtt, best_off = float("inf"), 0.0
            for _ in range(samples):
                t0 = time.time()
                comm.send(np.array([t0], np.float64), dest=peer,
                          tag=_TAG_PING)
                tp = float(comm.recv(source=peer, tag=_TAG_PONG)[0])
                t1 = time.time()
                rtt = t1 - t0
                if rtt < best_rtt:
                    best_rtt = rtt
                    best_off = tp - (t0 + t1) / 2.0
            out[peer] = (best_off, best_rtt)
        return out
    for _ in range(samples):
        comm.recv(source=0, tag=_TAG_PING)
        comm.send(np.array([time.time()], np.float64), dest=0,
                  tag=_TAG_PONG)
    return None


def main(argv: Optional[list[str]] = None) -> int:
    import argparse

    import ompi_tpu

    p = argparse.ArgumentParser(
        prog="ompi-tpu-sync",
        description="measure per-rank clock offsets against rank 0 "
                    "(≈ mpisync)")
    p.add_argument("-n", "--samples", type=int, default=32,
                   help="ping-pong samples per peer (min-RTT filtered)")
    args = p.parse_args(argv)

    comm = ompi_tpu.init()
    result = clock_offsets(comm, samples=args.samples)
    if result is not None:
        print(f"# clock offsets vs rank 0 ({comm.size} ranks, "
              f"{args.samples} samples, min-RTT filter)")
        print(f"# {'rank':>4} {'offset_us':>12} {'min_rtt_us':>12}")
        for rank in sorted(result):
            off, rtt = result[rank]
            print(f"  {rank:>4} {off * 1e6:>12.1f} {rtt * 1e6:>12.1f}")
    ompi_tpu.finalize()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
