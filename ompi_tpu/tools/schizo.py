"""schizo — CLI personality adapters.

≈ orte/mca/schizo: the reference accepts several launcher dialects
(OMPI mpirun, Slurm srun, ...) by translating each personality's argument
conventions into its own canonical form.  Here the shipped personality is
``ompi``: classic ``mpirun`` invocations translate to ``tpurun``'s CLI so
an Open MPI user's muscle memory (and scripts) keep working::

    mpirun -np 4 -x FOO=bar --machinefile hf ./a.out
      → tpurun -np 4 --hostfile hf -- ./a.out     (FOO exported)

Install the console entry as ``mpirun``/``mpiexec`` or invoke
``python -m ompi_tpu.tools.schizo`` directly.
"""

from __future__ import annotations

import os
import sys
from typing import Optional

__all__ = ["translate_mpirun", "main"]

# mpirun flags that take a value and have no tpurun meaning: swallow them
_IGNORED_WITH_VALUE = {
    "--bind-to", "--map-by-socket", "--rank-by", "--report-bindings-to",
    "--prefix", "--wdir", "-wdir", "--path", "--tmpdir",
}
# valueless mpirun flags to swallow
_IGNORED_FLAGS = {
    "--bind-to-core", "--bind-to-socket", "--report-bindings",
    "--oversubscribe", "--nooversubscribe", "--display-map",
    "--display-allocation", "--verbose", "-v", "--quiet", "-q",
    "--enable-recovery",
}


def translate_mpirun(argv: list[str]) -> tuple[list[str], dict[str, str]]:
    """mpirun argv → (tpurun argv, extra env).

    Handles: -np/-n/-c N, --mca A B, --hostfile/--machinefile F,
    -x VAR[=VAL] (env export), --map-by slot|node|..., --tag-output,
    --stdin, and the ``--`` command separator.  Unknown launcher flags
    before the command raise ValueError (matching mpirun's own strictness)
    except for the known-ignorable binding/reporting flags above.
    """
    out: list[str] = []
    env: dict[str, str] = {}
    i = 0
    n = len(argv)

    def take_value(flag: str) -> str:
        nonlocal i
        i += 1
        if i >= n:
            raise ValueError(f"{flag} requires a value")
        return argv[i]

    while i < n:
        a = argv[i]
        if a == "--":
            out.append("--")
            out.extend(argv[i + 1:])
            return out, env
        if a in ("-np", "-n", "-c", "--np", "--n"):
            out += ["-np", take_value(a)]
        elif a == "--mca" or a == "-mca" or a == "--gmca" or a == "-gmca":
            i += 2
            if i >= n:
                raise ValueError(f"{a} requires PARAM VALUE")
            out += ["--mca", argv[i - 1], argv[i]]
        elif a in ("--hostfile", "-hostfile", "--machinefile",
                   "-machinefile", "--default-hostfile"):
            out += ["--hostfile", take_value(a)]
        elif a in ("-x", "--x"):
            spec = take_value(a)
            if "=" in spec:
                k, _, v = spec.partition("=")
            else:
                k, v = spec, os.environ.get(spec, "")
            env[k] = v
        elif a in ("--map-by", "-map-by"):
            v = take_value(a)
            base = v.split(":", 1)[0].lower()
            mapping = {"slot": "byslot", "core": "byslot",
                       "node": "bynode", "socket": "bynode"}
            if base in mapping:
                out += ["--map-by", mapping[base]]
            # unknown policies: mpirun-specific NUMA talk — ignore
        elif a in ("--tag-output", "-tag-output"):
            out.append("--tag-output")
        elif a in ("--stdin", "-stdin"):
            out += ["--stdin", take_value(a)]
        elif a in ("--timeout", "-timeout"):
            out += ["--timeout", take_value(a)]
        elif a in _IGNORED_WITH_VALUE:
            take_value(a)
        elif a in _IGNORED_FLAGS:
            pass
        elif a.startswith("-") and len(a) > 1:
            raise ValueError(
                f"mpirun personality: unsupported option {a!r} "
                f"(use tpurun directly for native options)")
        else:
            # first non-flag token starts the command
            out.append("--")
            out.extend(argv[i:])
            return out, env
        i += 1
    return out, env


def main(argv: Optional[list[str]] = None) -> int:
    """Entry point for the mpirun/mpiexec personality."""
    argv = sys.argv[1:] if argv is None else argv
    try:
        targv, env = translate_mpirun(argv)
    except ValueError as e:
        print(f"mpirun: {e}", file=sys.stderr)
        return 2
    os.environ.update(env)
    from ompi_tpu.tools.tpurun import main as tpurun_main

    return tpurun_main(targv)


if __name__ == "__main__":
    raise SystemExit(main())
