"""Measured device-collective crossovers → dynamic rules file.

≈ the process behind the reference's fixed decision tables: the numbers in
ompi/mca/coll/tuned/coll_tuned_decision_fixed.c:56-74 were *measured* (UTK
Grig cluster) and then baked in.  This tool reproduces that process on the
mesh actually present: it times every registered device algorithm for each
collective across a size sweep, derives the per-size winners, and emits a
rules file in the ``ompi_tpu.mpi.coll.rules`` format with provenance
(``#!`` lines) recording platform, device kind, and mesh size.

``coll/xla`` auto-loads the emitted file (``xla_measured_rules.conf`` next
to the component) when — and only when — its provenance platform matches
the running backend, so CPU-measured crossovers can never steer a TPU run.

Run: ``python -m ompi_tpu.tools.tune [--out PATH]`` (also invoked by the
bench driver on the real backend, so measured numbers land with the round's
artifacts).
"""

from __future__ import annotations

import os
import sys
import time
from typing import Optional, Sequence

import numpy as np

__all__ = ["tune_device_colls", "measure_one", "DEFAULT_OUT"]

# element counts (float32) per device shard: 4KiB … 64MiB
DEFAULT_SIZES = (1 << 10, 1 << 14, 1 << 18, 1 << 21, 1 << 23, 1 << 24)

DEFAULT_OUT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "mpi", "coll", "xla_measured_rules.conf")

# collective → algorithm → DeviceCommunicator method (kept in sync with
# XlaColl._IMPL; imported lazily to avoid a cycle at module import)
def _impl_table() -> dict:
    from ompi_tpu.mpi.coll.xla import XlaColl

    return XlaColl._IMPL


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def measure_one(comm, mesh, method: str, elems: int,
                iters: int = 10) -> float:
    """Seconds per call of one device collective at one size (per-device
    shard of ``elems`` float32), timed device-resident through the same
    jit(shard_map) path coll/xla dispatches."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = comm.size
    x = jax.device_put(np.ones((n * elems,), np.float32),
                       NamedSharding(mesh, P("world")))
    fn_raw = getattr(comm, method)
    spec_out = P() if method in ("allgather", "allgather_ring") else \
        P("world")

    def kernel(s):
        return fn_raw(s)

    fn = jax.jit(jax.shard_map(kernel, mesh=mesh, in_specs=P("world"),
                               out_specs=spec_out, check_vma=False))
    out = fn(x)
    jax.block_until_ready(out)           # compile + warm
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(x)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def tune_device_colls(devices=None, sizes: Sequence[int] = DEFAULT_SIZES,
                      out_path: Optional[str] = None,
                      iters: int = 10) -> tuple[str, dict]:
    """Measure all (collective, algorithm, size) cells on a world mesh over
    ``devices`` and derive crossover rules.

    Returns (rules_text, table).  ``table[coll][label][alg] = us``.  Rules
    are only emitted for n ≥ 2 — on one device every collective compiles
    to a copy/no-op and "crossovers" would be noise; the measurement table
    still records that honestly.
    """
    import jax

    from ompi_tpu.mpi.device_comm import device_world
    from ompi_tpu.parallel.mesh import make_mesh

    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    mesh = make_mesh(devices=devices)
    comm = device_world(mesh)
    platform = devices[0].platform
    kind = getattr(devices[0], "device_kind", platform)

    from ompi_tpu.mpi.coll.xla import XlaColl

    table: dict[str, dict[str, dict[str, float]]] = {}
    winners: dict[str, list[tuple[int, str]]] = {}
    for coll, impls in _impl_table().items():
        table[coll] = {}
        winners[coll] = []
        lossy = XlaColl.LOSSY.get(coll, frozenset())
        for elems in sizes:
            nbytes = elems * 4
            label = (f"{nbytes >> 10}KiB" if nbytes < (1 << 20)
                     else f"{nbytes >> 20}MiB")
            row: dict[str, float] = {}
            for alg, method in impls.items():
                it = max(3, iters // 2) if elems >= (1 << 23) else iters
                try:
                    dt = measure_one(comm, mesh, method, elems, it)
                except Exception as e:  # noqa: BLE001 — record, keep going
                    _log(f"tune[{coll}/{alg}@{label}]: "
                         f"{type(e).__name__}: {e}")
                    continue
                row[alg] = round(dt * 1e6, 1)
            table[coll][label] = row
            # lossy algorithms (e.g. qint8): measured for the table, but
            # a crossover rule must never silently change results
            exact = {a: t for a, t in row.items() if a not in lossy}
            if exact:
                best = min(exact, key=exact.get)
                winners[coll].append((nbytes, best))
                _log(f"tune[{coll}@{label}]: {row} → {best}")

    lines = [
        "# Measured device-collective crossovers — generated by "
        "ompi_tpu.tools.tune",
        "# (the measured-numbers discipline of "
        "coll_tuned_decision_fixed.c:56-74, reproduced on this mesh)",
        "# msg_bytes_min is PER-SHARD bytes (what one ICI link moves) — "
        "the same unit",
        "# coll/xla._run_decided normalizes both dispatch modes to",
        f"#! platform={platform}",
        f"#! device_kind={kind.replace(' ', '_')}",
        f"#! n_devices={n}",
    ]
    if n < 2:
        lines.append("# n=1: collectives compile to copies; crossover "
                     "rules withheld (decision layer keeps its defaults)")
    else:
        for coll, picks in winners.items():
            prev = None
            for nbytes, alg in picks:
                if alg != prev:
                    # first rule of each collective applies from 0 bytes
                    lines.append(f"{coll}  0  {0 if prev is None else nbytes}"
                                 f"  {alg}")
                    prev = alg
    text = "\n".join(lines) + "\n"
    if out_path:
        with open(out_path, "w", encoding="utf-8") as f:
            f.write(text)
        _log(f"measured rules written to {out_path}")
    return text, table


def main(argv: Optional[list[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="measure device-collective crossovers, emit rules")
    ap.add_argument("--out", default=DEFAULT_OUT,
                    help=f"rules file to write (default {DEFAULT_OUT})")
    ap.add_argument("--no-write", action="store_true",
                    help="print rules to stdout only")
    ap.add_argument("--cpu", type=int, metavar="N", default=0,
                    help="force an N-device virtual CPU mesh (env "
                    "JAX_PLATFORMS alone loses to the ambient accelerator "
                    "plugin registration; this forces via jax.config)")
    ap.add_argument("--sizes", default="",
                    help="comma-separated per-device f32 element counts")
    args = ap.parse_args(argv)
    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", args.cpu)
    sizes = (tuple(int(s) for s in args.sizes.split(","))
             if args.sizes else DEFAULT_SIZES)
    text, _ = tune_device_colls(
        sizes=sizes, out_path=None if args.no_write else args.out)
    print(text)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
