"""Command-line tools (≈ orte/tools + ompi/tools): tpurun, ompi-tpu-info."""
