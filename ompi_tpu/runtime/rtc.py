"""RTC — runtime control: apply CPU binding to launched ranks.

≈ orte/mca/rtc/hwloc: the reference's rtc framework applies the binding
rmaps computed (cpuset per rank) at fork time.  Here the policy is
``--mca rtc_bind core|none`` (default none — oversubscribed test rigs and
single-core hosts must not serialize on one cpu): with ``core``, rank r
on a host is pinned to allowed-cpu ``local_rank mod n_allowed`` via
``sched_setaffinity`` in the child before exec, exactly the
one-core-per-rank default ``mpirun --bind-to core`` applies.
"""

from __future__ import annotations

import os
from typing import Callable, Optional

from ompi_tpu.core import output
from ompi_tpu.core.config import VarType, register_var, var_registry

__all__ = ["bind_child"]

_log = output.get_stream("rtc")

register_var("rtc", "bind", VarType.STRING, "none",
             "cpu binding applied to launched ranks: none | core "
             "(rank pinned to one allowed cpu, round-robin)",
             enumerator=("none", "core"))


def bind_child(pid: int, local_rank: int) -> Optional[int]:
    """Pin a freshly-spawned child to one allowed cpu; returns the cpu or
    None when binding is off/unsupported/pointless.

    Applied from the PARENT right after Popen (affinity survives exec) —
    NOT via preexec_fn, which is fork-unsafe in the multithreaded
    launcher/orted (inherited locks can deadlock the child) and disables
    the posix_spawn fast path.  Same effect as the reference's rtc/hwloc
    binding applied in the odls fork window."""
    if var_registry.get("rtc_bind") != "core":
        return None
    if not hasattr(os, "sched_setaffinity"):
        return None
    try:
        allowed = sorted(os.sched_getaffinity(0))
    except OSError:
        return None
    if len(allowed) < 2:
        # one schedulable cpu: pinning is a no-op that only removes the
        # scheduler's freedom — skip, like the reference's overload check
        return None
    cpu = allowed[local_rank % len(allowed)]
    try:
        os.sched_setaffinity(pid, {cpu})
    except OSError as e:
        _log.verbose(1, "rtc: binding pid %d failed: %r", pid, e)
        return None
    _log.verbose(1, "rtc: local rank %d (pid %d) → cpu %d",
                 local_rank, pid, cpu)
    return cpu
