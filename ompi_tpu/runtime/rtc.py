"""RTC — runtime control: apply CPU binding to launched ranks.

≈ orte/mca/rtc/hwloc: the reference's rtc framework applies the binding
rmaps computed (cpuset per rank) at fork time.  Here the policy is
``--mca rtc_bind core|none`` (default none — oversubscribed test rigs and
single-core hosts must not serialize on one cpu): with ``core``, rank r
on a host is pinned to allowed-cpu ``local_rank mod n_allowed`` via
``sched_setaffinity`` in the child before exec, exactly the
one-core-per-rank default ``mpirun --bind-to core`` applies.
"""

from __future__ import annotations

import os
from typing import Callable, Optional

from ompi_tpu.core import output
from ompi_tpu.core.config import VarType, register_var, var_registry

__all__ = ["bind_hook"]

_log = output.get_stream("rtc")

register_var("rtc", "bind", VarType.STRING, "none",
             "cpu binding applied to launched ranks: none | core "
             "(rank pinned to one allowed cpu, round-robin)",
             enumerator=("none", "core"))


def bind_hook(local_rank: int) -> Optional[Callable[[], None]]:
    """A ``preexec_fn`` pinning the child to one cpu, or None when binding
    is off/unsupported.  Runs in the forked child before exec (the same
    window the reference's odls applies rtc bindings in,
    odls_default_module.c:47-56)."""
    if var_registry.get("rtc_bind") != "core":
        return None
    if not hasattr(os, "sched_setaffinity"):
        return None
    try:
        allowed = sorted(os.sched_getaffinity(0))
    except OSError:
        return None
    if len(allowed) < 2:
        # one schedulable cpu: pinning is a no-op that only removes the
        # scheduler's freedom — skip, like the reference's overload check
        return None
    cpu = allowed[local_rank % len(allowed)]

    def _apply() -> None:  # pragma: no cover — runs post-fork, pre-exec
        try:
            os.sched_setaffinity(0, {cpu})
        except OSError:
            pass

    _log.verbose(1, "rtc: local rank %d → cpu %d", local_rank, cpu)
    return _apply
