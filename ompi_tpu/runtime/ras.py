"""RAS — resource allocation framework.

≈ orte/mca/ras: turns "where can I run" into a list of Nodes.  Components:

- ``localhost`` — N slots on this host (cpu count by default); the analog of
  oversubscribed local launch, the workhorse for tests.
- ``simulator`` — fabricates an arbitrary cluster from config vars, cloning
  orte/mca/ras/simulator/ras_sim_module.c:67-91 (ras_sim num_nodes /
  slots_per_node); lets mapping/binding logic be tested with no real machines.
- ``tpu``      — discovers the local TPU slice via jax.devices() and exposes
  one slot per chip, so ranks map 1:1 onto chips (the reference's
  ras components ask SLURM/PBS; here the "scheduler" is the slice topology).
- ``hostfile`` — parses a hostfile (``name slots=N`` lines), the reference's
  --hostfile path.
"""

from __future__ import annotations

import os
from typing import Optional

from ompi_tpu.core.config import VarType, register_var, var_registry
from ompi_tpu.core.mca import Component, Framework
from ompi_tpu.runtime.job import Job, Node

__all__ = ["ras_framework", "allocate"]

ras_framework = Framework("ras", "resource allocation")


@ras_framework.component
class LocalhostRAS(Component):
    NAME = "localhost"
    PRIORITY = 10

    def register_params(self) -> None:
        register_var("ras", "localhost_slots", VarType.INT, 0,
                     "slots on localhost (0 = discovered topology: "
                     "cpus this process may schedule on)")

    def allocate(self, job: Job, **ctx) -> list[Node]:
        slots = var_registry.get("ras_localhost_slots")
        if not slots:
            # topology-derived default (≈ hwloc feeding ras): the cpuset
            # width, not raw cpu count — a containerized launcher sees its
            # quota, not the whole machine
            from ompi_tpu.core.hwtopo import discover

            slots = discover().allowed_cpus
        # mpirun-style oversubscription: never under-allocate the job
        slots = max(slots, job.np)
        return [Node(name="localhost", slots=slots)]


@ras_framework.component
class SimulatorRAS(Component):
    """Fake clusters for tests (≈ ras_sim: num_nodes/topofiles params)."""

    NAME = "simulator"
    PRIORITY = 0  # never auto-selected; opt in via --mca ras simulator

    def register_params(self) -> None:
        register_var("ras", "sim_num_nodes", VarType.INT, 2,
                     "simulator: number of fake nodes")
        register_var("ras", "sim_slots_per_node", VarType.INT, 4,
                     "simulator: slots per fake node")
        register_var("ras", "sim_chips_per_node", VarType.INT, 0,
                     "simulator: fake TPU chips per node (0 = none)")

    def query(self, **ctx):
        return self.PRIORITY if ctx.get("allow_simulator", True) else None

    def allocate(self, job: Job, **ctx) -> list[Node]:
        n = var_registry.get("ras_sim_num_nodes")
        slots = var_registry.get("ras_sim_slots_per_node")
        chips = var_registry.get("ras_sim_chips_per_node")
        nodes = []
        for i in range(n):
            node = Node(name=f"sim{i:03d}", slots=slots)
            if chips:
                node.chips = [f"sim{i:03d}/chip{c}" for c in range(chips)]
                node.topology = {"chips": chips, "cores": slots}
            nodes.append(node)
        return nodes


@ras_framework.component
class TpuRAS(Component):
    """One slot per local TPU chip: ranks map 1:1 onto chips."""

    NAME = "tpu"
    PRIORITY = 50

    def query(self, **ctx):
        if not ctx.get("want_tpu", False):
            return None
        try:
            import jax

            if any(d.platform == "tpu" for d in jax.devices()):
                return self.PRIORITY
        except Exception:
            pass
        return None

    def allocate(self, job: Job, **ctx) -> list[Node]:
        import jax

        chips = [d for d in jax.devices() if d.platform == "tpu"]
        node = Node(name=os.uname().nodename, slots=len(chips), chips=chips)
        return [node]


@ras_framework.component
class HostfileRAS(Component):
    NAME = "hostfile"
    PRIORITY = 40

    def register_params(self) -> None:
        register_var("ras", "hostfile", VarType.STRING, "",
                     "path to hostfile (lines: <name> [slots=N])")

    def query(self, **ctx):
        path = ctx.get("hostfile") or var_registry.get("ras_hostfile")
        return self.PRIORITY if path else None

    def allocate(self, job: Job, hostfile: Optional[str] = None, **ctx) -> list[Node]:
        path = hostfile or var_registry.get("ras_hostfile")
        nodes = []
        with open(path) as fh:
            for line in fh:
                line = line.split("#", 1)[0].strip()
                if not line:
                    continue
                parts = line.split()
                slots = 1
                for p in parts[1:]:
                    if p.startswith("slots="):
                        slots = int(p.split("=", 1)[1])
                nodes.append(Node(name=parts[0], slots=slots))
        return nodes


def allocate(job: Job, **context) -> Job:
    """Run the allocation phase: fill job.nodes (≈ orte_ras_base_allocate)."""
    comp = ras_framework.select(**context)
    job.nodes = comp.allocate(job, **context)
    if not job.nodes or sum(n.slots for n in job.nodes) == 0:
        raise RuntimeError("allocation produced no usable slots")
    return job
