"""doctor — the cross-rank collective hang doctor.

The most expensive production question — "my job is stuck: which rank,
in which collective, waiting on whom, and is it a hang or an application
mismatch?" — answered from the collective flight recorder
(``trace.collrec``: every dispatch/round/Start/arena-wait, always on)
plus live per-rank state captures.

Three pieces live here:

- **rank side**: :class:`DoctorResponder`, a tiny UDP server each rank
  arms at ``init()`` (port registered with the job's PMIx server via the
  ``doctor`` RPC).  On a ``cap`` request it replies with
  :func:`capture`: the recorder tail, pending PML sends/recvs
  (peer/tag/cid/bytes/age), live arena arrive/depart counter snapshots
  (the "who hasn't arrived" signal) and every thread's
  ``sys._current_frames`` stack.  It runs on its own daemon thread, so
  a rank wedged in a collective wait still answers — only a fully
  frozen process (SIGSTOP) stays silent, and that silence is itself
  evidence (the owning orted attaches the pid's ``/proc`` state).
- **orted side**: :func:`query_rank` / :func:`proc_probe` — the
  TAG_DOCTOR handler queries each local rank's responder and falls back
  to ``/proc/<pid>`` for non-responders.
- **HNP side**: :func:`analyze` matches records by (cid, op_seq) across
  ranks and produces the machine-readable **verdict**:

  - ``mismatch``  — divergent collective kind (or, for uniform-count
    collectives, divergent signature) at one (cid, op_seq): the
    MUST-class application error that otherwise presents as an opaque
    hang;
  - ``deadlock``  — a cycle in the wait-for graph built from arena
    waits and pending point-to-point state;
  - ``straggler`` — the rank everyone waits on that itself waits on
    nobody (or a frozen pid: ``/proc`` state T/D), named with its
    stack;
  - ``healthy`` / ``no_data`` — nothing wedged / nothing captured.

Import discipline: this is a runtime module — the MPI surface
(``ompi_tpu.mpi.trace``, ``coll.shm``) is imported lazily inside the
rank-side functions only, mirroring runtime/metrics.py's rule.
"""

from __future__ import annotations

import os
import socket
import sys
import threading
import time
import traceback
from collections import Counter
from typing import Any, Optional

from ompi_tpu.core import dss, output
from ompi_tpu.core.config import VarType, register_var, var_registry

__all__ = ["DoctorResponder", "start_responder", "stop_responder",
           "capture", "query_rank", "query_timeline", "proc_probe",
           "analyze", "thread_stacks", "summarize_rows"]

_log = output.get_stream("doctor")

register_var("doctor", "rows_per_daemon", VarType.INT, 8,
             "full per-rank capture rows each orted sends up per "
             "TAG_DOCTOR round.  Beyond the budget the daemon "
             "pre-aggregates: non-responders, errored ops and the "
             "op_seq extremes (the divergence evidence the analyzer "
             "needs) keep full rows; the healthy middle collapses into "
             "one explicitly-truncated summary row — a 1000-rank "
             "/doctor document stays O(hosts) at the HNP.  0 = "
             "unbounded (every rank a full row)")

register_var("coll", "doctor_enable", VarType.BOOL, True,
             "arm the per-rank hang-doctor responder at init(): a UDP "
             "state-capture endpoint (port registered via the PMIx "
             "'doctor' RPC) the owning orted queries on TAG_DOCTOR — "
             "recorder tail, pending p2p, arena counters, thread "
             "stacks.  Costs one idle daemon thread per rank")

#: responder reply ceiling (UDP datagram with headroom below 64 KiB)
_MAX_REPLY = 60000

#: per-thread stack frame cap and per-stack character cap in a capture
_STACK_FRAMES = 25
_STACK_CHARS = 4000

#: collectives whose payload signature must agree across ranks (the
#: v-variants legitimately pass per-rank counts, so only kind
#: divergence convicts them)
_UNIFORM_SIG_KINDS = frozenset(
    k for base in ("barrier", "bcast", "reduce", "allreduce",
                   "allgather", "alltoall", "scan", "exscan",
                   "reduce_scatter_block")
    for k in (base, f"i{base}", f"p{base}"))

#: pending recvs younger than this are normal traffic, not wait-for
#: evidence (a doctor capture races healthy in-flight messages)
_RECV_EDGE_AGE_S = 0.5


# ---------------------------------------------------------------------------
# rank side: capture + responder
# ---------------------------------------------------------------------------

def thread_stacks(limit: int = _STACK_FRAMES) -> dict[str, str]:
    """Every live thread's formatted stack, keyed by thread name."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out: dict[str, str] = {}
    for tid, frame in sys._current_frames().items():
        name = names.get(tid) or f"tid-{tid}"
        text = "".join(traceback.format_stack(frame, limit=limit))
        out[name] = (text[-_STACK_CHARS:] if len(text) > _STACK_CHARS
                     else text)
    return out


def capture(rank: int, jobid: int = 0, pml: Any = None) -> dict:
    """One rank's doctor state: recorder tail, current-op head, pending
    p2p, arena counters, thread stacks.  Best-effort per section — a
    capture must never take a wedged-but-alive rank down."""
    from ompi_tpu.mpi import trace as trace_mod

    trace_mod.count("coll_doctor_captures_total")
    doc: dict[str, Any] = {
        "rank": int(rank), "jobid": int(jobid), "ts": time.time(),
        "pid": os.getpid(),
        "stuck": trace_mod.counters.get("coll_stuck_events_total", 0),
    }
    try:
        doc["collrec"] = [r for r in trace_mod.collrec_tail()
                          if r[1] == rank]
        h = trace_mod.collrec.head
        if h is not None and h[0] == rank:
            cur: dict[str, Any] = {
                "cid": h[1], "seq": h[2],
                "kind": trace_mod.collrec_kind_name(h[3]),
                "age_s": round((time.monotonic_ns() - h[4]) / 1e9, 3),
                "done": bool(h[5]),
            }
            # the head marks err-closed ops done; the analyzer needs
            # the distinction (an err-closed wait KEEPS its wait-for
            # edge — the rank died waiting, it did not finish)
            for rec in reversed(doc["collrec"]):
                if rec[5] == "err" and rec[2] == h[1] and rec[3] == h[2]:
                    cur["err"] = True
                    break
                if rec[5] == "done" and rec[2] == h[1] \
                        and rec[3] == h[2]:
                    break
            doc["cur"] = cur
    except Exception as e:  # noqa: BLE001 — capture survives anything
        doc["collrec_error"] = repr(e)
    if pml is None:
        try:
            from ompi_tpu.mpi import runtime as mpi_runtime

            pml = mpi_runtime._state.get("pml")
        except Exception:  # noqa: BLE001 — no live MPI epoch
            pml = None
    if pml is not None:
        try:
            doc["pending"] = pml.pending_summary()
        except Exception as e:  # noqa: BLE001
            doc["pending_error"] = repr(e)
    try:
        from ompi_tpu.mpi.coll import shm as shm_mod

        arenas = shm_mod.arena_states()
        if arenas:
            doc["arenas"] = arenas
    except Exception as e:  # noqa: BLE001
        doc["arenas_error"] = repr(e)
    try:
        doc["stacks"] = thread_stacks()
    except Exception as e:  # noqa: BLE001
        doc["stacks_error"] = repr(e)
    return doc


class DoctorResponder:
    """The rank-side capture endpoint: one UDP socket + daemon thread.

    Loopback-bound — the querying orted always shares the host with its
    ranks (the same invariant the metrics collector relies on)."""

    def __init__(self, rank: int, jobid: int = 0, pml: Any = None) -> None:
        self.rank = rank
        self.jobid = jobid
        self.pml = pml
        self._stop = threading.Event()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.bind(("127.0.0.1", 0))
        self._sock.settimeout(0.5)
        self.port = self._sock.getsockname()[1]
        self._thread = threading.Thread(
            target=self._run, name=f"doctor-resp-{rank}", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                blob, addr = self._sock.recvfrom(2048)
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                msg = dss.unpack(blob, n=1)[0]
                if msg[0] not in ("cap", "tl"):
                    continue
                req = msg[0]
                token = int(msg[1]) if len(msg) > 1 else 0
            except Exception:  # noqa: BLE001 — garbage datagram: drop
                continue
            if req == "tl":
                # live-timeline tail: the flight-recorder slice the
                # TAG_TIMELINE fan-out merges into the /timeline trace
                try:
                    tail = int(msg[2]) if len(msg) > 2 else 2048
                    from ompi_tpu.mpi import trace as trace_mod

                    doc = trace_mod.timeline_capture(tail)
                    doc.setdefault("rank", self.rank)
                except Exception as e:  # noqa: BLE001
                    doc = {"rank": self.rank, "error": repr(e)}
                try:
                    self._sock.sendto(self._shrink_tl(token, doc), addr)
                except OSError:
                    pass
                continue
            try:
                doc = capture(self.rank, self.jobid, self.pml)
            except Exception as e:  # noqa: BLE001
                doc = {"rank": self.rank, "error": repr(e)}
            try:
                self._sock.sendto(self._shrink(token, doc), addr)
            except OSError:
                continue

    @staticmethod
    def _shrink(token: int, doc: dict) -> bytes:
        """Pack the reply under the UDP ceiling, dropping the bulkiest
        sections progressively rather than failing the capture."""
        blob = dss.pack(("cap", token, doc))
        if len(blob) <= _MAX_REPLY:
            return blob
        doc = dict(doc)
        full = doc.get("collrec") or []
        doc["collrec"] = full[-64:]
        if len(full) > 64:
            # explicit truncation at EVERY shrink stage: a clipped tail
            # must say so (and how much fell off), never silently pose
            # as the whole recorder history
            doc["collrec_truncated"] = len(full) - 64
        blob = dss.pack(("cap", token, doc))
        if len(blob) <= _MAX_REPLY:
            return blob
        doc["stacks"] = {k: v[-800:]
                         for k, v in (doc.get("stacks") or {}).items()}
        doc["truncated"] = True
        blob = dss.pack(("cap", token, doc))
        if len(blob) <= _MAX_REPLY:
            return blob
        return dss.pack(("cap", token, {
            "rank": doc.get("rank"), "cur": doc.get("cur"),
            "truncated": True}))

    @staticmethod
    def _shrink_tl(token: int, doc: dict) -> bytes:
        """Pack a timeline reply under the UDP ceiling by halving the
        event tail (newest kept) until it fits — a shorter window beats
        a failed capture."""
        blob = dss.pack(("tl", token, doc))
        while len(blob) > _MAX_REPLY:
            events = doc.get("events") or []
            if not events:
                return dss.pack(("tl", token, {
                    "rank": doc.get("rank"), "truncated": True}))
            doc = dict(doc)
            doc["events"] = events[-(len(events) // 2):]
            doc["truncated"] = True
            blob = dss.pack(("tl", token, doc))
        return blob

    def close(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass


_responder: Optional[DoctorResponder] = None
_resp_lock = threading.Lock()


def start_responder(rank: int, jobid: int = 0, pml: Any = None,
                    client: Any = None) -> Optional[DoctorResponder]:
    """Arm the rank's doctor responder (idempotent; no-op when
    ``coll_doctor_enable`` is off).  ``client`` — the rank's PMIxClient —
    registers the port with the control plane so the owning orted can
    find it."""
    global _responder
    try:
        if not var_registry.get("coll_doctor_enable"):
            return None
    except Exception:  # noqa: BLE001 — unregistered knob: stay armed
        pass
    with _resp_lock:
        if _responder is None:
            _responder = DoctorResponder(rank, jobid=jobid, pml=pml)
        resp = _responder
    if client is not None:
        try:
            client.register_doctor(resp.port)
        except Exception as e:  # noqa: BLE001 — observability, not init
            _log.verbose(1, "doctor port registration failed: %r", e)
    return resp


def stop_responder() -> None:
    global _responder
    with _resp_lock:
        resp, _responder = _responder, None
    if resp is not None:
        resp.close()


# ---------------------------------------------------------------------------
# orted side: query one local rank / probe a frozen pid
# ---------------------------------------------------------------------------

def query_rank(port: int, timeout: float = 0.8) -> Optional[dict]:
    """One capture from a local rank's responder (None on silence — a
    SIGSTOP'd rank cannot answer, which is evidence in itself)."""
    token = time.monotonic_ns() & 0x7FFFFFFF
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        sock.settimeout(timeout)
        sock.sendto(dss.pack(("cap", token)), ("127.0.0.1", int(port)))
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                blob, _addr = sock.recvfrom(1 << 16)
            except socket.timeout:
                return None
            try:
                msg = dss.unpack(blob, n=1)[0]
            except Exception:  # noqa: BLE001
                continue
            if msg[0] == "cap" and int(msg[1]) == token:
                return dict(msg[2])
        return None
    except OSError:
        return None
    finally:
        try:
            sock.close()
        except OSError:
            pass


def query_timeline(port: int, tail: int = 2048,
                   timeout: float = 0.8) -> Optional[dict]:
    """One flight-recorder tail from a local rank's responder (None on
    silence) — the TAG_TIMELINE analog of :func:`query_rank`."""
    token = time.monotonic_ns() & 0x7FFFFFFF
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        sock.settimeout(timeout)
        sock.sendto(dss.pack(("tl", token, int(tail))),
                    ("127.0.0.1", int(port)))
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                blob, _addr = sock.recvfrom(1 << 16)
            except socket.timeout:
                return None
            try:
                msg = dss.unpack(blob, n=1)[0]
            except Exception:  # noqa: BLE001
                continue
            if msg[0] == "tl" and int(msg[1]) == token:
                return dict(msg[2])
        return None
    except OSError:
        return None
    finally:
        try:
            sock.close()
        except OSError:
            pass


def proc_probe(pid: int) -> dict:
    """Kernel-side evidence for a rank that did not answer: /proc state
    (T = stopped — the SIGSTOP signature), wchan and current syscall."""
    out: dict[str, Any] = {"pid": int(pid)}
    try:
        with open(f"/proc/{pid}/stat") as f:
            out["state"] = f.read().rsplit(")", 1)[1].split()[0]
    except (OSError, IndexError):
        out["state"] = "?"
    for name in ("wchan", "syscall"):
        try:
            with open(f"/proc/{pid}/{name}") as f:
                val = f.read(160).strip()
            if val:
                out[name] = val
        except OSError:
            continue
    return out


def summarize_rows(rows: list[dict],
                   limit: int) -> tuple[list[dict], Optional[dict]]:
    """Hierarchical doctor pre-aggregation, the orted half: bound one
    daemon's TAG_DOCTOR_REPLY to ``limit`` full per-rank rows plus ONE
    summary row for everyone else — so a fleet-wide capture costs the
    HNP O(hosts · limit), not O(ranks).

    Which rows keep full fidelity is chosen for the analyzer's benefit:
    non-responders and errored ops always (they decide deadlock /
    straggler verdicts), then the op_seq extremes of the rest (the
    slowest and fastest ranks ARE the divergence evidence a mismatch /
    straggler verdict needs; the agreeing middle of the distribution is
    what compresses).  The summary row is explicitly marked
    (``summary``/``truncated``) and carries the omitted ranks' aggregate
    shape — count, current-op kind histogram, op_seq min/max, a bounded
    rank sample — so the /doctor document SAYS what it dropped.

    Returns ``(kept_rows, summary_row_or_None)``; a row set within the
    budget (or ``limit <= 0`` = unbounded) passes through untouched."""
    rows = list(rows)
    if limit <= 0 or len(rows) <= limit:
        return rows, None

    def cur_of(c: dict) -> dict:
        return c.get("cur") or _pushed_cur(c) or {}

    def seq_of(c: dict) -> int:
        try:
            return int(cur_of(c).get("seq", -1))
        except (TypeError, ValueError):
            return -1

    hot = [i for i, c in enumerate(rows)
           if c.get("no_response") or cur_of(c).get("err")]
    keep = set(hot[:limit])
    room = limit - len(keep)
    if room > 0:
        cold = sorted((i for i in range(len(rows)) if i not in keep),
                      key=lambda i: (seq_of(rows[i]), i))
        n_head = (room + 1) // 2
        keep.update(cold[:n_head])
        keep.update(cold[max(n_head, len(cold) - (room - n_head)):])
    kept = [rows[i] for i in sorted(keep)]
    omitted = [rows[i] for i in range(len(rows)) if i not in keep]
    kinds: Counter = Counter()
    seqs: list[int] = []
    stuck = 0
    for c in omitted:
        cur = cur_of(c)
        if cur:
            kinds[str(cur.get("kind", "?"))] += 1
        s = seq_of(c)
        if s >= 0:
            seqs.append(s)
        try:
            stuck += int(bool(c.get("stuck")))
        except (TypeError, ValueError):
            pass
    sample = sorted(int(c.get("rank", -1)) for c in omitted)[:32]
    summary = {
        "summary": True, "truncated": True,
        "ranks_omitted": len(omitted),
        "rank_sample": sample,
        "cur_kinds": dict(kinds),
        "op_seq_min": (min(seqs) if seqs else None),
        "op_seq_max": (max(seqs) if seqs else None),
        "stuck": stuck,
    }
    return kept, summary


# ---------------------------------------------------------------------------
# HNP side: the analyzer
# ---------------------------------------------------------------------------

def _kind_name(kind_id: Any) -> str:
    from ompi_tpu.mpi import trace as trace_mod

    try:
        return trace_mod.collrec_kind_name(int(kind_id))
    except (TypeError, ValueError):
        return "?"


def _pushed_cur(c: dict) -> Optional[dict]:
    """A non-responder's last uplink-pushed recorder head, normalized to
    the responder ``cur`` shape."""
    pushed = c.get("pushed") or {}
    if "coll_cur_seq" not in pushed or pushed["coll_cur_seq"] < 0:
        return None
    ts = float(pushed.get("coll_cur_posted_ts", 0.0))
    return {
        "cid": int(pushed.get("coll_cur_cid", -1)),
        "seq": int(pushed["coll_cur_seq"]),
        "kind": _kind_name(pushed.get("coll_cur_kind_id", -1)),
        "age_s": (round(max(0.0, time.time() - ts), 3) if ts > 0
                  else 0.0),
        "done": bool(pushed.get("coll_cur_done", 0)),
        "pushed": True,
    }


def _rank_posts(c: dict) -> dict[tuple[int, int], tuple[str, Optional[int]]]:
    """(cid, op_seq) → (kind, sig) from one capture's recorder tail
    (plus the pushed head for non-responders).  Records are filtered to
    the capture's own rank: a tail from a process hosting several ranks
    (the in-process test harness) must not smear one rank's posts over
    another's and mask a divergence."""
    own = int(c.get("rank", -1))
    out: dict[tuple[int, int], tuple[str, Optional[int]]] = {}
    for rec in c.get("collrec") or []:
        try:
            _ts, r, cid, seq, kind, phase, sig = rec[:7]
        except (TypeError, ValueError):
            continue
        if int(r) != own:
            continue
        if phase == "post" and seq >= 0:
            out[(int(cid), int(seq))] = (str(kind), int(sig))
    cur = c.get("cur") or _pushed_cur(c)
    if cur is not None and cur.get("seq", -1) >= 0:
        out.setdefault((int(cur.get("cid", -1)), int(cur["seq"])),
                       (str(cur.get("kind", "?")), None))
    return out


def _rank_cur(c: dict) -> Optional[dict]:
    return c.get("cur") or _pushed_cur(c)


def _wait_edges(c: dict) -> set[int]:
    """Ranks this capture's rank is provably waiting on: the newest
    un-closed arena wait record, plus aged pending named-source recvs."""
    edges: set[int] = set()
    cur = _rank_cur(c)
    if cur is not None and (not cur.get("done") or cur.get("err")):
        # newest wait record for the in-flight (cid, seq); an op closed
        # by "err" (coll_shm_timeout killed the wait) keeps its edge —
        # a failed wait is the postmortem's strongest wait-for evidence
        for rec in reversed(c.get("collrec") or []):
            try:
                _ts, r, cid, seq, _kind, phase, _sig, info = rec[:8]
            except (TypeError, ValueError):
                continue
            if int(r) != int(c.get("rank", -1)):
                continue
            if phase == "done" and int(cid) == int(cur.get("cid", -2)) \
                    and int(seq) == int(cur["seq"]):
                break   # that op closed after its waits
            if phase in ("wait", "stuck") \
                    and int(cid) == int(cur.get("cid", -2)) \
                    and int(seq) == int(cur["seq"]) \
                    and isinstance(info, dict) and "on" in info:
                edges.add(int(info["on"]))
                break
    pending = c.get("pending") or {}
    for rv in pending.get("recvs") or []:
        try:
            if rv["src"] >= 0 and rv.get("age_s", 0) >= _RECV_EDGE_AGE_S:
                edges.add(int(rv["src"]))
        except (TypeError, KeyError):
            continue
    edges.discard(int(c.get("rank", -1)))
    return edges


def _find_cycle(edges: dict[int, set[int]]) -> Optional[list[int]]:
    """First cycle in the wait-for graph (DFS, deterministic order)."""
    WHITE, GREY, BLACK = 0, 1, 2
    color = {r: WHITE for r in edges}
    stack: list[int] = []

    def dfs(r: int) -> Optional[list[int]]:
        color[r] = GREY
        stack.append(r)
        for t in sorted(edges.get(r, ())):
            if color.get(t, WHITE) == GREY:
                return stack[stack.index(t):] + [t]
            if color.get(t, WHITE) == WHITE and t in edges:
                found = dfs(t)
                if found:
                    return found
        stack.pop()
        color[r] = BLACK
        return None

    for r in sorted(edges):
        if color[r] == WHITE:
            found = dfs(r)
            if found:
                return found
    return None


def analyze(captures: list[dict],
            nranks: Optional[int] = None) -> dict:
    """The cross-rank verdict from per-rank captures (responders and
    ``no_response`` /proc probes alike).  Pure function of its inputs —
    shared by the live DVM ``/doctor`` endpoint and the offline
    ``tools/hang_doctor.py`` crash-dump mode."""
    by_rank: dict[int, dict] = {}
    for c in captures or []:
        try:
            by_rank[int(c["rank"])] = c
        except (TypeError, KeyError, ValueError):
            continue
    doc: dict[str, Any] = {
        "nranks": nranks if nranks is not None else len(by_rank),
        "responders": sorted(r for r, c in by_rank.items()
                             if not c.get("no_response")),
        "no_response": sorted(r for r, c in by_rank.items()
                              if c.get("no_response")),
        "ranks": {},
    }
    for r, c in sorted(by_rank.items()):
        row: dict[str, Any] = {}
        cur = _rank_cur(c)
        if cur is not None:
            row["cur"] = cur
        if c.get("no_response"):
            row["no_response"] = True
            if "proc" in c:
                row["proc"] = c["proc"]
        doc["ranks"][str(r)] = row
    if not by_rank:
        doc["verdict"] = {"kind": "no_data",
                          "detail": "no rank state captured"}
        return doc

    # -- 1. collective mismatch: divergent (kind | uniform-count sig)
    #       at one (cid, op_seq) -----------------------------------------
    posts: dict[tuple[int, int], dict[int, tuple[str, Optional[int]]]] = {}
    for r, c in by_rank.items():
        for key, val in _rank_posts(c).items():
            posts.setdefault(key, {})[r] = val
    for (cid, seq) in sorted(posts):
        ranks = posts[(cid, seq)]
        if len(ranks) < 2:
            continue
        kinds = {k for k, _s in ranks.values()}
        divergent_sig = False
        if len(kinds) == 1 and next(iter(kinds)) in _UNIFORM_SIG_KINDS:
            sigs = {s for _k, s in ranks.values() if s is not None}
            divergent_sig = len(sigs) > 1
        if len(kinds) > 1 or divergent_sig:
            if len(kinds) > 1:
                majority, _n = Counter(
                    k for k, _s in ranks.values()).most_common(1)[0]
                culprits = sorted(r for r, (k, _s) in ranks.items()
                                  if k != majority)
            else:
                # kinds agree, signatures diverge: the minority
                # SIGNATURE holder is the culprit
                maj_sig, _n = Counter(
                    s for _k, s in ranks.values()
                    if s is not None).most_common(1)[0]
                culprits = sorted(r for r, (_k, s) in ranks.items()
                                  if s is not None and s != maj_sig)
            culprits = culprits or sorted(ranks)
            doc["verdict"] = {
                "kind": "mismatch",
                "cid": cid, "op_seq": seq,
                "rank": culprits[0],
                "ranks": culprits,
                "kinds": {str(r): k for r, (k, _s) in
                          sorted(ranks.items())},
                "detail": (
                    f"collective mismatch at (cid {cid}, op_seq {seq}): "
                    + ("divergent kinds "
                       + ", ".join(f"rank {r}={k}" for r, (k, _s)
                                   in sorted(ranks.items()))
                       if len(kinds) > 1 else
                       f"divergent payload signatures on "
                       f"{next(iter(kinds))} (dtype/count/root "
                       f"disagree across ranks)")),
            }
            stack = (by_rank.get(culprits[0], {})
                     .get("stacks") or {}).get("MainThread")
            if stack:
                doc["verdict"]["stack"] = stack
            return doc

    # -- 2. deadlock: a cycle in the wait-for graph ----------------------
    edges = {r: _wait_edges(c) for r, c in by_rank.items()
             if not c.get("no_response")}
    edges = {r: e for r, e in edges.items() if e}
    cycle = _find_cycle(edges)
    if cycle:
        doc["verdict"] = {
            "kind": "deadlock",
            "cycle": cycle,
            "rank": min(cycle[:-1]),
            "detail": ("wait-for cycle: "
                       + " -> ".join(str(r) for r in cycle)),
            "stacks": {str(r): (by_rank.get(r, {}).get("stacks") or {})
                       .get("MainThread", "")[-1500:]
                       for r in cycle[:-1]},
        }
        return doc

    # -- 3. straggler: the rank everyone waits on that waits on nobody --
    waited_on: Counter = Counter(t for targets in edges.values()
                                 for t in targets)
    suspect: Optional[int] = None
    why = ""
    frozen = [r for r, c in by_rank.items()
              if c.get("no_response")
              and (c.get("proc") or {}).get("state") in ("T", "t", "D")]
    if frozen:
        suspect = (max(frozen, key=lambda r: waited_on.get(r, 0))
                   if waited_on else frozen[0])
        st = (by_rank[suspect].get("proc") or {}).get("state")
        why = (f"pid frozen (/proc state {st!r}"
               + (", SIGSTOP signature)" if st in ("T", "t")
                  else ", uninterruptible)"))
    elif waited_on:
        def _gave_up(r: int) -> bool:
            cur = _rank_cur(by_rank.get(r, {}))
            return bool(cur and cur.get("err"))

        cand = [r for r, _n in waited_on.most_common()
                if not edges.get(r)]
        if cand:
            # among waited-on ranks that wait on nobody, one still
            # wedged in flight beats one that already erred out — the
            # err'd ranks are victims of the hang, not its cause
            alive = [r for r in cand if not _gave_up(r)]
            suspect = (alive or cand)[0]
            why = (f"{waited_on[suspect]} rank(s) wait on it "
                   f"(transitively); it waits on nobody")
        else:
            suspect, n = waited_on.most_common(1)[0]
            why = f"most-waited-on rank ({n} waiters)"
    else:
        # no wait evidence: the rank whose op_seq frontier is lowest
        # while peers moved on (a silently slow/stopped rank)
        curs = {r: _rank_cur(c) for r, c in by_rank.items()}
        inflight = {r: c for r, c in curs.items()
                    if c is not None and not c.get("done")}
        if inflight and len({c["seq"] for c in inflight.values()}) > 1:
            suspect = min(inflight, key=lambda r: inflight[r]["seq"])
            why = (f"behind the op_seq frontier "
                   f"(at {inflight[suspect]['seq']}, peers ahead)")
    if suspect is not None:
        verdict: dict[str, Any] = {
            "kind": "straggler", "rank": suspect, "detail": (
                f"rank {suspect} is the straggler: {why}"),
            "waiters": {str(r): sorted(t)
                        for r, t in sorted(edges.items())},
        }
        c = by_rank.get(suspect, {})
        cur = _rank_cur(c)
        if cur is not None:
            verdict["cid"] = cur.get("cid")
            verdict["op_seq"] = cur.get("seq")
            verdict["in"] = cur.get("kind")
        stacks = c.get("stacks")
        if stacks:
            verdict["stack"] = (stacks.get("MainThread")
                                or next(iter(stacks.values()), ""))
        elif "proc" in c:
            verdict["proc"] = c["proc"]
        doc["verdict"] = verdict
        return doc

    # -- 4. nothing wedged ----------------------------------------------
    curs = [(_rank_cur(c) or {}) for c in by_rank.values()]
    if any(cur and not cur.get("done") for cur in curs):
        doc["verdict"] = {
            "kind": "healthy",
            "detail": "collectives in flight, no wedge evidence "
                      "(capture may have raced normal progress)"}
    else:
        doc["verdict"] = {"kind": "healthy",
                          "detail": "no collective in flight"}
    return doc
