"""RML — the runtime's tagged messaging bus over a routed daemon tree.

≈ orte/mca/rml (rml.h:373,412 send/recv_buffer_nb) + orte/mca/oob/tcp +
orte/mca/routed/binomial (routed.h:123) + grpcomm xcast (grpcomm.h:110),
collapsed into one module sized for TPU pods (tens of hosts, not tens of
thousands):

- Every runtime node (the HNP = vpid 0, one daemon per host = vpid 1..N)
  is an :class:`RmlNode` with a TCP listener and tag→handler registry.
- **Bootstrap** is the reference's phone-home: each daemon dials the HNP
  and registers (vpid, uri).  When all have reported, the HNP computes a
  binary routing tree and sends each daemon a WIRE message naming its
  children; every parent then dials its children (the routed overlay).
- **xcast(tag, payload)** floods down the tree: each node delivers
  locally and relays to its children — O(log n) fan-out from the HNP,
  exactly grpcomm/xcast's job.
- **send_up(tag, payload)** relays toward vpid 0 through parents — the
  daemons' report channel (IOF, proc exits, registrations).

Messages are DSS-framed ``(kind, tag, origin, payload)`` tuples; handlers
run on the link reader thread (keep them short or hand off, the same
contract as the reference's event-loop callbacks).

Every link is a :class:`_Link` — (socket, send-lock) — because frames are
written by many threads (IOF readers, exit waiters, relays) and
``sendall`` is not atomic under backpressure: without the lock, partial
sends interleave and corrupt the length-prefixed stream (the same reason
TcpBTL keeps a per-socket lock).
"""

from __future__ import annotations

import heapq
import socket
import struct
import sys
import threading
import time
from typing import Any, Callable, Optional

from ompi_tpu.core import dss, output
from ompi_tpu.core.config import VarType, register_var, var_registry

__all__ = ["RmlNode", "tree_children", "tree_parent",
           "nearest_live_ancestor", "HeartbeatMonitor", "start_heartbeats",
           "scaled_timeout"]

_log = output.get_stream("rml")

register_var("rml", "heartbeat_period", VarType.DOUBLE, 0.0,
             "seconds between daemon liveness heartbeats up the tree "
             "(0 = disabled; link EOF detection still applies)")
register_var("rml", "heartbeat_timeout", VarType.DOUBLE, 3.0,
             "seconds of heartbeat silence before the HNP declares a "
             "daemon dead (only meaningful with rml_heartbeat_period > 0)")
register_var("rml", "reparent_timeout", VarType.DOUBLE, 10.0,
             "seconds an orphaned orted (tree parent lost under the "
             "notify errmgr policy) waits for the HNP-arbitrated "
             "re-parenting handshake before falling back to the lifeline "
             "teardown")

# well-known tags (≈ orte/mca/rml/rml_types.h:59-69)
TAG_REGISTER = "register"       # daemon → HNP: (vpid, uri, hostname)
TAG_WIRE = "wire"               # HNP → daemon: children to dial
TAG_LAUNCH = "launch"           # xcast: proc table
TAG_KILL = "kill"               # xcast: jobid | None — tear ONE job
#                                 down (daemons drop its spec/procs)
#                                 or, with None, every job (lifeline
#                                 teardown / VM shutdown)
TAG_SHUTDOWN = "shutdown"       # xcast: daemons exit
TAG_IOF = "iof"                 # up: (jobid, rank, stream, chunk)
TAG_STDIN = "stdin"             # xcast: (target_rank, chunk | None=EOF)
TAG_PROC_EXIT = "proc_exit"     # up: (jobid, rank, rc, errmsg)
TAG_DAEMON_READY = "ready"      # up: daemon wired + children connected
TAG_RESPAWN = "respawn"         # xcast: {jobid, rank, lives, target,
#                                 local_rank, chip} — the daemon whose
#                                 vpid == target adopts the row and
#                                 revives the rank (migration: every
#                                 daemon holds the job spec, so the
#                                 target need not be the original
#                                 owner); other daemons drop the row
TAG_STATS = "stats"             # xcast: request per-rank resource usage
TAG_STATS_REPLY = "stats_reply"  # up: (vpid, epoch,
#                                 [(jobid, rank, pid, rss, cpu_s)...])
TAG_HEARTBEAT = "heartbeat"     # up: vpid — daemon liveness beat
TAG_PROC_FAILED = "proc_failed"  # xcast: (rank, reason) — errmgr notify
#                                  propagating a rank death to survivors
#                                  instead of killing the job
TAG_ORPHANED = "orphaned"       # direct (boot link) daemon → HNP:
#                                 (vpid, lost_parent) — my tree parent
#                                 vanished; arbitrate a re-parenting
TAG_REPARENT = "reparent"       # direct HNP → orphan: new parent vpid —
#                                 expect its hello instead of tearing down
TAG_ADOPT = "adopt"             # direct HNP → adopter: [(vpid, uri), ...]
#                                 orphans to dial as tree children
TAG_REPARENT_ACK = "reparent_ack"  # up: (vpid, new_parent) — re-wired
TAG_KILL_RANK = "kill_rank"     # xcast: (jobid, rank) — the owning
#                                 daemon SIGKILLs
#                                 exactly that rank (reaping a hung pid
#                                 the gossip detector reported)
TAG_SIGNAL_RANK = "signal_rank"  # xcast: (jobid, rank, signum) — the
#                                 owning daemon signals the rank's
#                                 process group (the DVM remediation
#                                 actor's SIGCONT probe: resume a
#                                 SIGSTOP'd straggler before paying a
#                                 reap-and-revive)
TAG_DOCTOR = "doctor"           # xcast: epoch — every orted captures its
#                                 local ranks' hang-doctor state (UDP
#                                 query of each rank's responder; /proc
#                                 probe for frozen pids) and replies up
TAG_DOCTOR_REPLY = "doctor_reply"  # up: (vpid, epoch, [capture, ...]) —
#                                 the per-rank doctor captures the
#                                 HNP/DVM analyzer folds into a verdict
TAG_METRICS = "metrics"         # hop (one tree level, delivered at EVERY
#                                 hop, not send_up's root-only relay):
#                                 {jobid: {rank: [wall_ts, {pvar: value}]}}
#                                 — each orted merges its children's
#                                 payloads with its local ranks' and
#                                 forwards one combined delta per
#                                 trace_metrics_push_period; the HNP/DVM
#                                 folds the stream into the scrape
#                                 aggregate
TAG_CLOCK = "clock"             # hop child → parent: (vpid, seq, t0_ns) —
#                                 one leg of the min-RTT clock pingpong;
#                                 the receiving hop answers immediately so
#                                 each edge of the tree is measured against
#                                 its OWN parent (offsets compose down)
TAG_CLOCK_REPLY = "clock_reply"  # direct parent → child:
#                                 (seq, t0_ns, t_parent_ns) — t0 echoed so
#                                 the prober needs no outstanding-probe
#                                 table; the child stamps t3 on delivery
TAG_TIMELINE = "timeline"       # xcast: (epoch, tail) — every orted
#                                 gathers bounded flight-recorder tails
#                                 from its local ranks (UDP query of each
#                                 responder) and replies up: the live
#                                 /timeline capture, same shape as
#                                 TAG_DOCTOR
TAG_TIMELINE_REPLY = "timeline_reply"  # up: (vpid, epoch, [capture, ...])
#                                 — per-rank recorder tails the HNP/DVM
#                                 merges into one skew-corrected trace


def _pack_env(kind: str, tag: str, origin: int, payload: Any) -> bytes:
    """Frame one RML envelope.  With the flight recorder armed in this
    process the envelope grows a 5th element — the ``(trace_id,
    span_id)`` pair — and an ``rml_send`` instant lands in the
    recorder; the receiving side's matching ``rml_recv`` instant lets
    the timeline merge draw an arrow per OOB edge (control traffic —
    doctor rounds, rejoin epochs, metrics hops — becomes causally
    visible next to the data plane).  Readers tolerate both widths, so
    instrumented and plain processes interoperate.  Cost with tracing
    off (every daemon's default): one attribute check."""
    tc = None
    # sys.modules, not an import: the MPI layer must only be consulted
    # when something else already loaded it — a bare daemon's OOB sends
    # must not drag jax/numpy into the orted process
    trace = sys.modules.get("ompi_tpu.mpi.trace")
    if trace is not None:
        # the attribute reads live INSIDE the guard: sys.modules holds a
        # partially-initialized module while another thread runs its
        # first import, and an AttributeError here must degrade to an
        # unstamped envelope — not kill the send (an orphan report lost
        # to a tracing race once stalled a whole reparent epoch)
        try:
            if trace.active:
                tc = [trace.trace_id(), trace.next_span_id()]
                trace.instant("runtime", "rml_send", tag=tag, tc=tc)
        except Exception:  # noqa: BLE001 — tracing never breaks the OOB plane
            tc = None
    if tc is None:
        return dss.pack((kind, tag, origin, payload))
    return dss.pack((kind, tag, origin, payload, tc))


def _note_recv(tag: str, tc: Any) -> None:
    """The receive half of the envelope trace pair (no-op unless this
    process has the flight recorder armed)."""
    trace = sys.modules.get("ompi_tpu.mpi.trace")
    if trace is not None:
        try:  # see _pack_env on the partial-import hazard
            if trace.active:
                trace.instant("runtime", "rml_recv", tag=tag,
                              tc=list(tc))
        except Exception:  # noqa: BLE001
            pass


def tree_parent(vpid: int) -> Optional[int]:
    """Binary routing tree over vpids 0..N (0 = HNP) — the k=2 case of
    the shared netpatterns k-ary tree (≈ routed/binomial's role)."""
    from ompi_tpu.core.netpatterns import kary_parent

    return kary_parent(vpid, k=2)

def tree_children(vpid: int, n: int) -> list[int]:
    """Children of ``vpid`` among vpids 0..n-1."""
    from ompi_tpu.core.netpatterns import kary_children

    return kary_children(vpid, n, k=2)


def nearest_live_ancestor(vpid: int, dead: set[int]) -> int:
    """The closest ancestor of ``vpid`` not in ``dead`` — the adopter a
    mid-tree daemon death hands its orphans to (vpid arithmetic on the
    routing tree; the HNP, vpid 0, is never in ``dead``)."""
    p = tree_parent(vpid)
    while p is not None and p in dead:
        p = tree_parent(p)
    return 0 if p is None else p


#: routing-tree depth at which timeout scaling kicks in — depth 4 covers
#: a 31-node world, so every historical small-world test keeps its exact
#: configured timeout (factor 1.0) while a 100-daemon world gets 1.5x
#: and a 1000-daemon world 2.25x
_SCALE_BASE_DEPTH = 4


def scaled_timeout(base: float, world: int) -> float:
    """A liveness window scaled with world size: beats and reparent acks
    cross ``tree_depth`` store-and-forward hops, and a correlated loss
    makes every survivor re-wire at once — a timeout tuned on a 9-rank
    world declares half a 1000-rank fleet dead during one reparent wave.
    Scale is the routing-tree depth relative to :data:`_SCALE_BASE_DEPTH`
    (never below 1.0, so small worlds keep their configured window)."""
    from ompi_tpu.core.netpatterns import tree_depth

    depth = tree_depth(max(1, int(world)), k=2)
    return float(base) * max(1.0, depth / _SCALE_BASE_DEPTH)


class _Link:
    """One framed TCP link with a serialized writer side."""

    __slots__ = ("sock", "_wlock")

    def __init__(self, sock: socket.socket) -> None:
        self.sock = sock
        self._wlock = threading.Lock()

    def send(self, payload: bytes) -> None:
        frame = struct.pack("<I", len(payload)) + payload
        with self._wlock:
            self.sock.sendall(frame)

    def close(self) -> None:
        # shutdown() before close(): a close() alone does NOT tear the
        # connection down while this node's own reader is blocked in
        # recv on the fd (the in-flight syscall pins the file, so the
        # FIN is deferred until it returns — which is never, since the
        # peer is waiting on us).  A process death releases every ref at
        # once, but an in-process daemon (simfleet) or any multi-link
        # teardown needs the explicit half-close to wake both sides
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass


def _recv_frame(sock: socket.socket) -> Optional[bytes]:
    hdr = b""
    while len(hdr) < 4:
        chunk = sock.recv(4 - len(hdr))
        if not chunk:
            return None
        hdr += chunk
    (n,) = struct.unpack("<I", hdr)
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 16, n - len(buf)))
        if not chunk:
            return None
        buf += chunk
    return bytes(buf)


class RmlNode:
    """One runtime node on the bus (HNP or daemon)."""

    def __init__(self, vpid: int, host: str = "127.0.0.1") -> None:
        self.vpid = vpid
        self._handlers: dict[str, Callable[[int, Any], None]] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._parent_link: Optional[_Link] = None
        self.parent_wired = threading.Event()  # set when the up-link exists
        # which vpid is allowed to become my parent: tree position by
        # default, retargeted by the re-parenting handshake (an orphaned
        # daemon starts expecting its adopter instead)
        self.parent_vpid: Optional[int] = tree_parent(vpid)
        self._pending_hellos: dict[int, _Link] = {}  # hellos from peers
        # that are not (yet) my parent — an adopter's dial can race the
        # HNP's TAG_REPARENT order, so the link is kept until retargeted
        # an up-path of last resort (the daemon's bootstrap link to the
        # HNP): used while orphaned, so exit reports / heartbeats survive
        # the window between losing a parent and being adopted
        self.fallback_up: Optional[_Link] = None
        self._child_links: dict[int, _Link] = {}
        self.boot_links: dict[int, _Link] = {}  # HNP: vpid → link
        # Called with the peer vpid when a known link hits EOF — the
        # lifeline-lost signal (≈ ORTE aborting on a lost daemon lifeline).
        self.on_peer_lost: Optional[Callable[[int], None]] = None
        # Partition-injection seam: when set, called as gate(direction,
        # tag) with direction "in"/"out" before any non-hello frame is
        # delivered or sent; returning False blackholes the frame with
        # the socket left alive — a true network partition (no EOF, no
        # RST), unlike close().  Must be non-blocking: the inbound check
        # runs on the link reader thread.  None (the default) costs one
        # attribute test per frame.
        self.frame_gate: Optional[Callable[[str, str], bool]] = None
        self._listener = socket.create_server((host, 0), backlog=32)
        self.uri = f"{host}:{self._listener.getsockname()[1]}"
        self._threads: list[threading.Thread] = []
        t = threading.Thread(target=self._accept_loop,
                             name=f"rml-accept-{vpid}", daemon=True)
        t.start()
        self._threads.append(t)

    # -- wiring -----------------------------------------------------------

    def register_recv(self, tag: str,
                      cb: Callable[[int, Any], None]) -> None:
        """Register cb(origin_vpid, payload) for a tag (≈ rml.h:412)."""
        with self._lock:
            self._handlers[tag] = cb

    def dial_bootstrap(self, hnp_uri: str) -> _Link:
        """Daemon side phone-home: a direct link to the HNP used ONLY for
        registration and the WIRE reply (the tree does not exist yet —
        ≈ orted's callback to mpirun, orted_main.c)."""
        host, port = hnp_uri.rsplit(":", 1)
        sock = socket.create_connection((host, int(port)))
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        link = _Link(sock)
        link.send(dss.pack(("hello", self.vpid)))
        self._spawn_reader(link, 0)
        return link

    def dial_children(self, children: list[tuple[int, str]]) -> None:
        """Parent side: connect the down-links (the routed overlay edges)."""
        for cvpid, curi in children:
            host, port = curi.rsplit(":", 1)
            sock = socket.create_connection((host, int(port)))
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            link = _Link(sock)
            link.send(dss.pack(("hello", self.vpid)))
            with self._lock:
                self._child_links[cvpid] = link
            self._spawn_reader(link, cvpid)

    def wait_parent(self, timeout: float) -> bool:
        """Block until the tree parent has dialed in (the up-link exists).

        The WIRE handler must call this before replying DAEMON_READY: WIRE
        arrives over the bootstrap link, but the reply rides the tree —
        and the parent's dial may still be in flight.
        """
        return self.parent_wired.wait(timeout)

    def retarget_parent(self, new_parent: int) -> None:
        """Re-parenting: expect ``new_parent``'s hello as my new up-link.

        If the adopter already dialed in (its hello raced the HNP's
        TAG_REPARENT order), the pending link is promoted immediately;
        otherwise ``parent_wired`` clears until the hello arrives.
        """
        with self._lock:
            self.parent_vpid = new_parent
            link = self._pending_hellos.pop(new_parent, None)
            if link is None:
                self.parent_wired.clear()
            else:
                self._parent_link = link
                self.parent_wired.set()

    # -- traffic ----------------------------------------------------------

    def xcast(self, tag: str, payload: Any) -> None:
        """Deliver everywhere below me (incl. locally) — grpcomm xcast.

        Relay BEFORE local delivery: a handler may tear this node down
        (SHUTDOWN sets _done → close()), and relaying first guarantees the
        children got the message before our links can vanish.
        """
        if not self._gate("out", tag):
            return
        self._relay_down(tag, self.vpid, payload)
        self._deliver(tag, self.vpid, payload)

    def _gate(self, direction: str, tag: str) -> bool:
        gate = self.frame_gate
        if gate is None:
            return True
        try:
            return bool(gate(direction, tag))
        except Exception:  # noqa: BLE001 — a broken gate must not wedge the bus
            return True

    def send_up(self, tag: str, payload: Any) -> None:
        """Deliver at the HNP, relaying through the tree (or, while
        orphaned, over the bootstrap fallback link)."""
        if not self._gate("out", tag):
            return
        if self.vpid == 0:
            self._deliver(tag, 0, payload)
            return
        self._send_up_blob(_pack_env("up", tag, self.vpid, payload))

    def _send_up_blob(self, blob: bytes) -> None:
        """One pre-framed "up" message toward the HNP: the tree parent
        when wired, else the bootstrap fallback (re-parenting window —
        exit reports and heartbeats must survive an orphaned stretch)."""
        link = self._parent_link
        if link is not None and self.parent_wired.is_set():
            try:
                link.send(blob)
                return
            except OSError:
                pass  # parent just died — try the fallback below
        fb = self.fallback_up
        if fb is not None:
            fb.send(blob)
            return
        raise ConnectionError("rml: no parent link (not wired yet)")

    def send_direct(self, link: _Link, tag: str, payload: Any) -> None:
        """Bootstrap-only: a message over an explicit link (HNP replies to
        a registration before the tree exists)."""
        if not self._gate("out", tag):
            return
        link.send(_pack_env("direct", tag, self.vpid, payload))

    def send_child(self, vpid: int, tag: str, payload: Any) -> bool:
        """One message DOWN a single tree edge (or, at the HNP, down a
        bootstrap link) — the reply path for per-hop request/response
        exchanges like the TAG_CLOCK pingpong, where xcast (every
        descendant) and send_direct (caller must hold the link) both
        fit badly.  Returns False when no live link to ``vpid`` exists
        (the prober times out and retries — clock probes are lossy by
        design)."""
        if not self._gate("out", tag):
            return False
        with self._lock:
            link = self._child_links.get(vpid) or self.boot_links.get(vpid)
        if link is None:
            return False
        try:
            link.send(_pack_env("direct", tag, self.vpid, payload))
            return True
        except OSError:
            return False

    def send_hop(self, tag: str, payload: Any) -> None:
        """One tree level toward the root, DELIVERED at the receiving
        hop (unlike ``send_up``, which relays silently until vpid 0).
        The per-hop aggregation primitive: a mid-tree daemon's handler
        merges the payload and later forwards its own combined message —
        how TAG_METRICS folds a subtree's pvar deltas on the way up."""
        if not self._gate("out", tag):
            return
        if self.vpid == 0:
            self._deliver(tag, 0, payload)
            return
        self._send_up_blob(_pack_env("hop", tag, self.vpid, payload))

    def _relay_down(self, tag: str, origin: int, payload: Any) -> None:
        with self._lock:
            links = list(self._child_links.values())
        blob = _pack_env("xcast", tag, origin, payload)
        for link in links:
            try:
                link.send(blob)
            except OSError as e:
                _log.error("rml %d: xcast relay failed: %r", self.vpid, e)

    def _deliver(self, tag: str, origin: int, payload: Any,
                 tc: Any = None) -> None:
        if tc is not None:
            _note_recv(tag, tc)
        with self._lock:
            cb = self._handlers.get(tag)
        if cb is None:
            _log.verbose(1, "rml %d: no handler for tag %r", self.vpid, tag)
            return
        try:
            cb(origin, payload)
        except Exception as e:
            _log.error("rml %d: handler %r failed: %r", self.vpid, tag, e)

    # -- link management --------------------------------------------------

    def _accept_loop(self) -> None:
        try:
            self._listener.settimeout(0.2)
        except OSError:
            return
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._spawn_reader(_Link(conn), None)

    def _spawn_reader(self, link: _Link, peer: Optional[int]) -> None:
        t = threading.Thread(target=self._read_loop, args=(link, peer),
                             daemon=True)
        t.start()
        self._threads.append(t)

    def _read_loop(self, link: _Link, peer: Optional[int]) -> None:
        sock = link.sock
        with sock:
            while not self._stop.is_set():
                try:
                    blob = _recv_frame(sock)
                except OSError:
                    # an abrupt peer death arrives as an RST
                    # (ECONNRESET) — or EBADF when the peer's close()
                    # races this recv — not a clean FIN.  Either way
                    # the link is gone: take the same EOF path, so
                    # on_peer_lost fires instead of the reader dying
                    blob = None
                if blob is None:
                    break
                msg = dss.unpack(blob, n=1)[0]
                kind = msg[0]
                if kind == "hello":
                    peer = msg[1]
                    # an accepted hello from my expected parent IS my
                    # up-link; at the HNP an accepted hello is a bootstrap
                    # link; anything else is kept pending — a racing
                    # adopter whose TAG_REPARENT order is still in flight
                    with self._lock:
                        if self.parent_vpid == peer:
                            self._parent_link = link
                            self.parent_wired.set()
                        elif self.vpid != 0:
                            self._pending_hellos[peer] = link
                        if self.vpid == 0:
                            self.boot_links[peer] = link
                    continue
                tag, origin, payload = msg[1], msg[2], msg[3]
                if not self._gate("in", tag):
                    continue  # partitioned: the frame never arrived
                # instrumented senders append a (trace_id, span_id)
                # envelope stamp; plain 4-tuples stay the common case
                tc = msg[4] if len(msg) > 4 else None
                if kind == "xcast":
                    # relay first — see xcast() on the SHUTDOWN/close race
                    self._relay_down(tag, origin, payload)
                    self._deliver(tag, origin, payload, tc)
                elif kind == "up":
                    if self.vpid == 0:
                        self._deliver(tag, origin, payload, tc)
                    else:
                        try:
                            self._send_up_blob(blob)
                        except (ConnectionError, OSError) as e:
                            _log.error("rml %d: up relay failed: %r",
                                       self.vpid, e)
                elif kind == "hop":
                    # one-level message: deliver HERE (the handler owns
                    # any further forwarding — per-hop merge semantics)
                    self._deliver(tag, origin, payload, tc)
                elif kind == "direct":
                    self._deliver(tag, origin, payload, tc)
                else:
                    _log.error("rml %d: unknown kind %r", self.vpid, kind)
        if peer is not None and not self._stop.is_set():
            # prune the dead link so xcast relays and adoptions never
            # write into a corpse (a re-parented tree re-adds live edges)
            with self._lock:
                if self._child_links.get(peer) is link:
                    del self._child_links[peer]
                if self._pending_hellos.get(peer) is link:
                    del self._pending_hellos[peer]
            cb = self.on_peer_lost
            if cb is not None:
                try:
                    cb(peer)
                except Exception as e:
                    _log.error("rml %d: peer-lost cb failed: %r",
                               self.vpid, e)

    def close(self) -> None:
        self._stop.set()
        try:  # wake a blocked accept() so the thread exits (see _Link)
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        with self._lock:
            links = list(self._child_links.values())
            self._child_links.clear()
            links += list(self.boot_links.values())
            self.boot_links.clear()
            links += list(self._pending_hellos.values())
            self._pending_hellos.clear()
        if self._parent_link is not None:
            links.append(self._parent_link)
        if self.fallback_up is not None:
            # the daemon-side bootstrap link: closing it is what gives
            # the HNP a prompt boot-link EOF for a dying daemon — a
            # LEAF daemon has no live children to report it orphaned,
            # so without this its death waits on heartbeat silence
            links.append(self.fallback_up)
        for link in links:
            link.close()


class HeartbeatMonitor:
    """HNP-side liveness watchdog over the daemon heartbeats.

    ≈ the sensor/heartbeat component of the reference: link EOF already
    catches clean daemon death (TCP RST), but a SIGSTOP'd daemon, a hung
    host, or a half-open connection across a network partition stays
    silent with the socket alive.  When ``rml_heartbeat_period`` > 0 each
    orted beats :data:`TAG_HEARTBEAT` up the tree; this monitor declares
    any watched vpid dead after ``rml_heartbeat_timeout`` seconds of
    silence and fires ``on_silent(vpid)`` exactly once per vpid.

    The expiry sweep is incremental: every beat pushes a ``(beat_ts,
    vpid)`` entry on a min-heap and the tick pops only entries older
    than the timeout, lazily discarding ones a fresher beat superseded
    — a tick on a 1000-daemon world costs O(expired), not O(world).
    Each beat's entry is examined exactly once (when it ages past the
    timeout), so the heap is bounded by the beats of one timeout window.
    Two more fleet-survival hooks: :meth:`set_world` scales the
    effective timeout with world size (see :func:`scaled_timeout`) and
    :meth:`grace` suspends declarations for a bounded stretch — the PLM
    arms it around a batched reparent wave so survivors busy re-wiring
    are not declared dead mid-adoption (deferred entries re-arm with a
    fresh window; a daemon that stays silent after the grace is still
    declared).
    """

    def __init__(self, on_silent: Callable[[int], None]) -> None:
        self.on_silent = on_silent
        self._last: dict[int, float] = {}
        self._declared: set[int] = set()
        self._heap: list[tuple[float, int]] = []  # (beat_ts, vpid), lazy
        self._grace_until = 0.0
        self._world = 0
        #: sweep telemetry: heap entries examined / sweeps run — what the
        #: per-tick-cost unit test asserts against
        self.scanned_total = 0
        self.ticks_total = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def watch(self, vpid: int) -> None:
        """Start expecting beats from ``vpid`` (clock starts now)."""
        self.beat(vpid)

    def beat(self, vpid: int) -> None:
        """A heartbeat (or any sign of life) arrived from ``vpid``."""
        now = time.monotonic()
        with self._lock:
            self._last[vpid] = now
            heapq.heappush(self._heap, (now, vpid))

    def set_world(self, world: int) -> None:
        """Declare the world size (daemons + HNP) so the effective
        timeout scales with routing-tree depth."""
        with self._lock:
            self._world = int(world)

    def grace(self, seconds: float) -> None:
        """Suspend dead-declarations until ``seconds`` from now (extends,
        never shortens, an active grace window)."""
        until = time.monotonic() + float(seconds)
        with self._lock:
            self._grace_until = max(self._grace_until, until)

    def ages(self) -> dict[int, float]:
        """Seconds since each watched vpid's last beat (the /status
        last-heartbeat-age column; empty when heartbeats are off)."""
        now = time.monotonic()
        with self._lock:
            return {vpid: max(0.0, now - last)
                    for vpid, last in self._last.items()}

    def effective_timeout(self) -> float:
        """The declare threshold actually in force: the configured (and
        2x-period-clamped) timeout, world-scaled."""
        period = float(var_registry.get("rml_heartbeat_period") or 0)
        timeout = float(var_registry.get("rml_heartbeat_timeout") or 0)
        timeout = max(timeout, 2 * period)
        with self._lock:
            world = self._world or (len(self._last) + 1)
        return scaled_timeout(timeout, world)

    def start(self) -> None:
        period = float(var_registry.get("rml_heartbeat_period") or 0)
        if period <= 0 or self._thread is not None:
            return
        timeout = float(var_registry.get("rml_heartbeat_timeout") or 0)
        if timeout < 2 * period:
            # a timeout shorter than two beat intervals declares every
            # HEALTHY daemon dead between beats — clamp rather than
            # letting a plausible-looking config abort the job
            _log.verbose(0, "heartbeat: timeout %.2fs < 2x period %.2fs; "
                         "clamping to %.2fs", timeout, period, 2 * period)
        self._thread = threading.Thread(target=self._run, name="rml-hb-mon",
                                        daemon=True)
        self._thread.start()

    def _sweep(self, now: float, timeout: float) -> list[int]:
        """One incremental expiry sweep: pop heap entries older than the
        timeout, declaring the vpids whose NEWEST beat that is.  Returns
        the newly silent vpids (callers fire ``on_silent`` outside the
        lock)."""
        cutoff = now - timeout
        silent: list[int] = []
        with self._lock:
            self.ticks_total += 1
            grace = self._grace_until
            while self._heap and self._heap[0][0] <= cutoff:
                ts, vpid = heapq.heappop(self._heap)
                self.scanned_total += 1
                last = self._last.get(vpid)
                if last is None or vpid in self._declared or last > ts:
                    continue  # unwatched / already declared / stale entry
                if now < grace:
                    # reparent-wave grace: re-arm with a fresh window
                    # instead of declaring — still-silent daemons expire
                    # one timeout after the deferral
                    self._last[vpid] = now
                    heapq.heappush(self._heap, (now, vpid))
                    continue
                self._declared.add(vpid)
                silent.append(vpid)
        return silent

    def _run(self) -> None:
        period = float(var_registry.get("rml_heartbeat_period") or 0)
        # check at the beat cadence; declare at the (world-scaled) timeout
        while not self._stop.wait(max(0.05, period / 2)):
            timeout = self.effective_timeout()
            for vpid in self._sweep(time.monotonic(), timeout):
                _log.error("heartbeat: vpid %d silent for >%.1fs; "
                           "declaring it dead", vpid, timeout)
                try:
                    self.on_silent(vpid)
                except Exception as e:  # noqa: BLE001 — watchdog survives
                    _log.error("heartbeat: on_silent(%d) failed: %r",
                               vpid, e)

    def stop(self) -> None:
        self._stop.set()


def start_heartbeats(node: RmlNode, stop: threading.Event) -> None:
    """Daemon side: beat TAG_HEARTBEAT up the tree every
    ``rml_heartbeat_period`` seconds until ``stop`` is set (no thread is
    spawned when the period is 0)."""
    period = float(var_registry.get("rml_heartbeat_period") or 0)
    if period <= 0:
        return

    def beater() -> None:
        while not stop.wait(period):
            try:
                node.send_up(TAG_HEARTBEAT, node.vpid)
            except ConnectionError:
                return  # tree torn down; the lifeline path handles it

    threading.Thread(target=beater, name=f"rml-hb-{node.vpid}",
                     daemon=True).start()
