"""FT event timeline — the structured record of the failure machinery.

Every rung of the errmgr/selfheal ladder (detect, reap, revive, shrink,
escalate, abort) and the containment plane (daemon loss, re-parenting)
records one structured event here, stamped with wall-clock, monotonic
time, jobid, rank and incarnation — so a kill-storm is readable AFTER
the fact: the DVM serves the log per job on its ``/status`` endpoint,
and each event doubles as a flight-recorder instant (category
``errmgr``) when tracing is armed.

The log is a bounded ring (oldest events fall off first, like the trace
ring) and lives in the launcher/HNP process — the only place every
detection source converges.  Recording is lock-cheap (one deque append
under a lock) and must stay non-blocking: several record sites run on
RML link reader threads (see the ``reader-thread`` lint checker).
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Optional

__all__ = ["FtEventLog", "log", "record", "KINDS"]

#: the event vocabulary — the ladder rungs, the containment plane, and
#: the hang-doctor plane ("stuck" = a rank's watchdog crossed
#: coll_stuck_timeout; "doctor" = a cross-rank capture produced a
#: verdict)
#: ``coll_rejoin`` = a rank's epoch-fenced coll-hierarchy rebuild after
#: a selfheal revive landed (pushed by the rank via the one-way PMIx
#: "coll_rejoin" RPC — the rejoin half of the revive cycle)
#: ``remediate`` = the DVM's doctor-driven remediation actor acted on a
#: watchdog verdict (SIGCONT probe / reap-and-revive / kill+requeue /
#: budget-exhausted reject); ``requeue`` = a remediated job went back on
#: the admission queue for a fresh placement
#: ``truncated`` = a synthetic marker the ring PREPENDS to snapshots
#: once capacity eviction has discarded events — truncation is explicit,
#: never silent (the marker's info.dropped counts the forgotten events)
KINDS = ("detect", "reap", "revive", "shrink", "escalate", "abort",
         "daemon_lost", "reparent", "finished", "stuck", "doctor",
         "coll_rejoin", "remediate", "requeue", "truncated")


class FtEventLog:
    """Bounded, thread-safe timeline of FT events."""

    def __init__(self, capacity: int = 1024) -> None:
        self._lock = threading.Lock()
        self._events: collections.deque = collections.deque(
            maxlen=max(16, capacity))
        self._n = 0
        self._dropped = 0   # events the ring evicted (capacity)

    def record(self, kind: str, jobid: int = 0, rank: int = -1,
               lives: int = 0, **info: Any) -> dict:
        """Append one event; returns the record (tests/tools read it).
        Also emits an ``errmgr`` trace instant when tracing is armed, so
        the merged Perfetto timeline shows the FT plane inline with the
        transport spans."""
        ev = {
            "seq": 0,                     # stamped under the lock below
            "wall": time.time(),
            "mono_ns": time.monotonic_ns(),
            "kind": kind,
            "jobid": int(jobid),
            "rank": int(rank),
            "lives": int(lives),
        }
        if info:
            ev["info"] = {k: v for k, v in info.items() if v is not None}
        with self._lock:
            self._n += 1
            ev["seq"] = self._n
            if len(self._events) == self._events.maxlen:
                self._dropped += 1   # the append below evicts the oldest
            self._events.append(ev)
        from ompi_tpu.mpi import trace as trace_mod

        if trace_mod.active:
            trace_mod.instant("errmgr", f"ft:{kind}", rank=rank,
                              jobid=jobid, lives=lives,
                              **(ev.get("info") or {}))
        return ev

    def snapshot(self, jobid: Optional[int] = None) -> list[dict]:
        """Events oldest-first, optionally filtered to one job (events
        recorded with jobid 0 — pre-job containment noise — ride along
        with every job filter: a daemon loss belongs to any timeline
        that overlaps it).  Once capacity eviction has forgotten events,
        every snapshot leads with an explicit ``truncated`` marker
        (jobid 0, so it survives any job filter) naming how many — a
        reader must never mistake a clipped timeline for a complete
        one."""
        with self._lock:
            events = list(self._events)
            dropped = self._dropped
        if jobid is not None:
            events = [e for e in events
                      if e["jobid"] == int(jobid) or e["jobid"] == 0]
        if dropped:
            events.insert(0, {
                "seq": 0, "wall": 0.0, "mono_ns": 0,
                "kind": "truncated", "jobid": 0, "rank": -1, "lives": 0,
                "info": {"dropped": dropped,
                         "detail": f"ring evicted {dropped} older "
                                   f"event(s); timeline is a tail"}})
        return events

    def total(self) -> int:
        """Events ever recorded (including those the ring forgot)."""
        with self._lock:
            return self._n

    def dropped(self) -> int:
        """Events the bounded ring evicted (0 = the snapshot is the
        complete history)."""
        with self._lock:
            return self._dropped

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self._dropped = 0


#: process-global log (the launcher/HNP is one process; tests may make
#: their own FtEventLog instances)
log = FtEventLog()


def record(kind: str, jobid: int = 0, rank: int = -1, lives: int = 0,
           **info: Any) -> dict:
    """Record one FT event on the process-global timeline."""
    return log.record(kind, jobid=jobid, rank=rank, lives=lives, **info)
