"""Errmgr — failure response policy.

≈ orte/mca/errmgr (errmgr.h:87-136; default_hnp behavior at
errmgr_default_hnp.c:351-470: on proc abort / comm failure, terminate the
job).  Components decide what a proc-failure event does:

- ``abort``    — default: first failure kills every remaining proc and the
  job exits with the failed proc's status (mpirun's default).  The
  teardown is SIGTERM → ``launcher_kill_grace_s`` → SIGKILL; ranks
  running with the flight recorder armed (``tpurun --trace`` /
  ``OMPI_TPU_TRACE=1``) flush their trace ring to
  ``$TMPDIR/ompi_tpu_trace_<jobid>_rank<r>.json`` from that SIGTERM, so
  an aborted job leaves a per-rank timeline behind for post-mortem
  (merge with ``tools/trace_export.py``).
- ``continue`` — log and keep going.
- ``notify``   — keep going AND propagate the failure to the survivors
  (PMIx dead-set + TAG_PROC_FAILED xcast + notifier event) so they can
  run user-level recovery: ``Comm.revoke()/shrink()/agree()`` from
  ``ompi_tpu.mpi.ft`` — the ULFM shrink-and-continue recipe.  On the
  daemon tree, notify additionally arms mid-tree re-parenting: a
  non-leaf orted's death no longer tears down its subtree via the
  lifeline rule — the orphaned child daemons re-wire to the nearest
  live ancestor (TAG_REPARENT handshake, HNP arbitrating), confining
  the loss to the dead host's ranks.
- ``respawn``  — revive the failed rank in place up to
  ``errmgr_max_restarts`` times (≈ rmaps/resilient + the errmgr restart
  paths): same rank and env plus ``OMPI_TPU_RESTART=<n>`` so the app can
  restore from its last ``ckpt`` snapshot (+ msglog replay for in-flight
  p2p) instead of recomputing from step 0.  Select with
  ``--mca errmgr respawn``.  Works in both launchers (local fork/exec and
  the orted daemon tree via TAG_RESPAWN).

  Scope: respawn is a HOST-plane recovery.  A job using the multi-host
  DEVICE plane (jax.distributed) cannot revive a member in place — the
  coordination service rejects a reconnecting incarnation and its
  heartbeat failure poisons every surviving task — so device-plane jobs
  recover by full-job restart from the ``ckpt`` snapshots (run respawn
  jobs with ``--mca multihost_auto_init 0``).

- ``selfheal`` — the fused self-healing policy: respawn's revival and
  notify's propagation stop being separate worlds.  Every detection
  source the runtime has — the launcher exit reap, the daemon heartbeat
  monitor, rank-plane gossip (``report_failed`` → the hung pid is
  SIGKILLed), the coll/shm arena writer probe — lands here and runs the
  full cycle: the death is propagated to the survivors FIRST (dead-set
  reason + ``TAG_PROC_FAILED`` xcast, so their detectors fail pending
  ops fast instead of stalling), then the rank is revived in place
  through ``respawn_proc`` with ``OMPI_TPU_RESTART`` (snapshot restore
  via ``ckpt.snapc.auto_restore`` + msglog replay for the in-flight
  gap), survivors' detectors flip the peer back alive (the revive
  listeners), and **incarnation numbers** carried in PML data frames
  (``ep``/``si``) and FT control frames (``de``/``si``) fence stale
  traffic from the dead life out of the new one.

  Failure response is a LADDER, not a cliff — the policy degrades in
  order::

      revive  →  notify/shrink  →  abort

  The revive arm is crash-loop gated (shared with plain respawn): a
  revive only counts as successful once the rank stays up
  ``errmgr_min_uptime_s``, measured from the life's PMIx registration
  (boot excluded) — an instant re-death, or a death before the life
  ever registered, burns one ``errmgr_max_restarts`` slot *with
  exponential backoff* (the budget cannot drain in milliseconds),
  while a later death resets the budget (the revive worked).  The
  budget reset never touches the incarnation: ``proc.lives`` — the
  number survivors adopt and the fence compares — is monotone across
  resets, so a rank whose budget was earned back still announces a
  strictly higher life than any the survivors have seen.  A revived
  life that wedges *during* boot (never registers) is re-reapable: the
  PMIx server accepts failure reports about it regardless of their
  incarnation stamp after ``pmix_register_grace_s``.  When the budget is exhausted, the rank is
  unrevivable (no ``respawn_proc`` hook, or its daemon died with its
  host), or a revive fails to start, the policy degrades to the notify
  rung: the already-propagated death stands, survivors continue
  smaller (the ULFM shrink recipe applies).  Only when shrink is
  impossible — no survivors left to carry the job, or no control plane
  to propagate through — does it fall to the last rung and abort.
  ``errmgr_selfheal_{revives,escalations}_total`` count the cycle in
  the flight recorder.  Select with ``--mca errmgr selfheal``.

  The rejoin covers COLLECTIVES, not just the p2p plane: survivors
  fence every cached collective artifact (coll/shm node splits +
  arena, pinned persistent slots) on the per-communicator coll epoch
  (``mpi.ft.comm_coll_epoch`` — the sum of adopted incarnations), so
  the first dispatch after adopting the revived life tears the old
  hierarchy down and rebuilds it with the new life included, and
  persistent plans auto-``rebind()`` on their next Start.  The revived
  rank's ``coll_rejoin`` FT-timeline events (PMIx ``coll_rejoin`` RPC)
  and the ``rejoins`` column on ``--dvm-ps`` make the rejoin half
  observable; ``tools/chaos_soak.py --only selfheal-coll`` proves it
  end-to-end (kill *inside* a collective via ``kill@coll=N``).

Thread-context rules (machine-checked by ``tools/lint``): errmgr hooks
fire from rml ``register_recv`` callbacks and the PMIx server's
``on_failed_report``/``on_client_contact`` — link reader threads and
server connection threads respectively.  The ``reader-thread`` checker
classifies everything reachable from those callbacks and fails on
blocking PMIx RPCs, ``time.sleep``, and ``subprocess`` calls on the
path; the ``lock-order`` checker additionally fails on lock-acquisition
cycles and on blocking work under any reader-shared lock.  Keep new
detection→reaction paths non-blocking (queue + drain from a worker, the
way ``PmlFT._adopt_notify`` defers its RPC to the gossip loop) or the
lint gate in CI will name the offending call chain.
"""

from __future__ import annotations

import time
from typing import TYPE_CHECKING

from ompi_tpu.core import output
from ompi_tpu.core.config import VarType, register_var, var_registry
from ompi_tpu.core.mca import Component, Framework
from ompi_tpu.runtime.job import Job, Proc, ProcState

if TYPE_CHECKING:
    from ompi_tpu.runtime.launcher import LocalLauncher

__all__ = ["errmgr_framework", "ErrmgrAbort", "ErrmgrRespawn",
           "ErrmgrContinue", "ErrmgrNotify", "ErrmgrSelfheal"]

_log = output.get_stream("errmgr")

errmgr_framework = Framework("errmgr", "failure response policy")

register_var("errmgr", "max_restarts", VarType.SIZE, 2,
             "errmgr respawn/selfheal: revive a failed rank at most this "
             "many times before degrading (respawn: job abort; selfheal: "
             "the notify/shrink rung).  The budget counts CRASH-LOOP "
             "revivals: a rank that stays up errmgr_min_uptime_s earns "
             "its budget back")
register_var("errmgr", "min_uptime_s", VarType.DOUBLE, 5.0,
             "crash-loop gate for the reviving policies (respawn, "
             "selfheal): a revive only counts as successful once the "
             "rank stays up this long, measured from the life's PMIx "
             "registration (interpreter+jax boot does not count — a "
             "rank that crashes deterministically right after boot "
             "cannot earn its budget back).  An earlier re-death — or a "
             "death before the life ever registered — burns one "
             "errmgr_max_restarts slot with exponential backoff before "
             "the next revive (instant-death loops cannot drain the "
             "budget in milliseconds); a later death resets the budget. "
             "0 disables the gate: classic budget semantics — every "
             "revive counts against errmgr_max_restarts, no reset, no "
             "backoff")


def apply_host_plane_policy(errmgr, env: dict, *base_envs: dict) -> None:
    """Any REVIVING errmgr policy (``REVIVES`` — respawn, selfheal) is
    HOST-plane recovery: a revived rank cannot rejoin the coordination
    service, and survivors' jax.distributed threads then pin their
    processes at exit (a post-finalize spin).  The policy implies the
    plane — when a reviving policy is selected, launch app processes
    device-plane-off unless the user set the var explicitly (in ``env``
    or any of ``base_envs``)."""
    from ompi_tpu.core.config import var_registry

    if not getattr(errmgr, "REVIVES", False):
        return
    key = var_registry.ENV_PREFIX + "multihost_auto_init"
    if any(key in e for e in (env, *base_envs)):
        return
    env[key] = "0"


def _propagate_failure(launcher, job: Job, proc: Proc,
                       reason: str) -> None:
    """The notify rung shared by ErrmgrNotify and ErrmgrSelfheal: put the
    human-readable reason on the runtime dead-set (idempotent — the reap
    loop already called ``proc_died``) and flood a TAG_PROC_FAILED xcast
    down the daemon tree so every host's record shows which rank died.
    The dead-set lives on the JOB's rendezvous when the launcher runs
    per-job PMIx servers (multi-tenant DVM); ``launcher.server`` remains
    the fallback for single-job and custom launchers."""
    server = (getattr(job, "pmix_server", None)
              or getattr(launcher, "server", None))
    if server is not None:
        server.proc_died(proc.rank, reason=reason)
    node = getattr(launcher, "rml", None)
    if node is not None:
        from ompi_tpu.runtime import rml as rml_mod

        try:
            node.xcast(rml_mod.TAG_PROC_FAILED, (proc.rank, reason))
        except Exception as e:  # noqa: BLE001 — tree may be tearing down
            _log.error("failure propagation: TAG_PROC_FAILED xcast "
                       "failed: %r", e)


def _rank_span(ranks: list, head: int = 16) -> str:
    """A bounded textual rank list for batch events — a 160-rank rack
    loss must not inline 160 numbers into every log/notifier line."""
    ranks = sorted(int(r) for r in ranks)
    if len(ranks) <= head:
        return ",".join(map(str, ranks))
    return (",".join(map(str, ranks[:head]))
            + f",...(+{len(ranks) - head} more)")


def _propagate_failure_batch(launcher, job: Job, procs: list,
                             reason: str) -> None:
    """The batched twin of :func:`_propagate_failure` for correlated
    daemon loss: a rack death takes tens of ranks in one tick, and
    per-rank propagation turns that into N full-tree xcasts — its own
    control-plane storm.  The dead-set is already updated per rank (the
    PLM's _fail_daemon_ranks called ``proc_died`` before any policy
    ran), so ONE xcast carrying the whole rank batch suffices; the
    orted handler accepts a rank list in the rank slot."""
    node = getattr(launcher, "rml", None)
    if node is None or not procs:
        return
    from ompi_tpu.runtime import rml as rml_mod

    try:
        node.xcast(rml_mod.TAG_PROC_FAILED,
                   ([p.rank for p in procs], reason))
    except Exception as e:  # noqa: BLE001 — tree may be tearing down
        _log.error("failure propagation: batched TAG_PROC_FAILED xcast "
                   "failed: %r", e)


#: test seam: the backoff sleep (patched by unit tests).  The sleep runs
#: INSIDE proc_failed — on the local launcher's reap loop, or the
#: daemon link's RML reader thread — deliberately: deferring the revive
#: to a timer would race the reap loop's exit (a job whose last pending
#: rank is mid-backoff would be accounted done with the revive dropped).
#: The stall is bounded by _BACKOFF_CAP and only ever paid by a rank
#: that is actively crash-looping.
_sleep = time.sleep

#: first crash-loop revive backoff; doubles per instant re-death
_BACKOFF_BASE = 0.5
#: cap — a rank stuck in a crash loop is probed at most this often
_BACKOFF_CAP = 5.0


class _RestartGovernor:
    """Crash-loop gating shared by the reviving policies (respawn,
    selfheal): min-uptime success accounting + exponential revive
    backoff.  A revive counts as successful only once the rank stayed up
    ``errmgr_min_uptime_s`` — then the ``errmgr_max_restarts`` budget
    resets.  An instant re-death keeps the budget burn and returns a
    (doubling, capped) delay the policy sleeps before the next revive,
    so a crash loop drains the budget over seconds, not milliseconds."""

    def __init__(self) -> None:
        self._backoff: dict[tuple[int, int], float] = {}

    def pre_revive_delay(self, job: Job, proc: Proc) -> float:
        """Classify this death; returns the backoff (seconds) to sleep
        before reviving — 0.0 for a first death or an earned-uptime one
        (which also resets ``proc.restarts``).  Uptime is measured from
        the life's PMIx registration (``launched_at`` is stamped by the
        server's ``reg`` hook, not at fork), so a slow interpreter boot
        cannot earn the budget back; a life that died *before* ever
        registering (``launched_at is None``) is the crash-loopiest case
        of all and always burns a slot.  Only the budget counter resets
        here — ``proc.lives`` (the incarnation survivors adopted) is
        monotone and untouched."""
        key = (job.jobid, proc.rank)
        min_up = float(var_registry.get("errmgr_min_uptime_s") or 0)
        if min_up <= 0.0:
            # gate disabled: CLASSIC budget semantics — every revive
            # counts against errmgr_max_restarts, no reset, no backoff.
            # (Treating every death as "earned" instead would reset the
            # budget forever and revive a deterministic crasher in a
            # tight loop that never reaches the degrade rung.)
            self._backoff.pop(key, None)
            return 0.0
        up = (None if proc.launched_at is None
              else time.monotonic() - proc.launched_at)
        earned = up is not None and up >= min_up
        if proc.restarts == 0 or earned:
            if proc.restarts:
                _log.verbose(1, "rank %d ran %.1fs (>= min_uptime %.1fs); "
                             "restart budget reset", proc.rank,
                             up if up is not None else -1.0, min_up)
                proc.restarts = 0
            self._backoff.pop(key, None)
            return 0.0
        delay = self._backoff.get(key, _BACKOFF_BASE)
        self._backoff[key] = min(delay * 2, _BACKOFF_CAP)
        return min(delay, self._max_reader_stall())

    @staticmethod
    def _max_reader_stall() -> float:
        """On a daemon tree the backoff sleep runs on the RML link
        reader thread (see the ``_sleep`` note): a stall at or above
        ``rml_heartbeat_timeout`` would starve TAG_HEARTBEAT delivery
        queued behind it and the HNP would declare the healthy daemon
        hosting the crash-looping rank lost — failing every rank on
        that host.  With heartbeats armed, cap the sleep well below the
        declare timeout.  ``lookup`` rather than ``get``: a purely
        local run may never import rml, so the vars may be
        unregistered."""
        period = var_registry.lookup("rml_heartbeat_period")
        if period is None or float(period.value or 0) <= 0:
            return _BACKOFF_CAP
        timeout = var_registry.lookup("rml_heartbeat_timeout")
        if timeout is None or float(timeout.value or 0) <= 0:
            return _BACKOFF_CAP
        return min(_BACKOFF_CAP, 0.4 * float(timeout.value))


@errmgr_framework.component
class ErrmgrAbort(Component):
    NAME = "abort"
    PRIORITY = 10

    def proc_failed(self, launcher: "LocalLauncher", job: Job, proc: Proc) -> None:
        from ompi_tpu.runtime import ftevents

        if job.aborted_proc is None:
            job.aborted_proc = proc
            job.abort_reason = (
                f"rank {proc.rank} {proc.state.value} "
                f"(exit code {proc.exit_code})")
        ftevents.record("abort", jobid=job.jobid, rank=proc.rank,
                        lives=proc.lives, exit_code=proc.exit_code)
        _log.verbose(1, "aborting job %d: %s", job.jobid, job.abort_reason)
        launcher.kill_job(job, exclude=proc)


@errmgr_framework.component
class ErrmgrRespawn(Component):
    """Revive failed ranks in place (≈ errmgr restart + rmaps/resilient,
    errmgr_default_hnp.c:351-470's ORTE_PROC_STATE_RESTART arm).  Crash
    loops are gated by the shared governor: instant re-deaths burn the
    ``errmgr_max_restarts`` budget with exponential backoff, and a rank
    that stayed up ``errmgr_min_uptime_s`` earns its budget back."""

    NAME = "respawn"
    PRIORITY = 0    # opt-in via --mca errmgr respawn
    REVIVES = True

    def __init__(self) -> None:
        self._governor = _RestartGovernor()

    def proc_failed(self, launcher: "LocalLauncher", job: Job,
                    proc: Proc) -> None:
        from ompi_tpu.runtime import ftevents
        from ompi_tpu.runtime.notifier import Severity, notify

        ftevents.record("detect", jobid=job.jobid, rank=proc.rank,
                        lives=proc.lives, rung="respawn",
                        exit_code=proc.exit_code)
        limit = var_registry.get("errmgr_max_restarts")
        # both shipped launchers revive (local fork/exec + daemon tree via
        # TAG_RESPAWN); a custom launcher without the hook degrades to
        # abort instead of raising into its event dispatch
        respawn = getattr(launcher, "respawn_proc", None)
        if respawn is None:
            _log.error("errmgr/respawn: %s cannot revive ranks; aborting",
                       type(launcher).__name__)
        else:
            # may RESET proc.restarts (the previous revive earned its
            # min-uptime) — classify before the budget check
            delay = self._governor.pre_revive_delay(job, proc)
            if proc.restarts < limit:
                if delay:
                    _log.verbose(1, "rank %d re-died within "
                                 "errmgr_min_uptime_s; %.1fs backoff "
                                 "before revive %d/%d", proc.rank, delay,
                                 proc.restarts + 1, limit)
                    _sleep(delay)
                _log.verbose(1, "rank %d failed (exit %s); respawn %d/%d",
                             proc.rank, proc.exit_code, proc.restarts + 1,
                             limit)
                notify(Severity.WARN, "rank-respawn",
                       f"job {job.jobid} rank {proc.rank} exit "
                       f"{proc.exit_code}; restart "
                       f"{proc.restarts + 1}/{limit}")
                if respawn(job, proc):
                    return
                _log.error("rank %d respawn failed to start", proc.rank)
            else:
                _log.verbose(1, "rank %d exhausted %d restarts; aborting "
                             "job", proc.rank, limit)
        if job.aborted_proc is None:
            job.aborted_proc = proc
            job.abort_reason = (
                f"rank {proc.rank} {proc.state.value} after "
                f"{proc.restarts} restart(s) (exit code {proc.exit_code})")
        launcher.kill_job(job, exclude=proc)


@errmgr_framework.component
class ErrmgrContinue(Component):
    NAME = "continue"
    PRIORITY = 0

    def proc_failed(self, launcher: "LocalLauncher", job: Job, proc: Proc) -> None:
        _log.verbose(1, "rank %d failed (%s); continuing per policy",
                     proc.rank, proc.state.value)


@errmgr_framework.component
class ErrmgrNotify(Component):
    """ULFM-enabling policy: a rank death neither kills the job (abort)
    nor revives the rank (respawn) — it is *propagated* to the survivors
    so they can run user-level recovery (``Comm.revoke`` / ``shrink`` /
    ``agree``, mpi/ft.py):

    - the PMIx server's dead-set already holds the rank (the launcher
      calls ``proc_died`` before any policy runs), so survivors' failure
      detectors see it on their next poll and pending operations against
      the dead peer fail fast with MPI_ERR_PROC_FAILED;
    - on a daemon tree the failure additionally rides a TAG_PROC_FAILED
      xcast so every orted logs which rank died and why;
    - an admin notifier event records the death.

    Select with ``--mca errmgr notify``.  This is the policy behind the
    shrink-and-continue recipe (README "Fault tolerance").
    """

    NAME = "notify"
    PRIORITY = 0    # opt-in via --mca errmgr notify
    TOLERATES_DAEMON_LOSS = True

    def proc_failed(self, launcher: "LocalLauncher", job: Job,
                    proc: Proc) -> None:
        from ompi_tpu.runtime import ftevents
        from ompi_tpu.runtime.notifier import Severity, notify

        reason = (f"rank {proc.rank} {proc.state.value} "
                  f"(exit code {proc.exit_code})")
        _log.verbose(1, "notify policy: %s; propagating to survivors",
                     reason)
        ftevents.record("detect", jobid=job.jobid, rank=proc.rank,
                        lives=proc.lives, rung="notify", reason=reason)
        _propagate_failure(launcher, job, proc, reason)
        notify(Severity.WARN, "rank-failed",
               f"job {job.jobid} {reason}; survivors notified "
               f"(job continues)")

    def daemon_ranks_failed(self, launcher: "LocalLauncher", job: Job,
                            procs: list) -> None:
        """Correlated daemon loss, batched: ONE xcast / FT event /
        notifier event for the whole rack's worth of ranks — per-rank
        propagation would turn a 16-daemon loss into hundreds of
        full-tree control frames, a reparent-window storm of our own
        making.  The per-rank dead-set entries are already in place
        (the PLM recorded them before any policy ran)."""
        if not procs:
            return
        from ompi_tpu.runtime import ftevents
        from ompi_tpu.runtime.notifier import Severity, notify

        ranks = [p.rank for p in procs]
        reason = (f"{len(ranks)} rank(s) lost with their daemon(s): "
                  f"{_rank_span(ranks)}")
        _log.verbose(1, "notify policy: %s; propagating to survivors "
                     "(batched)", reason)
        ftevents.record("detect", jobid=job.jobid, rank=ranks[0],
                        rung="notify", reason=reason, count=len(ranks))
        _propagate_failure_batch(launcher, job, procs, reason)
        notify(Severity.WARN, "rank-failed",
               f"job {job.jobid} {reason}; survivors notified "
               f"(job continues)")


@errmgr_framework.component
class ErrmgrSelfheal(Component):
    """The fused self-healing policy: every failure runs the full
    detect → reap → revive → rejoin cycle, degrading down the ladder
    (revive → notify/shrink → abort) instead of falling off a cliff.
    See the module docstring for the full contract."""

    NAME = "selfheal"
    PRIORITY = 0    # opt-in via --mca errmgr selfheal
    REVIVES = True
    TOLERATES_DAEMON_LOSS = True

    def __init__(self) -> None:
        self._governor = _RestartGovernor()

    def proc_failed(self, launcher: "LocalLauncher", job: Job,
                    proc: Proc) -> None:
        from ompi_tpu.mpi import trace as trace_mod
        from ompi_tpu.runtime.notifier import Severity, notify

        reason = (f"rank {proc.rank} {proc.state.value} "
                  f"(exit code {proc.exit_code})")
        from ompi_tpu.runtime import ftevents

        ftevents.record("detect", jobid=job.jobid, rank=proc.rank,
                        lives=proc.lives, rung="selfheal", reason=reason)
        # rung 1 preamble is ALWAYS the notify propagation: survivors'
        # detectors learn the death now (pending ops toward the corpse
        # fail fast instead of stalling for the revive), and flip the
        # peer back alive when the revive lands (the revive listeners)
        _propagate_failure(launcher, job, proc, reason)
        limit = var_registry.get("errmgr_max_restarts")
        respawn = getattr(launcher, "respawn_proc", None)
        if proc.daemon_lost or proc.no_revive or respawn is None:
            why = ("its daemon died with its host" if proc.daemon_lost
                   else "a planned shrink retired it" if proc.no_revive
                   else f"{type(launcher).__name__} cannot revive ranks")
            self._escalate(launcher, job, proc,
                           f"rank {proc.rank} is not revivable ({why})")
            return
        # may RESET proc.restarts (min-uptime earned) — before the check
        delay = self._governor.pre_revive_delay(job, proc)
        if proc.restarts >= limit:
            self._escalate(launcher, job, proc,
                           f"rank {proc.rank} exhausted {limit} revive(s) "
                           f"within errmgr_min_uptime_s")
            return
        if delay:
            _log.verbose(1, "rank %d crash-looping; %.1fs backoff before "
                         "revive %d/%d", proc.rank, delay,
                         proc.restarts + 1, limit)
            _sleep(delay)
        t0 = trace_mod.begin() if trace_mod.active else 0
        notify(Severity.WARN, "rank-respawn",
               f"job {job.jobid} {reason}; selfheal revive "
               f"{proc.restarts + 1}/{limit}")
        if respawn(job, proc):
            trace_mod.count("errmgr_selfheal_revives_total")
            if t0 and trace_mod.active:
                # reap→revive half of the cycle; the revived rank's
                # runtime/init instant closes the rejoin half
                trace_mod.complete("errmgr", "selfheal_revive", t0,
                                   rank=proc.rank, restarts=proc.restarts,
                                   backoff=delay)
            return
        self._escalate(launcher, job, proc,
                       f"rank {proc.rank} revive failed to start")

    def daemon_ranks_failed(self, launcher: "LocalLauncher", job: Job,
                            procs: list) -> None:
        """Correlated daemon loss, batched.  Every victim is unrevivable
        (its daemon died with its host), so the whole batch takes the
        escalate-to-shrink rung in ONE decision: one propagation xcast,
        one FT event, one notifier event — not a per-rank storm of
        escalations during the exact window the tree is re-wiring."""
        if not procs:
            return
        from ompi_tpu.mpi import trace as trace_mod
        from ompi_tpu.runtime import ftevents
        from ompi_tpu.runtime.notifier import Severity, notify

        ranks = [p.rank for p in procs]
        reason = (f"{len(ranks)} rank(s) lost with their daemon(s): "
                  f"{_rank_span(ranks)}")
        ftevents.record("detect", jobid=job.jobid, rank=ranks[0],
                        rung="selfheal", reason=reason, count=len(ranks))
        _propagate_failure_batch(launcher, job, procs, reason)
        trace_mod.count("errmgr_selfheal_escalations_total")
        # victims are already ABORTED, so the carrier scan naturally
        # excludes the whole batch
        carriers = [p for p in job.procs if p.state
                    in (ProcState.RUNNING, ProcState.TERMINATED)]
        can_shrink = (bool(carriers)
                      and (getattr(job, "pmix_server", None)
                           or getattr(launcher, "server", None))
                      is not None)
        why = (f"{len(ranks)} rank(s) are not revivable (their daemon "
               f"died with its host)")
        ftevents.record("escalate", jobid=job.jobid, rank=ranks[0],
                        to="shrink" if can_shrink else "abort", why=why,
                        count=len(ranks))
        if trace_mod.active:
            trace_mod.instant("errmgr", "selfheal_escalate", rank=-1,
                              to="shrink" if can_shrink else "abort",
                              count=len(ranks))
        if can_shrink:
            notify(Severity.ERROR, "selfheal-escalate",
                   f"job {job.jobid}: {why}; degrading to shrink — "
                   f"survivors continue without ranks {_rank_span(ranks)}")
            return
        notify(Severity.CRITICAL, "selfheal-escalate",
               f"job {job.jobid}: {why} and no shrinkable survivors; "
               f"aborting")
        if job.aborted_proc is None:
            job.aborted_proc = procs[0]
            job.abort_reason = f"{reason}; selfheal ladder exhausted"
        launcher.kill_job(job, exclude=procs[0])

    def _escalate(self, launcher, job: Job, proc: Proc, why: str) -> None:
        """The revive arm is out — degrade to the notify/shrink rung (the
        propagated death stands, the job continues smaller) whenever any
        other rank can still carry the job; abort only when shrink is
        impossible (every other rank also failed, or there is no control
        plane to propagate through)."""
        from ompi_tpu.mpi import trace as trace_mod
        from ompi_tpu.runtime import ftevents
        from ompi_tpu.runtime.notifier import Severity, notify

        trace_mod.count("errmgr_selfheal_escalations_total")
        carriers = [p for p in job.procs if p is not proc and p.state
                    in (ProcState.RUNNING, ProcState.TERMINATED)]
        can_shrink = (bool(carriers)
                      and (getattr(job, "pmix_server", None)
                           or getattr(launcher, "server", None))
                      is not None)
        ftevents.record("escalate", jobid=job.jobid, rank=proc.rank,
                        lives=proc.lives,
                        to="shrink" if can_shrink else "abort", why=why)
        if trace_mod.active:
            trace_mod.instant("errmgr", "selfheal_escalate", rank=proc.rank,
                              to="shrink" if can_shrink else "abort")
        if can_shrink:
            notify(Severity.ERROR, "selfheal-escalate",
                   f"job {job.jobid}: {why}; degrading to shrink — "
                   f"survivors continue without rank {proc.rank}")
            return
        notify(Severity.CRITICAL, "selfheal-escalate",
               f"job {job.jobid}: {why} and no shrinkable survivors; "
               f"aborting")
        if job.aborted_proc is None:
            job.aborted_proc = proc
            job.abort_reason = (
                f"rank {proc.rank} {proc.state.value} after "
                f"{proc.restarts} revive(s); selfheal ladder exhausted "
                f"(exit code {proc.exit_code})")
        launcher.kill_job(job, exclude=proc)
