"""Errmgr — failure response policy.

≈ orte/mca/errmgr (errmgr.h:87-136; default_hnp behavior at
errmgr_default_hnp.c:351-470: on proc abort / comm failure, terminate the
job).  Components decide what a proc-failure event does:

- ``abort``    — default: first failure kills every remaining proc and the
  job exits with the failed proc's status (mpirun's default).  The
  teardown is SIGTERM → ``launcher_kill_grace_s`` → SIGKILL; ranks
  running with the flight recorder armed (``tpurun --trace`` /
  ``OMPI_TPU_TRACE=1``) flush their trace ring to
  ``$TMPDIR/ompi_tpu_trace_<jobid>_rank<r>.json`` from that SIGTERM, so
  an aborted job leaves a per-rank timeline behind for post-mortem
  (merge with ``tools/trace_export.py``).
- ``continue`` — log and keep going.
- ``notify``   — keep going AND propagate the failure to the survivors
  (PMIx dead-set + TAG_PROC_FAILED xcast + notifier event) so they can
  run user-level recovery: ``Comm.revoke()/shrink()/agree()`` from
  ``ompi_tpu.mpi.ft`` — the ULFM shrink-and-continue recipe.  On the
  daemon tree, notify additionally arms mid-tree re-parenting: a
  non-leaf orted's death no longer tears down its subtree via the
  lifeline rule — the orphaned child daemons re-wire to the nearest
  live ancestor (TAG_REPARENT handshake, HNP arbitrating), confining
  the loss to the dead host's ranks.
- ``respawn``  — revive the failed rank in place up to
  ``errmgr_max_restarts`` times (≈ rmaps/resilient + the errmgr restart
  paths): same rank and env plus ``OMPI_TPU_RESTART=<n>`` so the app can
  restore from its last ``ckpt`` snapshot (+ msglog replay for in-flight
  p2p) instead of recomputing from step 0.  Select with
  ``--mca errmgr respawn``.  Works in both launchers (local fork/exec and
  the orted daemon tree via TAG_RESPAWN).

  Scope: respawn is a HOST-plane recovery.  A job using the multi-host
  DEVICE plane (jax.distributed) cannot revive a member in place — the
  coordination service rejects a reconnecting incarnation and its
  heartbeat failure poisons every surviving task — so device-plane jobs
  recover by full-job restart from the ``ckpt`` snapshots (run respawn
  jobs with ``--mca multihost_auto_init 0``).
"""

from __future__ import annotations

from typing import TYPE_CHECKING

from ompi_tpu.core import output
from ompi_tpu.core.config import VarType, register_var, var_registry
from ompi_tpu.core.mca import Component, Framework
from ompi_tpu.runtime.job import Job, Proc, ProcState

if TYPE_CHECKING:
    from ompi_tpu.runtime.launcher import LocalLauncher

__all__ = ["errmgr_framework", "ErrmgrAbort", "ErrmgrRespawn",
           "ErrmgrContinue", "ErrmgrNotify"]

_log = output.get_stream("errmgr")

errmgr_framework = Framework("errmgr", "failure response policy")

register_var("errmgr", "max_restarts", VarType.SIZE, 2,
             "errmgr/respawn: revive a failed rank at most this many times "
             "before falling back to job abort")


def apply_host_plane_policy(errmgr, env: dict, *base_envs: dict) -> None:
    """errmgr/respawn is HOST-plane recovery: a revived rank cannot
    rejoin the coordination service, and survivors' jax.distributed
    threads then pin their processes at exit (a post-finalize spin).
    The policy implies the plane — when respawn is selected, launch app
    processes device-plane-off unless the user set the var explicitly
    (in ``env`` or any of ``base_envs``)."""
    from ompi_tpu.core.config import var_registry

    if getattr(errmgr, "NAME", "") != "respawn":
        return
    key = var_registry.ENV_PREFIX + "multihost_auto_init"
    if any(key in e for e in (env, *base_envs)):
        return
    env[key] = "0"


@errmgr_framework.component
class ErrmgrAbort(Component):
    NAME = "abort"
    PRIORITY = 10

    def proc_failed(self, launcher: "LocalLauncher", job: Job, proc: Proc) -> None:
        if job.aborted_proc is None:
            job.aborted_proc = proc
            job.abort_reason = (
                f"rank {proc.rank} {proc.state.value} "
                f"(exit code {proc.exit_code})")
        _log.verbose(1, "aborting job %d: %s", job.jobid, job.abort_reason)
        launcher.kill_job(job, exclude=proc)


@errmgr_framework.component
class ErrmgrRespawn(Component):
    """Revive failed ranks in place (≈ errmgr restart + rmaps/resilient,
    errmgr_default_hnp.c:351-470's ORTE_PROC_STATE_RESTART arm)."""

    NAME = "respawn"
    PRIORITY = 0    # opt-in via --mca errmgr respawn

    def proc_failed(self, launcher: "LocalLauncher", job: Job,
                    proc: Proc) -> None:
        from ompi_tpu.runtime.notifier import Severity, notify

        limit = var_registry.get("errmgr_max_restarts")
        # both shipped launchers revive (local fork/exec + daemon tree via
        # TAG_RESPAWN); a custom launcher without the hook degrades to
        # abort instead of raising into its event dispatch
        respawn = getattr(launcher, "respawn_proc", None)
        if respawn is None:
            _log.error("errmgr/respawn: %s cannot revive ranks; aborting",
                       type(launcher).__name__)
        elif proc.restarts < limit:
            _log.verbose(1, "rank %d failed (exit %s); respawn %d/%d",
                         proc.rank, proc.exit_code, proc.restarts + 1, limit)
            notify(Severity.WARN, "rank-respawn",
                   f"job {job.jobid} rank {proc.rank} exit "
                   f"{proc.exit_code}; restart {proc.restarts + 1}/{limit}")
            if respawn(job, proc):
                return
            _log.error("rank %d respawn failed to start", proc.rank)
        else:
            _log.verbose(1, "rank %d exhausted %d restarts; aborting job",
                         proc.rank, limit)
        if job.aborted_proc is None:
            job.aborted_proc = proc
            job.abort_reason = (
                f"rank {proc.rank} {proc.state.value} after "
                f"{proc.restarts} restart(s) (exit code {proc.exit_code})")
        launcher.kill_job(job, exclude=proc)


@errmgr_framework.component
class ErrmgrContinue(Component):
    NAME = "continue"
    PRIORITY = 0

    def proc_failed(self, launcher: "LocalLauncher", job: Job, proc: Proc) -> None:
        _log.verbose(1, "rank %d failed (%s); continuing per policy",
                     proc.rank, proc.state.value)


@errmgr_framework.component
class ErrmgrNotify(Component):
    """ULFM-enabling policy: a rank death neither kills the job (abort)
    nor revives the rank (respawn) — it is *propagated* to the survivors
    so they can run user-level recovery (``Comm.revoke`` / ``shrink`` /
    ``agree``, mpi/ft.py):

    - the PMIx server's dead-set already holds the rank (the launcher
      calls ``proc_died`` before any policy runs), so survivors' failure
      detectors see it on their next poll and pending operations against
      the dead peer fail fast with MPI_ERR_PROC_FAILED;
    - on a daemon tree the failure additionally rides a TAG_PROC_FAILED
      xcast so every orted logs which rank died and why;
    - an admin notifier event records the death.

    Select with ``--mca errmgr notify``.  This is the policy behind the
    shrink-and-continue recipe (README "Fault tolerance").
    """

    NAME = "notify"
    PRIORITY = 0    # opt-in via --mca errmgr notify

    def proc_failed(self, launcher: "LocalLauncher", job: Job,
                    proc: Proc) -> None:
        from ompi_tpu.runtime.notifier import Severity, notify

        reason = (f"rank {proc.rank} {proc.state.value} "
                  f"(exit code {proc.exit_code})")
        _log.verbose(1, "notify policy: %s; propagating to survivors",
                     reason)
        server = getattr(launcher, "server", None)
        if server is not None:
            # idempotent (the reap loop already called proc_died); this
            # adds the human-readable reason the detector surfaces
            server.proc_died(proc.rank, reason=reason)
        node = getattr(launcher, "rml", None)
        if node is not None:
            from ompi_tpu.runtime import rml as rml_mod

            try:
                node.xcast(rml_mod.TAG_PROC_FAILED, (proc.rank, reason))
            except Exception as e:  # noqa: BLE001 — tree may be tearing down
                _log.error("notify: TAG_PROC_FAILED xcast failed: %r", e)
        notify(Severity.WARN, "rank-failed",
               f"job {job.jobid} {reason}; survivors notified "
               f"(job continues)")
