"""Errmgr — failure response policy.

≈ orte/mca/errmgr (errmgr.h:87-136; default_hnp behavior at
errmgr_default_hnp.c:351-470: on proc abort / comm failure, terminate the
job).  Components decide what a proc-failure event does:

- ``abort``    — default: first failure kills every remaining proc and the
  job exits with the failed proc's status (mpirun's default).
- ``continue`` — log and keep going (the resilient-mapping hook point; a
  future component can respawn, ≈ rmaps/resilient + errmgr restart paths).
"""

from __future__ import annotations

from typing import TYPE_CHECKING

from ompi_tpu.core import output
from ompi_tpu.core.mca import Component, Framework
from ompi_tpu.runtime.job import Job, Proc, ProcState

if TYPE_CHECKING:
    from ompi_tpu.runtime.launcher import LocalLauncher

__all__ = ["errmgr_framework", "ErrmgrAbort"]

_log = output.get_stream("errmgr")

errmgr_framework = Framework("errmgr", "failure response policy")


@errmgr_framework.component
class ErrmgrAbort(Component):
    NAME = "abort"
    PRIORITY = 10

    def proc_failed(self, launcher: "LocalLauncher", job: Job, proc: Proc) -> None:
        if job.aborted_proc is None:
            job.aborted_proc = proc
            job.abort_reason = (
                f"rank {proc.rank} {proc.state.value} "
                f"(exit code {proc.exit_code})")
        _log.verbose(1, "aborting job %d: %s", job.jobid, job.abort_reason)
        launcher.kill_job(job, exclude=proc)


@errmgr_framework.component
class ErrmgrContinue(Component):
    NAME = "continue"
    PRIORITY = 0

    def proc_failed(self, launcher: "LocalLauncher", job: Job, proc: Proc) -> None:
        _log.verbose(1, "rank %d failed (%s); continuing per policy",
                     proc.rank, proc.state.value)
