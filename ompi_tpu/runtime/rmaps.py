"""RMAPS — mapping ranks onto nodes/slots/chips.

≈ orte/mca/rmaps (rmaps_base_map_job.c): given an allocation, place each rank
on a node+slot, assign local ranks, and bind to chips where available.

Components:
- ``round_robin`` — by-slot (fill a node) or by-node (spread) placement, the
  reference's default mapper.
- ``ppr``         — procs-per-resource: exactly N procs per node.
- ``seq``         — rank i on node[i % len], one per step (reference's seq).

Chip binding: if a node carries ``chips`` metadata, local rank r binds to
chip r (device-per-rank — the TPU replacement for cpu binding in
orte/mca/rmaps + rtc/hwloc).
"""

from __future__ import annotations

from ompi_tpu.core.config import VarType, register_var, var_registry
from ompi_tpu.core.mca import Component, Framework
from ompi_tpu.runtime.job import Job, Proc

__all__ = ["rmaps_framework", "map_job"]

rmaps_framework = Framework("rmaps", "process mapping")


def _finalize(job: Job) -> Job:
    """Assign local ranks, app indices, and chip bindings after placement."""
    # app boundaries: ranks [0, np0) run app 0, [np0, np0+np1) app 1, ...
    bounds = []
    acc = 0
    for i, app in enumerate(job.apps):
        acc += app.np
        bounds.append((acc, i))
    per_node_count: dict[str, int] = {}
    for proc in job.procs:
        assert proc.node is not None
        idx = per_node_count.get(proc.node.name, 0)
        proc.local_rank = idx
        per_node_count[proc.node.name] = idx + 1
        if proc.node.chips:
            proc.chip = proc.node.chips[idx % len(proc.node.chips)]
        for bound, app_i in bounds:
            if proc.rank < bound:
                proc.app_idx = app_i
                break
    return job


@rmaps_framework.component
class RoundRobinMapper(Component):
    NAME = "round_robin"
    PRIORITY = 10

    def register_params(self) -> None:
        register_var("rmaps", "rr_policy", VarType.STRING, "byslot",
                     "round-robin policy", enumerator=("byslot", "bynode"))

    def map_job(self, job: Job) -> Job:
        policy = var_registry.get("rmaps_rr_policy")
        job.procs = []
        n = job.np
        if policy == "byslot":
            rank = 0
            while rank < n:
                placed = False
                for node in job.nodes:
                    while node.slots_available > 0 and rank < n:
                        job.procs.append(
                            Proc(rank=rank, node=node, slot=node.slots_inuse))
                        node.slots_inuse += 1
                        rank += 1
                        placed = True
                if not placed:  # oversubscribe: wrap around ignoring slots
                    for node in job.nodes:
                        if rank >= n:
                            break
                        job.procs.append(
                            Proc(rank=rank, node=node, slot=node.slots_inuse))
                        node.slots_inuse += 1
                        rank += 1
        else:  # bynode: spread one per node per pass
            rank = 0
            while rank < n:
                for node in job.nodes:
                    if rank >= n:
                        break
                    job.procs.append(
                        Proc(rank=rank, node=node, slot=node.slots_inuse))
                    node.slots_inuse += 1
                    rank += 1
        return _finalize(job)


@rmaps_framework.component
class PprMapper(Component):
    """Procs-per-resource: exactly N ranks per node (≈ rmaps/ppr)."""

    NAME = "ppr"
    PRIORITY = 0

    def register_params(self) -> None:
        register_var("rmaps", "ppr_n", VarType.INT, 1, "procs per node")

    def query(self, **ctx):
        return self.PRIORITY

    def map_job(self, job: Job) -> Job:
        per = var_registry.get("rmaps_ppr_n")
        job.procs = []
        rank = 0
        n = job.np
        for node in job.nodes:
            for _ in range(per):
                if rank >= n:
                    break
                job.procs.append(Proc(rank=rank, node=node, slot=node.slots_inuse))
                node.slots_inuse += 1
                rank += 1
        if rank < n:
            raise RuntimeError(
                f"ppr mapping: {n} ranks do not fit at {per}/node on "
                f"{len(job.nodes)} nodes")
        return _finalize(job)


@rmaps_framework.component
class SeqMapper(Component):
    NAME = "seq"
    PRIORITY = 0

    def map_job(self, job: Job) -> Job:
        job.procs = []
        for rank in range(job.np):
            node = job.nodes[rank % len(job.nodes)]
            job.procs.append(Proc(rank=rank, node=node, slot=node.slots_inuse))
            node.slots_inuse += 1
        return _finalize(job)


def map_job(job: Job, **context) -> Job:
    """Run the mapping phase (≈ orte_rmaps_base_map_job)."""
    comp = rmaps_framework.select(**context)
    return comp.map_job(job)
