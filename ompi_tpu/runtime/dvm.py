"""Persistent distributed VM + live-job control plane.

≈ orte/tools/orte-dvm/orte-dvm.c:1 (a standing daemon VM that runs many
jobs without re-launching), orte/mca/state/dvm/state_dvm.c:1 (the job
lifecycle on a persistent VM: jobs come and go, daemons stay wired), and
orte/tools/orte-ps/orte-ps.c:1 (query a live VM's job/proc table).

The DVM HNP brings the daemon tree up ONCE (the expensive part — on real
pods that includes TPU runtime warm-up), writes its control URI to a
file, then serves job submissions over a line-JSON TCP control channel:

    tpurun --dvm-start --plm sim --hosts 2 --slots 8      # terminal 1
    tpurun --dvm-submit -np 4 python app.py               # terminal 2 (fast)
    tpurun --dvm-ps                                       # live proc table
    tpurun --dvm-stop

Jobs run sequentially (one at a time, like orte-dvm's default): each gets
a fresh PMIx rendezvous sized to its np, a map over the standing nodes,
and its IOF streamed back to the submitting client.

Observability plane (``--metrics-port N``): a long-lived HTTP endpoint
on the DVM serving

- ``/metrics`` — Prometheus text: every rank's pvar snapshot (pushed up
  the orted tree via TAG_METRICS) labeled ``{job=,rank=}``, real
  histogram families for the latency plane (``_bucket{le=}``/``_sum``/
  ``_count``), per-job ``ompi_tpu_job_*`` sums, and the DVM's own
  process pvars;
- ``/status`` — JSON: the daemon table (heartbeat ages), the proc table
  (``lives``, restarts budget, last-metrics age, p99 collective
  latency, ``last_coll`` pushed recorder head), the per-job FT event
  timeline (detect / reap / revive / shrink / escalate / stuck /
  doctor) and the per-job straggler panel (per-rank collective
  wait-time share over the last window, max/median skew, and the
  current slowest rank);
- ``/doctor`` — JSON: an on-demand cross-rank hang capture + verdict
  (TAG_DOCTOR fan-out → per-rank recorder tails, pending p2p, stacks,
  /proc probes → mismatch / deadlock / straggler analysis).  The same
  capture fires automatically when the watchdog sees a rank push a
  stuck event (``coll_stuck_timeout``).

``--metrics-port 0`` binds an ephemeral port; the bound address is
written next to the URI file as ``<uri>.metrics``.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional

from ompi_tpu.core import output
from ompi_tpu.core.config import var_registry
from ompi_tpu.runtime import ftevents, rmaps, rml
from ompi_tpu.runtime.job import AppContext, Job, ProcState
from ompi_tpu.runtime.plm import MultiHostLauncher

__all__ = ["DvmHnp", "submit", "ps", "stop", "default_uri_path"]

_log = output.get_stream("dvm")


def default_uri_path() -> str:
    return os.path.join(
        os.environ.get("TMPDIR", "/tmp"),
        f"ompi_tpu-dvm-{os.getuid()}.uri")


def _read_uri(path: str) -> str:
    with open(path, encoding="utf-8") as f:
        return f.read().strip()


class DvmHnp(MultiHostLauncher):
    """The standing-VM HNP: daemon tree up once, jobs on demand."""

    def __init__(self, plm_name: str = "sim", want_tpu: bool = False,
                 uri_path: Optional[str] = None,
                 metrics_port: Optional[int] = None, **select_ctx) -> None:
        super().__init__(plm_name=plm_name, want_tpu=want_tpu,
                         stdin_target="none", **select_ctx)
        self._persistent = True
        self.metrics_port = metrics_port
        self._http: Optional[ThreadingHTTPServer] = None
        self.metrics_uri: Optional[str] = None
        self._started_at = time.time()
        self.uri_path = uri_path or default_uri_path()
        self._job_lock = threading.Lock()     # one job at a time
        self._stopped = threading.Event()
        self._ctrl: Optional[socket.socket] = None
        self._client_sink = None              # active job's IOF stream
        # serializes writes to the client connection: IOF callbacks run
        # on per-daemon RML reader threads and would otherwise interleave
        # partial lines with each other and with the final exit reply
        self._sink_lock = threading.Lock()
        self._stats: dict[int, list] = {}     # vpid → latest stat rows
        self._stats_cv = threading.Condition()
        self._stats_epoch = 0                 # fences late replies
        self._stats_lock = threading.Lock()   # one collection at a time
        # hang-doctor capture plumbing (mirrors the stats collection:
        # epoch-fenced TAG_DOCTOR_REPLY fan-in, one capture at a time)
        self._doctor: dict[int, list] = {}    # vpid → capture rows
        self._doctor_cv = threading.Condition()
        self._doctor_epoch = 0
        self._doctor_lock = threading.Lock()
        self._last_doctor: Optional[dict] = None
        # live-timeline capture plumbing (same epoch-fenced fan-in as
        # the doctor, answering TAG_TIMELINE_REPLY)
        self._timeline: dict[int, list] = {}  # vpid → capture rows
        self._timeline_cv = threading.Condition()
        self._timeline_epoch = 0
        self._timeline_lock = threading.Lock()
        self._last_timeline: Optional[dict] = None
        self._tl_captures = 0                 # self-metering: /timeline
        self._tl_merge_ns = 0                 # rounds + HNP merge cost
        #: (jobid, rank) → highest coll_stuck_events_total seen — the
        #: watchdog's new-stuck-event edge detector
        self._stuck_seen: dict[tuple, float] = {}
        self.vm_job: Optional[Job] = None
        self._history: list[dict] = []        # completed-job records

    # -- VM lifecycle ------------------------------------------------------

    def start(self, np_slots: int) -> None:
        """Allocate nodes, spawn + wire the daemon tree, open the control
        channel, write the URI file."""
        from ompi_tpu.runtime import ras

        vm = Job([AppContext(argv=["-"], np=np_slots)])
        ras.allocate(vm, want_tpu=self.want_tpu, **self.select_ctx)
        rmaps.map_job(vm, **self.select_ctx)
        self.vm_job = vm
        if not self._vm_up(vm):
            raise RuntimeError(
                f"DVM bring-up failed: {vm.abort_reason}")
        self.rml.register_recv(rml.TAG_STATS_REPLY, self._on_stats_reply)
        self.rml.register_recv(rml.TAG_DOCTOR_REPLY,
                               self._on_doctor_reply)
        self.rml.register_recv(rml.TAG_TIMELINE_REPLY,
                               self._on_timeline_reply)
        self._ctrl = socket.create_server(("127.0.0.1", 0))
        port = self._ctrl.getsockname()[1]
        # metrics endpoint BEFORE the uri file: clients poll for the uri
        # file to detect "DVM up", so everything it implies (including
        # the recorded <uri>.metrics address) must exist by then
        if self.metrics_port is not None:
            self._start_metrics_server(self.metrics_port)
        with open(self.uri_path, "w", encoding="utf-8") as f:
            f.write(f"127.0.0.1:{port}\n")
        threading.Thread(target=self._accept_loop, daemon=True).start()
        _log.verbose(1, "DVM up: %d daemons, ctrl 127.0.0.1:%d (uri %s)",
                     len(vm.nodes), port, self.uri_path)

    def serve_forever(self) -> int:
        self._stopped.wait()
        return 0

    def shutdown(self) -> None:
        if self._stopped.is_set():
            return
        self._stopped.set()
        try:
            self._teardown_vm()
        finally:
            if self._http is not None:
                http, self._http = self._http, None

                def _close() -> None:
                    http.shutdown()       # stop serve_forever ...
                    http.server_close()   # ... THEN release the socket

                threading.Thread(target=_close, daemon=True).start()
            if self._ctrl is not None:
                self._ctrl.close()
            for path in (self.uri_path, self.uri_path + ".metrics"):
                try:
                    os.unlink(path)
                except OSError:
                    pass

    # -- control channel ---------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                conn, _addr = self._ctrl.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            rfile = conn.makefile("r", encoding="utf-8")
            wfile = conn.makefile("w", encoding="utf-8")
            line = rfile.readline()
            if not line:
                return
            req = json.loads(line)
            cmd = req.get("cmd")
            if cmd == "run":
                self._cmd_run(req, wfile)
            elif cmd == "ps":
                self._reply(wfile, {"ps": self._ps_table()})
            elif cmd == "stop":
                self._reply(wfile, {"ok": True})
                wfile.flush()
                self.shutdown()
            else:
                self._reply(wfile, {"error": f"unknown cmd {cmd!r}"})
        except (OSError, ValueError, json.JSONDecodeError) as e:
            _log.verbose(1, "control connection error: %r", e)
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _reply(self, wfile, obj: dict) -> None:
        with self._sink_lock:
            wfile.write(json.dumps(obj) + "\n")
            wfile.flush()

    # -- job execution on the warm VM --------------------------------------

    def _cmd_run(self, req: dict, wfile) -> None:
        argv = req.get("argv") or []
        np_ = int(req.get("np") or 1)
        if not argv:
            self._reply(wfile, {"error": "no argv"})
            return
        with self._job_lock:                  # sequential, like orte-dvm
            t0 = time.perf_counter()
            rc = self._run_one(argv, np_, req.get("env") or {},
                               req.get("cwd"), wfile)
            self._reply(wfile, {"exit": rc,
                                "wall_s": round(time.perf_counter() - t0,
                                                3)})

    def _run_one(self, argv, np_: int, env: dict, cwd, wfile) -> int:
        job = Job([AppContext(argv=list(argv), np=np_,
                              env=dict(env), cwd=cwd)])
        job.nodes = self.vm_job.nodes         # the standing allocation
        for n in job.nodes:
            n.slots_inuse = 0
        try:
            rmaps.map_job(job, **self.select_ctx)
        except Exception as e:  # noqa: BLE001 — report, keep the VM alive
            self._reply(wfile, {"error": f"map failed: {e}"})
            return 1
        # fresh per-job bookkeeping on the standing VM
        with self._cv:
            self._exited.clear()
            self._killed = False
            job_lost = self._lost_daemon
        if job_lost is not None:
            self._reply(wfile, {"error": f"daemon {job_lost} is down"})
            return 1
        self._client_sink = wfile
        try:
            self._launch_apps(job)
            self._wait_ranks(job)
        finally:
            self._client_sink = None
            if self.server is not None:
                self.server.close()
                self.server = None
        rcs = [self._exited.get(p.rank, 1) for p in job.procs]
        rc = (job.abort_status if job.abort_status
              else next((r for r in rcs if r), 0))
        if rc < 0:
            rc = 128 - rc   # signal exit, same mapping as the non-DVM path
        self._history.append({
            "jobid": job.jobid, "argv": argv, "np": np_, "rc": rc,
            "finished": time.time()})
        return rc

    def _on_iof(self, origin: int, payload) -> None:
        """Route a running job's output to the submitting client; fall
        back to the DVM's own stdout when no client is attached."""
        sink = self._client_sink
        if sink is None:
            return super()._on_iof(origin, payload)
        rank, stream, raw = payload
        try:
            self._reply(sink, {
                "iof": [rank, stream,
                        bytes(raw).decode(errors="replace")]})
        except (OSError, ValueError):
            self._client_sink = None          # client went away; drop

    # -- introspection (≈ orte-ps / orte-top) ------------------------------

    def _on_stats_reply(self, origin: int, payload) -> None:
        vpid, epoch, rows = payload
        with self._stats_cv:
            if epoch != self._stats_epoch:
                return                # late reply from an earlier round
            self._stats[vpid] = [tuple(r) for r in rows]
            self._stats_cv.notify_all()

    def _collect_stats(self, timeout: float = 1.0) -> dict[int, tuple]:
        """Pull live per-rank resource usage from every daemon
        (≈ orte-top's resusage sample): xcast the request, wait briefly
        for the tree to reply; late/dead daemons just contribute
        nothing.  Serialized + epoch-fenced: concurrent ps clients must
        not clear each other's reply set, and a straggler reply from a
        timed-out round must not pass as fresh."""
        with self._stats_lock:
            n = len(self.vm_job.nodes) if self.vm_job else 0
            with self._stats_cv:
                self._stats.clear()
                self._stats_epoch += 1
                epoch = self._stats_epoch
            try:
                self.rml.xcast(rml.TAG_STATS, epoch)
            except Exception:  # noqa: BLE001 — tree tearing down
                return {}
            deadline = time.monotonic() + timeout
            with self._stats_cv:
                self._stats_cv.wait_for(
                    lambda: len(self._stats) >= n,
                    timeout=max(0.0, deadline - time.monotonic()))
                merged: dict[int, tuple] = {}
                for rows in self._stats.values():
                    for rank, pid, rss, cpu_s in rows:
                        merged[int(rank)] = (int(pid), int(rss),
                                             float(cpu_s))
            return merged

    # -- the cross-rank hang doctor ----------------------------------------

    #: the pushed recorder-head gauges (see trace.py's coll_cur_* pvars)
    _CUR_NAMES = ("coll_cur_seq", "coll_cur_kind_id", "coll_cur_cid",
                  "coll_cur_done", "coll_cur_posted_ts")

    def _on_doctor_reply(self, origin: int, payload) -> None:
        vpid, epoch, rows = payload
        with self._doctor_cv:
            if epoch != self._doctor_epoch:
                return                # late reply from an earlier round
            self._doctor[vpid] = [dict(r) for r in rows]
            self._doctor_cv.notify_all()

    def _collect_doctor(self, timeout: float = 4.0) -> list[dict]:
        """One cross-rank state snapshot: xcast TAG_DOCTOR, gather every
        daemon's per-rank captures (a silent daemon contributes nothing
        — its ranks then read as no_response at the analyzer).
        Serialized + epoch-fenced like the stats collection."""
        with self._doctor_lock:
            n = len(self.vm_job.nodes) if self.vm_job else 0
            with self._doctor_cv:
                self._doctor.clear()
                self._doctor_epoch += 1
                epoch = self._doctor_epoch
            try:
                self.rml.xcast(rml.TAG_DOCTOR, epoch)
            except Exception:  # noqa: BLE001 — tree tearing down
                return []
            deadline = time.monotonic() + timeout
            with self._doctor_cv:
                self._doctor_cv.wait_for(
                    lambda: len(self._doctor) >= n,
                    timeout=max(0.0, deadline - time.monotonic()))
                captures: list[dict] = []
                for rows in self._doctor.values():
                    captures.extend(rows)
            return captures

    def _doctor_doc(self, trigger: str) -> dict:
        """The /doctor document: live capture + analyzer verdict while a
        job runs; the cached last verdict (or idle) otherwise."""
        from ompi_tpu.runtime import doctor

        vm = self.vm_job
        job = self._cur_job
        running = (job is not None and job is not vm
                   and any(p.state == ProcState.RUNNING
                           for p in job.procs))
        if not running:
            if self._last_doctor is not None:
                return dict(self._last_doctor, stale=True)
            return {"trigger": trigger, "ts": time.time(),
                    "verdict": {"kind": "idle",
                                "detail": "no job running and no "
                                          "cached verdict"}}
        captures = self._collect_doctor()
        # a frozen rank's last uplink-pushed recorder head stands in for
        # the capture it can no longer give
        pushed = self.metrics_agg.rank_values(job.jobid, self._CUR_NAMES)
        for c in captures:
            if c.get("no_response") and int(c.get("rank", -1)) in pushed:
                c["pushed"] = pushed[int(c["rank"])]
        doc = doctor.analyze(captures, nranks=job.np)
        doc["trigger"] = trigger
        doc["jobid"] = job.jobid
        doc["ts"] = time.time()
        v = doc.get("verdict") or {}
        # only verdicts worth remembering reach the FT timeline: a
        # dashboard polling /doctor every few seconds against a healthy
        # job must not flush real failure history out of the bounded
        # event ring (watchdog-triggered captures always record)
        if trigger == "watchdog" or v.get("kind") not in (
                "healthy", "idle", "no_data"):
            ftevents.record(
                "doctor", jobid=job.jobid, rank=int(v.get("rank", -1)),
                verdict=v.get("kind"), trigger=trigger,
                detail=(v.get("detail") or "")[:300])
        self._last_doctor = doc
        return doc

    def _doctor_watch(self) -> None:
        """The watchdog: a rank whose coll_stuck_events_total rose since
        the last tick pushed a stuck event up the uplink — record it on
        the FT timeline and auto-capture a verdict (one capture per
        tick, covering every newly-stuck rank)."""
        while not self._stopped.wait(1.0):
            vm = self.vm_job
            job = self._cur_job
            if job is None or job is vm:
                continue
            try:
                # a standing DVM serves many jobs: drop dead jobs'
                # edge-detector keys so the dict stays bounded
                for key in [k for k in self._stuck_seen
                            if k[0] != job.jobid]:
                    del self._stuck_seen[key]
                rows = self.metrics_agg.rank_values(
                    job.jobid, ("coll_stuck_events_total",))
                newly = []
                for rank, vals in sorted(rows.items()):
                    v = float(vals.get("coll_stuck_events_total", 0))
                    key = (job.jobid, rank)
                    if v > self._stuck_seen.get(key, 0.0):
                        self._stuck_seen[key] = v
                        newly.append((rank, int(v)))
                if not newly:
                    continue
                for rank, n in newly:
                    ftevents.record("stuck", jobid=job.jobid, rank=rank,
                                    events=n)
                self._doctor_doc("watchdog")
            except Exception as e:  # noqa: BLE001 — watchdog survives
                _log.verbose(1, "doctor watchdog tick failed: %r", e)

    # -- the live cross-rank timeline --------------------------------------

    def _on_timeline_reply(self, origin: int, payload) -> None:
        vpid, epoch, rows = payload
        with self._timeline_cv:
            if epoch != self._timeline_epoch:
                return                # late reply from an earlier round
            self._timeline[vpid] = [dict(r) for r in rows]
            self._timeline_cv.notify_all()

    def _collect_timeline(self, tail: int,
                          timeout: float = 4.0) -> list[dict]:
        """One live trace capture: xcast TAG_TIMELINE, gather every
        daemon's per-rank recorder tails (each stamped with the
        daemon's measured clock offset-to-root).  Serialized +
        epoch-fenced like the doctor collection."""
        with self._timeline_lock:
            n = len(self.vm_job.nodes) if self.vm_job else 0
            with self._timeline_cv:
                self._timeline.clear()
                self._timeline_epoch += 1
                epoch = self._timeline_epoch
            try:
                self.rml.xcast(rml.TAG_TIMELINE, (epoch, int(tail)))
            except Exception:  # noqa: BLE001 — tree tearing down
                return []
            deadline = time.monotonic() + timeout
            with self._timeline_cv:
                self._timeline_cv.wait_for(
                    lambda: len(self._timeline) >= n,
                    timeout=max(0.0, deadline - time.monotonic()))
                captures: list[dict] = []
                for rows in self._timeline.values():
                    captures.extend(rows)
            return captures

    def _timeline_doc(self, tail: int = 2048) -> dict:
        """The /timeline document: a merged, skew-corrected Chrome
        trace of the RUNNING job (live TAG_TIMELINE round); the cached
        last capture (marked stale) otherwise."""
        from ompi_tpu.runtime import timeline as timeline_mod

        vm = self.vm_job
        job = self._cur_job
        running = (job is not None and job is not vm
                   and any(p.state == ProcState.RUNNING
                           for p in job.procs))
        if not running:
            if self._last_timeline is not None:
                doc = dict(self._last_timeline)
                doc["otherData"] = dict(doc.get("otherData") or {},
                                        stale=True)
                return doc
            return {"displayTimeUnit": "ns", "traceEvents": [],
                    "otherData": {"idle": True,
                                  "detail": "no job running and no "
                                            "cached capture"}}
        captures = self._collect_timeline(tail)
        t0 = time.monotonic_ns()    # merge cost alone, not the fan-in
        doc = timeline_mod.merge_captures(captures, jobid=job.jobid)
        merge_ns = time.monotonic_ns() - t0
        with self._timeline_cv:
            self._tl_captures += 1
            self._tl_merge_ns += merge_ns
        doc["otherData"]["ts"] = time.time()
        doc["otherData"]["merge_ms"] = round(merge_ns / 1e6, 2)
        self._last_timeline = doc
        return doc

    def _daemon_rows(self) -> list[dict]:
        vm = self.vm_job
        if vm is None:
            return []
        # only meaningful with the heartbeat layer armed: without beats
        # every watched daemon's age grows forever and the column reads
        # as a fleet of silent daemons
        hb_on = float(var_registry.get("rml_heartbeat_period") or 0) > 0
        hb_ages = (self._hb_monitor.ages()
                   if hb_on and self._hb_monitor is not None else {})
        rows = []
        for i, n in enumerate(vm.nodes):
            row = {"vpid": i + 1, "host": n.name, "slots": n.slots,
                   "chips": (len(n.chips) if n.chips else 0),
                   "pid": (self._daemon_popen[i].pid
                           if i < len(self._daemon_popen) else None)}
            if i + 1 in hb_ages:
                row["hb_age_s"] = round(hb_ages[i + 1], 2)
            rows.append(row)
        return rows

    def _proc_rows(self, job, usage: dict[int, tuple]) -> list[dict]:
        from ompi_tpu.mpi import trace as trace_mod

        metrics_ages = self.metrics_agg.ages(job.jobid)
        p99s = self.metrics_agg.job_hist_quantiles(
            job.jobid, "coll_dispatch_ns", 0.99)
        heads = self.metrics_agg.rank_values(job.jobid, self._CUR_NAMES)
        rejoins = self.metrics_agg.rank_values(job.jobid,
                                               ("coll_rejoin_total",))
        traces = self.metrics_agg.rank_values(
            job.jobid, ("trace_dropped_total", "trace_ring_occupancy",
                        "trace_ring_capacity", "rank_clock_to_root_ns"))
        limit = int(var_registry.get("errmgr_max_restarts") or 0)
        procs = []
        for p in job.procs:
            row = {
                "rank": p.rank, "state": p.state.value,
                "host": p.node.name if p.node else "?",
                "local_rank": p.local_rank,
                # lives is the monotone revive count (the announced
                # incarnation); restarts is the governor's crash-loop
                # BUDGET counter, reset whenever a life earns its
                # uptime — it reads 0 for a rank revived many times
                "lives": p.lives,
                "restarts": p.restarts,
                "restarts_budget_left": max(0, limit - p.restarts),
                "exit_code": p.exit_code,
            }
            if p.rank in metrics_ages:
                # age of the rank's last pvar push through the uplink —
                # a live rank whose age keeps growing has a stalled
                # metrics plane (or a stalled rank)
                row["metrics_age_s"] = round(metrics_ages[p.rank], 2)
            if p.rank in p99s:
                # tail collective latency from the rank's pushed
                # histogram (the --dvm-ps p99 column)
                row["coll_p99_us"] = round(p99s[p.rank] / 1e3, 1)
            rj = rejoins.get(p.rank, {}).get("coll_rejoin_total")
            if rj:
                # epoch-fenced coll-hierarchy rebuilds this rank ran
                # after adopted revives (the rejoin half of selfheal) —
                # a rank whose lives grew without peers' rejoins
                # ticking is p2p-only recovered, not collective-capable
                row["rejoins"] = int(rj)
            tv = traces.get(p.rank)
            if tv is not None:
                # flight-recorder health from the pushed trace pvars: a
                # rank whose ring keeps dropping needs a bigger capacity
                # (or a narrower event set) before its captures lie
                cap = tv.get("trace_ring_capacity")
                if cap:
                    row["trace_ring"] = (
                        f"{int(tv.get('trace_ring_occupancy', 0))}"
                        f"/{int(cap)}")
                dropped = tv.get("trace_dropped_total")
                if dropped:
                    row["trace_dropped"] = int(dropped)
                # measured monotonic offset of the rank's host to the
                # HNP's clock domain (the skew /timeline corrects by)
                off = tv.get("rank_clock_to_root_ns")
                if off is not None:
                    row["clock_off_us"] = round(float(off) / 1e3, 1)
            hv = heads.get(p.rank)
            if hv is not None and hv.get("coll_cur_seq", -1) >= 0:
                # the pushed recorder head: the rank's last collective
                # as kind#seq ("!" = still in flight at push time) plus
                # its age — a wedged rank is visible here without a
                # full doctor capture
                kind = trace_mod.collrec_kind_name(
                    int(hv.get("coll_cur_kind_id", -1)))
                mark = "" if hv.get("coll_cur_done") else "!"
                row["last_coll"] = \
                    f'{kind}#{int(hv["coll_cur_seq"])}{mark}'
                ts = float(hv.get("coll_cur_posted_ts", 0.0))
                if ts > 0:
                    row["last_coll_age_s"] = round(
                        max(0.0, time.time() - ts), 2)
            if p.rank in usage:      # orte-top columns, live ranks
                pid, rss, cpu_s = usage[p.rank]
                row.update(pid=pid, rss_mb=round(rss / 2**20, 1),
                           cpu_s=round(cpu_s, 2))
            procs.append(row)
        return procs

    def _ps_table(self) -> dict:
        vm = self.vm_job
        job = self._cur_job
        procs = []
        if job is not None and job is not vm:
            usage = self._collect_stats() if any(
                p.state == ProcState.RUNNING for p in job.procs) else {}
            procs = self._proc_rows(job, usage)
        return {"daemons": self._daemon_rows(),
                "current_job": (None if job is None or job is vm else {
                    "jobid": job.jobid,
                    "argv": job.apps[0].argv,
                    "np": job.np,
                    "procs": procs}),
                "history": self._history[-20:]}

    # -- observability plane (≈ a standing Prometheus exporter) ------------

    def _start_metrics_server(self, port: int) -> None:
        """The long-lived scrape endpoint: /metrics + /status."""
        hnp = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler API
                path, _, query = self.path.partition("?")
                path = path.rstrip("/") or "/"
                if path == "/metrics":
                    body = hnp._metrics_text().encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif path == "/status":
                    body = json.dumps(hnp._status_doc()).encode()
                    ctype = "application/json"
                elif path == "/doctor":
                    # on-demand cross-rank hang capture + verdict (a
                    # live TAG_DOCTOR round while a job runs; blocking
                    # a handler thread for the collection window is
                    # fine — the server is threading)
                    body = json.dumps(
                        hnp._doctor_doc("scrape")).encode()
                    ctype = "application/json"
                elif path == "/timeline":
                    # live merged cross-rank trace (TAG_TIMELINE round
                    # while a job runs); ?tail=N bounds the per-rank
                    # recorder tail pulled from each rank
                    tail = 2048
                    for part in query.split("&"):
                        if part.startswith("tail="):
                            try:
                                tail = max(1, int(part[5:]))
                            except ValueError:
                                pass
                    body = json.dumps(hnp._timeline_doc(tail)).encode()
                    ctype = "application/json"
                elif path == "/":
                    body = (b"ompi_tpu dvm: /metrics /status /doctor "
                            b"/timeline\n")
                    ctype = "text/plain"
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args) -> None:
                pass  # scrapes every few seconds must not spam stderr

        self._http = ThreadingHTTPServer(("127.0.0.1", port), _Handler)
        self._http.daemon_threads = True
        bound = self._http.server_address[1]
        self.metrics_uri = f"http://127.0.0.1:{bound}"
        threading.Thread(target=self._http.serve_forever,
                         name="dvm-metrics-http", daemon=True).start()
        # the hang-doctor watchdog rides the observability plane: a
        # pushed stuck event auto-triggers a cross-rank capture
        threading.Thread(target=self._doctor_watch,
                         name="dvm-doctor-watch", daemon=True).start()
        # --metrics-port 0 binds an ephemeral port: record the actual
        # address where clients (tests, dashboards) can find it
        try:
            with open(self.uri_path + ".metrics", "w",
                      encoding="utf-8") as f:
                f.write(self.metrics_uri + "\n")
        except OSError:
            pass
        _log.verbose(0, "metrics endpoint: %s/metrics  %s/status",
                     self.metrics_uri, self.metrics_uri)

    def _metrics_text(self) -> str:
        """Prometheus text: the per-job/per-rank aggregate first, then
        DVM-level gauges, then this process's own pvars (unlabeled).

        The own-pvar section EXCLUDES any metric name the aggregate
        already emitted: the exposition format forbids a second # TYPE
        line (and a second, non-contiguous sample group) for a name —
        a real scraper would reject the whole page, and the HNP's own
        copies of rank counters are all-zero noise anyway."""
        from ompi_tpu.mpi import trace as trace_mod

        agg_text = self.metrics_agg.prometheus()
        agg_names = {line.split("{", 1)[0]
                     for line in agg_text.splitlines()
                     if line and not line.startswith("#")}
        own_lines = []
        skip_until_next_metric = False
        for line in trace_mod.metrics_snapshot().splitlines():
            if line.startswith("#"):
                name = line.split()[2] if len(line.split()) > 2 else ""
                skip_until_next_metric = name in agg_names
            else:
                skip_until_next_metric = \
                    line.split("{", 1)[0].split(" ", 1)[0] in agg_names
            if not skip_until_next_metric:
                own_lines.append(line)
        own = "\n".join(own_lines) + ("\n" if own_lines else "")
        dvm_lines = [
            "# TYPE ompi_tpu_dvm_jobs_completed_total counter",
            f"ompi_tpu_dvm_jobs_completed_total {len(self._history)}",
            "# TYPE ompi_tpu_dvm_daemons gauge",
            f"ompi_tpu_dvm_daemons "
            f"{len(self.vm_job.nodes) if self.vm_job else 0}",
            "# TYPE ompi_tpu_dvm_uptime_seconds gauge",
            f"ompi_tpu_dvm_uptime_seconds "
            f"{time.time() - self._started_at:.1f}",
            "# TYPE ompi_tpu_dvm_ft_events_total counter",
            f"ompi_tpu_dvm_ft_events_total {ftevents.log.total()}",
        ]
        return agg_text + "\n".join(dvm_lines) + "\n" + own

    def _uplink_stats(self) -> dict:
        """Telemetry about the telemetry: what the metrics uplink and
        the timeline plane themselves cost (the /status block that
        answers "is observability eating my run?")."""
        stats = getattr(self.metrics_agg, "stats", lambda: {})()
        doc: dict = {"hnp_merges_total": stats.get("merges_total", 0),
                     "hnp_merge_ms_total": round(
                         stats.get("merge_ns_total", 0) / 1e6, 2)}
        # rank-side push cost, summed from the pushed self-metering
        # counters (the ranks meter their own uplink datagrams)
        dgrams = nbytes = 0.0
        for jobid in self.metrics_agg.jobids():
            for vals in self.metrics_agg.rank_values(
                    jobid, ("metrics_push_datagrams_total",
                            "metrics_push_bytes_total")).values():
                dgrams += float(
                    vals.get("metrics_push_datagrams_total", 0))
                nbytes += float(vals.get("metrics_push_bytes_total", 0))
        doc["rank_push_datagrams_total"] = int(dgrams)
        doc["rank_push_bytes_total"] = int(nbytes)
        up = max(1e-9, time.time() - self._started_at)
        doc["rank_push_bytes_per_s"] = round(nbytes / up, 1)
        with self._timeline_cv:
            doc["timeline_captures_total"] = self._tl_captures
            doc["timeline_merge_ms_total"] = round(
                self._tl_merge_ns / 1e6, 2)
        return doc

    def _status_doc(self) -> dict:
        """The /status JSON: daemon table (heartbeat ages), per-job proc
        table (lives, restarts budget, last-metrics age) and the FT
        event timeline per job."""
        vm = self.vm_job
        job = self._cur_job
        now = time.time()
        jobids = set(self.metrics_agg.jobids())
        jobids.update(h["jobid"] for h in self._history)
        current = None if job is None or job is vm else job
        if current is not None:
            jobids.add(current.jobid)
        by_jobid = {h["jobid"]: h for h in self._history}
        jobs = []
        for jobid in sorted(jobids):
            entry: dict = {"jobid": jobid}
            # history wins over _cur_job: the launcher keeps its last
            # job object after completion, and a finished job must not
            # read as "running" between submissions
            if jobid in by_jobid:
                h = by_jobid[jobid]
                entry["state"] = "completed"
                entry["rc"] = h["rc"]
                entry["np"] = h["np"]
                entry["argv"] = h["argv"]
            elif current is not None and jobid == current.jobid:
                entry["state"] = "running"
                entry["np"] = current.np
                entry["argv"] = current.apps[0].argv
                entry["procs"] = self._proc_rows(current, {})
            entry["metrics_age_s"] = {
                str(r): round(a, 2)
                for r, a in self.metrics_agg.ages(jobid, now=now).items()}
            # the cross-rank straggler panel: per-rank collective
            # wait-time share over the last window + the current
            # slowest rank (None until latency histograms arrive)
            panel = self.metrics_agg.straggler(jobid)
            if panel is not None:
                entry["straggler"] = panel
            entry["ft_events"] = ftevents.log.snapshot(jobid)
            jobs.append(entry)
        return {
            "uptime_s": round(now - self._started_at, 1),
            "daemons": self._daemon_rows(),
            "current_jobid": (None if current is None
                              or current.jobid in by_jobid
                              else current.jobid),
            "jobs": jobs,
            "ft_events_total": ftevents.log.total(),
            "uplink": self._uplink_stats(),
        }


# -- client side -----------------------------------------------------------

def _connect(uri_or_path: Optional[str]) -> socket.socket:
    target = uri_or_path or default_uri_path()
    if os.path.exists(target):
        target = _read_uri(target)
    if ":" not in target:
        raise RuntimeError(
            f"no DVM running (uri file {target!r} not found — start one "
            f"with: tpurun --dvm-start)")
    host, port = target.rsplit(":", 1)
    try:
        return socket.create_connection((host, int(port)), timeout=30)
    except OSError as e:
        raise RuntimeError(
            f"cannot reach the DVM at {target} ({e}) — is it still "
            f"running?") from e


def submit(argv: list[str], np_: int = 1,
           env: Optional[dict] = None, cwd: Optional[str] = None,
           uri: Optional[str] = None, sink=None) -> int:
    """Run a job on a standing DVM; streams IOF to ``sink`` (default:
    this process's stdout/stderr).  Returns the job's exit code."""
    import sys

    conn = _connect(uri)
    try:
        wfile = conn.makefile("w", encoding="utf-8")
        rfile = conn.makefile("r", encoding="utf-8")
        wfile.write(json.dumps({
            "cmd": "run", "argv": argv, "np": np_,
            "env": env or {}, "cwd": cwd or os.getcwd()}) + "\n")
        wfile.flush()
        conn.settimeout(None)                 # jobs may run long
        for line in rfile:
            msg = json.loads(line)
            if "iof" in msg:
                rank, stream, text = msg["iof"]
                if sink is not None:
                    sink(rank, stream, text)
                else:
                    out = sys.stdout if stream == "out" else sys.stderr
                    out.write(f"[dvm,{rank}]{text}")
                    out.flush()
            elif "exit" in msg:
                return int(msg["exit"])
            elif "error" in msg:
                raise RuntimeError(f"dvm: {msg['error']}")
        raise RuntimeError("dvm: connection closed before job completion")
    finally:
        conn.close()


def ps(uri: Optional[str] = None) -> dict:
    """Live VM/job table (≈ orte-ps)."""
    conn = _connect(uri)
    try:
        wfile = conn.makefile("w", encoding="utf-8")
        rfile = conn.makefile("r", encoding="utf-8")
        wfile.write(json.dumps({"cmd": "ps"}) + "\n")
        wfile.flush()
        return json.loads(rfile.readline())["ps"]
    finally:
        conn.close()


def stop(uri: Optional[str] = None) -> None:
    conn = _connect(uri)
    try:
        wfile = conn.makefile("w", encoding="utf-8")
        wfile.write(json.dumps({"cmd": "stop"}) + "\n")
        wfile.flush()
        conn.makefile("r", encoding="utf-8").readline()
    finally:
        conn.close()
