"""Persistent distributed VM + live-job control plane.

≈ orte/tools/orte-dvm/orte-dvm.c:1 (a standing daemon VM that runs many
jobs without re-launching), orte/mca/state/dvm/state_dvm.c:1 (the job
lifecycle on a persistent VM: jobs come and go, daemons stay wired), and
orte/tools/orte-ps/orte-ps.c:1 (query a live VM's job/proc table).

The DVM HNP brings the daemon tree up ONCE (the expensive part — on real
pods that includes TPU runtime warm-up), writes its control URI to a
file, then serves job submissions over a line-JSON TCP control channel:

    tpurun --dvm-start --plm sim --hosts 2 --slots 8      # terminal 1
    tpurun --dvm-submit -np 4 python app.py               # terminal 2 (fast)
    tpurun --dvm-ps                                       # live proc table
    tpurun --dvm-stop

The pool is MULTI-TENANT: submissions enter a bounded admission queue
and a gang scheduler places each job atomically over the standing nodes
— all of a job's ranks get slots before any launch, least-loaded hosts
first (live ``slots_inuse`` + per-host activity from the ``ompi_tpu_job_*``
aggregates, heartbeat-dead hosts excluded) — so several jobs run
concurrently, each with its own PMIx rendezvous, jobid-tagged IOF
routing, and uuid-named shm namespace.  ``--dvm-submit`` gets a
machine-readable admission verdict (``queued`` with the depth, or
``rejected`` when the queue is full / the job can never fit) instead of
hanging at capacity.

Doctor-driven auto-remediation closes the loop on the watchdog: when a
pushed stuck event produces a straggler / deadlock / mismatch verdict
for a tenant, the remediation actor ACTS — a straggler gets a SIGCONT
probe (a SIGSTOP'd rank resumes and the job finishes) and, if it stays
wedged, a reap-and-revive onto a less-loaded host; a deadlock/mismatch
tenant is killed and requeued for a fresh placement with the doctor
capture attached; a bounded per-job budget (``dvm_remediation_max``)
degrades to a rejected verdict instead of livelocking.  Every action is
an ``ftevents`` entry and ticks ``ompi_tpu_dvm_remediations_total``.
Co-tenants are untouched throughout (kills are jobid-scoped).

Observability plane (``--metrics-port N``): a long-lived HTTP endpoint
on the DVM serving

- ``/metrics`` — Prometheus text: every rank's pvar snapshot (pushed up
  the orted tree via TAG_METRICS) labeled ``{job=,rank=}``, real
  histogram families for the latency plane (``_bucket{le=}``/``_sum``/
  ``_count``), per-job ``ompi_tpu_job_*`` sums, and the DVM's own
  process pvars;
- ``/status`` — JSON: the daemon table (heartbeat ages), the proc table
  (``lives``, restarts budget, last-metrics age, p99 collective
  latency, ``last_coll`` pushed recorder head), the per-job FT event
  timeline (detect / reap / revive / shrink / escalate / stuck /
  doctor) and the per-job straggler panel (per-rank collective
  wait-time share over the last window, max/median skew, and the
  current slowest rank);
- ``/doctor`` — JSON: an on-demand cross-rank hang capture + verdict
  (TAG_DOCTOR fan-out → per-rank recorder tails, pending p2p, stacks,
  /proc probes → mismatch / deadlock / straggler analysis).  The same
  capture fires automatically when the watchdog sees a rank push a
  stuck event (``coll_stuck_timeout``).

``--metrics-port 0`` binds an ephemeral port; the bound address is
written next to the URI file as ``<uri>.metrics``.
"""

from __future__ import annotations

import collections
import json
import os
import queue
import signal
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional

from ompi_tpu.core import output
from ompi_tpu.core.config import VarType, register_var, var_registry
from ompi_tpu.runtime import ftevents, rmaps, rml
from ompi_tpu.runtime.job import AppContext, Job, ProcState
from ompi_tpu.runtime.plm import MultiHostLauncher

__all__ = ["DvmHnp", "DvmRejected", "gang_place", "plan_remediation",
           "submit", "shrink", "ps", "stop", "default_uri_path"]

_log = output.get_stream("dvm")

register_var("dvm", "queue_max", VarType.SIZE, 8,
             "admission control: at most this many jobs may WAIT in the "
             "DVM queue; further submissions get a machine-readable "
             "rejected verdict instead of queueing without bound")
register_var("dvm", "max_concurrent", VarType.SIZE, 4,
             "at most this many jobs run on the pool at once (each still "
             "needs a full gang of free slots to start)")
register_var("dvm", "remediate", VarType.BOOL, True,
             "act on watchdog doctor verdicts (straggler → SIGCONT "
             "probe, then reap-and-revive elsewhere; deadlock/mismatch "
             "→ kill + requeue; budget exhausted → reject).  Off = the "
             "doctor only diagnoses, as before")
register_var("dvm", "remediation_max", VarType.SIZE, 2,
             "per-job remediation budget: after this many remediation "
             "actions the next actionable verdict rejects the job "
             "instead of retrying forever")
register_var("dvm", "remediate_grace_s", VarType.DOUBLE, 2.0,
             "seconds the remediation actor waits after a SIGCONT probe "
             "before re-capturing a verdict to decide recovered vs "
             "reap-and-revive")
register_var("dvm", "requeue_max", VarType.SIZE, 2,
             "how many times a remediated job may be requeued for a "
             "fresh placement before its next requeue becomes a reject")


def gang_place(nodes: list, np_: int, dead: frozenset = frozenset(),
               hb_ages: Optional[dict] = None, hb_timeout: float = 0.0,
               busy: Optional[dict] = None) -> Optional[list]:
    """Gang placement over a standing pool: pick an ordered subset of
    ``nodes`` whose free slots cover ``np_`` ranks, least-loaded host
    first — or None when the gang cannot be formed (the caller keeps the
    job queued).  All-or-nothing by construction: no slot is consumed
    here, so a partial fit never strands resources.

    - ``dead``: daemon vpids (node index + 1) already declared lost;
    - ``hb_ages``/``hb_timeout``: heartbeat ages — a host silent past
      the timeout is as good as dead for NEW placements even before the
      monitor formally declares it;
    - ``busy``: host name → activity weight from the live per-job
      metrics aggregates, so two equally-subscribed hosts tie-break
      toward the one whose tenants are idle.
    """
    hb_ages = hb_ages or {}
    busy = busy or {}
    candidates = []
    for i, n in enumerate(nodes):
        vpid = i + 1
        if vpid in dead:
            continue
        age = float(hb_ages.get(vpid, 0.0))
        if hb_timeout > 0 and age >= hb_timeout:
            continue
        if n.slots_available <= 0:
            continue
        candidates.append((n.slots_inuse + float(busy.get(n.name, 0.0)),
                           age, i, n))
    candidates.sort(key=lambda c: (c[0], c[1], c[2]))
    placed, have = [], 0
    for _load, _age, _i, n in candidates:
        placed.append(n)
        have += n.slots_available
        if have >= np_:
            return placed
    return None


def plan_remediation(kind: Optional[str], rank: int, used: int,
                     budget: int) -> str:
    """The remediation ladder, as a pure decision: doctor verdict kind +
    the job's burned budget → one of ``none`` (not actionable),
    ``sigcont_probe`` (straggler with a known rank: cheapest rung first
    — a SIGSTOP'd rank just resumes), ``requeue`` (deadlock/mismatch, or
    a straggler the doctor could not localize: this placement is
    poisoned, try a fresh one), ``reject`` (budget exhausted: degrade
    honestly instead of livelocking)."""
    if kind not in ("straggler", "deadlock", "mismatch"):
        return "none"
    if used >= budget:
        return "reject"
    if kind == "straggler" and rank >= 0:
        return "sigcont_probe"
    return "requeue"


class _Submission:
    """One queued/running job on the pool: the Job plus everything the
    scheduler, the IOF router, and the remediation actor need to know
    about it (state machine: queued → running ⇄ remediating →
    completed/rejected; a requeue goes back to queued)."""

    def __init__(self, job: Job, argv: list, np_: int, wfile) -> None:
        self.job = job
        self.argv = list(argv)
        self.np = np_
        self.wfile = wfile
        self.submitted_at = time.time()
        self.started_at: Optional[float] = None
        self.state = "queued"
        #: (node, nranks) pairs consumed from the pool — released (and
        #: possibly rebuilt by a migration) under the scheduler lock
        self.placed: list = []
        self.remediations = 0
        self.requeues = 0
        self.requeue = False           # set by the remediation actor
        self.doctor: Optional[dict] = None   # capture attached on requeue
        self.rejected_reason: Optional[str] = None
        self.done = threading.Event()


def default_uri_path() -> str:
    return os.path.join(
        os.environ.get("TMPDIR", "/tmp"),
        f"ompi_tpu-dvm-{os.getuid()}.uri")


def _read_uri(path: str) -> str:
    with open(path, encoding="utf-8") as f:
        return f.read().strip()


class DvmHnp(MultiHostLauncher):
    """The standing-VM HNP: daemon tree up once, jobs on demand."""

    def __init__(self, plm_name: str = "sim", want_tpu: bool = False,
                 uri_path: Optional[str] = None,
                 metrics_port: Optional[int] = None, **select_ctx) -> None:
        super().__init__(plm_name=plm_name, want_tpu=want_tpu,
                         stdin_target="none", **select_ctx)
        self._persistent = True
        self.metrics_port = metrics_port
        self._http: Optional[ThreadingHTTPServer] = None
        self.metrics_uri: Optional[str] = None
        self._started_at = time.time()
        self.uri_path = uri_path or default_uri_path()
        self._stopped = threading.Event()
        self._ctrl: Optional[socket.socket] = None
        self._ctrl_addr: Optional[str] = None
        # the multi-tenant scheduler plane: admission queue + live
        # submissions, all under one condition variable (NEVER nested
        # with the plm _cv — the lock-order lint enforces it)
        self._sched_cv = threading.Condition()
        self._pending: collections.deque = collections.deque()
        self._active: dict[int, _Submission] = {}   # jobid → running sub
        self._jobs_completed = 0     # counter (history is bounded)
        # jobid → the submitting client's stream: the IOF router fans a
        # tenant's output to ITS client only
        self._sinks: dict[int, Any] = {}
        # serializes writes to the client connections: IOF callbacks run
        # on per-daemon RML reader threads and would otherwise interleave
        # partial lines with each other and with the final exit reply
        self._sink_lock = threading.Lock()
        # doctor-verdict remediation: the watchdog (an RML-adjacent
        # thread) only ENQUEUES; the dedicated actor thread does the
        # blocking work (grace sleeps, re-captures) — the reader-thread
        # lint shape
        self._remed_q: queue.Queue = queue.Queue()
        self._remediations_total = 0
        self._stats: dict[int, list] = {}     # vpid → latest stat rows
        self._stats_cv = threading.Condition()
        self._stats_epoch = 0                 # fences late replies
        self._stats_lock = threading.Lock()   # one collection at a time
        # hang-doctor capture plumbing (mirrors the stats collection:
        # epoch-fenced TAG_DOCTOR_REPLY fan-in, one capture at a time)
        self._doctor: dict[int, list] = {}    # vpid → capture rows
        self._doctor_cv = threading.Condition()
        self._doctor_epoch = 0
        self._doctor_lock = threading.Lock()
        self._last_doctor: Optional[dict] = None
        # live-timeline capture plumbing (same epoch-fenced fan-in as
        # the doctor, answering TAG_TIMELINE_REPLY)
        self._timeline: dict[int, list] = {}  # vpid → capture rows
        self._timeline_cv = threading.Condition()
        self._timeline_epoch = 0
        self._timeline_lock = threading.Lock()
        self._last_timeline: Optional[dict] = None
        self._tl_captures = 0                 # self-metering: /timeline
        self._tl_merge_ns = 0                 # rounds + HNP merge cost
        #: (jobid, rank) → highest coll_stuck_events_total seen — the
        #: watchdog's new-stuck-event edge detector
        self._stuck_seen: dict[tuple, float] = {}
        self.vm_job: Optional[Job] = None
        self._history: list[dict] = []        # completed-job records

    # -- VM lifecycle ------------------------------------------------------

    def start(self, np_slots: int) -> None:
        """Allocate nodes, spawn + wire the daemon tree, open the control
        channel, write the URI file."""
        from ompi_tpu.runtime import ras

        vm = Job([AppContext(argv=["-"], np=np_slots)])
        ras.allocate(vm, want_tpu=self.want_tpu, **self.select_ctx)
        rmaps.map_job(vm, **self.select_ctx)
        self.vm_job = vm
        if not self._vm_up(vm):
            raise RuntimeError(
                f"DVM bring-up failed: {vm.abort_reason}")
        # the VM "job" map above was only sizing the daemon tree — its
        # rank count must not read as tenant load on the standing pool
        for n in vm.nodes:
            n.slots_inuse = 0
        self.rml.register_recv(rml.TAG_STATS_REPLY, self._on_stats_reply)
        self.rml.register_recv(rml.TAG_DOCTOR_REPLY,
                               self._on_doctor_reply)
        self.rml.register_recv(rml.TAG_TIMELINE_REPLY,
                               self._on_timeline_reply)
        self._ctrl = socket.create_server(("127.0.0.1", 0))
        port = self._ctrl.getsockname()[1]
        self._ctrl_addr = f"127.0.0.1:{port}"
        # metrics endpoint BEFORE the uri file: clients poll for the uri
        # file to detect "DVM up", so everything it implies (including
        # the recorded <uri>.metrics address) must exist by then
        if self.metrics_port is not None:
            self._start_metrics_server(self.metrics_port)
        with open(self.uri_path, "w", encoding="utf-8") as f:
            f.write(f"127.0.0.1:{port}\n")
        threading.Thread(target=self._accept_loop, daemon=True).start()
        threading.Thread(target=self._scheduler_loop,
                         name="dvm-scheduler", daemon=True).start()
        threading.Thread(target=self._remediation_loop,
                         name="dvm-remediator", daemon=True).start()
        _log.verbose(1, "DVM up: %d daemons, ctrl 127.0.0.1:%d (uri %s)",
                     len(vm.nodes), port, self.uri_path)

    def serve_forever(self) -> int:
        self._stopped.wait()
        return 0

    def shutdown(self) -> None:
        if self._stopped.is_set():
            return
        self._stopped.set()
        # queued tenants will never start — tell their clients so
        # instead of leaving them blocked on a dead socket
        with self._sched_cv:
            pending = list(self._pending)
            self._pending.clear()
            self._sched_cv.notify_all()
        for sub in pending:
            try:
                self._reply(sub.wfile, {"verdict": "rejected",
                                        "reason": "DVM shutting down"})
            except (OSError, ValueError):
                pass
            sub.done.set()
        try:
            self._teardown_vm()
        finally:
            if self._http is not None:
                http, self._http = self._http, None

                def _close() -> None:
                    http.shutdown()       # stop serve_forever ...
                    http.server_close()   # ... THEN release the socket

                threading.Thread(target=_close, daemon=True).start()
            if self._ctrl is not None:
                self._ctrl.close()
            for path in (self.uri_path, self.uri_path + ".metrics"):
                try:
                    os.unlink(path)
                except OSError:
                    pass

    # -- control channel ---------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                conn, _addr = self._ctrl.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            rfile = conn.makefile("r", encoding="utf-8")
            wfile = conn.makefile("w", encoding="utf-8")
            line = rfile.readline()
            if not line:
                return
            req = json.loads(line)
            cmd = req.get("cmd")
            if cmd == "run":
                self._cmd_run(req, wfile)
            elif cmd == "ps":
                self._reply(wfile, {"ps": self._ps_table()})
            elif cmd == "shrink":
                self._cmd_shrink(req, wfile)
            elif cmd == "stop":
                self._reply(wfile, {"ok": True})
                wfile.flush()
                self.shutdown()
            else:
                self._reply(wfile, {"error": f"unknown cmd {cmd!r}"})
        except (OSError, ValueError, json.JSONDecodeError) as e:
            _log.verbose(1, "control connection error: %r", e)
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _reply(self, wfile, obj: dict) -> None:
        with self._sink_lock:
            wfile.write(json.dumps(obj) + "\n")
            wfile.flush()

    # -- admission + gang scheduling on the warm VM ------------------------

    def _cmd_run(self, req: dict, wfile) -> None:
        """Admit (or reject) one submission; the client gets a verdict
        line IMMEDIATELY — queued submissions then stream IOF and the
        final exit when the scheduler gets to them."""
        argv = req.get("argv") or []
        np_ = int(req.get("np") or 1)
        if not argv:
            self._reply(wfile, {"error": "no argv"})
            return
        env = dict(req.get("env") or {})
        # elastic jobs: a tenant's MPI_Comm_spawn rides the SAME pool
        # (dpm switches to --dvm-submit when it sees this)
        if self._ctrl_addr:
            env.setdefault("OMPI_TPU_DVM_URI", self._ctrl_addr)
        job = Job([AppContext(argv=list(argv), np=np_, env=env,
                              cwd=req.get("cwd"))])
        sub = _Submission(job, argv, np_, wfile)
        pool = sum(n.slots for n in self.vm_job.nodes) if self.vm_job \
            else 0
        qmax = int(var_registry.get("dvm_queue_max") or 0)
        with self._sched_cv:
            if np_ < 1 or np_ > pool:
                verdict = {"verdict": "rejected",
                           "reason": f"np {np_} can never fit the pool "
                                     f"({pool} slots)"}
            elif len(self._pending) >= qmax:
                verdict = {"verdict": "rejected",
                           "reason": f"admission queue full "
                                     f"({qmax} waiting)"}
            else:
                self._pending.append(sub)
                verdict = {"verdict": "queued", "jobid": job.jobid,
                           "queue_depth": len(self._pending)}
                self._sched_cv.notify_all()
        self._reply(wfile, verdict)
        if verdict["verdict"] == "rejected":
            return
        sub.done.wait()                   # worker sends IOF + final exit

    def _scheduler_loop(self) -> None:
        """Place queued gangs whenever slots free up or tenants arrive."""
        while not self._stopped.is_set():
            with self._sched_cv:
                self._sched_cv.wait(timeout=0.25)
                try:
                    self._schedule_locked()
                except Exception as e:  # noqa: BLE001 — keep scheduling
                    _log.error("scheduler pass failed: %r", e)

    def _busy_by_host(self) -> dict[str, float]:
        """Host → activity weight for placement tie-breaks: each running
        rank counts 1, +0.25 when its metrics uplink pushed within 10s
        (an actively-computing tenant beats an idle one)."""
        busy: dict[str, float] = {}
        now = time.time()
        for sub in self._active.values():
            ages = self.metrics_agg.ages(sub.job.jobid, now=now)
            for p in sub.job.procs:
                if p.node is None or p.state != ProcState.RUNNING:
                    continue
                w = 1.0 + (0.25 if ages.get(p.rank, 99.0) < 10.0 else 0.0)
                busy[p.node.name] = busy.get(p.node.name, 0.0) + w
        return busy

    def _gang_place(self, np_: int) -> Optional[list]:
        hb_on = float(var_registry.get("rml_heartbeat_period") or 0) > 0
        hb_ages = (self._hb_monitor.ages()
                   if hb_on and self._hb_monitor is not None else {})
        hb_timeout = (float(var_registry.get("rml_heartbeat_timeout")
                            or 0) if hb_on else 0.0)
        return gang_place(self.vm_job.nodes if self.vm_job else [], np_,
                          dead=frozenset(self._dead_daemons),
                          hb_ages=hb_ages, hb_timeout=hb_timeout,
                          busy=self._busy_by_host())

    def _schedule_locked(self) -> None:
        """With ``_sched_cv`` held: FIFO admission with backfill — a big
        gang waiting for slots does not block a small one behind it that
        fits NOW.  Mapping runs inside the lock so two placements cannot
        race for the same slots."""
        maxc = int(var_registry.get("dvm_max_concurrent") or 1)
        for sub in list(self._pending):
            if len(self._active) >= maxc:
                return
            nodes = self._gang_place(sub.np)
            if nodes is None:
                continue                       # keep queued; try the next
            self._pending.remove(sub)
            job = sub.job
            job.nodes = nodes
            try:
                rmaps.map_job(job, **self.select_ctx)
            except Exception as e:  # noqa: BLE001 — keep the VM alive
                sub.state = "rejected"
                sub.rejected_reason = f"map failed: {e}"
                try:
                    self._reply(sub.wfile, {"error": f"map failed: {e}"})
                except (OSError, ValueError):
                    pass
                sub.done.set()
                continue
            sub.placed = [(n, len(job.procs_on(n))) for n in nodes
                          if job.procs_on(n)]
            sub.state = "running"
            sub.started_at = time.time()
            self._active[job.jobid] = sub
            threading.Thread(target=self._job_worker, args=(sub,),
                             name=f"dvm-job-{job.jobid}",
                             daemon=True).start()

    def _job_worker(self, sub: _Submission) -> None:
        """One placement attempt of one tenant: launch, wait, retire —
        then either account the job (history + exit reply) or, when the
        remediation actor flagged a requeue, put it back on the queue
        for a fresh placement."""
        job = sub.job
        t0 = time.perf_counter()
        with self._sink_lock:
            self._sinks[job.jobid] = sub.wfile
        try:
            self._launch_apps(job)
            self._wait_ranks(job)
        finally:
            with self._sink_lock:
                self._sinks.pop(job.jobid, None)
            server, job.pmix_server = job.pmix_server, None
            if server is not None:
                try:
                    server.close()
                except Exception:  # noqa: BLE001
                    pass
                if self.server is server:
                    self.server = None
            with self._cv:
                self._jobs_by_id.pop(job.jobid, None)
            # the daemons drop this job's rows/pipes (and reap any
            # lingering pid) — co-tenants' state is untouched
            if not self._stopped.is_set():
                try:
                    self.rml.xcast(rml.TAG_KILL, job.jobid)
                except Exception:  # noqa: BLE001 — tree tearing down
                    pass
        rcs = [job.exited.get(p.rank, 1) for p in job.procs]
        rc = (job.abort_status if job.abort_status
              else next((r for r in rcs if r), 0))
        if rc < 0:
            rc = 128 - rc   # signal exit, same mapping as the non-DVM path
        with self._sched_cv:
            for node, k in sub.placed:
                node.slots_inuse = max(0, node.slots_inuse - k)
            sub.placed = []
            self._active.pop(job.jobid, None)
            requeue = (sub.requeue and not self._stopped.is_set()
                       and sub.requeues
                       < int(var_registry.get("dvm_requeue_max") or 0))
            if requeue:
                sub.requeue = False
                sub.requeues += 1
                self._reset_for_requeue(sub)
                self._pending.appendleft(sub)   # remediated jobs first
            self._sched_cv.notify_all()
        if requeue:
            ftevents.record("requeue", jobid=job.jobid,
                            attempt=sub.requeues,
                            verdict=(sub.doctor or {}).get(
                                "verdict", {}).get("kind"))
            return                 # the scheduler spawns the next worker
        sub.state = "rejected" if sub.rejected_reason else "completed"
        rec = {"jobid": job.jobid, "argv": sub.argv, "np": sub.np,
               "rc": rc, "finished": time.time()}
        if sub.remediations:
            rec["remediations"] = sub.remediations
        if sub.requeues:
            rec["requeues"] = sub.requeues
        if sub.rejected_reason:
            rec["verdict"] = "rejected"
            rec["reason"] = sub.rejected_reason
        with self._sched_cv:
            self._jobs_completed += 1
            self._history.append(rec)
            # the history ring is bounded: when a record rotates out, its
            # per-rank metrics tables go with it (not only at the
            # aggregate's MAX_JOBS age eviction)
            while len(self._history) > 50:
                old = self._history.pop(0)
                self.metrics_agg.prune_job(old["jobid"])
        reply = {"exit": rc, "wall_s": round(time.perf_counter() - t0, 3)}
        if sub.rejected_reason:
            reply["verdict"] = "rejected"
            reply["reason"] = sub.rejected_reason
        try:
            self._reply(sub.wfile, reply)
        except (OSError, ValueError):
            pass                               # client went away
        sub.done.set()

    def _reset_for_requeue(self, sub: _Submission) -> None:
        """With ``_sched_cv`` held: scrub one attempt's state so the next
        placement starts clean — fresh procs/map, fresh exit table, and
        CRUCIALLY a pruned metrics aggregate + cleared stuck-event
        high-water marks (stale marks would blind the watchdog's edge
        detector to the second attempt's stuck events)."""
        job = sub.job
        job.procs = []
        job.nodes = []
        job.exited = {}
        job.killed = False
        job.aborted_proc = None
        job.abort_reason = None
        job.abort_status = None
        sub.state = "queued"
        sub.submitted_at = time.time()
        self.metrics_agg.prune_job(job.jobid)
        for key in [k for k in self._stuck_seen if k[0] == job.jobid]:
            del self._stuck_seen[key]

    def _cmd_shrink(self, req: dict, wfile) -> None:
        """Planned elastic shrink: retire one rank of a running tenant
        on purpose — ``no_revive`` keeps a reviving errmgr policy from
        resurrecting it, the reap produces the exit report, and the
        survivors continue smaller (the ULFM recipe)."""
        jobid = int(req.get("jobid") or 0)
        rank = int(req.get("rank", -1))
        with self._cv:
            job = self._jobs_by_id.get(jobid)
        if job is None:
            self._reply(wfile, {"error": f"no running job {jobid}"})
            return
        if not 0 <= rank < len(job.procs):
            self._reply(wfile, {"error": f"job {jobid} has no rank "
                                         f"{rank}"})
            return
        job.procs[rank].no_revive = True
        ftevents.record("shrink", jobid=jobid, rank=rank, planned=True)
        self._reap_reported(job, rank, "planned-shrink")
        self._reply(wfile, {"ok": True, "jobid": jobid, "rank": rank})

    def _on_iof(self, origin: int, payload) -> None:
        """Route a tenant's output to ITS submitting client (keyed by
        the jobid riding the IOF frame); fall back to the DVM's own
        stdout when no client is attached."""
        jobid, rank, stream, raw = payload
        with self._sink_lock:
            sink = self._sinks.get(int(jobid))
        if sink is None:
            return super()._on_iof(origin, payload)
        try:
            self._reply(sink, {
                "iof": [rank, stream,
                        bytes(raw).decode(errors="replace")]})
        except (OSError, ValueError):
            with self._sink_lock:              # client went away; drop
                self._sinks.pop(int(jobid), None)

    # -- introspection (≈ orte-ps / orte-top) ------------------------------

    def _on_stats_reply(self, origin: int, payload) -> None:
        vpid, epoch, rows = payload
        with self._stats_cv:
            if epoch != self._stats_epoch:
                return                # late reply from an earlier round
            self._stats[vpid] = [tuple(r) for r in rows]
            self._stats_cv.notify_all()

    def _collect_stats(self, timeout: float = 1.0) -> dict[int, dict]:
        """Pull live per-rank resource usage from every daemon
        (≈ orte-top's resusage sample): xcast the request, wait briefly
        for the tree to reply; late/dead daemons just contribute
        nothing.  Rows come back jobid-tagged (a multi-tenant daemon
        hosts several jobs' ranks) — the merge keys by jobid, then
        rank.  Serialized + epoch-fenced: concurrent ps clients must
        not clear each other's reply set, and a straggler reply from a
        timed-out round must not pass as fresh."""
        with self._stats_lock:
            n = len(self.vm_job.nodes) if self.vm_job else 0
            with self._stats_cv:
                self._stats.clear()
                self._stats_epoch += 1
                epoch = self._stats_epoch
            try:
                self.rml.xcast(rml.TAG_STATS, epoch)
            except Exception:  # noqa: BLE001 — tree tearing down
                return {}
            deadline = time.monotonic() + timeout
            with self._stats_cv:
                self._stats_cv.wait_for(
                    lambda: len(self._stats) >= n,
                    timeout=max(0.0, deadline - time.monotonic()))
                merged: dict[int, dict] = {}
                for rows in self._stats.values():
                    for jobid, rank, pid, rss, cpu_s in rows:
                        merged.setdefault(int(jobid), {})[int(rank)] = (
                            int(pid), int(rss), float(cpu_s))
            return merged

    # -- the cross-rank hang doctor ----------------------------------------

    #: the pushed recorder-head gauges (see trace.py's coll_cur_* pvars)
    _CUR_NAMES = ("coll_cur_seq", "coll_cur_kind_id", "coll_cur_cid",
                  "coll_cur_done", "coll_cur_posted_ts")

    def _on_doctor_reply(self, origin: int, payload) -> None:
        vpid, epoch, rows = payload
        with self._doctor_cv:
            if epoch != self._doctor_epoch:
                return                # late reply from an earlier round
            self._doctor[vpid] = [dict(r) for r in rows]
            self._doctor_cv.notify_all()

    def _collect_doctor(self, timeout: float = 4.0) -> list[dict]:
        """One cross-rank state snapshot: xcast TAG_DOCTOR, gather every
        daemon's per-rank captures (a silent daemon contributes nothing
        — its ranks then read as no_response at the analyzer).
        Serialized + epoch-fenced like the stats collection."""
        with self._doctor_lock:
            n = len(self.vm_job.nodes) if self.vm_job else 0
            with self._doctor_cv:
                self._doctor.clear()
                self._doctor_epoch += 1
                epoch = self._doctor_epoch
            try:
                self.rml.xcast(rml.TAG_DOCTOR, epoch)
            except Exception:  # noqa: BLE001 — tree tearing down
                return []
            deadline = time.monotonic() + timeout
            with self._doctor_cv:
                self._doctor_cv.wait_for(
                    lambda: len(self._doctor) >= n,
                    timeout=max(0.0, deadline - time.monotonic()))
                captures: list[dict] = []
                for rows in self._doctor.values():
                    captures.extend(rows)
            return captures

    def _running_job(self) -> Optional[Job]:
        """The first tenant with live ranks (for job-less /doctor and
        /timeline scrapes on a multi-tenant pool)."""
        with self._sched_cv:
            for sub in self._active.values():
                if any(p.state == ProcState.RUNNING
                       for p in sub.job.procs):
                    return sub.job
        return None

    def _doctor_doc(self, trigger: str, job: Optional[Job] = None) -> dict:
        """The /doctor document: live capture + analyzer verdict while a
        job runs; the cached last verdict (or idle) otherwise.  On a
        multi-tenant pool the capture is scoped to ONE job (the caller's,
        or the first running tenant): daemons stamp every capture row
        with its jobid, and a co-tenant's rows must never leak into
        another tenant's verdict."""
        from ompi_tpu.runtime import doctor

        if job is None:
            job = self._running_job()
        running = (job is not None
                   and any(p.state == ProcState.RUNNING
                           for p in job.procs))
        if not running:
            if self._last_doctor is not None:
                return dict(self._last_doctor, stale=True)
            return {"trigger": trigger, "ts": time.time(),
                    "verdict": {"kind": "idle",
                                "detail": "no job running and no "
                                          "cached verdict"}}
        rows = [c for c in self._collect_doctor()
                if int(c.get("jobid", job.jobid)) == job.jobid]
        # hierarchical capture: daemons over their doctor_rows_per_daemon
        # budget pre-aggregate the healthy middle into explicit summary
        # rows — split those out (the analyzer wants per-rank rows; the
        # document still reports what was compressed and says truncated)
        captures = [c for c in rows if not c.get("summary")]
        summaries = [c for c in rows if c.get("summary")]
        # a frozen rank's last uplink-pushed recorder head stands in for
        # the capture it can no longer give
        pushed = self.metrics_agg.rank_values(job.jobid, self._CUR_NAMES)
        for c in captures:
            if c.get("no_response") and int(c.get("rank", -1)) in pushed:
                c["pushed"] = pushed[int(c["rank"])]
        doc = doctor.analyze(captures, nranks=job.np)
        if summaries:
            doc["truncated"] = True
            doc["ranks_summarized"] = sum(
                int(s.get("ranks_omitted", 0)) for s in summaries)
            doc["host_summaries"] = summaries
        doc["trigger"] = trigger
        doc["jobid"] = job.jobid
        doc["ts"] = time.time()
        v = doc.get("verdict") or {}
        # only verdicts worth remembering reach the FT timeline: a
        # dashboard polling /doctor every few seconds against a healthy
        # job must not flush real failure history out of the bounded
        # event ring (watchdog-triggered captures always record)
        if trigger == "watchdog" or v.get("kind") not in (
                "healthy", "idle", "no_data"):
            ftevents.record(
                "doctor", jobid=job.jobid, rank=int(v.get("rank", -1)),
                verdict=v.get("kind"), trigger=trigger,
                detail=(v.get("detail") or "")[:300])
        self._last_doctor = doc
        return doc

    def _doctor_watch(self) -> None:
        """The watchdog: a rank whose coll_stuck_events_total rose since
        the last tick pushed a stuck event up the uplink — record it on
        the FT timeline, auto-capture a per-tenant verdict, and (when
        ``dvm_remediate`` is on) hand actionable verdicts to the
        remediation actor.  Every running tenant is watched each tick;
        captures are jobid-scoped so co-tenants never cross-trigger."""
        while not self._stopped.wait(1.0):
            with self._sched_cv:
                subs = [s for s in self._active.values()
                        if s.state in ("running", "remediating")]
            live = {s.job.jobid for s in subs}
            # a standing DVM serves many jobs: drop retired jobs'
            # edge-detector keys so the dict stays bounded
            for key in [k for k in self._stuck_seen if k[0] not in live]:
                del self._stuck_seen[key]
            for sub in subs:
                try:
                    self._watch_one(sub)
                except Exception as e:  # noqa: BLE001 — watchdog survives
                    _log.verbose(1, "doctor watchdog tick failed: %r", e)

    def _watch_one(self, sub: _Submission) -> None:
        jobid = sub.job.jobid
        rows = self.metrics_agg.rank_values(
            jobid, ("coll_stuck_events_total",))
        newly = []
        for rank, vals in sorted(rows.items()):
            v = float(vals.get("coll_stuck_events_total", 0))
            key = (jobid, rank)
            if v > self._stuck_seen.get(key, 0.0):
                self._stuck_seen[key] = v
                newly.append((rank, int(v)))
        if not newly:
            return
        for rank, n in newly:
            ftevents.record("stuck", jobid=jobid, rank=rank, events=n)
        doc = self._doctor_doc("watchdog", job=sub.job)
        v = doc.get("verdict") or {}
        if (bool(var_registry.get("dvm_remediate"))
                and v.get("kind") in ("straggler", "deadlock", "mismatch")
                and sub.state == "running"):
            # the actor does the blocking work (grace sleeps, kills,
            # re-captures) on its own thread; this path stays cheap
            self._remed_q.put((sub, doc))

    # -- doctor-driven auto-remediation ------------------------------------

    def _remediation_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                sub, doc = self._remed_q.get(timeout=0.5)
            except queue.Empty:
                continue
            try:
                self._remediate(sub, doc)
            except Exception as e:  # noqa: BLE001 — actor survives
                _log.error("remediation of job %d failed: %r",
                           sub.job.jobid, e)
                with self._sched_cv:
                    if sub.state == "remediating":
                        sub.state = "running"

    def _remediate(self, sub: _Submission, doc: dict) -> None:
        """Act on one watchdog verdict, one rung at a time (see
        ``plan_remediation``).  The budget check and the state flip are
        atomic under the scheduler lock, so a burst of verdicts from
        consecutive ticks collapses into one action."""
        job = sub.job
        v = doc.get("verdict") or {}
        kind = v.get("kind")
        rank = int(v.get("rank", -1))
        budget = int(var_registry.get("dvm_remediation_max") or 0)
        with self._sched_cv:
            if sub.state != "running" or job.killed:
                return                 # already being handled / retired
            action = plan_remediation(kind, rank, sub.remediations,
                                      budget)
            if action == "none":
                return
            sub.state = "remediating"
            if action != "reject":
                sub.remediations += 1
            self._remediations_total += 1
        t0 = time.monotonic_ns()
        try:
            if action == "sigcont_probe":
                self._probe_straggler(sub, rank, kind, t0)
            elif action == "requeue":
                sub.doctor = doc
                sub.requeue = True
                ftevents.record("remediate", jobid=job.jobid, rank=rank,
                                action="requeue", verdict=kind,
                                attempt=sub.remediations)
                _log.verbose(0, "remediation: job %d verdict %s — kill "
                             "+ requeue (attempt %d/%d)", job.jobid,
                             kind, sub.remediations, budget)
                self.kill_job(job)
            elif action == "reject":
                sub.rejected_reason = (
                    f"remediation budget exhausted "
                    f"({sub.remediations}/{budget} used; last verdict "
                    f"{kind})")
                ftevents.record("remediate", jobid=job.jobid, rank=rank,
                                action="reject", verdict=kind)
                _log.verbose(0, "remediation: job %d verdict %s — budget "
                             "exhausted, rejecting", job.jobid, kind)
                self.kill_job(job)
        finally:
            with self._sched_cv:
                if sub.state == "remediating":
                    sub.state = "running"

    def _probe_straggler(self, sub: _Submission, rank: int, kind: str,
                         t0: int) -> None:
        """Straggler rung 1: SIGCONT the rank's process group via its
        owning daemon (a faultinjected stall@coll self-SIGSTOPs — the
        probe genuinely resumes it), wait the grace window, re-capture.
        Recovered → done; still wedged → reap-and-revive on a
        less-loaded host (rung 2)."""
        job = sub.job
        ftevents.record("remediate", jobid=job.jobid, rank=rank,
                        action="sigcont", verdict=kind,
                        attempt=sub.remediations)
        try:
            self.rml.xcast(rml.TAG_SIGNAL_RANK,
                           (job.jobid, rank, int(signal.SIGCONT)))
        except Exception as e:  # noqa: BLE001 — tree tearing down
            _log.error("SIGCONT probe xcast for job %d rank %d "
                       "failed: %r", job.jobid, rank, e)
            return
        self._stopped.wait(
            float(var_registry.get("dvm_remediate_grace_s") or 2.0))
        doc = self._doctor_doc("remediation", job=job)
        after = doc.get("verdict") or {}
        with self._cv:
            finished = len(job.exited) >= job.np
        # a job that finished during the grace window plainly recovered;
        # a stale doc (no live capture possible) can't testify that the
        # rank is still wedged — never reap ranks of a completed job
        still = (not finished and not doc.get("stale")
                 and after.get("kind") in ("straggler", "deadlock",
                                           "mismatch"))
        if not still:
            ftevents.record(
                "remediate", jobid=job.jobid, rank=rank,
                action="recovered", verdict=after.get("kind"),
                latency_ms=round((time.monotonic_ns() - t0) / 1e6, 1))
            _log.verbose(0, "remediation: job %d rank %d recovered after "
                         "SIGCONT probe", job.jobid, rank)
            return
        self._revive_elsewhere(sub, rank,
                               f"rank stayed {after.get('kind')} after "
                               f"the SIGCONT probe")

    def _revive_elsewhere(self, sub: _Submission, rank: int,
                          why: str) -> None:
        """Straggler rung 2: migrate the wedged rank — retarget its proc
        to the least-loaded OTHER live host (slot accounting moves with
        it), then reap it through the tree.  The exit report runs the
        errmgr; under a reviving policy (selfheal/respawn) the
        TAG_RESPAWN order carries the NEW placement, so the rank's next
        life boots on the new host.  Under a non-reviving policy this
        degrades to that policy's normal failure handling."""
        job = sub.job
        if not 0 <= rank < len(job.procs):
            return
        proc = job.procs[rank]
        with self._sched_cv:
            pool = self.vm_job.nodes if self.vm_job else []
            cands = [n for i, n in enumerate(pool)
                     if (i + 1) not in self._dead_daemons
                     and n is not proc.node and n.slots_available > 0]
            cands.sort(key=lambda n: n.slots_inuse)
            target = cands[0] if cands else None
            if target is not None:
                old = proc.node
                proc.node = target
                target.slots_inuse += 1
                if old is not None:
                    old.slots_inuse = max(0, old.slots_inuse - 1)
                placed, seen = [], False
                for n, k in sub.placed:
                    if n is old:
                        k -= 1
                    if n is target:
                        k += 1
                        seen = True
                    if k > 0:
                        placed.append((n, k))
                if not seen:
                    placed.append((target, 1))
                sub.placed = placed
        ftevents.record("remediate", jobid=job.jobid, rank=rank,
                        action="revive",
                        target=(proc.node.name if proc.node else "?"),
                        why=why)
        _log.verbose(0, "remediation: job %d rank %d — reap and revive "
                     "on %s (%s)", job.jobid, rank,
                     proc.node.name if proc.node else "?", why)
        self._reap_reported(job, rank, f"dvm-remediation: {why}")

    # -- the live cross-rank timeline --------------------------------------

    def _on_timeline_reply(self, origin: int, payload) -> None:
        vpid, epoch, rows = payload
        with self._timeline_cv:
            if epoch != self._timeline_epoch:
                return                # late reply from an earlier round
            self._timeline[vpid] = [dict(r) for r in rows]
            self._timeline_cv.notify_all()

    def _collect_timeline(self, tail: int,
                          timeout: float = 4.0) -> list[dict]:
        """One live trace capture: xcast TAG_TIMELINE, gather every
        daemon's per-rank recorder tails (each stamped with the
        daemon's measured clock offset-to-root).  Serialized +
        epoch-fenced like the doctor collection."""
        with self._timeline_lock:
            n = len(self.vm_job.nodes) if self.vm_job else 0
            with self._timeline_cv:
                self._timeline.clear()
                self._timeline_epoch += 1
                epoch = self._timeline_epoch
            try:
                self.rml.xcast(rml.TAG_TIMELINE, (epoch, int(tail)))
            except Exception:  # noqa: BLE001 — tree tearing down
                return []
            deadline = time.monotonic() + timeout
            with self._timeline_cv:
                self._timeline_cv.wait_for(
                    lambda: len(self._timeline) >= n,
                    timeout=max(0.0, deadline - time.monotonic()))
                captures: list[dict] = []
                for rows in self._timeline.values():
                    captures.extend(rows)
            return captures

    def _timeline_doc(self, tail: int = 2048) -> dict:
        """The /timeline document: a merged, skew-corrected Chrome
        trace of the RUNNING job (live TAG_TIMELINE round); the cached
        last capture (marked stale) otherwise."""
        from ompi_tpu.runtime import timeline as timeline_mod

        job = self._running_job()
        if job is None:
            if self._last_timeline is not None:
                doc = dict(self._last_timeline)
                doc["otherData"] = dict(doc.get("otherData") or {},
                                        stale=True)
                return doc
            return {"displayTimeUnit": "ns", "traceEvents": [],
                    "otherData": {"idle": True,
                                  "detail": "no job running and no "
                                            "cached capture"}}
        captures = [c for c in self._collect_timeline(tail)
                    if int(c.get("jobid", job.jobid)) == job.jobid]
        t0 = time.monotonic_ns()    # merge cost alone, not the fan-in
        doc = timeline_mod.merge_captures(captures, jobid=job.jobid)
        merge_ns = time.monotonic_ns() - t0
        with self._timeline_cv:
            self._tl_captures += 1
            self._tl_merge_ns += merge_ns
        doc["otherData"]["ts"] = time.time()
        doc["otherData"]["merge_ms"] = round(merge_ns / 1e6, 2)
        self._last_timeline = doc
        return doc

    def _daemon_rows(self) -> list[dict]:
        vm = self.vm_job
        if vm is None:
            return []
        # only meaningful with the heartbeat layer armed: without beats
        # every watched daemon's age grows forever and the column reads
        # as a fleet of silent daemons
        hb_on = float(var_registry.get("rml_heartbeat_period") or 0) > 0
        hb_ages = (self._hb_monitor.ages()
                   if hb_on and self._hb_monitor is not None else {})
        rows = []
        for i, n in enumerate(vm.nodes):
            row = {"vpid": i + 1, "host": n.name, "slots": n.slots,
                   "slots_inuse": n.slots_inuse,
                   "chips": (len(n.chips) if n.chips else 0),
                   "pid": (self._daemon_popen[i].pid
                           if i < len(self._daemon_popen) else None)}
            if i + 1 in hb_ages:
                row["hb_age_s"] = round(hb_ages[i + 1], 2)
            rows.append(row)
        return rows

    def _proc_rows(self, job, usage: dict[int, tuple]) -> list[dict]:
        from ompi_tpu.mpi import trace as trace_mod

        metrics_ages = self.metrics_agg.ages(job.jobid)
        p99s = self.metrics_agg.job_hist_quantiles(
            job.jobid, "coll_dispatch_ns", 0.99)
        heads = self.metrics_agg.rank_values(job.jobid, self._CUR_NAMES)
        rejoins = self.metrics_agg.rank_values(job.jobid,
                                               ("coll_rejoin_total",))
        traces = self.metrics_agg.rank_values(
            job.jobid, ("trace_dropped_total", "trace_ring_occupancy",
                        "trace_ring_capacity", "rank_clock_to_root_ns"))
        limit = int(var_registry.get("errmgr_max_restarts") or 0)
        procs = []
        for p in job.procs:
            row = {
                "rank": p.rank, "state": p.state.value,
                "host": p.node.name if p.node else "?",
                "local_rank": p.local_rank,
                # lives is the monotone revive count (the announced
                # incarnation); restarts is the governor's crash-loop
                # BUDGET counter, reset whenever a life earns its
                # uptime — it reads 0 for a rank revived many times
                "lives": p.lives,
                "restarts": p.restarts,
                "restarts_budget_left": max(0, limit - p.restarts),
                "exit_code": p.exit_code,
            }
            if p.rank in metrics_ages:
                # age of the rank's last pvar push through the uplink —
                # a live rank whose age keeps growing has a stalled
                # metrics plane (or a stalled rank)
                row["metrics_age_s"] = round(metrics_ages[p.rank], 2)
            if p.rank in p99s:
                # tail collective latency from the rank's pushed
                # histogram (the --dvm-ps p99 column)
                row["coll_p99_us"] = round(p99s[p.rank] / 1e3, 1)
            rj = rejoins.get(p.rank, {}).get("coll_rejoin_total")
            if rj:
                # epoch-fenced coll-hierarchy rebuilds this rank ran
                # after adopted revives (the rejoin half of selfheal) —
                # a rank whose lives grew without peers' rejoins
                # ticking is p2p-only recovered, not collective-capable
                row["rejoins"] = int(rj)
            tv = traces.get(p.rank)
            if tv is not None:
                # flight-recorder health from the pushed trace pvars: a
                # rank whose ring keeps dropping needs a bigger capacity
                # (or a narrower event set) before its captures lie
                cap = tv.get("trace_ring_capacity")
                if cap:
                    row["trace_ring"] = (
                        f"{int(tv.get('trace_ring_occupancy', 0))}"
                        f"/{int(cap)}")
                dropped = tv.get("trace_dropped_total")
                if dropped:
                    row["trace_dropped"] = int(dropped)
                # measured monotonic offset of the rank's host to the
                # HNP's clock domain (the skew /timeline corrects by)
                off = tv.get("rank_clock_to_root_ns")
                if off is not None:
                    row["clock_off_us"] = round(float(off) / 1e3, 1)
            hv = heads.get(p.rank)
            if hv is not None and hv.get("coll_cur_seq", -1) >= 0:
                # the pushed recorder head: the rank's last collective
                # as kind#seq ("!" = still in flight at push time) plus
                # its age — a wedged rank is visible here without a
                # full doctor capture
                kind = trace_mod.collrec_kind_name(
                    int(hv.get("coll_cur_kind_id", -1)))
                mark = "" if hv.get("coll_cur_done") else "!"
                row["last_coll"] = \
                    f'{kind}#{int(hv["coll_cur_seq"])}{mark}'
                ts = float(hv.get("coll_cur_posted_ts", 0.0))
                if ts > 0:
                    row["last_coll_age_s"] = round(
                        max(0.0, time.time() - ts), 2)
            if p.rank in usage:      # orte-top columns, live ranks
                pid, rss, cpu_s = usage[p.rank]
                row.update(pid=pid, rss_mb=round(rss / 2**20, 1),
                           cpu_s=round(cpu_s, 2))
            procs.append(row)
        return procs

    def _sub_row(self, sub: _Submission, now: float) -> dict:
        row = {"jobid": sub.job.jobid, "state": sub.state, "np": sub.np,
               "argv": sub.argv}
        if sub.state == "queued":
            row["queue_age_s"] = round(now - sub.submitted_at, 2)
        else:
            row["placement"] = sorted({p.node.name
                                       for p in sub.job.procs if p.node})
        if sub.remediations:
            row["remediations"] = sub.remediations
        if sub.requeues:
            row["requeues"] = sub.requeues
        return row

    def _ps_table(self) -> dict:
        now = time.time()
        with self._sched_cv:
            active = list(self._active.values())
            queued = list(self._pending)
        run_subs = [s for s in active
                    if s.state in ("running", "remediating")]
        usage = self._collect_stats() if run_subs else {}
        cur = run_subs[0] if run_subs else None
        jobs = ([self._sub_row(s, now) for s in queued]
                + [self._sub_row(s, now) for s in active])
        return {"daemons": self._daemon_rows(),
                "current_job": (None if cur is None else {
                    "jobid": cur.job.jobid,
                    "argv": cur.argv,
                    "np": cur.np,
                    "procs": self._proc_rows(
                        cur.job, usage.get(cur.job.jobid, {}))}),
                "jobs": jobs,
                "queue_depth": len(queued),
                "history": self._history[-20:]}

    # -- observability plane (≈ a standing Prometheus exporter) ------------

    def _start_metrics_server(self, port: int) -> None:
        """The long-lived scrape endpoint: /metrics + /status."""
        hnp = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler API
                path, _, query = self.path.partition("?")
                path = path.rstrip("/") or "/"
                if path == "/metrics":
                    body = hnp._metrics_text().encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif path == "/status":
                    body = json.dumps(hnp._status_doc()).encode()
                    ctype = "application/json"
                elif path == "/doctor":
                    # on-demand cross-rank hang capture + verdict (a
                    # live TAG_DOCTOR round while a job runs; blocking
                    # a handler thread for the collection window is
                    # fine — the server is threading)
                    body = json.dumps(
                        hnp._doctor_doc("scrape")).encode()
                    ctype = "application/json"
                elif path == "/timeline":
                    # live merged cross-rank trace (TAG_TIMELINE round
                    # while a job runs); ?tail=N bounds the per-rank
                    # recorder tail pulled from each rank
                    tail = 2048
                    for part in query.split("&"):
                        if part.startswith("tail="):
                            try:
                                tail = max(1, int(part[5:]))
                            except ValueError:
                                pass
                    body = json.dumps(hnp._timeline_doc(tail)).encode()
                    ctype = "application/json"
                elif path == "/":
                    body = (b"ompi_tpu dvm: /metrics /status /doctor "
                            b"/timeline\n")
                    ctype = "text/plain"
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args) -> None:
                pass  # scrapes every few seconds must not spam stderr

        self._http = ThreadingHTTPServer(("127.0.0.1", port), _Handler)
        self._http.daemon_threads = True
        bound = self._http.server_address[1]
        self.metrics_uri = f"http://127.0.0.1:{bound}"
        threading.Thread(target=self._http.serve_forever,
                         name="dvm-metrics-http", daemon=True).start()
        # the hang-doctor watchdog rides the observability plane: a
        # pushed stuck event auto-triggers a cross-rank capture
        threading.Thread(target=self._doctor_watch,
                         name="dvm-doctor-watch", daemon=True).start()
        # --metrics-port 0 binds an ephemeral port: record the actual
        # address where clients (tests, dashboards) can find it
        try:
            with open(self.uri_path + ".metrics", "w",
                      encoding="utf-8") as f:
                f.write(self.metrics_uri + "\n")
        except OSError:
            pass
        _log.verbose(0, "metrics endpoint: %s/metrics  %s/status",
                     self.metrics_uri, self.metrics_uri)

    def _metrics_text(self) -> str:
        """Prometheus text: the per-job/per-rank aggregate first, then
        DVM-level gauges, then this process's own pvars (unlabeled).

        The own-pvar section EXCLUDES any metric name the aggregate
        already emitted: the exposition format forbids a second # TYPE
        line (and a second, non-contiguous sample group) for a name —
        a real scraper would reject the whole page, and the HNP's own
        copies of rank counters are all-zero noise anyway."""
        from ompi_tpu.mpi import trace as trace_mod

        agg_text = self.metrics_agg.prometheus()
        agg_names = {line.split("{", 1)[0]
                     for line in agg_text.splitlines()
                     if line and not line.startswith("#")}
        own_lines = []
        skip_until_next_metric = False
        for line in trace_mod.metrics_snapshot().splitlines():
            if line.startswith("#"):
                name = line.split()[2] if len(line.split()) > 2 else ""
                skip_until_next_metric = name in agg_names
            else:
                skip_until_next_metric = \
                    line.split("{", 1)[0].split(" ", 1)[0] in agg_names
            if not skip_until_next_metric:
                own_lines.append(line)
        own = "\n".join(own_lines) + ("\n" if own_lines else "")
        with self._sched_cv:
            completed = self._jobs_completed
            qdepth = len(self._pending)
            running = len(self._active)
            remediations = self._remediations_total
        dvm_lines = [
            "# TYPE ompi_tpu_dvm_jobs_completed_total counter",
            f"ompi_tpu_dvm_jobs_completed_total {completed}",
            "# TYPE ompi_tpu_dvm_queue_depth gauge",
            f"ompi_tpu_dvm_queue_depth {qdepth}",
            "# TYPE ompi_tpu_dvm_jobs_running gauge",
            f"ompi_tpu_dvm_jobs_running {running}",
            "# TYPE ompi_tpu_dvm_remediations_total counter",
            f"ompi_tpu_dvm_remediations_total {remediations}",
            "# TYPE ompi_tpu_dvm_daemons gauge",
            f"ompi_tpu_dvm_daemons "
            f"{len(self.vm_job.nodes) if self.vm_job else 0}",
            "# TYPE ompi_tpu_dvm_uptime_seconds gauge",
            f"ompi_tpu_dvm_uptime_seconds "
            f"{time.time() - self._started_at:.1f}",
            "# TYPE ompi_tpu_dvm_ft_events_total counter",
            f"ompi_tpu_dvm_ft_events_total {ftevents.log.total()}",
            "# TYPE ompi_tpu_dvm_metrics_sheds_total counter",
            f"ompi_tpu_dvm_metrics_sheds_total "
            f"{getattr(self.metrics_agg, 'sheds_total', 0)}",
            "# TYPE ompi_tpu_dvm_metrics_shed_rows_total counter",
            f"ompi_tpu_dvm_metrics_shed_rows_total "
            f"{getattr(self.metrics_agg, 'shed_rows_total', 0)}",
        ]
        return agg_text + "\n".join(dvm_lines) + "\n" + own

    def _uplink_stats(self) -> dict:
        """Telemetry about the telemetry: what the metrics uplink and
        the timeline plane themselves cost (the /status block that
        answers "is observability eating my run?")."""
        stats = getattr(self.metrics_agg, "stats", lambda: {})()
        doc: dict = {"hnp_merges_total": stats.get("merges_total", 0),
                     "hnp_merge_ms_total": round(
                         stats.get("merge_ns_total", 0) / 1e6, 2),
                     # the shed-and-count fan-in policy's ledger: how
                     # many payloads (and rank-rows) overload cost
                     "hnp_sheds_total": stats.get("sheds_total", 0),
                     "hnp_shed_rows_total": stats.get(
                         "shed_rows_total", 0)}
        # rank-side push cost, summed from the pushed self-metering
        # counters (the ranks meter their own uplink datagrams)
        dgrams = nbytes = 0.0
        for jobid in self.metrics_agg.jobids():
            for vals in self.metrics_agg.rank_values(
                    jobid, ("metrics_push_datagrams_total",
                            "metrics_push_bytes_total")).values():
                dgrams += float(
                    vals.get("metrics_push_datagrams_total", 0))
                nbytes += float(vals.get("metrics_push_bytes_total", 0))
        doc["rank_push_datagrams_total"] = int(dgrams)
        doc["rank_push_bytes_total"] = int(nbytes)
        up = max(1e-9, time.time() - self._started_at)
        doc["rank_push_bytes_per_s"] = round(nbytes / up, 1)
        with self._timeline_cv:
            doc["timeline_captures_total"] = self._tl_captures
            doc["timeline_merge_ms_total"] = round(
                self._tl_merge_ns / 1e6, 2)
        return doc

    def _status_doc(self) -> dict:
        """The /status JSON: daemon table (heartbeat ages), the queue
        (depth + per-job queue age), per-job proc/placement tables
        (lives, restarts budget, last-metrics age, remediations) and the
        FT event timeline per job."""
        now = time.time()
        with self._sched_cv:
            active = {s.job.jobid: s for s in self._active.values()}
            queued = {s.job.jobid: s for s in self._pending}
            qdepth = len(self._pending)
            remediations = self._remediations_total
        jobids = set(self.metrics_agg.jobids())
        jobids.update(h["jobid"] for h in self._history)
        jobids.update(active)
        jobids.update(queued)
        by_jobid = {h["jobid"]: h for h in self._history}
        jobs = []
        for jobid in sorted(jobids):
            entry: dict = {"jobid": jobid}
            # history wins over the live tables: a finished job must not
            # read as "running" from a stale submission record
            if jobid in by_jobid:
                h = by_jobid[jobid]
                entry["state"] = "completed"
                entry["rc"] = h["rc"]
                entry["np"] = h["np"]
                entry["argv"] = h["argv"]
                for k in ("remediations", "requeues", "verdict",
                          "reason"):
                    if k in h:
                        entry[k] = h[k]
            elif jobid in active or jobid in queued:
                sub = active.get(jobid) or queued[jobid]
                entry.update(self._sub_row(sub, now))
                if jobid in active:
                    entry["procs"] = self._proc_rows(sub.job, {})
            entry["metrics_age_s"] = {
                str(r): round(a, 2)
                for r, a in self.metrics_agg.ages(jobid, now=now).items()}
            # the cross-rank straggler panel: per-rank collective
            # wait-time share over the last window + the current
            # slowest rank (None until latency histograms arrive)
            panel = self.metrics_agg.straggler(jobid)
            if panel is not None:
                entry["straggler"] = panel
            entry["ft_events"] = ftevents.log.snapshot(jobid)
            jobs.append(entry)
        running_ids = sorted(j for j, s in active.items()
                             if s.state in ("running", "remediating"))
        return {
            "uptime_s": round(now - self._started_at, 1),
            "daemons": self._daemon_rows(),
            "current_jobid": (running_ids[0] if running_ids else None),
            "running": len(running_ids),
            "queue_depth": qdepth,
            "remediations_total": remediations,
            "jobs": jobs,
            "ft_events_total": ftevents.log.total(),
            "ft_events_dropped": ftevents.log.dropped(),
            "uplink": self._uplink_stats(),
        }


# -- client side -----------------------------------------------------------

class DvmRejected(RuntimeError):
    """The DVM's admission control (or its remediation governor) refused
    the job.  ``verdict`` holds the machine-readable reply — callers can
    distinguish a full queue from a never-fits np from an exhausted
    remediation budget and react (retry later, shrink, give up)."""

    def __init__(self, verdict: dict) -> None:
        super().__init__(verdict.get("reason") or "rejected by the DVM")
        self.verdict = dict(verdict)


def _connect(uri_or_path: Optional[str]) -> socket.socket:
    target = uri_or_path or default_uri_path()
    if os.path.exists(target):
        target = _read_uri(target)
    if ":" not in target:
        raise RuntimeError(
            f"no DVM running (uri file {target!r} not found — start one "
            f"with: tpurun --dvm-start)")
    host, port = target.rsplit(":", 1)
    try:
        return socket.create_connection((host, int(port)), timeout=30)
    except OSError as e:
        raise RuntimeError(
            f"cannot reach the DVM at {target} ({e}) — is it still "
            f"running?") from e


def submit(argv: list[str], np_: int = 1,
           env: Optional[dict] = None, cwd: Optional[str] = None,
           uri: Optional[str] = None, sink=None,
           on_verdict=None) -> int:
    """Run a job on a standing DVM; streams IOF to ``sink`` (default:
    this process's stdout/stderr).  Returns the job's exit code.

    The first reply line is the admission verdict: ``queued`` (keep
    streaming — ``on_verdict`` sees it, with the assigned jobid and the
    queue depth) or ``rejected``, which raises :class:`DvmRejected`
    immediately instead of blocking forever on a full pool."""
    import sys

    conn = _connect(uri)
    try:
        wfile = conn.makefile("w", encoding="utf-8")
        rfile = conn.makefile("r", encoding="utf-8")
        wfile.write(json.dumps({
            "cmd": "run", "argv": argv, "np": np_,
            "env": env or {}, "cwd": cwd or os.getcwd()}) + "\n")
        wfile.flush()
        conn.settimeout(None)                 # jobs may run long
        for line in rfile:
            msg = json.loads(line)
            if "iof" in msg:
                rank, stream, text = msg["iof"]
                if sink is not None:
                    sink(rank, stream, text)
                else:
                    out = sys.stdout if stream == "out" else sys.stderr
                    out.write(f"[dvm,{rank}]{text}")
                    out.flush()
            elif "verdict" in msg:
                if msg["verdict"] == "rejected":
                    raise DvmRejected(msg)
                if on_verdict is not None:
                    on_verdict(msg)
            elif "exit" in msg:
                return int(msg["exit"])
            elif "error" in msg:
                raise RuntimeError(f"dvm: {msg['error']}")
        raise RuntimeError("dvm: connection closed before job completion")
    finally:
        conn.close()


def shrink(jobid: int, rank: int, uri: Optional[str] = None) -> dict:
    """Planned elastic shrink: retire one rank of a running DVM job on
    purpose (no revive; survivors continue smaller per ULFM)."""
    conn = _connect(uri)
    try:
        wfile = conn.makefile("w", encoding="utf-8")
        rfile = conn.makefile("r", encoding="utf-8")
        wfile.write(json.dumps({"cmd": "shrink", "jobid": int(jobid),
                                "rank": int(rank)}) + "\n")
        wfile.flush()
        msg = json.loads(rfile.readline())
        if "error" in msg:
            raise RuntimeError(f"dvm: {msg['error']}")
        return msg
    finally:
        conn.close()


def ps(uri: Optional[str] = None) -> dict:
    """Live VM/job table (≈ orte-ps)."""
    conn = _connect(uri)
    try:
        wfile = conn.makefile("w", encoding="utf-8")
        rfile = conn.makefile("r", encoding="utf-8")
        wfile.write(json.dumps({"cmd": "ps"}) + "\n")
        wfile.flush()
        return json.loads(rfile.readline())["ps"]
    finally:
        conn.close()


def stop(uri: Optional[str] = None) -> None:
    conn = _connect(uri)
    try:
        wfile = conn.makefile("w", encoding="utf-8")
        wfile.write(json.dumps({"cmd": "stop"}) + "\n")
        wfile.flush()
        conn.makefile("r", encoding="utf-8").readline()
    finally:
        conn.close()
