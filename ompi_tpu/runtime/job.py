"""Job / Node / Proc data model.

TPU-native analog of the reference's job objects
(orte/runtime/orte_globals.h:215-342: orte_job_t, orte_node_t, orte_proc_t).
A Node is a host (optionally with TPU chips); a slot is one rank's worth of
resources (a core, or a chip in device-per-rank mode).
"""

from __future__ import annotations

import dataclasses
import enum
import itertools
from typing import Any, Optional

__all__ = ["JobState", "ProcState", "Node", "Proc", "AppContext", "Job"]


class JobState(enum.Enum):
    """Job lifecycle (subset of ORTE_JOB_STATE_*, orte_globals.h)."""

    INIT = "init"
    ALLOCATE = "allocate"
    ALLOCATION_COMPLETE = "allocation_complete"
    MAP = "map"
    MAP_COMPLETE = "map_complete"
    LAUNCH_APPS = "launch_apps"
    RUNNING = "running"
    TERMINATED = "terminated"
    ABORTED = "aborted"


class ProcState(enum.Enum):
    """Proc lifecycle (subset of ORTE_PROC_STATE_*)."""

    INIT = "init"
    LAUNCHED = "launched"
    RUNNING = "running"
    TERMINATED = "terminated"
    ABORTED = "aborted"
    FAILED_TO_START = "failed_to_start"
    KILLED_BY_CMD = "killed_by_cmd"


@dataclasses.dataclass
class Node:
    """A host with schedulable slots (≈ orte_node_t)."""

    name: str
    slots: int = 1
    # TPU metadata: chip coordinates for device-per-rank mapping, or None.
    chips: Optional[list[Any]] = None
    slots_inuse: int = 0
    topology: Optional[dict] = None  # fake hwloc-ish topology from simulator

    @property
    def slots_available(self) -> int:
        return max(0, self.slots - self.slots_inuse)


@dataclasses.dataclass
class Proc:
    """One rank of the job (≈ orte_proc_t)."""

    rank: int
    node: Optional[Node] = None
    slot: Optional[int] = None
    chip: Optional[Any] = None
    app_idx: int = 0  # which AppContext this rank runs
    state: ProcState = ProcState.INIT
    pid: Optional[int] = None
    exit_code: Optional[int] = None
    local_rank: int = 0  # rank among procs on the same node
    # crash-loop BUDGET counter: revives since the rank last earned its
    # errmgr_min_uptime_s (the governor resets it on an earned-uptime
    # death) — never use it as an identity
    restarts: int = 0
    # monotone incarnation number (OMPI_TPU_RESTART / the PMIx life /
    # the PML si stamp): total revives over the rank's whole history.
    # Survivors adopt it and the incarnation fence drops anything lower,
    # so unlike `restarts` it must NEVER go backwards
    lives: int = 0
    # monotonic time of this life's PMIx registration (first client
    # contact) — the errmgr crash-loop governor measures uptime from it
    # (errmgr_min_uptime_s), so interpreter+jax boot doesn't count; None
    # until the life registers (a pre-registration death is the
    # crash-loopiest case of all)
    launched_at: Optional[float] = None
    # set by plm._fail_daemon_ranks: this rank's daemon died with its
    # host, so no revival order can reach it — a reviving errmgr policy
    # must skip straight to its degrade rung
    daemon_lost: bool = False
    # planned shrink (elastic jobs): the rank is being retired on
    # purpose, so a reviving policy must NOT resurrect it — selfheal
    # degrades straight to its notify/shrink rung and the survivors
    # continue smaller (the ULFM recipe)
    no_revive: bool = False


@dataclasses.dataclass
class AppContext:
    """What to run (≈ orte_app_context_t): argv + env + working dir."""

    argv: list[str]
    np: int
    env: dict[str, str] = dataclasses.field(default_factory=dict)
    cwd: Optional[str] = None


_jobid_counter = itertools.count(1)


class Job:
    """A job: app contexts + allocation + map + proc states (≈ orte_job_t)."""

    def __init__(self, apps: list[AppContext], jobid: Optional[int] = None) -> None:
        self.jobid = jobid if jobid is not None else next(_jobid_counter)
        self.apps = apps
        self.state = JobState.INIT
        self.nodes: list[Node] = []
        self.procs: list[Proc] = []
        self.aborted_proc: Optional[Proc] = None
        self.abort_reason: Optional[str] = None
        self.abort_status: Optional[int] = None
        # per-job launcher bookkeeping (a multi-tenant DVM runs several
        # jobs concurrently, so none of this can live on the launcher):
        # rank → rc once the exit report landed, the job-scoped kill
        # latch, and the job's own PMIx rendezvous
        self.exited: dict[int, int] = {}
        self.killed: bool = False
        self.pmix_server: Optional[Any] = None

    @property
    def np(self) -> int:
        return sum(app.np for app in self.apps)

    def procs_on(self, node: Node) -> list[Proc]:
        return [p for p in self.procs if p.node is node]

    def all_terminated(self) -> bool:
        return all(
            p.state in (ProcState.TERMINATED, ProcState.ABORTED,
                        ProcState.FAILED_TO_START, ProcState.KILLED_BY_CMD)
            for p in self.procs)
