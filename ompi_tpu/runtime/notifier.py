"""Notifier — admin-facing event alerts.

≈ orte/mca/notifier (syslog/smtp components): job-level events the
operator should see even when stdout scrolled away — job abort, daemon
loss, rank respawn — go through a severity-filtered notifier framework.

Components:
- ``syslog`` — forwards to the system log via the stdlib syslog binding.
- ``log``    — forwards to the framework's own output streams (always
  available; the default, so tests and containers without a syslog daemon
  still capture events).

Select with ``--mca notifier syslog``; filter with
``--mca notifier_severity warn``.
"""

from __future__ import annotations

import enum
from typing import Optional

from ompi_tpu.core import output
from ompi_tpu.core.config import VarType, register_var, var_registry
from ompi_tpu.core.mca import Component, Framework

__all__ = ["Severity", "notifier_framework", "notify"]

_log = output.get_stream("notifier")

notifier_framework = Framework("notifier", "admin event alerts")

register_var("notifier", "severity", VarType.STRING, "warn",
             "minimum severity forwarded: debug|info|warn|error|critical")


class Severity(enum.IntEnum):
    DEBUG = 0
    INFO = 1
    WARN = 2
    ERROR = 3
    CRITICAL = 4


@notifier_framework.component
class LogNotifier(Component):
    """Default sink: the framework's own output streams."""

    NAME = "log"
    PRIORITY = 10

    def notify(self, severity: Severity, event: str, detail: str) -> None:
        # the severity threshold already filtered — everything arriving
        # here must be VISIBLE (a verbosity gate on top would hide the
        # 'admin must see this' events the framework exists for)
        if severity >= Severity.ERROR:
            _log.error("[%s] %s: %s", severity.name, event, detail)
        else:
            _log.emit("[%s] %s: %s", severity.name, event, detail)


@notifier_framework.component
class SyslogNotifier(Component):
    """≈ notifier/syslog: forward to the system log."""

    NAME = "syslog"
    PRIORITY = 0    # opt-in via --mca notifier syslog

    _PRIO = None

    def query(self, **ctx) -> Optional[int]:
        try:
            import syslog  # noqa: F401
        except ImportError:  # non-POSIX
            return None
        return self.PRIORITY

    def notify(self, severity: Severity, event: str, detail: str) -> None:
        import syslog

        prio = {Severity.DEBUG: syslog.LOG_DEBUG,
                Severity.INFO: syslog.LOG_INFO,
                Severity.WARN: syslog.LOG_WARNING,
                Severity.ERROR: syslog.LOG_ERR,
                Severity.CRITICAL: syslog.LOG_CRIT}[severity]
        syslog.openlog("ompi_tpu")
        try:
            syslog.syslog(prio, f"{event}: {detail}")
        finally:
            syslog.closelog()


def _threshold() -> Severity:
    name = (var_registry.get("notifier_severity") or "warn").upper()
    try:
        return Severity[name]
    except KeyError:
        return Severity.WARN


def notify(severity: Severity, event: str, detail: str = "") -> None:
    """Emit one admin event through the selected notifier component."""
    if severity < _threshold():
        return
    comp = notifier_framework.select()
    comp.notify(severity, event, detail)
