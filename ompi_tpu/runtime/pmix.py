"""Rendezvous / modex service: put, get, fence, abort.

≈ opal/mca/pmix (pmix.h:328-861: put :396, get :407, fence :384) plus the
server side ORTE provides.  The launcher (HNP) hosts a TCP key-value server;
every app proc connects as a client using the ``OMPI_TPU_HNP_URI`` it
inherits.  The *modex* — each rank publishing its business card (host p2p
listening address, chip binding) and fencing — is exactly the reference's
PMIx_Put/Commit/Fence flow from ompi_mpi_init.c:673-703.

Wire protocol: 4-byte LE length + DSS-packed (cmd, *args) tuple per message,
one reply per request.  GET blocks server-side until the key is published
(PMIx's "direct modex on demand" behavior), FENCE blocks until all ranks of
the epoch arrive.
"""

from __future__ import annotations

import os
import socket
import struct
import threading
import time
from typing import Any, Callable, Optional

from ompi_tpu.core import dss, output
from ompi_tpu.core.config import VarType, register_var, var_registry

__all__ = ["PMIxServer", "PMIxClient", "PMIxError", "query_regcount",
           "query_regstate", "query_doctor_ports"]

_log = output.get_stream("pmix")

register_var("pmix", "register_grace_s", VarType.DOUBLE, 20.0,
             "wedge escape for the stale-failure-report gate: a "
             "revived life normally announces its incarnation, and "
             "reports stamped with an older one are dropped.  A life "
             "still silent this long after its revive — never "
             "registered (SIGSTOP, OOM stall, import deadlock during "
             "boot), or registered but hung before any survivor "
             "adopted its incarnation — is presumed wedged: reports "
             "about it are accepted regardless of incarnation so it "
             "can be re-reaped instead of stalling the job forever.  "
             "The escape closes permanently for a life once any "
             "survivor reports having adopted its incarnation (an "
             "adopted life provably announced — a later stale report "
             "is a partitioned reporter or a cached dead-life probe, "
             "not a wedge).  0 disables the escape (stale reports are "
             "always dropped)")

ENV_URI = "OMPI_TPU_HNP_URI"
ENV_RANK = "OMPI_TPU_RANK"
ENV_SIZE = "OMPI_TPU_SIZE"
ENV_JOBID = "OMPI_TPU_JOBID"
ENV_LOCAL_RANK = "OMPI_TPU_LOCAL_RANK"
ENV_CHIP = "OMPI_TPU_CHIP"


class PMIxError(RuntimeError):
    pass


def _send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(struct.pack("<I", len(payload)) + payload)


def _recv_frame(sock: socket.socket) -> Optional[bytes]:
    hdr = b""
    while len(hdr) < 4:
        chunk = sock.recv(4 - len(hdr))
        if not chunk:
            return None
        hdr += chunk
    (n,) = struct.unpack("<I", hdr)
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 16, n - len(buf)))
        if not chunk:
            return None
        buf += chunk
    return bytes(buf)


class PMIxServer:
    """The HNP-side rendezvous server (thread-per-connection)."""

    def __init__(self, size: int,
                 on_abort: Optional[Callable[[int, int, str], None]] = None,
                 host: str = "127.0.0.1") -> None:
        self.size = size
        self.on_abort = on_abort
        # optional launcher hook for client-reported failures (a rank's
        # gossip detector declaring a hung-but-alive peer): called once
        # per newly-reported rank with (rank, reason) so the launcher can
        # reap the pid — the exit report then drives the errmgr normally
        self.on_failed_report: Optional[Callable[[int, str], None]] = None
        # optional launcher hook fired once per life when the rank's
        # client registers ("reg", sent at PMIxClient construction): the
        # errmgr crash-loop governor starts the uptime clock here so
        # interpreter+jax boot never counts toward errmgr_min_uptime_s
        self.on_client_contact: Optional[Callable[[int], None]] = None
        self._store: dict[str, Any] = {}
        self._cv = threading.Condition()
        self._fence_counts: dict[int, int] = {}
        self._fence_done: set[int] = set()
        self._client_epoch: dict[int, int] = {}
        self._dead: set[int] = set()
        self._failed_reasons: dict[int, str] = {}
        self._life: dict[int, int] = {}   # rank → current incarnation
        self._finished: set[int] = set()  # ranks that exited cleanly
        self._registered: set[int] = set()  # ranks whose CURRENT life reg'd
        self._ready: set[int] = set()   # ranks whose current life LEFT
        # init (the one-way "ready" notice at the end of ompi_tpu.init)
        self._revived_at: dict[int, float] = {}  # rank → last revive time
        self._adopted_life: dict[int, int] = {}  # rank → highest life any
        # SURVIVOR adopted (the "adopted" RPC, pushed once per life per
        # survivor on its peer_reincarnated transition): an adopted life
        # provably announced — it cannot be boot-wedged, so the stale-
        # report escape below closes for it and a late stale report
        # (partitioned reporter, cached dead-life pid probe) can no
        # longer SIGKILL a long-healthy revived rank
        self._doctor_ports: dict[int, int] = {}  # rank → hang-doctor
        # responder UDP port (current life only; a revive drops it until
        # the new life re-registers)
        self._aborted: Optional[tuple[int, int, str]] = None
        self._listener = socket.create_server((host, 0))
        self._port = self._listener.getsockname()[1]
        self._host = host
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="pmix-accept", daemon=True)
        self._accept_thread.start()

    @property
    def uri(self) -> str:
        return f"tcp://{self._host}:{self._port}"

    # -- server loop -----------------------------------------------------

    def _accept_loop(self) -> None:
        self._listener.settimeout(0.2)
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            t = threading.Thread(target=self._serve, args=(conn,), daemon=True)
            t.start()
            self._threads.append(t)

    def _serve(self, conn: socket.socket) -> None:
        with conn:
            while True:
                try:
                    payload = _recv_frame(conn)
                except OSError:
                    return  # client died mid-frame (SIGKILL/injected
                    # fault resets the socket) — same as a clean EOF
                if payload is None:
                    return
                msg = dss.unpack(payload, n=1)[0]
                cmd = msg[0]
                try:
                    reply = self._handle(cmd, msg[1:])
                except Exception as e:  # report, don't kill the server thread
                    reply = ("err", f"{type(e).__name__}: {e}")
                _send_frame(conn, dss.pack(reply))
                if cmd == "fin":
                    return

    def _handle(self, cmd: str, args: tuple) -> tuple:
        if cmd == "put":
            rank, key, value = args
            with self._cv:
                self._store[f"{key}@{rank}"] = value
                self._cv.notify_all()
            return ("ok",)
        if cmd == "get":
            key, rank, timeout = args
            full = f"{key}@{rank}" if rank >= 0 else key
            with self._cv:
                ok = self._cv.wait_for(
                    lambda: full in self._store or self._aborted is not None,
                    timeout=timeout if timeout > 0 else None)
                if self._aborted is not None:
                    return ("abort", *self._aborted)
                if not ok:
                    return ("timeout",)
                return ("ok", self._store[full])
        if cmd == "fence":
            (rank, collect) = args
            with self._cv:
                epoch = self._client_epoch.get(rank, 0)
                self._client_epoch[rank] = epoch + 1
                self._fence_counts[epoch] = self._fence_counts.get(epoch, 0) + 1
                self._check_fence_done(epoch)
                self._cv.wait_for(
                    lambda: epoch in self._fence_done or self._aborted is not None)
                if self._aborted is not None:
                    return ("abort", *self._aborted)
                if collect:
                    return ("ok", dict(self._store))
                return ("ok",)
        if cmd == "abort":
            rank, status, msg = args
            with self._cv:
                if self._aborted is None:
                    self._aborted = (rank, status, msg)
                self._cv.notify_all()
            if self.on_abort is not None:
                self.on_abort(rank, status, msg)
            return ("ok",)
        if cmd == "reg":
            # client registration (sent once at PMIxClient construction):
            # marks the rank's CURRENT life as having finished booting.
            # Gates the stale-report escape below and starts the errmgr
            # governor's uptime clock via on_client_contact.
            rank = int(args[0])
            with self._cv:
                first = rank not in self._registered
                self._registered.add(rank)
            if first and self.on_client_contact is not None:
                try:
                    self.on_client_contact(rank)
                except Exception as e:  # noqa: BLE001 — server survives
                    _log.error("on_client_contact(%d) failed: %r", rank, e)
            return ("ok",)
        if cmd == "regcount":
            # introspection: how many ranks' CURRENT lives have
            # registered (finished booting), how many fence epochs have
            # completed, and how many ranks are READY (left init — the
            # one-way notice below).  Chaos schedules key on these
            # (``daemon=V:kill@reg=N`` fires only once N ranks are
            # ready, so the kill cannot land mid-init), and together
            # they make a cheap job-readiness probe.
            with self._cv:
                return ("ok", len(self._registered),
                        len(self._fence_done), len(self._ready))
        if cmd == "ready":
            # the rank's current life finished ompi_tpu.init(): user
            # code is running from here on
            with self._cv:
                self._ready.add(int(args[0]))
            return ("ok",)
        if cmd == "adopted":
            # a survivor adopted a peer's new incarnation (its rebind /
            # first si-stamped frame arrived): the life announced, so it
            # is not boot-wedged — close the stale-report escape for it
            rank, inc = int(args[0]), int(args[1])
            with self._cv:
                if inc > self._adopted_life.get(rank, 0):
                    self._adopted_life[rank] = inc
            return ("ok",)
        if cmd == "coll_rejoin":
            # one-way notice: the rank finished its epoch-fenced rebuild
            # of the coll/shm hierarchy after a revive (the rejoin half
            # of the selfheal cycle) — recorded on the FT timeline so
            # /status (and the --dvm-ps rejoins column, fed by the
            # coll_rejoin_total pvar on the metrics uplink) shows it.
            # jobid 0: the server is per-job, and jobid-0 events ride
            # every job filter by design (ftevents.snapshot)
            rank, oe, ne, ms = (int(args[0]), int(args[1]),
                                int(args[2]), int(args[3]))
            from ompi_tpu.runtime import ftevents

            with self._cv:
                lives = self._life.get(rank, 0)
            ftevents.record("coll_rejoin", jobid=0, rank=rank,
                            lives=lives, old_epoch=oe, new_epoch=ne,
                            rebuild_ms=ms)
            return ("ok",)
        if cmd == "report_failed":
            # the reverse direction of "failed": an app rank PUSHES a
            # death its rank-plane gossip detector observed (hung pid —
            # alive to the daemon heartbeats, silent to its peers).  The
            # dead-set gains it (so every other detector's poll sees it)
            # and the launcher hook may reap the pid.
            reporter, failed_rank, reason = args[:3]
            # optional 4th arg: the incarnation the reporter observed
            # dead.  Under a reviving errmgr (respawn/selfheal) several
            # reporters race to declare the same corpse — the first
            # report reaps and revives it, and a second report about the
            # DEAD life must not SIGKILL the new one (or re-poison the
            # dead-set the revive just cleared).
            inc = int(args[3]) if len(args) > 3 else 0
            failed_rank = int(failed_rank)
            with self._cv:
                if inc < self._life.get(failed_rank, 0):
                    # stale — UNLESS the current life is wedged: revived
                    # a while ago yet either never registered (hung
                    # during interpreter boot) or registered but hung
                    # before its announce/beats reached any survivor.
                    # Either way no reporter can ever have adopted its
                    # incarnation, so the gate would drop every report
                    # forever, leaving a hung pid unreapable.  grace 0
                    # disables the escape: an always-open escape would
                    # let a racing stale report SIGKILL a legitimately
                    # booting revived rank.
                    revived_at = self._revived_at.get(failed_rank)
                    grace = float(
                        var_registry.get("pmix_register_grace_s") or 0)
                    adopted = (self._adopted_life.get(failed_rank, 0)
                               >= self._life.get(failed_rank, 0))
                    wedged = (grace > 0
                              and not adopted
                              and revived_at is not None
                              and time.monotonic() - revived_at >= grace)
                    if not wedged:
                        _log.verbose(1, "stale failure report for rank %d "
                                     "(life %d < %d); ignored", failed_rank,
                                     inc, self._life[failed_rank])
                        return ("ok", "stale")
                    _log.verbose(1, "accepting stale-incarnation report "
                                 "for rank %d: life %d %s within %.1fs "
                                 "(wedged)", failed_rank,
                                 self._life[failed_rank],
                                 ("never registered"
                                  if failed_rank not in self._registered
                                  else "registered but never adopted by "
                                  "any survivor"),
                                 grace)
                if failed_rank in self._finished:
                    # the rank exited CLEANLY: its gossip beats stopped
                    # with its transports, which peers can misread as a
                    # hang — poisoning the dead-set (or reaping a pid
                    # slot) for a rank that finished its work would turn
                    # a healthy completion into a failure event
                    _log.verbose(1, "failure report for finished rank "
                                 "%d; ignored", failed_rank)
                    return ("ok", "finished")
                fresh = failed_rank not in self._dead
                if fresh:
                    self._dead.add(failed_rank)
                    if reason:
                        self._failed_reasons[failed_rank] = str(reason)
                    for epoch in list(self._fence_counts):
                        if epoch not in self._fence_done:
                            self._check_fence_done(epoch)
                    self._cv.notify_all()
            if fresh:
                _log.verbose(1, "rank %s reported rank %d failed (%s)",
                             reporter, failed_rank, reason)
                if self.on_failed_report is not None:
                    try:
                        self.on_failed_report(failed_rank, str(reason))
                    except Exception as e:  # noqa: BLE001 — server survives
                        _log.error("on_failed_report(%d) failed: %r",
                                   failed_rank, e)
            return ("ok",)
        if cmd == "failed":
            # ULFM failure-detector query: the launcher's reap loop /
            # heartbeat monitor feeds _dead via proc_died; app ranks poll
            # this to turn silent peer death into MPI_ERR_PROC_FAILED
            with self._cv:
                return ("ok", sorted(self._dead),
                        dict(self._failed_reasons))
        if cmd == "doctor":
            # hang-doctor responder registration: the rank's capture
            # endpoint (UDP port, loopback on the rank's host) — the
            # owning orted resolves it through "doctor_ports" when a
            # TAG_DOCTOR capture fans out
            rank, port = int(args[0]), int(args[1])
            with self._cv:
                self._doctor_ports[rank] = port
            return ("ok",)
        if cmd == "doctor_ports":
            with self._cv:
                return ("ok", dict(self._doctor_ports))
        if cmd == "fin":
            return ("ok",)
        raise PMIxError(f"unknown command {cmd!r}")

    def _check_fence_done(self, epoch: int) -> None:
        """With _cv held: a fence completes when every *live* rank arrived."""
        live = self.size - len(self._dead)
        if self._fence_counts.get(epoch, 0) >= live:
            self._fence_done.add(epoch)
            self._cv.notify_all()

    def proc_finished(self, rank: int) -> None:
        """Launcher notification: the rank exited CLEANLY (rc 0).  Its
        beats/transports are gone, so late gossip suspicions about it
        are completion, not failure — ``report_failed`` ignores them."""
        with self._cv:
            self._finished.add(rank)

    def proc_died(self, rank: int, reason: str = "") -> None:
        """Launcher notification: rank exited abnormally. Re-evaluates every
        pending fence so survivors don't block on a dead peer forever."""
        with self._cv:
            self._dead.add(rank)
            if reason:
                self._failed_reasons[rank] = reason
            for epoch in list(self._fence_counts):
                if epoch not in self._fence_done:
                    self._check_fence_done(epoch)
            self._cv.notify_all()

    def proc_revived(self, rank: int,
                     incarnation: Optional[int] = None) -> None:
        """errmgr respawn/selfheal notification: the rank is back.
        Future fences count it again; its fence-epoch counter restarts
        (already-completed epochs return immediately, so a restarted rank
        fast-forwards through barriers the survivors already passed).
        ``incarnation`` (the new life number, = the monotone
        ``proc.lives`` — NOT the governor-resettable restart budget)
        fences stale ``report_failed`` pushes about the dead life."""
        with self._cv:
            self._dead.discard(rank)
            self._failed_reasons.pop(rank, None)
            self._finished.discard(rank)
            self._client_epoch[rank] = 0
            self._life[rank] = (self._life.get(rank, 0) + 1
                                if incarnation is None else int(incarnation))
            # the new life hasn't booted yet: it must "reg" again, and
            # the boot-wedge escape measures from this revive
            self._registered.discard(rank)
            self._ready.discard(rank)
            # the dead life's doctor endpoint is a stale port — a
            # capture must not read a stranger's socket
            self._doctor_ports.pop(rank, None)
            self._revived_at[rank] = time.monotonic()
            self._cv.notify_all()

    # -- host-side access (launcher uses these directly) ------------------

    def lookup(self, key: str, rank: int = -1) -> Any:
        full = f"{key}@{rank}" if rank >= 0 else key
        with self._cv:
            return self._store.get(full)

    def publish(self, key: str, value: Any) -> None:
        with self._cv:
            self._store[key] = value
            self._cv.notify_all()

    def close(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass


def _oneshot_query(uri: str, cmd: str,
                   timeout: float) -> Optional[tuple]:
    """One transient connection, one command, one "ok" reply — the
    shared skeleton of every registration-free probe (a non-rank caller
    must NOT send "reg": it would inflate the very barrier it watches).
    None when the server is unreachable or the reply is not ok."""
    host, port = uri.removeprefix("tcp://").rsplit(":", 1)
    try:
        with socket.create_connection((host, int(port)),
                                      timeout=timeout) as sock:
            sock.settimeout(timeout)
            _send_frame(sock, dss.pack((cmd,)))
            payload = _recv_frame(sock)
        if payload is None:
            return None
        reply = dss.unpack(payload, n=1)[0]
        if reply[0] != "ok":
            return None
        return tuple(reply[1:])
    except (OSError, ValueError, IndexError):
        return None


def query_regstate(uri: str, timeout: float = 2.0
                   ) -> Optional[tuple[int, int, int]]:
    """One-shot, registration-free probe of the server's readiness
    state → ``(ranks_registered, fence_epochs_done, ranks_ready)``.
    None when the server is unreachable."""
    reply = _oneshot_query(uri, "regcount", timeout)
    if reply is None or not reply:
        return None
    try:
        return (int(reply[0]),
                int(reply[1]) if len(reply) > 1 else 0,
                int(reply[2]) if len(reply) > 2 else 0)
    except (TypeError, ValueError):
        return None


def query_regcount(uri: str, timeout: float = 2.0) -> Optional[int]:
    """The ranks-registered half of :func:`query_regstate`."""
    state = query_regstate(uri, timeout=timeout)
    return None if state is None else state[0]


def query_doctor_ports(uri: str,
                       timeout: float = 2.0) -> Optional[dict[int, int]]:
    """One-shot, registration-free probe of the registered hang-doctor
    responder ports → {rank: udp_port} (the orted's TAG_DOCTOR handler
    resolves its local ranks through this).  None when the server is
    unreachable."""
    reply = _oneshot_query(uri, "doctor_ports", timeout)
    if reply is None or not reply:
        return None
    try:
        return {int(r): int(p) for r, p in dict(reply[0]).items()}
    except (TypeError, ValueError):
        return None


class PMIxClient:
    """App-proc side client. Thread-safe (one in-flight request at a time)."""

    def __init__(self, uri: Optional[str] = None, rank: Optional[int] = None,
                 size: Optional[int] = None) -> None:
        uri = uri or os.environ.get(ENV_URI)
        if not uri:
            raise PMIxError(
                f"no rendezvous URI: {ENV_URI} not set (run under tpurun)")
        self.rank = rank if rank is not None else int(os.environ[ENV_RANK])
        self.size = size if size is not None else int(os.environ[ENV_SIZE])
        host, port = uri.removeprefix("tcp://").rsplit(":", 1)
        self._sock = socket.create_connection((host, int(port)))
        self._lock = threading.Lock()
        self._local: dict[str, Any] = {}
        # register this life: boot is over (interpreter + framework
        # imports are behind us) — the server starts the crash-loop
        # governor's uptime clock and lifts the boot-wedge presumption
        self._rpc("reg", self.rank)

    def _rpc(self, *msg: Any) -> tuple:
        with self._lock:
            _send_frame(self._sock, dss.pack(tuple(msg)))
            payload = _recv_frame(self._sock)
        if payload is None:
            raise PMIxError("connection to rendezvous server lost")
        reply = dss.unpack(payload, n=1)[0]
        if reply[0] == "abort":
            raise PMIxError(
                f"job aborted by rank {reply[1]} (status {reply[2]}): {reply[3]}")
        if reply[0] == "err":
            raise PMIxError(reply[1])
        if reply[0] == "timeout":
            raise TimeoutError("pmix get timed out")
        return reply

    def put(self, key: str, value: Any) -> None:
        self._local[key] = value
        self._rpc("put", self.rank, key, value)

    def get(self, key: str, rank: int = -1, timeout: float = 60.0) -> Any:
        if rank == self.rank and key in self._local:
            return self._local[key]
        return self._rpc("get", key, rank, float(timeout))[1]

    def fence(self, collect: bool = False) -> Optional[dict]:
        reply = self._rpc("fence", self.rank, bool(collect))
        return reply[1] if collect else None

    def barrier(self) -> None:
        self.fence(collect=False)

    def regcount(self) -> int:
        """How many ranks' current lives have registered with the server
        — the ranks-registered barrier (see :func:`query_regcount` for
        the registration-free variant non-rank probes must use)."""
        return int(self._rpc("regcount")[1])

    def ready(self) -> None:
        """One-way init-complete notice: this life finished
        ompi_tpu.init() and user code is running (counts toward the
        readiness probe's third field)."""
        self._rpc("ready", self.rank)

    def failed_ranks(self) -> dict[int, str]:
        """The runtime's current dead-set (ranks the launcher reaped dead
        or the heartbeat monitor declared silent) → human-readable
        reason ('' when the runtime recorded none) — the control-plane
        source the ULFM failure detector (mpi/ft.py) polls."""
        reply = self._rpc("failed")
        reasons = reply[2] if len(reply) > 2 else {}
        return {int(r): str(reasons.get(r, "")) for r in reply[1]}

    def report_failed(self, failed_rank: int, reason: str = "",
                      incarnation: int = 0) -> Optional[str]:
        """Push a locally-observed death (gossip suspect, arena pid
        probe) into the runtime dead-set so the control plane — and
        every other rank's detector poll — learns it, and the launcher
        can reap a hung-but-alive pid.  ``incarnation`` is the life of
        the rank the reporter observed dead (its adopted incarnation
        number) — the server drops reports about already-reaped lives so
        racing reporters cannot kill a freshly-revived rank.  Returns
        the server's gate verdict: ``"stale"`` / ``"finished"`` when the
        report was dropped, None when it was taken (the caller retries
        stale-gated pushes — a life that wedges after the drop would
        otherwise never be re-reported)."""
        reply = self._rpc("report_failed", self.rank, int(failed_rank),
                          reason, int(incarnation))
        return reply[1] if len(reply) > 1 else None

    def register_doctor(self, port: int) -> None:
        """Register this rank's hang-doctor responder UDP port with the
        control plane (the owning orted queries it on TAG_DOCTOR)."""
        self._rpc("doctor", self.rank, int(port))

    def doctor_ports(self) -> dict[int, int]:
        """Every registered hang-doctor responder port by rank (the
        registration-free probe non-rank callers must use is
        :func:`query_doctor_ports`)."""
        return {int(r): int(p)
                for r, p in dict(self._rpc("doctor_ports")[1]).items()}

    def coll_rejoin(self, old_epoch: int, new_epoch: int,
                    rebuild_ms: int) -> None:
        """One-way notice that this rank completed an epoch-fenced
        rebuild of its coll/shm hierarchy after a revive was adopted
        (old -> new coll epoch, rebuild latency) — lands on the HNP's
        FT timeline as a ``coll_rejoin`` event.  Best-effort
        observability; called from the coll dispatch (app) thread."""
        self._rpc("coll_rejoin", self.rank, int(old_epoch),
                  int(new_epoch), int(rebuild_ms))

    def peer_adopted(self, rank: int, incarnation: int) -> None:
        """Tell the control plane this process adopted ``rank``'s new
        life ``incarnation`` (its rebind / first si-stamped frame
        arrived): the life provably announced, so the server's
        boot-wedge escape closes for it and a late stale-incarnation
        report can no longer reap the healthy rank.  Pushed once per
        adopted life per survivor (see ``PmlFT.peer_reincarnated``)."""
        self._rpc("adopted", int(rank), int(incarnation))

    def abort(self, msg: str = "", status: int = 1) -> None:
        self._rpc("abort", self.rank, int(status), msg)

    def finalize(self) -> None:
        try:
            self._rpc("fin", self.rank)
        finally:
            self._sock.close()
