"""Rendezvous / modex service: put, get, fence, abort.

≈ opal/mca/pmix (pmix.h:328-861: put :396, get :407, fence :384) plus the
server side ORTE provides.  The launcher (HNP) hosts a TCP key-value server;
every app proc connects as a client using the ``OMPI_TPU_HNP_URI`` it
inherits.  The *modex* — each rank publishing its business card (host p2p
listening address, chip binding) and fencing — is exactly the reference's
PMIx_Put/Commit/Fence flow from ompi_mpi_init.c:673-703.

Wire protocol: 4-byte LE length + DSS-packed (cmd, *args) tuple per message,
one reply per request.  GET blocks server-side until the key is published
(PMIx's "direct modex on demand" behavior), FENCE blocks until all ranks of
the epoch arrive.
"""

from __future__ import annotations

import os
import socket
import struct
import threading
from typing import Any, Callable, Optional

from ompi_tpu.core import dss, output

__all__ = ["PMIxServer", "PMIxClient", "PMIxError"]

_log = output.get_stream("pmix")

ENV_URI = "OMPI_TPU_HNP_URI"
ENV_RANK = "OMPI_TPU_RANK"
ENV_SIZE = "OMPI_TPU_SIZE"
ENV_JOBID = "OMPI_TPU_JOBID"
ENV_LOCAL_RANK = "OMPI_TPU_LOCAL_RANK"
ENV_CHIP = "OMPI_TPU_CHIP"


class PMIxError(RuntimeError):
    pass


def _send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(struct.pack("<I", len(payload)) + payload)


def _recv_frame(sock: socket.socket) -> Optional[bytes]:
    hdr = b""
    while len(hdr) < 4:
        chunk = sock.recv(4 - len(hdr))
        if not chunk:
            return None
        hdr += chunk
    (n,) = struct.unpack("<I", hdr)
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 16, n - len(buf)))
        if not chunk:
            return None
        buf += chunk
    return bytes(buf)


class PMIxServer:
    """The HNP-side rendezvous server (thread-per-connection)."""

    def __init__(self, size: int,
                 on_abort: Optional[Callable[[int, int, str], None]] = None,
                 host: str = "127.0.0.1") -> None:
        self.size = size
        self.on_abort = on_abort
        # optional launcher hook for client-reported failures (a rank's
        # gossip detector declaring a hung-but-alive peer): called once
        # per newly-reported rank with (rank, reason) so the launcher can
        # reap the pid — the exit report then drives the errmgr normally
        self.on_failed_report: Optional[Callable[[int, str], None]] = None
        self._store: dict[str, Any] = {}
        self._cv = threading.Condition()
        self._fence_counts: dict[int, int] = {}
        self._fence_done: set[int] = set()
        self._client_epoch: dict[int, int] = {}
        self._dead: set[int] = set()
        self._failed_reasons: dict[int, str] = {}
        self._aborted: Optional[tuple[int, int, str]] = None
        self._listener = socket.create_server((host, 0))
        self._port = self._listener.getsockname()[1]
        self._host = host
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="pmix-accept", daemon=True)
        self._accept_thread.start()

    @property
    def uri(self) -> str:
        return f"tcp://{self._host}:{self._port}"

    # -- server loop -----------------------------------------------------

    def _accept_loop(self) -> None:
        self._listener.settimeout(0.2)
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            t = threading.Thread(target=self._serve, args=(conn,), daemon=True)
            t.start()
            self._threads.append(t)

    def _serve(self, conn: socket.socket) -> None:
        with conn:
            while True:
                try:
                    payload = _recv_frame(conn)
                except OSError:
                    return  # client died mid-frame (SIGKILL/injected
                    # fault resets the socket) — same as a clean EOF
                if payload is None:
                    return
                msg = dss.unpack(payload, n=1)[0]
                cmd = msg[0]
                try:
                    reply = self._handle(cmd, msg[1:])
                except Exception as e:  # report, don't kill the server thread
                    reply = ("err", f"{type(e).__name__}: {e}")
                _send_frame(conn, dss.pack(reply))
                if cmd == "fin":
                    return

    def _handle(self, cmd: str, args: tuple) -> tuple:
        if cmd == "put":
            rank, key, value = args
            with self._cv:
                self._store[f"{key}@{rank}"] = value
                self._cv.notify_all()
            return ("ok",)
        if cmd == "get":
            key, rank, timeout = args
            full = f"{key}@{rank}" if rank >= 0 else key
            with self._cv:
                ok = self._cv.wait_for(
                    lambda: full in self._store or self._aborted is not None,
                    timeout=timeout if timeout > 0 else None)
                if self._aborted is not None:
                    return ("abort", *self._aborted)
                if not ok:
                    return ("timeout",)
                return ("ok", self._store[full])
        if cmd == "fence":
            (rank, collect) = args
            with self._cv:
                epoch = self._client_epoch.get(rank, 0)
                self._client_epoch[rank] = epoch + 1
                self._fence_counts[epoch] = self._fence_counts.get(epoch, 0) + 1
                self._check_fence_done(epoch)
                self._cv.wait_for(
                    lambda: epoch in self._fence_done or self._aborted is not None)
                if self._aborted is not None:
                    return ("abort", *self._aborted)
                if collect:
                    return ("ok", dict(self._store))
                return ("ok",)
        if cmd == "abort":
            rank, status, msg = args
            with self._cv:
                if self._aborted is None:
                    self._aborted = (rank, status, msg)
                self._cv.notify_all()
            if self.on_abort is not None:
                self.on_abort(rank, status, msg)
            return ("ok",)
        if cmd == "report_failed":
            # the reverse direction of "failed": an app rank PUSHES a
            # death its rank-plane gossip detector observed (hung pid —
            # alive to the daemon heartbeats, silent to its peers).  The
            # dead-set gains it (so every other detector's poll sees it)
            # and the launcher hook may reap the pid.
            reporter, failed_rank, reason = args
            failed_rank = int(failed_rank)
            with self._cv:
                fresh = failed_rank not in self._dead
                if fresh:
                    self._dead.add(failed_rank)
                    if reason:
                        self._failed_reasons[failed_rank] = str(reason)
                    for epoch in list(self._fence_counts):
                        if epoch not in self._fence_done:
                            self._check_fence_done(epoch)
                    self._cv.notify_all()
            if fresh:
                _log.verbose(1, "rank %s reported rank %d failed (%s)",
                             reporter, failed_rank, reason)
                if self.on_failed_report is not None:
                    try:
                        self.on_failed_report(failed_rank, str(reason))
                    except Exception as e:  # noqa: BLE001 — server survives
                        _log.error("on_failed_report(%d) failed: %r",
                                   failed_rank, e)
            return ("ok",)
        if cmd == "failed":
            # ULFM failure-detector query: the launcher's reap loop /
            # heartbeat monitor feeds _dead via proc_died; app ranks poll
            # this to turn silent peer death into MPI_ERR_PROC_FAILED
            with self._cv:
                return ("ok", sorted(self._dead),
                        dict(self._failed_reasons))
        if cmd == "fin":
            return ("ok",)
        raise PMIxError(f"unknown command {cmd!r}")

    def _check_fence_done(self, epoch: int) -> None:
        """With _cv held: a fence completes when every *live* rank arrived."""
        live = self.size - len(self._dead)
        if self._fence_counts.get(epoch, 0) >= live:
            self._fence_done.add(epoch)
            self._cv.notify_all()

    def proc_died(self, rank: int, reason: str = "") -> None:
        """Launcher notification: rank exited abnormally. Re-evaluates every
        pending fence so survivors don't block on a dead peer forever."""
        with self._cv:
            self._dead.add(rank)
            if reason:
                self._failed_reasons[rank] = reason
            for epoch in list(self._fence_counts):
                if epoch not in self._fence_done:
                    self._check_fence_done(epoch)
            self._cv.notify_all()

    def proc_revived(self, rank: int) -> None:
        """errmgr/respawn notification: the rank is back.  Future fences
        count it again; its fence-epoch counter restarts (already-completed
        epochs return immediately, so a restarted rank fast-forwards
        through barriers the survivors already passed)."""
        with self._cv:
            self._dead.discard(rank)
            self._failed_reasons.pop(rank, None)
            self._client_epoch[rank] = 0
            self._cv.notify_all()

    # -- host-side access (launcher uses these directly) ------------------

    def lookup(self, key: str, rank: int = -1) -> Any:
        full = f"{key}@{rank}" if rank >= 0 else key
        with self._cv:
            return self._store.get(full)

    def publish(self, key: str, value: Any) -> None:
        with self._cv:
            self._store[key] = value
            self._cv.notify_all()

    def close(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass


class PMIxClient:
    """App-proc side client. Thread-safe (one in-flight request at a time)."""

    def __init__(self, uri: Optional[str] = None, rank: Optional[int] = None,
                 size: Optional[int] = None) -> None:
        uri = uri or os.environ.get(ENV_URI)
        if not uri:
            raise PMIxError(
                f"no rendezvous URI: {ENV_URI} not set (run under tpurun)")
        self.rank = rank if rank is not None else int(os.environ[ENV_RANK])
        self.size = size if size is not None else int(os.environ[ENV_SIZE])
        host, port = uri.removeprefix("tcp://").rsplit(":", 1)
        self._sock = socket.create_connection((host, int(port)))
        self._lock = threading.Lock()
        self._local: dict[str, Any] = {}

    def _rpc(self, *msg: Any) -> tuple:
        with self._lock:
            _send_frame(self._sock, dss.pack(tuple(msg)))
            payload = _recv_frame(self._sock)
        if payload is None:
            raise PMIxError("connection to rendezvous server lost")
        reply = dss.unpack(payload, n=1)[0]
        if reply[0] == "abort":
            raise PMIxError(
                f"job aborted by rank {reply[1]} (status {reply[2]}): {reply[3]}")
        if reply[0] == "err":
            raise PMIxError(reply[1])
        if reply[0] == "timeout":
            raise TimeoutError("pmix get timed out")
        return reply

    def put(self, key: str, value: Any) -> None:
        self._local[key] = value
        self._rpc("put", self.rank, key, value)

    def get(self, key: str, rank: int = -1, timeout: float = 60.0) -> Any:
        if rank == self.rank and key in self._local:
            return self._local[key]
        return self._rpc("get", key, rank, float(timeout))[1]

    def fence(self, collect: bool = False) -> Optional[dict]:
        reply = self._rpc("fence", self.rank, bool(collect))
        return reply[1] if collect else None

    def barrier(self) -> None:
        self.fence(collect=False)

    def failed_ranks(self) -> dict[int, str]:
        """The runtime's current dead-set (ranks the launcher reaped dead
        or the heartbeat monitor declared silent) → human-readable
        reason ('' when the runtime recorded none) — the control-plane
        source the ULFM failure detector (mpi/ft.py) polls."""
        reply = self._rpc("failed")
        reasons = reply[2] if len(reply) > 2 else {}
        return {int(r): str(reasons.get(r, "")) for r in reply[1]}

    def report_failed(self, failed_rank: int, reason: str = "") -> None:
        """Push a locally-observed death (gossip suspect, arena pid
        probe) into the runtime dead-set so the control plane — and
        every other rank's detector poll — learns it, and the launcher
        can reap a hung-but-alive pid."""
        self._rpc("report_failed", self.rank, int(failed_rank), reason)

    def abort(self, msg: str = "", status: int = 1) -> None:
        self._rpc("abort", self.rank, int(status), msg)

    def finalize(self) -> None:
        try:
            self._rpc("fin", self.rank)
        finally:
            self._sock.close()
