"""Runtime/launcher layer (≈ the reference's ORTE, orte/).

Job launch and process wire-up, heavily simplified for the TPU world where
"nodes" are usually TPU hosts and "slots" are chips:

- ``job``     — Job/Node/Proc data model (≈ orte_job_t/orte_node_t/orte_proc_t,
                orte/runtime/orte_globals.h:215-342).
- ``state``   — event-driven job state machine; the launch DAG is data, not
                code (≈ orte/mca/state/hnp/state_hnp.c:74-112).
- ``ras``     — resource allocation framework: localhost, simulator (fake
                clusters for tests, ≈ orte/mca/ras/simulator), tpu (slice
                topology from jax.devices()).
- ``rmaps``   — proc→node/slot mapping and ranking (round_robin, ppr, seq).
- ``pmix``    — rendezvous/modex service: put/get/fence business-card exchange
                (≈ opal/mca/pmix; the launcher hosts the server, app procs are
                clients).
- ``errmgr``  — failure response policy (≈ orte/mca/errmgr).
- ``launcher``— fork/exec of app procs with IOF forwarding and the error-pipe
                protocol (≈ orte/mca/odls/default + orte/mca/iof).
"""
