"""Metrics uplink — the control-plane half of the live observability
plane.

Each app rank pushes its pvar snapshot (``trace.metrics_values()``,
delta-compressed) over UDP to its owning orted's :class:`MetricsCollector`
every ``trace_metrics_push_period`` seconds.  Each orted merges its local
ranks with whatever its tree children pushed up (``TAG_METRICS`` is a
one-hop message delivered at every level, not an HNP-only ``send_up``)
and forwards ONE merged delta per period toward the root.  The HNP/DVM
folds the stream into a :class:`MetricsAggregate` keyed by jobid and
rank — what the DVM's ``/metrics`` scrape endpoint and ``tpurun
--dvm-ps``'s last-metrics-age column read.

Wire shapes:

- rank → orted (UDP datagram): ``("m1", jobid, rank, push_n, {name: value})``
  — ``push_n`` fences reordered/stale datagrams; every
  ``trace.FULL_EVERY``-th push is a full snapshot so UDP loss heals.
- orted → parent (``TAG_METRICS``, one hop):
  ``{jobid: {rank: [wall_ts, {name: value}]}}`` — scalar values are
  cumulative counter readings (NOT increments), so a per-hop merge is a
  plain ``dict.update`` per rank and double-delivery cannot double-count.
- histogram vectors (the latency plane) ride the same value dicts as
  marker-tagged int lists: ``["d", …]`` is an element-wise INCREMENT
  since the sender's last push, ``["a", …]`` the absolute cumulative
  vector (full pushes + final flush).  :func:`merge_hop` folds them
  element-wise — delta∘delta adds, absolute subsumes older deltas,
  absolute∘absolute takes the element-wise max (vectors are monotone,
  so max is reorder-safe) — and the terminal aggregate row converges to
  an ``"a"``-tagged cumulative vector per (rank, series).

Thread-context rules: the TAG_METRICS handler runs on an RML link
reader thread — :func:`merge_hop` is dict surgery under one lock, no
RPC/sleep/subprocess (see the ``reader-thread`` lint checker).
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Any, Callable, Optional

from ompi_tpu.core import dss, output
from ompi_tpu.core.config import VarType, register_var, var_registry

__all__ = ["merge_hop", "MetricsCollector", "MetricsAggregate",
           "AGG_METRICS", "AGG_HISTS", "vec_merge", "hist_counts",
           "straggler_panel"]

_log = output.get_stream("metrics")

register_var("metrics", "agg_budget_rows", VarType.INT, 200000,
             "HNP metrics fan-in budget: rank-rows the terminal "
             "aggregate accepts per second (token bucket, 1s burst). "
             "Payloads beyond the budget are SHED WHOLE and counted "
             "(sheds_total / shed_rows_total in /status uplink stats) "
             "instead of queueing unboundedly when every daemon pushes "
             "a full snapshot at once — counters are cumulative and "
             "vector deltas heal on the next full push, so a shed "
             "costs staleness, never corruption.  0 = unlimited")

#: the per-job aggregated-metric name family: counters the DVM scrape
#: endpoint ADDITIONALLY exports summed across a job's ranks as
#: ``ompi_tpu_job_<name>{job="<jobid>"}``.  Every entry must name a
#: ``trace._COUNTER_SPECS`` counter — the ompi-lint ``pvar-spec``
#: checker cross-checks both directions so a renamed counter cannot
#: silently vanish from the scrape surface.
AGG_METRICS = (
    "pml_zero_copy_sends_total",
    "pml_packed_sends_total",
    "btl_shm_publish_total",
    "btl_shm_drained_total",
    "coll_shm_fanin_total",
    "coll_shm_fanout_total",
    "coll_shm_fallback_total",
    "ft_rank_deaths_total",
    "ft_gossip_beats_total",
    "ft_fenced_frames_total",
    "errmgr_selfheal_revives_total",
    "errmgr_selfheal_escalations_total",
    "coll_stuck_events_total",
    "coll_rejoin_total",
    "btl_tcp_native_writes_total",
    "btl_tcp_native_batched_frames_total",
    "btl_tcp_native_parks_total",
)

#: the per-job aggregated-HISTOGRAM name family: latency histograms the
#: DVM scrape endpoint ADDITIONALLY exports summed element-wise across
#: a job's ranks as ``ompi_tpu_job_<name>`` histogram series.  Every
#: entry must name a ``trace._HIST_SPECS`` histogram — the pvar-spec
#: lint checker cross-checks (the AGG_METRICS discipline, vector form).
AGG_HISTS = (
    "coll_dispatch_ns",
    "coll_pstart_ns",
    "btl_tcp_write_ns",
)

#: jobs kept in the aggregate before the oldest (by last update) fall off
MAX_JOBS = 64

#: straggler panel: the delta window the per-rank wait shares are
#: computed over (the baseline snapshot rotates at this age)
STRAGGLER_WINDOW_S = 30.0

#: vector wire markers (mirrors trace.VEC_DELTA/VEC_ABS — no trace
#: import: the runtime layer must not pull the MPI surface at import)
_VEC_DELTA = "d"
_VEC_ABS = "a"


def _is_vec(v: Any) -> bool:
    """A marker-tagged histogram vector value on the wire/in a row."""
    return (isinstance(v, list) and bool(v)
            and v[0] in (_VEC_DELTA, _VEC_ABS))


def hist_counts(v: Any) -> list:
    """A tagged vector's ints (counts + trailing sum), marker stripped;
    [] for anything that is not a vector value."""
    return list(v[1:]) if _is_vec(v) else []


def vec_merge(old: Any, new: Any) -> list:
    """Fold two tagged vectors (see the module doc for the algebra).
    Length mismatches (a version-skewed peer) resolve to the newer
    vector rather than corrupting the element-wise fold."""
    if not _is_vec(old) or len(old) != len(new):
        return list(new)
    if new[0] == _VEC_ABS:
        if old[0] != _VEC_ABS:
            return list(new)       # absolute subsumes pending deltas
        return [_VEC_ABS] + [max(a, b)
                             for a, b in zip(old[1:], new[1:])]
    # new is a delta: increments stack onto whatever came before,
    # keeping the older marker (cumulative + increments stays absolute)
    return [old[0]] + [a + b for a, b in zip(old[1:], new[1:])]

#: a per-(job, rank) stale-datagram fence older than this is itself
#: stale: accept the "regressed" sequence (a revived rank whose first
#: low-numbered pushes were lost would otherwise be fenced until its
#: push counter climbed past the dead life's)
_FENCE_EXPIRE_S = 10.0

#: TAG_METRICS payload / aggregate row: {jobid: {rank: [ts, {name: val}]}}
HopPayload = dict[int, dict[int, list]]


def merge_hop(pending: HopPayload, payload: Any) -> None:
    """Fold one TAG_METRICS payload (or one rank datagram already in hop
    shape) into ``pending`` in place — the per-hop merge.  Scalar values
    are cumulative readings, so their merge is last-writer-wins per
    counter with the freshest wall timestamp kept per rank; histogram
    vectors fold element-wise through :func:`vec_merge` (delta adds,
    absolute subsumes — losing a pending delta to ``dict.update`` would
    silently drop bucket increments)."""
    if not isinstance(payload, dict):
        return
    for jobid, ranks in payload.items():
        if not isinstance(ranks, dict):
            continue
        for rank, row in ranks.items():
            try:
                key, rkey = int(jobid), int(rank)
                ts, vals = float(row[0]), dict(row[1])
            except (TypeError, ValueError, IndexError):
                continue
            cur = pending.setdefault(key, {}).setdefault(rkey, [0.0, {}])
            cur[0] = max(cur[0], ts)
            for name, v in vals.items():
                if _is_vec(v):
                    cur[1][name] = vec_merge(cur[1].get(name), v)
                else:
                    cur[1][name] = v


class MetricsCollector:
    """orted-side uplink stage: local ranks' UDP datagrams + child
    daemons' TAG_METRICS payloads, merged and drained one hop up per
    period.

    The caller owns the cadence (``send_fn`` is invoked from an internal
    timer thread every ``period`` seconds with the drained pending
    payload) and wires :meth:`on_child_payload` to the TAG_METRICS
    handler.
    """

    def __init__(self, period: float,
                 send_fn: Callable[[HopPayload], None],
                 host: str = "127.0.0.1") -> None:
        self.period = period
        self._send_fn = send_fn
        self._lock = threading.Lock()
        self._pending: HopPayload = {}
        # uplink self-metering (the first real data for ROADMAP item
        # 6's fan-in sizing): plain counters under the merge lock,
        # read by /status via stats().  Cumulative, like everything
        # else on this plane.
        self.rx_datagrams = 0
        self.rx_bytes = 0
        self.child_payloads = 0
        self.merge_ns_total = 0
        self.pushes_up = 0
        self.up_bytes = 0
        #: optional {name: value} injected into every local rank row at
        #: drain time — how the measured clock offsets ride the
        #: existing uplink instead of needing their own message shape
        self.extra_values_fn: Optional[Callable[[], dict]] = None
        #: per (jobid, rank): (last accepted datagram seq, monotonic
        #: accept time) — the reorder fence and its expiry clock
        self._seq: dict[tuple[int, int], tuple[int, float]] = {}
        self._stop = threading.Event()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.bind((host, 0))
        self._sock.settimeout(0.5)
        self.uri = f"{host}:{self._sock.getsockname()[1]}"
        threading.Thread(target=self._recv_datagrams,
                         name="metrics-recv", daemon=True).start()
        threading.Thread(target=self._push_up,
                         name="metrics-push", daemon=True).start()

    # -- inputs -----------------------------------------------------------

    def _recv_datagrams(self) -> None:
        while not self._stop.is_set():
            try:
                blob, _addr = self._sock.recvfrom(1 << 16)
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                msg = dss.unpack(blob, n=1)[0]
                tag, jobid, rank, push_n, vals = msg
                if tag != "m1":
                    continue
                key = (int(jobid), int(rank))
                push_n = int(push_n)
                vals = dict(vals)
            except Exception:  # noqa: BLE001 — garbage datagram: drop
                # anything may write to a reused ephemeral UDP port; a
                # bad-typed field must not kill the collector thread
                continue
            now = time.monotonic()
            with self._lock:
                last, t_last = self._seq.get(key, (0, 0.0))
                # reordered/stale datagrams regress cumulative counters —
                # fence them, EXCEPT: a restarted life's seq starts over
                # (push_n <= 2), and a fence older than _FENCE_EXPIRE_S
                # is stale itself (a revived rank whose first datagrams
                # were lost must not be blacked out until its push_n
                # climbs past the dead life's)
                if (push_n <= last and push_n > 2
                        and now - t_last < _FENCE_EXPIRE_S):
                    continue
                self._seq[key] = (push_n, now)
                t0 = time.monotonic_ns()
                merge_hop(self._pending,
                          {key[0]: {key[1]: [time.time(), vals]}})
                self.rx_datagrams += 1
                self.rx_bytes += len(blob)
                self.merge_ns_total += time.monotonic_ns() - t0

    def on_child_payload(self, payload: Any) -> None:
        """TAG_METRICS from a tree child (RML reader thread — merge
        only, no blocking work)."""
        t0 = time.monotonic_ns()
        with self._lock:
            merge_hop(self._pending, payload)
            self.child_payloads += 1
            self.merge_ns_total += time.monotonic_ns() - t0

    # -- drain ------------------------------------------------------------

    def _push_up(self) -> None:
        while not self._stop.wait(self.period):
            payload = self.drain()
            if not payload:
                continue
            try:
                # one extra pack per period buys the actual per-hop
                # byte rate the fan-in sizing needs (payloads are a few
                # KiB; the RML frame adds a constant it doesn't count)
                nbytes = len(dss.pack(payload))
                self._send_fn(payload)
                with self._lock:
                    self.pushes_up += 1
                    self.up_bytes += nbytes
            except Exception:  # noqa: BLE001 — keep the merged delta:
                # an orphaned-window send failure must not lose it
                with self._lock:
                    merged = self._pending
                    self._pending = payload
                    merge_hop(self._pending, merged)

    def drain(self) -> HopPayload:
        """Take the pending merged delta (callers push it one hop up),
        stamping any ``extra_values_fn`` values into every rank row —
        scalars are last-writer-wins downstream, so re-stamping each
        period is idempotent."""
        with self._lock:
            payload, self._pending = self._pending, {}
        fn = self.extra_values_fn
        if fn is not None and payload:
            try:
                extras = {k: v for k, v in dict(fn()).items()
                          if v is not None}
            except Exception:  # noqa: BLE001 — metering must not lose
                extras = {}    # the real payload to a stats callback
            if extras:
                for ranks in payload.values():
                    for row in ranks.values():
                        row[1].update(extras)
        return payload

    def stats(self) -> dict:
        """Uplink self-metrics for /status (cumulative counters)."""
        with self._lock:
            return {"rx_datagrams": self.rx_datagrams,
                    "rx_bytes": self.rx_bytes,
                    "child_payloads": self.child_payloads,
                    "merge_ns_total": self.merge_ns_total,
                    "pushes_up": self.pushes_up,
                    "up_bytes": self.up_bytes}

    def close(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass


#: log2 bucket layout (mirrors trace.HIST_MIN_EXP — same no-import rule
#: as the vector markers): bucket i's upper bound is 2**(_HIST_MIN_EXP+i)
_HIST_MIN_EXP = 10


def _series_base(key: str) -> str:
    """A vector series key's declared base name (label suffix stripped)."""
    return key.split("{", 1)[0]


def _series_labels(key: str) -> str:
    """The label-pair fragment of a series key ('' when unlabeled)."""
    if "{" not in key:
        return ""
    return key.split("{", 1)[1].rstrip("}")


def _quantile_from_counts(counts: list, q: float) -> float:
    """q-quantile estimate in ns from a bucket-count vector (geometric
    midpoint of the landing bucket; the last bucket is the overflow)."""
    total = sum(counts)
    if total <= 0:
        return 0.0
    target = q * total
    seen = 0
    for i, c in enumerate(counts):
        seen += c
        if seen >= target and c:
            hi = 1 << (_HIST_MIN_EXP + i)
            return float(hi) / 1.4142135623730951
    return float(1 << (_HIST_MIN_EXP + len(counts) - 1))


def _hist_series_lines(metric: str, label_prefix: str,
                       ints: list) -> list[str]:
    """One histogram series (counts + trailing sum) as exposition
    lines: CUMULATIVE ``_bucket{le=}`` samples ending at +Inf, then
    ``_sum`` and ``_count``."""
    counts, total_sum = ints[:-1], ints[-1]
    lines = []
    cum = 0
    for i, c in enumerate(counts):
        cum += c
        le = ("+Inf" if i == len(counts) - 1
              else str(1 << (_HIST_MIN_EXP + i)))
        lines.append(
            f'{metric}_bucket{{{label_prefix},le="{le}"}} {cum}')
    lines.append(f'{metric}_sum{{{label_prefix}}} {total_sum}')
    lines.append(f'{metric}_count{{{label_prefix}}} {cum}')
    return lines


def straggler_panel(waits: dict[int, float], publishes: dict[int, float],
                    signal: str, window_s: float) -> Optional[dict]:
    """The cross-rank straggler verdict from per-rank wait/publish sums
    (ns) over one window.  Pure math, shared by the live /status panel
    and tools/straggler_report.py's offline mode.

    The inversion that makes this a straggler detector: a rank whose
    share of the job's total collective WAIT time is lowest is the rank
    everyone else spent their wait time waiting FOR — the last arriver
    barely waits.  ``suspect`` therefore names the min-share rank (the
    job's current slowest), and the max/median skew of the wait
    distribution says how lopsided the window was (≈1 ⇒ balanced)."""
    if not waits:
        return None
    total = float(sum(waits.values()))
    ranks = {}
    for r in sorted(waits):
        share = (waits[r] / total) if total > 0 else 0.0
        ranks[str(r)] = {
            "wait_ms": round(waits[r] / 1e6, 3),
            "publish_ms": round(publishes.get(r, 0.0) / 1e6, 3),
            "wait_share": round(share, 4),
        }
    vals = sorted(waits.values())
    median = vals[len(vals) // 2] if len(vals) % 2 else (
        (vals[len(vals) // 2 - 1] + vals[len(vals) // 2]) / 2.0)
    suspect = None
    if len(waits) >= 2 and total > 0:
        suspect = min(waits, key=lambda r: waits[r])
    return {
        "signal": signal,
        "window_s": round(window_s, 1),
        "ranks": ranks,
        "suspect": suspect,
        "max_wait_ms": round(max(vals) / 1e6, 3),
        "median_wait_ms": round(median / 1e6, 3),
        "skew": (round(max(vals) / median, 2) if median > 0 else None),
    }


class MetricsAggregate:
    """HNP/DVM-side terminal stage: the cumulative per-job, per-rank
    counter table the scrape endpoint and ``--dvm-ps`` read."""

    def __init__(self, max_jobs: int = MAX_JOBS) -> None:
        self._lock = threading.Lock()
        self._jobs: HopPayload = {}
        self._max_jobs = max_jobs
        # terminal-stage self-metering: what one merge costs the HNP
        # and how often the stream arrives (ROADMAP item 6's numbers)
        self.merges_total = 0
        self.merge_ns_total = 0
        #: the explicit shed-and-count policy: payloads refused by the
        #: fan-in budget (metrics_agg_budget_rows), and the rank-rows
        #: they carried — "how much telemetry did overload cost" is
        #: itself telemetry
        self.sheds_total = 0
        self.shed_rows_total = 0
        # None = bucket not yet primed; the first budgeted merge starts
        # with the FULL burst, so boot-time pushes are never shed by an
        # accident of how soon after construction they arrive
        self._budget_tokens: Optional[float] = None
        self._budget_ts = time.monotonic()
        #: jobid → last-merge monotonic ts — the incremental eviction
        #: index (age eviction picks min() here instead of re-scanning
        #: every job's every rank row on each overflow)
        self._job_ts: dict[int, float] = {}
        #: straggler baselines: jobid → (monotonic ts, signal, {rank:
        #: (wait, publish)}); rotated once older than the panel window,
        #: discarded on a signal flip (sums from different histograms
        #: must never be subtracted) and pruned with job eviction
        self._strag_base: dict[int, tuple[float, str,
                                          dict[int, tuple[float,
                                                          float]]]] = {}

    def merge(self, payload: Any) -> None:
        """Fold one TAG_METRICS payload in (RML reader thread safe).

        Admission first: the token bucket (``metrics_agg_budget_rows``
        rank-rows/s, one-second burst) is the uplink-overload valve.
        When every daemon pushes a full snapshot at once the excess
        payloads are dropped WHOLE and counted — bounded merge cost and
        an honest ``sheds_total``, never an unbounded queue.  Rows are
        counted before the lock; a shed costs O(payload keys)."""
        try:
            rows = sum(len(ranks) for ranks in payload.values()
                       if isinstance(ranks, dict))
        except AttributeError:
            rows = 1   # malformed payload: let merge_hop reject it
        t0 = time.monotonic_ns()
        with self._lock:
            rate = float(var_registry.get("metrics_agg_budget_rows") or 0)
            if rate > 0:
                now = time.monotonic()
                if self._budget_tokens is None:
                    self._budget_tokens = rate
                else:
                    self._budget_tokens = min(
                        rate, self._budget_tokens
                        + (now - self._budget_ts) * rate)
                self._budget_ts = now
                if rows > self._budget_tokens:
                    self.sheds_total += 1
                    self.shed_rows_total += rows
                    return
                self._budget_tokens -= rows
            merge_hop(self._jobs, payload)
            now_ts = time.monotonic()
            for jobid in payload:
                self._job_ts[jobid] = now_ts
            self.merges_total += 1
            self.merge_ns_total += time.monotonic_ns() - t0
            while len(self._jobs) > self._max_jobs:
                # incremental age eviction: min() over the per-job
                # last-merge index — O(jobs), not O(total rank rows)
                oldest = min(self._jobs,
                             key=lambda j: self._job_ts.get(j, 0.0))
                del self._jobs[oldest]
                # evicted jobs take their straggler baseline along
                # (a long-lived DVM must not leak one per dead job)
                self._strag_base.pop(oldest, None)
                self._job_ts.pop(oldest, None)

    def prune_job(self, jobid: int) -> None:
        """Drop one job's per-rank counter tables and straggler baseline
        NOW instead of waiting for the MAX_JOBS age eviction: the DVM
        scheduler calls this when a job's record rotates out of its
        bounded history (and on requeue, so a fresh attempt's counters
        don't stack on the killed attempt's) — a standing pool serving
        thousands of short jobs must not hold 64 dead tables between
        evictions."""
        with self._lock:
            self._jobs.pop(int(jobid), None)
            self._strag_base.pop(int(jobid), None)
            self._job_ts.pop(int(jobid), None)

    def stats(self) -> dict:
        """Terminal-stage self-metrics for /status."""
        with self._lock:
            return {"merges_total": self.merges_total,
                    "merge_ns_total": self.merge_ns_total,
                    "sheds_total": self.sheds_total,
                    "shed_rows_total": self.shed_rows_total}

    def snapshot(self) -> HopPayload:
        with self._lock:
            return {j: {r: [row[0], dict(row[1])]
                        for r, row in ranks.items()}
                    for j, ranks in self._jobs.items()}

    def jobids(self) -> list[int]:
        """Known jobids without copying the counter tables (what a
        /status render wants — snapshot() deep-copies everything)."""
        with self._lock:
            return list(self._jobs)

    def rank_values(self, jobid: int,
                    names: tuple) -> dict[int, dict[str, float]]:
        """Per-rank current values of the named scalar metrics for one
        job — the pushed recorder head (``coll_cur_*``) the --dvm-ps
        last_coll column and the doctor's no-response fallback read.
        One table scan under the lock; vectors are skipped."""
        out: dict[int, dict[str, float]] = {}
        with self._lock:
            ranks = self._jobs.get(int(jobid), {})
            for rank, row in ranks.items():
                vals = {n: row[1][n] for n in names
                        if n in row[1] and not _is_vec(row[1][n])}
                if vals:
                    out[int(rank)] = vals
        return out

    def ages(self, jobid: int,
             now: Optional[float] = None) -> dict[int, float]:
        """Per-rank seconds since the last metrics update for ``jobid``
        (the --dvm-ps last-metrics-age column)."""
        now = time.time() if now is None else now
        with self._lock:
            ranks = self._jobs.get(int(jobid), {})
            return {r: max(0.0, now - row[0]) for r, row in ranks.items()}

    def prometheus(self) -> str:
        """The aggregate as Prometheus text: one per-rank series per
        counter (``ompi_tpu_<name>{job=,rank=}``), real histogram
        families for the latency plane (``_bucket{le=}``/``_sum``/
        ``_count``, cumulative le buckets), the per-job ``AGG_METRICS``
        sums (``ompi_tpu_job_<name>{job=}``) and the per-job
        ``AGG_HISTS`` bucket sums.  All samples of one metric name are
        emitted contiguously under a single # TYPE line — the grouping
        the exposition format demands."""
        snap = self.snapshot()
        lines: list[str] = []

        # -- per-rank scalars, grouped by metric name ---------------------
        scalar_names = sorted({
            name for ranks in snap.values() for row in ranks.values()
            for name, v in row[1].items() if not _is_vec(v)})
        for name in scalar_names:
            metric = f"ompi_tpu_{name}"
            kind = "counter" if name.endswith("_total") else "gauge"
            lines.append(f"# TYPE {metric} {kind}")
            for jobid in sorted(snap):
                for rank in sorted(snap[jobid]):
                    v = snap[jobid][rank][1].get(name)
                    if v is not None and not _is_vec(v):
                        lines.append(
                            f'{metric}{{job="{jobid}",rank="{rank}"}} '
                            f"{v}")

        # -- per-rank histograms, grouped by base name --------------------
        hist_bases = sorted({
            _series_base(key)
            for ranks in snap.values() for row in ranks.values()
            for key, v in row[1].items() if _is_vec(v)})
        for base in hist_bases:
            metric = f"ompi_tpu_{base}"
            lines.append(f"# TYPE {metric} histogram")
            for jobid in sorted(snap):
                for rank in sorted(snap[jobid]):
                    vals = snap[jobid][rank][1]
                    for key in sorted(k for k, v in vals.items()
                                      if _is_vec(v)
                                      and _series_base(k) == base):
                        ints = hist_counts(vals[key])
                        if len(ints) < 2:
                            # a version-skewed/corrupt peer's stub
                            # vector must not 500 the whole scrape
                            continue
                        labels = _series_labels(key)
                        pre = (f'job="{jobid}",rank="{rank}"'
                               + ("," + labels if labels else ""))
                        lines += _hist_series_lines(metric, pre, ints)

        # -- per-job scalar sums ------------------------------------------
        for name in AGG_METRICS:
            metric = f"ompi_tpu_job_{name}"
            kind = "counter" if name.endswith("_total") else "gauge"
            job_lines = []
            for jobid in sorted(snap):
                total = sum(row[1].get(name, 0)
                            for row in snap[jobid].values()
                            if not _is_vec(row[1].get(name)))
                job_lines.append(f'{metric}{{job="{jobid}"}} {total}')
            if job_lines:
                lines.append(f"# TYPE {metric} {kind}")
                lines += job_lines

        # -- per-job histogram sums (element-wise across ranks, labels
        #    preserved) ----------------------------------------------------
        for base in AGG_HISTS:
            metric = f"ompi_tpu_job_{base}"
            job_lines = []
            for jobid in sorted(snap):
                by_labels: dict[str, list] = {}
                for row in snap[jobid].values():
                    for key, v in row[1].items():
                        if not _is_vec(v) or _series_base(key) != base:
                            continue
                        ints = hist_counts(v)
                        if len(ints) < 2:
                            continue
                        cur = by_labels.get(_series_labels(key))
                        if cur is None or len(cur) != len(ints):
                            by_labels[_series_labels(key)] = list(ints)
                        else:
                            by_labels[_series_labels(key)] = [
                                a + b for a, b in zip(cur, ints)]
                for labels in sorted(by_labels):
                    pre = (f'job="{jobid}"'
                           + ("," + labels if labels else ""))
                    job_lines += _hist_series_lines(
                        metric, pre, by_labels[labels])
            if job_lines:
                lines.append(f"# TYPE {metric} histogram")
                lines += job_lines
        return "\n".join(lines) + ("\n" if lines else "")

    # -- the latency plane: per-rank quantiles + the straggler panel -------

    def _rank_hist_rows(self, jobid: int, base: str
                        ) -> dict[int, tuple[list, float, float]]:
        """Per rank: (bucket counts summed over the base's label
        variants, observation-sum ns, count) — lock held briefly."""
        out: dict[int, tuple[list, float, float]] = {}
        with self._lock:
            ranks = self._jobs.get(int(jobid), {})
            for rank, row in ranks.items():
                counts: list = []
                total_sum = 0.0
                n = 0.0
                for key, v in row[1].items():
                    if not _is_vec(v) or _series_base(key) != base:
                        continue
                    ints = hist_counts(v)
                    if len(ints) < 2:
                        continue
                    c, s = ints[:-1], ints[-1]
                    if len(counts) != len(c):
                        counts = list(c)
                    else:
                        counts = [a + b for a, b in zip(counts, c)]
                    total_sum += s
                    n += sum(c)
                if counts:
                    out[rank] = (counts, total_sum, n)
        return out

    def job_hist_quantiles(self, jobid: int, base: str,
                           q: float) -> dict[int, float]:
        """Estimated q-quantile in ns of ``base`` for every rank that
        pushed one — ONE table scan per render (the --dvm-ps p99
        column; per-rank calls would rescan under the merge lock)."""
        return {r: _quantile_from_counts(counts, q)
                for r, (counts, _s, n)
                in self._rank_hist_rows(jobid, base).items() if n > 0}

    def rank_hist_quantile(self, jobid: int, rank: int, base: str,
                           q: float) -> Optional[float]:
        """One rank's q-quantile (None when the rank pushed no such
        histogram) — convenience over :meth:`job_hist_quantiles`."""
        return self.job_hist_quantiles(jobid, base, q).get(rank)

    def straggler(self, jobid: int,
                  window_s: float = STRAGGLER_WINDOW_S,
                  now: Optional[float] = None) -> Optional[dict]:
        """The per-job straggler panel over the last window: per-rank
        collective wait-time share, max/median skew, and the current
        slowest rank.  Prefers the arena wait histogram (the direct
        signal); falls back to total coll dispatch time when no arena
        series exists (cross-host jobs), where the same min-share
        inversion holds — the last arriver spends the least time inside
        the collective.  None when no rank pushed latency data."""
        now = time.monotonic() if now is None else now
        wait_rows = self._rank_hist_rows(jobid, "coll_arena_wait_ns")
        signal = "arena_wait"
        if not any(n > 0 for _c, _s, n in wait_rows.values()):
            wait_rows = self._rank_hist_rows(jobid, "coll_dispatch_ns")
            signal = "coll_dispatch"
        if not wait_rows:
            return None
        pub_rows = self._rank_hist_rows(jobid, "coll_ppublish_ns")
        cur = {r: (s, pub_rows.get(r, ([], 0.0, 0.0))[1])
               for r, (_c, s, _n) in wait_rows.items()}
        with self._lock:
            base = self._strag_base.get(int(jobid))
            # a baseline from the OTHER signal is poison: subtracting
            # dispatch sums from arena-wait sums (a job whose first
            # arena series appeared after a cross-host phase) yields
            # garbage shares — start a fresh window instead
            if base is not None and base[1] != signal:
                base = None
            if base is None:
                base_t, base_sums = now, {}
                self._strag_base[int(jobid)] = (now, signal, dict(cur))
            else:
                base_t, _sig, base_sums = base
                if now - base_t > window_s:
                    self._strag_base[int(jobid)] = (now, signal,
                                                    dict(cur))
        waits = {r: max(0.0, s - base_sums.get(r, (0.0, 0.0))[0])
                 for r, (s, _p) in cur.items()}
        pubs = {r: max(0.0, p - base_sums.get(r, (0.0, 0.0))[1])
                for r, (_s, p) in cur.items()}
        window = max(0.0, now - base_t)
        if not any(waits.values()):
            # an empty delta window (baseline just rotated, or an idle
            # job): fall back to the cumulative sums so the panel never
            # goes blank; window_s 0.0 marks a whole-history verdict
            waits = {r: s for r, (s, _p) in cur.items()}
            pubs = {r: p for r, (_s, p) in cur.items()}
            window = 0.0
        return straggler_panel(waits, pubs, signal, window_s=window)
