"""Metrics uplink — the control-plane half of the live observability
plane.

Each app rank pushes its pvar snapshot (``trace.metrics_values()``,
delta-compressed) over UDP to its owning orted's :class:`MetricsCollector`
every ``trace_metrics_push_period`` seconds.  Each orted merges its local
ranks with whatever its tree children pushed up (``TAG_METRICS`` is a
one-hop message delivered at every level, not an HNP-only ``send_up``)
and forwards ONE merged delta per period toward the root.  The HNP/DVM
folds the stream into a :class:`MetricsAggregate` keyed by jobid and
rank — what the DVM's ``/metrics`` scrape endpoint and ``tpurun
--dvm-ps``'s last-metrics-age column read.

Wire shapes:

- rank → orted (UDP datagram): ``("m1", jobid, rank, push_n, {name: value})``
  — ``push_n`` fences reordered/stale datagrams; every
  ``trace.FULL_EVERY``-th push is a full snapshot so UDP loss heals.
- orted → parent (``TAG_METRICS``, one hop):
  ``{jobid: {rank: [wall_ts, {name: value}]}}`` — values are cumulative
  counter readings (NOT increments), so a per-hop merge is a plain
  ``dict.update`` per rank and double-delivery cannot double-count.

Thread-context rules: the TAG_METRICS handler runs on an RML link
reader thread — :func:`merge_hop` is dict surgery under one lock, no
RPC/sleep/subprocess (see the ``reader-thread`` lint checker).
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Any, Callable, Optional

from ompi_tpu.core import dss, output

__all__ = ["merge_hop", "MetricsCollector", "MetricsAggregate",
           "AGG_METRICS"]

_log = output.get_stream("metrics")

#: the per-job aggregated-metric name family: counters the DVM scrape
#: endpoint ADDITIONALLY exports summed across a job's ranks as
#: ``ompi_tpu_job_<name>{job="<jobid>"}``.  Every entry must name a
#: ``trace._COUNTER_SPECS`` counter — the ompi-lint ``pvar-spec``
#: checker cross-checks both directions so a renamed counter cannot
#: silently vanish from the scrape surface.
AGG_METRICS = (
    "pml_zero_copy_sends_total",
    "pml_packed_sends_total",
    "btl_shm_publish_total",
    "btl_shm_drained_total",
    "coll_shm_fanin_total",
    "coll_shm_fanout_total",
    "coll_shm_fallback_total",
    "ft_rank_deaths_total",
    "ft_gossip_beats_total",
    "ft_fenced_frames_total",
    "errmgr_selfheal_revives_total",
    "errmgr_selfheal_escalations_total",
)

#: jobs kept in the aggregate before the oldest (by last update) fall off
MAX_JOBS = 64

#: a per-(job, rank) stale-datagram fence older than this is itself
#: stale: accept the "regressed" sequence (a revived rank whose first
#: low-numbered pushes were lost would otherwise be fenced until its
#: push counter climbed past the dead life's)
_FENCE_EXPIRE_S = 10.0

#: TAG_METRICS payload / aggregate row: {jobid: {rank: [ts, {name: val}]}}
HopPayload = dict[int, dict[int, list]]


def merge_hop(pending: HopPayload, payload: Any) -> None:
    """Fold one TAG_METRICS payload (or one rank datagram already in hop
    shape) into ``pending`` in place — the per-hop merge.  Values are
    cumulative readings, so the merge is last-writer-wins per counter
    with the freshest wall timestamp kept per rank."""
    if not isinstance(payload, dict):
        return
    for jobid, ranks in payload.items():
        if not isinstance(ranks, dict):
            continue
        for rank, row in ranks.items():
            try:
                key, rkey = int(jobid), int(rank)
                ts, vals = float(row[0]), dict(row[1])
            except (TypeError, ValueError, IndexError):
                continue
            cur = pending.setdefault(key, {}).setdefault(rkey, [0.0, {}])
            cur[0] = max(cur[0], ts)
            cur[1].update(vals)


class MetricsCollector:
    """orted-side uplink stage: local ranks' UDP datagrams + child
    daemons' TAG_METRICS payloads, merged and drained one hop up per
    period.

    The caller owns the cadence (``send_fn`` is invoked from an internal
    timer thread every ``period`` seconds with the drained pending
    payload) and wires :meth:`on_child_payload` to the TAG_METRICS
    handler.
    """

    def __init__(self, period: float,
                 send_fn: Callable[[HopPayload], None],
                 host: str = "127.0.0.1") -> None:
        self.period = period
        self._send_fn = send_fn
        self._lock = threading.Lock()
        self._pending: HopPayload = {}
        #: per (jobid, rank): (last accepted datagram seq, monotonic
        #: accept time) — the reorder fence and its expiry clock
        self._seq: dict[tuple[int, int], tuple[int, float]] = {}
        self._stop = threading.Event()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.bind((host, 0))
        self._sock.settimeout(0.5)
        self.uri = f"{host}:{self._sock.getsockname()[1]}"
        threading.Thread(target=self._recv_datagrams,
                         name="metrics-recv", daemon=True).start()
        threading.Thread(target=self._push_up,
                         name="metrics-push", daemon=True).start()

    # -- inputs -----------------------------------------------------------

    def _recv_datagrams(self) -> None:
        while not self._stop.is_set():
            try:
                blob, _addr = self._sock.recvfrom(1 << 16)
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                msg = dss.unpack(blob, n=1)[0]
                tag, jobid, rank, push_n, vals = msg
                if tag != "m1":
                    continue
                key = (int(jobid), int(rank))
                push_n = int(push_n)
                vals = dict(vals)
            except Exception:  # noqa: BLE001 — garbage datagram: drop
                # anything may write to a reused ephemeral UDP port; a
                # bad-typed field must not kill the collector thread
                continue
            now = time.monotonic()
            with self._lock:
                last, t_last = self._seq.get(key, (0, 0.0))
                # reordered/stale datagrams regress cumulative counters —
                # fence them, EXCEPT: a restarted life's seq starts over
                # (push_n <= 2), and a fence older than _FENCE_EXPIRE_S
                # is stale itself (a revived rank whose first datagrams
                # were lost must not be blacked out until its push_n
                # climbs past the dead life's)
                if (push_n <= last and push_n > 2
                        and now - t_last < _FENCE_EXPIRE_S):
                    continue
                self._seq[key] = (push_n, now)
                merge_hop(self._pending,
                          {key[0]: {key[1]: [time.time(), vals]}})

    def on_child_payload(self, payload: Any) -> None:
        """TAG_METRICS from a tree child (RML reader thread — merge
        only, no blocking work)."""
        with self._lock:
            merge_hop(self._pending, payload)

    # -- drain ------------------------------------------------------------

    def _push_up(self) -> None:
        while not self._stop.wait(self.period):
            payload = self.drain()
            if not payload:
                continue
            try:
                self._send_fn(payload)
            except Exception:  # noqa: BLE001 — keep the merged delta:
                # an orphaned-window send failure must not lose it
                with self._lock:
                    merged = self._pending
                    self._pending = payload
                    merge_hop(self._pending, merged)

    def drain(self) -> HopPayload:
        """Take the pending merged delta (callers push it one hop up)."""
        with self._lock:
            payload, self._pending = self._pending, {}
        return payload

    def close(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass


class MetricsAggregate:
    """HNP/DVM-side terminal stage: the cumulative per-job, per-rank
    counter table the scrape endpoint and ``--dvm-ps`` read."""

    def __init__(self, max_jobs: int = MAX_JOBS) -> None:
        self._lock = threading.Lock()
        self._jobs: HopPayload = {}
        self._max_jobs = max_jobs

    def merge(self, payload: Any) -> None:
        """Fold one TAG_METRICS payload in (RML reader thread safe)."""
        with self._lock:
            merge_hop(self._jobs, payload)
            if len(self._jobs) > self._max_jobs:
                by_age = sorted(
                    self._jobs,
                    key=lambda j: max((r[0] for r in
                                       self._jobs[j].values()),
                                      default=0.0))
                for jobid in by_age[:len(self._jobs) - self._max_jobs]:
                    del self._jobs[jobid]

    def snapshot(self) -> HopPayload:
        with self._lock:
            return {j: {r: [row[0], dict(row[1])]
                        for r, row in ranks.items()}
                    for j, ranks in self._jobs.items()}

    def jobids(self) -> list[int]:
        """Known jobids without copying the counter tables (what a
        /status render wants — snapshot() deep-copies everything)."""
        with self._lock:
            return list(self._jobs)

    def ages(self, jobid: int,
             now: Optional[float] = None) -> dict[int, float]:
        """Per-rank seconds since the last metrics update for ``jobid``
        (the --dvm-ps last-metrics-age column)."""
        now = time.time() if now is None else now
        with self._lock:
            ranks = self._jobs.get(int(jobid), {})
            return {r: max(0.0, now - row[0]) for r, row in ranks.items()}

    def prometheus(self) -> str:
        """The aggregate as Prometheus text: one per-rank series per
        counter (``ompi_tpu_<name>{job=,rank=}``) plus the per-job
        ``AGG_METRICS`` sums (``ompi_tpu_job_<name>{job=}``)."""
        snap = self.snapshot()
        lines: list[str] = []
        typed: set[str] = set()

        def _type_line(metric: str) -> None:
            if metric not in typed:
                typed.add(metric)
                kind = ("counter" if metric.endswith("_total")
                        else "gauge")
                lines.append(f"# TYPE {metric} {kind}")

        for jobid in sorted(snap):
            for rank in sorted(snap[jobid]):
                _ts, vals = snap[jobid][rank]
                for name in sorted(vals):
                    metric = f"ompi_tpu_{name}"
                    _type_line(metric)
                    lines.append(
                        f'{metric}{{job="{jobid}",rank="{rank}"}} '
                        f"{vals[name]}")
        for jobid in sorted(snap):
            for name in AGG_METRICS:
                total = sum(row[1].get(name, 0)
                            for row in snap[jobid].values())
                metric = f"ompi_tpu_job_{name}"
                _type_line(metric)
                lines.append(f'{metric}{{job="{jobid}"}} {total}')
        return "\n".join(lines) + ("\n" if lines else "")
