"""Local process launcher: fork/exec + IOF forwarding + state machine.

The HNP role of the reference, collapsed to one host: orterun's event-driven
launch DAG (orte/mca/state/hnp/state_hnp.c:74-112:
INIT→ALLOCATE→MAP→LAUNCH_APPS→RUNNING→TERMINATED), odls's fork/exec with
error reporting (orte/mca/odls/default/odls_default_module.c:47-56,140), and
iof's stdout/stderr forwarding with rank tagging (orte/mca/iof).

Multi-host launch (the reference's plm/rsh ssh tree) is out of scope for the
local launcher; the TPU analog — one launcher per TPU host, coordinated via
jax.distributed — plugs in as a different plm component later, reusing this
state machine.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time
from typing import Optional

from ompi_tpu.core import output
from ompi_tpu.core.config import VarType, register_var, var_registry
from ompi_tpu.runtime import errmgr as errmgr_mod
from ompi_tpu.runtime import pmix, ras, rmaps
from ompi_tpu.runtime.job import AppContext, Job, JobState, Proc, ProcState
from ompi_tpu.runtime.state import StateMachine

__all__ = ["LocalLauncher", "launch"]

_log = output.get_stream("launcher")

register_var("launcher", "tag_output", VarType.BOOL, True,
             "prefix forwarded stdout/stderr with [jobid,rank]")
register_var("launcher", "kill_grace_s", VarType.DOUBLE, 2.0,
             "seconds between SIGTERM and SIGKILL when aborting a job")


class LocalLauncher:
    """Launches a job's ranks as local OS processes (device-per-rank aware)."""

    def __init__(self, want_tpu: bool = False,
                 stdin_target: Optional[str] = None, **select_ctx) -> None:
        self.want_tpu = want_tpu
        # ≈ iof.h:27-43: launcher stdin goes to rank 0 by default;
        # "all" duplicates it to every rank, "none" gives ranks /dev/null.
        self.stdin_target = "0" if stdin_target is None else str(stdin_target)
        self.select_ctx = select_ctx
        self.sm = StateMachine()
        self.sm.add_state(JobState.INIT, self._st_init)
        self.sm.add_state(JobState.ALLOCATE, self._st_allocate)
        self.sm.add_state(JobState.MAP, self._st_map)
        self.sm.add_state(JobState.LAUNCH_APPS, self._st_launch)
        self.sm.add_state(JobState.RUNNING, self._st_running)
        self.server: Optional[pmix.PMIxServer] = None
        self._popen: dict[int, subprocess.Popen] = {}
        self._iof_threads: list[threading.Thread] = []
        self._errmgr = errmgr_mod.errmgr_framework.select(**select_ctx)
        self._kill_lock = threading.Lock()
        self._stdin_sinks: dict[int, object] = {}   # rank → _StdinWriter
        self._respawned: set[int] = set()  # ranks revived since last reap

    # -- state handlers (the launch DAG) ---------------------------------

    def _st_init(self, sm: StateMachine, job: Job) -> JobState:
        return JobState.ALLOCATE

    def _st_allocate(self, sm: StateMachine, job: Job) -> JobState:
        ras.allocate(job, want_tpu=self.want_tpu, **self.select_ctx)
        return JobState.MAP

    def _st_map(self, sm: StateMachine, job: Job) -> JobState:
        rmaps.map_job(job, **self.select_ctx)
        return JobState.LAUNCH_APPS

    def _proc_env(self, job: Job, proc: Proc) -> dict:
        # ≈ plm_rsh prefixing PATH/LD_LIBRARY_PATH with its install prefix
        # (orte/mca/plm/rsh/plm_rsh_module.c): make this framework importable
        # in children no matter their cwd.
        from ompi_tpu.core import pkg_root as _pkg_root

        root = _pkg_root()
        app = job.apps[proc.app_idx]
        env = dict(os.environ)
        env.update(app.env)
        errmgr_mod.apply_host_plane_policy(self._errmgr, env)
        pypath = env.get("PYTHONPATH", "")
        if root not in pypath.split(os.pathsep):
            env["PYTHONPATH"] = (
                root + (os.pathsep + pypath if pypath else ""))
        env[pmix.ENV_URI] = self.server.uri
        env[pmix.ENV_RANK] = str(proc.rank)
        env[pmix.ENV_SIZE] = str(job.np)
        env[pmix.ENV_JOBID] = str(job.jobid)
        env[pmix.ENV_LOCAL_RANK] = str(proc.local_rank)
        if proc.chip is not None:
            env[pmix.ENV_CHIP] = str(proc.chip)
        if proc.lives:
            env["OMPI_TPU_RESTART"] = str(proc.lives)
        return env

    def _launch_proc(self, job: Job, proc: Proc) -> bool:
        """Fork/exec one rank (first launch or errmgr respawn); False on
        failure to start (proc.state records why)."""
        app = job.apps[proc.app_idx]
        want_stdin = (self.stdin_target == "all"
                      or self.stdin_target == str(proc.rank))
        from ompi_tpu.runtime.rtc import bind_child

        try:
            p = subprocess.Popen(
                app.argv, env=self._proc_env(job, proc), cwd=app.cwd,
                stdin=(subprocess.PIPE if want_stdin
                       else subprocess.DEVNULL),
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                start_new_session=True)
        except OSError as e:
            # ≈ odls error-pipe protocol: exec failure surfaces here.
            proc.state = ProcState.FAILED_TO_START
            proc.exit_code = 127
            output.show_help(
                "launcher", "failed-to-start",
                rank=proc.rank, argv0=app.argv[0], error=str(e))
            return False
        proc.pid = p.pid
        proc.state = ProcState.RUNNING
        # uptime clock (errmgr crash-loop governor) starts at the rank's
        # PMIx registration, not here — interpreter+jax boot (seconds on
        # a loaded box) must not count toward errmgr_min_uptime_s
        proc.launched_at = None
        bind_child(p.pid, proc.local_rank)
        with self._kill_lock:  # kill_job may iterate concurrently
            self._popen[proc.rank] = p
        if want_stdin:
            from ompi_tpu.runtime.orted import _StdinWriter

            # a respawned rank replaces its dead incarnation's writer —
            # retire the old one (its pipe is broken anyway) so sinks and
            # threads don't accumulate per restart
            old = self._stdin_sinks.pop(proc.rank, None)
            if old is not None:
                old.feed(None)
            self._stdin_sinks[proc.rank] = _StdinWriter(proc.rank, p.stdin)
        self._start_iof(job, proc, p)
        return True

    def respawn_proc(self, job: Job, proc: Proc) -> bool:
        """errmgr/respawn hook: revive a failed rank in place (same rank,
        same env plus OMPI_TPU_RESTART=<n>).  The running reap loop picks
        the new child up; the PMIx server counts the rank live again."""
        from ompi_tpu.runtime import ftevents

        proc.restarts += 1   # budget burn (governor may reset it)
        proc.lives += 1      # identity: monotone, survives budget resets
        proc.exit_code = None
        if not self._launch_proc(job, proc):
            return False
        ftevents.record("revive", jobid=job.jobid, rank=proc.rank,
                        lives=proc.lives)
        if self.server is not None:
            self.server.proc_revived(proc.rank, proc.lives)
        with self._kill_lock:
            self._respawned.add(proc.rank)
        return True

    def _st_launch(self, sm: StateMachine, job: Job) -> JobState:
        self.server = pmix.PMIxServer(
            size=job.np, on_abort=lambda r, s, m: self._on_abort(job, r, s, m))
        # rank-plane gossip feedback: a client-reported hung rank (alive
        # pid, silent to its peers) gets its pid reaped so the reap loop
        # sees a real exit and the errmgr policy runs
        self.server.on_failed_report = \
            lambda r, reason: self._reap_reported(r, reason)
        # the rank's first PMIx contact starts its uptime clock — the
        # crash-loop governor must not count interpreter boot as uptime
        self.server.on_client_contact = \
            lambda r: self._mark_contact(job, r)
        for proc in job.procs:
            if not self._launch_proc(job, proc):
                # Failure to start is fatal regardless of errmgr policy —
                # the job never assembled, so no policy (not even respawn)
                # is consulted: record the abort and reap what launched.
                if job.aborted_proc is None:
                    job.aborted_proc = proc
                    job.abort_reason = f"rank {proc.rank} failed to start"
                self.kill_job(job, exclude=proc)
                return JobState.RUNNING  # reap launched ranks, then ABORTED
        if self._stdin_sinks:
            self._start_stdin_pump()
        return JobState.RUNNING

    def _st_running(self, sm: StateMachine, job: Job) -> Optional[JobState]:
        # Reap children; first abnormal exit triggers the errmgr policy.
        with self._kill_lock:
            pending = dict(self._popen)
        while pending:
            for rank, p in list(pending.items()):
                rc = p.poll()
                if rc is None:
                    continue
                proc = job.procs[rank]
                proc.exit_code = rc
                if proc.state == ProcState.KILLED_BY_CMD:
                    pass  # we killed it during abort
                elif rc == 0:
                    proc.state = ProcState.TERMINATED
                    # late gossip suspicions about a clean finisher
                    # (its beats stopped with its transports) must not
                    # read as failures — tell the report_failed gate
                    if self.server is not None:
                        self.server.proc_finished(rank)
                else:
                    proc.state = ProcState.ABORTED
                    # wake fence/get waiters so surviving ranks don't hang
                    # on a dead peer (matters under errmgr/continue)
                    if self.server is not None:
                        self.server.proc_died(rank)
                    self._errmgr.proc_failed(self, job, proc)
                del pending[rank]
            # adopt ranks the errmgr revived (≈ rmaps/resilient re-map +
            # relaunch: same rank, fresh pid, reap continues seamlessly)
            with self._kill_lock:
                while self._respawned:
                    r = self._respawned.pop()
                    pending[r] = self._popen[r]
            if pending:
                time.sleep(0.01)
        for t in self._iof_threads:
            t.join(timeout=2.0)
        if self.server is not None:
            self.server.close()
        return (JobState.ABORTED if job.aborted_proc is not None
                else JobState.TERMINATED)

    # -- IOF --------------------------------------------------------------

    def _start_iof(self, job: Job, proc: Proc, p: subprocess.Popen) -> None:
        tag = var_registry.get("launcher_tag_output")

        def reader(pipe, sink):
            prefix = f"[{job.jobid},{proc.rank}]" if tag else ""
            for raw in iter(pipe.readline, b""):
                line = raw.decode(errors="replace")
                sink.write(f"{prefix}{line}" if prefix else line)
                sink.flush()
            pipe.close()

        for pipe, sink in ((p.stdout, sys.stdout), (p.stderr, sys.stderr)):
            t = threading.Thread(target=reader, args=(pipe, sink), daemon=True)
            t.start()
            self._iof_threads.append(t)

    def _start_stdin_pump(self) -> None:
        """Forward launcher stdin to the target rank(s) (≈ iof hnp stdin).

        Each sink is a bounded-queue ``_StdinWriter`` (shared with orted),
        so one rank that never drains stdin cannot head-of-line block the
        others under ``--stdin all``.
        """
        def pump() -> None:
            # raw-fd reads, NOT sys.stdin.buffer: a daemon thread blocked
            # in BufferedReader.read1 holds the buffer lock, and CPython's
            # shutdown aborts the whole launcher (_enter_buffered_busy,
            # SIGABRT masking the job's real exit code) when it cannot
            # reacquire it — os.read involves no Python-level lock
            import os as _os

            try:
                fd = sys.stdin.fileno()
            except (AttributeError, ValueError, OSError):
                fd = None   # stdin replaced (pytest capture) — nothing here
            try:
                while fd is not None:
                    chunk = _os.read(fd, 1 << 16)
                    if not chunk:
                        break
                    for w in list(self._stdin_sinks.values()):
                        w.feed(chunk)
            except (OSError, ValueError):
                pass
            for w in list(self._stdin_sinks.values()):
                w.feed(None)  # EOF

        threading.Thread(target=pump, daemon=True).start()

    def _mark_contact(self, job: Job, rank: int) -> None:
        """PMIx server hook: the rank's current life registered — start
        its uptime clock (errmgr_min_uptime_s measures from here, so a
        slow boot can't earn the crash-loop budget back)."""
        if 0 <= rank < len(job.procs):
            job.procs[rank].launched_at = time.monotonic()

    def _reap_reported(self, rank: int, reason: str) -> None:
        """SIGKILL one reported-dead rank (it is hung, not exited — a
        SIGSTOP'd or deadlocked pid never reports on its own).  The reap
        loop then accounts the exit and the errmgr policy decides."""
        with self._kill_lock:
            p = self._popen.get(rank)
        if p is None or p.poll() is not None:
            return
        from ompi_tpu.runtime import ftevents

        _log.verbose(1, "reaping reported-dead rank %d (pid %d): %s",
                     rank, p.pid, reason or "gossip-declared")
        ftevents.record("reap", rank=rank,
                        reason=reason or "gossip-declared")
        try:
            os.killpg(p.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass

    # -- abort path --------------------------------------------------------

    def _on_abort(self, job: Job, rank: int, status: int, msg: str) -> None:
        proc = job.procs[rank]
        if job.aborted_proc is None:
            job.aborted_proc = proc
            job.abort_reason = f"rank {rank} called abort: {msg}"
            job.abort_status = status
        # The aborting rank asked for job teardown; it gets killed too (its
        # requested status is preserved via job.abort_status).
        self.kill_job(job)

    def kill_job(self, job: Job, exclude: Optional[Proc] = None) -> None:
        """SIGTERM all live ranks, then SIGKILL stragglers after a grace."""
        with self._kill_lock:
            victims = []
            for rank, p in list(self._popen.items()):
                proc = job.procs[rank]
                if proc is exclude or p.poll() is not None:
                    continue
                proc.state = ProcState.KILLED_BY_CMD
                try:
                    os.killpg(p.pid, signal.SIGTERM)
                except (ProcessLookupError, PermissionError):
                    continue
                victims.append(p)
        if not victims:
            return
        deadline = time.monotonic() + var_registry.get("launcher_kill_grace_s")
        for p in victims:
            remaining = deadline - time.monotonic()
            try:
                p.wait(timeout=max(0.0, remaining))
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(p.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass

    # -- entry -------------------------------------------------------------

    def run(self, job: Job) -> int:
        """Drive the job to completion; return the job exit code."""
        self.sm.run_to_completion(job, JobState.INIT)
        if job.aborted_proc is not None:
            from ompi_tpu.runtime.notifier import Severity, notify

            notify(Severity.ERROR, "job-abort",
                   f"job {job.jobid}: {job.abort_reason or 'unknown'}")
            output.show_help(
                "launcher", "job-aborted",
                jobid=job.jobid, reason=job.abort_reason or "unknown")
            if job.abort_status is not None:
                return job.abort_status or 1
            rc = job.aborted_proc.exit_code or 1
            # signal death: report the shell convention 128+signum, not a
            # negative value that the OS would truncate meaninglessly
            return 128 - rc if rc < 0 else rc
        return 0


def launch(argv: list[str], np: int, want_tpu: bool = False,
           env: Optional[dict[str, str]] = None,
           stdin_target: Optional[str] = None, **select_ctx) -> int:
    """One-call launch: build the job, run it, return exit code."""
    job = Job([AppContext(argv=argv, np=np, env=env or {})])
    return LocalLauncher(want_tpu=want_tpu, stdin_target=stdin_target,
                         **select_ctx).run(job)
