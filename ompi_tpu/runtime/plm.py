"""PLM — process lifecycle management: launching the daemon VM.

≈ orte/mca/plm (plm_rsh_module.c:102,697: the ssh tree-spawn) plus the HNP
launch logic of plm_base_launch_support.c.  Components start one orted per
allocated node; the :class:`MultiHostLauncher` drives the full job DAG
(clone of state_hnp.c:74-112):

    INIT → ALLOCATE → MAP → LAUNCH_DAEMONS → VM_READY → LAUNCH_APPS
         → RUNNING → TERMINATED/ABORTED

Components:

- ``sim`` — daemons are local child processes with simulated host
  identities (``--fake-host sim-host-N``): the multi-host control plane,
  modex routing, IOF tree, and cross-"host" data paths all run for real on
  one machine (ranks on different sim-hosts refuse shm and ride tcp).
  This is the test fixture the reference gets from ras_sim + rsh on
  localhost.
- ``ssh`` — real remote spawn over ssh (non-interactive auth assumed,
  exactly plm/rsh's contract).  The TPU-pod analog of the rsh tree: one
  daemon per TPU host; app procs then bind their local chips.
"""

from __future__ import annotations

import os
import shlex
import subprocess
import sys
import threading
import time
from typing import Optional

from ompi_tpu.core import output
from ompi_tpu.core.config import VarType, register_var, var_registry
from ompi_tpu.core.mca import Component, Framework
from ompi_tpu.runtime import clocksync
from ompi_tpu.runtime import errmgr as errmgr_mod
from ompi_tpu.runtime import launcher as _launcher  # registers launcher_* vars
from ompi_tpu.runtime import pmix, ras, rmaps, rml
from ompi_tpu.runtime.job import AppContext, Job, JobState, Proc, ProcState
from ompi_tpu.runtime.state import StateMachine

__all__ = ["plm_framework", "MultiHostLauncher"]

_log = output.get_stream("plm")

plm_framework = Framework("plm", "process lifecycle management")

register_var("plm", "daemon_timeout", VarType.DOUBLE, 30.0,
             "seconds to wait for daemons to phone home / wire up")
register_var("plm", "ssh_args", VarType.STRING,
             "-o BatchMode=yes -o StrictHostKeyChecking=no",
             "extra arguments for the ssh transport")
register_var("plm", "ssh_python", VarType.STRING, "",
             "python interpreter to exec on remote hosts (empty = same "
             "path as the HNP's sys.executable)")
register_var("plm", "exit_report_timeout", VarType.DOUBLE, 3.0,
             "seconds to wait for straggler rank-exit reports during "
             "teardown (VM stop mid-job, daemon loss) before accounting "
             "the job without them")
register_var("plm", "loss_epoch_window", VarType.DOUBLE, 0.25,
             "seconds the HNP's reparent worker waits after a daemon "
             "death for more deaths to join the same loss epoch — a "
             "correlated rack loss collapses into ONE batched adoption "
             "round (O(orphans) frames) instead of a per-dead-vpid "
             "storm (0 = handle each death immediately)")
register_var("plm", "daemon_drain_timeout", VarType.DOUBLE, 5.0,
             "seconds the VM teardown waits for orted daemons to exit "
             "after the SHUTDOWN xcast before killing them")


def _orted_argv(hnp_uri: str, vpid: int, ndaemons: int,
                fake_host: Optional[str] = None) -> list[str]:
    argv = [sys.executable, "-m", "ompi_tpu.runtime.orted",
            "--hnp", hnp_uri, "--vpid", str(vpid),
            "--ndaemons", str(ndaemons)]
    if fake_host:
        argv += ["--fake-host", fake_host]
    return argv


@plm_framework.component
class SimPlm(Component):
    """Local daemon processes with simulated host identities."""

    NAME = "sim"
    PRIORITY = 10

    def spawn_daemons(self, job: Job, hnp_uri: str) -> list[subprocess.Popen]:
        procs = []
        for i, node in enumerate(job.nodes):
            argv = _orted_argv(hnp_uri, i + 1, len(job.nodes) + 1,
                               fake_host=node.name)
            procs.append(subprocess.Popen(
                argv, env=dict(os.environ), start_new_session=True))
        return procs


@plm_framework.component
class SshPlm(Component):
    """≈ plm/rsh: 'ssh <node> orted ...' per allocated host."""

    NAME = "ssh"
    PRIORITY = 20

    def query(self, **ctx):
        return self.PRIORITY if ctx.get("remote_hosts") else None

    def spawn_daemons(self, job: Job, hnp_uri: str) -> list[subprocess.Popen]:
        ssh_args = shlex.split(var_registry.get("plm_ssh_args") or "")
        # ≈ plm_rsh prefixing PATH/LD_LIBRARY_PATH on the remote command
        # (plm_rsh_module.c): env does NOT travel over ssh, so the remote
        # python must be told where this framework lives (same-path
        # assumption for the interpreter itself — shared-filesystem
        # clusters; override the interpreter via plm_ssh_python).
        from ompi_tpu.core import pkg_root

        procs = []
        for i, node in enumerate(job.nodes):
            orted = _orted_argv(hnp_uri, i + 1, len(job.nodes) + 1)
            py = var_registry.get("plm_ssh_python") or orted[0]
            remote = (f"PYTHONPATH={shlex.quote(pkg_root())}"
                      "${PYTHONPATH:+:$PYTHONPATH} "
                      + " ".join(shlex.quote(a) for a in [py, *orted[1:]]))
            argv = ["ssh", *ssh_args, node.name, remote]
            procs.append(subprocess.Popen(
                argv, env=dict(os.environ), start_new_session=True))
        return procs


class MultiHostLauncher:
    """The HNP for a daemon-tree launch (≈ orterun driving state_hnp)."""

    def __init__(self, plm_name: str = "sim", want_tpu: bool = False,
                 stdin_target: str = "none", **select_ctx) -> None:
        self.want_tpu = want_tpu
        # validate before any daemon exists: a bad --stdin must fail the
        # CLI, not blow up the state machine mid-launch
        if stdin_target not in ("all", "none") and not str(stdin_target).isdigit():
            raise ValueError(
                f"--stdin must be a rank number, 'all' or 'none' "
                f"(got {stdin_target!r})")
        self.stdin_target = str(stdin_target)
        self.select_ctx = select_ctx
        self.plm = plm_framework.lookup(plm_name)
        self.sm = StateMachine()
        self.sm.add_state(JobState.INIT, lambda sm, job: JobState.ALLOCATE)
        self.sm.add_state(JobState.ALLOCATE, self._st_allocate)
        self.sm.add_state(JobState.MAP, self._st_map)
        self.sm.add_state(JobState.LAUNCH_APPS, self._st_launch)
        self.sm.add_state(JobState.RUNNING, self._st_running)
        self._errmgr = errmgr_mod.errmgr_framework.select(**select_ctx)
        self.rml: Optional[rml.RmlNode] = None
        self.server: Optional[pmix.PMIxServer] = None
        self._daemon_popen: list[subprocess.Popen] = []
        self._registered: dict[int, tuple[str, str]] = {}  # vpid→(uri,host)
        self._ready: set[int] = set()
        self._cv = threading.Condition()
        self._killed = False
        self._lost_daemon: Optional[int] = None            # vpid, if died
        self._dead_daemons: set[int] = set()   # every vpid ever declared
        # dead (link EOF / Popen / heartbeat / orphan report) — the
        # idempotence guard AND the ancestry map re-parenting skips over
        self._np_hint = 1 << 30                            # set at launch
        self._cur_job: Optional[Job] = None
        self._n_daemons = 0        # world size minus the HNP, set at _vm_up
        # the EFFECTIVE routing tree: vpid → current parent, seeded from
        # the static tree at wire time and rewritten by every adoption.
        # Loss epochs compute orphanhood against THIS map (not the static
        # tree), so a dead adopter's previously adopted children are
        # re-orphaned and re-homed — never left holding a child-link to
        # a corpse — and an already-re-homed orphan is never adopted twice
        self._eff_parent: dict[int, int] = {}
        # loss-epoch queue: detectors (link EOF on reader threads, the
        # heartbeat sweep, Popen polls, orphan reports) only ENQUEUE dead
        # vpids; one worker thread coalesces deaths within
        # plm_loss_epoch_window into a single batched adoption round.
        # Serializing epochs through one worker is also the concurrency
        # fix: overlapping subtree losses can no longer race two
        # _reparent_orphans bodies into double adoptions
        self._loss_cv = threading.Condition()
        self._loss_q: list[int] = []
        self._loss_worker: Optional[threading.Thread] = None
        #: reparent-storm telemetry, asserted by the simfleet tests: one
        #: epoch per correlated loss, frames bounded by
        #: orphans + adopter groups (strictly O(orphans))
        self.reparent_epochs_total = 0
        self.reparent_orphans_total = 0
        self.reparent_frames_total = 0
        # the standing allocation the daemon vpids index into (vpid =
        # pool index + 1) — job.nodes may be a gang-placed SUBSET of
        # these on a multi-tenant DVM, so vpid↔node lookups must never
        # go through job.nodes
        self._pool_nodes: list = []
        # every job with apps launched and not yet retired, keyed by
        # jobid: the exit/IOF/doctor routers resolve payloads here (a
        # multi-tenant DVM runs several at once)
        self._jobs_by_id: dict[int, Job] = {}
        self._persistent = False          # DVM mode: VM outlives jobs
        self._vm_stop = threading.Event()
        self._hb_monitor: Optional[rml.HeartbeatMonitor] = None
        # terminal stage of the metrics uplink: TAG_METRICS deltas from
        # the daemon tree fold in here, keyed by jobid and rank — what
        # the DVM scrape endpoint and --dvm-ps read
        from ompi_tpu.runtime.metrics import MetricsAggregate

        self.metrics_agg = MetricsAggregate()

    # -- state handlers ----------------------------------------------------

    def _st_allocate(self, sm: StateMachine, job: Job) -> JobState:
        ras.allocate(job, want_tpu=self.want_tpu, **self.select_ctx)
        return JobState.MAP

    def _st_map(self, sm: StateMachine, job: Job) -> JobState:
        rmaps.map_job(job, **self.select_ctx)
        return JobState.LAUNCH_APPS

    def _st_launch(self, sm: StateMachine, job: Job) -> Optional[JobState]:
        if not self._vm_up(job):
            return JobState.ABORTED
        self._launch_apps(job)
        return JobState.RUNNING

    def _vm_up(self, job: Job) -> bool:
        """LAUNCH_DAEMONS + VM_READY: spawn one orted per node and wire
        the routed tree.  The VM outlives a single job in DVM mode (≈
        orte-dvm), which is why this phase is separate from app launch."""
        n_daemons = len(job.nodes)
        self._np_hint = job.np
        self._cur_job = job
        self._pool_nodes = list(job.nodes)
        self._n_daemons = n_daemons
        self.rml = rml.RmlNode(0)
        self.rml.register_recv(rml.TAG_REGISTER, self._on_register)
        self.rml.register_recv(rml.TAG_DAEMON_READY, self._on_ready)
        self.rml.register_recv(rml.TAG_IOF, self._on_iof)
        self.rml.register_recv(rml.TAG_PROC_EXIT, self._route_proc_exit)
        self.rml.register_recv(rml.TAG_ORPHANED, self._on_orphaned)
        self.rml.register_recv(rml.TAG_REPARENT_ACK, self._on_reparent_ack)
        self.rml.register_recv(rml.TAG_METRICS,
                               lambda o, p: self.metrics_agg.merge(p))
        # answer the daemons' clock-sync pingpongs: the HNP is the root
        # clock domain, so its offset-to-root is 0 by definition
        clocksync.install_responder(self.rml, lambda: 0)
        self.rml.on_peer_lost = self._on_daemon_lost
        # liveness beats (rml_heartbeat_period > 0): any beat — or any
        # other up-traffic from the daemon — refreshes its clock; silence
        # past rml_heartbeat_timeout is a daemon death the socket never
        # reported (hung host, half-open link)
        self._hb_monitor = rml.HeartbeatMonitor(self._on_daemon_lost)
        self.rml.register_recv(
            rml.TAG_HEARTBEAT,
            lambda o, vpid: self._hb_monitor.beat(vpid))

        self._daemon_popen = self.plm.spawn_daemons(job, self.rml.uri)
        threading.Thread(target=self._daemon_monitor, args=(job,),
                         daemon=True).start()
        timeout = var_registry.get("plm_daemon_timeout")
        with self._cv:
            ok = self._cv.wait_for(
                lambda: (len(self._registered) >= n_daemons
                         or self._lost_daemon is not None), timeout=timeout)
        if not ok or self._lost_daemon is not None:
            job.abort_reason = (
                f"daemon {self._lost_daemon} died during launch"
                if self._lost_daemon is not None else
                f"only {len(self._registered)}/{n_daemons} daemons "
                f"reported within {timeout}s")
            job.aborted_proc = job.procs[0]
            self.kill_job(job)
            return False

        # VM_READY: wire the routed tree (vpid 0 = me, 1..N = daemons).
        # Dial my own children BEFORE sending any WIRE: a daemon replies
        # DAEMON_READY up the tree, so its up-link must exist (orted also
        # gates the reply on wait_parent — belt and suspenders).
        total = n_daemons + 1
        with self._cv:
            self._eff_parent = {v: (rml.tree_parent(v) or 0)
                                for v in range(1, total)}
        uris = {0: self.rml.uri}
        uris.update({v: u for v, (u, _h) in self._registered.items()})
        self.rml.dial_children(
            [(c, uris[c]) for c in rml.tree_children(0, total)])
        # only the policies that survive a daemon death (notify, selfheal)
        # should have orphans wait for adoption instead of applying the
        # lifeline teardown — the flag rides the WIRE payload
        reparent = getattr(self._errmgr, "TOLERATES_DAEMON_LOSS", False)
        for v in range(1, total):
            children = [(c, uris[c]) for c in rml.tree_children(v, total)]
            self.rml.send_direct(self.rml.boot_links[v], rml.TAG_WIRE,
                                 {"children": children,
                                  "reparent": reparent})
        with self._cv:
            ok = self._cv.wait_for(
                lambda: (len(self._ready) >= n_daemons
                         or self._lost_daemon is not None), timeout=timeout)
        if not ok or self._lost_daemon is not None:
            job.abort_reason = (
                f"daemon {self._lost_daemon} died during tree wiring"
                if self._lost_daemon is not None
                else "daemon tree wiring timed out")
            job.aborted_proc = job.procs[0]
            self.kill_job(job)
            return False
        # daemons are wired: arm the liveness watchdog (no-op when
        # rml_heartbeat_period is 0) with its timeout scaled to this
        # world's tree depth — a 9-daemon timeout on a 1000-daemon world
        # declares healthy-but-busy daemons dead during a reparent wave
        self._hb_monitor.set_world(total)
        for vpid in self._registered:
            self._hb_monitor.watch(vpid)
        self._hb_monitor.start()
        if reparent and self._loss_worker is None:
            self._loss_worker = threading.Thread(
                target=self._loss_epoch_worker, name="plm-loss-epoch",
                daemon=True)
            self._loss_worker.start()
        return True

    def _node_vpid(self, node) -> int:
        """The daemon vpid owning a pool node (identity lookup against
        the STANDING allocation — a gang-placed job's job.nodes is a
        subset of the pool in arbitrary least-loaded order, so indexing
        job.nodes would address the wrong daemon)."""
        for i, n in enumerate(self._pool_nodes):
            if n is node:
                return i + 1
        return 0

    def _launch_apps(self, job: Job) -> None:
        """LAUNCH_APPS: fresh pmix rendezvous sized to this job, then one
        xcast with the whole map; daemons pick their rows."""
        self._cur_job = job
        self._np_hint = job.np
        job.exited = {}
        job.killed = False
        server = pmix.PMIxServer(
            size=job.np, host="0.0.0.0",
            on_abort=lambda r, s, m: self._on_abort(job, r, s, m))
        # rank-plane gossip feedback: a reported hung rank is reaped by
        # its owning daemon (TAG_KILL_RANK) so the exit report flows and
        # the errmgr policy runs — without this a SIGSTOP'd pid would
        # stall _wait_ranks forever
        server.on_failed_report = \
            lambda r, reason: self._reap_reported(job, r, reason)
        # uptime clock (errmgr crash-loop governor): starts at each
        # rank's PMIx registration so boot doesn't count toward
        # errmgr_min_uptime_s
        server.on_client_contact = \
            lambda r: self._mark_contact(job, r)
        # per-job rendezvous: concurrent tenants each get their own
        # server/port; self.server mirrors the latest for the non-DVM
        # single-job paths (and custom-launcher compat in errmgr)
        job.pmix_server = server
        self.server = server
        self._jobs_by_id[job.jobid] = job
        app = job.apps[0]
        env = dict(app.env)
        # the xcast env overlays the daemons' os.environ (orted merge
        # order), so the client's own environ counts as an explicit
        # user setting here
        errmgr_mod.apply_host_plane_policy(self._errmgr, env, os.environ)
        env[pmix.ENV_URI] = server.uri.replace("0.0.0.0",
                                               self._my_address())
        env[pmix.ENV_SIZE] = str(job.np)
        env[pmix.ENV_JOBID] = str(job.jobid)
        env.update(self._jax_coord_env(job))
        by_daemon = []
        for node in job.nodes:
            rows = [(p.rank, p.local_rank,
                     None if p.chip is None else str(p.chip))
                    for p in job.procs_on(node)]
            by_daemon.append((self._node_vpid(node), rows))
        stdin_rank = (self.stdin_target if self.stdin_target in ("all",)
                      else None if self.stdin_target == "none"
                      else int(self.stdin_target))
        self.rml.xcast(rml.TAG_LAUNCH, {
            "jobid": job.jobid, "by_daemon": by_daemon, "argv": app.argv,
            "env": env, "cwd": app.cwd, "stdin_rank": stdin_rank})
        for p in job.procs:
            p.state = ProcState.RUNNING
        if stdin_rank is not None:
            self._start_stdin_pump(stdin_rank)

    def _wait_ranks(self, job: Job) -> None:
        """Block until every rank reported (or the VM lost a daemon)."""
        # A lost daemon is a lost lifeline (≈ ORTE aborting the job when an
        # orted dies): its ranks' PROC_EXIT reports are gone forever, so
        # waiting only on rank exits would hang.
        with self._cv:
            self._cv.wait_for(
                lambda: (len(job.exited) >= job.np
                         or self._lost_daemon is not None
                         or self._vm_stop.is_set()),
                )
            lost = self._lost_daemon
        report_wait = var_registry.get("plm_exit_report_timeout")
        if self._vm_stop.is_set() and len(job.exited) < job.np:
            # VM shutdown ordered mid-job (DVM stop): ranks were killed
            # with the daemons; give their exit reports a moment, then
            # account the job as aborted rather than hanging forever
            with self._cv:
                self._cv.wait_for(lambda: len(job.exited) >= job.np,
                                  timeout=report_wait)
            if job.aborted_proc is None and len(job.exited) < job.np:
                job.abort_reason = "VM shut down while the job was running"
                job.aborted_proc = job.procs[0]
            return
        if lost is not None and len(job.exited) < job.np:
            if job.aborted_proc is None:
                job.abort_reason = (
                    f"daemon {lost} (host "
                    f"{self._registered.get(lost, ('?', '?'))[1]}) died "
                    f"before its ranks reported")
                job.aborted_proc = job.procs[0]
            self.kill_job(job)
            # best effort: wait only for ranks whose daemon still lives —
            # the dead daemon's ranks can never report
            lost_node = (self._pool_nodes[lost - 1]
                         if 0 < lost <= len(self._pool_nodes) else None)
            dead = ({p.rank for p in job.procs_on(lost_node)}
                    if lost_node is not None else set())
            alive = [p.rank for p in job.procs if p.rank not in dead]
            with self._cv:
                self._cv.wait_for(
                    lambda: all(r in job.exited for r in alive),
                    timeout=report_wait)

    def _teardown_vm(self) -> None:
        with self._cv:
            self._vm_stop.set()
            self._cv.notify_all()   # wake a _wait_ranks blocked mid-job
        with self._loss_cv:
            self._loss_cv.notify_all()  # release the loss-epoch worker
        if self._hb_monitor is not None:
            self._hb_monitor.stop()
        self.rml.xcast(rml.TAG_SHUTDOWN, None)
        deadline = (time.monotonic()
                    + var_registry.get("plm_daemon_drain_timeout"))
        for p in self._daemon_popen:
            try:
                p.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                p.kill()
        if self.server is not None:
            self.server.close()
        self.rml.close()

    def _st_running(self, sm: StateMachine, job: Job) -> JobState:
        self._wait_ranks(job)
        self._teardown_vm()
        return (JobState.ABORTED if job.aborted_proc is not None
                else JobState.TERMINATED)

    # -- rml handlers ------------------------------------------------------

    def _on_register(self, origin: int, payload) -> None:
        vpid, uri, hostname = payload
        with self._cv:
            self._registered[vpid] = (uri, hostname)
            self._cv.notify_all()

    def _on_ready(self, origin: int, payload) -> None:
        with self._cv:
            self._ready.add(payload)
            self._cv.notify_all()

    def _on_iof(self, origin: int, payload) -> None:
        _jobid, rank, stream, raw = payload
        sink = sys.stdout if stream == "out" else sys.stderr
        line = bytes(raw).decode(errors="replace")
        if var_registry.get("launcher_tag_output"):
            line = f"[mh,{rank}]{line}"
        sink.write(line)
        sink.flush()

    def _route_proc_exit(self, origin: int, payload) -> None:
        """TAG_PROC_EXIT router: resolve the owning job by jobid and feed
        the job-scoped handler.  A report for an already-retired job
        (raced with a jobid-scoped kill) is dropped — its submission has
        been accounted."""
        jobid, rank, rc, errmsg = payload
        with self._cv:
            job = self._jobs_by_id.get(int(jobid)) or self._cur_job
        if job is None or not (0 <= int(rank) < len(job.procs)):
            return
        self._on_proc_exit(job, (int(rank), rc, errmsg))

    def respawn_proc(self, job: Job, proc) -> bool:
        """errmgr/respawn hook for the daemon tree: xcast a revival order;
        the daemon owning the rank relaunches it with OMPI_TPU_RESTART.
        Spawn failure on the daemon surfaces as another TAG_PROC_EXIT
        (exit 127), which re-enters the errmgr until restarts exhaust."""
        from ompi_tpu.runtime import ftevents

        proc.restarts += 1   # budget burn (governor may reset it)
        proc.lives += 1      # identity: monotone, survives budget resets
        # the revival order carries the rank's CURRENT placement: the
        # daemon whose vpid matches `target` adopts the row and spawns
        # (a remediation may have migrated proc.node to a less-loaded
        # host); every other daemon drops any stale row it still holds
        try:
            self.rml.xcast(rml.TAG_RESPAWN, {
                "jobid": job.jobid, "rank": proc.rank, "lives": proc.lives,
                "target": (self._node_vpid(proc.node)
                           if proc.node is not None else 0),
                "local_rank": proc.local_rank,
                "chip": None if proc.chip is None else str(proc.chip)})
        except Exception as e:  # noqa: BLE001 — tree may be tearing down
            _log.error("respawn xcast for rank %d failed: %r", proc.rank, e)
            return False
        ftevents.record("revive", jobid=job.jobid, rank=proc.rank,
                        lives=proc.lives)
        # only a successful revival order flips the state — a failed xcast
        # must leave ABORTED so _on_proc_exit records the exit (the job
        # would otherwise wait forever on a rank nobody revived)
        proc.exit_code = None
        proc.state = ProcState.RUNNING
        proc.launched_at = None  # stamped again at PMIx registration
        server = getattr(job, "pmix_server", None) or self.server
        if server is not None:
            server.proc_revived(proc.rank, proc.lives)
        return True

    def _on_proc_exit(self, job: Job, payload) -> None:
        rank, rc, errmsg = payload
        proc = job.procs[rank]
        proc.exit_code = rc
        server = getattr(job, "pmix_server", None) or self.server
        if proc.state == ProcState.KILLED_BY_CMD:
            pass
        elif rc == 0:
            proc.state = ProcState.TERMINATED
            # a clean finisher's stopped beats are completion, not a
            # hang — gate late gossip reports about it
            if server is not None:
                server.proc_finished(rank)
        else:
            proc.state = (ProcState.FAILED_TO_START if errmsg
                          else ProcState.ABORTED)
            if server is not None:
                server.proc_died(rank)
            self._errmgr.proc_failed(self, job, proc)
            if proc.state == ProcState.RUNNING:
                return  # errmgr revived the rank; its exit is yet to come
        with self._cv:
            job.exited[rank] = rc
            self._cv.notify_all()

    def _on_daemon_lost(self, vpid: int) -> None:
        """A daemon vanished: RML link EOF (crash/SIGKILL/host death),
        heartbeat silence (hung host, half-open link), or an orphan's
        report.  Under a daemon-loss-tolerant errmgr policy (notify,
        selfheal) the daemon's ranks become proc-failure events
        propagated to the survivors, its orphaned tree children re-wire
        to the nearest live ancestor, and the job continues; every other
        policy treats a lost daemon as a lost lifeline and aborts."""
        with self._cv:
            if vpid in self._dead_daemons:
                return  # several detectors race to the same corpse
            self._dead_daemons.add(vpid)
            cur = self._cur_job
            if self._killed or self._vm_stop.is_set() or (
                    not self._persistent and cur is not None
                    and len(cur.exited) >= self._np_hint):
                return  # normal teardown, not a failure
            # a multi-tenant pool may have several jobs with ranks on the
            # dead host — every one of them takes the loss (fall back to
            # the current job so the single-job path behaves as before)
            jobs = ([j for j in self._jobs_by_id.values()
                     if not j.killed] or
                    ([cur] if cur is not None else []))
            job = jobs[0] if jobs else None
            reparent = (getattr(self._errmgr, "TOLERATES_DAEMON_LOSS",
                                False)
                        and job is not None
                        and 0 < vpid <= len(self._pool_nodes))
            if reparent:
                for j in jobs:
                    self._fail_daemon_ranks(j, vpid)
            else:
                if self._lost_daemon is None:
                    self._lost_daemon = vpid
                self._cv.notify_all()
        from ompi_tpu.runtime import ftevents

        ftevents.record("daemon_lost",
                        jobid=(job.jobid if reparent and job else 0),
                        vpid=vpid, contained=bool(reparent))
        if reparent:
            # confine the loss: the dead daemon's live children re-wire
            # to their grandparent instead of applying the lifeline rule.
            # Survivors are busy re-wiring for the next stretch — hold
            # heartbeat declarations so the wave itself cannot cascade
            # into false daemon deaths
            if self._hb_monitor is not None:
                window = float(
                    var_registry.get("plm_loss_epoch_window") or 0)
                self._hb_monitor.grace(1.0 + 2 * window)
            self._enqueue_loss(vpid)
            return
        from ompi_tpu.runtime.notifier import Severity, notify

        notify(Severity.CRITICAL, "daemon-lost",
               f"orted vpid {vpid} vanished (host death/crash); "
               f"aborting the job")

    def _on_orphaned(self, origin: int, payload) -> None:
        """An orphan's bootstrap-link report: its tree parent's link hit
        EOF before any HNP-side detector fired — the fastest daemon-death
        signal there is, so feed it into the same (idempotent) path."""
        orphan, lost_parent = payload
        _log.verbose(1, "orted %d reports parent %d lost", orphan,
                     lost_parent)
        self._on_daemon_lost(int(lost_parent))

    def _enqueue_loss(self, vpid: int) -> None:
        """Hand a detected death to the loss-epoch worker (or, when no
        worker runs — direct unit-test drives of _on_daemon_lost — run a
        one-death epoch inline)."""
        if self._loss_worker is None:
            self._reparent_epoch({int(vpid)})
            return
        with self._loss_cv:
            self._loss_q.append(int(vpid))
            self._loss_cv.notify_all()

    def _loss_epoch_worker(self) -> None:
        """The single thread every adoption round runs on.  Detectors
        enqueue; this worker sleeps ``plm_loss_epoch_window`` after the
        first death of a round so a correlated loss (a rack dying in one
        tick, detected by N racing link EOFs / heartbeat expiries /
        orphan reports) collapses into ONE batched epoch.  The window is
        measured from the first death and is NOT extended by later ones
        — epoch latency stays bounded under a trickling failure."""
        while not self._vm_stop.is_set():
            with self._loss_cv:
                while not self._loss_q and not self._vm_stop.is_set():
                    self._loss_cv.wait(0.5)
                if self._vm_stop.is_set():
                    return
            window = float(var_registry.get("plm_loss_epoch_window") or 0)
            deadline = time.monotonic() + window
            with self._loss_cv:
                while True:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or self._vm_stop.is_set():
                        break
                    self._loss_cv.wait(remaining)
                batch = set(self._loss_q)
                self._loss_q.clear()
            if batch and not self._vm_stop.is_set():
                try:
                    self._reparent_epoch(batch)
                except Exception as e:  # noqa: BLE001 — worker survives
                    _log.error("reparent epoch for %s failed: %r",
                               sorted(batch), e)

    def _reparent_orphans(self, dead_vpid: int) -> None:
        """Compat shim: a single-death adoption round."""
        self._reparent_epoch({int(dead_vpid)})

    def _reparent_epoch(self, new_dead: set[int]) -> None:
        """One batched adoption round for a loss epoch: every live
        daemon whose EFFECTIVE parent is now dead gets exactly one
        TAG_REPARENT naming its new parent (the nearest live ancestor
        along the effective tree), and each adopter gets ONE TAG_ADOPT
        listing all its new children — total frames = orphans + adopter
        groups, O(orphans) regardless of how many daemons died at once.
        Deeper descendants keep their live links; only severed edges are
        rebuilt.  Orphanhood is computed against the effective-parent
        map (updated here on every adoption), so a dead ADOPTER's
        previously adopted children are re-homed and nobody is adopted
        twice — all epochs run serialized on the loss worker."""
        with self._cv:
            dead = set(self._dead_daemons) | set(new_dead)
            registered = dict(self._registered)
            eff = dict(self._eff_parent)
        orphans = sorted(v for v, p in eff.items()
                         if p in dead and v not in dead
                         and v in registered)
        if not orphans:
            return
        if self._hb_monitor is not None:
            # survivors re-wire now: no dead-declarations mid-round
            self._hb_monitor.grace(2.0)

        def live_ancestor(v: int) -> int:
            p = eff.get(v, 0)
            for _hop in range(len(eff) + 1):   # cycle-proof bound
                if p == 0 or p not in dead:
                    return p
                p = eff.get(p, 0)
            return 0

        by_adopter: dict[int, list[tuple[int, str]]] = {}
        frames = 0
        for o in orphans:
            adopter = live_ancestor(o)
            boot = self.rml.boot_links.get(o)
            if boot is None:
                continue
            try:
                self.rml.send_direct(boot, rml.TAG_REPARENT, adopter)
            except OSError as e:
                _log.error("reparent order to orted %d failed: %r", o, e)
                continue
            frames += 1
            by_adopter.setdefault(adopter, []).append(
                (o, registered[o][0]))
        if not by_adopter:
            return
        placed: dict[int, int] = {}   # orphan → adopter, orders sent
        for adopter, adoptees in sorted(by_adopter.items()):
            try:
                if adopter == 0:
                    self.rml.dial_children(adoptees)
                else:
                    aboot = self.rml.boot_links.get(adopter)
                    if aboot is None:
                        continue
                    self.rml.send_direct(aboot, rml.TAG_ADOPT, adoptees)
                    frames += 1
            except OSError as e:
                _log.error("adoption order under %d failed: %r",
                           adopter, e)
                continue
            for o, _u in adoptees:
                placed[o] = adopter
        with self._cv:
            self._eff_parent.update(placed)
        self.reparent_epochs_total += 1
        self.reparent_orphans_total += len(placed)
        self.reparent_frames_total += frames
        ordered = sorted(placed)
        adopters = sorted(by_adopter)
        _log.verbose(0, "re-parenting orteds %s under %s (epoch: vpids "
                     "%s died)", ordered, adopters, sorted(new_dead))
        from ompi_tpu.mpi import trace as trace_mod

        if trace_mod.active:
            trace_mod.instant("errmgr", "reparent", rank=-1,
                              dead_vpid=min(new_dead),
                              dead=sorted(new_dead),
                              adopter=adopters[0], orphans=ordered)
        from ompi_tpu.runtime import ftevents
        from ompi_tpu.runtime.notifier import Severity, notify

        ftevents.record(
            "reparent",
            jobid=(self._cur_job.jobid if self._cur_job else 0),
            vpid=min(new_dead), dead=sorted(new_dead),
            adopter=adopters[0], adopters=adopters,
            orphans=ordered, frames=frames)
        notify(Severity.WARN, "daemon-reparent",
               f"orted vpid(s) {sorted(new_dead)} died mid-tree; orphans "
               f"{ordered} re-parented under vpid(s) {adopters} in one "
               f"batched round ({frames} frames; loss confined)")

    def _on_reparent_ack(self, origin: int, payload) -> None:
        vpid, new_parent = payload
        _log.verbose(1, "orted %d re-wired under %d", vpid, new_parent)

    def _mark_contact(self, job: Job, rank: int) -> None:
        """PMIx server hook: the rank's current life registered — start
        its uptime clock (errmgr_min_uptime_s measures from here)."""
        if job is not None and 0 <= rank < len(job.procs):
            job.procs[rank].launched_at = time.monotonic()

    def _reap_reported(self, job: Job, rank: int, reason: str) -> None:
        """Order the owning daemon to SIGKILL one reported-hung rank."""
        from ompi_tpu.runtime import ftevents

        _log.verbose(1, "reaping reported-dead rank %d via the tree: %s",
                     rank, reason or "gossip-declared")
        ftevents.record(
            "reap", jobid=job.jobid,
            rank=rank, reason=reason or "gossip-declared")
        try:
            self.rml.xcast(rml.TAG_KILL_RANK, (job.jobid, rank))
        except Exception as e:  # noqa: BLE001 — tree may be tearing down
            _log.error("kill-rank xcast for %d failed: %r", rank, e)

    def _fail_daemon_ranks(self, job: Job, vpid: int) -> None:
        """With self._cv held: a dead daemon's ranks can never report —
        declare each of them failed NOW (the errmgr policy propagates
        each death to the survivors) and record synthetic exits so
        _wait_ranks completes on the survivors alone."""
        node = self._pool_nodes[vpid - 1]
        victims = [p for p in job.procs_on(node)
                   if p.rank not in job.exited]
        server = getattr(job, "pmix_server", None) or self.server
        for proc in victims:
            proc.state = ProcState.ABORTED
            proc.exit_code = -9
            # no revival order can reach a rank whose daemon died with
            # its host — a reviving policy (selfheal) must degrade to
            # its shrink rung instead of marking the rank RUNNING and
            # waiting forever on an exit that cannot come
            proc.daemon_lost = True
            if server is not None:
                server.proc_died(
                    proc.rank,
                    reason=f"daemon vpid {vpid} (host {node.name}) died")
            job.exited[proc.rank] = -9
        self._cv.notify_all()
        # notify's and selfheal's daemon-lost arms are non-blocking (an
        # xcast + a log line, no revive attempt) and take no plm locks,
        # so running them with self._cv held is safe — and the synthetic
        # exits above are already visible.  Policies exposing the batched
        # arm get the whole victim set in ONE call (one propagation
        # xcast per dead daemon instead of one per dead rank)
        if not victims:
            return
        batch = getattr(self._errmgr, "daemon_ranks_failed", None)
        if batch is not None:
            batch(self, job, victims)
        else:
            for proc in victims:
                self._errmgr.proc_failed(self, job, proc)

    def _daemon_monitor(self, job: Job) -> None:
        """Poll orted Popen handles: a dead daemon before job end = abort
        (first loss ends the watch — the job is coming down anyway) —
        EXCEPT under the notify policy, where the job continues and the
        monitor must keep watching for further daemon deaths: a
        non-HNP-child daemon's link EOF lands at its tree parent, not
        here, so Popen polling is the only detector the HNP always has.
        In DVM mode the monitor runs for the VM's lifetime."""
        handled: set[int] = set()
        notify = getattr(self._errmgr, "TOLERATES_DAEMON_LOSS", False)
        while True:
            if self._vm_stop.is_set():
                return
            with self._cv:
                # _killed is job-scoped on a persistent VM (reset per
                # submission): the monitor must outlive an aborted job
                if (not self._persistent
                        and (self._killed or len(job.exited) >= job.np)):
                    return
            for i, p in enumerate(self._daemon_popen):
                if i + 1 in handled:
                    continue
                if p.poll() is not None:
                    handled.add(i + 1)
                    self._on_daemon_lost(i + 1)
                    if not notify:
                        return
            time.sleep(0.25)

    def _on_abort(self, job: Job, rank: int, status: int, msg: str) -> None:
        proc = job.procs[rank]
        if job.aborted_proc is None:
            job.aborted_proc = proc
            job.abort_reason = f"rank {rank} called abort: {msg}"
            job.abort_status = status
        self.kill_job(job)

    # -- control -----------------------------------------------------------

    def kill_job(self, job: Job, exclude: Optional[Proc] = None) -> None:
        """errmgr entry point: xcast a jobid-scoped kill; the daemons
        SIGTERM/SIGKILL that job's ranks and drop its state — co-tenants
        on the same pool are untouched."""
        if job.killed or self.rml is None:
            return
        job.killed = True
        if not self._persistent:
            # single-job launch: the job dying means the VM is coming
            # down — keep the launcher-global latch for the monitor and
            # the daemon-loss teardown checks
            self._killed = True
        for p in job.procs:
            if p.state == ProcState.RUNNING and p is not exclude:
                p.state = ProcState.KILLED_BY_CMD
        self.rml.xcast(rml.TAG_KILL, job.jobid)

    def _start_stdin_pump(self, target) -> None:
        """IOF stdin forwarding (≈ iof.h:27-43; default target rank 0)."""
        def pump() -> None:
            try:
                stdin = sys.stdin.buffer
            except AttributeError:
                stdin = None  # stdin replaced (pytest capture)
            try:
                if stdin is None:
                    raise OSError
                while True:
                    chunk = stdin.read1(1 << 16)
                    if not chunk:
                        break
                    self.rml.xcast(rml.TAG_STDIN, (target, chunk))
            except (OSError, ValueError):
                pass
            try:
                self.rml.xcast(rml.TAG_STDIN, (target, None))  # EOF
            except Exception:
                pass

        threading.Thread(target=pump, daemon=True).start()

    def _my_address(self) -> str:
        """An address remote hosts can dial (sim: loopback is fine)."""
        if self.plm.NAME == "sim":
            return "127.0.0.1"
        import socket as _s

        try:
            probe = _s.socket(_s.AF_INET, _s.SOCK_DGRAM)
            probe.connect(("8.8.8.8", 80))
            addr = probe.getsockname()[0]
            probe.close()
            return addr
        except OSError:
            return _s.gethostbyname(_s.gethostname())

    def _jax_coord_env(self, job: Job) -> dict[str, str]:
        """jax.distributed coordination: rank 0's host runs the coordinator
        on a port the HNP picks; every rank learns (coord, nprocs, my id)
        and multihost.initialize_from_env() does the rest."""
        import socket as _s

        if self.plm.NAME == "sim":
            # coordinator binds on this host: a real free-port probe works
            with _s.socket() as s:
                s.bind(("", 0))
                port = s.getsockname()[1]
            host0 = "127.0.0.1"
        else:
            # the coordinator binds on rank 0's (remote) host, which the
            # HNP cannot probe — derive a port from the jobid in the
            # dynamic range to make collisions unlikely (the reference's
            # oob/tcp static-port story has the same limitation)
            port = 49152 + (job.jobid * 211 + os.getpid()) % 16000
            host0 = job.procs[0].node.name
        return {"OMPI_TPU_COORD": f"{host0}:{port}",
                "OMPI_TPU_NHOSTS": str(len(job.nodes))}

    # -- entry -------------------------------------------------------------

    def run(self, job: Job) -> int:
        self.sm.run_to_completion(job, JobState.INIT)
        if job.aborted_proc is not None:
            output.show_help("launcher", "job-aborted",
                             jobid=job.jobid,
                             reason=job.abort_reason or "unknown")
            if job.abort_status is not None:
                return job.abort_status or 1
            rc = job.aborted_proc.exit_code or 1
            return 128 - rc if rc < 0 else rc
        return 0
