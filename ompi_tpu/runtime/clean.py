"""Stale-artifact cleanup — the orte-clean equivalent.

≈ orte/tools/orte-clean (orte-clean.c): crashed or killed jobs leave
debris behind — here that is shared-memory inbox directories and ring
files (btl/shm), shared-file-pointer and shared-window segments
(sharedfp/sm, osc SharedWindow), orphaned ``.seg-*`` temp files from
interrupted segment creation, and a dead DVM's uri file.  ``tpurun
--clean`` sweeps everything owned by the current user whose owning
process is provably gone (or, with ``age>0``, anything older than the
given seconds — the reference's "no jobs of mine are running" big
hammer).

Liveness: an inbox dir name carries no pid, but the doorbell FIFO inside
it has an OPEN reader exactly while its rank lives — a zero-reader FIFO
(nonblocking write raises ENXIO) marks the whole inbox dead.  Segment
files have no such signal and fall back to the age threshold.
"""

from __future__ import annotations

import errno
import os
import shutil
import tempfile
import time
from typing import Optional

from ompi_tpu.core import output

__all__ = ["clean", "scan"]

_log = output.get_stream("clean")

#: /dev/shm (or TMPDIR) name prefixes this framework creates
_PREFIXES = ("otpu-shm-", "otpu-shfp-", "otpu-shwin-", ".seg-")


def _dirs() -> list[str]:
    out = []
    if os.path.isdir("/dev/shm"):
        out.append("/dev/shm")
    td = tempfile.gettempdir()
    if td not in out:
        out.append(td)
    return out


def _inbox_alive(path: str) -> bool:
    """A live btl/shm inbox has its owning rank blocked on (or at least
    holding) the doorbell FIFO's read end; opening the write end
    nonblocking fails with ENXIO when no reader exists."""
    db = os.path.join(path, "doorbell")
    try:
        fd = os.open(db, os.O_WRONLY | os.O_NONBLOCK)
    except OSError as e:
        return e.errno != errno.ENXIO   # ENOENT/EACCES: can't prove dead
    os.close(fd)
    return True


def _mapped_paths() -> Optional[set]:
    """ONE pass over /proc/*/maps collecting every mapped path that
    carries our prefixes — the precise liveness signal for mmap-backed
    artifacts (their mtime never advances after creation, so age alone
    would hit live windows).  None ⇒ procfs unreadable: prove nothing,
    keep everything."""
    try:
        pids = [n for n in os.listdir("/proc") if n.isdigit()]
    except OSError:
        return None
    mapped: set = set()
    for pid in pids:
        try:
            with open(f"/proc/{pid}/maps", encoding="utf-8",
                      errors="replace") as f:
                for line in f:
                    if "otpu-" not in line and "/.seg-" not in line:
                        continue
                    # path starts at the 6th field; spaces in our names
                    # never occur (prefix + hex)
                    idx = line.find("/")
                    if idx >= 0:
                        mapped.add(line[idx:].rstrip("\n")
                                   .removesuffix(" (deleted)"))
        except OSError:
            continue   # other-uid / vanished process
    return mapped


def _dead_dvm_uri() -> Optional[str]:
    """Path of a uri file whose LOCAL HNP provably refused the
    connection (port closed ⇒ dead), else None.  An unreachable or slow
    HNP is NOT death — sweeping it would orphan a live daemon tree."""
    import socket

    from ompi_tpu.runtime import dvm as dvm_mod

    uri_path = dvm_mod.default_uri_path()
    if not os.path.exists(uri_path):
        return None
    try:
        with open(uri_path, encoding="utf-8") as f:
            target = f.read().strip()
        host, port = target.rsplit(":", 1)
    except (OSError, ValueError):
        return uri_path   # unreadable/garbled uri file IS debris
    if host not in ("127.0.0.1", "localhost", "::1",
                    os.uname().nodename):
        return None       # cannot judge a remote HNP from here
    try:
        conn = socket.create_connection((host, int(port)), timeout=2)
        conn.close()
        return None       # something listens: leave it alone
    except ConnectionRefusedError:
        return uri_path   # positive death: nothing on the port
    except OSError:
        return None       # timeout/route problems prove nothing


def scan(age: float = 0.0) -> list[tuple[str, str]]:
    """→ [(path, reason)] of artifacts that WOULD be removed."""
    me = os.getuid()
    now = time.time()
    victims: list[tuple[str, str]] = []
    mapped: Optional[set] = ()   # lazily computed on first segment
    for base in _dirs():
        try:
            names = os.listdir(base)
        except OSError:
            continue
        for name in names:
            if not any(name.startswith(p) for p in _PREFIXES):
                continue
            path = os.path.join(base, name)
            try:
                st = os.lstat(path)
            except OSError:
                continue
            if st.st_uid != me:
                continue            # never touch other users' jobs
            if age > 0:
                if now - st.st_mtime > age:
                    victims.append((path, f"older than {age:.0f}s"))
                continue
            if name.startswith("otpu-shm-") and os.path.isdir(path):
                if not _inbox_alive(path):
                    victims.append((path, "no doorbell reader (rank gone)"))
            else:
                # mmap-backed segments: mtime never advances after
                # creation, so "old" ≠ "idle" — only sweep when no live
                # process maps the file (plus a short grace for the
                # create→mmap window).  The /proc sweep runs ONCE for
                # the whole scan, not per candidate.
                if now - st.st_mtime <= 60:
                    continue
                if mapped == ():
                    mapped = _mapped_paths()
                if mapped is not None and path not in mapped:
                    victims.append((path, "segment mapped by no process"))
    dead_uri = _dead_dvm_uri()
    if dead_uri is not None:
        victims.append((dead_uri, "DVM uri, local port refused"))
    return victims


def clean(age: float = 0.0, dry_run: bool = False,
          report=None) -> list[str]:
    """Remove stale artifacts; returns the removed paths.

    ``age``: 0 = liveness-based (safe while jobs run); >0 = also remove
    anything older than this many seconds (use when no jobs are active,
    the orte-clean stance).  ``report``: callable(line) for progress
    (tpurun passes print).  ``dry_run``: returns the would-remove paths
    without touching anything.
    """
    removed = []
    failed = []
    for path, reason in scan(age):
        if report:
            report(f"{'would remove' if dry_run else 'removing'} "
                   f"{path}  ({reason})")
        if dry_run:
            removed.append(path)
            continue
        try:
            if os.path.isdir(path):
                shutil.rmtree(path)   # errors surface: a path we could
            else:                     # not remove must not be reported
                os.unlink(path)       # as cleaned
            removed.append(path)
        except OSError as e:
            failed.append(path)
            msg = f"could NOT remove {path}: {e}"
            if report:
                report(msg)           # visible, not verbose-only: the
            _log.error("clean: %s", msg)   # caller believes it cleaned
    if failed and not dry_run:
        raise OSError(f"{len(failed)} artifact(s) could not be removed "
                      f"(removed {len(removed)}): {failed[:3]}")
    return removed
