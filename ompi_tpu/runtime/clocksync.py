"""Clock sync — measured per-daemon monotonic offsets over the OOB tree.

≈ the reference's lack of one, and MPI Advance's point that measurement
has to come first: every host has its own CLOCK_MONOTONIC origin (boot
time), so merging per-rank trace dumps by raw timestamps scrambles
cross-host ordering by seconds to days.  The fix is the classic
NTP-style pingpong, run over the RML tree's existing edges:

- Each orted periodically sends ``TAG_CLOCK (vpid, seq, t0)`` one hop
  toward the root; the receiving hop stamps its own clock and answers
  straight back down that edge with ``TAG_CLOCK_REPLY (seq, t0,
  t_parent, parent_off_root)``.
- The child stamps ``t3`` on delivery and feeds the triple to a
  min-RTT midpoint estimator: ``offset = t_parent - (t0 + t3)/2`` is
  exact when the two legs are symmetric, and the error is bounded by
  ``rtt/2`` — so keeping the minimum-RTT sample in a sliding window
  both bounds the error and tracks drift (old samples age out).
- Offsets COMPOSE down the tree: the reply echoes the responder's own
  offset-to-root (0 at the HNP), so ``off_root(child) = off_to_parent
  + off_root(parent)`` without any global exchange.  Ranks share their
  host daemon's kernel clock, so a daemon's offset is its ranks'.

The estimator is pure (no sockets, no threads) so tests drive it with
synthetic clocks; :class:`ClockProber` owns the probe loop and the
reply handler; :func:`install_responder` is the three-line server side
any node (orted or HNP) installs.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from typing import Any, Callable, Optional

from ompi_tpu.core import output
from ompi_tpu.core.config import VarType, register_var, var_registry
from ompi_tpu.runtime import rml

__all__ = ["OffsetEstimator", "ClockProber", "install_responder"]

_log = output.get_stream("clocksync")

register_var("clock", "sync_period", VarType.DOUBLE, 1.0,
             "seconds between clock-sync pingpongs up the RML tree "
             "(0 = disabled; trace merges then fall back to per-rank "
             "wall-clock anchors)")
register_var("clock", "sync_window", VarType.INT, 16,
             "sliding window of pingpong samples the min-RTT offset "
             "estimator keeps (drift tracking: old samples age out)")


class OffsetEstimator:
    """Min-RTT midpoint offset estimator for ONE edge.

    ``observe(t0, t_peer, t3)`` takes the local send stamp, the peer's
    reply stamp, and the local delivery stamp (all ns).  The reported
    offset is peer_clock - local_clock — ADD it to a local monotonic
    timestamp to express it on the peer's clock.  Error is bounded by
    half the retained sample's RTT (asymmetry can use at most the
    whole of one leg).
    """

    def __init__(self, window: int = 16) -> None:
        self._samples: deque[tuple[int, int]] = deque(maxlen=max(1, window))
        self._n = 0

    def observe(self, t0_ns: int, t_peer_ns: int, t3_ns: int) -> None:
        rtt = t3_ns - t0_ns
        if rtt < 0:
            return   # reordered/stale delivery: not a usable sample
        self._samples.append((rtt, t_peer_ns - (t0_ns + t3_ns) // 2))
        self._n += 1

    def reset(self) -> None:
        """Forget everything (the peer changed: offsets don't mix)."""
        self._samples.clear()

    def offset_ns(self) -> Optional[int]:
        """Offset of the min-RTT sample in the window, or None."""
        if not self._samples:
            return None
        return min(self._samples)[1]

    def rtt_ns(self) -> Optional[int]:
        """RTT of the best sample — 2x the worst-case offset error."""
        if not self._samples:
            return None
        return min(self._samples)[0]

    def sample_count(self) -> int:
        """Samples observed over the estimator's lifetime."""
        return self._n


def install_responder(node: rml.RmlNode,
                      off_root_fn: Callable[[], Optional[int]]) -> None:
    """Answer TAG_CLOCK probes on ``node``: stamp-and-reply down the
    probed edge.  ``off_root_fn`` supplies this node's own
    offset-to-root (0 at the HNP, the prober's composed estimate on a
    mid-tree daemon, None while unknown).  Runs on the link reader
    thread by design — handing off to a worker would add scheduler
    jitter between delivery and the t_parent stamp; the reply itself
    is a tiny fire-and-forget send."""

    def _on_clock(origin: int, payload: Any) -> None:
        t_here = time.monotonic_ns()   # stamp FIRST: jitter below only
        # inflates the prober's RTT, never skews the midpoint
        vpid, seq, t0_ns = payload
        if not node.send_child(   # lint: reader-ok
                vpid, rml.TAG_CLOCK_REPLY,
                (seq, t0_ns, t_here, off_root_fn())):
            _log.verbose(2, "clocksync %d: no link to prober %d",
                         node.vpid, vpid)

    node.register_recv(rml.TAG_CLOCK, _on_clock)


class ClockProber:
    """Daemon-side probe loop: pingpong the parent edge, compose the
    offset-to-root, hand the answer to /status and the metrics plane."""

    def __init__(self, node: rml.RmlNode,
                 period: Optional[float] = None) -> None:
        self.node = node
        if period is None:
            period = float(var_registry.get("clock_sync_period") or 0)
        self.period = period
        window = int(var_registry.get("clock_sync_window") or 16)
        self.est = OffsetEstimator(window)
        self._responder: Optional[int] = None
        self._parent_off_root: Optional[int] = None
        self._seq = itertools.count(1)
        self._pending: dict[int, int] = {}   # seq → t0 (lossy, pruned)
        self._last_reply_mono = 0.0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        node.register_recv(rml.TAG_CLOCK_REPLY, self._on_reply)

    # -- probe side -------------------------------------------------------

    def start(self) -> None:
        if self.period <= 0 or self.node.vpid == 0 \
                or self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name=f"clocksync-{self.node.vpid}",
            daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _run(self) -> None:
        # a short opening burst fills the window fast (the first trace
        # capture should not wait a full minute for 16 samples)
        for _ in range(4):
            if self._stop.wait(0.05):
                return
            self.probe_once()
        while not self._stop.wait(self.period):
            self.probe_once()

    def probe_once(self) -> None:
        """Send one probe up the tree (lossy: no retry, the next round
        re-probes; stale pendings are pruned on a small bound)."""
        seq = next(self._seq)
        with self._lock:
            if len(self._pending) > 64:
                for k in sorted(self._pending)[:32]:
                    del self._pending[k]
            t0 = time.monotonic_ns()
            self._pending[seq] = t0
        try:
            self.node.send_hop(rml.TAG_CLOCK, (self.node.vpid, seq, t0))
        except (ConnectionError, OSError):
            pass   # orphaned window: the next round retries

    def _on_reply(self, origin: int, payload: Any) -> None:
        t3 = time.monotonic_ns()   # stamp before any bookkeeping
        seq, t0_ns, t_peer_ns, peer_off_root = payload
        with self._lock:
            sent_t0 = self._pending.pop(seq, None)
            if sent_t0 is None or sent_t0 != t0_ns:
                return   # duplicate or stale reply
            if origin != self._responder:
                # re-parented (or fallback answered): samples against a
                # different clock must not mix into the min-RTT window
                self._responder = origin
                self.est.reset()
                self._parent_off_root = None
            self.est.observe(t0_ns, t_peer_ns, t3)
            if peer_off_root is not None:
                self._parent_off_root = int(peer_off_root)
            self._last_reply_mono = time.monotonic()

    # -- answers ----------------------------------------------------------

    def offset_to_root_ns(self) -> Optional[int]:
        """This daemon's composed monotonic offset to vpid 0 (add to a
        local monotonic ns to express it on the root's clock), or None
        until both the edge estimate and the parent's own composition
        exist.  The HNP is its own root: always 0."""
        if self.node.vpid == 0:
            return 0
        with self._lock:
            edge = self.est.offset_ns()
            if edge is None or self._parent_off_root is None:
                return None
            return edge + self._parent_off_root

    def stats(self) -> dict[str, Any]:
        """The /status block: edge estimate, composed offset, quality."""
        with self._lock:
            edge = self.est.offset_ns()
            rtt = self.est.rtt_ns()
            n = self.est.sample_count()
            responder = self._responder
            por = self._parent_off_root
            age = (time.monotonic() - self._last_reply_mono
                   if self._last_reply_mono else None)
        off_root = 0 if self.node.vpid == 0 else (
            None if edge is None or por is None else edge + por)
        return {"offset_to_root_ns": off_root, "edge_offset_ns": edge,
                "rtt_ns": rtt, "samples": n, "responder": responder,
                "reply_age_s": age}
