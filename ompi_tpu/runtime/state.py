"""Event-driven job state machine — the launch DAG as data.

The reference's defining runtime idea (orte/mca/state/state.h;
orte/mca/state/hnp/state_hnp.c:74-112): each job state maps to a callback;
``activate(job, state)`` enqueues an event; handlers run on the event loop and
activate the next state.  Errors activate error states handled by the errmgr.

Here the machine is synchronous-by-default (``run_to_completion``) with an
optional queue-driven mode; the *table of (state → handler)* is still data, so
launch flows are introspectable and components (tests, errmgr) can splice in
handlers — the property the reference gets from its state framework.
"""

from __future__ import annotations

import collections
import threading
from typing import Callable, Optional

from ompi_tpu.core import output
from ompi_tpu.runtime.job import Job, JobState

__all__ = ["StateMachine", "StateMachineError"]

_log = output.get_stream("state")

Handler = Callable[["StateMachine", Job], Optional[JobState]]


class StateMachineError(RuntimeError):
    pass


class StateMachine:
    """A per-job state machine with a data-driven transition table.

    Handlers return the next state to activate (or None to pause, e.g. while
    waiting for external events such as child exits; external code then calls
    ``activate``).
    """

    def __init__(self) -> None:
        self._table: dict[JobState, Handler] = {}
        self._queue: collections.deque[tuple[Job, JobState]] = collections.deque()
        self._lock = threading.Lock()
        self._trace: list[JobState] = []

    # -- table management (≈ orte_state.add_job_state) -------------------

    def add_state(self, state: JobState, handler: Handler) -> None:
        self._table[state] = handler

    def remove_state(self, state: JobState) -> None:
        self._table.pop(state, None)

    def states(self) -> dict[JobState, Handler]:
        return dict(self._table)

    @property
    def trace(self) -> list[JobState]:
        """States activated so far (for tests and diagnostics)."""
        return list(self._trace)

    # -- activation ------------------------------------------------------

    def activate(self, job: Job, state: JobState) -> None:
        with self._lock:
            self._queue.append((job, state))

    def run_pending(self) -> bool:
        """Process queued activations until quiescent. Returns True if any ran."""
        ran = False
        while True:
            with self._lock:
                if not self._queue:
                    return ran
                job, state = self._queue.popleft()
            ran = True
            self._dispatch(job, state)

    def _dispatch(self, job: Job, state: JobState) -> None:
        handler = self._table.get(state)
        self._trace.append(state)
        job.state = state
        _log.verbose(1, "job %d: activating state %s", job.jobid, state.value)
        if handler is None:
            if state in (JobState.TERMINATED, JobState.ABORTED):
                return  # terminal states need no handler by default
            raise StateMachineError(f"no handler for state {state.value}")
        nxt = handler(self, job)
        if nxt is not None:
            self.activate(job, nxt)

    def run_to_completion(self, job: Job, start: JobState = JobState.INIT) -> Job:
        """Drive the job from ``start`` until the queue drains."""
        self.activate(job, start)
        self.run_pending()
        return job
