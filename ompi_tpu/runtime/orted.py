"""orted — the per-host runtime daemon.

≈ orte/orted/orted_main.c:223: launched by the plm on every host of the
job, it phones home to the HNP, joins the routed tree, and runs the local
half of the runtime: fork/exec of its ranks (odls), IOF up-forwarding,
stdin down-delivery, exit reporting, and kill-on-command.

Run as ``python -m ompi_tpu.runtime.orted --hnp <uri> --vpid <n> ...``.
``--fake-host`` gives the daemon a simulated host identity (exported as
``OMPI_TPU_FAKE_HOST``) so multi-host paths are testable on one machine —
the process-level analog of ras/simulator's fake nodes.
"""

from __future__ import annotations

import argparse
import os
import queue
import signal
import subprocess
import sys
import threading
import time
from typing import Optional

from ompi_tpu.core import output
from ompi_tpu.runtime import pmix, rml

_log = output.get_stream("orted")


class _StdinWriter:
    """Per-rank stdin pump: a bounded queue + writer thread, so blocking
    pipe writes (rank not draining stdin) never stall an RML reader."""

    def __init__(self, rank: int, pipe) -> None:
        self.rank = rank
        self._q: queue.Queue = queue.Queue(maxsize=64)
        self._eof = threading.Event()  # survives a full queue: EOF is a
        self._t = threading.Thread(target=self._run, args=(pipe,),
                                   daemon=True)
        self._t.start()

    def feed(self, chunk: Optional[bytes]) -> None:
        if chunk is None:
            # the close sentinel must NEVER be lost (a rank blocked in
            # read() would wait for EOF forever) — it rides a flag the
            # writer checks between chunks, not a droppable queue slot
            self._eof.set()
            try:
                self._q.put_nowait(b"")   # wake the writer if it is idle
            except queue.Full:
                pass                      # writer is busy; it checks _eof
            return
        try:
            self._q.put(chunk, timeout=1.0)
        except queue.Full:
            _log.error("stdin to rank %d backed up; dropping %d bytes",
                       self.rank, len(chunk))

    def _run(self, pipe) -> None:
        while True:
            try:
                chunk = self._q.get(timeout=0.5)
            except queue.Empty:
                chunk = b""
            try:
                if chunk:
                    pipe.write(chunk)
                    pipe.flush()
                if self._eof.is_set() and self._q.empty():
                    pipe.close()
                    return
            except (BrokenPipeError, ValueError, OSError):
                return


class _LocalJob:
    """One tenant's local state on this daemon: the launch spec, the
    rows this daemon owns (rank → (local_rank, chip)), and the live
    Popen/stdin handles.  A multi-tenant DVM runs several jobs at once,
    so everything that used to be daemon-global lives here, keyed by
    jobid.  The full spec is stored on EVERY daemon (the launch xcast
    carries the whole map), which is what lets a TAG_RESPAWN retarget a
    rank to a daemon that never owned it (migration on revive)."""

    def __init__(self, jobid: int, spec: dict) -> None:
        self.jobid = jobid
        self.spec = spec
        self.rows: dict[int, tuple[int, Optional[str]]] = {}
        self.popen: dict[int, subprocess.Popen] = {}
        self.stdin_writers: dict[int, _StdinWriter] = {}


class Orted:
    def __init__(self, hnp_uri: str, vpid: int, ndaemons: int,
                 fake_host: Optional[str] = None) -> None:
        self.vpid = vpid
        self.ndaemons = ndaemons
        self.fake_host = fake_host
        self.hostname = fake_host or os.uname().nodename
        self.node = rml.RmlNode(vpid)
        self._jobs: dict[int, _LocalJob] = {}
        self._launched = False
        self._pending_stdin: list = []  # stdin xcasts that beat the launch
        self._lock = threading.Lock()
        self._done = threading.Event()
        self._wired = threading.Event()
        # re-parenting: armed by the WIRE payload when the errmgr policy
        # tolerates daemon loss (notify) — a lost tree parent then opens
        # a bounded adoption window instead of the lifeline teardown
        self._reparent_ok = False
        self._reparented = threading.Event()
        self.node.register_recv(rml.TAG_WIRE, self._on_wire)
        self.node.register_recv(rml.TAG_LAUNCH, self._on_launch)
        self.node.register_recv(rml.TAG_KILL, self._on_kill)
        self.node.register_recv(rml.TAG_STDIN, self._on_stdin)
        self.node.register_recv(rml.TAG_RESPAWN, self._on_respawn)
        self.node.register_recv(rml.TAG_STATS, self._on_stats)
        self.node.register_recv(rml.TAG_DOCTOR, self._on_doctor)
        self.node.register_recv(rml.TAG_PROC_FAILED, self._on_proc_failed)
        self.node.register_recv(rml.TAG_REPARENT, self._on_reparent)
        self.node.register_recv(rml.TAG_ADOPT, self._on_adopt)
        self.node.register_recv(rml.TAG_KILL_RANK, self._on_kill_rank)
        self.node.register_recv(rml.TAG_SIGNAL_RANK, self._on_signal_rank)
        self.node.register_recv(rml.TAG_TIMELINE, self._on_timeline)
        # measured clock sync: pingpong my parent edge, compose the
        # offset to the root, and answer my own children's probes with
        # it (offsets compose down the tree; ranks share my kernel
        # clock, so my offset is theirs)
        from ompi_tpu.runtime import clocksync

        self._clock = clocksync.ClockProber(self.node)
        clocksync.install_responder(self.node,
                                    self._clock.offset_to_root_ns)
        # metrics uplink: when trace_metrics_push_period > 0 this daemon
        # runs a UDP collector its local ranks push pvar snapshots to
        # (the URI is exported into every rank's env), merges them with
        # child daemons' TAG_METRICS payloads, and forwards one combined
        # delta per period ONE hop up — per-level aggregation, exactly
        # the HiCCL per-level-visibility argument applied to metrics
        self._metrics = None
        from ompi_tpu.mpi import trace as trace_mod

        period = trace_mod.push_period()
        if period > 0:
            from ompi_tpu.runtime.metrics import MetricsCollector

            self._metrics = MetricsCollector(
                period, lambda payload: self.node.send_hop(
                    rml.TAG_METRICS, payload))
            self.node.register_recv(
                rml.TAG_METRICS,
                lambda o, p: self._metrics.on_child_payload(p))
            # the measured offset rides the existing uplink: every rank
            # row this daemon forwards carries its host's composed
            # clock offset (None until the pingpong window fills —
            # drain() drops None values)
            self._metrics.extra_values_fn = lambda: {
                "rank_clock_to_root_ns":
                    self._clock.offset_to_root_ns()}
        self.node.register_recv(rml.TAG_SHUTDOWN,
                                lambda o, p: self._done.set())
        # lifeline: if the HNP or my tree parent vanishes, my ranks'
        # reports have nowhere to go — kill them and die rather than leak
        # (≈ orted treating a lost lifeline as job abort, orted_main.c)
        self.node.on_peer_lost = self._on_lifeline_lost
        self._boot = self.node.dial_bootstrap(hnp_uri)
        # while orphaned (tree parent dead, adoption pending) up-traffic
        # — exit reports, heartbeats — rides the bootstrap link instead
        self.node.fallback_up = self._boot
        self.node.send_direct(self._boot, rml.TAG_REGISTER,
                              (vpid, self.node.uri, self.hostname))
        # liveness beats toward the HNP (no-op when the period var is 0);
        # beats start only once the tree up-link exists
        threading.Thread(target=self._start_heartbeats, daemon=True).start()
        # deterministic chaos: a fault plan naming this daemon arms a
        # self-SIGKILL (the injected 'host death' the heartbeat detector
        # and notify policy exist to survive)
        from ompi_tpu.testing import faultinject

        faultinject.arm_daemon(vpid)

    def _start_heartbeats(self) -> None:
        if self.node.wait_parent(timeout=60.0) or self.vpid == 0:
            rml.start_heartbeats(self.node, self._done)
            self._clock.start()   # probes need the up-link to exist

    def _on_proc_failed(self, origin: int, payload) -> None:
        """errmgr notify propagation: a rank somewhere in the job died and
        the job is continuing — log it so every host's record shows which
        peer vanished (app ranks learn through the PMIx dead-set).  The
        rank slot carries a LIST for a batched correlated-daemon-loss
        propagation (one xcast for a whole rack's worth of ranks)."""
        rank, reason = payload
        ranks = list(rank) if isinstance(rank, (list, tuple)) else [rank]
        _log.verbose(1, "orted %d: peer rank(s) %s failed (%s); job "
                     "continues", self.vpid, ranks, reason)

    # -- tree wiring -------------------------------------------------------

    def _on_wire(self, origin: int, payload) -> None:
        if isinstance(payload, dict):
            children = payload["children"]   # [(vpid, uri), ...]
            self._reparent_ok = bool(payload.get("reparent"))
        else:
            children = payload  # legacy list form
        try:
            self.node.dial_children([tuple(c) for c in children])
        except OSError as e:
            _log.error("orted %d: wiring children failed: %r", self.vpid, e)
            os._exit(1)
        # WIRE arrives on the bootstrap link, but DAEMON_READY rides the
        # tree — the parent's dial may still be in flight.  Gate the reply
        # on the up-link actually existing (this runs on the bootstrap
        # reader thread; the parent's hello arrives on its own thread).
        if not self.node.wait_parent(timeout=30.0):
            _log.error("orted %d: parent never dialed in", self.vpid)
            os._exit(1)
        self._wired.set()
        self.node.send_up(rml.TAG_DAEMON_READY, self.vpid)

    def _on_lifeline_lost(self, peer: int) -> None:
        if peer not in (0, self.node.parent_vpid):
            return  # a child daemon died; the HNP handles that
        if self._done.is_set():
            return  # normal teardown: SHUTDOWN already processed
        if peer != 0 and self._reparent_ok:
            # mid-tree parent death under the notify policy: do NOT apply
            # the lifeline rule — report orphanhood on the bootstrap link
            # and wait (bounded) for the HNP-arbitrated adoption, so loss
            # stays confined to the dead host's ranks
            _log.error("orted %d: tree parent %d lost; requesting "
                       "re-parenting", self.vpid, peer)
            self._reparented.clear()
            try:
                self.node.send_direct(self._boot, rml.TAG_ORPHANED,
                                      (self.vpid, peer))
            except OSError:
                pass  # HNP unreachable too → the watch below tears down
            threading.Thread(target=self._orphan_watch,
                             daemon=True).start()
            return
        _log.error("orted %d: lifeline to %d lost; tearing down", self.vpid,
                   peer)
        self._on_kill(peer, None)
        os._exit(1)

    def _orphan_watch(self) -> None:
        """Bounded adoption window: no TAG_REPARENT handshake within
        ``rml_reparent_timeout`` seconds means the job really is coming
        down — fall back to the lifeline teardown rather than leak."""
        from ompi_tpu.core.config import var_registry

        timeout = float(var_registry.get("rml_reparent_timeout") or 10.0)
        if self._reparented.wait(timeout) or self._done.is_set():
            return
        _log.error("orted %d: no adoption within %.1fs; tearing down",
                   self.vpid, timeout)
        self._on_kill(0, None)
        os._exit(1)

    def _on_reparent(self, origin: int, payload) -> None:
        """HNP arbitration reply (bootstrap link): expect ``payload``'s
        hello as my new tree parent, then ack up the re-wired tree."""
        new_parent = int(payload)
        _log.verbose(1, "orted %d: re-parenting to %d", self.vpid,
                     new_parent)
        self.node.retarget_parent(new_parent)

        def wire() -> None:
            if not self.node.wait_parent(timeout=30.0):
                return  # the orphan watch handles the teardown
            self._reparented.set()
            try:
                self.node.send_up(rml.TAG_REPARENT_ACK,
                                  (self.vpid, new_parent))
            except (ConnectionError, OSError):
                pass

        threading.Thread(target=wire, daemon=True).start()

    def _on_adopt(self, origin: int, payload) -> None:
        """HNP adoption order (bootstrap link): dial the orphans as my
        new tree children (the parent side always dials)."""
        orphans = [tuple(c) for c in payload]

        def dial() -> None:
            try:
                self.node.dial_children(orphans)
            except OSError as e:
                _log.error("orted %d: adopting %r failed: %r", self.vpid,
                           [v for v, _u in orphans], e)

        threading.Thread(target=dial, daemon=True).start()

    def _on_kill_rank(self, origin: int, payload) -> None:
        """Reap exactly one rank (a hung pid the rank-plane gossip
        detector reported): SIGKILL its process group; the exit report
        then flows through the normal waiter → errmgr path."""
        jobid, rank = int(payload[0]), int(payload[1])
        with self._lock:
            lj = self._jobs.get(jobid)
            p = lj.popen.get(rank) if lj is not None else None
        if p is None or p.poll() is not None:
            return
        _log.verbose(1, "orted %d: reaping reported-dead rank %d (pid %d)",
                     self.vpid, rank, p.pid)
        try:
            os.killpg(p.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass

    def _on_signal_rank(self, origin: int, payload) -> None:
        """Deliver one signal to one rank's process group — the DVM
        remediation actor's SIGCONT probe (a SIGSTOP'd straggler may
        just resume; only if it stays wedged does the actor pay a
        reap-and-revive)."""
        jobid, rank, signum = (int(payload[0]), int(payload[1]),
                               int(payload[2]))
        with self._lock:
            lj = self._jobs.get(jobid)
            p = lj.popen.get(rank) if lj is not None else None
        if p is None or p.poll() is not None:
            return
        _log.verbose(1, "orted %d: signal %d → rank %d (pid %d)",
                     self.vpid, signum, rank, p.pid)
        try:
            os.killpg(p.pid, signum)
        except (ProcessLookupError, PermissionError):
            pass

    # -- odls: local launch ------------------------------------------------

    def _on_launch(self, origin: int, payload) -> None:
        # payload: {"by_daemon": [(vpid, [(rank, local_rank, chip)...])...],
        #           "argv", "env", "cwd", "stdin_rank"} — the whole map is
        # xcast once; each daemon picks its own rows (≈ the launch msg
        # grpcomm floods down the tree)
        threading.Thread(target=self._launch_local, args=(payload,),
                         daemon=True).start()

    def _spawn_rank(self, lj: _LocalJob, rank: int, local_rank: int,
                    chip, restarts: int = 0) -> None:
        """Fork/exec one rank (first launch or TAG_RESPAWN revival)."""
        from ompi_tpu.core import pkg_root as _pkg_root
        from ompi_tpu.runtime.rtc import bind_child

        spec = lj.spec
        root = _pkg_root()
        env = dict(os.environ)
        env.update(spec["env"])
        pypath = env.get("PYTHONPATH", "")
        if root not in pypath.split(os.pathsep):
            env["PYTHONPATH"] = (
                root + (os.pathsep + pypath if pypath else ""))
        env[pmix.ENV_RANK] = str(rank)
        env[pmix.ENV_LOCAL_RANK] = str(local_rank)
        if chip is not None:
            env[pmix.ENV_CHIP] = str(chip)
        if self.fake_host:
            env["OMPI_TPU_FAKE_HOST"] = self.fake_host
        if restarts:
            env["OMPI_TPU_RESTART"] = str(restarts)
        if self._metrics is not None:
            # ranks and their orted share a host, so loopback always
            # reaches the collector — no remote-address discovery needed
            from ompi_tpu.mpi import trace as trace_mod

            env[trace_mod.ENV_METRICS_URI] = self._metrics.uri
        want_stdin = spec.get("stdin_rank") in ("all", rank)
        try:
            p = subprocess.Popen(
                spec["argv"], env=env, cwd=spec.get("cwd"),
                stdin=subprocess.PIPE if want_stdin
                else subprocess.DEVNULL,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                start_new_session=True)
        except OSError as e:
            # ≈ odls error-pipe: report the exec failure as an exit
            self.node.send_up(rml.TAG_PROC_EXIT,
                              (lj.jobid, rank, 127, str(e)))
            return
        bind_child(p.pid, local_rank)
        with self._lock:
            lj.popen[rank] = p
            if want_stdin:
                old = lj.stdin_writers.pop(rank, None)
                if old is not None:
                    old.feed(None)
                lj.stdin_writers[rank] = _StdinWriter(rank, p.stdin)
        self._start_iof(lj.jobid, rank, p)
        threading.Thread(target=self._waiter, args=(lj.jobid, rank, p),
                         daemon=True).start()

    def _launch_local(self, spec: dict) -> None:
        jobid = int(spec.get("jobid") or 0)
        mine: list = []
        for vpid, rows in spec["by_daemon"]:
            if vpid == self.vpid:
                mine = rows
                break
        with self._lock:
            lj = self._jobs.get(jobid)
            if lj is None:
                lj = self._jobs[jobid] = _LocalJob(jobid, spec)
            else:
                lj.spec = spec
            lj.rows = {r: (lr, ch) for r, lr, ch in mine}
        # deterministic chaos, barrier-keyed: a plan entry
        # ``daemon=<vpid>:kill@reg=N`` arms a self-SIGKILL that fires
        # only once N ranks have registered with the job's PMIx server
        # (+ an ``after=`` grace) — the kill cannot land mid-init on a
        # slow box the way a fixed kill@t could
        from ompi_tpu.testing import faultinject

        faultinject.arm_daemon_launch(self.vpid, spec.get("env") or {})
        for rank, local_rank, chip in mine:
            self._spawn_rank(lj, rank, local_rank, chip)
        # replay stdin that raced ahead of the launch xcast.  The replay
        # must happen under the lock that gates _launched: otherwise a
        # chunk arriving on the RML thread right after the flag flips
        # could be written before the buffered chunks (reordered stream).
        # feed() is non-blocking (bounded queue), so holding the lock
        # across it is safe.
        with self._lock:
            pending, self._pending_stdin = self._pending_stdin, []
            for rank, chunk in pending:
                for w in self._stdin_targets(rank):
                    w.feed(chunk)
            self._launched = True

    def _stdin_targets(self, rank) -> list[_StdinWriter]:
        """Writers a stdin chunk fans out to (caller holds _lock).
        stdin forwarding is a non-DVM, single-job feature, but routing
        across every job keeps it correct if a tenant ever asks."""
        if rank == "all":
            return [w for lj in self._jobs.values()
                    for w in lj.stdin_writers.values()]
        return [w for lj in self._jobs.values()
                for r, w in lj.stdin_writers.items() if r == rank]

    def _start_iof(self, jobid: int, rank: int,
                   p: subprocess.Popen) -> None:
        def reader(pipe, stream: str) -> None:
            for raw in iter(pipe.readline, b""):
                try:
                    self.node.send_up(rml.TAG_IOF,
                                      (jobid, rank, stream, raw))
                except ConnectionError:
                    return
            pipe.close()

        for pipe, stream in ((p.stdout, "out"), (p.stderr, "err")):
            threading.Thread(target=reader, args=(pipe, stream),
                             daemon=True).start()

    def _waiter(self, jobid: int, rank: int, p: subprocess.Popen) -> None:
        rc = p.wait()
        # let IOF readers drain the tail before the exit report races them
        time.sleep(0.05)
        try:
            self.node.send_up(rml.TAG_PROC_EXIT, (jobid, rank, rc, ""))
        except ConnectionError:
            pass

    # -- control -----------------------------------------------------------

    def _on_kill(self, origin: int, payload) -> None:
        """Tear one job down (payload = jobid: its state is dropped —
        the DVM sends this when a tenant leaves the pool) or every job
        (payload None: lifeline teardown / VM shutdown)."""
        with self._lock:
            if payload is None:
                doomed = list(self._jobs.values())
            else:
                lj = self._jobs.pop(int(payload), None)
                doomed = [lj] if lj is not None else []
            victims = [p for lj in doomed for p in lj.popen.values()]
            writers = [w for lj in doomed
                       for w in lj.stdin_writers.values()]
        for w in writers:
            w.feed(None)
        for p in victims:
            if p.poll() is None:
                try:
                    os.killpg(p.pid, signal.SIGTERM)
                except (ProcessLookupError, PermissionError):
                    pass
        deadline = time.monotonic() + 2.0
        for p in victims:
            try:
                p.wait(timeout=max(0.0, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(p.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass

    def _on_respawn(self, origin: int, payload) -> None:
        """errmgr/respawn xcast: the TARGET daemon revives the rank
        (≈ the odls relaunch arm of the errmgr restart path).  The
        payload names an explicit target vpid: normally the original
        owner, but the DVM remediation actor may retarget a straggler
        to a less-loaded host — every daemon holds the job spec, so the
        adopter just adds the row; the old owner drops it."""
        jobid = int(payload["jobid"])
        rank = int(payload["rank"])
        lives = int(payload["lives"])
        target = int(payload.get("target") or 0)
        with self._lock:
            lj = self._jobs.get(jobid)
            if lj is None:
                return  # this daemon never saw the job's launch
            if target != self.vpid:
                # migrated away (or another daemon's rank all along):
                # make sure no stale row revives it here later
                lj.rows.pop(rank, None)
                lj.popen.pop(rank, None)
                return
            row = lj.rows.get(rank)
            if row is None:
                # adoption: keep the rank's original local_rank/chip —
                # on a sim pool local_rank only feeds ENV/bind hints,
                # and a real placement would remap chips at rejoin
                row = (int(payload.get("local_rank") or 0),
                       payload.get("chip"))
                lj.rows[rank] = row
        local_rank, chip = row
        _log.verbose(1, "orted %d: respawning rank %d (restart %d)",
                     self.vpid, rank, lives)
        # spawn off the RML reader thread (fork/exec + iof setup)
        threading.Thread(
            target=self._spawn_rank, args=(lj, rank, local_rank, chip),
            kwargs={"restarts": lives}, daemon=True).start()

    def _on_stats(self, origin: int, payload) -> None:
        """≈ the sensor/resusage sampling orte-top pulls: per-rank
        rss + cpu time from /proc for my live ranks, replied up the
        tree (runs on the RML reader thread — /proc reads don't block)."""
        page = os.sysconf("SC_PAGE_SIZE")
        tick = os.sysconf("SC_CLK_TCK")
        rows = []
        with self._lock:
            procs = [(lj.jobid, rank, p)
                     for lj in self._jobs.values()
                     for rank, p in lj.popen.items()]
        for jobid, rank, p in procs:
            if p.poll() is not None:
                continue
            try:
                with open(f"/proc/{p.pid}/statm") as f:
                    rss = int(f.read().split()[1]) * page
                with open(f"/proc/{p.pid}/stat") as f:
                    parts = f.read().rsplit(")", 1)[1].split()
                    cpu_s = (int(parts[11]) + int(parts[12])) / tick
            except (OSError, IndexError, ValueError):
                continue
            rows.append((jobid, rank, p.pid, rss, cpu_s))
        try:
            # payload is the requester's epoch — echoed so a late reply
            # from an earlier round cannot satisfy a newer collection
            self.node.send_up(rml.TAG_STATS_REPLY,
                              (self.vpid, payload, rows))
        except ConnectionError:
            pass

    def _on_doctor(self, origin: int, payload) -> None:
        """Hang-doctor capture fan-out: query each LIVE local rank's
        responder (UDP, loopback — ranks share this host), fall back to
        a /proc probe for a rank that stays silent (a SIGSTOP'd pid
        cannot answer; its frozen state IS the evidence), reply the
        captures up the tree.  The UDP waits block up to ~1s per silent
        rank — handed off a thread, never run on the RML reader."""
        threading.Thread(target=self._doctor_capture, args=(payload,),
                         name=f"orted-doctor-{self.vpid}",
                         daemon=True).start()

    def _doctor_capture(self, epoch) -> None:
        from ompi_tpu.runtime import doctor

        with self._lock:
            jobs = [(lj.jobid, lj.spec,
                     [(r, p) for r, p in lj.popen.items()
                      if p.poll() is None])
                    for lj in self._jobs.values()]
        rows = []
        for jobid, spec, procs in jobs:
            ports: dict[int, int] = {}
            uri = ((spec or {}).get("env") or {}).get(pmix.ENV_URI)
            if uri and procs:
                ports = pmix.query_doctor_ports(uri) or {}
            job_rows = []
            for rank, p in sorted(procs):
                cap = None
                port = ports.get(rank)
                if port:
                    cap = doctor.query_rank(port)
                if cap is None:
                    cap = {"rank": rank, "no_response": True,
                           "proc": doctor.proc_probe(p.pid)}
                cap["pid"] = p.pid
                cap["jobid"] = jobid
                job_rows.append(cap)
            # hierarchical pre-aggregation: bound this daemon's reply to
            # doctor_rows_per_daemon full rows + one explicit summary
            # row per job, so the HNP's fan-in is O(hosts), not O(ranks)
            from ompi_tpu.core.config import var_registry

            limit = int(var_registry.get("doctor_rows_per_daemon") or 0)
            kept, summary = doctor.summarize_rows(job_rows, limit)
            if summary is not None:
                summary["jobid"] = jobid
                summary["vpid"] = self.vpid
                kept.append(summary)
            rows.extend(kept)
        try:
            self.node.send_up(rml.TAG_DOCTOR_REPLY,
                              (self.vpid, epoch, rows))
        except ConnectionError:
            pass

    def _on_timeline(self, origin: int, payload) -> None:
        """Live-timeline fan-out (the TAG_DOCTOR shape): query each
        live local rank's responder for a bounded flight-recorder tail,
        reply up.  Handed off a thread — the UDP waits block."""
        threading.Thread(target=self._timeline_capture, args=(payload,),
                         name=f"orted-timeline-{self.vpid}",
                         daemon=True).start()

    def _timeline_capture(self, payload) -> None:
        from ompi_tpu.runtime import doctor

        try:
            epoch, tail = payload
            tail = int(tail)
        except (TypeError, ValueError):
            epoch, tail = payload, 2048
        with self._lock:
            jobs = [(lj.jobid, lj.spec,
                     [(r, p) for r, p in lj.popen.items()
                      if p.poll() is None])
                    for lj in self._jobs.values()]
        off_root = self._clock.offset_to_root_ns()
        rows = []
        for jobid, spec, procs in jobs:
            ports: dict[int, int] = {}
            uri = ((spec or {}).get("env") or {}).get(pmix.ENV_URI)
            if uri and procs:
                ports = pmix.query_doctor_ports(uri) or {}
            for rank, p in sorted(procs):
                port = ports.get(rank)
                cap = doctor.query_timeline(port, tail) if port else None
                if cap is None:
                    cap = {"rank": rank, "no_response": True}
                # stamp the daemon-measured offset: ranks share this
                # host's kernel clock, so one offset corrects every
                # local rank
                cap["clock_to_root_ns"] = off_root
                cap["jobid"] = jobid
                rows.append(cap)
        try:
            self.node.send_up(rml.TAG_TIMELINE_REPLY,
                              (self.vpid, epoch, rows))
        except ConnectionError:
            pass

    def _on_stdin(self, origin: int, payload) -> None:
        # Runs on the RML link reader thread: never write the pipe here —
        # a rank that doesn't drain stdin would fill the OS pipe, block
        # this thread, and stall TAG_KILL/TAG_SHUTDOWN on the same link.
        # Hand the chunk to the per-rank writer thread instead.
        rank, chunk = payload
        with self._lock:
            if not self._launched:
                self._pending_stdin.append(payload)
                return
            writers = self._stdin_targets(rank)
        for w in writers:
            w.feed(chunk)

    def run(self) -> int:
        self._done.wait()
        self._on_kill(0, None)   # stragglers die with the daemon
        self._clock.stop()
        if self._metrics is not None:
            self._metrics.close()
        self.node.close()
        return 0


def main(argv: Optional[list[str]] = None) -> int:
    ap = argparse.ArgumentParser(prog="ompi-tpu-orted")
    ap.add_argument("--hnp", required=True, help="HNP rml uri host:port")
    ap.add_argument("--vpid", type=int, required=True)
    ap.add_argument("--ndaemons", type=int, required=True)
    ap.add_argument("--fake-host", default=None)
    args = ap.parse_args(argv)
    return Orted(args.hnp, args.vpid, args.ndaemons,
                 fake_host=args.fake_host).run()


if __name__ == "__main__":
    sys.exit(main())
