"""Live cross-rank timeline merge — skew-corrected Chrome traces.

≈ the post-mortem merge in tools/trace_export.py, lifted into the
control plane: the HNP's ``/timeline`` endpoint xcasts TAG_TIMELINE,
every orted pulls a bounded flight-recorder tail from each live rank
(runtime/doctor.py's "tl" query) and stamps it with the daemon's
MEASURED offset-to-root (runtime/clocksync.py), and this module folds
the replies into one Perfetto-loadable document.

Two jobs post-mortem merges cannot do:

- **Measured skew correction.**  Dump merges only have each rank's
  wall-vs-monotonic anchor; a live capture carries the clock-sync
  plane's pingpong-measured monotonic offsets, so cross-host event
  ordering is correct to ~rtt/2 instead of NTP-grade seconds.  When
  any capture lacks a measured offset (sync disabled, window still
  filling) the merge degrades to the wall anchors and says so in
  ``otherData.clock_domain``.
- **Causal flow edges.**  Send→recv arrows from the flow ids the PML
  stamps into match headers, round arrows chaining every rank's span
  of one collective (same ``(cid, seq)``), and RML envelope arrows
  from the ``(trace_id, span_id)`` pair OOB messages carry.

Self-contained by design: the DVM imports this at HNP runtime where
``ompi_tpu.mpi`` may never load (no job ran yet), and tests feed it
synthetic captures — so it touches neither the MPI layer nor tools/.
"""

from __future__ import annotations

from typing import Any, Optional

__all__ = ["merge_captures", "flow_events", "causality_problems"]

# keep in sync with ompi_tpu.mpi.trace.CATEGORIES (see module docstring
# for why this is a copy, not an import)
CATEGORIES = ("pml", "btl", "coll", "osc", "io", "ckpt", "datatype",
              "runtime", "errmgr")

#: span names carrying ``args.fl`` — the send/recv halves of one
#: message (keep in sync with tools/trace_export.py)
FLOW_SEND_SPANS = ("eager_send", "rndv_send")
FLOW_RECV_SPANS = ("eager_recv", "rndv_recv")

#: instant names carrying ``args.tc`` — the two ends of one RML envelope
RML_SEND_NAME = "rml_send"
RML_RECV_NAME = "rml_recv"


def _span_end(ev: dict) -> float:
    """A flow endpoint must land INSIDE its span (Chrome binds flows to
    the slice enclosing the ts), so anchors ride just before span end."""
    return float(ev.get("ts", 0.0)) + max(0.0, float(ev.get("dur", 0.0)))


def flow_events(events: list[dict]) -> list[dict]:
    """Causal arrows for a merged event list (events must already carry
    their final ``pid``/``ts``):

    - p2p: ``{eager,rndv}_send`` → ``{eager,rndv}_recv`` paired by
      ``args.fl`` (scoped by ``args.tc`` when the header carried the
      trace id — flow ids from different jobs must not collide);
    - collective rounds: every rank's ``coll``-category span of one
      ``(cid, seq)`` chained rank-to-rank in time order (``s``/``t``/
      ``f``) — the arrow path makes the straggler visible;
    - RML envelopes: ``rml_send`` → ``rml_recv`` instants paired by the
      ``(trace_id, span_id)`` stamp.
    """
    sends: dict = {}
    recvs: dict = {}
    colls: dict = {}
    rml_s: dict = {}
    rml_r: dict = {}
    for ev in events:
        args = ev.get("args") or {}
        name = ev.get("name")
        if ev.get("ph") == "X":
            fl = args.get("fl")
            if fl is not None:
                key = (args.get("tc"), fl)
                if name in FLOW_SEND_SPANS:
                    sends.setdefault(key, ev)
                elif name in FLOW_RECV_SPANS:
                    recvs.setdefault(key, ev)
            if ev.get("cat") == "coll" and "seq" in args and "cid" in args:
                colls.setdefault((args["cid"], args["seq"]),
                                 []).append(ev)
        elif name == RML_SEND_NAME and args.get("tc") is not None:
            rml_s.setdefault(tuple(args["tc"]), ev)
        elif name == RML_RECV_NAME and args.get("tc") is not None:
            rml_r.setdefault(tuple(args["tc"]), ev)
    out: list[dict] = []
    for key, sev in sends.items():
        rev = recvs.get(key)
        if rev is None or rev.get("pid") == sev.get("pid"):
            continue   # no recv half, or a self-send — no arrow
        # s anchors at the send span's START: the transfer happens
        # somewhere inside the send call, and a fast receiver can
        # legitimately finish before the sender's span closes
        s_ts, f_ts = float(sev.get("ts", 0.0)), _span_end(rev)
        if f_ts < s_ts:
            # recv ends before the send even started: residual skew,
            # no binding placement exists (see causality_problems —
            # the merge reports these)
            continue
        tc, fl = key
        fid = f"{tc}:{fl}" if tc is not None else fl
        common = {"cat": "flow", "name": "msg", "id": fid}
        out.append({**common, "ph": "s", "ts": s_ts,
                    "pid": sev["pid"], "tid": sev.get("tid", 0)})
        out.append({**common, "ph": "f", "bp": "e", "ts": f_ts,
                    "pid": rev["pid"], "tid": rev.get("tid", 0)})
    for (cid, seq), group in colls.items():
        # one span per pid (a rank re-entering the same (cid, seq) is a
        # recorder artifact — keep the earliest), chained in end order
        by_pid: dict = {}
        for ev in group:
            cur = by_pid.get(ev.get("pid"))
            if cur is None or float(ev.get("ts", 0)) < float(
                    cur.get("ts", 0)):
                by_pid[ev.get("pid")] = ev
        chain = sorted(by_pid.values(), key=_span_end)
        if len(chain) < 2:
            continue   # single-rank round: nothing to stitch
        common = {"cat": "flow", "name": "coll_round",
                  "id": f"coll:{cid}:{seq}"}
        for i, ev in enumerate(chain):
            ph = "s" if i == 0 else ("f" if i == len(chain) - 1 else "t")
            step = {**common, "ph": ph, "ts": _span_end(ev),
                    "pid": ev["pid"], "tid": ev.get("tid", 0)}
            if ph == "f":
                step["bp"] = "e"
            out.append(step)
    for key, sev in rml_s.items():
        rev = rml_r.get(key)
        if rev is None or rev.get("pid") == sev.get("pid"):
            continue
        s_ts, f_ts = float(sev.get("ts", 0)), float(rev.get("ts", 0))
        if f_ts < s_ts:
            continue
        common = {"cat": "flow", "name": "rml",
                  "id": f"rml:{key[0]}:{key[1]}"}
        out.append({**common, "ph": "s", "ts": s_ts,
                    "pid": sev["pid"], "tid": sev.get("tid", 0)})
        out.append({**common, "ph": "f", "bp": "e", "ts": f_ts,
                    "pid": rev["pid"], "tid": rev.get("tid", 0)})
    return out


def causality_problems(events: list[dict]) -> list[str]:
    """Post-correction sanity: a recv span that ENDS before its matching
    send span even STARTED means the applied offsets failed to restore
    causality (data cannot finish arriving before the send call began;
    comparing span ENDS would false-positive on every fast receiver
    outpacing a slow sender).  Returns one line per violated pair —
    what the merge surfaces and the exporter's validator asserts
    empty."""
    sends: dict = {}
    recvs: dict = {}
    for ev in events:
        if ev.get("ph") != "X":
            continue
        args = ev.get("args") or {}
        fl = args.get("fl")
        if fl is None:
            continue
        key = (args.get("tc"), fl)
        if ev.get("name") in FLOW_SEND_SPANS:
            sends.setdefault(key, ev)
        elif ev.get("name") in FLOW_RECV_SPANS:
            recvs.setdefault(key, ev)
    problems = []
    for key, sev in sends.items():
        rev = recvs.get(key)
        if rev is None or rev.get("pid") == sev.get("pid"):
            continue
        s_start = float(sev.get("ts", 0.0))
        r_end = _span_end(rev)
        if r_end < s_start:
            problems.append(
                f"flow {key[1]}: recv on rank {rev.get('pid')} ends "
                f"{s_start - r_end:.1f}us before its send on rank "
                f"{sev.get('pid')} even started — clock correction "
                f"failed to restore causality")
    return problems


def merge_captures(captures: list[dict],
                   jobid: Optional[int] = None) -> dict[str, Any]:
    """Fold TAG_TIMELINE_REPLY capture rows (trace.timeline_capture
    dicts, each stamped with the serving daemon's ``clock_to_root_ns``)
    into one Chrome trace document.

    Clock domain: when EVERY responding capture carries a measured
    offset, all timestamps shift onto the root daemon's monotonic
    clock (``clock_domain: "root_monotonic"``); otherwise every rank
    falls back to its wall anchor (``clock_domain: "wall"``) — mixing
    the two axes would fabricate ordering.
    """
    rows = [c for c in captures if isinstance(c, dict)]
    live = [c for c in rows if not c.get("no_response")]
    measured = bool(live) and all(
        isinstance(c.get("clock_to_root_ns"), (int, float))
        for c in live)
    domain = "root_monotonic" if measured else "wall"
    all_events: list[dict] = []
    meta: list[dict] = []
    per_rank: dict[int, dict] = {}
    trace_ids = set()
    for cap in sorted(rows, key=lambda c: int(c.get("rank", -1))):
        rank = int(cap.get("rank", -1))
        info = {k: cap.get(k) for k in
                ("events_total", "dropped", "capacity",
                 "clock_to_root_ns", "clock_offset_ns", "truncated",
                 "counters", "collrec")}
        if cap.get("no_response"):
            info["no_response"] = True
            per_rank[rank] = info
            continue
        per_rank[rank] = info
        if cap.get("trace_id"):
            trace_ids.add(cap["trace_id"])
        off_ns = (cap.get("clock_to_root_ns") if measured
                  else cap.get("clock_offset_ns"))
        shift_us = float(off_ns or 0) / 1000.0
        meta.append({"ph": "M", "name": "process_name", "pid": rank,
                     "tid": 0, "args": {"name": f"rank {rank}"}})
        tids = set()
        for ev in cap.get("events") or []:
            ev = dict(ev)
            ev["pid"] = rank
            if "ts" in ev:
                ev["ts"] = float(ev["ts"]) + shift_us
            all_events.append(ev)
            tids.add(int(ev.get("tid", 0)))
        for tid in sorted(tids):
            name = CATEGORIES[tid] if tid < len(CATEGORIES) else "other"
            meta.append({"ph": "M", "name": "thread_name", "pid": rank,
                         "tid": tid, "args": {"name": name}})
    problems = causality_problems(all_events)
    all_events.extend(flow_events(all_events))
    if all_events:
        # Perfetto wants a non-negative, roughly-sorted axis; measured
        # offsets can legally shift early events below zero
        base = min(float(e.get("ts", 0.0)) for e in all_events)
        if base < 0:
            for ev in all_events:
                ev["ts"] = float(ev.get("ts", 0.0)) - base
    all_events.sort(key=lambda e: float(e.get("ts", 0.0)))
    n_flows = sum(1 for e in all_events if e.get("ph") == "s")
    return {
        "displayTimeUnit": "ns",
        "otherData": {
            "jobid": jobid,
            "trace_id": (sorted(trace_ids)[0] if trace_ids else None),
            "clock_domain": domain,
            "ranks": sorted(per_rank),
            "flow_edges": n_flows,
            "causality_problems": problems,
            "per_rank": {str(r): v for r, v in sorted(per_rank.items())},
        },
        "traceEvents": meta + all_events,
    }
