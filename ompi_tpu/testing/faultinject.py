"""faultinject — a seeded, schedule-driven chaos layer.

The proof harness for every recovery path the stack ships (errmgr
respawn / continue / notify, pml park-and-heal, ULFM revoke/shrink/agree
in mpi/ft.py): instead of hand-scripted ``os._exit`` calls sprinkled
through test apps, a *fault plan* — one string, replayable byte-for-byte
from its seed — declares which rank dies when and which messages the
transport loses, delays, or duplicates.

Plan grammar (entries separated by ``;``)::

    rank=2:kill@step=3        rank 2 exits when its step counter hits 3
    rank=2:kill@t=0.5         rank 2 exits ~0.5 s after arming
    rank=2:hang@step=3        rank 2 STALLS (alive pid, silent rank) at
                              step 3 — SIGSTOP by default, a cooperative
                              spin with faultinject_hang_mode=spin
    rank=2:crash@step=3       like kill, but fires in EVERY life — the
                              crash loop that proves the errmgr revive
                              budget/escalation ladder (kill and hang
                              are first-life-only by design)
    rank=2:kill@coll=5        rank 2 exits INSIDE its 5th top-level
                              collective dispatch (after the recorder
                              post, before the collective runs — so the
                              victim never publishes and the revive
                              lands mid-collective-loop; first-life-
                              only like every kill): the deterministic
                              mid-collective death behind the
                              selfheal-coll rejoin chaos class
    rank=2:stall@coll=5       rank 2 stalls INSIDE its 5th recorded
                              collective (counted by the flight
                              recorder's dispatch ordinal, 0-based):
                              SIGSTOP by default, a cooperative spin
                              with faultinject_hang_mode=spin — the
                              deterministic straggler the hang doctor
                              must name
    rank=1:mismatch@coll=5    rank 1 dispatches a DIVERGENT collective
                              kind at ordinal 5 (recorded at the same
                              (cid, op_seq) its peers run the real op),
                              then spin-parks so it stays capturable —
                              the deterministic collective mismatch
                              behind the doctor's mismatch verdict
    daemon=1:kill@t=1.0       orted vpid 1 SIGKILLs itself after 1 s
    daemon=1:kill@reg=4:after=1.5
                              orted vpid 1 SIGKILLs itself 1.5 s after
                              4 ranks have REGISTERED with the job's
                              PMIx server — a barrier-keyed schedule
                              that cannot land mid-init on a slow box
                              (``after`` defaults to 1.0 s)
    drop=0.01                 drop outgoing FT-control frames with p=0.01
    drop=0.05@all             drop ANY outgoing frame with p=0.05
    rank=1:drop=0.1           restrict the action to rank 1
    delay=0.02,5              delay frames 5 ms with p=0.02
    dup=0.01                  duplicate frames with p=0.01

Activation: ``OMPI_TPU_FAULT_PLAN`` / ``OMPI_TPU_FAULT_SEED`` in the
environment, or the registered MCA vars (``--mca faultinject_plan ...``
— tpurun exports --mca pairs into the job env, so the same plan reaches
every rank).

Determinism: a frame's verdict is a pure function of
``(seed, rank, peer, frame identity)`` where the identity is built from
the header's protocol fields (t/tag/cid/seq/op/attempt...) — no
wall-clock, no global RNG, no thread-timing or send-path dependence: the
same logical frame draws the same verdict whether it rides the inline
fast path, the send worker, or a heal retry (FT control frames carry an
attempt counter, so each *retransmission* is a fresh identity — a
dropped revoke cannot be dropped forever).  ``step``-triggered kills
fire at exactly the same application step on replay.  Every fired fault
is recorded (and mirrored onto the flight recorder when tracing is
armed); ``events()`` / the ``OMPI_TPU_FAULT_LOG_DIR`` dump let a driver
assert replay equality (tools/chaos_soak.py does).

Scope note on drops: the PML assumes a *reliable* transport — an
unconditionally dropped data frame is a hung collective, by design.
``drop`` therefore defaults to the FT control plane (``t: "ft"`` frames,
whose revoke/agree protocols carry their own retransmission) and must be
widened to ``@all`` explicitly by plans that want to prove timeout
behavior rather than completion.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
import zlib
from typing import Any, Optional

from ompi_tpu.core import output
from ompi_tpu.core.config import VarType, register_var, var_registry

__all__ = ["active", "plan_text", "injector_for", "step", "arm_daemon",
           "arm_daemon_launch", "events", "reset", "Injector"]

_log = output.get_stream("faultinject")

register_var("faultinject", "plan", VarType.STRING, "",
             "fault plan (see ompi_tpu.testing.faultinject grammar); "
             "empty = chaos disabled.  OMPI_TPU_FAULT_PLAN is a synonym.")
register_var("faultinject", "seed", VarType.INT, 0,
             "seed for the deterministic fault decision streams")
register_var("faultinject", "exit_code", VarType.INT, 9,
             "exit code an injected rank kill dies with")
register_var("faultinject", "hang_mode", VarType.STRING, "stop",
             "how an injected hang stalls the rank: 'stop' = SIGSTOP the "
             "whole process (full-process freeze — the in-host hang the "
             "rank-plane gossip heartbeats exist to catch); 'spin' = park "
             "only the calling thread in a sleep loop (an app-thread "
             "deadlock; background threads keep running)",
             enumerator=("stop", "spin"))

ENV_PLAN = "OMPI_TPU_FAULT_PLAN"
ENV_SEED = "OMPI_TPU_FAULT_SEED"
ENV_LOG_DIR = "OMPI_TPU_FAULT_LOG_DIR"


def plan_text() -> str:
    """The active plan string ('' when chaos is disabled)."""
    return (os.environ.get(ENV_PLAN)
            or var_registry.get("faultinject_plan") or "")


def plan_seed() -> int:
    env = os.environ.get(ENV_SEED)
    if env is not None:
        return int(env)
    return int(var_registry.get("faultinject_seed") or 0)


def active() -> bool:
    return bool(plan_text())


class _Action:
    """One parsed plan entry."""

    __slots__ = ("kind", "rank", "prob", "scope", "delay_ms", "at_step",
                 "at_time", "at_reg", "at_coll", "after", "vpid")

    def __init__(self) -> None:
        self.kind = ""            # kill | daemon_kill | drop | delay | dup
        self.rank: Optional[int] = None   # None = every rank
        self.vpid: Optional[int] = None
        self.prob = 0.0
        self.scope = "ft"         # ft | all
        self.delay_ms = 0.0
        self.at_step: Optional[int] = None
        self.at_time: Optional[float] = None
        self.at_reg: Optional[int] = None   # ranks-registered barrier
        self.at_coll: Optional[int] = None  # flight-recorder dispatch
        # ordinal (stall/mismatch fire inside that collective)
        self.after = 1.0          # grace after the @reg barrier clears


def _parse_entry(entry: str) -> _Action:
    act = _Action()
    for part in entry.split(":"):
        part = part.strip()
        if not part:
            continue
        key, _, val = part.partition("=")
        key = key.strip()
        val = val.strip()
        if key == "rank":
            act.rank = int(val)
        elif key == "daemon":
            act.vpid = int(val)
        elif (key in ("kill", "hang", "crash", "stall", "mismatch")
              or key.startswith(("kill@", "hang@", "crash@", "stall@",
                                 "mismatch@"))):
            base = key.partition("@")[0]
            act.kind = ("daemon_kill" if act.vpid is not None
                        and base == "kill" else base)
            # kill@step=N / kill@t=SEC arrive as key "kill@step"/"kill@t"
            # (same for hang@ / crash@ / stall@ / mismatch@)
            trig = key.partition("@")[2]
            if trig == "step":
                act.at_step = int(val)
            elif trig == "t":
                act.at_time = float(val)
            elif trig == "reg":
                act.at_reg = int(val)
            elif trig == "coll":
                act.at_coll = int(val)
            else:
                raise ValueError(
                    f"{base} needs a trigger: {base}@step=N, "
                    f"{base}@t=SEC, {base}@reg=NRANKS or "
                    f"{base}@coll=N (got {part!r})")
        elif key == "after":
            act.after = float(val)
        elif key in ("drop", "dup"):
            act.kind = key
            prob, _, scope = val.partition("@")
            act.prob = float(prob)
            act.scope = scope or ("ft" if key == "drop" else "all")
        elif key == "delay":
            act.kind = "delay"
            prob, _, rest = val.partition(",")
            act.prob = float(prob)
            ms, _, scope = rest.partition("@")
            act.delay_ms = float(ms or 1.0)
            act.scope = scope or "all"
        else:
            raise ValueError(f"unknown fault-plan token {part!r} "
                             f"in entry {entry!r}")
    if not act.kind:
        raise ValueError(f"fault-plan entry {entry!r} names no action")
    if act.scope not in ("ft", "all"):
        raise ValueError(f"unknown fault scope {act.scope!r} (ft|all)")
    # whole-entry validation (field order within an entry is free, so
    # per-field checks can be sidestepped): hangs target ranks only —
    # a hung DAEMON is the heartbeat layer's job, and a daemon= field
    # anywhere in a hang entry is a contradiction, not a default
    if act.kind in ("hang", "crash", "stall", "mismatch") \
            and act.vpid is not None:
        raise ValueError(
            f"{act.kind} targets ranks, not daemons (entry {entry!r})")
    # the collective triggers fire from inside the coll dispatch choke
    # point — the @coll ordinal is their ONLY trigger (a wall-clock
    # stall would not be deterministic against the recorder's seq).
    # kill@coll=N rides the same ordinal (die at the Nth TOP-LEVEL
    # dispatch, never inside a nested/infrastructure phase); hang and
    # crash keep their step/t triggers — a hang inside the dispatch is
    # spelled stall, and crash must fire in every life, which the
    # first-life-only _colls arm cannot express
    if act.kind in ("stall", "mismatch") and act.at_coll is None:
        raise ValueError(
            f"{act.kind} needs an @coll=N trigger (entry {entry!r})")
    if act.at_coll is not None and act.kind not in ("stall", "mismatch",
                                                    "kill"):
        raise ValueError(
            f"@coll triggers are stall/mismatch/kill only "
            f"(entry {entry!r})")
    # a kill that saw daemon= before the kill key is a daemon_kill; one
    # that saw it after must settle to the same action
    if act.kind == "kill" and act.vpid is not None:
        act.kind = "daemon_kill"
    # ...and @coll is a RANK trigger (the ordinal lives in the rank's
    # coll dispatcher) — a daemon kill keyed on it could never fire
    if act.kind == "daemon_kill" and act.at_coll is not None:
        raise ValueError(
            f"@coll triggers target ranks, not daemons (entry {entry!r})")
    # the ranks-registered barrier is a DAEMON schedule: only an orted
    # can watch the PMIx regcount without being counted by it (a rank's
    # own registration is part of the barrier it would be waiting on)
    if act.at_reg is not None and act.kind != "daemon_kill":
        raise ValueError(
            f"@reg triggers are daemon-kill only (entry {entry!r})")
    if act.after < 0:
        raise ValueError(f"after= must be >= 0 (entry {entry!r})")
    return act


def parse_plan(text: str) -> list[_Action]:
    return [_parse_entry(e) for e in text.split(";") if e.strip()]


#: header fields that identify a logical frame (+ attempt counters) —
#: what the deterministic verdict hashes over
_IDENT_KEYS = ("t", "tag", "cid", "seq", "ep", "op", "aseq", "n", "sid",
               "rid", "off", "from")


def _frame_ident(header: dict) -> str:
    return ",".join(f"{k}={header[k]}" for k in _IDENT_KEYS if k in header)


def _u01(seed: int, rank: int, peer: int, ident: str, salt: str) -> float:
    """Deterministic uniform [0,1) per logical frame — a pure hash, so
    the verdict is independent of thread timing and send path."""
    key = f"{seed}:{rank}:{peer}:{ident}:{salt}".encode()
    return (zlib.crc32(key) & 0xFFFFFFFF) / 4294967296.0


class Injector:
    """Per-rank chaos engine: frame verdicts + kill triggers + event log."""

    def __init__(self, rank: int, actions: list[_Action], seed: int) -> None:
        self.rank = rank
        self.seed = seed
        self._acts = [a for a in actions
                      if a.rank is None or a.rank == rank]
        self._frame_acts = [a for a in self._acts
                            if a.kind in ("drop", "delay", "dup")]
        # kills AND hangs fire in a rank's FIRST life only: an
        # errmgr-respawned incarnation re-arms the injector and would
        # otherwise die again at the same step, looping until restarts
        # exhaust.  ``crash`` is that loop ON PURPOSE — it fires in
        # every life, proving the revive budget / escalation ladder.
        restarted = bool(os.environ.get("OMPI_TPU_RESTART"))
        self._kills = [a for a in self._acts
                       if a.kind == "crash"
                       or (a.kind in ("kill", "hang") and not restarted)]
        # collective-choke-point triggers (stall/mismatch/kill@coll=N),
        # first life only like kills/hangs — a revived victim must not
        # re-wedge/re-die at the same ordinal
        self._colls = [a for a in self._acts
                       if a.at_coll is not None
                       and a.kind in ("stall", "mismatch", "kill")
                       and not restarted]
        # the @coll ordinal: TOP-LEVEL dispatched collectives of this
        # life (the dispatcher skips nested composed sub-collectives —
        # firing inside e.g. the init barrier's internal allgather would
        # wedge peers mid-arena-build, outside every timeout)
        self._coll_n = 0
        self._step = 0
        self._lock = threading.Lock()
        self.events: list[dict] = []
        self._dead = False
        for k in self._kills:
            if k.at_time is not None:
                if k.kind == "hang":
                    t = threading.Timer(k.at_time, self._fire_hang,
                                        args=("t", k.at_time))
                else:
                    t = threading.Timer(k.at_time, self._fire_kill,
                                        args=("t", k.at_time, k.kind))
                t.daemon = True
                t.start()

    # -- kill triggers -----------------------------------------------------

    def step(self) -> int:
        """Advance the application step counter; fires any kill@step
        scheduled for the new step.  Returns the step just entered."""
        with self._lock:
            s = self._step
            self._step += 1
        for k in self._kills:
            if k.at_step == s:
                if k.kind == "hang":
                    self._fire_hang("step", s)
                else:
                    self._fire_kill("step", s, kind=k.kind)
        return s

    def _fire_kill(self, trigger: str, value, kind: str = "kill") -> None:
        if self._dead:
            return
        self._dead = True
        self._record(kind, trigger=trigger, value=value)
        _log.emit("faultinject: rank %d injected kill (%s=%s)",
                  self.rank, trigger, value)
        _dump_events_now()
        import sys

        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(int(var_registry.get("faultinject_exit_code")))

    def _fire_hang(self, trigger: str, value) -> None:
        """The injected in-host hang: the rank stalls WITHOUT exiting —
        the pid stays alive (invisible to the daemon heartbeat layer and
        the launcher reap loop), only its peers' gossip can tell."""
        if self._dead:
            return
        self._dead = True   # one terminal fault per life, like kills
        self._record("hang", trigger=trigger, value=value,
                     mode=var_registry.get("faultinject_hang_mode"))
        _log.emit("faultinject: rank %d injected hang (%s=%s)",
                  self.rank, trigger, value)
        _dump_events_now()
        import sys

        sys.stdout.flush()
        sys.stderr.flush()
        self._hang_impl()

    def _hang_impl(self) -> None:
        """Separated so tests can observe the trigger without actually
        freezing the test process."""
        if var_registry.get("faultinject_hang_mode") == "spin":
            while True:            # cooperative: only this thread parks
                time.sleep(3600)
        import signal

        os.kill(os.getpid(), signal.SIGSTOP)

    # -- collective triggers (coll dispatch choke-point hook) --------------

    def coll_faults(self) -> bool:
        """Any armed stall/mismatch@coll actions?  The coll dispatcher
        caches this so a plan without collective triggers costs one
        dict hit per dispatch."""
        return bool(self._colls)

    def coll_op(self) -> tuple[Optional[str], int]:
        """Advance the top-level collective ordinal (called once per
        top-level dispatch) → (armed action | None, the ordinal just
        entered).  :meth:`fire_coll` fires the returned action."""
        n = self._coll_n
        self._coll_n += 1
        if self._dead:
            return None, n
        for a in self._colls:
            if a.at_coll == n:
                return a.kind, n
        return None, n

    def fire_coll(self, kind: str, n: int, seq: int) -> None:
        """Fire a collective trigger from inside the dispatch: record
        the fault, then park (or die).  ``kill`` exits immediately —
        after the recorder post, before the collective body, so the
        victim never publishes into the arena and its revive lands
        mid-collective-loop.  ``stall`` follows faultinject_hang_mode
        (SIGSTOP / spin); ``mismatch`` ALWAYS spin-parks — the divergent
        rank must stay capturable so the doctor can read its recorder
        tail with the divergent (cid, op_seq) record."""
        if self._dead:
            return
        if kind == "kill":
            self._fire_kill("coll", n)
            return
        self._dead = True
        mode = ("spin" if kind == "mismatch"
                else var_registry.get("faultinject_hang_mode"))
        self._record(kind, trigger="coll", value=n, seq=seq, mode=mode)
        _log.emit("faultinject: rank %d injected %s (coll=%s, op_seq %s)",
                  self.rank, kind, n, seq)
        _dump_events_now()
        import sys

        sys.stdout.flush()
        sys.stderr.flush()
        if mode == "spin":
            while True:            # cooperative: only this thread parks
                time.sleep(3600)
        import signal

        os.kill(os.getpid(), signal.SIGSTOP)

    # -- frame verdicts (BtlEndpoint hook) ---------------------------------

    def on_frame(self, peer: int, header: dict) -> Any:
        """Verdict for one outgoing frame: "send" | "drop" | "dup" |
        ("delay", ms).  Called on the BTL send path; must stay cheap."""
        if not self._frame_acts:
            return "send"
        is_ft = header.get("t") == "ft"
        ident = None
        for a in self._frame_acts:
            if a.scope == "ft" and not is_ft:
                continue
            if ident is None:
                ident = _frame_ident(header)
            if _u01(self.seed, self.rank, peer, ident, a.kind) < a.prob:
                # p rides along so a replay checker recomputes the
                # verdict against the action's own threshold
                self._record(a.kind, peer=peer, frame=ident, p=a.prob)
                if a.kind == "delay":
                    return ("delay", a.delay_ms)
                return a.kind
        return "send"

    def _record(self, kind: str, **info) -> None:
        ev = {"kind": kind, "rank": self.rank, **info}
        with self._lock:
            self.events.append(ev)
        from ompi_tpu.mpi import trace as trace_mod

        if trace_mod.active:
            trace_mod.instant("faultinject", kind, rank=self.rank, **info)


_lock = threading.Lock()
_injectors: dict[int, Injector] = {}
_parsed: Optional[list[_Action]] = None
_dump_armed = False


def injector_for(rank: int) -> Optional[Injector]:
    """The rank's injector, or None when no plan is armed.  Safe to call
    from transport constructors — parsing happens once per process."""
    text = plan_text()
    if not text:
        return None
    global _parsed, _dump_armed
    with _lock:
        inj = _injectors.get(rank)
        if inj is not None:
            return inj
        if _parsed is None:
            try:
                _parsed = parse_plan(text)
            except ValueError as e:
                _log.error("faultinject: bad plan %r: %s (chaos disabled)",
                           text, e)
                _parsed = []
        inj = Injector(rank, _parsed, plan_seed())
        _injectors[rank] = inj
        if not _dump_armed and os.environ.get(ENV_LOG_DIR):
            _dump_armed = True
            atexit.register(_dump_events_now)
        return inj


def step(rank: Optional[int] = None) -> None:
    """Application step marker (soak apps call this once per iteration);
    fires kill@step triggers.  With rank=None every installed injector
    in this process advances (single-rank processes have exactly one)."""
    with _lock:
        injs = (list(_injectors.values()) if rank is None
                else [i for i in (_injectors.get(rank),) if i is not None])
    for inj in injs:
        inj.step()


def _daemon_die(vpid: int) -> None:
    import signal

    _log.emit("faultinject: daemon %d injected SIGKILL", vpid)
    os.kill(os.getpid(), signal.SIGKILL)


def arm_daemon(vpid: int) -> None:
    """orted side: a plan entry ``daemon=<vpid>:kill@t=<sec>`` arms a
    self-SIGKILL — the injected silent host death."""
    text = plan_text()
    if not text:
        return
    try:
        actions = parse_plan(text)
    except ValueError:
        return
    for a in actions:
        if a.kind == "daemon_kill" and a.vpid == vpid \
                and a.at_time is not None:
            t = threading.Timer(a.at_time, _daemon_die, args=(vpid,))
            t.daemon = True
            t.start()


def arm_daemon_launch(vpid: int, env: dict) -> None:
    """orted side, at app launch: arm ``daemon=<vpid>:kill@reg=N`` —
    the barrier-keyed variant of the daemon kill.  A watcher thread
    polls the job's PMIx server (URI from the launch env) until N
    ranks' current lives have registered AND are READY (sent the
    init-complete notice — registration alone says the interpreters
    are up, but ranks can still be seconds deep in init's modex fence
    or first barrier on a loaded box), waits the entry's ``after``
    grace, then SIGKILLs the daemon.  Keyed on runtime barriers
    instead of wall-clock so the kill cannot land mid-init on a slow
    box (the midtree-kill chaos class's old t=6–8 s flake)."""
    text = plan_text()
    if not text:
        return
    try:
        actions = parse_plan(text)
    except ValueError:
        return
    from ompi_tpu.runtime import pmix as pmix_mod

    uri = (env or {}).get(pmix_mod.ENV_URI)
    if not uri:
        return
    for a in actions:
        if a.kind != "daemon_kill" or a.vpid != vpid or a.at_reg is None:
            continue

        def watch(need: int = a.at_reg, grace: float = a.after) -> None:
            deadline = time.monotonic() + 120.0
            while time.monotonic() < deadline:
                state = pmix_mod.query_regstate(uri)
                if state is not None and state[0] >= need \
                        and state[2] >= need:
                    _log.emit("faultinject: daemon %d reg barrier "
                              "(%d ranks registered + ready) cleared; "
                              "killing in %.1fs", vpid, need, grace)
                    time.sleep(grace)
                    _daemon_die(vpid)
                    return
                time.sleep(0.2)

        t = threading.Thread(target=watch, daemon=True,
                             name=f"faultinject-reg-{vpid}")
        t.start()


def events(rank: Optional[int] = None) -> list[dict]:
    """Fired-fault log (for replay-determinism assertions)."""
    with _lock:
        if rank is not None:
            inj = _injectors.get(rank)
            return list(inj.events) if inj is not None else []
        out: list[dict] = []
        for inj in _injectors.values():
            out.extend(inj.events)
        return out


def _dump_events_now() -> None:
    """Write every injector's fired-event log to OMPI_TPU_FAULT_LOG_DIR
    (one JSON per rank) — called at exit AND right before an injected
    kill (atexit does not run under os._exit)."""
    log_dir = os.environ.get(ENV_LOG_DIR)
    if not log_dir:
        return
    with _lock:
        injs = list(_injectors.values())
    # a respawned incarnation gets its own file: overwriting the first
    # life's log would erase exactly the kill event a replay check needs
    life = int(os.environ.get("OMPI_TPU_RESTART") or 0)
    suffix = f"_life{life}" if life else ""
    for inj in injs:
        path = os.path.join(log_dir,
                            f"faults_rank{inj.rank}{suffix}.json")
        try:
            with open(path, "w") as fh:
                json.dump({"rank": inj.rank, "seed": inj.seed,
                           "plan": plan_text(), "events": inj.events,
                           "ts": time.time()}, fh)
        except OSError as e:
            _log.error("faultinject: event dump to %s failed: %r", path, e)


def reset() -> None:
    """Drop all per-process injector state (tests re-arm with new plans)."""
    global _parsed
    with _lock:
        _injectors.clear()
        _parsed = None
    # the coll dispatcher caches its per-rank injector resolution —
    # a re-armed plan must be re-resolved, not read through stale Nones
    try:
        from ompi_tpu.mpi import coll as _coll

        _coll._fi_cache.clear()
    except Exception:  # noqa: BLE001 — tests without the coll layer
        pass
