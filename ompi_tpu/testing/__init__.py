"""Testing support — deterministic chaos (faultinject) and harness glue.

Nothing here runs unless explicitly armed (a fault plan in the
environment / MCA vars); importing this package from production paths is
free.
"""
