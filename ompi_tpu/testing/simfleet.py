"""simfleet — the thousand-rank control plane in one process.

Generalizes the fake-host machinery into a simulated fleet: hundreds of
in-process lightweight daemons — each a real :class:`rml.RmlNode` plus
the orted control-protocol subset (register / wire / heartbeat / orphan
/ reparent / adopt / doctor / metrics) — carrying thousands of STUB
ranks that never start an interpreter.  The HNP side is the real
:class:`MultiHostLauncher` with the real loss-epoch reparenter, the real
heartbeat sweep and the real metrics fan-in: only the daemon processes
and the rank interpreters are simulated, so a 100-daemon / 1000-rank
world exercises the genuine control-plane code paths inside a CI box.

Correlated-failure injectors:

- :meth:`SimFleet.rack_kill` — N daemons (mid-tree included) die in one
  tick: every node socket closes at once, racing link EOFs, orphan
  reports and heartbeat expiries into the HNP exactly like a rack
  losing power.
- :meth:`SimFleet.partition` — a subtree drops ALL frames for T seconds
  via the :attr:`RmlNode.frame_gate` seam.  Sockets stay alive (no EOF,
  no RST): a true network partition, which the heartbeat timeout — not
  the lifeline rule — must adjudicate.
- :meth:`SimFleet.metrics_storm` — every daemon pushes a full metrics
  snapshot in the same wave (deepest level first, so each hop folds its
  children's payloads), the HNP-uplink-overload case the
  ``metrics_agg_budget_rows`` shed-and-count valve bounds.

Accounting the tests assert on rides on the launcher itself
(``reparent_epochs_total`` / ``reparent_orphans_total`` /
``reparent_frames_total``, ``MetricsAggregate.stats()``,
``HeartbeatMonitor.scanned_total``) plus the fleet-side convergence
clock (:meth:`SimFleet.wait_adopted`) and the false-positive audit
(:meth:`SimFleet.false_positive_rank_deaths`).
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Optional

from ompi_tpu.core import output
from ompi_tpu.core.config import var_registry
from ompi_tpu.runtime import metrics as metrics_mod
from ompi_tpu.runtime import rml
from ompi_tpu.runtime.job import AppContext, Job, Node, Proc, ProcState

__all__ = ["SimDaemon", "SimFleet"]

_log = output.get_stream("simfleet")


def _depth(vpid: int) -> int:
    """Tree depth of a vpid (hops to the HNP) — storm waves push
    deepest-first so every hop's payload includes its children's."""
    d = 0
    v = vpid
    while v:
        parent = rml.tree_parent(v)
        v = 0 if parent is None else parent
        d += 1
    return d


class SimDaemon:
    """One simulated daemon: a real RmlNode speaking the orted control
    protocol, no subprocess and no rank interpreters.

    Mirrors orted's handshakes faithfully (register → wire → ready,
    heartbeats, ORPHANED → REPARENT/ADOPT → REPARENT_ACK, doctor
    captures pre-aggregated by ``doctor_rows_per_daemon``) with one
    deliberate difference: where a real orted calls ``os._exit`` (lost
    lifeline under a non-tolerant policy, adoption timeout) a SimDaemon
    records ``self.failed`` and closes its node — the harness must
    observe the death, not die with it.
    """

    def __init__(self, fleet: "SimFleet", vpid: int, hnp_uri: str,
                 ranks: list[tuple[int, int]]) -> None:
        self.fleet = fleet
        self.vpid = vpid
        self.ranks = list(ranks)          # [(jobid, rank), ...] stubs
        self.hostname = f"fleet{vpid:04d}"
        self.failed: Optional[str] = None  # why this daemon gave up
        self.killed = False                # harness-injected death
        self._stop = threading.Event()
        self._done = threading.Event()
        self._reparent_ok = False
        self._reparented = threading.Event()
        self.wired = threading.Event()
        self.adoptions_total = 0           # REPARENT orders taken
        self.orphan_reports_total = 0      # ORPHANED frames sent
        self._push_n = 0
        self._mlock = threading.Lock()
        self._pending: dict = {}           # children's TAG_METRICS hops
        self._rng = random.Random(fleet.seed * 100003 + vpid)
        node = self.node = rml.RmlNode(vpid)
        node.register_recv(rml.TAG_WIRE, self._on_wire)
        node.register_recv(rml.TAG_SHUTDOWN, self._on_shutdown)
        node.register_recv(rml.TAG_REPARENT, self._on_reparent)
        node.register_recv(rml.TAG_ADOPT, self._on_adopt)
        node.register_recv(rml.TAG_DOCTOR, self._on_doctor)
        node.register_recv(rml.TAG_METRICS, self._on_child_metrics)
        # control frames a stub world carries no ranks for: accept and
        # drop (the xcast relay to children happens below the handler,
        # so a mid-tree stub still forwards them)
        for tag in (rml.TAG_PROC_FAILED, rml.TAG_KILL, rml.TAG_LAUNCH,
                    rml.TAG_STDIN, rml.TAG_RESPAWN, rml.TAG_KILL_RANK,
                    rml.TAG_SIGNAL_RANK, rml.TAG_TIMELINE,
                    rml.TAG_STATS):
            node.register_recv(tag, self._on_noop)
        node.on_peer_lost = self._on_lifeline_lost
        self._boot = node.dial_bootstrap(hnp_uri)
        node.fallback_up = self._boot
        node.send_direct(self._boot, rml.TAG_REGISTER,
                         (vpid, node.uri, self.hostname))
        threading.Thread(target=self._start_beats,
                         name=f"fleet-hb-{vpid}", daemon=True).start()

    # -- liveness ---------------------------------------------------------

    @property
    def alive(self) -> bool:
        return (not self.killed and self.failed is None
                and not self._done.is_set())

    def _fail(self, why: str) -> None:
        """Where orted would os._exit: record the reason and go dark."""
        if self.failed is None and not self.killed:
            self.failed = why
            _log.verbose(1, "simdaemon %d failed: %s", self.vpid, why)
        self._stop.set()
        self._done.set()
        try:
            self.node.close()
        except OSError:
            pass

    def kill(self) -> None:
        """Harness-injected SIGKILL: every socket closes at once — the
        parent sees a child EOF, the children see the lifeline EOF, the
        HNP sees the boot link EOF.  No goodbye frames."""
        self.killed = True
        self._stop.set()
        self._done.set()
        try:
            self.node.close()
        except OSError:
            pass

    # -- boot / wire (mirrors orted._on_wire) -----------------------------

    def _start_beats(self) -> None:
        if self.node.wait_parent(60.0) and not self._stop.is_set():
            rml.start_heartbeats(self.node, self._stop)

    def _on_wire(self, origin: int, payload: Any) -> None:
        if isinstance(payload, dict):
            children = payload["children"]
            self._reparent_ok = bool(payload.get("reparent"))
        else:
            children = payload
        try:
            self.node.dial_children([tuple(c) for c in children])
        except OSError as e:
            self._fail(f"wiring children failed: {e!r}")
            return
        if not self.node.wait_parent(timeout=30.0):
            self._fail("parent never dialed in")
            return
        self.wired.set()
        self.node.send_up(rml.TAG_DAEMON_READY, self.vpid)

    def _on_shutdown(self, origin: int, payload: Any) -> None:
        self._done.set()
        self._stop.set()
        threading.Thread(target=self.node.close, daemon=True).start()

    def _on_noop(self, origin: int, payload: Any) -> None:
        return

    # -- lifeline / reparent (mirrors orted's orphan machinery) -----------

    def _on_lifeline_lost(self, peer: int) -> None:
        if peer not in (0, self.node.parent_vpid):
            return  # a child died; its own subtree reports it
        if self._done.is_set() or self._stop.is_set():
            return
        if peer != 0 and self._reparent_ok:
            self._reparented.clear()
            self.orphan_reports_total += 1
            try:
                self.node.send_direct(self._boot, rml.TAG_ORPHANED,
                                      (self.vpid, peer))
            except OSError:
                pass  # boot link also dead: the HNP's detectors take it
            threading.Thread(target=self._orphan_watch,
                             daemon=True).start()
            return
        self._fail(f"lifeline to vpid {peer} lost")

    def _orphan_watch(self) -> None:
        base = float(var_registry.get("rml_reparent_timeout") or 10.0)
        timeout = rml.scaled_timeout(base, self.fleet.world)
        if self._reparented.wait(timeout) or self._done.is_set():
            return
        self._fail(f"orphaned and no adoption within {timeout:.1f}s")

    def _on_reparent(self, origin: int, payload: Any) -> None:
        new_parent = int(payload)
        self.adoptions_total += 1
        self.node.retarget_parent(new_parent)

        def rewire() -> None:
            if not self.node.wait_parent(timeout=30.0):
                if not self._done.is_set():
                    self._fail(f"adopter {new_parent} never dialed in")
                return
            self._reparented.set()
            try:
                self.node.send_up(rml.TAG_REPARENT_ACK,
                                  (self.vpid, new_parent))
            except (ConnectionError, OSError):
                pass

        threading.Thread(target=rewire, daemon=True).start()

    def _on_adopt(self, origin: int, payload: Any) -> None:
        children = [tuple(c) for c in payload]

        def dial() -> None:
            try:
                self.node.dial_children(children)
            except OSError as e:
                _log.verbose(1, "simdaemon %d adopt dial failed: %r",
                             self.vpid, e)

        threading.Thread(target=dial, daemon=True).start()

    # -- doctor (hierarchical capture, O(hosts) at the HNP) ---------------

    def _on_doctor(self, origin: int, payload: Any) -> None:
        threading.Thread(target=self._doctor_reply, args=(payload,),
                         daemon=True).start()

    def _doctor_reply(self, epoch: Any) -> None:
        from ompi_tpu.runtime import doctor

        limit = int(var_registry.get("doctor_rows_per_daemon") or 0)
        by_job: dict[int, list[int]] = {}
        for jobid, rank in self.ranks:
            by_job.setdefault(jobid, []).append(rank)
        rows: list[dict] = []
        for jobid, rks in by_job.items():
            job_rows = [self._stub_capture(jobid, r) for r in rks]
            kept, summary = doctor.summarize_rows(job_rows, limit)
            if summary is not None:
                summary["jobid"] = jobid
                summary["vpid"] = self.vpid
                kept.append(summary)
            rows.extend(kept)
        try:
            self.node.send_up(rml.TAG_DOCTOR_REPLY,
                              (self.vpid, epoch, rows))
        except (ConnectionError, OSError):
            pass

    def _stub_capture(self, jobid: int, rank: int) -> dict:
        """A synthetic per-rank capture: every stub is mid-allreduce at
        the fleet's shared op_seq — the all-healthy shape, so any
        no_response / stuck rows in a collected doc are real signal."""
        return {"jobid": jobid, "rank": rank, "pid": 0, "stuck": 0,
                "cur": {"cid": 0, "seq": self.fleet.op_seq,
                        "kind": "allreduce", "age_s": 0.01,
                        "done": False},
                "collrec": []}

    # -- metrics uplink ---------------------------------------------------

    def _on_child_metrics(self, origin: int, payload: Any) -> None:
        with self._mlock:
            metrics_mod.merge_hop(self._pending, payload)

    def push_metrics(self, full: bool = False) -> None:
        """One uplink push: this daemon's stub-rank counters merged over
        whatever its children pushed since the last wave (the per-hop
        aggregation a real orted's collector thread does).  ``full``
        fattens each row into a whole-snapshot push — the storm shape."""
        if not self.alive:
            return
        now = time.time()
        self._push_n += 1
        with self._mlock:
            payload, self._pending = self._pending, {}
        for jobid, rank in self.ranks:
            row: dict[str, float] = {
                "fleet_steps_total": float(self._push_n),
                "fleet_push_datagrams_total": float(self._push_n),
            }
            if full:
                row["fleet_bytes_total"] = float(
                    self._rng.randrange(1 << 20))
                for i in range(14):
                    row[f"fleet_snapshot_pad_{i}_total"] = float(i)
            payload.setdefault(jobid, {})[rank] = [now, row]
        try:
            self.node.send_hop(rml.TAG_METRICS, payload)
        except (ConnectionError, OSError):
            with self._mlock:  # like UDP loss: counters are cumulative
                metrics_mod.merge_hop(self._pending, payload)


class SimFleet:
    """A simulated N-daemon / M-stub-rank world around the REAL HNP.

    Usage::

        fleet = SimFleet(n_daemons=100, n_ranks=1000, seed=7)
        fleet.start()
        try:
            fleet.rack_kill(fleet.rack(16))
            dt = fleet.wait_adopted(timeout=30.0)
            assert fleet.false_positive_rank_deaths() == []
        finally:
            fleet.stop()
    """

    def __init__(self, n_daemons: int, n_ranks: int, *,
                 errmgr: str = "notify", seed: int = 0,
                 hb_period: float = 0.0, hb_timeout: float = 3.0,
                 loss_window: float = 0.25,
                 doctor_rows: Optional[int] = None,
                 agg_budget_rows: Optional[int] = None) -> None:
        if n_ranks % n_daemons:
            raise ValueError("n_ranks must divide evenly over n_daemons")
        self.n_daemons = n_daemons
        self.n_ranks = n_ranks
        self.world = n_daemons + 1       # + the HNP, for timeout scaling
        self.seed = seed
        self.op_seq = 1 + seed % 97      # shared stub collective seq
        self.daemons: dict[int, SimDaemon] = {}
        self.launcher = None
        self.job: Optional[Job] = None
        self._killed_vpids: set[int] = set()
        self._saved_vars: dict[str, Any] = {}
        self._want_vars = {
            "errmgr_": errmgr,
            "rml_heartbeat_period": hb_period,
            "rml_heartbeat_timeout": hb_timeout,
            "plm_loss_epoch_window": loss_window,
        }
        if doctor_rows is not None:
            self._want_vars["doctor_rows_per_daemon"] = doctor_rows
        if agg_budget_rows is not None:
            self._want_vars["metrics_agg_budget_rows"] = agg_budget_rows
        # fleet-side doctor collection (epoch-fenced, like DvmHnp's)
        self._doc_cv = threading.Condition()
        self._doc_epoch = 0
        self._doc_rows: list[dict] = []
        self._doc_seen: set[int] = set()

    # -- lifecycle --------------------------------------------------------

    def start(self, timeout: float = 60.0) -> None:
        from ompi_tpu.runtime.plm import MultiHostLauncher

        for name, val in self._want_vars.items():
            self._saved_vars[name] = var_registry.get(name)
            var_registry.set(name, val)
        rpd = self.n_ranks // self.n_daemons
        nodes = [Node(name=f"fleet{i + 1:04d}", slots=rpd)
                 for i in range(self.n_daemons)]
        app = AppContext(argv=["<fleet-stub>"], np=self.n_ranks)
        job = self.job = Job([app])
        job.nodes = nodes
        job.procs = [Proc(rank=r, node=nodes[r // rpd],
                          state=ProcState.RUNNING, local_rank=r % rpd)
                     for r in range(self.n_ranks)]
        launcher = self.launcher = MultiHostLauncher(plm_name="sim")
        launcher.plm = _FleetPlm(self)
        launcher._persistent = True      # the VM outlives any one job
        # apps are never launched (the ranks are stubs), so register the
        # job for the exit/doctor/metrics routers by hand
        launcher._jobs_by_id[job.jobid] = job
        old_timeout = var_registry.get("plm_daemon_timeout")
        var_registry.set("plm_daemon_timeout",
                         max(float(old_timeout or 30.0), timeout))
        try:
            if not launcher._vm_up(job):
                raise RuntimeError(
                    f"fleet VM failed to come up: {job.abort_reason}")
        finally:
            var_registry.set("plm_daemon_timeout", old_timeout)
        launcher.rml.register_recv(rml.TAG_DOCTOR_REPLY,
                                   self._on_doctor_reply)

    def _spawn(self, job: Job, hnp_uri: str) -> None:
        """_FleetPlm's spawn hook: bring up every SimDaemon in-process
        (vpid = pool index + 1, exactly like the subprocess plms)."""
        for i, node in enumerate(job.nodes):
            vpid = i + 1
            ranks = [(job.jobid, p.rank) for p in job.procs_on(node)]
            self.daemons[vpid] = SimDaemon(self, vpid, hnp_uri, ranks)

    def stop(self) -> None:
        for d in self.daemons.values():
            d._done.set()   # teardown, not a failure to diagnose
        if self.launcher is not None and self.launcher.rml is not None:
            self.launcher._teardown_vm()
        for d in self.daemons.values():
            d._stop.set()
            try:
                d.node.close()
            except OSError:
                pass
        for name, val in self._saved_vars.items():
            var_registry.set(name, val)
        self._saved_vars.clear()

    # -- failure injectors ------------------------------------------------

    def rack(self, n: int, *, mid_tree: bool = True) -> list[int]:
        """Pick a deterministic 'rack' of n daemon vpids to kill: a
        contiguous vpid band starting mid-tree (so victims include
        interior daemons with live children — the reparent-storm case),
        never vpid 1 alone at the root of everything."""
        if n > self.n_daemons:
            raise ValueError("rack bigger than the fleet")
        start = max(2, self.n_daemons // 4) if mid_tree else 1
        start = min(start, self.n_daemons - n + 1)
        return list(range(start, start + n))

    def rack_kill(self, vpids: list[int]) -> None:
        """Correlated loss: every named daemon dies in the same tick."""
        for v in vpids:
            self._killed_vpids.add(v)
        for v in vpids:
            self.daemons[v].kill()

    def partition(self, vpids: list[int]) -> None:
        """Fence a set of daemons: ALL frames (both directions) drop,
        sockets stay alive.  Call :meth:`heal` to lift it."""
        for v in vpids:
            self.daemons[v].node.frame_gate = lambda _d, _t: False

    def heal(self, vpids: list[int]) -> None:
        for v in vpids:
            self.daemons[v].node.frame_gate = None

    def metrics_storm(self, full: bool = True,
                      settle: float = 0.05) -> None:
        """Every live daemon pushes in one wave, deepest tree level
        first so each hop's push folds its children's payloads — the
        worst-case HNP fan-in the shed-and-count budget must bound."""
        by_depth: dict[int, list[SimDaemon]] = {}
        for d in self.daemons.values():
            if d.alive:
                by_depth.setdefault(_depth(d.vpid), []).append(d)
        for depth in sorted(by_depth, reverse=True):
            for d in by_depth[depth]:
                d.push_metrics(full=full)
            time.sleep(settle)

    # -- doctor collection (epoch-fenced, O(hosts) fan-in) ----------------

    def _on_doctor_reply(self, origin: int, payload: Any) -> None:
        try:
            vpid, epoch, rows = payload
        except (TypeError, ValueError):
            return
        with self._doc_cv:
            if epoch != self._doc_epoch or vpid in self._doc_seen:
                return  # stale epoch or duplicate relay
            self._doc_seen.add(int(vpid))
            self._doc_rows.extend(rows)
            self._doc_cv.notify_all()

    def collect_doctor(self, timeout: float = 8.0) -> tuple[list[dict],
                                                            set[int]]:
        """One fleet-wide doctor capture: xcast the epoch, gather the
        per-daemon pre-aggregated rows.  Returns (rows, replied_vpids);
        rows is O(hosts × doctor_rows_per_daemon), not O(ranks)."""
        live = {v for v, d in self.daemons.items() if d.alive}
        with self._doc_cv:
            self._doc_epoch += 1
            epoch = self._doc_epoch
            self._doc_rows = []
            self._doc_seen = set()
        self.launcher.rml.xcast(rml.TAG_DOCTOR, epoch)
        with self._doc_cv:
            self._doc_cv.wait_for(lambda: self._doc_seen >= live,
                                  timeout=timeout)
            return list(self._doc_rows), set(self._doc_seen)

    # -- convergence / audit ----------------------------------------------

    def converged(self) -> bool:
        """Every injected death detected, every surviving daemon wired
        to a LIVE parent, nobody failed on its own."""
        dead = set(self.launcher._dead_daemons)
        if not self._killed_vpids <= dead:
            return False  # a corpse the HNP hasn't noticed yet
        for vpid, d in self.daemons.items():
            if not d.alive:
                if not d.killed:
                    return False  # died on its own — never converges
                continue
            if not d.node.parent_wired.is_set():
                return False
            parent = d.node.parent_vpid
            if parent is None or parent in dead:
                return False
            if parent != 0 and not self.daemons[parent].alive:
                return False
        return True

    def wait_adopted(self, timeout: float = 30.0) -> Optional[float]:
        """Block until the fleet converges; returns the elapsed seconds
        (the convergence clock fleet_bench records) or None on timeout."""
        t0 = time.monotonic()
        deadline = t0 + timeout
        while time.monotonic() < deadline:
            if self.converged():
                return time.monotonic() - t0
            time.sleep(0.02)
        return None

    def false_positive_rank_deaths(self) -> list[int]:
        """Ranks the control plane declared dead whose daemon the
        harness never killed — must be empty after any injected loss."""
        out = []
        for p in self.job.procs:
            if p.state is ProcState.ABORTED or p.daemon_lost:
                vpid = self.launcher._node_vpid(p.node)
                if vpid not in self._killed_vpids:
                    out.append(p.rank)
        return sorted(out)

    def live_daemons(self) -> int:
        return sum(1 for d in self.daemons.values() if d.alive)

    def self_failed(self) -> dict[int, str]:
        """Daemons that gave up on their own (adoption timeout, wire
        failure) — any entry here is a containment bug."""
        return {v: d.failed for v, d in self.daemons.items()
                if d.failed is not None}

    def stats(self) -> dict:
        """The control-plane cost counters fleet_bench records."""
        la = self.launcher
        agg = la.metrics_agg.stats()
        hb = la._hb_monitor
        return {
            "world": self.world,
            "n_ranks": self.n_ranks,
            "reparent_epochs_total": la.reparent_epochs_total,
            "reparent_orphans_total": la.reparent_orphans_total,
            "reparent_frames_total": la.reparent_frames_total,
            "agg_merges_total": agg.get("merges_total", 0),
            "agg_merge_ns_total": agg.get("merge_ns_total", 0),
            "agg_sheds_total": agg.get("sheds_total", 0),
            "agg_shed_rows_total": agg.get("shed_rows_total", 0),
            "hb_scanned_total": 0 if hb is None else hb.scanned_total,
            "hb_ticks_total": 0 if hb is None else hb.ticks_total,
            "live_daemons": self.live_daemons(),
        }


class _FleetPlm:
    """The plm seam: spawn_daemons brings up in-process SimDaemons and
    returns no Popen handles (every Popen consumer tolerates an empty
    list).  NAME is 'sim' so the launcher advertises a loopback HNP
    address, same as the subprocess sim plm."""

    NAME = "sim"

    def __init__(self, fleet: SimFleet) -> None:
        self.fleet = fleet

    def spawn_daemons(self, job: Job, hnp_uri: str) -> list:
        self.fleet._spawn(job, hnp_uri)
        return []
