"""ompi_tpu — a TPU-native communication framework with Open MPI's capabilities.

A brand-new framework (not a port) providing the MPI and OpenSHMEM programming
models, re-designed for TPU hardware: hot-path collectives lower to XLA
collectives (``jax.lax.psum``/``all_gather``/``ppermute``/``all_to_all``) over
an ICI device mesh with HBM-resident buffers and zero host staging, while a
host-side process runtime provides real multi-rank MPI matching semantics
(tag/source wildcards, unexpected queues) the way the reference's ob1 PML does.

Layer map (mirrors the reference's OPAL/ORTE/OMPI/OSHMEM stack — see
/root/reference layout and SURVEY.md §1):

- ``ompi_tpu.core``     ≈ OPAL  — component registry (MCA), typed config vars,
                                   logging/diagnostics, serialization, buffers.
- ``ompi_tpu.runtime``  ≈ ORTE  — job state machine, resource allocation and
                                   rank mapping, launcher, failure policy.
- ``ompi_tpu.mpi``      ≈ OMPI  — communicators, datatypes, ops, requests,
                                   point-to-point, collectives, RMA, IO.
- ``ompi_tpu.shmem``    ≈ OSHMEM — symmetric heap, put/get, collectives.
- ``ompi_tpu.parallel``          — TPU-first sharding/mesh helpers, sequence
                                   parallelism (ring attention, all-to-all).
- ``ompi_tpu.models``            — flagship models built on the framework.
- ``ompi_tpu.ops``               — pallas kernels for hot ops.

Two execution modes share one API:

1. **Device SPMD mode** — ranks are devices of a ``jax.sharding.Mesh``;
   communicator operations called inside ``shard_map``/``jit`` trace to XLA
   collectives and compile to ICI transfers (the ``coll/xla`` + ``btl/tpu``
   path of BASELINE.json's north star).
2. **Host process mode** — one OS process per rank (launched by ``tpurun``),
   host buffers move over sockets/shared memory with full MPI matching
   semantics (the reference's ob1/BTL path, reimagined).
"""

from ompi_tpu.core.config import var_registry, register_var, get_var
from ompi_tpu.core.mca import Framework, Component, framework_registry

__version__ = "0.1.0"

# Lazy top-level MPI-like API (heavy imports deferred; jax only loads when the
# device path is actually used).
_LAZY = {
    "init": ("ompi_tpu.mpi.runtime", "init"),
    "finalize": ("ompi_tpu.mpi.runtime", "finalize"),
    "initialized": ("ompi_tpu.mpi.runtime", "initialized"),
    "wtime": ("ompi_tpu.mpi.runtime", "wtime"),
    "wtick": ("ompi_tpu.mpi.runtime", "wtick"),
    "abort": ("ompi_tpu.mpi.runtime", "abort"),
    "get_processor_name": ("ompi_tpu.mpi.runtime", "get_processor_name"),
    "get_version": ("ompi_tpu.mpi.runtime", "get_version"),
    "get_library_version": ("ompi_tpu.mpi.runtime",
                            "get_library_version"),
    "error_string": ("ompi_tpu.mpi.constants", "error_string"),
    "error_class": ("ompi_tpu.mpi.constants", "error_class"),
    "add_error_class": ("ompi_tpu.mpi.constants", "add_error_class"),
    "add_error_code": ("ompi_tpu.mpi.constants", "add_error_code"),
    "add_error_string": ("ompi_tpu.mpi.constants", "add_error_string"),
    "GeneralizedRequest": ("ompi_tpu.mpi.request", "GeneralizedRequest"),
    "grequest_start": ("ompi_tpu.mpi.request", "grequest_start"),
    "get_count": ("ompi_tpu.mpi.request", "get_count"),
    "get_elements": ("ompi_tpu.mpi.request", "get_elements"),
    "reduce_local": ("ompi_tpu.mpi.op", "reduce_local"),
    "op_commutative": ("ompi_tpu.mpi.op", "op_commutative"),
    "publish_name": ("ompi_tpu.mpi.dpm", "publish_name"),
    "unpublish_name": ("ompi_tpu.mpi.dpm", "unpublish_name"),
    "lookup_name": ("ompi_tpu.mpi.dpm", "lookup_name"),
    "COMM_WORLD": ("ompi_tpu.mpi.runtime", "COMM_WORLD"),
    "COMM_SELF": ("ompi_tpu.mpi.runtime", "COMM_SELF"),
    "Communicator": ("ompi_tpu.mpi.comm", "Communicator"),
    "Group": ("ompi_tpu.mpi.group", "Group"),
    "Datatype": ("ompi_tpu.mpi.datatype", "Datatype"),
    "Op": ("ompi_tpu.mpi.op", "Op"),
    "Request": ("ompi_tpu.mpi.request", "Request"),
    "Status": ("ompi_tpu.mpi.request", "Status"),
    "PersistentRequest": ("ompi_tpu.mpi.request", "PersistentRequest"),
    "wait_all": ("ompi_tpu.mpi.request", "wait_all"),
    "wait_any": ("ompi_tpu.mpi.request", "wait_any"),
    "wait_some": ("ompi_tpu.mpi.request", "wait_some"),
    "test_all": ("ompi_tpu.mpi.request", "test_all"),
    "test_any": ("ompi_tpu.mpi.request", "test_any"),
    "test_some": ("ompi_tpu.mpi.request", "test_some"),
    "start_all": ("ompi_tpu.mpi.request", "start_all"),
    "buffer_attach": ("ompi_tpu.mpi.pml", "buffer_attach"),
    "buffer_detach": ("ompi_tpu.mpi.pml", "buffer_detach"),
    "ANY_SOURCE": ("ompi_tpu.mpi.constants", "ANY_SOURCE"),
    "ANY_TAG": ("ompi_tpu.mpi.constants", "ANY_TAG"),
    "PROC_NULL": ("ompi_tpu.mpi.constants", "PROC_NULL"),
    "UNDEFINED": ("ompi_tpu.mpi.constants", "UNDEFINED"),
    "IN_PLACE": ("ompi_tpu.mpi.constants", "IN_PLACE"),
    "SUM": ("ompi_tpu.mpi.op", "SUM"),
    "PROD": ("ompi_tpu.mpi.op", "PROD"),
    "MAX": ("ompi_tpu.mpi.op", "MAX"),
    "MIN": ("ompi_tpu.mpi.op", "MIN"),
    "LAND": ("ompi_tpu.mpi.op", "LAND"),
    "LOR": ("ompi_tpu.mpi.op", "LOR"),
    "BAND": ("ompi_tpu.mpi.op", "BAND"),
    "BOR": ("ompi_tpu.mpi.op", "BOR"),
    "MAXLOC": ("ompi_tpu.mpi.op", "MAXLOC"),
    "MINLOC": ("ompi_tpu.mpi.op", "MINLOC"),
    "device_world": ("ompi_tpu.mpi.device_comm", "device_world"),
    "Window": ("ompi_tpu.mpi.osc", "Window"),
    "REPLACE": ("ompi_tpu.mpi.op", "REPLACE"),
    "NO_OP": ("ompi_tpu.mpi.op", "NO_OP"),
    "DeviceCommunicator": ("ompi_tpu.mpi.device_comm", "DeviceCommunicator"),
}


# Names that are rebound at runtime (init() replaces them) must be resolved on
# every access, never cached in this module's globals.
_MUTABLE = {"COMM_WORLD", "COMM_SELF"}


def __getattr__(name):
    try:
        mod_name, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(f"module 'ompi_tpu' has no attribute {name!r}") from None
    import importlib

    mod = importlib.import_module(mod_name)
    value = getattr(mod, attr)
    if name not in _MUTABLE:
        globals()[name] = value
    return value


def __dir__():
    return sorted(set(globals()) | set(_LAZY))
