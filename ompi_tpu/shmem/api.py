"""OpenSHMEM host API (≈ oshmem/shmem/c/: shmem_init, shmem_put,
shmem_long_max_to_all, ...).

The symmetric heap (≈ oshmem/mca/memheap) is a registry of collectively
allocated SymmetricArrays; allocation order is the "symmetric address":
every PE's Nth allocation refers to the same logical object, so a PE can
name remote memory by (array, offset) exactly as SHMEM names it by
symmetric address.  The transport (≈ oshmem/mca/spml) is an RMA window per
allocation; collectives (≈ oshmem/mca/scoll/mpi) delegate to the MPI coll
framework.  Atomics (≈ oshmem/mca/atomic) ride the window's fetch/cswap
service.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

import numpy as np

from ompi_tpu.mpi import op as op_mod
from ompi_tpu.mpi.constants import MPIException
from ompi_tpu.mpi.osc import Window

__all__ = [
    "init", "finalize", "my_pe", "n_pes", "barrier_all", "array", "free",
    "put", "get", "broadcast", "collect", "to_all", "atomic_add",
    "atomic_fetch_add", "atomic_cswap", "fence", "quiet", "SymmetricArray",
    "Lock", "set_lock", "test_lock", "clear_lock",
    "broadcast_active", "collect_active", "to_all_active",
]

_state: dict = {"comm": None, "heap": []}
_lock = threading.Lock()

_CMP = {
    "eq": lambda a, b: a == b,
    "ne": lambda a, b: a != b,
    "gt": lambda a, b: a > b,
    "ge": lambda a, b: a >= b,
    "lt": lambda a, b: a < b,
    "le": lambda a, b: a <= b,
}


def init():
    """shmem_init: brings up MPI underneath (the reference requires the
    same — oshmem layers on ompi)."""
    import ompi_tpu

    with _lock:
        if _state["comm"] is None:
            world = ompi_tpu.init()
            _state["comm"] = world.dup(name="SHMEM")
    return _state["comm"]


def _comm():
    if _state["comm"] is None:
        raise MPIException("shmem not initialized (call shmem.init())")
    return _state["comm"]


def finalize() -> None:
    with _lock:
        comm = _state["comm"]
        if comm is None:
            return
        for arr in list(_state["heap"]):
            if arr is not None:
                arr._win.free()
        _state["heap"].clear()
        _state.pop("lock_slabs", None)
        _state["comm"] = None
    import ompi_tpu

    ompi_tpu.finalize()


def my_pe() -> int:
    return _comm().rank


def n_pes() -> int:
    return _comm().size


def barrier_all() -> None:
    _comm().barrier()


class SymmetricArray:
    """A symmetric-heap allocation: same shape/dtype on every PE.

    ``arr[:]`` is the local data (numpy view); remote access goes through
    put/get/atomics with a target PE.
    """

    def __init__(self, shape, dtype, heap_idx: int) -> None:
        self.local = np.zeros(shape, dtype=dtype)
        self.heap_idx = heap_idx
        self._win = Window(_comm(), buffer=self.local.reshape(-1),
                           name=f"sym{heap_idx}")

    @property
    def shape(self):
        return self.local.shape

    @property
    def dtype(self):
        return self.local.dtype

    def __getitem__(self, idx):
        return self.local[idx]

    def __setitem__(self, idx, value):
        self.local[idx] = value

    # -- one-sided ops (≈ shmem_put/get/atomics) --------------------------

    def put(self, target_pe: int, data, offset: int = 0) -> None:
        self._win.put(target_pe, np.asarray(data).reshape(-1), offset)

    def iput(self, target_pe: int, data, target_stride: int,
             offset: int = 0) -> None:
        """Strided put (≈ shmem_iput): element i lands at
        ``offset + i*target_stride`` — one wire message, one counted op."""
        self._win.put_strided(target_pe, np.asarray(data).reshape(-1),
                              offset, target_stride)

    def get(self, target_pe: int, count: Optional[int] = None,
            offset: int = 0) -> np.ndarray:
        count = count if count is not None else self.local.size - offset
        return self._win.get(target_pe, count, offset)

    def iget(self, target_pe: int, count: int, source_stride: int,
             offset: int = 0) -> np.ndarray:
        """Strided get (≈ shmem_iget): element i comes from
        ``offset + i*source_stride`` — one covering-range round trip,
        strided locally."""
        if source_stride < 1:
            raise MPIException(f"iget needs stride >= 1, got {source_stride}")
        if count == 0:
            return np.zeros(0, dtype=self.dtype)
        span = (count - 1) * source_stride + 1
        return self._win.get(target_pe, span, offset)[::source_stride].copy()

    def wait_until(self, cmp: str, value, offset: int = 0,
                   timeout: Optional[float] = None) -> None:
        """≈ shmem_wait_until: block until the *local* element at ``offset``
        satisfies ``cmp`` against ``value``.  Remote puts/atomics land via
        the window service, which signals the same condition variable —
        so this is a real sleep, not a spin."""
        pred = _CMP.get(cmp)
        if pred is None:
            raise MPIException(
                f"wait_until cmp must be one of {sorted(_CMP)}, got {cmp!r}")
        win = self._win
        flat = self.local.reshape(-1)
        with win._cv:
            ok = win._cv.wait_for(
                lambda: pred(flat[offset], value) or win._service_dead,
                timeout=timeout)
            if not ok:
                raise TimeoutError(
                    f"wait_until({cmp}, {value}) timed out at offset {offset}")
            if not pred(flat[offset], value):
                raise MPIException(
                    "wait_until: window service stopped before the "
                    "condition held")

    def quiet(self) -> None:
        """≈ shmem_quiet: my outstanding puts to all PEs are complete."""
        for pe in range(n_pes()):
            if pe != my_pe():
                self._win.flush(pe)

    def barrier(self) -> None:
        """Window-level fence (completes all pending ops everywhere)."""
        self._win.fence()


def array(shape, dtype=np.float64) -> SymmetricArray:
    """shmem_malloc: collective allocation on every PE."""
    with _lock:
        idx = len(_state["heap"])
        arr = SymmetricArray(shape, dtype, idx)
        _state["heap"].append(arr)
    return arr


def free(arr: SymmetricArray) -> None:
    """shmem_free (collective)."""
    arr._win.free()
    with _lock:
        _state["heap"][arr.heap_idx] = None


# -- flat-API conveniences (the C-style spelling) ---------------------------

def put(arr: SymmetricArray, target_pe: int, data, offset: int = 0) -> None:
    arr.put(target_pe, data, offset)


def get(arr: SymmetricArray, target_pe: int, count=None, offset: int = 0):
    return arr.get(target_pe, count, offset)


def fence() -> None:
    """shmem_fence: ordering of puts per target — our transport is FIFO per
    pair, so fence is a no-op (documented ordering guarantee)."""


def quiet() -> None:
    """shmem_quiet across the whole heap."""
    for arr in _state["heap"]:
        if arr is not None:
            arr.quiet()


# -- collectives (≈ scoll; delegate to MPI coll like scoll/mpi) -------------

def broadcast(arr: SymmetricArray, root: int = 0) -> None:
    """shmem_broadcast: root's local data replaces everyone's."""
    out = _comm().bcast(arr.local.copy(), root=root)
    arr.local[...] = out.reshape(arr.shape)


def collect(arr: SymmetricArray) -> np.ndarray:
    """shmem_collect / fcollect: concatenation of every PE's data."""
    return _comm().allgather(arr.local).reshape(
        (n_pes() * arr.local.shape[0],) + arr.local.shape[1:])


def to_all(arr: SymmetricArray, op=op_mod.MAX) -> None:
    """shmem_*_to_all reductions (max/min/sum/prod/and/or): elementwise
    reduce across PEs, result replacing every PE's local data."""
    out = _comm().allreduce(arr.local, op=op)
    arr.local[...] = out.reshape(arr.shape)


# -- atomics (≈ oshmem/mca/atomic) ------------------------------------------

def atomic_add(arr: SymmetricArray, target_pe: int, value,
               offset: int = 0) -> None:
    arr._win.accumulate(target_pe, np.asarray([value]), op_mod.SUM, offset)


def atomic_fetch_add(arr: SymmetricArray, target_pe: int, value,
                     offset: int = 0):
    return arr._win.fetch_op(target_pe, np.asarray([value]), op_mod.SUM,
                             offset)[0]


def atomic_cswap(arr: SymmetricArray, target_pe: int, compare, value,
                 offset: int = 0):
    return arr._win.compare_swap(target_pe, compare, value, offset)[0]


# -- distributed locks (≈ oshmem/shmem/c/shmem_lock.c) ----------------------
#
# The reference implements an MCS-style queue lock over remote atomics; the
# same fairness comes cheaper here as a ticket lock: two symmetric int64
# slots (next-ticket, now-serving) on a home PE.  set_lock draws a ticket
# with fetch_add and sleeps on the serving counter via wait_until on the
# home PE (remote waiters poll with backoff); clear_lock quiets my
# outstanding puts (the OpenSHMEM release guarantee) then advances serving.
#
# Locks share chunked slabs of the symmetric heap (64 locks per slab) so a
# thousand locks cost one window, not a thousand service threads.

_LOCKS_PER_SLAB = 64


def _lock_slot() -> tuple["SymmetricArray", int]:
    with _lock:
        slabs = _state.setdefault("lock_slabs", [])
        if not slabs or slabs[-1][1] >= _LOCKS_PER_SLAB:
            slabs.append([None, 0])   # allocated outside _lock (collective)
            need_alloc = True
        else:
            need_alloc = False
        slab = slabs[-1]
        slot = slab[1]
        slab[1] += 1
    if need_alloc:
        slab[0] = array(2 * _LOCKS_PER_SLAB, dtype=np.int64)
    return slab[0], 2 * slot


class Lock:
    """A symmetric distributed lock (collective constructor: every PE must
    create its locks in the same order, like any heap allocation)."""

    def __init__(self) -> None:
        self._arr, base = _lock_slot()
        self._next = base          # next-ticket slot
        self._serving = base + 1   # now-serving slot
        self._home = (base // 2) % n_pes()

    def set_lock(self) -> None:
        """≈ shmem_set_lock: fair (FIFO by ticket), blocking."""
        ticket = int(atomic_fetch_add(self._arr, self._home, 1,
                                      offset=self._next))
        if self._home == my_pe():
            self._arr.wait_until("ge", ticket, offset=self._serving)
            return
        delay = 1e-4
        while int(self._arr.get(self._home, 1, self._serving)[0]) < ticket:
            time.sleep(delay)
            delay = min(delay * 2, 0.01)

    def test_lock(self) -> bool:
        """≈ shmem_test_lock: one attempt; True ⇒ acquired."""
        serving = int(self._arr.get(self._home, 1, self._serving)[0])
        old = int(atomic_cswap(self._arr, self._home, serving, serving + 1,
                               offset=self._next))
        return old == serving

    def clear_lock(self) -> None:
        """≈ shmem_clear_lock: embeds a quiet — my puts are applied at
        their targets before the next holder can observe the release."""
        quiet()
        atomic_add(self._arr, self._home, 1, offset=self._serving)

    def __enter__(self) -> "Lock":
        self.set_lock()
        return self

    def __exit__(self, *exc) -> None:
        self.clear_lock()


def set_lock(lock: Lock) -> None:
    lock.set_lock()


def test_lock(lock: Lock) -> bool:
    return lock.test_lock()


def clear_lock(lock: Lock) -> None:
    lock.clear_lock()


# -- active-set collectives (PE_start, logPE_stride, PE_size) ---------------
#
# ≈ the reference's scoll active-set signatures (oshmem/mca/scoll/scoll.h):
# only the member PEs call, so these cannot ride MPI communicators (whose
# construction is collective over the parent); they run directly over the
# SHMEM comm's internal p2p on reserved tags, the way scoll/basic runs over
# put+flags.  Linear algorithms: active sets are small by construction.

_TAG_AS_BCAST, _TAG_AS_COLLECT, _TAG_AS_REDUCE = 600, 601, 602


def _active_pes(active_set) -> list[int]:
    start, logstride, size = active_set
    pes = [start + (i << logstride) for i in range(size)]
    if my_pe() not in pes:
        raise MPIException(
            f"PE {my_pe()} called an active-set collective for {pes}")
    if pes[-1] >= n_pes():
        raise MPIException(f"active set {pes} exceeds n_pes {n_pes()}")
    return pes


def _as_sendrecv(tag):
    comm = _comm()
    return (lambda buf, pe: comm._coll_isend(buf, pe, tag),
            lambda pe: comm._coll_irecv(None, pe, tag).wait())


def broadcast_active(arr: SymmetricArray, root_pe: int,
                     active_set) -> None:
    """shmem_broadcast over an active set; root's data replaces members'."""
    pes = _active_pes(active_set)
    if root_pe not in pes:
        raise MPIException(f"root {root_pe} not in active set {pes}")
    send, recv = _as_sendrecv(_TAG_AS_BCAST)
    if my_pe() == root_pe:
        reqs = [send(arr.local.reshape(-1), pe)
                for pe in pes if pe != root_pe]
        for r in reqs:
            r.wait()
    else:
        arr.local[...] = recv(root_pe).reshape(arr.shape)


def collect_active(arr: SymmetricArray, active_set) -> np.ndarray:
    """shmem_collect over an active set: concatenation in PE order."""
    pes = _active_pes(active_set)
    send, recv = _as_sendrecv(_TAG_AS_COLLECT)
    root = pes[0]
    if my_pe() == root:
        parts = {root: arr.local.reshape(-1)}
        for pe in pes[1:]:
            parts[pe] = np.asarray(recv(pe))
        full = np.concatenate([parts[pe] for pe in pes])
        reqs = [send(full, pe) for pe in pes[1:]]
        for r in reqs:
            r.wait()
    else:
        send(arr.local.reshape(-1), root).wait()
        full = np.asarray(recv(root))
    return full.reshape((len(pes) * arr.local.shape[0],)
                        + arr.local.shape[1:])


def to_all_active(arr: SymmetricArray, active_set, op=op_mod.MAX) -> None:
    """shmem_*_to_all over an active set: elementwise reduction, result
    replacing every member's local data."""
    pes = _active_pes(active_set)
    send, recv = _as_sendrecv(_TAG_AS_REDUCE)
    root = pes[0]
    if my_pe() == root:
        acc = arr.local.reshape(-1).copy()
        for pe in pes[1:]:
            acc = op.host(acc, np.asarray(recv(pe)).astype(acc.dtype))
        reqs = [send(acc, pe) for pe in pes[1:]]
        for r in reqs:
            r.wait()
        arr.local[...] = acc.reshape(arr.shape)
    else:
        send(arr.local.reshape(-1), root).wait()
        arr.local[...] = np.asarray(recv(root)).reshape(arr.shape)
