"""OpenSHMEM host API (≈ oshmem/shmem/c/: shmem_init, shmem_put,
shmem_long_max_to_all, ...).

The symmetric heap (≈ oshmem/mca/memheap) is a registry of collectively
allocated SymmetricArrays; allocation order is the "symmetric address":
every PE's Nth allocation refers to the same logical object, so a PE can
name remote memory by (array, offset) exactly as SHMEM names it by
symmetric address.  The transport (≈ oshmem/mca/spml) is an RMA window per
allocation; collectives (≈ oshmem/mca/scoll/mpi) delegate to the MPI coll
framework.  Atomics (≈ oshmem/mca/atomic) ride the window's fetch/cswap
service.
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np

from ompi_tpu.mpi import op as op_mod
from ompi_tpu.mpi.constants import MPIException
from ompi_tpu.mpi.osc import Window

__all__ = [
    "init", "finalize", "my_pe", "n_pes", "barrier_all", "array", "free",
    "put", "get", "broadcast", "collect", "to_all", "atomic_add",
    "atomic_fetch_add", "atomic_cswap", "fence", "quiet", "SymmetricArray",
]

_state: dict = {"comm": None, "heap": []}
_lock = threading.Lock()


def init():
    """shmem_init: brings up MPI underneath (the reference requires the
    same — oshmem layers on ompi)."""
    import ompi_tpu

    with _lock:
        if _state["comm"] is None:
            world = ompi_tpu.init()
            _state["comm"] = world.dup(name="SHMEM")
    return _state["comm"]


def _comm():
    if _state["comm"] is None:
        raise MPIException("shmem not initialized (call shmem.init())")
    return _state["comm"]


def finalize() -> None:
    with _lock:
        comm = _state["comm"]
        if comm is None:
            return
        for arr in list(_state["heap"]):
            if arr is not None:
                arr._win.free()
        _state["heap"].clear()
        _state["comm"] = None
    import ompi_tpu

    ompi_tpu.finalize()


def my_pe() -> int:
    return _comm().rank


def n_pes() -> int:
    return _comm().size


def barrier_all() -> None:
    _comm().barrier()


class SymmetricArray:
    """A symmetric-heap allocation: same shape/dtype on every PE.

    ``arr[:]`` is the local data (numpy view); remote access goes through
    put/get/atomics with a target PE.
    """

    def __init__(self, shape, dtype, heap_idx: int) -> None:
        self.local = np.zeros(shape, dtype=dtype)
        self.heap_idx = heap_idx
        self._win = Window(_comm(), buffer=self.local.reshape(-1),
                           name=f"sym{heap_idx}")

    @property
    def shape(self):
        return self.local.shape

    @property
    def dtype(self):
        return self.local.dtype

    def __getitem__(self, idx):
        return self.local[idx]

    def __setitem__(self, idx, value):
        self.local[idx] = value

    # -- one-sided ops (≈ shmem_put/get/atomics) --------------------------

    def put(self, target_pe: int, data, offset: int = 0) -> None:
        self._win.put(target_pe, np.asarray(data).reshape(-1), offset)

    def iput(self, target_pe: int, data, target_stride: int,
             offset: int = 0) -> None:
        """Strided put (≈ shmem_iput): element i lands at
        ``offset + i*target_stride``.  Implemented as one window put per
        element (each counted toward fence/flush totals); batching into a
        single strided message is a host-path optimization for later."""
        data = np.asarray(data).reshape(-1)
        for i, v in enumerate(data):
            self._win.put(target_pe, np.asarray([v]),
                          offset + i * target_stride)

    def get(self, target_pe: int, count: Optional[int] = None,
            offset: int = 0) -> np.ndarray:
        count = count if count is not None else self.local.size - offset
        return self._win.get(target_pe, count, offset)

    def quiet(self) -> None:
        """≈ shmem_quiet: my outstanding puts to all PEs are complete."""
        for pe in range(n_pes()):
            if pe != my_pe():
                self._win.flush(pe)

    def barrier(self) -> None:
        """Window-level fence (completes all pending ops everywhere)."""
        self._win.fence()


def array(shape, dtype=np.float64) -> SymmetricArray:
    """shmem_malloc: collective allocation on every PE."""
    with _lock:
        idx = len(_state["heap"])
        arr = SymmetricArray(shape, dtype, idx)
        _state["heap"].append(arr)
    return arr


def free(arr: SymmetricArray) -> None:
    """shmem_free (collective)."""
    arr._win.free()
    with _lock:
        _state["heap"][arr.heap_idx] = None


# -- flat-API conveniences (the C-style spelling) ---------------------------

def put(arr: SymmetricArray, target_pe: int, data, offset: int = 0) -> None:
    arr.put(target_pe, data, offset)


def get(arr: SymmetricArray, target_pe: int, count=None, offset: int = 0):
    return arr.get(target_pe, count, offset)


def fence() -> None:
    """shmem_fence: ordering of puts per target — our transport is FIFO per
    pair, so fence is a no-op (documented ordering guarantee)."""


def quiet() -> None:
    """shmem_quiet across the whole heap."""
    for arr in _state["heap"]:
        if arr is not None:
            arr.quiet()


# -- collectives (≈ scoll; delegate to MPI coll like scoll/mpi) -------------

def broadcast(arr: SymmetricArray, root: int = 0) -> None:
    """shmem_broadcast: root's local data replaces everyone's."""
    out = _comm().bcast(arr.local.copy(), root=root)
    arr.local[...] = out.reshape(arr.shape)


def collect(arr: SymmetricArray) -> np.ndarray:
    """shmem_collect / fcollect: concatenation of every PE's data."""
    return _comm().allgather(arr.local).reshape(
        (n_pes() * arr.local.shape[0],) + arr.local.shape[1:])


def to_all(arr: SymmetricArray, op=op_mod.MAX) -> None:
    """shmem_*_to_all reductions (max/min/sum/prod/and/or): elementwise
    reduce across PEs, result replacing every PE's local data."""
    out = _comm().allreduce(arr.local, op=op)
    arr.local[...] = out.reshape(arr.shape)


# -- atomics (≈ oshmem/mca/atomic) ------------------------------------------

def atomic_add(arr: SymmetricArray, target_pe: int, value,
               offset: int = 0) -> None:
    arr._win.accumulate(target_pe, np.asarray([value]), op_mod.SUM, offset)


def atomic_fetch_add(arr: SymmetricArray, target_pe: int, value,
                     offset: int = 0):
    return arr._win.fetch_op(target_pe, np.asarray([value]), op_mod.SUM,
                             offset)[0]


def atomic_cswap(arr: SymmetricArray, target_pe: int, compare, value,
                 offset: int = 0):
    return arr._win.compare_swap(target_pe, compare, value, offset)[0]
