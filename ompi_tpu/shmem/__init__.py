"""OSHMEM — the OpenSHMEM 1.3 programming model (≈ the reference's oshmem/).

PGAS over the framework: a symmetric heap of identically-shaped arrays on
every PE, one-sided put/get/atomics, and the SHMEM collective set.  The host
path layers on MPI exactly as the reference does (oshmem requires MPI init;
scoll/mpi delegates collectives — SURVEY.md §2.5); windows provide the spml
transport.  On device, the symmetric heap is the natural object: an
identically-sharded jax array IS a symmetric allocation, and put/get become
``ppermute``/collectives (SURVEY.md §3.5 TPU mapping).
"""

from ompi_tpu.shmem.api import (
    init, finalize, my_pe, n_pes, barrier_all, array, free,
    put, get, broadcast, collect, to_all, atomic_add, atomic_fetch_add,
    atomic_cswap, fence, quiet, SymmetricArray,
    Lock, set_lock, test_lock, clear_lock,
    broadcast_active, collect_active, to_all_active,
)
