"""Device-mode symmetric heap: OpenSHMEM on HBM over an ICI mesh.

≈ oshmem/mca/sshmem + spml re-imagined per SURVEY.md §3.5: a symmetric
allocation is an **identically-sharded jax array** — one equal block per PE
(device), resident in HBM.  Remote access is what the hardware is good at:

    shmem_put/get to a neighbor   →  lax.ppermute over the mesh axis
    circular shift (cshift)       →  ppermute ring (oshmem_circular_shift.c)
    shmem_*_to_all reductions     →  psum/pmax/pmin over the axis
    shmem_broadcast               →  psum of a masked block
    shmem_collect/fcollect        →  all_gather
    shmem_alltoall                →  all_to_all

There is no per-message matching or remote-key directory (the spml/mkey
machinery): symmetric addressing *is* the sharding — every PE's block of
allocation N is the same slice of the same global array, so "the address of
x on PE p" needs no translation.  Ops are SPMD: every PE in the active axis
participates (traced under ``shard_map``/``jit``), which is exactly how the
hardware moves data; a lone PE cannot interrupt another — the classic
"asynchronous put" becomes a compiled collective exchange, with zero host
staging.

Usage::

    heap = DeviceSymmetricHeap(device_world(mesh))
    x = heap.array((4,), jnp.float32)          # one (4,) block per PE
    def step(c, x):
        y = heap.cshift(x, 1)                  # put to right neighbor
        return heap.to_all(y, op=MAX)          # max-reduction to all
    out = heap.run(step, x)
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import numpy as np

from ompi_tpu.mpi.constants import MPIException
from ompi_tpu.mpi.device_comm import DeviceCommunicator
from ompi_tpu.mpi.op import MAX, SUM, Op

__all__ = ["DeviceSymmetricHeap"]


class DeviceSymmetricHeap:
    """A symmetric heap over a :class:`DeviceCommunicator`'s PEs.

    Allocations are global jax arrays whose leading dimension is sharded
    over the communicator's axes — block ``p`` is PE ``p``'s local part,
    the way every PE's Nth shmem_malloc names the same object.
    """

    def __init__(self, comm: DeviceCommunicator) -> None:
        self.comm = comm
        self._allocs = 0

    @property
    def n_pes(self) -> int:
        return self.comm.size

    # -- allocation (collective, ≈ shmem_malloc) --------------------------

    def array(self, local_shape: Sequence[int], dtype=np.float32,
              fill=0):
        """Allocate one ``local_shape`` block per PE in HBM: a global array
        of shape ``(n_pes, *local_shape)`` sharded over the PE axis."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        local_shape = tuple(int(s) for s in local_shape)
        spec = P(self.comm.axes)      # leading dim over all comm axes
        sharding = NamedSharding(self.comm.mesh, spec)
        self._allocs += 1
        # materialize directly into the sharded layout: each PE's block is
        # created on its own device (no full-size host/device-0 staging)
        shape = (self.n_pes,) + local_shape
        return jax.jit(lambda: jnp.full(shape, fill, dtype=dtype),
                       out_shardings=sharding)()

    def run(self, fn: Callable, *arrays, out_specs: Any = None):
        """Run ``fn(comm, *local_blocks)`` SPMD over the PEs (shard_map +
        jit): inside, each PE sees its block with the leading PE dim
        dropped and the heap's traced ops are available."""
        import jax.numpy as jnp

        squeeze = lambda fn_: (
            lambda c, *blocks: fn_(c, *[jnp.squeeze(b, 0) for b in blocks]))
        wrapped = lambda c, *blocks: jnp.expand_dims(
            squeeze(fn)(c, *blocks), 0)
        return self.comm.run(wrapped, *arrays, out_specs=out_specs)

    # -- traced one-sided ops (call inside run/shard_map) -----------------

    def cshift(self, x, displacement: int = 1):
        """Circular shift: my block lands at PE (me+displacement) — the
        oshmem_circular_shift.c pattern, one ppermute over ICI."""
        return self.comm.shift(x, displacement)

    def put_to(self, x, pairs: Sequence[tuple[int, int]], fill=0):
        """Explicit-pair put: ``pairs`` is (src_pe, dst_pe); PEs not
        receiving get ``fill``.  SPMD: all PEs call (a compiled exchange —
        the shape the "asynchronous put" takes on ICI)."""
        import jax.numpy as jnp

        out = self.comm.permute(x, pairs)
        if fill == 0:
            return out          # ppermute already zero-fills non-receivers
        me = self.comm.rank()
        received = jnp.zeros((), dtype=bool)
        for _, dst in pairs:
            received = received | (me == dst)
        return jnp.where(received, out, jnp.full_like(out, fill))

    def get_from(self, x, src_pe: int):
        """Every PE reads PE ``src_pe``'s block (shmem_get with a single
        source = a broadcast from that PE)."""
        return self.comm.bcast(x, root=int(src_pe))

    # -- true one-sided (remote DMA, not a permutation/collective) --------
    #
    # put_to/get_from above are *exchange-shaped*: ppermute/psum move
    # bytes on every PE.  These move bytes on exactly one ICI path —
    # shmem_put's real contract (oshmem/spml put over btl put) — via the
    # pallas remote-copy kernel in ops/remote_dma.

    def put(self, sym, value, src_pe: int, dst_pe: int):
        """Traced: PE ``src_pe`` writes ``value`` (its local block shape)
        into PE ``dst_pe``'s block of symmetric allocation ``sym``;
        returns the updated allocation.  All PEs call (SPMD), only the
        src→dst ICI path carries traffic."""
        return self.comm.put(sym, value, int(src_pe), int(dst_pe))

    def get(self, sym, src_pe: int, dst_pe: int):
        """Traced: PE ``dst_pe`` fetches PE ``src_pe``'s block of ``sym``
        one-sided; other PEs keep their own block."""
        return self.comm.get(sym, int(src_pe), int(dst_pe))

    def quiet(self, token=None):
        """shmem_quiet: remote-DMA puts complete inside their kernel
        (implicit per-op quiet), so this only orders the program — a
        barrier-token no-op kept for API parity with the host heap."""
        return token

    # -- traced collectives (≈ scoll on device) ---------------------------

    def broadcast(self, x, root: int = 0):
        return self.comm.bcast(x, root=root)

    def collect(self, x, axis: int = 0):
        """fcollect: concatenation of every PE's block (all_gather)."""
        return self.comm.allgather(x, axis=axis)

    def to_all(self, x, op: Op = MAX):
        """shmem_*_to_all: elementwise reduction, result on every PE."""
        return self.comm.allreduce(x, op=op)

    def alltoall(self, x, split_axis: int = 0, concat_axis: int = 0):
        return self.comm.alltoall(x, split_axis, concat_axis)

    def barrier_all(self, token=None):
        return self.comm.barrier(token)

    def my_pe(self):
        """Traced: the calling PE's index."""
        return self.comm.rank()

    def __repr__(self) -> str:
        return (f"DeviceSymmetricHeap(pes={self.n_pes}, "
                f"axes={self.comm.axes}, allocs={self._allocs})")
