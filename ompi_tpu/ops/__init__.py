"""Hand-written TPU kernels (pallas) for the framework's hot ops.

The reference keeps its hot loops in C (the convertor, the coll algorithm
library); the TPU analog of "hand-tuned native hot path" is a pallas
kernel feeding the MXU directly from VMEM.  Everything here has a pure-XLA
fallback — kernels are accelerators, never requirements (same policy as
ompi_tpu/_native).
"""

from ompi_tpu.ops.flash_attention import flash_attention, flash_attention_lse

__all__ = ["flash_attention", "flash_attention_lse"]
