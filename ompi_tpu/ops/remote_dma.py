"""One-sided device put/get: pallas remote-DMA kernels over ICI.

≈ opal/mca/btl/btl.h:970 (btl_put), :1007 (btl_get), :1048 (atomics) —
the BTL's one-sided contract realized as TPU inter-chip RDMA
(``pltpu.make_async_remote_copy``) instead of a collective.  Every prior
device-path op in this framework is a *collective* (psum/ppermute over an
axis: all devices move bytes).  Here bytes move ONLY src→dst over ICI:
the other devices in the SPMD program run the same compiled kernel but
issue no traffic — the TPU-native analog of a vader-BTL put landing in a
peer's mapped segment while the rest of the node does nothing.

SPMD shape: XLA compiles one program for all devices, so "one-sided"
means *one-sided dataflow*, not one-sided control: every device enters
the kernel, the sender starts the DMA and awaits its send semaphore, the
receiver awaits its receive semaphore, everyone else falls through.

The ops are functional (windows are values): ``window_put`` returns the
new window, with only the destination device's shard changed.  They must
be called inside ``shard_map`` over the mesh axis (the same contract as
every DeviceCommunicator method); ``DeviceCommunicator.put/get`` wrap
them for driver mode.

CPU testing: pass ``interpret=pltpu.InterpretParams()`` (the TPU
interpret mode models cross-device DMA + semaphores on the host); the
real path lowers to ICI RDMA on TPU.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

__all__ = ["window_put", "window_get", "fetch_bcast"]


def _pl():
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    return pl, pltpu


def _interp(interpret):
    """Default: interpret on non-TPU backends (CPU tests), native on TPU."""
    if interpret is not None:
        return interpret
    import jax

    if jax.default_backend() == "tpu":
        return False
    _, pltpu = _pl()
    return pltpu.InterpretParams()


def _put_kernel(src_ref, win_ref, out_ref, send_sem, recv_sem, *,
                src: int, dst: int, axis: str):
    """dst's out ← src's src_ref; every other device: out = own win.

    out_ref is input/output-aliased to win_ref, so "unchanged" costs
    nothing; only the landing shard is written remotely.
    """
    import jax
    from jax import lax

    pl, pltpu = _pl()
    my = lax.axis_index(axis)
    if src == dst:  # degenerate self-put: local DMA on the one device
        @pl.when(my == src)
        def _self():
            copy = pltpu.make_async_copy(src_ref, out_ref, send_sem)
            copy.start()
            copy.wait()
        return
    rdma = pltpu.make_async_remote_copy(
        src_ref=src_ref, dst_ref=out_ref, send_sem=send_sem,
        recv_sem=recv_sem, device_id=dst,
        device_id_type=pltpu.DeviceIdType.LOGICAL)

    @pl.when(my == src)
    def _send():
        rdma.start()
        rdma.wait_send()

    @pl.when(my == dst)
    def _recv():
        rdma.wait_recv()


def window_put(win, value, src: int, dst: int, axis: str,
               interpret: Optional[Any] = None):
    """One-sided put (inside shard_map): device ``src`` writes ``value``
    into device ``dst``'s window shard; returns the new window.  Bytes
    cross ICI once, src→dst — no collective dataflow.

    ≈ btl.h:970 mca_btl_base_module_put_fn_t with the window as the
    registered remote segment.
    """
    import jax

    pl, pltpu = _pl()
    if win.shape != value.shape or win.dtype != value.dtype:
        raise ValueError(
            f"window_put: value {value.shape}/{value.dtype} must match the "
            f"window shard {win.shape}/{win.dtype}")
    return pl.pallas_call(
        functools.partial(_put_kernel, src=src, dst=dst, axis=axis),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        out_shape=jax.ShapeDtypeStruct(win.shape, win.dtype),
        scratch_shapes=[pltpu.SemaphoreType.DMA, pltpu.SemaphoreType.DMA],
        input_output_aliases={1: 0},      # win -> out
        interpret=_interp(interpret),
        compiler_params=pltpu.CompilerParams(has_side_effects=True),
    )(value, win)


def _get_kernel(win_ref, local_ref, out_ref, send_sem, recv_sem, *,
                src: int, dst: int, axis: str):
    """dst's out ← src's win; every other device: out = own local buf."""
    from jax import lax

    pl, pltpu = _pl()
    my = lax.axis_index(axis)
    if src == dst:
        @pl.when(my == src)
        def _self():
            copy = pltpu.make_async_copy(win_ref, out_ref, send_sem)
            copy.start()
            copy.wait()
        return
    rdma = pltpu.make_async_remote_copy(
        src_ref=win_ref, dst_ref=out_ref, send_sem=send_sem,
        recv_sem=recv_sem, device_id=dst,
        device_id_type=pltpu.DeviceIdType.LOGICAL)

    @pl.when(my == src)
    def _serve():
        rdma.start()
        rdma.wait_send()

    @pl.when(my == dst)
    def _recv():
        rdma.wait_recv()


def window_get(win, src: int, dst: int, axis: str,
               interpret: Optional[Any] = None):
    """One-sided get (inside shard_map): device ``dst`` fetches device
    ``src``'s window shard; returns the fetched buffer (on every other
    device: its own window shard, via a local copy).

    The wire direction is identical to put — the serving device pushes —
    because ICI RDMA is sender-driven; the *semantics* are a get: the
    value read is ``src``'s window content, untouched.
    ≈ btl.h:1007 mca_btl_base_module_get_fn_t.
    """
    import jax

    pl, pltpu = _pl()
    return pl.pallas_call(
        functools.partial(_get_kernel, src=src, dst=dst, axis=axis),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        out_shape=jax.ShapeDtypeStruct(win.shape, win.dtype),
        scratch_shapes=[pltpu.SemaphoreType.DMA, pltpu.SemaphoreType.DMA],
        input_output_aliases={1: 0},      # local buf -> out
        interpret=_interp(interpret),
        compiler_params=pltpu.CompilerParams(has_side_effects=True),
    )(win, win)


def _bcast_kernel(src_ref, out_ref, send_sem, recv_sem, *,
                  root: int, n: int, axis: str):
    """Root pushes its buffer to every other device, point-to-point —
    n-1 RDMAs from root, no tree, no psum.  The btl-put composition the
    reference builds its rdma-pipeline broadcasts from."""
    from jax import lax

    pl, pltpu = _pl()
    my = lax.axis_index(axis)

    @pl.when(my == root)
    def _serve():
        copy = pltpu.make_async_copy(src_ref, out_ref, send_sem)
        copy.start()
        copy.wait()
        for peer in range(n):
            if peer == root:
                continue
            rdma = pltpu.make_async_remote_copy(
                src_ref=src_ref, dst_ref=out_ref, send_sem=send_sem,
                recv_sem=recv_sem, device_id=peer,
                device_id_type=pltpu.DeviceIdType.LOGICAL)
            rdma.start()
            rdma.wait_send()

    @pl.when(my != root)
    def _recv():
        pltpu.make_async_remote_copy(
            src_ref=src_ref, dst_ref=out_ref, send_sem=send_sem,
            recv_sem=recv_sem, device_id=root,
            device_id_type=pltpu.DeviceIdType.LOGICAL).wait_recv()


def fetch_bcast(x, root: int, n: int, axis: str,
                interpret: Optional[Any] = None):
    """Root's buffer delivered to all n devices by explicit one-sided
    puts (demonstrates put composition; the production bcast stays on
    the coll/xla decision layer)."""
    import jax

    pl, pltpu = _pl()
    return pl.pallas_call(
        functools.partial(_bcast_kernel, root=root, n=n, axis=axis),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        scratch_shapes=[pltpu.SemaphoreType.DMA, pltpu.SemaphoreType.DMA],
        interpret=_interp(interpret),
        compiler_params=pltpu.CompilerParams(has_side_effects=True),
    )(x)
