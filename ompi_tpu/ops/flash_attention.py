"""Flash attention: blockwise online-softmax attention as a pallas kernel.

The framework's densest compute op.  The jnp path in
ompi_tpu.parallel.attention materializes the full (Tq, Tk) score matrix in
HBM; this kernel streams K/V blocks through VMEM and keeps only the
running (max, normalizer, accumulator) per query row — O(Tq·D) memory,
MXU-fed matmuls, no HBM round-trip for the scores.  It is the per-chip
building block under ring/Ulysses sequence parallelism (the ring supplies
one K/V block per hop; this kernel handles the within-block math).

Autodiff: wrapped in jax.custom_vjp; the backward pass recomputes
attention weights in pure XLA from the saved (q, k, v, out, logsumexp)
residuals — the standard flash-attention recompute strategy (no O(T²)
activation storage).

Fallback policy: non-TPU backends run the kernel in pallas interpret mode
(tests on the virtual CPU mesh); shapes that don't tile (T % block != 0)
fall back to the jnp reference implementation.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ompi_tpu.core.config import VarType, register_var

__all__ = ["flash_attention", "flash_attention_lse", "flash_tiles"]

register_var("ops", "flash_block_q", VarType.INT, 128,
             "flash kernel q-block rows per grid cell (tuning knob; "
             "t_q must tile by it)")
register_var("ops", "flash_block_k", VarType.INT, 128,
             "flash kernel k/v streaming block size (tuning knob; "
             "t_k must tile by it)")
register_var("ops", "flash_bwd_kernel", VarType.BOOL, False,
             "use the pallas backward kernels for flash attention "
             "(recompute-from-lse, O(T·D) memory) instead of the "
             "materialized pure-XLA backward")

_NEG = -1e30


def flash_tiles(t_q: int, t_k: int, block_q: int = 128,
                block_k: int = 128) -> bool:
    """True when these sequence lengths tile for :func:`flash_attention`
    (the single source of the tiling rule — callers deciding between the
    kernel and the jnp fallback use this, not a re-derived check)."""
    return (t_q % min(block_q, t_q) == 0 and t_k % min(block_k, t_k) == 0
            and t_q > 0 and t_k > 0)


# ---------------------------------------------------------------------------
# kernel
# ---------------------------------------------------------------------------

def _fwd_kernel(qoff_ref, koff_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                *, scale: float, causal: bool, block_q: int, block_k: int,
                t_k: int):
    """One (batch·head, q-block) grid cell: stream K/V blocks, online
    softmax in float32, write the normalized output + per-row logsumexp
    (lse is laid out (bh, n_q_blocks, block_q) so its last dim is a full
    128 lane tile — the TPU lowering disallows a (1, block_q) block).

    Matmul inputs stay in the storage dtype (bf16 feeds the MXU natively;
    bf16 values are exactly representable in f32, so bf16×bf16→f32 equals
    the f32 product) with float32 accumulation via preferred_element_type.
    """
    from jax import lax
    from jax.experimental import pallas as pl

    iq = pl.program_id(1)
    q = q_ref[0]                                             # (bq, D)
    d = q.shape[-1]
    qpos = (qoff_ref[0] + iq * block_q
            + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0))

    def body(j, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(j * block_k, block_k), :]     # (bk, D)
        v_blk = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(                             # (bq, bk)
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            kpos = (koff_ref[0] + j * block_k
                    + lax.broadcasted_iota(jnp.int32,
                                           (block_q, block_k), 1))
            s = jnp.where(qpos >= kpos, s, _NEG)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[:, None])
        if causal:
            p = jnp.where(qpos >= kpos, p, 0.0)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(axis=-1)
        # p→storage dtype for the MXU; accumulation stays f32
        acc_new = acc * corr[:, None] + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((block_q,), _NEG, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m, l, acc = lax.fori_loop(0, t_k // block_k, body, (m0, l0, acc0))
    safe_l = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / safe_l[:, None]).astype(o_ref.dtype)
    # lse broadcast over 8 sublanes: the TPU lowering needs the block's
    # last two dims (8, block_q)-tileable; callers read sublane 0
    lse_ref[0, 0] = jnp.broadcast_to((m + jnp.log(safe_l))[None, :],
                                     (8, block_q))


def _flash_fwd_raw(q3, k3, v3, q_offset, k_offset, scale: float,
                   causal: bool, block_q: int, block_k: int,
                   interpret: bool):
    """(BH, Tq, D) × (BH, Tk, D) → ((BH, Tq, D), (BH, Tq) lse f32)."""
    from jax.experimental import pallas as pl

    bh, t_q, d = q3.shape
    t_k = k3.shape[1]
    nq = t_q // block_q
    grid = (bh, nq)
    kern = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, t_k=t_k)
    qoff = jnp.asarray(q_offset, jnp.int32).reshape(1)
    koff = jnp.asarray(k_offset, jnp.int32).reshape(1)
    o3, lse3 = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=_smem()),
            pl.BlockSpec(memory_space=_smem()),
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, t_k, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, t_k, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, 1, 8, block_q), lambda b, i: (b, i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t_q, d), q3.dtype),
            jax.ShapeDtypeStruct((bh, nq, 8, block_q), jnp.float32),
        ],
        interpret=interpret,
    )(qoff, koff, q3, k3, v3)
    return o3, lse3[:, :, 0, :].reshape(bh, t_q)


def _smem():
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.SMEM


# ---------------------------------------------------------------------------
# backward kernels (opt-in: --mca ops flash_bwd_kernel 1)
#
# The pure-XLA backward materializes (B,H,Tq,Tk) f32 score/weight tensors —
# HBM-bound at scale.  These kernels recompute p blockwise from the saved
# lse (the standard flash strategy): dq streams k/v blocks per q block;
# dk/dv streams q/g blocks per k block.  delta' = rowsum(g·out) − g_lse is
# precomputed in XLA (cheap elementwise) and folds the lse cotangent into
# the same ds term.
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(qoff_ref, koff_ref, q_ref, k_ref, v_ref, g_ref, lse_ref,
                   dm_ref, dq_ref, *, scale: float, causal: bool,
                   block_q: int, block_k: int, t_k: int):
    from jax import lax
    from jax.experimental import pallas as pl

    iq = pl.program_id(1)
    q = q_ref[0]                                             # (bq, D)
    g = g_ref[0]
    # lse/dm ride the forward's (…, 8, block_q) sublane-broadcast layout
    # (a (block_q, 1) trailing-dim block does not lower on TPU); read
    # sublane 0
    lse = lse_ref[0, 0, 0]                                   # (bq,)
    dm = dm_ref[0, 0, 0]                                     # (bq,)
    qpos = (qoff_ref[0] + iq * block_q
            + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0))

    def body(j, acc):
        k_blk = k_ref[0, pl.ds(j * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        if causal:
            kpos = (koff_ref[0] + j * block_k
                    + lax.broadcasted_iota(jnp.int32,
                                           (block_q, block_k), 1))
            s = jnp.where(qpos >= kpos, s, _NEG)
        p = jnp.exp(s - lse[:, None])                        # (bq, bk)
        if causal:
            p = jnp.where(qpos >= kpos, p, 0.0)
        dp = lax.dot_general(g, v_blk, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = (p * (dp - dm[:, None]) * scale).astype(q.dtype)
        return acc + lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    acc0 = jnp.zeros((block_q, q.shape[-1]), jnp.float32)
    dq = lax.fori_loop(0, t_k // block_k, body, acc0)
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(qoff_ref, koff_ref, q_ref, k_ref, v_ref, g_ref,
                    lse_ref, dm_ref, dk_ref, dv_ref, *, scale: float,
                    causal: bool, block_q: int, block_k: int, t_q: int):
    from jax import lax
    from jax.experimental import pallas as pl

    jk = pl.program_id(1)
    k_blk = k_ref[0]                                         # (bk, D)
    v_blk = v_ref[0]
    kpos = (koff_ref[0] + jk * block_k
            + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1))

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(i * block_q, block_q), :]         # (bq, D)
        g = g_ref[0, pl.ds(i * block_q, block_q), :]
        lse = lse_ref[0, i, 0]                               # (bq,)
        dm = dm_ref[0, i, 0]
        s = lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        if causal:
            qpos = (qoff_ref[0] + i * block_q
                    + lax.broadcasted_iota(jnp.int32,
                                           (block_q, block_k), 0))
            s = jnp.where(qpos >= kpos, s, _NEG)
        p = jnp.exp(s - lse[:, None])
        if causal:
            p = jnp.where(qpos >= kpos, p, 0.0)
        pc = p.astype(g.dtype)
        dv = dv + lax.dot_general(pc, g, (((0,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        dp = lax.dot_general(g, v_blk, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = (p * (dp - dm[:, None]) * scale).astype(q.dtype)
        dk = dk + lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        return dk, dv

    d = k_blk.shape[-1]
    z = jnp.zeros((block_k, d), jnp.float32)
    dk, dv = lax.fori_loop(0, t_q // block_q, body, (z, z))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_bwd_raw(q3, k3, v3, g3, lse3, dm3, qoff, koff, scale: float,
                   causal: bool, block_q: int, block_k: int,
                   interpret: bool):
    """(BH,·,D) inputs → (dq3, dk3, dv3)."""
    from jax.experimental import pallas as pl

    bh, t_q, d = q3.shape
    t_k = k3.shape[1]
    nq = t_q // block_q
    # same layout the forward emits: (bh, nq, 8, block_q) with the value
    # broadcast over the 8 sublanes — the last two block dims form a full
    # (8, block_q) tile, which the TPU lowering accepts (a trailing-dim-1
    # block does not lower; ADVICE r3)
    lse_c = jnp.broadcast_to(lse3.reshape(bh, nq, 1, block_q),
                             (bh, nq, 8, block_q))
    dm_c = jnp.broadcast_to(dm3.reshape(bh, nq, 1, block_q),
                            (bh, nq, 8, block_q))
    row = [
        pl.BlockSpec(memory_space=_smem()),
        pl.BlockSpec(memory_space=_smem()),
    ]
    dq3 = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, t_k=t_k),
        grid=(bh, t_q // block_q),
        in_specs=row + [
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),   # q
            pl.BlockSpec((1, t_k, d), lambda b, i: (b, 0, 0)),       # k
            pl.BlockSpec((1, t_k, d), lambda b, i: (b, 0, 0)),       # v
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),   # g
            pl.BlockSpec((1, 1, 8, block_q),
                         lambda b, i: (b, i, 0, 0)),                 # lse
            pl.BlockSpec((1, 1, 8, block_q),
                         lambda b, i: (b, i, 0, 0)),                 # dm
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, t_q, d), q3.dtype),
        interpret=interpret,
    )(qoff, koff, q3, k3, v3, g3, lse_c, dm_c)
    dk3, dv3 = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, t_q=t_q),
        grid=(bh, t_k // block_k),
        in_specs=row + [
            pl.BlockSpec((1, t_q, d), lambda b, j: (b, 0, 0)),       # q
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),   # k
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),   # v
            pl.BlockSpec((1, t_q, d), lambda b, j: (b, 0, 0)),       # g
            pl.BlockSpec((1, nq, 8, block_q),
                         lambda b, j: (b, 0, 0, 0)),                 # lse
            pl.BlockSpec((1, nq, 8, block_q),
                         lambda b, j: (b, 0, 0, 0)),                 # dm
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t_k, d), k3.dtype),
            jax.ShapeDtypeStruct((bh, t_k, d), v3.dtype),
        ],
        interpret=interpret,
    )(qoff, koff, q3, k3, v3, g3, lse_c, dm_c)
    return dq3, dk3, dv3


def _bwd_kernel_wanted() -> bool:
    from ompi_tpu.core.config import var_registry

    return bool(var_registry.get("ops_flash_bwd_kernel"))


# ---------------------------------------------------------------------------
# public op with recompute backward
# ---------------------------------------------------------------------------

def _to3(x):
    """(B, T, H, D) → (B·H, T, D)."""
    b, t, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)


def _from3(x3, b, h):
    bh, t, d = x3.shape
    return x3.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def _flash(q, k, v, qoff, koff, scale, causal, blocks):
    return _flash_core(q, k, v, qoff, koff, scale, causal, blocks)


def _flash_core(q, k, v, qoff, koff, scale, causal, blocks):
    b, t_q, h, d = q.shape
    block_q, block_k = blocks
    o3, lse3 = _flash_fwd_raw(_to3(q), _to3(k), _to3(v), qoff, koff,
                              scale, causal, block_q, block_k,
                              _use_interpret())
    return _from3(o3, b, h), lse3.reshape(b, h, t_q)


def _flash_fwd(q, k, v, qoff, koff, scale, causal, blocks):
    out, lse = _flash_core(q, k, v, qoff, koff, scale, causal, blocks)
    return (out, lse), (q, k, v, qoff, koff, out, lse)


def _flash_bwd(scale, causal, blocks, res, cts):
    """Backward via recompute.  Default: pure XLA (rebuild s + logsumexp —
    same bf16 matmul inputs with f32 accumulation, so the weights match
    the forward exactly) with the lse cotangent folded into ds
    (d lse/d s = p).  With ``--mca ops flash_bwd_kernel 1``: the pallas
    dq and dk/dv kernels recompute p blockwise from the SAVED lse —
    O(T·D) memory instead of materialized (B,H,Tq,Tk) tensors."""
    q, k, v, qoff, koff, out, lse = res
    g, g_lse = cts
    zoff = np.zeros((1,), dtype=jax.dtypes.float0)  # int args: no tangent
    b, t_q, h, d = q.shape
    if _bwd_kernel_wanted():
        block_q, block_k = blocks
        f32 = jnp.float32
        g3, o3, q3 = _to3(g), _to3(out), _to3(q)
        delta = jnp.sum(g3.astype(f32) * o3.astype(f32), axis=-1)  # (BH,T)
        dm = delta
        if g_lse is not None:
            # fold the lse cotangent: ds = p·(dp − (delta − g_lse))·scale
            dm = delta - g_lse.reshape(b * h, t_q).astype(f32)
        dq3, dk3, dv3 = _flash_bwd_raw(
            q3, _to3(k), _to3(v), g3, lse.reshape(b * h, t_q), dm,
            qoff, koff, scale, causal, block_q, block_k, _use_interpret())
        return (_from3(dq3, b, h), _from3(dk3, b, h), _from3(dv3, b, h),
                zoff, zoff)
    f32 = jnp.float32
    gf32 = g.astype(f32)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=f32) * scale
    if causal:
        qpos = qoff + jnp.arange(t_q)
        kpos = koff + jnp.arange(k.shape[1])
        keep = (qpos[:, None] >= kpos[None, :])[None, None]
        s = jnp.where(keep, s, _NEG)
    m = s.max(axis=-1, keepdims=True)
    l = jnp.sum(jnp.exp(s - m), axis=-1, keepdims=True)
    p = jnp.exp(s - m) / jnp.maximum(l, 1e-30)       # fwd weights
    if causal:
        p = jnp.where(keep, p, 0.0)
    pc = p.astype(q.dtype)
    dv = jnp.einsum("bhqk,bqhd->bkhd", pc, g, preferred_element_type=f32)
    dp = jnp.einsum("bqhd,bkhd->bhqk", g, v, preferred_element_type=f32)
    delta = jnp.einsum("bqhd,bqhd->bqh", gf32,
                       out.astype(f32)).transpose(0, 2, 1)
    resid = dp - delta[..., None]
    if g_lse is not None:
        resid = resid + g_lse.astype(f32)[..., None]  # (B,H,Tq,1)
    ds = (p * resid * scale).astype(q.dtype)
    dq = jnp.einsum("bhqk,bkhd->bqhd", ds, k, preferred_element_type=f32)
    dk = jnp.einsum("bhqk,bqhd->bkhd", ds, q, preferred_element_type=f32)
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            zoff, zoff)


_flash.defvjp(_flash_fwd, _flash_bwd)


def _check_blocks(q, k, block_q, block_k):
    t_q, t_k = q.shape[1], k.shape[1]
    if not flash_tiles(t_q, t_k, block_q, block_k):
        raise ValueError(
            f"flash_attention: T ({t_q},{t_k}) must tile by blocks "
            f"({block_q},{block_k})")
    return min(block_q, t_q), min(block_k, t_k)


def flash_attention(q, k, v, causal: bool = True,
                    q_offset=0, k_offset=0,
                    scale: Optional[float] = None,
                    block_q: int = 128, block_k: int = 128):
    """Blockwise-streamed exact attention (pallas; MXU matmuls, O(T·D)
    memory).  Same contract as parallel.attention.local_attention:
    q (B, Tq, H, D), k/v (B, Tk, H, D) → (B, Tq, H, D); offsets give
    global positions for causal masking of sequence slices and may be
    **traced** int32 scalars (the ring-attention hop index feeds one in).

    Shapes must tile (Tq % block_q == 0, Tk % block_k == 0) — callers
    (local_attention) fall back to the jnp path otherwise.
    """
    out, _ = flash_attention_lse(q, k, v, causal=causal, q_offset=q_offset,
                                 k_offset=k_offset, scale=scale,
                                 block_q=block_q, block_k=block_k)
    return out


def flash_attention_lse(q, k, v, causal: bool = True,
                        q_offset=0, k_offset=0,
                        scale: Optional[float] = None,
                        block_q: int = 128, block_k: int = 128):
    """:func:`flash_attention` that also returns the per-row logsumexp
    ((B, H, Tq) float32) — the merge state ring attention needs to combine
    this block's contribution with other hops' (≈ the reference's segmented
    ring allreduce partial, coll_base_allreduce.c:615)."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    block_q, block_k = _check_blocks(q, k, block_q, block_k)
    qoff = jnp.asarray(q_offset, jnp.int32).reshape(1)
    koff = jnp.asarray(k_offset, jnp.int32).reshape(1)
    return _flash(q, k, v, qoff, koff, float(scale), bool(causal),
                  (block_q, block_k))
