"""Native-code loader: compiles and loads the C++ convertor on demand.

≈ the reference's native OPAL core — where it ships compiled C, we ship
C++ compiled on first use (g++ is part of the supported toolchain; there
is no wheel-building step in this environment).  The build is cached next
to the package keyed by a source hash, guarded by an exclusive-create lock
so N simultaneously-launched ranks build once.  Every entry point degrades
to the pure-numpy path when a compiler is unavailable: the native layer is
an accelerator, never a requirement.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import time
from typing import Optional

#: external knob: set to "1" to force the numpy/python fallbacks (the
#: declared-constant form is what lets ompi-lint vouch the name is not
#: a typo'd read)
ENV_NO_NATIVE = "OMPI_TPU_NO_NATIVE"

_ABI = 2
_ARENA_ABI = 3
_NET_ABI = 3
_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "convertor.cpp")
_FASTDSS_SRC = os.path.join(_DIR, "fastdss.c")
_ARENA_SRC = os.path.join(_DIR, "arena.c")
_NET_SRC = os.path.join(_DIR, "net.c")

_lib: Optional[ctypes.CDLL] = None
_tried = False
_fastdss = None
_fastdss_tried = False
_arena: Optional[ctypes.CDLL] = None
_arena_tried = False
_net: Optional[ctypes.CDLL] = None
_net_tried = False
_net_py: Optional[ctypes.PyDLL] = None


def _hash_name(src: str, stem: str) -> str:
    with open(src, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    return os.path.join(_DIR, f"{stem}-{digest}.so")


def _so_path() -> str:
    return _hash_name(_SRC, "_convertor")


_LOCK_STALE_S = 150.0   # > the 120 s compile timeout: a lock this old
# belongs to a builder that was killed mid-compile


def _lock_age(lock: str) -> float:
    try:
        return time.time() - os.path.getmtime(lock)
    except OSError:
        return 0.0


def _build(so: str, src: str = _SRC,
           extra_flags: tuple = ()) -> bool:
    """Compile once across concurrent ranks (O_EXCL lock + wait).  A lock
    older than the compile timeout is debris from a killed builder — it is
    removed and the build retried, instead of every later process stalling
    30 s and silently degrading to the numpy path forever."""
    lock = so + ".lock"
    try:
        fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
    except FileExistsError:
        # someone else is building: wait for the .so (or their failure)
        for _ in range(300):
            if os.path.exists(so):
                return True
            if not os.path.exists(lock):      # builder gave up
                return os.path.exists(so)
            if _lock_age(lock) > _LOCK_STALE_S:
                try:
                    os.unlink(lock)           # stale: take over
                except OSError:
                    pass
                return _build(so, src, extra_flags)
            # one-time memoized compile wait (first use per machine,
            # during single-threaded bring-up) — not a steady-state
            # blocking path
            time.sleep(0.1)   # lint: reader-ok lock-ok
        return os.path.exists(so)
    except OSError:
        return False
    try:
        os.close(fd)
        tmp = so + ".tmp"
        # one-time memoized compile (see lib()'s _tried gate) — not a
        # steady-state blocking path
        proc = subprocess.run(   # lint: reader-ok lock-ok
            ["g++", "-O3", "-shared", "-fPIC", *extra_flags,
             "-o", tmp, src],
            capture_output=True, timeout=120)
        if proc.returncode != 0:
            return False
        os.replace(tmp, so)
        return True
    except (OSError, subprocess.SubprocessError):
        return False
    finally:
        try:
            os.unlink(lock)
        except OSError:
            pass


def lib() -> Optional[ctypes.CDLL]:
    """The loaded native library, or None (numpy fallback)."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if os.environ.get(ENV_NO_NATIVE) == "1":
        return None
    so = _so_path()
    if not os.path.exists(so) and not _build(so):
        return None
    try:
        cdll = ctypes.CDLL(so)
        cdll.ompi_tpu_native_abi.restype = ctypes.c_int64
        if cdll.ompi_tpu_native_abi() != _ABI:
            return None
        u8p = ctypes.POINTER(ctypes.c_uint8)
        i64 = ctypes.c_int64
        i64p = ctypes.POINTER(ctypes.c_int64)
        # per-item walk (+ uniform-length hint + packed item size, ABI 2)
        cdll.ompi_tpu_pack.argtypes = [u8p, u8p, i64, i64, i64p, i64p, i64,
                                       i64, i64]
        cdll.ompi_tpu_pack.restype = None
        cdll.ompi_tpu_unpack.argtypes = [u8p, u8p, i64, i64, i64p, i64p,
                                         i64, i64, i64]
        cdll.ompi_tpu_unpack.restype = None
        # coalesced absolute-run plan walk
        cdll.ompi_tpu_pack_runs.argtypes = [u8p, u8p, i64p, i64p, i64, i64]
        cdll.ompi_tpu_pack_runs.restype = None
        cdll.ompi_tpu_unpack_runs.argtypes = [u8p, u8p, i64p, i64p, i64,
                                              i64]
        cdll.ompi_tpu_unpack_runs.restype = None
        # strided progressions (vector-class plans, no run metadata)
        cdll.ompi_tpu_pack_strided.argtypes = [u8p, u8p, i64, i64, i64]
        cdll.ompi_tpu_pack_strided.restype = None
        cdll.ompi_tpu_unpack_strided.argtypes = [u8p, u8p, i64, i64, i64]
        cdll.ompi_tpu_unpack_strided.restype = None
        _lib = cdll
    except OSError:
        _lib = None
    return _lib


def available() -> bool:
    return lib() is not None


def arena() -> Optional[ctypes.CDLL]:
    """The arena/ring executor library, or None (python fallback).

    Plain-C ctypes like the convertor — unlike the per-frame fastdss
    codec, every call here either parks (waits: the ~1 µs ctypes
    marshalling cost vanishes into the park) or moves a payload (the
    copy/fold dominates), so the C-API route's extra complexity buys
    nothing.  What ctypes DOES buy is the whole point: the GIL is
    released for the duration of each call, so waits, publishes, and
    folds stop serializing against the other in-process threads."""
    global _arena, _arena_tried
    if _arena is not None or _arena_tried:
        return _arena
    _arena_tried = True
    if os.environ.get(ENV_NO_NATIVE) == "1":
        return None
    so = _hash_name(_ARENA_SRC, "_arena")
    if not os.path.exists(so) and not _build(so, src=_ARENA_SRC):
        return None
    try:
        cdll = ctypes.CDLL(so)
        cdll.ompi_tpu_arena_abi.restype = ctypes.c_int64
        if cdll.ompi_tpu_arena_abi() != _ARENA_ABI:
            return None
        i64, u64, vp = ctypes.c_int64, ctypes.c_uint64, ctypes.c_void_p
        # pointers travel as raw integer addresses (c_void_p): every
        # mapped-segment address is computed Python-side, and arrays of
        # slot pointers ride (c_void_p * n) blocks
        cdll.ompi_tpu_arena_wait.argtypes = [vp, i64, u64, i64, i64]
        cdll.ompi_tpu_arena_wait.restype = i64
        cdll.ompi_tpu_arena_wait_all.argtypes = [vp, i64, i64, i64, u64,
                                                 i64, i64]
        cdll.ompi_tpu_arena_wait_all.restype = i64
        cdll.ompi_tpu_arena_wait_change.argtypes = [vp, u64, i64, i64]
        cdll.ompi_tpu_arena_wait_change.restype = i64
        cdll.ompi_tpu_arena_wake.argtypes = [vp, i64]
        cdll.ompi_tpu_arena_wake.restype = None
        cdll.ompi_tpu_ring_wait_any.argtypes = [vp, vp, i64, i64, i64]
        cdll.ompi_tpu_ring_wait_any.restype = i64
        cdll.ompi_tpu_arena_publish.argtypes = [vp, vp, i64, vp, i64, u64]
        cdll.ompi_tpu_arena_publish.restype = None
        cdll.ompi_tpu_arena_publish_strided.argtypes = [vp, vp, i64, i64,
                                                        i64, vp, i64, u64]
        cdll.ompi_tpu_arena_publish_strided.restype = None
        cdll.ompi_tpu_arena_copy_blocks.argtypes = [vp, vp, vp, i64, vp,
                                                    i64, u64]
        cdll.ompi_tpu_arena_copy_blocks.restype = None
        cdll.ompi_tpu_arena_fold.argtypes = [vp, vp, i64, i64, i64, i64]
        cdll.ompi_tpu_arena_fold.restype = i64
        cdll.ompi_tpu_arena_spans_enable.argtypes = [i64]
        cdll.ompi_tpu_arena_spans_enable.restype = None
        cdll.ompi_tpu_arena_spans_drain.argtypes = [vp, i64]
        cdll.ompi_tpu_arena_spans_drain.restype = i64
        cdll.ompi_tpu_arena_spans_enable(_span_min_ns)  # pending arm
        _arena = cdll
    except OSError:
        _arena = None
    return _arena


def arena_available() -> bool:
    return arena() is not None


#: net.c's EOF sentinel (outside the errno range, so every other
#: negative return is unambiguously -errno)
NET_EOF = -4096


def net() -> Optional[ctypes.CDLL]:
    """The network executor library, or None (pure-python plane).

    Same plain-C ctypes shape as the arena: every entry either parks
    (the poll/backpressure waits) or moves a payload (the writev drain,
    the rndv landing recv), so ctypes' marshalling cost vanishes and
    the GIL release is the entire point — a writer draining a burst of
    frames or a poller parked across every connection no longer
    serializes against the in-process ranks."""
    global _net, _net_tried
    if _net is not None or _net_tried:
        return _net
    _net_tried = True
    if os.environ.get(ENV_NO_NATIVE) == "1":
        return None
    so = _hash_name(_NET_SRC, "_net")
    if not os.path.exists(so) and not _build(so, src=_NET_SRC):
        return None
    try:
        cdll = ctypes.CDLL(so)
        cdll.ompi_tpu_net_abi.restype = ctypes.c_int64
        if cdll.ompi_tpu_net_abi() != _NET_ABI:
            return None
        i64, vp = ctypes.c_int64, ctypes.c_void_p
        # buffers travel as raw integer addresses, iovec lists as
        # (c_uint64 * 2n) (addr, len) pair blocks — no ctypes structs
        cdll.ompi_tpu_net_writev.argtypes = [i64, vp, i64, i64]
        cdll.ompi_tpu_net_writev.restype = i64
        # send3: ctypes passes bytes objects straight through vp
        # params (address extraction happens in C, not Python) — the
        # single-crossing latency path
        cdll.ompi_tpu_net_send3.argtypes = [
            i64, vp, i64, vp, i64, vp, i64, i64]
        cdll.ompi_tpu_net_send3.restype = i64
        cdll.ompi_tpu_net_poll.argtypes = [vp, i64, vp, i64, i64]
        cdll.ompi_tpu_net_poll.restype = i64
        cdll.ompi_tpu_net_read.argtypes = [i64, vp, i64]
        cdll.ompi_tpu_net_read.restype = i64
        cdll.ompi_tpu_net_recv_into.argtypes = [i64, vp, i64, i64]
        cdll.ompi_tpu_net_recv_into.restype = i64
        cdll.ompi_tpu_net_scan.argtypes = [vp, i64, vp, i64]
        cdll.ompi_tpu_net_scan.restype = i64
        cdll.ompi_tpu_net_spans_enable.argtypes = [i64]
        cdll.ompi_tpu_net_spans_enable.restype = None
        cdll.ompi_tpu_net_spans_drain.argtypes = [vp, i64]
        cdll.ompi_tpu_net_spans_drain.restype = i64
        cdll.ompi_tpu_net_spans_enable(_span_min_ns)  # pending arm
        _net = cdll
    except OSError:
        _net = None
    return _net


def net_available() -> bool:
    return net() is not None


def net_nogil() -> Optional[ctypes.PyDLL]:
    """The SAME library through a PyDLL handle: calls keep the GIL.

    For a small-frame sendmsg(MSG_DONTWAIT) that's the faster calling
    convention on a busy interpreter — releasing the GIL for a ~2us
    syscall invites another runnable thread (the peer's poller, woken
    by this very send) to steal the interpreter, and the sender then
    waits out that thread's whole dispatch pass to get it back.  Safe
    ONLY for entries that cannot block: callers must pass slice_ns=0
    so send3 returns on the first EAGAIN instead of parking in poll()
    while holding the interpreter hostage."""
    global _net_py
    if _net_py is not None:
        return _net_py
    if net() is None:   # shares the build/ABI gate (and NO_NATIVE)
        return None
    try:
        pdll = ctypes.PyDLL(_hash_name(_NET_SRC, "_net"))
        i64, vp = ctypes.c_int64, ctypes.c_void_p
        pdll.ompi_tpu_net_send3.argtypes = [
            i64, vp, i64, vp, i64, vp, i64, i64]
        pdll.ompi_tpu_net_send3.restype = i64
        _net_py = pdll
    except OSError:
        _net_py = None
    return _net_py


# -- native span rings ------------------------------------------------------
#
# arena.c and net.c stamp begin–end timestamps of their GIL-released
# parks into small per-thread rings; trace.py drains them into the
# flight recorder.  The arm state lives here so trace.enable() can arm
# BEFORE either library is loaded (the load applies the pending value).

#: current arm threshold: spans shorter than this are dropped in C;
#: < 0 disarms recording entirely (the default)
_span_min_ns = -1

#: native kind codes → recorder span names, per library (must mirror
#: the SPAN_KIND_* constants in each .c file)
_ARENA_SPAN_NAMES = {1: "arena_wait", 2: "arena_wait_all",
                     3: "arena_wait_change", 4: "ring_wait"}
_NET_SPAN_NAMES = {1: "net_writev", 2: "net_send3",
                   3: "net_poll", 4: "net_recv_into"}

_SPAN_DRAIN_CAP = 4096
_span_buf = None


def spans_enable(min_ns: int) -> None:
    """Arm (min_ns >= 0: record parks at least that long, in ns) or
    disarm (min_ns < 0) the native span rings in both executor libs.
    Safe before either library is loaded — the value is applied at
    load time — and a no-op when native is unavailable."""
    global _span_min_ns
    _span_min_ns = int(min_ns)
    if _arena is not None:
        _arena.ompi_tpu_arena_spans_enable(_span_min_ns)
    if _net is not None:
        _net.ompi_tpu_net_spans_enable(_span_min_ns)


def spans_drain(limit: int = 1024) -> list:
    """Drain completed native park spans from both libraries.

    Returns [(name, t0_ns, t1_ns), ...] in per-ring order (t0/t1 are
    CLOCK_MONOTONIC ns, the flight recorder's clock).  Single-drainer
    contract: callers serialize (trace.py drains under its own lock)."""
    global _span_buf
    out: list = []
    limit = min(int(limit), _SPAN_DRAIN_CAP)
    if limit <= 0 or (_arena is None and _net is None):
        return out
    if _span_buf is None:
        _span_buf = (ctypes.c_uint64 * (3 * _SPAN_DRAIN_CAP))()
    buf = _span_buf
    for cdll, drain, names in (
            (_arena, "ompi_tpu_arena_spans_drain", _ARENA_SPAN_NAMES),
            (_net, "ompi_tpu_net_spans_drain", _NET_SPAN_NAMES)):
        if cdll is None:
            continue
        got = int(getattr(cdll, drain)(buf, limit - len(out)))
        for i in range(got):
            kind = buf[3 * i]
            out.append((names.get(kind, f"k{kind}"),
                        int(buf[3 * i + 1]), int(buf[3 * i + 2])))
        if len(out) >= limit:
            break
    return out


def addr_of(mv) -> Optional[int]:
    """Raw address of a writable buffer's first byte — the mapped
    segment base every native arena/ring offset is relative to.  The
    ctypes object is dropped immediately so the buffer export does not
    outlive the call (mmap.close() would otherwise raise BufferError)."""
    try:
        c = ctypes.c_char.from_buffer(mv)
    except (TypeError, ValueError, BufferError):
        return None
    addr = ctypes.addressof(c)
    del c     # refcount GC releases the export immediately
    return addr


#: shared spin burst for every native park (arena flag waits, btl ring
#: parks): on a 1-2 core host even a GIL-free spin steals the
#: publisher's quantum, so those hosts go straight to the bounded
#: block (measured: spins=0 beat every burst size on small boxes)
PARK_SPINS = 4000 if (os.cpu_count() or 1) > 2 else 0


def fastdss():
    """The compiled DSS codec extension module, or None.

    A real CPython extension (not ctypes): the codec is called once per
    control-plane frame, where ctypes marshalling was measured to cost
    more than the work saved — the C API's ~100 ns call overhead is what
    makes native pay at this granularity."""
    global _fastdss, _fastdss_tried
    if _fastdss is not None or _fastdss_tried:
        return _fastdss
    _fastdss_tried = True
    if os.environ.get(ENV_NO_NATIVE) == "1":
        return None
    import sysconfig

    # the name must carry the interpreter ABI: unlike the plain-C ctypes
    # helpers, this is a real CPython extension — loading a .so built for
    # another Python version would dlopen mismatched object layouts
    soabi = sysconfig.get_config_var("SOABI") or "abi-unknown"
    so = _hash_name(_FASTDSS_SRC, f"_fastdss-{soabi}")
    inc = sysconfig.get_paths().get("include")
    if not inc or not os.path.exists(os.path.join(inc, "Python.h")):
        return None
    if not os.path.exists(so) and not _build(
            so, src=_FASTDSS_SRC, extra_flags=("-I" + inc,)):
        return None
    try:
        import importlib.machinery
        import importlib.util

        loader = importlib.machinery.ExtensionFileLoader("_fastdss", so)
        spec = importlib.util.spec_from_file_location(
            "_fastdss", so, loader=loader)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        # self-check against a known vector before trusting it
        # a DSS round-trip vector, not a wire frame  # lint: frame-ok
        probe = {"t": "x", "n": 1, "f": 1.5, "l": [1, "a"], "b": b"\x00",
                 "none": None, "tt": (True, False)}
        if mod.unpack(mod.pack((probe,)), 1) != [probe]:
            return None
        _fastdss = mod
    except Exception:  # noqa: BLE001 — any load failure → python codec
        _fastdss = None
    return _fastdss
