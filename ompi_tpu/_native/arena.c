/* Native arena executor: the GIL-free steady-state data plane.
 *
 * ≈ opal's sm/vader progress engine — the reference runs its shared-
 * memory flag waits, slot copies, and reduction loops in C; our Python
 * layer pays the GIL for every one of them, and on a host where ranks
 * (or a rank and its transport threads) share cores, a Python spin
 * loop in ONE rank steals the quantum the flag WRITER needs (measured:
 * PR 10's every-rank redundant fold was slower than a single-rank fold
 * purely from spinner interference).
 *
 * Every entry point here is called through ctypes, which drops the GIL
 * for the duration of the call — so a rank parked in a flag wait, a
 * 64 KiB slot publish, or a segment fold no longer serializes against
 * the other in-process ranks.  Policy stays in Python: a wait runs for
 * one bounded SLICE and returns, so the caller re-checks the FT
 * contract (revocation, detector-declared deaths, the dead-writer pid
 * probe) and the overall deadline between slices at the same cadence
 * the pure-Python loop did.
 *
 * Layout contracts (shared with coll/shm.py and btl_shm.py):
 *   - arena flags are a u64 array at the segment base; flag i is the
 *     aligned 8-byte word at index i (cacheline padding is the
 *     caller's indexing problem).  All flag loads are acquire, all
 *     flag stores release — on x86 both compile to plain MOVs, the
 *     same TSO discipline the memoryview.cast("Q") path relies on.
 *   - ring counter blocks put head at u64 index 0 (btl_shm._OFF_HEAD).
 *   - fold sources are element-aligned slot pointers; the fold chain
 *     is acc = op(acc, src[s]) in s-order per element — bit-identical
 *     to the numpy rank-ordered chain (signed overflow wraps via the
 *     unsigned detour; float min/max propagate NaN like np.minimum).
 */

#include <stdint.h>
#include <string.h>
#include <time.h>

#if defined(__linux__)
#include <errno.h>
#include <sys/syscall.h>
#include <unistd.h>
#if defined(__BYTE_ORDER__) && (__BYTE_ORDER__ == __ORDER_LITTLE_ENDIAN__)
/* SHARED futex (no PRIVATE flag: the flag words live in cross-process
 * shm segments) on the LOW 32 bits of the monotonic u64 counter — on
 * little-endian that is the word that changes every increment */
#define ARENA_HAVE_FUTEX 1
#endif
#endif

#ifdef __cplusplus
extern "C" {
#endif

#if defined(__x86_64__) || defined(__i386__)
#define ARENA_RELAX() __builtin_ia32_pause()
#else
#define ARENA_RELAX() do { } while (0)
#endif

/* escalating in-slice nap: start near a context-switch quantum, cap at
 * 1 ms so a slice never oversleeps its caller's FT-check cadence much */
#define NAP_START_NS 20000LL
#define NAP_MAX_NS 1000000LL

/* longest single futex block: a publisher whose flag store took the
 * PYTHON path sends no wake, so every futex wait is bounded — the
 * missed-wake worst case degrades to the python loop's own 1 ms
 * escalation cap instead of a hang */
#define FUTEX_CAP_NS 1000000LL

#ifdef ARENA_HAVE_FUTEX
#define ARENA_FUTEX_WAIT 0
#define ARENA_FUTEX_WAKE 1

static void futex_wait32(const uint64_t *word, uint32_t seen,
                         int64_t max_ns) {
    struct timespec ts;
    if (max_ns > FUTEX_CAP_NS)
        max_ns = FUTEX_CAP_NS;
    ts.tv_sec = (time_t)(max_ns / 1000000000LL);
    ts.tv_nsec = (long)(max_ns % 1000000000LL);
    /* EAGAIN (word moved already), EINTR, ETIMEDOUT: caller re-checks */
    syscall(SYS_futex, (const uint32_t *)(const void *)word,
            ARENA_FUTEX_WAIT, seen, &ts, (void *)0, 0);
}

static void futex_wake32(const uint64_t *word) {
    syscall(SYS_futex, (const uint32_t *)(const void *)word,
            ARENA_FUTEX_WAKE, 0x7fffffff, (void *)0, (void *)0, 0);
}
#endif

static int64_t now_ns(void) {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (int64_t)ts.tv_sec * 1000000000LL + (int64_t)ts.tv_nsec;
}

static void park_ns(int64_t ns) {
    struct timespec ts;
    ts.tv_sec = (time_t)(ns / 1000000000LL);
    ts.tv_nsec = (long)(ns % 1000000000LL);
    /* EINTR just shortens the nap — the predicate re-check handles it */
    nanosleep(&ts, (struct timespec *)0);
}

static uint64_t load_u64(const uint64_t *p) {
    return __atomic_load_n(p, __ATOMIC_ACQUIRE);
}

/* -- span rings ----------------------------------------------------------- *
 *
 * Begin–end timestamps of the GIL-released parks, recorded into small
 * per-thread rings the Python side drains into its flight recorder —
 * without this the timeline shows gaps exactly where the interesting
 * waits happen.  Threads hash onto SPAN_SLOTS single-writer rings (a
 * slot collision can tear a triple; span data is metrics, the same
 * unlocked-loss tolerance as the python counters).  Disarmed (min_ns
 * < 0, the default) the only cost per entry is one relaxed load.
 */

#define SPAN_SLOTS 16
#define SPAN_RING 256
#define SPAN_KIND_WAIT 1
#define SPAN_KIND_WAIT_ALL 2
#define SPAN_KIND_WAIT_CHANGE 3
#define SPAN_KIND_RING_WAIT 4

typedef struct {
    uint64_t n;                  /* triples ever recorded (writer-owned) */
    uint64_t drained;            /* drain cursor (drainer-owned)         */
    uint64_t buf[SPAN_RING * 3]; /* kind, t0_ns, t1_ns                   */
} span_ring_t;

static span_ring_t g_spans[SPAN_SLOTS];
static int64_t g_span_min_ns = -1;   /* < 0 = disarmed */
static uint64_t g_span_slot_seq = 0;
static __thread int t_span_slot = -1;

/* begin-of-span stamp: 0 when disarmed (entries skip the end stamp) */
static int64_t span_t0(void) {
    if (__atomic_load_n(&g_span_min_ns, __ATOMIC_RELAXED) < 0)
        return 0;
    return now_ns();
}

static void span_record(uint64_t kind, int64_t t0) {
    span_ring_t *r;
    uint64_t i;
    int64_t t1 = now_ns();
    int64_t min_ns = __atomic_load_n(&g_span_min_ns, __ATOMIC_RELAXED);
    if (min_ns < 0 || t1 - t0 < min_ns)
        return;
    if (t_span_slot < 0)
        t_span_slot = (int)(__atomic_fetch_add(&g_span_slot_seq, 1,
                                               __ATOMIC_RELAXED)
                            % SPAN_SLOTS);
    r = &g_spans[t_span_slot];
    i = (r->n % SPAN_RING) * 3;
    r->buf[i] = kind;
    r->buf[i + 1] = (uint64_t)t0;
    r->buf[i + 2] = (uint64_t)t1;
    __atomic_store_n(&r->n, r->n + 1, __ATOMIC_RELEASE);
}

/* Arm (min_ns >= 0: record spans at least that long) or disarm (< 0). */
void ompi_tpu_arena_spans_enable(int64_t min_ns) {
    __atomic_store_n(&g_span_min_ns, min_ns, __ATOMIC_RELEASE);
}

/* Copy completed triples (kind, t0_ns, t1_ns) since the last drain into
 * out (capacity 3*max_triples u64s); returns the triple count.  Single
 * drainer assumed (Python under the GIL).  A ring that wrapped past the
 * cursor loses the overwritten spans — bounded memory wins. */
int64_t ompi_tpu_arena_spans_drain(uint64_t *out, int64_t max_triples) {
    int64_t got = 0;
    int s;
    for (s = 0; s < SPAN_SLOTS && got < max_triples; ++s) {
        span_ring_t *r = &g_spans[s];
        uint64_t n = __atomic_load_n(&r->n, __ATOMIC_ACQUIRE);
        uint64_t from = r->drained;
        if (n - from > SPAN_RING)
            from = n - SPAN_RING;
        for (; from < n && got < max_triples; ++from, ++got) {
            uint64_t i = (from % SPAN_RING) * 3;
            out[got * 3] = r->buf[i];
            out[got * 3 + 1] = r->buf[i + 1];
            out[got * 3 + 2] = r->buf[i + 2];
        }
        r->drained = from;
    }
    return got;
}

/* -- flag waits ----------------------------------------------------------- */

/* One bounded block on a single flag word: futex on the counter's low
 * half where available (publishers wake it — the wake-to-run latency
 * is the scheduler's, not a nap grid's), escalating nanosleep
 * otherwise.  *nap is the caller-held escalation state. */
static void block_on(const uint64_t *p, uint64_t cur, int64_t deadline,
                     int64_t *nap) {
#ifdef ARENA_HAVE_FUTEX
    int64_t remain = deadline - now_ns();
    (void)nap;
    if (remain > 0)
        futex_wait32(p, (uint32_t)cur, remain);
#else
    (void)p;
    (void)cur;
    (void)deadline;
    park_ns(*nap);
    if (*nap < NAP_MAX_NS)
        *nap *= 2;
#endif
}

/* Park until flags[idx] >= want: a bounded spin burst (pause-backed,
 * each iteration one acquire load), then futex-style blocks until the
 * slice expires.  1 = satisfied, 0 = slice expired (caller re-checks
 * FT + deadline and calls again). */
static int64_t arena_wait_impl(const uint64_t *flags, int64_t idx,
                               uint64_t want, int64_t spins,
                               int64_t slice_ns) {
    const uint64_t *p = flags + idx;
    int64_t s, deadline, nap;
    uint64_t cur;
    for (s = 0; s < spins; ++s) {
        if (load_u64(p) >= want)
            return 1;
        ARENA_RELAX();
    }
    deadline = now_ns() + slice_ns;
    nap = NAP_START_NS;
    for (;;) {
        cur = load_u64(p);
        if (cur >= want)
            return 1;
        if (now_ns() >= deadline)
            return 0;
        block_on(p, cur, deadline, &nap);
    }
}

/* Park until flags[base + i*stride] >= want for EVERY i in [0, n) —
 * the _wait_all_arrive/_wait_all_depart sweep as one GIL-released
 * call.  Satisfied prefixes are never re-checked (i only advances). */
static int64_t arena_wait_all_impl(const uint64_t *flags, int64_t base,
                                   int64_t stride, int64_t n, uint64_t want,
                                   int64_t spins, int64_t slice_ns) {
    int64_t i = 0, s, deadline, nap;
    uint64_t cur;
    for (s = 0; s < spins; ++s) {
        while (i < n && load_u64(flags + base + i * stride) >= want)
            ++i;
        if (i >= n)
            return 1;
        ARENA_RELAX();
    }
    deadline = now_ns() + slice_ns;
    nap = NAP_START_NS;
    for (;;) {
        while (i < n && load_u64(flags + base + i * stride) >= want)
            ++i;
        if (i >= n)
            return 1;
        if (now_ns() >= deadline)
            return 0;
        /* block on the first unsatisfied flag: its publisher's wake
         * releases us; the loop then advances past it */
        cur = load_u64(flags + base + i * stride);
        if (cur >= want)
            continue;
        block_on(flags + base + i * stride, cur, deadline, &nap);
    }
}

/* Park until *p != seen (a counter moved at all) — the writer-side
 * ring-full backpressure wait, layout-agnostic. */
static int64_t arena_wait_change_impl(const uint64_t *p, uint64_t seen,
                                      int64_t spins, int64_t slice_ns) {
    int64_t s, deadline, nap;
    for (s = 0; s < spins; ++s) {
        if (load_u64(p) != seen)
            return 1;
        ARENA_RELAX();
    }
    deadline = now_ns() + slice_ns;
    nap = NAP_START_NS;
    for (;;) {
        if (load_u64(p) != seen)
            return 1;
        if (now_ns() >= deadline)
            return 0;
        block_on(p, seen, deadline, &nap);
    }
}

/* Wake every futex waiter parked on flag word idx — publishers call
 * this right after a release flag store (native publishes fuse it;
 * python-side memoryview stores call it through ctypes).  A no-op
 * build (no futex) leaves waiters on their bounded naps. */
void ompi_tpu_arena_wake(const uint64_t *flags, int64_t idx) {
#ifdef ARENA_HAVE_FUTEX
    futex_wake32(flags + idx);
#else
    (void)flags;
    (void)idx;
#endif
}

/* Park until ANY ring i has head (ctrs[i][0]) != tails[i]; returns the
 * first such index, or -1 on slice expiry.  The btl/shm poller's idle
 * window: one GIL-released call instead of a time.sleep(0) spin that
 * fights every other thread for the interpreter. */
static int64_t ring_wait_any_impl(uint64_t **ctrs, const uint64_t *tails,
                                  int64_t n, int64_t spins,
                                  int64_t slice_ns) {
    int64_t s, i, deadline, nap;
    for (s = 0; s < spins; ++s) {
        for (i = 0; i < n; ++i)
            if (load_u64(ctrs[i]) != tails[i])
                return i;
        ARENA_RELAX();
    }
    deadline = now_ns() + slice_ns;
    nap = NAP_START_NS;
    for (;;) {
        for (i = 0; i < n; ++i)
            if (load_u64(ctrs[i]) != tails[i])
                return i;
        if (now_ns() >= deadline)
            return -1;
        park_ns(nap);
        if (nap < NAP_MAX_NS)
            nap *= 2;
    }
}

/* Exported park entries: the impl bracketed by the span stamps.  When
 * disarmed span_t0() returns 0 and the wrapper adds one relaxed load. */
int64_t ompi_tpu_arena_wait(const uint64_t *flags, int64_t idx,
                            uint64_t want, int64_t spins,
                            int64_t slice_ns) {
    int64_t t0 = span_t0();
    int64_t r = arena_wait_impl(flags, idx, want, spins, slice_ns);
    if (t0)
        span_record(SPAN_KIND_WAIT, t0);
    return r;
}

int64_t ompi_tpu_arena_wait_all(const uint64_t *flags, int64_t base,
                                int64_t stride, int64_t n, uint64_t want,
                                int64_t spins, int64_t slice_ns) {
    int64_t t0 = span_t0();
    int64_t r = arena_wait_all_impl(flags, base, stride, n, want, spins,
                                    slice_ns);
    if (t0)
        span_record(SPAN_KIND_WAIT_ALL, t0);
    return r;
}

int64_t ompi_tpu_arena_wait_change(const uint64_t *p, uint64_t seen,
                                   int64_t spins, int64_t slice_ns) {
    int64_t t0 = span_t0();
    int64_t r = arena_wait_change_impl(p, seen, spins, slice_ns);
    if (t0)
        span_record(SPAN_KIND_WAIT_CHANGE, t0);
    return r;
}

int64_t ompi_tpu_ring_wait_any(uint64_t **ctrs, const uint64_t *tails,
                               int64_t n, int64_t spins,
                               int64_t slice_ns) {
    int64_t t0 = span_t0();
    int64_t r = ring_wait_any_impl(ctrs, tails, n, spins, slice_ns);
    if (t0)
        span_record(SPAN_KIND_RING_WAIT, t0);
    return r;
}

/* -- publishes ------------------------------------------------------------ */

/* THE send-side copy + arrive store as one GIL-released call: memcpy
 * into the mapped slot, then a release store of the flag (NULL flags
 * ⇒ pure copy — the drain-side read uses the same entry point). */
void ompi_tpu_arena_publish(uint8_t *dst, const uint8_t *src,
                            int64_t nbytes, uint64_t *flags, int64_t fidx,
                            uint64_t fval) {
    if (nbytes > 0)
        memcpy(dst, src, (size_t)nbytes);
    if (flags) {
        __atomic_store_n(flags + fidx, fval, __ATOMIC_RELEASE);
        ompi_tpu_arena_wake(flags, fidx);
    }
}

/* Strided-source publish (the convertor plan ABI's vector-class shape:
 * nblocks blocks of bl bytes, source block i at src + i*stride, packed
 * dense into dst) + the same release flag store. */
void ompi_tpu_arena_publish_strided(uint8_t *dst, const uint8_t *src,
                                    int64_t nblocks, int64_t bl,
                                    int64_t stride, uint64_t *flags,
                                    int64_t fidx, uint64_t fval) {
    int64_t i;
    for (i = 0; i < nblocks; ++i) {
        memcpy(dst, src, (size_t)bl);
        dst += bl;
        src += stride;
    }
    if (flags) {
        __atomic_store_n(flags + fidx, fval, __ATOMIC_RELEASE);
        ompi_tpu_arena_wake(flags, fidx);
    }
}

/* Scattered-block copy plan + the same optional fused release store:
 * nblocks independent (dst, src, len) copies, then one flag publish.
 * This is the dense-exchange workhorse — the alltoall gather side reads
 * its column out of every peer slot (p copies), and the alltoallv
 * scatter side lays a length header plus variable blocks into its own
 * slot (p+1 copies) — as ONE GIL-released call instead of p ctypes
 * crossings.  NULL flags ⇒ pure copy plan (the gather side, which
 * signs completion through depart flags separately). */
void ompi_tpu_arena_copy_blocks(uint8_t **dsts, uint8_t **srcs,
                                const int64_t *lens, int64_t nblocks,
                                uint64_t *flags, int64_t fidx,
                                uint64_t fval) {
    int64_t i;
    for (i = 0; i < nblocks; ++i)
        if (lens[i] > 0)
            memcpy(dsts[i], srcs[i], (size_t)lens[i]);
    if (flags) {
        __atomic_store_n(flags + fidx, fval, __ATOMIC_RELEASE);
        ompi_tpu_arena_wake(flags, fidx);
    }
}

/* -- width-specialized segment folds -------------------------------------- */

/* dtype codes (numpy native-endian fixed widths):
 *   0 int8  1 int16  2 int32  3 int64
 *   4 uint8 5 uint16 6 uint32 7 uint64
 *   8 float32  9 float64
 * op codes: 0 sum, 1 prod, 2 min, 3 max (the commutative builtins).
 * Chain order per element is s = 0..nsrc-1, identical to the Python
 * rank-ordered op.host() fold, so results are bit-identical. */

#define FOLD_LOOP(T, OPEXPR)                                            \
    do {                                                                \
        T *d = (T *)dst;                                                \
        int64_t j, s_;                                                  \
        for (j = 0; j < nelems; ++j) {                                  \
            T a = ((const T *)(const void *)srcs[0])[j];                \
            for (s_ = 1; s_ < nsrc; ++s_) {                             \
                T b = ((const T *)(const void *)srcs[s_])[j];           \
                a = (OPEXPR);                                           \
            }                                                           \
            d[j] = a;                                                   \
        }                                                               \
        return 0;                                                       \
    } while (0)

/* signed sum/prod detour through the unsigned twin: numpy wraps on
 * overflow, and signed overflow is UB the sanitizer build would trap */
#define FOLD_TYPE_SINT(T, UT)                                           \
    switch (op) {                                                       \
    case 0: FOLD_LOOP(T, (T)(UT)((UT)a + (UT)b));                       \
    case 1: FOLD_LOOP(T, (T)(UT)((UT)a * (UT)b));                       \
    case 2: FOLD_LOOP(T, a < b ? a : b);                                \
    case 3: FOLD_LOOP(T, a > b ? a : b);                                \
    default: return -1;                                                 \
    }

#define FOLD_TYPE_UINT(T)                                               \
    switch (op) {                                                       \
    case 0: FOLD_LOOP(T, (T)(a + b));                                   \
    case 1: FOLD_LOOP(T, (T)(a * b));                                   \
    case 2: FOLD_LOOP(T, a < b ? a : b);                                \
    case 3: FOLD_LOOP(T, a > b ? a : b);                                \
    default: return -1;                                                 \
    }

/* float min/max propagate NaN FIRST-operand-first, matching
 * np.minimum/np.maximum ("if one element is NaN, that element is
 * returned") applied down the acc chain */
#define FOLD_TYPE_FLT(T)                                                \
    switch (op) {                                                       \
    case 0: FOLD_LOOP(T, a + b);                                        \
    case 1: FOLD_LOOP(T, a * b);                                        \
    case 2: FOLD_LOOP(T, (a != a) ? a : ((b != b) ? b                   \
                                         : (a < b ? a : b)));           \
    case 3: FOLD_LOOP(T, (a != a) ? a : ((b != b) ? b                   \
                                         : (a > b ? a : b)));           \
    default: return -1;                                                 \
    }

/* Fold nsrc equal-length segments elementwise into dst.  0 = done,
 * -1 = unsupported (dtype, op) — the caller pre-validates, so -1 is a
 * contract violation it surfaces, never a silent wrong answer. */
int64_t ompi_tpu_arena_fold(uint8_t *dst, uint8_t **srcs, int64_t nsrc,
                            int64_t nelems, int64_t dtype, int64_t op) {
    if (nsrc < 1 || nelems < 0)
        return -1;
    switch (dtype) {
    case 0: FOLD_TYPE_SINT(int8_t, uint8_t);
    case 1: FOLD_TYPE_SINT(int16_t, uint16_t);
    case 2: FOLD_TYPE_SINT(int32_t, uint32_t);
    case 3: FOLD_TYPE_SINT(int64_t, uint64_t);
    case 4: FOLD_TYPE_UINT(uint8_t);
    case 5: FOLD_TYPE_UINT(uint16_t);
    case 6: FOLD_TYPE_UINT(uint32_t);
    case 7: FOLD_TYPE_UINT(uint64_t);
    case 8: FOLD_TYPE_FLT(float);
    case 9: FOLD_TYPE_FLT(double);
    default: return -1;
    }
}

/* version tag so the loader can detect stale cached builds */
int64_t ompi_tpu_arena_abi(void) { return 3; }

#ifdef __cplusplus
}  /* extern "C" */
#endif
