/* fastdss — CPython-C-API codec for the DSS wire format's common subset.
 *
 * ≈ the reference's compiled opal/dss pack/unpack (dss_pack.c/dss_unpack.c):
 * every shm/tcp frame header and RML control message pays one encode +
 * one decode; the optimized pure-python codec costs ~3.3/3.8 µs per
 * 7-key header, this module ~0.3/0.4 µs.  The ctypes route was measured
 * and rejected (call marshalling exceeded the work saved) — the C API's
 * ~100 ns call overhead is what makes native pay here.
 *
 * Wire format (must stay byte-identical to ompi_tpu/core/dss.py):
 *   [1B tag][payload]; u32 little-endian lengths for var-size payloads.
 * Handled tags: NONE, BOOL, INT64, FLOAT64, STRING, BYTES, LIST, TUPLE,
 * DICT.  Anything else (ndarray, exotic types, out-of-range ints) raises
 * Unsupported and the caller falls back to the python codec; truncated
 * or corrupt input raises ValueError (the wrapper converts to DSSError).
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <stdint.h>
#include <string.h>

#define T_INT64 1
#define T_FLOAT64 2
#define T_STRING 3
#define T_BYTES 4
#define T_BOOL 5
#define T_NONE 6
#define T_LIST 7
#define T_DICT 8
#define T_TUPLE 10

static PyObject *Unsupported;
static PyObject *RingFull;
static PyObject *TooBig;

/* -- growable output buffer -------------------------------------------- */

typedef struct {
    uint8_t *buf;
    Py_ssize_t len;
    Py_ssize_t cap;
} Out;

static int out_reserve(Out *o, Py_ssize_t extra) {
    if (o->len + extra <= o->cap) return 0;
    Py_ssize_t ncap = o->cap ? o->cap * 2 : 256;
    while (ncap < o->len + extra) ncap *= 2;
    uint8_t *nb = (uint8_t *)PyMem_Realloc(o->buf, (size_t)ncap);
    if (!nb) { PyErr_NoMemory(); return -1; }
    o->buf = nb;
    o->cap = ncap;
    return 0;
}

static int out_put(Out *o, const void *src, Py_ssize_t n) {
    if (out_reserve(o, n) < 0) return -1;
    memcpy(o->buf + o->len, src, (size_t)n);
    o->len += n;
    return 0;
}

static int out_u8(Out *o, uint8_t b) { return out_put(o, &b, 1); }

static int out_u32(Out *o, uint32_t v) {
    uint8_t le[4] = {(uint8_t)v, (uint8_t)(v >> 8), (uint8_t)(v >> 16),
                     (uint8_t)(v >> 24)};
    return out_put(o, le, 4);
}

/* -- pack ---------------------------------------------------------------
 * Returns 0 ok, -1 error set.  Unsupported values raise Unsupported —
 * the python wrapper falls back to the general codec for the WHOLE call
 * (wire compatibility: partial native output is discarded). */

static int pack_obj(Out *o, PyObject *v);

static int pack_obj_rec(Out *o, PyObject *v) {
    /* C-stack guard: a deeply nested structure must raise, not segfault
     * (the python codec raises RecursionError for the same input) */
    if (Py_EnterRecursiveCall(" in fastdss pack")) return -1;
    int rc = pack_obj(o, v);
    Py_LeaveRecursiveCall();
    return rc;
}

static int pack_obj(Out *o, PyObject *v) {
    if (v == Py_None) return out_u8(o, T_NONE);
    if (v == Py_True) { uint8_t b[2] = {T_BOOL, 1}; return out_put(o, b, 2); }
    if (v == Py_False) { uint8_t b[2] = {T_BOOL, 0}; return out_put(o, b, 2); }
    if (PyLong_CheckExact(v)) {
        int overflow = 0;
        int64_t x = (int64_t)PyLong_AsLongLongAndOverflow(v, &overflow);
        if (overflow || (x == -1 && PyErr_Occurred())) {
            PyErr_Clear();
            PyErr_SetString(Unsupported, "int out of int64 range");
            return -1;
        }
        uint8_t rec[9];
        rec[0] = T_INT64;
        memcpy(rec + 1, &x, 8); /* little-endian hosts only (x86/arm64) */
        return out_put(o, rec, 9);
    }
    if (PyFloat_CheckExact(v)) {
        double d = PyFloat_AS_DOUBLE(v);
        uint8_t rec[9];
        rec[0] = T_FLOAT64;
        memcpy(rec + 1, &d, 8);
        return out_put(o, rec, 9);
    }
    if (PyUnicode_CheckExact(v)) {
        Py_ssize_t n;
        const char *s = PyUnicode_AsUTF8AndSize(v, &n);
        if (!s) return -1;
        if (n > (Py_ssize_t)0xFFFFFFFF) {
            PyErr_SetString(Unsupported, "string exceeds u32 length");
            return -1;
        }
        if (out_u8(o, T_STRING) < 0 || out_u32(o, (uint32_t)n) < 0)
            return -1;
        return out_put(o, s, n);
    }
    if (PyBytes_CheckExact(v)) {
        Py_ssize_t n = PyBytes_GET_SIZE(v);
        if (n > (Py_ssize_t)0xFFFFFFFF) {
            PyErr_SetString(Unsupported, "bytes exceed u32 length");
            return -1;
        }
        if (out_u8(o, T_BYTES) < 0 || out_u32(o, (uint32_t)n) < 0)
            return -1;
        return out_put(o, PyBytes_AS_STRING(v), n);
    }
    if (PyList_CheckExact(v) || PyTuple_CheckExact(v)) {
        int is_list = PyList_CheckExact(v);
        Py_ssize_t n = is_list ? PyList_GET_SIZE(v) : PyTuple_GET_SIZE(v);
        if (n > (Py_ssize_t)0xFFFFFFFF) {
            PyErr_SetString(Unsupported, "sequence exceeds u32 length");
            return -1;
        }
        if (out_u8(o, is_list ? T_LIST : T_TUPLE) < 0 ||
            out_u32(o, (uint32_t)n) < 0)
            return -1;
        for (Py_ssize_t i = 0; i < n; i++) {
            PyObject *it = is_list ? PyList_GET_ITEM(v, i)
                                   : PyTuple_GET_ITEM(v, i);
            if (pack_obj_rec(o, it) < 0) return -1;
        }
        return 0;
    }
    if (PyDict_CheckExact(v)) {
        Py_ssize_t n = PyDict_GET_SIZE(v);
        if (out_u8(o, T_DICT) < 0 || out_u32(o, (uint32_t)n) < 0) return -1;
        PyObject *key, *val;
        Py_ssize_t pos = 0;
        while (PyDict_Next(v, &pos, &key, &val)) {
            if (pack_obj_rec(o, key) < 0 || pack_obj_rec(o, val) < 0)
                return -1;
        }
        return 0;
    }
    PyErr_Format(Unsupported, "fastdss cannot pack %s",
                 Py_TYPE(v)->tp_name);
    return -1;
}

static PyObject *fastdss_pack(PyObject *self, PyObject *values) {
    /* values: a tuple of the objects to pack in sequence */
    if (!PyTuple_CheckExact(values)) {
        PyErr_SetString(PyExc_TypeError, "pack expects a tuple");
        return NULL;
    }
    Out o = {NULL, 0, 0};
    for (Py_ssize_t i = 0; i < PyTuple_GET_SIZE(values); i++) {
        if (pack_obj(&o, PyTuple_GET_ITEM(values, i)) < 0) {
            PyMem_Free(o.buf);
            return NULL;
        }
    }
    PyObject *out = PyBytes_FromStringAndSize((const char *)o.buf, o.len);
    PyMem_Free(o.buf);
    return out;
}

/* -- unpack ------------------------------------------------------------ */

typedef struct {
    const uint8_t *d;
    Py_ssize_t len;
    Py_ssize_t pos;
} In;

static int need(In *in, Py_ssize_t n) {
    if (in->pos + n > in->len) {
        PyErr_SetString(PyExc_ValueError, "buffer underrun");
        return -1;
    }
    return 0;
}

static uint32_t rd_u32(In *in) {
    const uint8_t *p = in->d + in->pos;
    in->pos += 4;
    return (uint32_t)p[0] | ((uint32_t)p[1] << 8) | ((uint32_t)p[2] << 16) |
           ((uint32_t)p[3] << 24);
}

static PyObject *unpack_obj(In *in);

static PyObject *unpack_obj_rec(In *in) {
    if (Py_EnterRecursiveCall(" in fastdss unpack")) return NULL;
    PyObject *v = unpack_obj(in);
    Py_LeaveRecursiveCall();
    return v;
}

static PyObject *unpack_obj(In *in) {
    if (need(in, 1) < 0) return NULL;
    uint8_t tag = in->d[in->pos++];
    switch (tag) {
    case T_NONE:
        Py_RETURN_NONE;
    case T_BOOL: {
        if (need(in, 1) < 0) return NULL;
        uint8_t b = in->d[in->pos++];
        if (b) Py_RETURN_TRUE;
        Py_RETURN_FALSE;
    }
    case T_INT64: {
        if (need(in, 8) < 0) return NULL;
        int64_t x;
        memcpy(&x, in->d + in->pos, 8);
        in->pos += 8;
        return PyLong_FromLongLong((long long)x);
    }
    case T_FLOAT64: {
        if (need(in, 8) < 0) return NULL;
        double d;
        memcpy(&d, in->d + in->pos, 8);
        in->pos += 8;
        return PyFloat_FromDouble(d);
    }
    case T_STRING: {
        if (need(in, 4) < 0) return NULL;
        uint32_t n = rd_u32(in);
        if (need(in, (Py_ssize_t)n) < 0) return NULL;
        PyObject *s = PyUnicode_DecodeUTF8(
            (const char *)(in->d + in->pos), (Py_ssize_t)n, NULL);
        in->pos += n;
        return s;
    }
    case T_BYTES: {
        if (need(in, 4) < 0) return NULL;
        uint32_t n = rd_u32(in);
        if (need(in, (Py_ssize_t)n) < 0) return NULL;
        PyObject *b = PyBytes_FromStringAndSize(
            (const char *)(in->d + in->pos), (Py_ssize_t)n);
        in->pos += n;
        return b;
    }
    case T_LIST:
    case T_TUPLE: {
        if (need(in, 4) < 0) return NULL;
        uint32_t n = rd_u32(in);
        /* a hostile length can't exceed the remaining bytes: every item
         * is >= 1 byte, so bound the allocation before trusting it */
        if ((Py_ssize_t)n > in->len - in->pos) {
            PyErr_SetString(PyExc_ValueError, "buffer underrun in list");
            return NULL;
        }
        PyObject *seq = (tag == T_LIST) ? PyList_New((Py_ssize_t)n)
                                        : PyTuple_New((Py_ssize_t)n);
        if (!seq) return NULL;
        for (uint32_t i = 0; i < n; i++) {
            PyObject *it = unpack_obj_rec(in);
            if (!it) { Py_DECREF(seq); return NULL; }
            if (tag == T_LIST) PyList_SET_ITEM(seq, i, it);
            else PyTuple_SET_ITEM(seq, i, it);
        }
        return seq;
    }
    case T_DICT: {
        if (need(in, 4) < 0) return NULL;
        uint32_t n = rd_u32(in);
        if ((Py_ssize_t)n * 2 > in->len - in->pos) {
            PyErr_SetString(PyExc_ValueError, "buffer underrun in dict");
            return NULL;
        }
        PyObject *d = PyDict_New();
        if (!d) return NULL;
        for (uint32_t i = 0; i < n; i++) {
            PyObject *k = unpack_obj_rec(in);
            if (!k) { Py_DECREF(d); return NULL; }
            PyObject *v = unpack_obj_rec(in);
            if (!v) { Py_DECREF(k); Py_DECREF(d); return NULL; }
            int rc = PyDict_SetItem(d, k, v);
            Py_DECREF(k);
            Py_DECREF(v);
            if (rc < 0) { Py_DECREF(d); return NULL; }
        }
        return d;
    }
    default:
        /* ndarray or unknown: let the python codec handle the whole call */
        PyErr_Format(Unsupported, "fastdss cannot unpack tag %d", tag);
        return NULL;
    }
}

static PyObject *fastdss_unpack(PyObject *self, PyObject *args) {
    Py_buffer view;
    Py_ssize_t limit = -1;
    if (!PyArg_ParseTuple(args, "y*|n", &view, &limit)) return NULL;
    In in = {(const uint8_t *)view.buf, view.len, 0};
    PyObject *out = PyList_New(0);
    if (!out) { PyBuffer_Release(&view); return NULL; }
    while (in.pos < in.len &&
           (limit < 0 || PyList_GET_SIZE(out) < limit)) {
        PyObject *v = unpack_obj(&in);
        if (!v) { Py_DECREF(out); PyBuffer_Release(&view); return NULL; }
        int rc = PyList_Append(out, v);
        Py_DECREF(v);
        if (rc < 0) { Py_DECREF(out); PyBuffer_Release(&view); return NULL; }
    }
    PyBuffer_Release(&view);
    return out;
}

/* -- shared-memory atomics (sharedfp/sm, host-side counters) ----------- */

static int atomic_slot(Py_buffer *mm, Py_ssize_t off, uint64_t **slot) {
    if (off < 0 || off % 8 || off + 8 > mm->len) {
        PyErr_SetString(PyExc_ValueError, "bad atomic slot offset");
        return -1;
    }
    *slot = (uint64_t *)((uint8_t *)mm->buf + off);
    return 0;
}

static PyObject *fastdss_atomic_add(PyObject *self, PyObject *args) {
    Py_buffer mm;
    Py_ssize_t off;
    long long delta;
    if (!PyArg_ParseTuple(args, "w*nL", &mm, &off, &delta)) return NULL;
    uint64_t *slot;
    PyObject *res = NULL;
    if (atomic_slot(&mm, off, &slot) == 0) {
        uint64_t old = __atomic_fetch_add(slot, (uint64_t)(int64_t)delta,
                                          __ATOMIC_ACQ_REL);
        res = PyLong_FromUnsignedLongLong(old);
    }
    PyBuffer_Release(&mm);
    return res;
}

static PyObject *fastdss_atomic_load(PyObject *self, PyObject *args) {
    Py_buffer mm;
    Py_ssize_t off;
    if (!PyArg_ParseTuple(args, "w*n", &mm, &off)) return NULL;
    uint64_t *slot;
    PyObject *res = NULL;
    if (atomic_slot(&mm, off, &slot) == 0)
        res = PyLong_FromUnsignedLongLong(
            __atomic_load_n(slot, __ATOMIC_ACQUIRE));
    PyBuffer_Release(&mm);
    return res;
}

static PyObject *fastdss_atomic_store(PyObject *self, PyObject *args) {
    Py_buffer mm;
    Py_ssize_t off;
    unsigned long long v;
    if (!PyArg_ParseTuple(args, "w*nK", &mm, &off, &v)) return NULL;
    uint64_t *slot;
    PyObject *res = NULL;
    if (atomic_slot(&mm, off, &slot) == 0) {
        __atomic_store_n(slot, (uint64_t)v, __ATOMIC_RELEASE);
        res = Py_None;
        Py_INCREF(res);
    }
    PyBuffer_Release(&mm);
    return res;
}

/* -- module ------------------------------------------------------------ */

static PyObject *fastdss_ring_send(PyObject *self, PyObject *args);
static PyObject *fastdss_ring_recv(PyObject *self, PyObject *args);

static PyMethodDef methods[] = {
    {"pack", fastdss_pack, METH_O,
     "pack(tuple_of_values) -> bytes (DSS wire format)"},
    {"unpack", fastdss_unpack, METH_VARARGS,
     "unpack(data[, n]) -> list of values"},
    {"ring_send", fastdss_ring_send, METH_VARARGS,
     "ring_send(mm, head, header, payload) -> (new_head, sleep_flag)"},
    {"ring_recv", fastdss_ring_recv, METH_VARARGS,
     "ring_recv(mm, tail) -> None | (header, payload, new_tail)"},
    {"atomic_add", fastdss_atomic_add, METH_VARARGS,
     "atomic_add(mm, offset, delta) -> old (u64 fetch-add, acq_rel)"},
    {"atomic_load", fastdss_atomic_load, METH_VARARGS,
     "atomic_load(mm, offset) -> value (u64, acquire)"},
    {"atomic_store", fastdss_atomic_store, METH_VARARGS,
     "atomic_store(mm, offset, value) (u64, release)"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_fastdss",
    "compiled DSS codec for the common control-message subset", -1,
    methods,
};

PyMODINIT_FUNC PyInit__fastdss(void) {
    PyObject *m = PyModule_Create(&moduledef);
    if (!m) return NULL;
    Unsupported = PyErr_NewException("_fastdss.Unsupported", NULL, NULL);
    if (!Unsupported || PyModule_AddObject(m, "Unsupported", Unsupported) < 0) {
        Py_XDECREF(Unsupported);
        Py_DECREF(m);
        return NULL;
    }
    RingFull = PyErr_NewException("_fastdss.RingFull", NULL, NULL);
    if (!RingFull || PyModule_AddObject(m, "RingFull", RingFull) < 0) {
        Py_XDECREF(RingFull);
        Py_DECREF(m);
        return NULL;
    }
    TooBig = PyErr_NewException("_fastdss.FrameTooBig", NULL, NULL);
    if (!TooBig || PyModule_AddObject(m, "FrameTooBig", TooBig) < 0) {
        Py_XDECREF(TooBig);
        Py_DECREF(m);
        return NULL;
    }
    return m;
}

/* -- fused ring framing -------------------------------------------------
 * Encode a header dict + payload DIRECTLY into the shm ring mapping and
 * publish, or decode a frame straight out of it — one C call per frame,
 * no intermediate bytes object (the shm BTL's vader-class data plane).
 * Ring layout matches btl_shm.py / convertor.cpp: u64 head @0 (writer,
 * release-store publishes), u64 tail @8 (reader), u64 capacity @16,
 * u32 magic @24, u64 sleep flag @32, data @64 modulo capacity.
 */

#define RING_HDR 64

static void ring_out(uint8_t *mm, Py_ssize_t cap, Py_ssize_t pos,
                     const uint8_t *src, Py_ssize_t len) {
    Py_ssize_t off = pos % cap;
    Py_ssize_t first = cap - off < len ? cap - off : len;
    memcpy(mm + RING_HDR + off, src, (size_t)first);
    if (first < len)
        memcpy(mm + RING_HDR, src + first, (size_t)(len - first));
}

static void ring_in(const uint8_t *mm, Py_ssize_t cap, Py_ssize_t pos,
                    uint8_t *dst, Py_ssize_t len) {
    Py_ssize_t off = pos % cap;
    Py_ssize_t first = cap - off < len ? cap - off : len;
    memcpy(dst, mm + RING_HDR + off, (size_t)first);
    if (first < len)
        memcpy(dst + first, mm + RING_HDR, (size_t)(len - first));
}


/* ring_send(mm, head, header, payload) -> (new_head, sleep_flag)
 * Raises RingFull when the frame does not fit right now (caller sleeps
 * and retries), ValueError when it can never fit (> capacity/2), and
 * Unsupported when the header needs the python codec. */
static PyObject *fastdss_ring_send(PyObject *self, PyObject *args) {
    Py_buffer mm, pay;
    Py_ssize_t head;
    PyObject *header;
    if (!PyArg_ParseTuple(args, "w*nOy*", &mm, &head, &header, &pay))
        return NULL;
    Out o = {NULL, 0, 0};
    PyObject *res = NULL;
    if (mm.len < RING_HDR) {
        PyErr_SetString(PyExc_ValueError, "ring mapping too small");
        goto done;
    }
    if (pack_obj_rec(&o, header) < 0)
        goto done;
    {
        uint8_t *base = (uint8_t *)mm.buf;
        Py_ssize_t cap = (Py_ssize_t)((uint64_t *)base)[2];
        if (cap <= 0 || RING_HDR + cap > mm.len) {
            PyErr_SetString(PyExc_ValueError, "bad ring capacity");
            goto done;
        }
        Py_ssize_t need = 8 + o.len + pay.len;
        if (need > cap / 2) {
            PyErr_Format(TooBig,
                         "frame of %zd bytes exceeds the %zd-byte ring's "
                         "single-frame limit", need, cap);
            goto done;
        }
        uint64_t tail = __atomic_load_n((uint64_t *)base + 1,
                                        __ATOMIC_ACQUIRE);
        if ((uint64_t)head - tail + (uint64_t)need > (uint64_t)cap) {
            PyErr_SetString(RingFull, "ring full");
            goto done;
        }
        uint32_t lens[2] = {(uint32_t)(o.len + pay.len), (uint32_t)o.len};
        ring_out(base, cap, head, (const uint8_t *)lens, 8);
        ring_out(base, cap, head + 8, o.buf, o.len);
        if (pay.len)
            ring_out(base, cap, head + 8 + o.len,
                     (const uint8_t *)pay.buf, pay.len);
        uint64_t new_head = (uint64_t)head + (uint64_t)need;
        __atomic_store_n((uint64_t *)base, new_head, __ATOMIC_RELEASE);
        uint64_t sleeping = ((uint64_t *)base)[4];
        res = Py_BuildValue("(Ln)", (long long)new_head,
                            (Py_ssize_t)(sleeping ? 1 : 0));
    }
done:
    PyMem_Free(o.buf);
    PyBuffer_Release(&mm);
    PyBuffer_Release(&pay);
    return res;
}

/* ring_recv(mm, tail) -> None | (header, payload_bytes, new_tail)
 * Decodes the header straight from the ring (wraparound staged through
 * a stack/heap buffer only when the frame wraps); release-stores the
 * new tail.  Raises ValueError on corruption, Unsupported when the
 * header carries a tag only the python codec knows (caller drains via
 * the python path). */
static PyObject *fastdss_ring_recv(PyObject *self, PyObject *args) {
    Py_buffer mm;
    Py_ssize_t tail;
    if (!PyArg_ParseTuple(args, "w*n", &mm, &tail))
        return NULL;
    PyObject *res = NULL;
    uint8_t *staged = NULL;
    if (mm.len < RING_HDR) {
        PyErr_SetString(PyExc_ValueError, "ring mapping too small");
        goto out;
    }
    {
        uint8_t *base = (uint8_t *)mm.buf;
        Py_ssize_t cap = (Py_ssize_t)((uint64_t *)base)[2];
        if (cap <= 0 || RING_HDR + cap > mm.len) {
            PyErr_SetString(PyExc_ValueError, "bad ring capacity");
            goto out;
        }
        uint64_t head = __atomic_load_n((uint64_t *)base, __ATOMIC_ACQUIRE);
        int64_t avail = (int64_t)(head - (uint64_t)tail);
        if (avail == 0) {
            res = Py_None;
            Py_INCREF(res);
            goto out;
        }
        if (avail < 8 || avail > cap) {
            PyErr_SetString(PyExc_ValueError, "corrupt ring state");
            goto out;
        }
        uint32_t lens[2];
        ring_in(base, cap, tail, (uint8_t *)lens, 8);
        Py_ssize_t total = (Py_ssize_t)lens[0];
        Py_ssize_t hdr_len = (Py_ssize_t)lens[1];
        if (total < hdr_len || 8 + total > avail) {
            PyErr_SetString(PyExc_ValueError, "corrupt ring frame");
            goto out;
        }
        /* frame body: contiguous in the mapping unless it wraps */
        Py_ssize_t body_off = (tail + 8) % cap;
        const uint8_t *body;
        if (body_off + total <= cap) {
            body = base + RING_HDR + body_off;
        } else {
            staged = (uint8_t *)PyMem_Malloc((size_t)total);
            if (!staged) { PyErr_NoMemory(); goto out; }
            ring_in(base, cap, tail + 8, staged, total);
            body = staged;
        }
        In in = {body, hdr_len, 0};
        PyObject *header = unpack_obj_rec(&in);
        if (!header)
            goto out;
        if (in.pos != hdr_len) {
            Py_DECREF(header);
            PyErr_SetString(PyExc_ValueError, "trailing header bytes");
            goto out;
        }
        PyObject *payload = PyBytes_FromStringAndSize(
            (const char *)(body + hdr_len), total - hdr_len);
        if (!payload) { Py_DECREF(header); goto out; }
        /* build the python result BEFORE the tail store publishes the
         * slot back to the writer: an allocation failure here must not
         * desync the shm tail from the reader's python-side mirror */
        uint64_t new_tail = (uint64_t)tail + 8 + (uint64_t)total;
        PyObject *tup = PyTuple_New(3);
        PyObject *nt = PyLong_FromLongLong((long long)new_tail);
        if (!tup || !nt) {
            Py_XDECREF(tup);
            Py_XDECREF(nt);
            Py_DECREF(header);
            Py_DECREF(payload);
            goto out;
        }
        PyTuple_SET_ITEM(tup, 0, header);
        PyTuple_SET_ITEM(tup, 1, payload);
        PyTuple_SET_ITEM(tup, 2, nt);
        __atomic_store_n((uint64_t *)base + 1, new_tail, __ATOMIC_RELEASE);
        res = tup;
    }
out:
    PyMem_Free(staged);
    PyBuffer_Release(&mm);
    return res;
}
