/* fastdss — CPython-C-API codec for the DSS wire format's common subset.
 *
 * ≈ the reference's compiled opal/dss pack/unpack (dss_pack.c/dss_unpack.c):
 * every shm/tcp frame header and RML control message pays one encode +
 * one decode; the optimized pure-python codec costs ~3.3/3.8 µs per
 * 7-key header, this module ~0.3/0.4 µs.  The ctypes route was measured
 * and rejected (call marshalling exceeded the work saved) — the C API's
 * ~100 ns call overhead is what makes native pay here.
 *
 * Wire format (must stay byte-identical to ompi_tpu/core/dss.py):
 *   [1B tag][payload]; u32 little-endian lengths for var-size payloads.
 * Handled tags: NONE, BOOL, INT64, FLOAT64, STRING, BYTES, LIST, TUPLE,
 * DICT.  Anything else (ndarray, exotic types, out-of-range ints) raises
 * Unsupported and the caller falls back to the python codec; truncated
 * or corrupt input raises ValueError (the wrapper converts to DSSError).
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <stdint.h>
#include <string.h>

#define T_INT64 1
#define T_FLOAT64 2
#define T_STRING 3
#define T_BYTES 4
#define T_BOOL 5
#define T_NONE 6
#define T_LIST 7
#define T_DICT 8
#define T_TUPLE 10

static PyObject *Unsupported;
static PyObject *RingFull;
static PyObject *TooBig;

/* -- growable output buffer -------------------------------------------- */

typedef struct {
    uint8_t *buf;
    Py_ssize_t len;
    Py_ssize_t cap;
} Out;

static int out_reserve(Out *o, Py_ssize_t extra) {
    if (o->len + extra <= o->cap) return 0;
    Py_ssize_t ncap = o->cap ? o->cap * 2 : 256;
    while (ncap < o->len + extra) ncap *= 2;
    uint8_t *nb = (uint8_t *)PyMem_Realloc(o->buf, (size_t)ncap);
    if (!nb) { PyErr_NoMemory(); return -1; }
    o->buf = nb;
    o->cap = ncap;
    return 0;
}

static int out_put(Out *o, const void *src, Py_ssize_t n) {
    if (out_reserve(o, n) < 0) return -1;
    memcpy(o->buf + o->len, src, (size_t)n);
    o->len += n;
    return 0;
}

static int out_u8(Out *o, uint8_t b) { return out_put(o, &b, 1); }

static int out_u32(Out *o, uint32_t v) {
    uint8_t le[4] = {(uint8_t)v, (uint8_t)(v >> 8), (uint8_t)(v >> 16),
                     (uint8_t)(v >> 24)};
    return out_put(o, le, 4);
}

/* -- pack ---------------------------------------------------------------
 * Returns 0 ok, -1 error set.  Unsupported values raise Unsupported —
 * the python wrapper falls back to the general codec for the WHOLE call
 * (wire compatibility: partial native output is discarded). */

static int pack_obj(Out *o, PyObject *v);

static int pack_obj_rec(Out *o, PyObject *v) {
    /* C-stack guard: a deeply nested structure must raise, not segfault
     * (the python codec raises RecursionError for the same input) */
    if (Py_EnterRecursiveCall(" in fastdss pack")) return -1;
    int rc = pack_obj(o, v);
    Py_LeaveRecursiveCall();
    return rc;
}

static int pack_obj(Out *o, PyObject *v) {
    if (v == Py_None) return out_u8(o, T_NONE);
    if (v == Py_True) { uint8_t b[2] = {T_BOOL, 1}; return out_put(o, b, 2); }
    if (v == Py_False) { uint8_t b[2] = {T_BOOL, 0}; return out_put(o, b, 2); }
    if (PyLong_CheckExact(v)) {
        int overflow = 0;
        int64_t x = (int64_t)PyLong_AsLongLongAndOverflow(v, &overflow);
        if (overflow || (x == -1 && PyErr_Occurred())) {
            PyErr_Clear();
            PyErr_SetString(Unsupported, "int out of int64 range");
            return -1;
        }
        uint8_t rec[9];
        rec[0] = T_INT64;
        memcpy(rec + 1, &x, 8); /* little-endian hosts only (x86/arm64) */
        return out_put(o, rec, 9);
    }
    if (PyFloat_CheckExact(v)) {
        double d = PyFloat_AS_DOUBLE(v);
        uint8_t rec[9];
        rec[0] = T_FLOAT64;
        memcpy(rec + 1, &d, 8);
        return out_put(o, rec, 9);
    }
    if (PyUnicode_CheckExact(v)) {
        Py_ssize_t n;
        const char *s = PyUnicode_AsUTF8AndSize(v, &n);
        if (!s) return -1;
        if (n > (Py_ssize_t)0xFFFFFFFF) {
            PyErr_SetString(Unsupported, "string exceeds u32 length");
            return -1;
        }
        if (out_u8(o, T_STRING) < 0 || out_u32(o, (uint32_t)n) < 0)
            return -1;
        return out_put(o, s, n);
    }
    if (PyBytes_CheckExact(v)) {
        Py_ssize_t n = PyBytes_GET_SIZE(v);
        if (n > (Py_ssize_t)0xFFFFFFFF) {
            PyErr_SetString(Unsupported, "bytes exceed u32 length");
            return -1;
        }
        if (out_u8(o, T_BYTES) < 0 || out_u32(o, (uint32_t)n) < 0)
            return -1;
        return out_put(o, PyBytes_AS_STRING(v), n);
    }
    if (PyList_CheckExact(v) || PyTuple_CheckExact(v)) {
        int is_list = PyList_CheckExact(v);
        Py_ssize_t n = is_list ? PyList_GET_SIZE(v) : PyTuple_GET_SIZE(v);
        if (n > (Py_ssize_t)0xFFFFFFFF) {
            PyErr_SetString(Unsupported, "sequence exceeds u32 length");
            return -1;
        }
        if (out_u8(o, is_list ? T_LIST : T_TUPLE) < 0 ||
            out_u32(o, (uint32_t)n) < 0)
            return -1;
        for (Py_ssize_t i = 0; i < n; i++) {
            PyObject *it = is_list ? PyList_GET_ITEM(v, i)
                                   : PyTuple_GET_ITEM(v, i);
            if (pack_obj_rec(o, it) < 0) return -1;
        }
        return 0;
    }
    if (PyDict_CheckExact(v)) {
        Py_ssize_t n = PyDict_GET_SIZE(v);
        if (out_u8(o, T_DICT) < 0 || out_u32(o, (uint32_t)n) < 0) return -1;
        PyObject *key, *val;
        Py_ssize_t pos = 0;
        while (PyDict_Next(v, &pos, &key, &val)) {
            if (pack_obj_rec(o, key) < 0 || pack_obj_rec(o, val) < 0)
                return -1;
        }
        return 0;
    }
    PyErr_Format(Unsupported, "fastdss cannot pack %s",
                 Py_TYPE(v)->tp_name);
    return -1;
}

static PyObject *fastdss_pack(PyObject *self, PyObject *values) {
    /* values: a tuple of the objects to pack in sequence */
    if (!PyTuple_CheckExact(values)) {
        PyErr_SetString(PyExc_TypeError, "pack expects a tuple");
        return NULL;
    }
    Out o = {NULL, 0, 0};
    for (Py_ssize_t i = 0; i < PyTuple_GET_SIZE(values); i++) {
        if (pack_obj(&o, PyTuple_GET_ITEM(values, i)) < 0) {
            PyMem_Free(o.buf);
            return NULL;
        }
    }
    PyObject *out = PyBytes_FromStringAndSize((const char *)o.buf, o.len);
    PyMem_Free(o.buf);
    return out;
}

/* -- unpack ------------------------------------------------------------ */

typedef struct {
    const uint8_t *d;
    Py_ssize_t len;
    Py_ssize_t pos;
} In;

static int need(In *in, Py_ssize_t n) {
    if (in->pos + n > in->len) {
        PyErr_SetString(PyExc_ValueError, "buffer underrun");
        return -1;
    }
    return 0;
}

static uint32_t rd_u32(In *in) {
    const uint8_t *p = in->d + in->pos;
    in->pos += 4;
    return (uint32_t)p[0] | ((uint32_t)p[1] << 8) | ((uint32_t)p[2] << 16) |
           ((uint32_t)p[3] << 24);
}

static PyObject *unpack_obj(In *in);

static PyObject *unpack_obj_rec(In *in) {
    if (Py_EnterRecursiveCall(" in fastdss unpack")) return NULL;
    PyObject *v = unpack_obj(in);
    Py_LeaveRecursiveCall();
    return v;
}

static PyObject *unpack_obj(In *in) {
    if (need(in, 1) < 0) return NULL;
    uint8_t tag = in->d[in->pos++];
    switch (tag) {
    case T_NONE:
        Py_RETURN_NONE;
    case T_BOOL: {
        if (need(in, 1) < 0) return NULL;
        uint8_t b = in->d[in->pos++];
        if (b) Py_RETURN_TRUE;
        Py_RETURN_FALSE;
    }
    case T_INT64: {
        if (need(in, 8) < 0) return NULL;
        int64_t x;
        memcpy(&x, in->d + in->pos, 8);
        in->pos += 8;
        return PyLong_FromLongLong((long long)x);
    }
    case T_FLOAT64: {
        if (need(in, 8) < 0) return NULL;
        double d;
        memcpy(&d, in->d + in->pos, 8);
        in->pos += 8;
        return PyFloat_FromDouble(d);
    }
    case T_STRING: {
        if (need(in, 4) < 0) return NULL;
        uint32_t n = rd_u32(in);
        if (need(in, (Py_ssize_t)n) < 0) return NULL;
        PyObject *s = PyUnicode_DecodeUTF8(
            (const char *)(in->d + in->pos), (Py_ssize_t)n, NULL);
        in->pos += n;
        return s;
    }
    case T_BYTES: {
        if (need(in, 4) < 0) return NULL;
        uint32_t n = rd_u32(in);
        if (need(in, (Py_ssize_t)n) < 0) return NULL;
        PyObject *b = PyBytes_FromStringAndSize(
            (const char *)(in->d + in->pos), (Py_ssize_t)n);
        in->pos += n;
        return b;
    }
    case T_LIST:
    case T_TUPLE: {
        if (need(in, 4) < 0) return NULL;
        uint32_t n = rd_u32(in);
        /* a hostile length can't exceed the remaining bytes: every item
         * is >= 1 byte, so bound the allocation before trusting it */
        if ((Py_ssize_t)n > in->len - in->pos) {
            PyErr_SetString(PyExc_ValueError, "buffer underrun in list");
            return NULL;
        }
        PyObject *seq = (tag == T_LIST) ? PyList_New((Py_ssize_t)n)
                                        : PyTuple_New((Py_ssize_t)n);
        if (!seq) return NULL;
        for (uint32_t i = 0; i < n; i++) {
            PyObject *it = unpack_obj_rec(in);
            if (!it) { Py_DECREF(seq); return NULL; }
            if (tag == T_LIST) PyList_SET_ITEM(seq, i, it);
            else PyTuple_SET_ITEM(seq, i, it);
        }
        return seq;
    }
    case T_DICT: {
        if (need(in, 4) < 0) return NULL;
        uint32_t n = rd_u32(in);
        if ((Py_ssize_t)n * 2 > in->len - in->pos) {
            PyErr_SetString(PyExc_ValueError, "buffer underrun in dict");
            return NULL;
        }
        PyObject *d = PyDict_New();
        if (!d) return NULL;
        for (uint32_t i = 0; i < n; i++) {
            PyObject *k = unpack_obj_rec(in);
            if (!k) { Py_DECREF(d); return NULL; }
            PyObject *v = unpack_obj_rec(in);
            if (!v) { Py_DECREF(k); Py_DECREF(d); return NULL; }
            int rc = PyDict_SetItem(d, k, v);
            Py_DECREF(k);
            Py_DECREF(v);
            if (rc < 0) { Py_DECREF(d); return NULL; }
        }
        return d;
    }
    default:
        /* ndarray or unknown: let the python codec handle the whole call */
        PyErr_Format(Unsupported, "fastdss cannot unpack tag %d", tag);
        return NULL;
    }
}

static PyObject *fastdss_unpack(PyObject *self, PyObject *args) {
    Py_buffer view;
    Py_ssize_t limit = -1;
    if (!PyArg_ParseTuple(args, "y*|n", &view, &limit)) return NULL;
    In in = {(const uint8_t *)view.buf, view.len, 0};
    PyObject *out = PyList_New(0);
    if (!out) { PyBuffer_Release(&view); return NULL; }
    while (in.pos < in.len &&
           (limit < 0 || PyList_GET_SIZE(out) < limit)) {
        PyObject *v = unpack_obj(&in);
        if (!v) { Py_DECREF(out); PyBuffer_Release(&view); return NULL; }
        int rc = PyList_Append(out, v);
        Py_DECREF(v);
        if (rc < 0) { Py_DECREF(out); PyBuffer_Release(&view); return NULL; }
    }
    PyBuffer_Release(&view);
    return out;
}

/* -- shared-memory atomics (sharedfp/sm, host-side counters) ----------- */

static int atomic_slot(Py_buffer *mm, Py_ssize_t off, uint64_t **slot) {
    if (off < 0 || off % 8 || off + 8 > mm->len) {
        PyErr_SetString(PyExc_ValueError, "bad atomic slot offset");
        return -1;
    }
    *slot = (uint64_t *)((uint8_t *)mm->buf + off);
    return 0;
}

static PyObject *fastdss_atomic_add(PyObject *self, PyObject *args) {
    Py_buffer mm;
    Py_ssize_t off;
    long long delta;
    if (!PyArg_ParseTuple(args, "w*nL", &mm, &off, &delta)) return NULL;
    uint64_t *slot;
    PyObject *res = NULL;
    if (atomic_slot(&mm, off, &slot) == 0) {
        uint64_t old = __atomic_fetch_add(slot, (uint64_t)(int64_t)delta,
                                          __ATOMIC_ACQ_REL);
        res = PyLong_FromUnsignedLongLong(old);
    }
    PyBuffer_Release(&mm);
    return res;
}

static PyObject *fastdss_atomic_load(PyObject *self, PyObject *args) {
    Py_buffer mm;
    Py_ssize_t off;
    if (!PyArg_ParseTuple(args, "w*n", &mm, &off)) return NULL;
    uint64_t *slot;
    PyObject *res = NULL;
    if (atomic_slot(&mm, off, &slot) == 0)
        res = PyLong_FromUnsignedLongLong(
            __atomic_load_n(slot, __ATOMIC_ACQUIRE));
    PyBuffer_Release(&mm);
    return res;
}

static PyObject *fastdss_atomic_store(PyObject *self, PyObject *args) {
    Py_buffer mm;
    Py_ssize_t off;
    unsigned long long v;
    if (!PyArg_ParseTuple(args, "w*nK", &mm, &off, &v)) return NULL;
    uint64_t *slot;
    PyObject *res = NULL;
    if (atomic_slot(&mm, off, &slot) == 0) {
        __atomic_store_n(slot, (uint64_t)v, __ATOMIC_RELEASE);
        res = Py_None;
        Py_INCREF(res);
    }
    PyBuffer_Release(&mm);
    return res;
}

/* -- matching engine ----------------------------------------------------
 * The PML's matching authority in C (≈ ob1's receive matching,
 * pml_ob1_recvfrag.c:143-173, compiled): posted-recv + unexpected queues
 * per communicator, the per-(peer,cid) wire-sequence gate with held
 * out-of-order frames, and wildcard matching with the reserved-tag
 * guard.  Every method MUST be called with the PML lock held — the
 * engine itself takes no locks (it replaces the pure-python structures
 * those same lock-holding code paths used to mutate).
 *
 * Matching results come back as small "action" tuples the caller
 * executes in Python (deliver / CTS / sack / nack / event emission):
 * the protocol stays in Python, only the hot bookkeeping is native.
 */

typedef struct MatchPosted {
    int64_t source, tag;
    PyObject *req;             /* owned */
    Py_buffer buf;             /* valid iff has_buf: posted contiguous dst */
    int has_buf;
    int64_t itemsize;          /* recv element size (status.count) */
    int64_t max_bytes;         /* truncation bound (count·size); -1 = none */
    struct MatchPosted *next;
} MatchPosted;

typedef struct MatchUnex {
    int64_t peer, tag;
    PyObject *hdr;             /* owned dict */
    PyObject *payload;         /* owned bytes */
    struct MatchUnex *next;
} MatchUnex;

typedef struct CidEntry {
    int64_t cid;
    MatchPosted *ph, *pt;      /* posted queue, FIFO */
    MatchUnex *uh, *ut;        /* unexpected queue, arrival order */
    struct CidEntry *next;
} CidEntry;

typedef struct SeqEntry {
    int64_t peer, cid;
    int64_t expect;
    struct SeqEntry *next;
} SeqEntry;

typedef struct {
    PyObject_HEAD
    CidEntry *cids;
    SeqEntry *seqs;
    PyObject *held;            /* {(peer,cid): {seq: (hdr, payload)}} */
} EngineObject;

#define ENG_ANY_SOURCE (-1)    /* ompi_tpu.mpi.constants.ANY_SOURCE */
#define ENG_ANY_TAG (-2)       /* ompi_tpu.mpi.constants.ANY_TAG */

static int eng_matches(int64_t want_src, int64_t want_tag,
                       int64_t peer, int64_t tag) {
    if (want_src != ENG_ANY_SOURCE && want_src != peer) return 0;
    if (want_tag == ENG_ANY_TAG)
        return tag >= 0;   /* wildcard never matches reserved tags */
    return want_tag == tag;
}

static CidEntry *eng_cid(EngineObject *e, int64_t cid, int create) {
    CidEntry *c = e->cids;
    for (; c; c = c->next)
        if (c->cid == cid) return c;
    if (!create) return NULL;
    c = (CidEntry *)PyMem_Calloc(1, sizeof(CidEntry));
    if (!c) { PyErr_NoMemory(); return NULL; }
    c->cid = cid;
    c->next = e->cids;
    e->cids = c;
    return c;
}

static SeqEntry *eng_seq(EngineObject *e, int64_t peer, int64_t cid,
                         int create) {
    SeqEntry *s = e->seqs;
    for (; s; s = s->next)
        if (s->peer == peer && s->cid == cid) return s;
    if (!create) return NULL;
    s = (SeqEntry *)PyMem_Calloc(1, sizeof(SeqEntry));
    if (!s) { PyErr_NoMemory(); return NULL; }
    s->peer = peer;
    s->cid = cid;
    s->next = e->seqs;
    e->seqs = s;
    return s;
}

static void eng_free_posted(MatchPosted *p) {
    if (p->has_buf) PyBuffer_Release(&p->buf);
    Py_XDECREF(p->req);
    PyMem_Free(p);
}

static void eng_free_unex(MatchUnex *u) {
    Py_XDECREF(u->hdr);
    Py_XDECREF(u->payload);
    PyMem_Free(u);
}

static int64_t eng_dict_i64(PyObject *d, const char *key, int64_t dflt,
                            int *found) {
    PyObject *v = PyDict_GetItemString(d, key);   /* borrowed */
    if (found) *found = v != NULL;
    if (!v) return dflt;
    return (int64_t)PyLong_AsLongLong(v);
}

/* payload stored beyond the call must own its bytes (zero-copy self/proc
 * payloads alias the sender's live buffer) */
static PyObject *eng_own_bytes(PyObject *payload) {
    if (PyBytes_CheckExact(payload)) {
        Py_INCREF(payload);
        return payload;
    }
    return PyBytes_FromObject(payload);
}

/* match one in-order data frame; appends one action tuple to `acts`.
 * Returns 0 ok / -1 error. */
static int eng_match_one(EngineObject *e, int64_t peer, PyObject *hdr,
                         PyObject *payload, PyObject *acts) {
    int64_t cid = eng_dict_i64(hdr, "cid", 0, NULL);
    int64_t tag = eng_dict_i64(hdr, "tag", 0, NULL);
    if (PyErr_Occurred()) return -1;
    CidEntry *c = eng_cid(e, cid, 1);
    if (!c) return -1;
    MatchPosted *p = c->ph, *prev = NULL;
    for (; p; prev = p, p = p->next) {
        if (eng_matches(p->source, p->tag, peer, tag)) {
            if (prev) prev->next = p->next; else c->ph = p->next;
            if (c->pt == p) c->pt = prev;
            PyObject *act = Py_BuildValue("(sOLOO)", "match", p->req,
                                          (long long)peer, hdr, payload);
            int rc = act ? PyList_Append(acts, act) : -1;
            Py_XDECREF(act);
            eng_free_posted(p);
            return rc;
        }
    }
    /* no posted match */
    PyObject *sm = PyDict_GetItemString(hdr, "sm");
    if (sm && PyUnicode_CheckExact(sm)
        && PyUnicode_CompareWithASCIIString(sm, "r") == 0) {
        PyObject *act = Py_BuildValue("(sLO)", "rnack", (long long)peer,
                                      hdr);
        int rc = act ? PyList_Append(acts, act) : -1;
        Py_XDECREF(act);
        return rc;
    }
    MatchUnex *u = (MatchUnex *)PyMem_Calloc(1, sizeof(MatchUnex));
    if (!u) { PyErr_NoMemory(); return -1; }
    u->peer = peer;
    u->tag = tag;
    Py_INCREF(hdr);
    u->hdr = hdr;
    u->payload = eng_own_bytes(payload);
    if (!u->payload) { eng_free_unex(u); return -1; }
    if (c->ut) c->ut->next = u; else c->uh = u;
    c->ut = u;
    PyObject *act = Py_BuildValue("(sLO)", "unexpected", (long long)peer,
                                  hdr);
    int rc = act ? PyList_Append(acts, act) : -1;
    Py_XDECREF(act);
    return rc;
}

static PyObject *Engine_post(EngineObject *e, PyObject *args) {
    /* post(cid, source, tag, req, buf_or_None, itemsize, max_bytes)
     *   → None (posted) | (peer, hdr, payload) unexpected hit (removed) */
    long long cid, source, tag, itemsize, max_bytes = -1;
    PyObject *req, *buf;
    if (!PyArg_ParseTuple(args, "LLLOOL|L", &cid, &source, &tag, &req,
                          &buf, &itemsize, &max_bytes))
        return NULL;
    CidEntry *c = eng_cid(e, cid, 1);
    if (!c) return NULL;
    MatchUnex *u = c->uh, *prev = NULL;
    for (; u; prev = u, u = u->next) {
        if (eng_matches(source, tag, u->peer, u->tag)) {
            if (prev) prev->next = u->next; else c->uh = u->next;
            if (c->ut == u) c->ut = prev;
            PyObject *out = Py_BuildValue("(LOO)", (long long)u->peer,
                                          u->hdr, u->payload);
            eng_free_unex(u);
            return out;
        }
    }
    MatchPosted *p = (MatchPosted *)PyMem_Calloc(1, sizeof(MatchPosted));
    if (!p) return PyErr_NoMemory();
    p->source = source;
    p->tag = tag;
    p->itemsize = itemsize > 0 ? itemsize : 1;
    p->max_bytes = max_bytes;
    if (buf != Py_None) {
        if (PyObject_GetBuffer(buf, &p->buf,
                               PyBUF_WRITABLE | PyBUF_C_CONTIGUOUS) == 0)
            p->has_buf = 1;
        else
            PyErr_Clear();   /* exotic buffer: deliver via python path */
    }
    Py_INCREF(req);
    p->req = req;
    if (c->pt) c->pt->next = p; else c->ph = p;
    c->pt = p;
    Py_RETURN_NONE;
}

static PyObject *Engine_cancel(EngineObject *e, PyObject *args) {
    /* cancel(cid, req) → True iff the posted entry was removed */
    long long cid;
    PyObject *req;
    if (!PyArg_ParseTuple(args, "LO", &cid, &req)) return NULL;
    CidEntry *c = eng_cid(e, cid, 0);
    if (c) {
        MatchPosted *p = c->ph, *prev = NULL;
        for (; p; prev = p, p = p->next) {
            if (p->req == req) {
                if (prev) prev->next = p->next; else c->ph = p->next;
                if (c->pt == p) c->pt = prev;
                eng_free_posted(p);
                Py_RETURN_TRUE;
            }
        }
    }
    Py_RETURN_FALSE;
}

static PyObject *Engine_iprobe(EngineObject *e, PyObject *args) {
    /* iprobe(cid, source, tag) → None | (peer, hdr)  (not removed) */
    long long cid, source, tag;
    if (!PyArg_ParseTuple(args, "LLL", &cid, &source, &tag)) return NULL;
    CidEntry *c = eng_cid(e, cid, 0);
    if (c) {
        MatchUnex *u = c->uh;
        for (; u; u = u->next)
            if (eng_matches(source, tag, u->peer, u->tag))
                return Py_BuildValue("(LO)", (long long)u->peer, u->hdr);
    }
    Py_RETURN_NONE;
}

static PyObject *Engine_improbe(EngineObject *e, PyObject *args) {
    /* improbe(cid, source, tag) → None | (peer, hdr, payload) (removed —
     * the match-and-detach MPI_Mprobe exists for) */
    long long cid, source, tag;
    if (!PyArg_ParseTuple(args, "LLL", &cid, &source, &tag)) return NULL;
    CidEntry *c = eng_cid(e, cid, 0);
    if (c) {
        MatchUnex *u = c->uh, *prev = NULL;
        for (; u; prev = u, u = u->next) {
            if (eng_matches(source, tag, u->peer, u->tag)) {
                if (prev) prev->next = u->next; else c->uh = u->next;
                if (c->ut == u) c->ut = prev;
                PyObject *out = Py_BuildValue("(LOO)", (long long)u->peer,
                                              u->hdr, u->payload);
                eng_free_unex(u);
                return out;
            }
        }
    }
    Py_RETURN_NONE;
}

static int64_t eng_drain_held(EngineObject *e, int64_t peer, int64_t cid,
                              int64_t nxt, PyObject *acts);

/* run the seq gate for one frame, then match it and any held
 * continuations.  Appends actions; 0 ok / -1 error. */
static int eng_gate_and_match(EngineObject *e, int64_t peer, PyObject *hdr,
                              PyObject *payload, PyObject *acts) {
    int has_seq = 0;
    int64_t seq = eng_dict_i64(hdr, "seq", 0, &has_seq);
    int64_t cid = eng_dict_i64(hdr, "cid", 0, NULL);
    if (PyErr_Occurred()) return -1;
    if (!has_seq)
        return eng_match_one(e, peer, hdr, payload, acts);
    SeqEntry *s = eng_seq(e, peer, cid, 1);
    if (!s) return -1;
    if (seq != s->expect) {
        /* early frame: hold (owning copies) until its turn */
        PyObject *key = Py_BuildValue("(LL)", (long long)peer,
                                      (long long)cid);
        if (!key) return -1;
        PyObject *per = PyDict_GetItem(e->held, key);   /* borrowed */
        if (!per) {
            per = PyDict_New();
            if (!per || PyDict_SetItem(e->held, key, per) < 0) {
                Py_XDECREF(per);
                Py_DECREF(key);
                return -1;
            }
            Py_DECREF(per);   /* held dict keeps it alive */
            per = PyDict_GetItem(e->held, key);
        }
        Py_DECREF(key);
        PyObject *owned = eng_own_bytes(payload);
        if (!owned) return -1;
        PyObject *val = Py_BuildValue("(ON)", hdr, owned);
        if (!val) return -1;
        PyObject *k2 = PyLong_FromLongLong((long long)seq);
        int rc = k2 ? PyDict_SetItem(per, k2, val) : -1;
        Py_XDECREF(k2);
        Py_DECREF(val);
        return rc;
    }
    if (eng_match_one(e, peer, hdr, payload, acts) < 0) return -1;
    int64_t nxt = eng_drain_held(e, peer, cid, seq + 1, acts);
    if (nxt < 0) return -1;
    s->expect = nxt;
    return 0;
}

static PyObject *Engine_incoming(EngineObject *e, PyObject *args) {
    /* incoming(peer, hdr, payload) → [actions]
     * action ∈ ("match", req, peer, hdr, payload)
     *        | ("unexpected", peer, hdr)
     *        | ("rnack", peer, hdr)                                   */
    long long peer;
    PyObject *hdr, *payload;
    if (!PyArg_ParseTuple(args, "LO!O", &peer, &PyDict_Type, &hdr,
                          &payload))
        return NULL;
    PyObject *acts = PyList_New(0);
    if (!acts) return NULL;
    if (eng_gate_and_match(e, peer, hdr, payload, acts) < 0) {
        Py_DECREF(acts);
        return NULL;
    }
    return acts;
}

/* drain held continuations after `expect` advanced past an accepted
 * frame; returns the new expect value or -1 on error */
static int64_t eng_drain_held(EngineObject *e, int64_t peer, int64_t cid,
                              int64_t nxt, PyObject *acts) {
    PyObject *key = Py_BuildValue("(LL)", (long long)peer, (long long)cid);
    if (!key) return -1;
    PyObject *per = PyDict_GetItem(e->held, key);   /* borrowed */
    while (per) {
        PyObject *k2 = PyLong_FromLongLong((long long)nxt);
        if (!k2) { Py_DECREF(key); return -1; }
        PyObject *val = PyDict_GetItem(per, k2);    /* borrowed */
        if (!val) { Py_DECREF(k2); break; }
        Py_INCREF(val);
        PyDict_DelItem(per, k2);
        Py_DECREF(k2);
        int rc = eng_match_one(e, peer, PyTuple_GET_ITEM(val, 0),
                               PyTuple_GET_ITEM(val, 1), acts);
        Py_DECREF(val);
        if (rc < 0) { Py_DECREF(key); return -1; }
        nxt++;
    }
    Py_DECREF(key);
    return nxt;
}

static PyObject *Engine_incoming_fast(EngineObject *e, PyObject *args) {
    /* incoming_fast(peer, tag, cid, seq, payload, dt, elems, shp)
     *   → None: NOT consumed — state untouched; the caller must take
     *     the header-dict path (out-of-order frame, truncation risk,
     *     exotic posted buffer)
     *   | [action, …held actions] where the first action is one of
     *     ("done", req, peer, tag, count, nbytes)   — payload memcpy'd
     *        into the posted contiguous buffer: match+deliver with no
     *        header object at all, or
     *     ("adeliver", req, peer, tag, payload, dt, shp) — matched an
     *        allocate-on-match recv (no posted buffer); python builds
     *        the array, or
     *     ("unexpected", peer, hdr)                 — stored in C (the
     *        header dict is materialized here, once, for later probes).
     *   Caller contract: plain eager standard frames only (no
     *   sm/sid/ep/si), engine called under the PML lock. */
    long long peer, tag, cid, seq, elems;
    Py_buffer pay;
    PyObject *dt, *shp;
    if (!PyArg_ParseTuple(args, "LLLLy*OLO", &peer, &tag, &cid, &seq,
                          &pay, &dt, &elems, &shp))
        return NULL;
    PyObject *result = NULL;
    SeqEntry *s = eng_seq(e, peer, cid, 1);
    if (!s) goto err;
    if (seq != s->expect) goto none;          /* dict path holds it */
    {
        CidEntry *c = eng_cid(e, cid, 1);
        if (!c) goto err;
        MatchPosted *p = c->ph, *prev = NULL;
        for (; p; prev = p, p = p->next)
            if (eng_matches(p->source, p->tag, peer, tag)) break;
        PyObject *acts = NULL, *act = NULL;
        if (p && p->has_buf) {
            if (pay.len > p->buf.len
                || (p->max_bytes >= 0 && pay.len > p->max_bytes))
                goto none;   /* truncation: header path raises properly */
            memcpy(p->buf.buf, pay.buf, (size_t)pay.len);
            act = Py_BuildValue(
                "(sOLLLL)", "done", p->req, (long long)peer,
                (long long)tag, (long long)(pay.len / p->itemsize),
                (long long)pay.len);
        } else if (p) {
            if (p->max_bytes >= 0 && pay.len > p->max_bytes)
                goto none;   /* posted count bound: header path raises */
            PyObject *owned = PyBytes_FromStringAndSize(
                (const char *)pay.buf, pay.len);
            if (!owned) goto err;
            act = Py_BuildValue("(sOLLNOO)", "adeliver", p->req,
                                (long long)peer, (long long)tag, owned,
                                dt, shp);
        } else {
            /* no posted recv: materialize the header dict ONCE and
             * store the frame unexpected, exactly like the dict path */
            PyObject *hdr = Py_BuildValue(
                "{s:s,s:L,s:L,s:L,s:O,s:L,s:O}", "t", "eager",
                "tag", (long long)tag, "cid", (long long)cid,
                "seq", (long long)seq, "dt", dt, "elems", (long long)elems,
                "shp", shp);
            if (!hdr) goto err;
            MatchUnex *u = (MatchUnex *)PyMem_Calloc(1, sizeof(MatchUnex));
            if (!u) { Py_DECREF(hdr); PyErr_NoMemory(); goto err; }
            u->peer = peer;
            u->tag = tag;
            u->hdr = hdr;
            u->payload = PyBytes_FromStringAndSize(
                (const char *)pay.buf, pay.len);
            if (!u->payload) { eng_free_unex(u); goto err; }
            if (c->ut) c->ut->next = u; else c->uh = u;
            c->ut = u;
            act = Py_BuildValue("(sLO)", "unexpected", (long long)peer,
                                hdr);
        }
        if (!act) goto err;
        acts = PyList_New(0);
        if (!acts || PyList_Append(acts, act) < 0) {
            Py_XDECREF(acts);
            Py_DECREF(act);
            goto err;
        }
        Py_DECREF(act);
        if (p) {
            if (prev) prev->next = p->next; else c->ph = p->next;
            if (c->pt == p) c->pt = prev;
            eng_free_posted(p);
        }
        int64_t nxt = eng_drain_held(e, peer, cid, seq + 1, acts);
        if (nxt < 0) { Py_DECREF(acts); goto err; }
        s->expect = nxt;
        result = acts;
    }
    goto out;
none:
    result = Py_None;
    Py_INCREF(result);
    goto out;
err:
    result = NULL;
out:
    PyBuffer_Release(&pay);
    return result;
}

/* -- fused shm-ring drain ----------------------------------------------
 * Decode frames straight out of a mapped SPSC ring (btl_shm layout, see
 * ring_send/ring_recv below) and run them through the matcher in one C
 * call per batch.  The plain-eager hot case copies the payload RING →
 * POSTED USER BUFFER directly (single copy, no intermediate bytes
 * object, no header object).  Declared above the ring helpers it uses.
 */

static void ring_in(const uint8_t *mm, Py_ssize_t cap, Py_ssize_t pos,
                    uint8_t *dst, Py_ssize_t len);

#define RING_HDR 64   /* identical to the ring-framing section below */

/* fast header scan: DSS dict of ONLY the plain-eager keys
 * {t:"eager", tag, cid, seq, dt, elems, shp:[ints]} → scalar fields,
 * no PyObjects.  Returns 1 = fast ok, 0 = not fast (caller builds the
 * dict), -1 = corrupt (ValueError set). */
typedef struct {
    int64_t tag, cid, seq;
    int has_tag, has_cid, has_seq;
} FastHdr;

static Py_ssize_t scan_skip_value(const uint8_t *d, Py_ssize_t len,
                                  Py_ssize_t pos, int *fast_ok) {
    if (pos >= len) return -1;
    uint8_t tag = d[pos++];
    switch (tag) {
    case T_NONE: return pos;
    case T_BOOL: return pos + 1 <= len ? pos + 1 : -1;
    case T_INT64:
    case T_FLOAT64: return pos + 8 <= len ? pos + 8 : -1;
    case T_STRING:
    case T_BYTES: {
        if (pos + 4 > len) return -1;
        uint32_t n = (uint32_t)d[pos] | ((uint32_t)d[pos + 1] << 8) |
                     ((uint32_t)d[pos + 2] << 16) |
                     ((uint32_t)d[pos + 3] << 24);
        pos += 4;
        return pos + (Py_ssize_t)n <= len ? pos + (Py_ssize_t)n : -1;
    }
    case T_LIST:
    case T_TUPLE: {
        if (pos + 4 > len) return -1;
        uint32_t n = (uint32_t)d[pos] | ((uint32_t)d[pos + 1] << 8) |
                     ((uint32_t)d[pos + 2] << 16) |
                     ((uint32_t)d[pos + 3] << 24);
        pos += 4;
        for (uint32_t i = 0; i < n; i++) {
            if (pos >= len) return -1;
            if (d[pos] != T_INT64) { *fast_ok = 0; /* still skip? no — */
                return -2; }       /* nested non-int: not scannable */
            pos += 9;
            if (pos > len) return -1;
        }
        return pos;
    }
    default:
        return -2;   /* exotic tag: let the full decoder judge it */
    }
}

static int scan_fast_hdr(const uint8_t *d, Py_ssize_t len, FastHdr *out) {
    Py_ssize_t pos = 0;
    int is_eager = 0;
    memset(out, 0, sizeof(*out));
    if (len < 5 || d[pos++] != T_DICT) return 0;
    uint32_t n = (uint32_t)d[pos] | ((uint32_t)d[pos + 1] << 8) |
                 ((uint32_t)d[pos + 2] << 16) | ((uint32_t)d[pos + 3] << 24);
    pos += 4;
    for (uint32_t i = 0; i < n; i++) {
        /* key: short string */
        if (pos + 5 > len || d[pos] != T_STRING) return 0;
        uint32_t klen = (uint32_t)d[pos + 1] | ((uint32_t)d[pos + 2] << 8) |
                        ((uint32_t)d[pos + 3] << 16) |
                        ((uint32_t)d[pos + 4] << 24);
        pos += 5;
        if (pos + (Py_ssize_t)klen > len || klen > 8) return 0;
        const char *k = (const char *)(d + pos);
        pos += klen;
        if (klen == 1 && k[0] == 't') {
            /* value must be the string "eager" */
            if (pos + 5 > len || d[pos] != T_STRING) return 0;
            uint32_t vlen = (uint32_t)d[pos + 1] |
                            ((uint32_t)d[pos + 2] << 8) |
                            ((uint32_t)d[pos + 3] << 16) |
                            ((uint32_t)d[pos + 4] << 24);
            pos += 5;
            if (pos + (Py_ssize_t)vlen > len) return 0;
            if (vlen == 5 && memcmp(d + pos, "eager", 5) == 0)
                is_eager = 1;
            else
                return 0;      /* rndv/control: dict path */
            pos += vlen;
        } else if ((klen == 3 && memcmp(k, "tag", 3) == 0) ||
                   (klen == 3 && memcmp(k, "cid", 3) == 0) ||
                   (klen == 3 && memcmp(k, "seq", 3) == 0)) {
            if (pos + 9 > len || d[pos] != T_INT64) return 0;
            int64_t v;
            memcpy(&v, d + pos + 1, 8);
            pos += 9;
            if (k[0] == 't') { out->tag = v; out->has_tag = 1; }
            else if (k[0] == 'c') { out->cid = v; out->has_cid = 1; }
            else { out->seq = v; out->has_seq = 1; }
        } else if ((klen == 2 && memcmp(k, "dt", 2) == 0) ||
                   (klen == 5 && memcmp(k, "elems", 5) == 0) ||
                   (klen == 3 && memcmp(k, "shp", 3) == 0)) {
            int fast_ok = 1;
            Py_ssize_t np_ = scan_skip_value(d, len, pos, &fast_ok);
            if (np_ < 0) return 0;   /* unscannable/odd: dict path */
            pos = np_;
        } else {
            return 0;   /* sm/sid/ep/si/size/unknown: dict path */
        }
    }
    return (is_eager && out->has_tag && out->has_cid && out->has_seq
            && pos == len) ? 1 : 0;
}

static PyObject *Engine_drain_ring(EngineObject *e, PyObject *args) {
    /* drain_ring(peer, mm, tail, limit)
     *   → (new_tail, nframes, actions)
     * Frames with t ∈ {eager, rndv} and no respawn stamps run through
     * the matcher (fast or dict path) — their actions come back for the
     * caller (holding the PML lock) to execute.  Control frames and
     * stamped frames come back as ("frame", hdr, payload) punts the
     * caller feeds to the full _on_frame AFTER releasing the lock (they
     * take the lock themselves; ordering analysis: a ring never mixes
     * incarnations, and control frames are independent state machines).
     * Failure atomicity: the loop COMMITS per frame (engine state,
     * shm tail, actions).  An error on frame k>0 therefore must not
     * throw away the k committed frames' actions — the batch stops and
     * returns them; the caller's NEXT drain call hits the bad frame
     * first (k=0, nothing committed) and only then raises: ValueError
     * on ring corruption (tail NOT advanced past the bad frame),
     * Unsupported when a header needs the python codec.
     */
    long long peer, tail, limit;
    Py_buffer mm;
    if (!PyArg_ParseTuple(args, "Lw*LL", &peer, &mm, &tail, &limit))
        return NULL;
    PyObject *acts = PyList_New(0);
    if (!acts) { PyBuffer_Release(&mm); return NULL; }
    uint8_t *staged = NULL;
    Py_ssize_t staged_cap = 0;
    long long nframes = 0;
    uint8_t *base = (uint8_t *)mm.buf;
    if (mm.len < RING_HDR) {
        PyErr_SetString(PyExc_ValueError, "ring mapping too small");
        goto fail;
    }
    {
        Py_ssize_t cap = (Py_ssize_t)((uint64_t *)base)[2];
        if (cap <= 0 || RING_HDR + cap > mm.len) {
            PyErr_SetString(PyExc_ValueError, "bad ring capacity");
            goto fail;
        }
        while (nframes < limit) {
            uint64_t head = __atomic_load_n((uint64_t *)base,
                                            __ATOMIC_ACQUIRE);
            int64_t avail = (int64_t)(head - (uint64_t)tail);
            if (avail == 0) break;
            if (avail < 8 || avail > cap) {
                PyErr_SetString(PyExc_ValueError, "corrupt ring state");
                goto fail;
            }
            uint32_t lens[2];
            ring_in(base, cap, (Py_ssize_t)tail, (uint8_t *)lens, 8);
            Py_ssize_t total = (Py_ssize_t)lens[0];
            Py_ssize_t hdr_len = (Py_ssize_t)lens[1];
            if (total < hdr_len || 8 + total > avail) {
                PyErr_SetString(PyExc_ValueError, "corrupt ring frame");
                goto fail;
            }
            Py_ssize_t body_off = (Py_ssize_t)((tail + 8) % cap);
            const uint8_t *hdr_bytes;
            int hdr_staged = 0;
            if (body_off + hdr_len <= cap) {
                hdr_bytes = base + RING_HDR + body_off;
            } else {
                if (hdr_len > staged_cap) {
                    uint8_t *ns = (uint8_t *)PyMem_Realloc(staged, hdr_len);
                    if (!ns) { PyErr_NoMemory(); goto fail; }
                    staged = ns;
                    staged_cap = hdr_len;
                }
                ring_in(base, cap, (Py_ssize_t)(tail + 8), staged,
                        hdr_len);
                hdr_bytes = staged;
                hdr_staged = 1;
            }
            Py_ssize_t pay_len = total - hdr_len;
            Py_ssize_t pay_pos = (Py_ssize_t)(tail + 8 + hdr_len);
            FastHdr fh;
            int fast = scan_fast_hdr(hdr_bytes, hdr_len, &fh);
            int consumed = 0;
            if (fast) {
                SeqEntry *s = eng_seq(e, peer, fh.cid, 1);
                if (!s) goto fail;
                if (fh.seq == s->expect) {
                    CidEntry *c = eng_cid(e, fh.cid, 1);
                    if (!c) goto fail;
                    MatchPosted *p = c->ph, *prev = NULL;
                    for (; p; prev = p, p = p->next)
                        if (eng_matches(p->source, p->tag, peer, fh.tag))
                            break;
                    if (p && p->has_buf && pay_len <= p->buf.len
                        && (p->max_bytes < 0 || pay_len <= p->max_bytes)) {
                        /* single copy: ring → posted user buffer */
                        ring_in(base, cap, pay_pos, (uint8_t *)p->buf.buf,
                                pay_len);
                        if (prev) prev->next = p->next;
                        else c->ph = p->next;
                        if (c->pt == p) c->pt = prev;
                        PyObject *act = Py_BuildValue(
                            "(sOLLLL)", "done", p->req, (long long)peer,
                            (long long)fh.tag,
                            (long long)(pay_len / p->itemsize),
                            (long long)pay_len);
                        int rc = act ? PyList_Append(acts, act) : -1;
                        Py_XDECREF(act);
                        eng_free_posted(p);
                        if (rc < 0) goto fail;
                        int64_t nxt = eng_drain_held(e, peer, fh.cid,
                                                     fh.seq + 1, acts);
                        if (nxt < 0) goto fail;
                        s->expect = nxt;
                        consumed = 1;
                    }
                }
            }
            if (!consumed) {
                /* build the dict + payload and run the generic path */
                In in = {hdr_bytes, hdr_len, 0};
                PyObject *hdr = unpack_obj_rec(&in);
                if (!hdr) goto fail;
                if (in.pos != hdr_len) {
                    Py_DECREF(hdr);
                    PyErr_SetString(PyExc_ValueError,
                                    "trailing header bytes");
                    goto fail;
                }
                PyObject *payload = PyBytes_FromStringAndSize(NULL,
                                                              pay_len);
                if (!payload) { Py_DECREF(hdr); goto fail; }
                if (pay_len)
                    ring_in(base, cap, pay_pos,
                            (uint8_t *)PyBytes_AS_STRING(payload),
                            pay_len);
                int is_data = 0;
                if (PyDict_CheckExact(hdr)) {
                    PyObject *t = PyDict_GetItemString(hdr, "t");
                    if (t && PyUnicode_CheckExact(t)
                        && (PyUnicode_CompareWithASCIIString(t, "eager")
                                == 0
                            || PyUnicode_CompareWithASCIIString(t, "rndv")
                                == 0)
                        && !PyDict_GetItemString(hdr, "si")
                        && !PyDict_GetItemString(hdr, "ep"))
                        is_data = 1;
                }
                int rc;
                if (is_data) {
                    rc = eng_gate_and_match(e, peer, hdr, payload, acts);
                } else {
                    PyObject *act = Py_BuildValue("(sOO)", "frame", hdr,
                                                  payload);
                    rc = act ? PyList_Append(acts, act) : -1;
                    Py_XDECREF(act);
                }
                Py_DECREF(hdr);
                Py_DECREF(payload);
                if (rc < 0) goto fail;
            }
            (void)hdr_staged;
            tail += 8 + total;
            __atomic_store_n((uint64_t *)base + 1, (uint64_t)tail,
                             __ATOMIC_RELEASE);
            nframes++;
        }
    }
    goto batch_done;
fail:
    if (nframes == 0) {
        PyMem_Free(staged);
        Py_DECREF(acts);
        PyBuffer_Release(&mm);
        return NULL;
    }
    /* frames before the bad one are already committed (engine state +
     * shm tail advanced per frame): return their actions — dropping
     * them would hang their completed-in-C recvs.  The next drain call
     * faces the bad frame FIRST, with nothing committed, and raises
     * cleanly for the caller's Unsupported/corrupt recovery. */
    PyErr_Clear();
batch_done:
    PyMem_Free(staged);
    {
        PyObject *out = Py_BuildValue("(LLO)", (long long)tail,
                                      (long long)nframes, acts);
        Py_DECREF(acts);
        PyBuffer_Release(&mm);
        return out;
    }
}

static PyObject *Engine_reset_peer(EngineObject *e, PyObject *args) {
    /* reset_peer(peer): drop the seq gate + held frames toward a peer
     * whose incarnation changed (≈ _adopt_incarnation's recv-side) */
    long long peer;
    if (!PyArg_ParseTuple(args, "L", &peer)) return NULL;
    SeqEntry **sp = &e->seqs;
    while (*sp) {
        if ((*sp)->peer == peer) {
            SeqEntry *dead = *sp;
            *sp = dead->next;
            PyMem_Free(dead);
        } else {
            sp = &(*sp)->next;
        }
    }
    PyObject *keys = PyDict_Keys(e->held);
    if (!keys) return NULL;
    for (Py_ssize_t i = 0; i < PyList_GET_SIZE(keys); i++) {
        PyObject *k = PyList_GET_ITEM(keys, i);
        PyObject *kp = PyTuple_GET_ITEM(k, 0);
        if (PyLong_AsLongLong(kp) == peer)
            PyDict_DelItem(e->held, k);
    }
    Py_DECREF(keys);
    Py_RETURN_NONE;
}

static PyObject *Engine_counts(EngineObject *e, PyObject *args) {
    /* counts(cid) → (n_posted, n_unexpected) — introspection/tests */
    long long cid;
    if (!PyArg_ParseTuple(args, "L", &cid)) return NULL;
    int64_t np_ = 0, nu = 0;
    CidEntry *c = eng_cid(e, cid, 0);
    if (c) {
        for (MatchPosted *p = c->ph; p; p = p->next) np_++;
        for (MatchUnex *u = c->uh; u; u = u->next) nu++;
    }
    return Py_BuildValue("(LL)", (long long)np_, (long long)nu);
}

static void Engine_dealloc(EngineObject *e) {
    CidEntry *c = e->cids;
    while (c) {
        MatchPosted *p = c->ph;
        while (p) { MatchPosted *n = p->next; eng_free_posted(p); p = n; }
        MatchUnex *u = c->uh;
        while (u) { MatchUnex *n = u->next; eng_free_unex(u); u = n; }
        CidEntry *cn = c->next;
        PyMem_Free(c);
        c = cn;
    }
    SeqEntry *s = e->seqs;
    while (s) { SeqEntry *n = s->next; PyMem_Free(s); s = n; }
    Py_XDECREF(e->held);
    Py_TYPE(e)->tp_free((PyObject *)e);
}

static PyObject *Engine_new(PyTypeObject *type, PyObject *args,
                            PyObject *kwds) {
    EngineObject *e = (EngineObject *)type->tp_alloc(type, 0);
    if (!e) return NULL;
    e->cids = NULL;
    e->seqs = NULL;
    e->held = PyDict_New();
    if (!e->held) { Py_DECREF(e); return NULL; }
    return (PyObject *)e;
}

static PyMethodDef Engine_methods[] = {
    {"post", (PyCFunction)Engine_post, METH_VARARGS,
     "post(cid, source, tag, req, buf_or_None, itemsize) -> None | "
     "(peer, hdr, payload)"},
    {"cancel", (PyCFunction)Engine_cancel, METH_VARARGS,
     "cancel(cid, req) -> bool"},
    {"iprobe", (PyCFunction)Engine_iprobe, METH_VARARGS,
     "iprobe(cid, source, tag) -> None | (peer, hdr)"},
    {"improbe", (PyCFunction)Engine_improbe, METH_VARARGS,
     "improbe(cid, source, tag) -> None | (peer, hdr, payload)"},
    {"incoming", (PyCFunction)Engine_incoming, METH_VARARGS,
     "incoming(peer, hdr, payload) -> [actions]"},
    {"incoming_fast", (PyCFunction)Engine_incoming_fast, METH_VARARGS,
     "incoming_fast(peer, tag, cid, seq, payload, dt, elems, shp) -> "
     "None | [actions]"},
    {"drain_ring", (PyCFunction)Engine_drain_ring, METH_VARARGS,
     "drain_ring(peer, mm, tail, limit) -> (new_tail, nframes, actions)"},
    {"reset_peer", (PyCFunction)Engine_reset_peer, METH_VARARGS,
     "reset_peer(peer)"},
    {"counts", (PyCFunction)Engine_counts, METH_VARARGS,
     "counts(cid) -> (n_posted, n_unexpected)"},
    {NULL, NULL, 0, NULL},
};

static PyTypeObject EngineType = {
    PyVarObject_HEAD_INIT(NULL, 0)
    /* field order matters: this file is compiled as C++ (g++), which
     * enforces declaration-order designated initializers */
    .tp_name = "_fastdss.Engine",
    .tp_basicsize = sizeof(EngineObject),
    .tp_dealloc = (destructor)Engine_dealloc,
    .tp_flags = Py_TPFLAGS_DEFAULT,
    .tp_doc = "compiled PML matching engine (call under the PML lock)",
    .tp_methods = Engine_methods,
    .tp_new = Engine_new,
};

/* -- module ------------------------------------------------------------ */

static PyObject *fastdss_ring_send(PyObject *self, PyObject *args);
static PyObject *fastdss_ring_send_fast(PyObject *self, PyObject *args);
static PyObject *fastdss_ring_recv(PyObject *self, PyObject *args);

static PyMethodDef methods[] = {
    {"pack", fastdss_pack, METH_O,
     "pack(tuple_of_values) -> bytes (DSS wire format)"},
    {"unpack", fastdss_unpack, METH_VARARGS,
     "unpack(data[, n]) -> list of values"},
    {"ring_send", fastdss_ring_send, METH_VARARGS,
     "ring_send(mm, head, header, payload) -> (new_head, sleep_flag)"},
    {"ring_send_fast", fastdss_ring_send_fast, METH_VARARGS,
     "ring_send_fast(mm, head, tag, cid, seq, dt, elems, shp, payload)"
     " -> (new_head, sleep_flag)"},
    {"ring_recv", fastdss_ring_recv, METH_VARARGS,
     "ring_recv(mm, tail) -> None | (header, payload, new_tail)"},
    {"atomic_add", fastdss_atomic_add, METH_VARARGS,
     "atomic_add(mm, offset, delta) -> old (u64 fetch-add, acq_rel)"},
    {"atomic_load", fastdss_atomic_load, METH_VARARGS,
     "atomic_load(mm, offset) -> value (u64, acquire)"},
    {"atomic_store", fastdss_atomic_store, METH_VARARGS,
     "atomic_store(mm, offset, value) (u64, release)"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_fastdss",
    "compiled DSS codec for the common control-message subset", -1,
    methods,
};

PyMODINIT_FUNC PyInit__fastdss(void) {
    PyObject *m = PyModule_Create(&moduledef);
    if (!m) return NULL;
    Unsupported = PyErr_NewException("_fastdss.Unsupported", NULL, NULL);
    if (!Unsupported || PyModule_AddObject(m, "Unsupported", Unsupported) < 0) {
        Py_XDECREF(Unsupported);
        Py_DECREF(m);
        return NULL;
    }
    RingFull = PyErr_NewException("_fastdss.RingFull", NULL, NULL);
    if (!RingFull || PyModule_AddObject(m, "RingFull", RingFull) < 0) {
        Py_XDECREF(RingFull);
        Py_DECREF(m);
        return NULL;
    }
    TooBig = PyErr_NewException("_fastdss.FrameTooBig", NULL, NULL);
    if (!TooBig || PyModule_AddObject(m, "FrameTooBig", TooBig) < 0) {
        Py_XDECREF(TooBig);
        Py_DECREF(m);
        return NULL;
    }
    if (PyType_Ready(&EngineType) < 0) {
        Py_DECREF(m);
        return NULL;
    }
    Py_INCREF(&EngineType);
    if (PyModule_AddObject(m, "Engine", (PyObject *)&EngineType) < 0) {
        Py_DECREF(&EngineType);
        Py_DECREF(m);
        return NULL;
    }
    return m;
}

/* -- fused ring framing -------------------------------------------------
 * Encode a header dict + payload DIRECTLY into the shm ring mapping and
 * publish, or decode a frame straight out of it — one C call per frame,
 * no intermediate bytes object (the shm BTL's vader-class data plane).
 * Ring layout matches btl_shm.py / convertor.cpp: u64 head @0 (writer,
 * release-store publishes), u64 tail @8 (reader), u64 capacity @16,
 * u32 magic @24, u64 sleep flag @32, data @64 modulo capacity.
 */

#define RING_HDR 64

static void ring_out(uint8_t *mm, Py_ssize_t cap, Py_ssize_t pos,
                     const uint8_t *src, Py_ssize_t len) {
    Py_ssize_t off = pos % cap;
    Py_ssize_t first = cap - off < len ? cap - off : len;
    memcpy(mm + RING_HDR + off, src, (size_t)first);
    if (first < len)
        memcpy(mm + RING_HDR, src + first, (size_t)(len - first));
}

static void ring_in(const uint8_t *mm, Py_ssize_t cap, Py_ssize_t pos,
                    uint8_t *dst, Py_ssize_t len) {
    Py_ssize_t off = pos % cap;
    Py_ssize_t first = cap - off < len ? cap - off : len;
    memcpy(dst, mm + RING_HDR + off, (size_t)first);
    if (first < len)
        memcpy(dst + first, mm + RING_HDR, (size_t)(len - first));
}


/* shared publish protocol (both senders MUST stay wire-identical):
 * validate the mapping, enforce the single-frame limit, check space,
 * write [lens | header | payload], release-store the new head.
 * Returns new_head ≥ 0 and sets *ring_db (doorbell armed); -1 with a
 * ValueError / FrameTooBig / RingFull set. */
static int64_t ring_publish(Py_buffer *mm, Py_ssize_t head,
                            const uint8_t *hdr, Py_ssize_t hdr_len,
                            Py_buffer *pay, int *ring_db) {
    uint8_t *base = (uint8_t *)mm->buf;
    if (mm->len < RING_HDR) {
        PyErr_SetString(PyExc_ValueError, "ring mapping too small");
        return -1;
    }
    Py_ssize_t cap = (Py_ssize_t)((uint64_t *)base)[2];
    if (cap <= 0 || RING_HDR + cap > mm->len) {
        PyErr_SetString(PyExc_ValueError, "bad ring capacity");
        return -1;
    }
    Py_ssize_t need = 8 + hdr_len + pay->len;
    if (need > cap / 2) {
        PyErr_Format(TooBig,
                     "frame of %zd bytes exceeds the %zd-byte ring's "
                     "single-frame limit", need, cap);
        return -1;
    }
    uint64_t tail = __atomic_load_n((uint64_t *)base + 1,
                                    __ATOMIC_ACQUIRE);
    if ((uint64_t)head - tail + (uint64_t)need > (uint64_t)cap) {
        PyErr_SetString(RingFull, "ring full");
        return -1;
    }
    uint32_t lens[2] = {(uint32_t)(hdr_len + pay->len),
                        (uint32_t)hdr_len};
    ring_out(base, cap, head, (const uint8_t *)lens, 8);
    ring_out(base, cap, head + 8, hdr, hdr_len);
    if (pay->len)
        ring_out(base, cap, head + 8 + hdr_len,
                 (const uint8_t *)pay->buf, pay->len);
    uint64_t new_head = (uint64_t)head + (uint64_t)need;
    __atomic_store_n((uint64_t *)base, new_head, __ATOMIC_RELEASE);
    *ring_db = ((uint64_t *)base)[4] ? 1 : 0;
    return (int64_t)new_head;
}

/* ring_send(mm, head, header, payload) -> (new_head, sleep_flag)
 * Raises RingFull when the frame does not fit right now (caller sleeps
 * and retries), ValueError when it can never fit (> capacity/2), and
 * Unsupported when the header needs the python codec. */
static PyObject *fastdss_ring_send(PyObject *self, PyObject *args) {
    Py_buffer mm, pay;
    Py_ssize_t head;
    PyObject *header;
    if (!PyArg_ParseTuple(args, "w*nOy*", &mm, &head, &header, &pay))
        return NULL;
    Out o = {NULL, 0, 0};
    PyObject *res = NULL;
    if (pack_obj_rec(&o, header) < 0)
        goto done;
    {
        int ring_db = 0;
        int64_t new_head = ring_publish(&mm, head, o.buf, o.len, &pay,
                                        &ring_db);
        if (new_head >= 0)
            res = Py_BuildValue("(Ln)", (long long)new_head,
                                (Py_ssize_t)ring_db);
    }
done:
    PyMem_Free(o.buf);
    PyBuffer_Release(&mm);
    PyBuffer_Release(&pay);
    return res;
}

/* ring_send_fast(mm, head, tag, cid, seq, dt, elems, shp, payload)
 *   -> (new_head, sleep_flag)
 * Builds the plain-eager header {t:"eager",tag,cid,seq,dt,elems,shp}
 * DSS-encoded straight into the ring — the sender-side twin of the
 * engine's fast header scan.  Wire-identical to dss.pack of the same
 * dict; RingFull/FrameTooBig as ring_send. */
static int out_key_str(Out *o, const char *k) {
    size_t n = strlen(k);
    if (out_u8(o, T_STRING) < 0 || out_u32(o, (uint32_t)n) < 0) return -1;
    return out_put(o, k, (Py_ssize_t)n);
}

static int out_i64_field(Out *o, const char *k, int64_t v) {
    if (out_key_str(o, k) < 0 || out_u8(o, T_INT64) < 0) return -1;
    return out_put(o, &v, 8);
}

static PyObject *fastdss_ring_send_fast(PyObject *self, PyObject *args) {
    Py_buffer mm, pay;
    Py_ssize_t head;
    long long tag, cid, seq, elems;
    PyObject *dt, *shp;
    if (!PyArg_ParseTuple(args, "w*nLLLOLO!y*", &mm, &head, &tag, &cid,
                          &seq, &dt, &elems, &PyTuple_Type, &shp, &pay))
        return NULL;
    Out o = {NULL, 0, 0};
    PyObject *res = NULL;
    {
        Py_ssize_t ndim = PyTuple_GET_SIZE(shp);
        Py_ssize_t dlen;
        const char *dstr = PyUnicode_AsUTF8AndSize(dt, &dlen);
        if (!dstr) goto done;
        if (out_u8(&o, T_DICT) < 0 || out_u32(&o, 7) < 0) goto done;
        if (out_key_str(&o, "t") < 0 || out_u8(&o, T_STRING) < 0 ||
            out_u32(&o, 5) < 0 || out_put(&o, "eager", 5) < 0)
            goto done;
        if (out_i64_field(&o, "tag", tag) < 0 ||
            out_i64_field(&o, "cid", cid) < 0 ||
            out_i64_field(&o, "seq", seq) < 0)
            goto done;
        if (out_key_str(&o, "dt") < 0 || out_u8(&o, T_STRING) < 0 ||
            out_u32(&o, (uint32_t)dlen) < 0 || out_put(&o, dstr, dlen) < 0)
            goto done;
        if (out_i64_field(&o, "elems", elems) < 0) goto done;
        if (out_key_str(&o, "shp") < 0 || out_u8(&o, T_LIST) < 0 ||
            out_u32(&o, (uint32_t)ndim) < 0)
            goto done;
        for (Py_ssize_t i = 0; i < ndim; i++) {
            int64_t d = (int64_t)PyLong_AsLongLong(
                PyTuple_GET_ITEM(shp, i));
            if (d == -1 && PyErr_Occurred()) goto done;
            if (out_u8(&o, T_INT64) < 0 || out_put(&o, &d, 8) < 0)
                goto done;
        }
        int ring_db = 0;
        int64_t new_head = ring_publish(&mm, head, o.buf, o.len, &pay,
                                        &ring_db);
        if (new_head >= 0)
            res = Py_BuildValue("(Ln)", (long long)new_head,
                                (Py_ssize_t)ring_db);
    }
done:
    PyMem_Free(o.buf);
    PyBuffer_Release(&mm);
    PyBuffer_Release(&pay);
    return res;
}

/* ring_recv(mm, tail) -> None | (header, payload_bytes, new_tail)
 * Decodes the header straight from the ring (wraparound staged through
 * a stack/heap buffer only when the frame wraps); release-stores the
 * new tail.  Raises ValueError on corruption, Unsupported when the
 * header carries a tag only the python codec knows (caller drains via
 * the python path). */
static PyObject *fastdss_ring_recv(PyObject *self, PyObject *args) {
    Py_buffer mm;
    Py_ssize_t tail;
    if (!PyArg_ParseTuple(args, "w*n", &mm, &tail))
        return NULL;
    PyObject *res = NULL;
    uint8_t *staged = NULL;
    if (mm.len < RING_HDR) {
        PyErr_SetString(PyExc_ValueError, "ring mapping too small");
        goto out;
    }
    {
        uint8_t *base = (uint8_t *)mm.buf;
        Py_ssize_t cap = (Py_ssize_t)((uint64_t *)base)[2];
        if (cap <= 0 || RING_HDR + cap > mm.len) {
            PyErr_SetString(PyExc_ValueError, "bad ring capacity");
            goto out;
        }
        uint64_t head = __atomic_load_n((uint64_t *)base, __ATOMIC_ACQUIRE);
        int64_t avail = (int64_t)(head - (uint64_t)tail);
        if (avail == 0) {
            res = Py_None;
            Py_INCREF(res);
            goto out;
        }
        if (avail < 8 || avail > cap) {
            PyErr_SetString(PyExc_ValueError, "corrupt ring state");
            goto out;
        }
        uint32_t lens[2];
        ring_in(base, cap, tail, (uint8_t *)lens, 8);
        Py_ssize_t total = (Py_ssize_t)lens[0];
        Py_ssize_t hdr_len = (Py_ssize_t)lens[1];
        if (total < hdr_len || 8 + total > avail) {
            PyErr_SetString(PyExc_ValueError, "corrupt ring frame");
            goto out;
        }
        /* frame body: contiguous in the mapping unless it wraps */
        Py_ssize_t body_off = (tail + 8) % cap;
        const uint8_t *body;
        if (body_off + total <= cap) {
            body = base + RING_HDR + body_off;
        } else {
            staged = (uint8_t *)PyMem_Malloc((size_t)total);
            if (!staged) { PyErr_NoMemory(); goto out; }
            ring_in(base, cap, tail + 8, staged, total);
            body = staged;
        }
        In in = {body, hdr_len, 0};
        PyObject *header = unpack_obj_rec(&in);
        if (!header)
            goto out;
        if (in.pos != hdr_len) {
            Py_DECREF(header);
            PyErr_SetString(PyExc_ValueError, "trailing header bytes");
            goto out;
        }
        PyObject *payload = PyBytes_FromStringAndSize(
            (const char *)(body + hdr_len), total - hdr_len);
        if (!payload) { Py_DECREF(header); goto out; }
        /* build the python result BEFORE the tail store publishes the
         * slot back to the writer: an allocation failure here must not
         * desync the shm tail from the reader's python-side mirror */
        uint64_t new_tail = (uint64_t)tail + 8 + (uint64_t)total;
        PyObject *tup = PyTuple_New(3);
        PyObject *nt = PyLong_FromLongLong((long long)new_tail);
        if (!tup || !nt) {
            Py_XDECREF(tup);
            Py_XDECREF(nt);
            Py_DECREF(header);
            Py_DECREF(payload);
            goto out;
        }
        PyTuple_SET_ITEM(tup, 0, header);
        PyTuple_SET_ITEM(tup, 1, payload);
        PyTuple_SET_ITEM(tup, 2, nt);
        __atomic_store_n((uint64_t *)base + 1, new_tail, __ATOMIC_RELEASE);
        res = tup;
    }
out:
    PyMem_Free(staged);
    PyBuffer_Release(&mm);
    return res;
}
