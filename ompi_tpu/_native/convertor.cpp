// Native convertor: the datatype pack/unpack hot loop.
//
// ≈ opal/datatype's compiled-descriptor convertor (opal_convertor_pack/
// unpack, opal_convertor.h:136,142) — the reference runs this loop in C for
// every non-contiguous send/recv; the Python layer's numpy gather is fine
// for small payloads but pays per-element index overhead.
//
// ABI 2 (run-coalescing pack plans): the Python side compiles a datatype ×
// count into a *plan* — either one strided progression (vector-class
// layouts: zero per-run metadata here), a flat list of absolute coalesced
// (offset, length) runs, or the per-item segment walk of ABI 1 for plans
// too large to expand.  Every entry point takes a ``uniform`` hint: when
// all runs share one small length the inner memcpy is specialized to a
// fixed-width copy, which removes the per-call memcpy dispatch that
// dominated the 1M-run pack (VERDICT r5 "What's weak" #6).
//
// Layout contracts:
//   *_runs:    absolute runs (off[j], len[j]) into the user buffer; the
//              packed stream is their concatenation in order.
//   *_strided: nblocks blocks of bl bytes, block i at start + i*stride.
//   pack/unpack (per-item): item i occupies [i*extent, ...); its payload
//              bytes are the runs (seg_off[j], seg_len[j]) relative to the
//              item origin, in declaration order (ABI-1 contract).

#include <cstdint>
#include <cstring>

namespace {

template <int L>
void pack_uniform(uint8_t *dst, const uint8_t *src, const int64_t *off,
                  int64_t n) {
    for (int64_t i = 0; i < n; ++i) {
        std::memcpy(dst, src + off[i], L);  // fixed-width: compiles to movs
        dst += L;
    }
}

template <int L>
void unpack_uniform(const uint8_t *src, uint8_t *dst, const int64_t *off,
                    int64_t n) {
    for (int64_t i = 0; i < n; ++i) {
        std::memcpy(dst + off[i], src, L);
        src += L;
    }
}

template <int L>
void pack_strided_fixed(uint8_t *dst, const uint8_t *src, int64_t n,
                        int64_t stride) {
    for (int64_t i = 0; i < n; ++i) {
        std::memcpy(dst, src, L);
        dst += L;
        src += stride;
    }
}

template <int L>
void unpack_strided_fixed(const uint8_t *src, uint8_t *dst, int64_t n,
                          int64_t stride) {
    for (int64_t i = 0; i < n; ++i) {
        std::memcpy(dst, src, L);
        src += L;
        dst += stride;
    }
}

}  // namespace

extern "C" {

// -- coalesced absolute-run plans -----------------------------------------

void ompi_tpu_pack_runs(uint8_t *dst, const uint8_t *src,
                        const int64_t *off, const int64_t *len,
                        int64_t n, int64_t uniform) {
    switch (uniform) {
    case 1:  pack_uniform<1>(dst, src, off, n);  return;
    case 2:  pack_uniform<2>(dst, src, off, n);  return;
    case 4:  pack_uniform<4>(dst, src, off, n);  return;
    case 8:  pack_uniform<8>(dst, src, off, n);  return;
    case 16: pack_uniform<16>(dst, src, off, n); return;
    case 32: pack_uniform<32>(dst, src, off, n); return;
    }
    for (int64_t j = 0; j < n; ++j) {
        std::memcpy(dst, src + off[j], static_cast<size_t>(len[j]));
        dst += len[j];
    }
}

void ompi_tpu_unpack_runs(const uint8_t *src, uint8_t *dst,
                          const int64_t *off, const int64_t *len,
                          int64_t n, int64_t uniform) {
    switch (uniform) {
    case 1:  unpack_uniform<1>(src, dst, off, n);  return;
    case 2:  unpack_uniform<2>(src, dst, off, n);  return;
    case 4:  unpack_uniform<4>(src, dst, off, n);  return;
    case 8:  unpack_uniform<8>(src, dst, off, n);  return;
    case 16: unpack_uniform<16>(src, dst, off, n); return;
    case 32: unpack_uniform<32>(src, dst, off, n); return;
    }
    for (int64_t j = 0; j < n; ++j) {
        std::memcpy(dst + off[j], src, static_cast<size_t>(len[j]));
        src += len[j];
    }
}

// -- strided progressions (vector-class plans: no per-run metadata) -------

void ompi_tpu_pack_strided(uint8_t *dst, const uint8_t *src,
                           int64_t nblocks, int64_t bl, int64_t stride) {
    switch (bl) {
    case 1:  pack_strided_fixed<1>(dst, src, nblocks, stride);  return;
    case 2:  pack_strided_fixed<2>(dst, src, nblocks, stride);  return;
    case 4:  pack_strided_fixed<4>(dst, src, nblocks, stride);  return;
    case 8:  pack_strided_fixed<8>(dst, src, nblocks, stride);  return;
    case 16: pack_strided_fixed<16>(dst, src, nblocks, stride); return;
    case 32: pack_strided_fixed<32>(dst, src, nblocks, stride); return;
    }
    for (int64_t i = 0; i < nblocks; ++i) {
        std::memcpy(dst, src, static_cast<size_t>(bl));
        dst += bl;
        src += stride;
    }
}

void ompi_tpu_unpack_strided(const uint8_t *src, uint8_t *dst,
                             int64_t nblocks, int64_t bl, int64_t stride) {
    switch (bl) {
    case 1:  unpack_strided_fixed<1>(src, dst, nblocks, stride);  return;
    case 2:  unpack_strided_fixed<2>(src, dst, nblocks, stride);  return;
    case 4:  unpack_strided_fixed<4>(src, dst, nblocks, stride);  return;
    case 8:  unpack_strided_fixed<8>(src, dst, nblocks, stride);  return;
    case 16: unpack_strided_fixed<16>(src, dst, nblocks, stride); return;
    case 32: unpack_strided_fixed<32>(src, dst, nblocks, stride); return;
    }
    for (int64_t i = 0; i < nblocks; ++i) {
        std::memcpy(dst, src, static_cast<size_t>(bl));
        src += bl;
        dst += stride;
    }
}

// -- per-item segment walk (plans too large to expand; ABI-1 semantics,
//    now with the uniform-length specialization in the inner loop) --------

void ompi_tpu_pack(uint8_t *dst, const uint8_t *src, int64_t count,
                   int64_t extent, const int64_t *seg_off,
                   const int64_t *seg_len, int64_t nsegs,
                   int64_t uniform, int64_t item_size) {
    for (int64_t i = 0; i < count; ++i) {
        ompi_tpu_pack_runs(dst, src + i * extent, seg_off, seg_len, nsegs,
                           uniform);
        dst += item_size;
    }
}

void ompi_tpu_unpack(const uint8_t *src, uint8_t *dst, int64_t count,
                     int64_t extent, const int64_t *seg_off,
                     const int64_t *seg_len, int64_t nsegs,
                     int64_t uniform, int64_t item_size) {
    for (int64_t i = 0; i < count; ++i) {
        ompi_tpu_unpack_runs(src, dst + i * extent, seg_off, seg_len, nsegs,
                             uniform);
        src += item_size;
    }
}

// version tag so the loader can detect stale cached builds
int64_t ompi_tpu_native_abi(void) { return 2; }

}  // extern "C"
