// Native convertor: the datatype pack/unpack hot loop.
//
// ≈ opal/datatype's compiled-descriptor convertor (opal_convertor_pack/
// unpack, opal_convertor.h:136,142) — the reference runs this loop in C for
// every non-contiguous send/recv; the Python layer's numpy gather is fine
// for small payloads but pays per-element index overhead.  This version
// walks the compiled byte-run segments with memcpy, which is what the
// reference's PREDEFINED/contiguous-loop descriptors boil down to.
//
// Layout contract (matches DerivedDatatype.segments()):
//   item i occupies [i*extent, i*extent + span) in the user buffer;
//   its payload bytes are the runs (seg_off[j], seg_len[j]) relative to
//   the item origin, ascending, non-overlapping.
// The packed stream is the concatenation of runs in order, per item.

#include <cstdint>
#include <cstring>

extern "C" {

void ompi_tpu_pack(uint8_t *dst, const uint8_t *src, int64_t count,
                   int64_t extent, const int64_t *seg_off,
                   const int64_t *seg_len, int64_t nsegs) {
    uint8_t *out = dst;
    for (int64_t i = 0; i < count; ++i) {
        const uint8_t *origin = src + i * extent;
        for (int64_t j = 0; j < nsegs; ++j) {
            std::memcpy(out, origin + seg_off[j],
                        static_cast<size_t>(seg_len[j]));
            out += seg_len[j];
        }
    }
}

void ompi_tpu_unpack(const uint8_t *src, uint8_t *dst, int64_t count,
                     int64_t extent, const int64_t *seg_off,
                     const int64_t *seg_len, int64_t nsegs) {
    const uint8_t *in = src;
    for (int64_t i = 0; i < count; ++i) {
        uint8_t *origin = dst + i * extent;
        for (int64_t j = 0; j < nsegs; ++j) {
            std::memcpy(origin + seg_off[j], in,
                        static_cast<size_t>(seg_len[j]));
            in += seg_len[j];
        }
    }
}

// version tag so the loader can detect stale cached builds
int64_t ompi_tpu_native_abi(void) { return 1; }

}  // extern "C"
