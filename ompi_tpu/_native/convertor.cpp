// Native convertor: the datatype pack/unpack hot loop.
//
// ≈ opal/datatype's compiled-descriptor convertor (opal_convertor_pack/
// unpack, opal_convertor.h:136,142) — the reference runs this loop in C for
// every non-contiguous send/recv; the Python layer's numpy gather is fine
// for small payloads but pays per-element index overhead.  This version
// walks the compiled byte-run segments with memcpy, which is what the
// reference's PREDEFINED/contiguous-loop descriptors boil down to.
//
// Layout contract (matches DerivedDatatype.segments()):
//   item i occupies [i*extent, i*extent + span) in the user buffer;
//   its payload bytes are the runs (seg_off[j], seg_len[j]) relative to
//   the item origin, ascending, non-overlapping.
// The packed stream is the concatenation of runs in order, per item.

#include <cstdint>
#include <cstring>

extern "C" {

void ompi_tpu_pack(uint8_t *dst, const uint8_t *src, int64_t count,
                   int64_t extent, const int64_t *seg_off,
                   const int64_t *seg_len, int64_t nsegs) {
    uint8_t *out = dst;
    for (int64_t i = 0; i < count; ++i) {
        const uint8_t *origin = src + i * extent;
        for (int64_t j = 0; j < nsegs; ++j) {
            std::memcpy(out, origin + seg_off[j],
                        static_cast<size_t>(seg_len[j]));
            out += seg_len[j];
        }
    }
}

void ompi_tpu_unpack(const uint8_t *src, uint8_t *dst, int64_t count,
                     int64_t extent, const int64_t *seg_off,
                     const int64_t *seg_len, int64_t nsegs) {
    const uint8_t *in = src;
    for (int64_t i = 0; i < count; ++i) {
        uint8_t *origin = dst + i * extent;
        for (int64_t j = 0; j < nsegs; ++j) {
            std::memcpy(origin + seg_off[j], in,
                        static_cast<size_t>(seg_len[j]));
            in += seg_len[j];
        }
    }
}

// ---------------------------------------------------------------------------
// Native shm-ring framing (the vader-BTL data plane's hot loop).
//
// ≈ opal/mca/btl/vader's fast-box/fifo writes: one C call frames and
// publishes a message into the per-pair shared-memory ring (or drains
// one), replacing several Python slice writes, struct packs, and counter
// stores per frame.  Memory layout matches btl_shm.py:
//   [0]  u64 head   (writer-owned; release-store publishes)
//   [8]  u64 tail   (reader-owned; release-store frees space)
//   [16] u64 capacity
//   [24] u32 magic
//   [32] u64 sleep flag
//   [64] data area of `capacity` bytes, byte-addressed modulo capacity
// Frame: [u32 total][u32 hdr_len][hdr][payload], total = hdr_len+pay_len.
// ---------------------------------------------------------------------------

static const int64_t kRingHdr = 64;

static inline void ring_copy_in(uint8_t *mm, int64_t cap, int64_t pos,
                                const uint8_t *src, int64_t len) {
    int64_t off = pos % cap;
    int64_t first = cap - off < len ? cap - off : len;
    std::memcpy(mm + kRingHdr + off, src, static_cast<size_t>(first));
    if (first < len)
        std::memcpy(mm + kRingHdr, src + first,
                    static_cast<size_t>(len - first));
}

static inline void ring_copy_out(const uint8_t *mm, int64_t cap, int64_t pos,
                                 uint8_t *dst, int64_t len) {
    int64_t off = pos % cap;
    int64_t first = cap - off < len ? cap - off : len;
    std::memcpy(dst, mm + kRingHdr + off, static_cast<size_t>(first));
    if (first < len)
        std::memcpy(dst + first, mm + kRingHdr,
                    static_cast<size_t>(len - first));
}

// Frame + publish one message.  Caller verified capacity under its lock.
// Returns the new head (also release-stored into the ring header, which
// is what makes the frame visible to the reader).
int64_t ompi_tpu_ring_write(uint8_t *mm, int64_t cap, int64_t head,
                            const uint8_t *hdr, int64_t hdr_len,
                            const uint8_t *pay, int64_t pay_len) {
    uint32_t lens[2] = {static_cast<uint32_t>(hdr_len + pay_len),
                        static_cast<uint32_t>(hdr_len)};
    ring_copy_in(mm, cap, head, reinterpret_cast<uint8_t *>(lens), 8);
    ring_copy_in(mm, cap, head + 8, hdr, hdr_len);
    if (pay_len)
        ring_copy_in(mm, cap, head + 8 + hdr_len, pay, pay_len);
    int64_t new_head = head + 8 + hdr_len + pay_len;
    __atomic_store_n(reinterpret_cast<uint64_t *>(mm),
                     static_cast<uint64_t>(new_head), __ATOMIC_RELEASE);
    return new_head;
}

// Drain one frame into `out` ([u32 total][u32 hdr_len][hdr][payload]).
// Returns the consumed byte count (8+total) with the tail release-stored;
// 0 when the ring is empty; -(8+total) when `out` is too small (nothing
// consumed — the caller grows its scratch and retries); -1 when the
// published region is corrupt.
int64_t ompi_tpu_ring_read(uint8_t *mm, int64_t cap, int64_t tail,
                           uint8_t *out, int64_t out_cap) {
    uint64_t head = __atomic_load_n(reinterpret_cast<uint64_t *>(mm),
                                    __ATOMIC_ACQUIRE);
    int64_t avail = static_cast<int64_t>(head) - tail;
    if (avail == 0)
        return 0;
    if (avail < 8 || avail > cap)
        return -1;
    uint32_t lens[2];
    ring_copy_out(mm, cap, tail, reinterpret_cast<uint8_t *>(lens), 8);
    int64_t total = static_cast<int64_t>(lens[0]);
    if (total < static_cast<int64_t>(lens[1]) || 8 + total > avail)
        return -1;
    if (8 + total > out_cap)
        return -(8 + total);
    ring_copy_out(mm, cap, tail, out, 8 + total);
    __atomic_store_n(reinterpret_cast<uint64_t *>(mm) + 1,
                     static_cast<uint64_t>(tail + 8 + total),
                     __ATOMIC_RELEASE);
    return 8 + total;
}

// version tag so the loader can detect stale cached builds
int64_t ompi_tpu_native_abi(void) { return 2; }

}  // extern "C"
