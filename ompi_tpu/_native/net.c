/* Native network executor: the GIL-free inter-node transport plane.
 *
 * ≈ opal's btl/tcp progress engine — the reference drains its endpoint
 * send queues and runs its event-loop reads in C; our Python plane pays
 * one b"".join copy, one syscall, and two GIL transitions per frame on
 * the send side, and a whole Python thread per accepted connection on
 * the receive side.  Every entry point here is called through ctypes,
 * which drops the GIL for the duration of the call, so:
 *
 *   - a writer drains an entire per-peer submission-ring backlog in one
 *     sendmsg (scatter-gather, MSG_DONTWAIT) call — the burst of small
 *     frames a collective fan-in produces coalesces into one syscall;
 *   - one poller parks in poll() across EVERY connection's fd instead
 *     of N Python read loops blocking in recv and then fighting for the
 *     interpreter to parse 8 bytes of length prefix;
 *   - rendezvous payloads land straight into the plan-registered
 *     receive buffer (recv into the caller-supplied pointer), not into
 *     an intermediate bytes object.
 *
 * Policy stays in Python, exactly like arena.c: every blocking entry
 * runs for ONE bounded slice and returns, so the caller re-runs the FT
 * contract (revocation, detector-declared deaths, stop flags) between
 * parks at the same cadence the pure-Python loop did.  Sockets are
 * never made nonblocking here — MSG_DONTWAIT gives per-call
 * nonblocking I/O, so the Python fallback plane can keep using the
 * very same (blocking) socket objects when `btl_tcp_native` flips off.
 *
 * Wire contract (shared with btl.py's python plane, bit-identical):
 *   frame = u32 LE total | u32 LE hdrlen | dss(header) | raw payload
 */

#include <stdint.h>
#include <string.h>
#include <time.h>

#include <errno.h>
#include <poll.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <unistd.h>

#ifndef MSG_NOSIGNAL
#define MSG_NOSIGNAL 0
#endif

#ifdef __cplusplus
extern "C" {
#endif

#if defined(__x86_64__) || defined(__i386__)
#define NET_RELAX() __builtin_ia32_pause()
#else
#define NET_RELAX() do { } while (0)
#endif

/* EOF sentinel, outside the errno range so -errno stays unambiguous */
#define NET_EOF (-4096)

/* sendmsg batch width: frames are <= 3 iovecs (prefix, header,
 * payload), so 256 slots cover ~85 frames per syscall — far under any
 * IOV_MAX and a modest stack frame */
#define NET_IOV_BATCH 256

/* poll() fan-in cap (stack pollfd array) — worlds are far smaller; the
 * Python side falls back to select() past this */
#define NET_POLL_MAX 1024

static int64_t now_ns(void) {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (int64_t)ts.tv_sec * 1000000000LL + (int64_t)ts.tv_nsec;
}

static int poll_ms(int64_t remain_ns) {
    int64_t ms = (remain_ns + 999999LL) / 1000000LL;
    if (ms < 1)
        ms = 1;
    if (ms > 1000)
        ms = 1000;   /* missed-wake worst case stays bounded */
    return (int)ms;
}

/* -- span rings ----------------------------------------------------------- *
 *
 * Begin–end timestamps of the GIL-released transport parks, drained by
 * the Python side into its flight recorder (same design as arena.c:
 * per-thread single-writer rings, slot collisions may tear a triple —
 * span data is metrics, not control flow).  Disarmed (min_ns < 0, the
 * default) each entry pays one relaxed load. */

#define SPAN_SLOTS 16
#define SPAN_RING 256
#define SPAN_KIND_WRITEV 1
#define SPAN_KIND_SEND3 2
#define SPAN_KIND_POLL 3
#define SPAN_KIND_RECV_INTO 4

typedef struct {
    uint64_t n;                  /* triples ever recorded (writer-owned) */
    uint64_t drained;            /* drain cursor (drainer-owned)         */
    uint64_t buf[SPAN_RING * 3]; /* kind, t0_ns, t1_ns                   */
} span_ring_t;

static span_ring_t g_spans[SPAN_SLOTS];
static int64_t g_span_min_ns = -1;   /* < 0 = disarmed */
static uint64_t g_span_slot_seq = 0;
static __thread int t_span_slot = -1;

/* begin-of-span stamp: 0 when disarmed (entries skip the end stamp) */
static int64_t span_t0(void) {
    if (__atomic_load_n(&g_span_min_ns, __ATOMIC_RELAXED) < 0)
        return 0;
    return now_ns();
}

static void span_record(uint64_t kind, int64_t t0) {
    span_ring_t *r;
    uint64_t i;
    int64_t t1 = now_ns();
    int64_t min_ns = __atomic_load_n(&g_span_min_ns, __ATOMIC_RELAXED);
    if (min_ns < 0 || t1 - t0 < min_ns)
        return;
    if (t_span_slot < 0)
        t_span_slot = (int)(__atomic_fetch_add(&g_span_slot_seq, 1,
                                               __ATOMIC_RELAXED)
                            % SPAN_SLOTS);
    r = &g_spans[t_span_slot];
    i = (r->n % SPAN_RING) * 3;
    r->buf[i] = kind;
    r->buf[i + 1] = (uint64_t)t0;
    r->buf[i + 2] = (uint64_t)t1;
    __atomic_store_n(&r->n, r->n + 1, __ATOMIC_RELEASE);
}

/* Arm (min_ns >= 0: record spans at least that long) or disarm (< 0). */
void ompi_tpu_net_spans_enable(int64_t min_ns) {
    __atomic_store_n(&g_span_min_ns, min_ns, __ATOMIC_RELEASE);
}

/* Copy completed triples (kind, t0_ns, t1_ns) since the last drain into
 * out (capacity 3*max_triples u64s); returns the triple count.  Single
 * drainer assumed (Python under the GIL); a wrapped ring drops the
 * overwritten spans. */
int64_t ompi_tpu_net_spans_drain(uint64_t *out, int64_t max_triples) {
    int64_t got = 0;
    int s;
    for (s = 0; s < SPAN_SLOTS && got < max_triples; ++s) {
        span_ring_t *r = &g_spans[s];
        uint64_t n = __atomic_load_n(&r->n, __ATOMIC_ACQUIRE);
        uint64_t from = r->drained;
        if (n - from > SPAN_RING)
            from = n - SPAN_RING;
        for (; from < n && got < max_triples; ++from, ++got) {
            uint64_t i = (from % SPAN_RING) * 3;
            out[got * 3] = r->buf[i];
            out[got * 3 + 1] = r->buf[i + 1];
            out[got * 3 + 2] = r->buf[i + 2];
        }
        r->drained = from;
    }
    return got;
}

/* -- send side ------------------------------------------------------------ */

/* Drain a scatter-gather backlog: `parts` is niov (addr, len) u64
 * pairs; the whole list is pushed through sendmsg(MSG_DONTWAIT) in
 * NET_IOV_BATCH chunks, polling POLLOUT between short writes, until
 * everything is written or the slice expires.
 *
 * Returns bytes written THIS call (>= 0; the caller re-slices the
 * remainder and re-runs its FT checks), or -errno on a hard socket
 * error with no progress (progress-then-error returns the progress;
 * the next call surfaces the error). */
static int64_t net_writev_impl(int64_t fd, const uint64_t *parts,
                               int64_t niov, int64_t slice_ns) {
    struct iovec iov[NET_IOV_BATCH];
    struct msghdr msg;
    int64_t i = 0, written = 0, deadline;
    uint64_t skip = 0;   /* bytes of parts[i] already written */
    ssize_t n;

    deadline = now_ns() + slice_ns;
    while (i < niov) {
        int64_t k = 0, j;
        for (j = i; j < niov && k < NET_IOV_BATCH; ++j) {
            uint64_t base = parts[2 * j];
            uint64_t len = parts[2 * j + 1];
            if (j == i) {
                base += skip;
                len -= skip;
            }
            if (len == 0 && j == i) {   /* fully-sent head: advance */
                ++i;
                skip = 0;
                continue;
            }
            iov[k].iov_base = (void *)(uintptr_t)base;
            iov[k].iov_len = (size_t)len;
            ++k;
        }
        if (k == 0)
            break;
        memset(&msg, 0, sizeof(msg));
        msg.msg_iov = iov;
        msg.msg_iovlen = (size_t)k;
        n = sendmsg((int)fd, &msg, MSG_DONTWAIT | MSG_NOSIGNAL);
        if (n > 0) {
            uint64_t left = (uint64_t)n;
            written += n;
            while (i < niov) {
                uint64_t len = parts[2 * i + 1] - skip;
                if (left < len) {
                    skip += left;
                    break;
                }
                left -= len;
                ++i;
                skip = 0;
            }
            continue;
        }
        if (n < 0 && errno == EINTR)
            continue;
        if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
            struct pollfd pfd;
            int64_t remain = deadline - now_ns();
            if (remain <= 0)
                return written;
            pfd.fd = (int)fd;
            pfd.events = POLLOUT;
            pfd.revents = 0;
            (void)poll(&pfd, 1, poll_ms(remain));
            continue;
        }
        return written > 0 ? written : -(int64_t)errno;
    }
    return written;
}

/* Latency-path variant: one whole frame (prefix, header, payload) in a
 * single ctypes crossing.  ompi_tpu_net_writev needs the caller to
 * marshal (addr, len) pairs into a u64 array — ~10us of Python per
 * frame, which swamps the syscall on the ping-pong path.  Here ctypes
 * passes the three buffers straight through as pointer arguments (it
 * extracts bytes-object addresses in C), so the Python side does no
 * marshalling at all.  Same drain discipline as writev: sendmsg
 * MSG_DONTWAIT with partial-advance, POLLOUT waits bounded by the
 * slice.  Returns total bytes written this call (the caller resumes a
 * partial frame through writev with adjusted offsets), or -errno on a
 * hard error with no progress. */
static int64_t net_send3_impl(int64_t fd,
                              const uint8_t *p0, int64_t l0,
                              const uint8_t *p1, int64_t l1,
                              const uint8_t *p2, int64_t l2,
                              int64_t slice_ns) {
    struct iovec iov[3];
    struct msghdr msg;
    int64_t total = l0 + l1 + l2, written = 0, deadline;
    int n = 0, idx = 0;

    if (l0 > 0) { iov[n].iov_base = (void *)p0; iov[n].iov_len = (size_t)l0; ++n; }
    if (l1 > 0) { iov[n].iov_base = (void *)p1; iov[n].iov_len = (size_t)l1; ++n; }
    if (l2 > 0) { iov[n].iov_base = (void *)p2; iov[n].iov_len = (size_t)l2; ++n; }
    deadline = now_ns() + slice_ns;
    while (written < total) {
        ssize_t w;
        memset(&msg, 0, sizeof(msg));
        msg.msg_iov = iov + idx;
        msg.msg_iovlen = (size_t)(n - idx);
        w = sendmsg((int)fd, &msg, MSG_DONTWAIT | MSG_NOSIGNAL);
        if (w > 0) {
            written += w;
            while (idx < n && (size_t)w >= iov[idx].iov_len) {
                w -= (ssize_t)iov[idx].iov_len;
                ++idx;
            }
            if (idx < n && w > 0) {
                iov[idx].iov_base = (uint8_t *)iov[idx].iov_base + w;
                iov[idx].iov_len -= (size_t)w;
            }
            continue;
        }
        if (w < 0 && errno == EINTR)
            continue;
        if (w < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
            struct pollfd pfd;
            int64_t remain = deadline - now_ns();
            if (remain <= 0)
                return written;
            pfd.fd = (int)fd;
            pfd.events = POLLOUT;
            pfd.revents = 0;
            (void)poll(&pfd, 1, poll_ms(remain));
            continue;
        }
        return written > 0 ? written : -(int64_t)errno;
    }
    return written;
}

/* -- receive side --------------------------------------------------------- */

/* ONE park across every connection: a bounded spin burst of
 * zero-timeout polls (each iteration one syscall — cheap enough to
 * catch a ping-pong reply without a scheduler wake), then a single
 * blocking poll for the remaining slice.  ready[i] is set for any fd
 * with POLLIN/POLLERR/POLLHUP/POLLNVAL pending (errors count as
 * readable: the read surfaces them).  Returns the number of ready
 * fds, 0 on slice expiry, or -errno (-EINVAL when nfds exceeds the
 * stack cap — the caller falls back to select()). */
static int64_t net_poll_impl(const int64_t *fds, int64_t nfds,
                             uint8_t *ready, int64_t spins,
                             int64_t slice_ns) {
    struct pollfd pfds[NET_POLL_MAX];
    int64_t i, s, deadline;
    int rc;

    if (nfds < 0 || nfds > NET_POLL_MAX)
        return -(int64_t)EINVAL;
    for (i = 0; i < nfds; ++i) {
        pfds[i].fd = (int)fds[i];
        pfds[i].events = POLLIN;
        pfds[i].revents = 0;
        ready[i] = 0;
    }
    for (s = 0; s < spins; ++s) {
        rc = poll(pfds, (nfds_t)nfds, 0);
        if (rc != 0)
            goto harvest;
        NET_RELAX();
    }
    deadline = now_ns() + slice_ns;
    for (;;) {
        int64_t remain = deadline - now_ns();
        if (remain <= 0)
            return 0;
        rc = poll(pfds, (nfds_t)nfds, poll_ms(remain));
        if (rc > 0)
            goto harvest;
        if (rc < 0 && errno != EINTR && errno != EAGAIN)
            return -(int64_t)errno;
        /* rc == 0 (poll's own timeout) or EINTR: re-check the slice */
    }
harvest:
    if (rc < 0)
        return (errno == EINTR || errno == EAGAIN) ? 0 : -(int64_t)errno;
    for (i = 0; i < nfds; ++i)
        if (pfds[i].revents & (POLLIN | POLLERR | POLLHUP | POLLNVAL))
            ready[i] = 1;
    return (int64_t)rc;
}

/* One nonblocking gulp into the connection's staging buffer.  Returns
 * bytes read (> 0), NET_EOF on orderly shutdown, -EAGAIN when nothing
 * is pending, or -errno. */
int64_t ompi_tpu_net_read(int64_t fd, uint8_t *buf, int64_t cap) {
    ssize_t n;
    for (;;) {
        n = recv((int)fd, buf, (size_t)cap, MSG_DONTWAIT);
        if (n > 0)
            return (int64_t)n;
        if (n == 0)
            return NET_EOF;
        if (errno == EINTR)
            continue;
        return -(int64_t)errno;
    }
}

/* Land payload bytes straight into the caller's buffer (the rndv
 * zero-copy leg): poll(POLLIN) + recv(MSG_DONTWAIT) until `want`
 * bytes arrived or the slice expired.  Returns bytes landed THIS call
 * (>= 0; the caller re-runs FT checks and calls again with the
 * remainder), NET_EOF on orderly shutdown with no progress this call,
 * or -errno. */
static int64_t net_recv_into_impl(int64_t fd, uint8_t *dst, int64_t want,
                                  int64_t slice_ns) {
    int64_t got = 0, deadline;
    ssize_t n;

    deadline = now_ns() + slice_ns;
    while (got < want) {
        n = recv((int)fd, dst + got, (size_t)(want - got), MSG_DONTWAIT);
        if (n > 0) {
            got += n;
            continue;
        }
        if (n == 0)
            return got > 0 ? got : NET_EOF;
        if (errno == EINTR)
            continue;
        if (errno == EAGAIN || errno == EWOULDBLOCK) {
            struct pollfd pfd;
            int64_t remain = deadline - now_ns();
            if (remain <= 0)
                return got;
            pfd.fd = (int)fd;
            pfd.events = POLLIN;
            pfd.revents = 0;
            (void)poll(&pfd, 1, poll_ms(remain));
            continue;
        }
        return got > 0 ? got : -(int64_t)errno;
    }
    return got;
}

/* Exported transport parks: the impl bracketed by the span stamps.
 * When disarmed span_t0() returns 0 and the wrapper adds one relaxed
 * load. */
int64_t ompi_tpu_net_writev(int64_t fd, const uint64_t *parts,
                            int64_t niov, int64_t slice_ns) {
    int64_t t0 = span_t0();
    int64_t r = net_writev_impl(fd, parts, niov, slice_ns);
    if (t0)
        span_record(SPAN_KIND_WRITEV, t0);
    return r;
}

int64_t ompi_tpu_net_send3(int64_t fd,
                           const uint8_t *p0, int64_t l0,
                           const uint8_t *p1, int64_t l1,
                           const uint8_t *p2, int64_t l2,
                           int64_t slice_ns) {
    int64_t t0 = span_t0();
    int64_t r = net_send3_impl(fd, p0, l0, p1, l1, p2, l2, slice_ns);
    if (t0)
        span_record(SPAN_KIND_SEND3, t0);
    return r;
}

int64_t ompi_tpu_net_poll(const int64_t *fds, int64_t nfds,
                          uint8_t *ready, int64_t spins,
                          int64_t slice_ns) {
    int64_t t0 = span_t0();
    int64_t r = net_poll_impl(fds, nfds, ready, spins, slice_ns);
    if (t0)
        span_record(SPAN_KIND_POLL, t0);
    return r;
}

int64_t ompi_tpu_net_recv_into(int64_t fd, uint8_t *dst, int64_t want,
                               int64_t slice_ns) {
    int64_t t0 = span_t0();
    int64_t r = net_recv_into_impl(fd, dst, want, slice_ns);
    if (t0)
        span_record(SPAN_KIND_RECV_INTO, t0);
    return r;
}

/* Parse the length-prefix framing natively: scan buf[0..len) for
 * complete `u32 LE total | u32 LE hdrlen` frames and emit one
 * (offset, total, hdrlen) u64 triple per COMPLETE frame into `out`
 * (room for max_frames triples).  Stops at the first incomplete frame
 * (or when `out` is full).  Returns the number of frames emitted, or
 * -EPROTO on a malformed prefix (hdrlen > total): the stream can only
 * desync from a code bug, and a loud error beats a silent misparse. */
int64_t ompi_tpu_net_scan(const uint8_t *buf, int64_t len,
                          uint64_t *out, int64_t max_frames) {
    int64_t off = 0, nf = 0;
    while (nf < max_frames && len - off >= 8) {
        const uint8_t *p = buf + off;
        uint32_t total = (uint32_t)p[0] | ((uint32_t)p[1] << 8)
            | ((uint32_t)p[2] << 16) | ((uint32_t)p[3] << 24);
        uint32_t hdrlen = (uint32_t)p[4] | ((uint32_t)p[5] << 8)
            | ((uint32_t)p[6] << 16) | ((uint32_t)p[7] << 24);
        if (hdrlen > total)
            return -(int64_t)EPROTO;
        if (len - off - 8 < (int64_t)total)
            break;
        out[3 * nf] = (uint64_t)off;
        out[3 * nf + 1] = (uint64_t)total;
        out[3 * nf + 2] = (uint64_t)hdrlen;
        ++nf;
        off += 8 + (int64_t)total;
    }
    return nf;
}

/* version tag so the loader can detect stale cached builds */
int64_t ompi_tpu_net_abi(void) { return 3; }

#ifdef __cplusplus
}  /* extern "C" */
#endif
