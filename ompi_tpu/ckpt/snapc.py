"""Snapshot coordination: collective checkpoint/restart + manager.

≈ orte/mca/snapc/full (snapc.h:47-166): the coordinator that quiesces the
job, has every process dump its image, collects success reports, and marks
the global snapshot valid.  The TPU redesign runs the whole protocol over
the collective layer:

    checkpoint(comm, state):
      barrier            — quiesce ≈ crcp/bkmrk drain (step boundary: SPMD
                           programs have no in-flight user traffic here)
      write_rank         — ≈ crs checkpoint of this process
      allreduce(MIN ok)  — every rank's success report
      rank0 commit       — the snapc "global snapshot valid" record
      barrier            — restart-safety: nobody races ahead of the commit

Device arrays are pulled to host by the store; on restart, pass
``restore_fn`` (e.g. a jax.device_put with the right sharding) to place
arrays back on the mesh — the checkpoint layer is deliberately ignorant of
shardings, exactly as sstore is ignorant of what's in an image.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable, Optional

import numpy as np

from ompi_tpu.ckpt.store import SnapshotStore
from ompi_tpu.mpi import trace as trace_mod
from ompi_tpu.mpi.constants import ERR_IO, MPIException

__all__ = ["checkpoint", "restart", "restart_incarnation", "auto_restore",
           "CheckpointManager"]


def restart_incarnation() -> int:
    """The ``OMPI_TPU_RESTART`` life number the errmgr stamped on this
    process — 0 for a first life, n for the n-th revival (errmgr
    respawn/selfheal)."""
    return int(os.environ.get("OMPI_TPU_RESTART") or 0)


def auto_restore(comm, store: SnapshotStore,
                 restore_fn: Optional[Callable[[str, np.ndarray], Any]]
                 = None, rank: Optional[int] = None
                 ) -> Optional[tuple[int, dict[str, Any]]]:
    """``OMPI_TPU_RESTART``-keyed revival restore (the errmgr
    respawn/selfheal rejoin): when this process is a revived incarnation
    and a committed snapshot exists, load THIS rank's view of the latest
    one and return ``(seq, state)``; None on a first life (or when
    nothing was ever committed — the revived rank recomputes from 0).

    Deliberately NON-collective, unlike :func:`restart`: the survivors
    are mid-step and cannot pair a collective restore with the revived
    rank — each life reads only its own committed shard.  The in-flight
    gap between the snapshot and the failure point is the message log's
    job (``ckpt.msglog`` auto-replay on the peer-revived event).

    ``rank`` overrides the in-store rank key (apps using one store PER
    rank pass 0 — they keyed the store path by rank instead).
    """
    if not restart_incarnation():
        return None
    seq = store.latest()
    if seq is None:
        return None
    if trace_mod.active:
        trace_mod.instant("ckpt", "auto_restore", rank=comm.pml.rank,
                          seq=int(seq), life=restart_incarnation())
    state = store.load_rank(seq, comm.rank if rank is None else rank)
    if restore_fn is not None:
        state = {k: restore_fn(k, v) for k, v in state.items()}
    return seq, state


def checkpoint(comm, store: SnapshotStore, state: dict[str, Any],
               seq: Optional[int] = None,
               keep_last: Optional[int] = None,
               extra_meta: Optional[dict] = None) -> int:
    """Collective: snapshot every rank's `state` dict; returns the seq.

    All-or-nothing: if any rank fails to write, no commit record is
    created and the snapshot is invisible to restart.
    """
    if trace_mod.active:
        with trace_mod.span("ckpt", "checkpoint", rank=comm.pml.rank,
                            seq=-1 if seq is None else int(seq),
                            arrays=len(state)):
            return _checkpoint_impl(comm, store, state, seq, keep_last,
                                    extra_meta)
    return _checkpoint_impl(comm, store, state, seq, keep_last, extra_meta)


def _checkpoint_impl(comm, store, state, seq, keep_last, extra_meta) -> int:
    if seq is None:
        latest = store.latest()
        # all ranks compute the same next seq from the committed history,
        # then agree on the max (defensive against stale directory listings
        # on shared filesystems)
        mine = (latest + 1) if latest is not None else 0
        agreed = comm.allreduce(np.array([mine], np.int64), op=_MAX())
        seq = int(np.asarray(agreed)[0])
    comm.barrier()                      # quiesce at the step boundary
    if hasattr(store, "save"):
        # collective single-file store (ShardedSnapshotStore): save() is
        # the whole write+commit protocol — per-rank write_rank/commit
        # do not apply to the shared-file layout
        store.save(seq, state, extra=extra_meta)
        if comm.rank == 0 and keep_last is not None:
            try:
                store.gc(keep_last)
            except Exception:  # noqa: BLE001 — best-effort, like below
                pass
        return seq
    ok = 1
    err = ""
    try:
        store.write_rank(seq, comm.rank, state)
    except Exception as e:  # noqa: BLE001 — must still participate below
        ok = 0
        err = str(e)
    agreed = comm.allreduce(np.array([ok], np.int64), op=_MIN())
    if not int(np.asarray(agreed)[0]):
        raise MPIException(
            f"checkpoint {seq} failed"
            + (f" on this rank: {err}" if err else " on a peer rank"),
            error_class=ERR_IO)
    # commit success must be agreed too: if rank 0's commit throws (e.g. a
    # peer's file not yet visible on a laggy shared fs), a bare barrier
    # would strand every other rank — broadcast the outcome instead
    commit_ok = 1
    commit_err = ""
    if comm.rank == 0:
        try:
            store.commit(seq, comm.size, extra_meta)
        except Exception as e:  # noqa: BLE001 — reported collectively
            commit_ok = 0
            commit_err = str(e)
        if commit_ok and keep_last is not None:
            try:
                store.gc(keep_last)   # best-effort: a failed cleanup must
            except Exception:         # not report a durable commit as
                pass                  # failed (restart would load it)
    flag = comm.bcast(np.array([commit_ok], np.int8), root=0)
    if not int(np.asarray(flag)[0]):
        raise MPIException(
            f"checkpoint {seq} commit failed on rank 0"
            + (f": {commit_err}" if commit_err else ""),
            error_class=ERR_IO)
    return seq


def restart(comm, store: SnapshotStore, seq: Optional[int] = None,
            restore_fn: Optional[Callable[[str, np.ndarray], Any]] = None,
            ) -> tuple[int, dict[str, Any]]:
    """Collective: load the latest (or given) committed snapshot.

    ``restore_fn(name, host_array)`` re-places each array (device_put with
    a sharding, dtype cast, ...); default returns the host array.
    """
    if trace_mod.active:
        with trace_mod.span("ckpt", "restart", rank=comm.pml.rank,
                            seq=-1 if seq is None else int(seq)):
            return _restart_impl(comm, store, seq, restore_fn)
    return _restart_impl(comm, store, seq, restore_fn)


def _restart_impl(comm, store, seq, restore_fn):
    if seq is None:
        # rank 0 decides (directory listings may race GC on shared fs)
        mine = store.latest()
        chosen = comm.bcast(
            np.array([mine if mine is not None else -1], np.int64), root=0)
        seq = int(np.asarray(chosen)[0])
        if seq < 0:
            raise MPIException("no committed snapshot to restart from",
                               error_class=ERR_IO)
    state = store.load_rank(seq, comm.rank)
    if restore_fn is not None:
        state = {k: restore_fn(k, v) for k, v in state.items()}
    comm.barrier()
    return seq, state


class CheckpointManager:
    """Step-driven convenience (≈ orbax CheckpointManager, carrying the
    snapc policy knobs): checkpoint every `interval` steps, keep the last
    `keep_last`, optionally writing in a background thread (async save —
    the barrier cost stays, the serialization cost moves off the step
    path)."""

    def __init__(self, comm, store: SnapshotStore, interval: int = 1,
                 keep_last: int = 2, async_save: bool = False) -> None:
        if interval < 1:
            raise MPIException("interval must be >= 1")
        # private communicator (MPI library idiom): async saves run their
        # collectives from a background thread, which would cross-match
        # with the application's traffic on the same cid
        self.comm = comm.dup(name=f"{comm.name}.ckpt")
        self.store = store
        self.interval = interval
        self.keep_last = keep_last
        self.async_save = async_save
        self._pending: Optional[threading.Thread] = None
        self._pending_err: list[BaseException] = []

    def should_checkpoint(self, step: int) -> bool:
        return step % self.interval == 0

    def maybe_checkpoint(self, step: int,
                         state: dict[str, Any]) -> Optional[int]:
        if not self.should_checkpoint(step):
            return None
        return self.save(step, state)

    def save(self, step: int, state: dict[str, Any]) -> int:
        self.wait()                      # one outstanding async save max
        if not self.async_save:
            return checkpoint(self.comm, self.store, state, seq=step,
                              keep_last=self.keep_last)
        # snapshot the host copies NOW (the caller may mutate/donate the
        # arrays right after), then serialize in the background
        host = {k: np.asarray(v).copy() for k, v in state.items()}

        def work() -> None:
            try:
                checkpoint(self.comm, self.store, host, seq=step,
                           keep_last=self.keep_last)
            except BaseException as e:  # noqa: BLE001 — reported at wait()
                self._pending_err.append(e)

        self._pending = threading.Thread(target=work, daemon=True)
        self._pending.start()
        return step

    def wait(self) -> None:
        """Block until the outstanding async save (if any) lands; re-raise
        its failure here."""
        if self._pending is not None:
            self._pending.join()
            self._pending = None
        if self._pending_err:
            raise self._pending_err.pop(0)

    def restore(self, seq: Optional[int] = None,
                restore_fn: Optional[Callable] = None
                ) -> tuple[int, dict[str, Any]]:
        self.wait()
        return restart(self.comm, self.store, seq, restore_fn)

    def auto_restore(self, restore_fn: Optional[Callable] = None,
                     rank: Optional[int] = None
                     ) -> Optional[tuple[int, dict[str, Any]]]:
        """``OMPI_TPU_RESTART``-keyed revival restore (see module-level
        :func:`auto_restore`): non-collective latest-snapshot load when
        this process is an errmgr-revived incarnation, else None.
        ``rank`` overrides the in-store rank key, exactly as on the
        module function (per-rank stores pass 0)."""
        return auto_restore(self.comm, self.store, restore_fn, rank)


def _MAX():
    from ompi_tpu.mpi import op as op_mod

    return op_mod.MAX


def _MIN():
    from ompi_tpu.mpi import op as op_mod

    return op_mod.MIN
